#!/usr/bin/env python
"""Headline benchmark: 1000-replica LogisticRegression bag on
covtype-shaped data — base-learner fits/sec vs the CPU baseline
[B:2, B:5, BASELINE.md row ★].

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "fits/sec", "vs_baseline": N,
   "parity": true, ...}

The result is only valid at accuracy parity: if the TPU ensemble's
accuracy falls below the CPU single-model accuracy minus tolerance,
``value`` is null and ``parity`` false — a speed "win" from a broken
solver must not parse as a win [VERDICT r1 weak#2].

Backend protocol: the ambient TPU plugin can block indefinitely in
client init when the chip is unreachable, so the backend is probed in a
subprocess with a bounded timeout before anything imports jax here.
The probe POLLS on a bounded deadline (default 25 min, re-probing
every ~2 min) rather than giving up after two attempts: round 3's only
live tunnel window lasted ~3 minutes and appeared mid-round, narrower
than a one-shot probe could catch [VERDICT r3 weak#3]. If the deadline
lapses the script prints a one-line JSON error and exits 1 instead of
hanging to rc=124 [VERDICT r1 weak#1].

Baseline protocol (BASELINE.md measurement notes): no Spark/JVM exists
in this environment, so the documented CPU proxy is sklearn
LogisticRegression fits on the same data, single process. The CPU
number is measured once (5 bootstrap fits) and cached in
``bench_baseline_cache.json`` keyed by config; delete the file to
re-measure.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "benchmarks"))
CACHE_PATH = os.path.join(REPO, "bench_baseline_cache.json")


def _sk_lr(l2: float, n_rows: int):
    """The sklearn stand-in at matched regularization — one definition
    for the serial/parallel/predict baselines so they can't drift."""
    from sklearn.linear_model import LogisticRegression as SkLR

    return SkLR(max_iter=100, C=1.0 / (l2 * n_rows))

def _probe_code(platform: str | None) -> str:
    force = (
        f"jax.config.update('jax_platforms', {platform!r}); "
        if platform else ""
    )
    return f"import jax; {force}print('BACKEND=' + jax.default_backend())"


def probe_backend(timeout_s: float = 120.0, retries: int = 1,
                  platform: str | None = None) -> tuple[str | None, str]:
    """Initialize the JAX backend in a subprocess with a hard timeout.

    Returns ``(backend_name, "")`` on success or ``(None, reason)`` when
    init hangs or crashes — the parent process never touches jax until
    the probe succeeds, so an unreachable TPU cannot wedge the
    benchmark itself. ``reason`` distinguishes a timeout from a crash
    and carries the subprocess's stderr tail.
    """
    # Don't probe over a measurement in flight: the probe's TPU client
    # + matmul would perturb a flock-holding run's steady-state timings
    # on the single chip. Wait for the lock (bounded), release, probe —
    # a wedged holder past the deadline degrades to probing anyway
    # rather than losing liveness detection.
    if platform is None:
        try:
            from isolation import _acquire_device_lock

            lock = _acquire_device_lock(deadline_s=timeout_s)
            if lock is not None:
                lock.close()
        except Exception:  # noqa: BLE001 — lock is best-effort here
            pass
    reason = "no probe attempt ran"
    for attempt in range(retries + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _probe_code(platform)],
                capture_output=True, text=True, timeout=timeout_s,
            )
            for line in proc.stdout.splitlines():
                if line.startswith("BACKEND="):
                    return line.split("=", 1)[1], ""
            reason = (
                f"probe exited rc={proc.returncode}: "
                + proc.stderr.strip()[-300:]
            )
        except subprocess.TimeoutExpired:
            reason = f"probe timed out at {timeout_s:.0f}s (backend init hang)"
        if attempt < retries:
            time.sleep(5.0)
    return None, reason


def probe_backend_until(
    deadline_s: float,
    attempt_timeout_s: float = 120.0,
    interval_s: float = 120.0,
    platform: str | None = None,
    _probe=None,
    _sleep=time.sleep,
    _clock=time.monotonic,
) -> tuple[str | None, str]:
    """Poll ``probe_backend`` until it succeeds or ``deadline_s`` lapses.

    The driver invokes ``bench.py`` exactly once per round; a flapping
    tunnel whose live windows are minutes long needs the single
    invocation to keep watching, watcher-style, instead of giving up
    after one attempt [VERDICT r3 ask#2]. Between failed attempts the
    poller sleeps ``interval_s``; it stops starting new cycles once the
    next sleep would cross the deadline (a final attempt may overrun by
    up to ``attempt_timeout_s`` for the probe subprocess plus another
    ``attempt_timeout_s`` of flock wait — see below). Each attempt
    re-takes the capture flock via ``probe_backend``, so polling never
    perturbs a measurement in flight. ``_probe``/``_sleep``/``_clock`` exist for
    injection in tests.
    """
    probe = _probe if _probe is not None else probe_backend
    t0 = _clock()
    attempts = 0
    reason = "no probe attempt ran"
    while True:
        backend, reason = probe(
            attempt_timeout_s, retries=0, platform=platform
        )
        attempts += 1
        if backend is not None:
            return backend, ""
        elapsed = _clock() - t0
        if elapsed + interval_s >= deadline_s:
            return None, (
                f"{attempts} probe attempt(s) over {elapsed:.0f}s "
                f"(deadline {deadline_s:.0f}s) — last: {reason}"
            )
        _sleep(interval_s)


def load_sweep_winner(min_acc: float, workload: dict) -> dict | None:
    """Best measured cell from the on-chip tuning sweep, if captured.

    Lets the headline bench self-tune from data that may land (via the
    detached watcher) after the builder's session. Cells without
    accuracy, or below ``min_acc`` (the bench's own parity bar:
    cached CPU baseline accuracy − parity tolerance), can't win — a
    config that would fail the parity gate must not be selected by it.
    Cells whose stamped ``workload`` differs from the current one
    (older sweep constants, older synthetic generator) can't win
    either: their fps and acc were measured on a different problem.
    """
    path = os.path.join(REPO, "benchmarks", "tune_headline.json")
    try:
        cells = json.load(open(path))
    except Exception:  # noqa: BLE001 — absent/corrupt: no sweep yet
        return None
    ok = [
        c for c in cells
        if c.get("fps") and c.get("acc") and c["acc"] >= min_acc
        and c.get("workload") == workload
    ]
    return max(ok, key=lambda c: c["fps"]) if ok else None


def fail(metric: str, error: str) -> None:
    print(json.dumps({
        "metric": metric, "value": None, "unit": "fits/sec",
        "vs_baseline": None, "parity": None, "error": error,
    }))
    sys.exit(1)


def measure_cpu_baseline(X, y, l2: float, n_fits: int = 5,
                         budget_s: float = 180.0) -> dict:
    """sklearn CPU proxy: seconds per base-learner fit (mean over up to
    n_fits bootstrap fits, stopping early past the time budget)."""
    rng = np.random.default_rng(0)
    times, accs = [], []
    t_start = time.perf_counter()
    for _ in range(n_fits):
        # bootstrap resample, as the reference's loop would
        w = rng.poisson(1.0, len(y))
        idx = np.repeat(np.arange(len(y)), w)
        t0 = time.perf_counter()
        lr = _sk_lr(l2, len(idx)).fit(X[idx], y[idx])
        times.append(time.perf_counter() - t0)
        accs.append(lr.score(X, y))
        if time.perf_counter() - t_start > budget_s and len(times) >= 2:
            break
    return {
        "seconds_per_fit": float(np.mean(times)),
        "fits_per_sec": 1.0 / float(np.mean(times)),
        "accuracy": float(np.mean(accs)),
        "n_fits_measured": len(times),
        "proxy": "sklearn LogisticRegression (no Spark/JVM available)",
    }


def measure_cpu_predict_baseline(X, y, l2: float) -> dict:
    """CPU proxy for the INFERENCE hot path [SURVEY §3.2]: rows/sec of
    ONE sklearn model's predict_proba; an R-model soft-vote ensemble
    costs ~R× that, so the ensemble-side proxy is this divided by
    n_replicas (no batching tricks exist in the reference's per-model
    UDF loop to beat that)."""
    import time as _time

    lr = _sk_lr(l2, len(y)).fit(X, y)
    n = min(100_000, len(y))
    lr.predict_proba(X[:n])  # warm (BLAS paging)
    t0 = _time.perf_counter()
    lr.predict_proba(X[:n])
    rows_per_sec = n / (_time.perf_counter() - t0)
    return {"predict_rows_per_sec_single": rows_per_sec, "n_rows": n}


def measure_cpu_baseline_parallel(X, y, l2: float) -> dict:
    """All-cores CPU proxy [VERDICT r2 weak#5]: the SAME bare-LR
    bootstrap-fit loop as the serial baseline, fanned out with joblib
    ``n_jobs=-1`` — the `local[*]`-analog the single-process number can
    be challenged with. Workload-matched on purpose: a different
    estimator (e.g. sklearn's BaggingClassifier) adds per-estimator
    resample-copy overhead that would make the parallel baseline
    SLOWER than serial on few cores and so inflate, not stress,
    the reported speedup. ``cpu_cores`` is emitted so the comparison is
    auditable either way.
    """
    import os as _os

    from joblib import Parallel, delayed

    n_cores = _os.cpu_count() or 1
    n_fits = max(4, min(32, 2 * n_cores))
    rng = np.random.default_rng(0)
    idxs = [
        np.repeat(np.arange(len(y)), rng.poisson(1.0, len(y)))
        for _ in range(n_fits)
    ]

    def one(idx):
        # fit ONLY inside the timed window — the serial baseline times
        # fits and scores outside it, and the two must stay
        # workload-matched or vs_baseline_parallel is biased; the
        # fitted model returns to the parent (small: coef_ + intercept_)
        # and scoring happens after the clock stops
        return _sk_lr(l2, len(idx)).fit(X[idx], y[idx])

    # warm the worker pool before the timed window: loky process spawn
    # (~1s+) must not be billed as baseline fit time — that would
    # DEFLATE the baseline and overstate our speedup
    pool = Parallel(n_jobs=-1)
    pool(delayed(int)(i) for i in range(n_cores))
    t0 = time.perf_counter()
    models = pool(delayed(one)(idx) for idx in idxs)
    wall = time.perf_counter() - t0
    accs = [m.score(X, y) for m in models]
    return {
        "seconds_per_fit": wall / n_fits,
        "fits_per_sec": n_fits / wall,
        "accuracy": float(np.mean(accs)),
        "n_fits_measured": n_fits,
        "cpu_cores": n_cores,
        "proxy": (
            "joblib n_jobs=-1 over sklearn LogisticRegression "
            "bootstrap fits (workload-matched to the serial baseline)"
        ),
    }


def _measure(args) -> dict:
    """The measured phase (child mode): repeated fits, accuracy, and
    the steady-state predict path. Returns a JSON-serializable dict.

    The whole phase runs under ``telemetry.capture`` writing
    ``telemetry.jsonl`` into the telemetry dir (``$SBT_TELEMETRY_DIR``,
    default ``./telemetry/`` — run artifacts stay out of the git
    tree): every compile/fit/h2d span and registry counter of the
    measured run is machine-readable afterwards (render with
    ``python -m spark_bagging_tpu.telemetry dump telemetry/telemetry.jsonl``).
    """
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import compile_cache

    compile_cache.enable()

    from headline_data import HEADLINE, load_headline_data
    from spark_bagging_tpu import BaggingClassifier, LogisticRegression
    from spark_bagging_tpu import telemetry

    jsonl_path = telemetry.default_log_path("telemetry.jsonl")
    try:  # fresh log per measured run (capture appends)
        os.unlink(jsonl_path)
    except OSError:
        pass

    X, y = load_headline_data(args.n_rows)
    learner = LogisticRegression(
        l2=args.l2,
        max_iter=(HEADLINE["max_iter"] if args.max_iter is None
                  else args.max_iter),
        init=args.init or HEADLINE["init"],
        precision=args.precision,
        row_tile=args.row_tile, hessian_impl=args.hessian_impl,
    )
    clf = BaggingClassifier(
        base_learner=learner,
        n_estimators=args.n_replicas,
        chunk_size=args.chunk_size or None,  # 0 → HBM-aware auto
        seed=0,
    )
    report, first_report, fit_seconds_all = None, None, []
    with telemetry.capture(jsonl_path, label="bench_headline") as t_run:
        for _ in range(max(1, args.repeat)):
            clf.fit(X, y)  # includes compile; fit_report_ splits the two
            if first_report is None:
                first_report = clf.fit_report_
            fit_seconds_all.append(round(clf.fit_report_["fit_seconds"], 2))
            if report is None or clf.fit_report_["fit_seconds"] < report["fit_seconds"]:
                report = clf.fit_report_
        # compile/h2d come from the FIRST run — later runs hit the
        # compile cache and would report ~0, hiding the one-time cost
        report = dict(report)
        report["compile_seconds"] = first_report["compile_seconds"]
        report["h2d_seconds"] = first_report["h2d_seconds"]
        acc = float(clf.score(X[:100_000], y[:100_000]))

        # Inference hot path [SURVEY §3.2]: the batched 1000-replica
        # forward + soft-vote reduction, timed steady-state (one warm-up
        # call compiles + pages in the row block).
        n_pred = min(100_000, args.n_rows)
        clf.predict_proba(X[:n_pred])
        t0 = time.perf_counter()
        clf.predict_proba(X[:n_pred])
        predict_rows_per_sec = n_pred / (time.perf_counter() - t0)
    return {
        "report": json.loads(json.dumps(report, default=str)),
        "fit_seconds_all": fit_seconds_all,
        "acc": acc,
        "predict_rows_per_sec": predict_rows_per_sec,
        # persistent-cache counters: evidence of whether executables
        # from a prior window were reused (hits) or the remote-compile
        # path defeated client-side caching [VERDICT r4 ask#2]
        "compile_cache": compile_cache.stats(),
        "telemetry_jsonl": jsonl_path,
        "telemetry_events": t_run.n_events,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n-replicas", type=int, default=1000)
    p.add_argument("--n-rows", type=int, default=581_012)
    # Tuned on v5e-1 (2026-07-29): chunk=200 is the HBM sweet spot
    # without row tiling (500 OOMs on the (chunk, n, C) softmax temp);
    # 3 damped-Newton iters reach accuracy parity (0.7756 vs CPU
    # 0.7762, tolerance 0.01); "high" (bf16_3x) matmul precision keeps
    # parity at ~2.7x the fp32 MXU rate. --row-tile bounds the softmax
    # temps at (chunk, tile, C), lifting the chunk ceiling. When the
    # on-chip sweep (tune_headline.json) has been captured, its winner
    # supersedes these hand-tuned defaults (explicit flags still win).
    p.add_argument("--chunk-size", type=int, default=None,
                   help="0 = HBM-aware auto resolution (utils/memory.py); "
                   "unset = sweep winner if captured, else 200")
    p.add_argument("--row-tile", type=int, default=None)
    p.add_argument("--no-sweep", action="store_true",
                   help="ignore a captured tune_headline.json and run "
                   "the pre-sweep hand-tuned defaults")
    # "blocked" emits C²/2 (d, d)-output matmuls — at d=55 the MXU's
    # 128x128 output tiles run ~18% full; "fused" emits one
    # (C·d, n)@(n, C·d) matmul whose 385-wide output tiles far better
    # (but 1.75x the FLOPs); "packed" keeps blocked's FLOPs while
    # concatenating the scaled copies into one (d, n)@(n, P·d) matmul
    # (~43% fill) — needs --row-tile.
    p.add_argument("--hessian-impl", default="auto",
                   choices=["auto", "blocked", "fused", "packed", "pallas"])
    # max_iter/init are sweep-tunable solver knobs (None = sweep winner
    # if captured, else the HEADLINE defaults 3/"zeros"); init="pooled"
    # warm-starts every replica from one shared pooled solve
    p.add_argument("--max-iter", type=int, default=None)
    p.add_argument("--init", default=None, choices=["zeros", "pooled"])
    p.add_argument("--l2", type=float, default=1e-3)
    p.add_argument("--precision", default="high")
    p.add_argument("--parity-tol", type=float, default=0.01)
    # The axon tunnel shows large transient run-to-run variance (a
    # 4x fit-time swing between back-to-back identical runs was
    # recorded 2026-07-30); the compile cache makes re-fits cheap, so
    # the headline is the BEST fit wall-clock over --repeat executions
    # — steady-state device throughput, not tunnel weather.
    p.add_argument("--repeat", type=int, default=2)
    p.add_argument("--probe-timeout", type=float, default=120.0,
                   help="per-attempt backend-init timeout (seconds)")
    p.add_argument("--probe-deadline", type=float, default=1500.0,
                   help="keep re-probing a dead backend every "
                   "--probe-interval seconds until this deadline — wide "
                   "enough that the driver's single invocation catches "
                   "a flapping tunnel [VERDICT r3 ask#2]")
    p.add_argument("--probe-interval", type=float, default=120.0)
    # A tunnel-side crash can wedge a JAX client mid-fit (not error —
    # hang); the measured phase therefore runs in an isolated child
    # process group, and on expiry the parent still prints the one-line
    # JSON error the driver parses [VERDICT r1 weak#1].
    p.add_argument("--measure-timeout", type=float, default=1500.0)
    p.add_argument(
        "--measure-only", action="store_true",
        help="(internal) run the measured phase in-process and print a "
        "MEASURE_RESULT line — the isolation child mode",
    )
    p.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. 'cpu' to debug off-TPU)",
    )
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    metric = "fits_per_sec_logreg_bag1000_covtype581k"

    if args.measure_only:
        try:
            measured = _measure(args)
        except Exception as e:  # noqa: BLE001 — child reports, parent records
            measured = {"error": f"{type(e).__name__}: {e}"[:400]}
        print("MEASURE_RESULT " + json.dumps(measured, default=str),
              flush=True)
        return

    backend, reason = probe_backend_until(
        args.probe_deadline, args.probe_timeout, args.probe_interval,
        platform=args.platform,
    )
    if backend is None:
        fail(metric, f"jax backend unavailable — {reason}")

    from headline_data import HEADLINE, WORKLOAD, baseline_cache_key

    config_key = baseline_cache_key(args.n_rows, args.l2)
    cache = {}
    if os.path.exists(CACHE_PATH):
        with open(CACHE_PATH) as f:
            cache = json.load(f)
    # the parallel baseline is host-shaped: a cached entry from a
    # different core count would silently mis-scale vs_baseline_parallel
    cores_stale = (
        config_key in cache
        and cache[config_key].get("parallel", {}).get("cpu_cores")
        != (os.cpu_count() or 1)
    )
    predict_missing = (
        config_key in cache and "predict" not in cache[config_key]
    )
    if config_key not in cache or cores_stale or predict_missing:
        from headline_data import load_headline_data

        X, y = load_headline_data(args.n_rows)
        fresh = config_key not in cache
        if fresh:
            cache[config_key] = measure_cpu_baseline(X, y, args.l2)
        if fresh or cores_stale:
            cache[config_key]["parallel"] = measure_cpu_baseline_parallel(
                X, y, args.l2
            )
        if "predict" not in cache[config_key]:
            cache[config_key]["predict"] = measure_cpu_predict_baseline(
                X, y, args.l2
            )
        with open(CACHE_PATH, "w") as f:
            json.dump(cache, f, indent=2)
    baseline = cache[config_key]
    baseline_par = baseline["parallel"]

    # Self-tuning from the captured on-chip sweep: the winner's
    # (impl, chunk, row_tile) apply ALL-OR-NOTHING, and only when every
    # one of the three knobs was left at its default — the trio is
    # co-tuned (packed's temp is O(chunk·tile·P·d); a winner chunk
    # under a different impl is meaningless), so explicit flags opt the
    # whole run out of sweep tuning. --no-sweep forces the pre-sweep
    # defaults even with all flags defaulted.
    hessian_impl = args.hessian_impl
    chunk_size = args.chunk_size
    row_tile = args.row_tile
    max_iter = args.max_iter
    init = args.init
    tuned_from = None
    all_defaulted = (
        hessian_impl == "auto" and chunk_size is None and row_tile is None
        and max_iter is None and init is None
    )
    # …and only on the sweep's own workload + backend: a winner measured
    # on 581k TPU rows at l2=1e-3 says nothing about --n-rows 50000 or
    # --platform cpu (where a pallas winner wouldn't even compile), and
    # its acc would gate against an incomparable baseline
    workload_matches = (
        backend == "tpu"
        and args.n_replicas == HEADLINE["n_replicas"]
        and args.n_rows == HEADLINE["n_rows"]
        and args.l2 == HEADLINE["l2"]
        and args.precision == HEADLINE["precision"]
    )
    if all_defaulted and workload_matches and not args.no_sweep:
        sweep = load_sweep_winner(
            baseline["accuracy"] - args.parity_tol, WORKLOAD
        )
        if sweep is not None:
            hessian_impl = sweep["impl"]
            # prefer what the winning cell actually resolved to; a null
            # chunk_resolved on the auto cell means it ran UNchunked, so
            # reproduce that via auto (chunk_size=0), not the hand-tuned
            # 200 the sweep never measured
            if sweep.get("chunk_resolved") is not None:
                chunk_size = sweep["chunk_resolved"]
            elif sweep["chunk"] is not None:
                chunk_size = sweep["chunk"]
            else:
                chunk_size = 0
            row_tile = sweep["row_tile"]
            max_iter = sweep.get("max_iter", HEADLINE["max_iter"])
            init = sweep.get("init", HEADLINE["init"])
            tuned_from = {
                k: sweep.get(k)
                for k in ("impl", "chunk", "row_tile", "max_iter",
                          "init", "fps")
            }
    if chunk_size is None:
        chunk_size = 200  # pre-sweep hand-tuned default
    if max_iter is None:
        max_iter = HEADLINE["max_iter"]
    if init is None:
        init = HEADLINE["init"]

    # measured phase: isolated child process group with a hard timeout
    # (a wedged tunnel RPC must yield the JSON error line, not rc=124)
    from isolation import child_cmd, run_isolated_child

    cmd = child_cmd(
        os.path.abspath(__file__), "--measure-only",
        "--hessian-impl", hessian_impl,
        "--chunk-size", str(chunk_size),
        "--n-replicas", str(args.n_replicas),
        "--n-rows", str(args.n_rows),
        "--l2", str(args.l2),
        "--max-iter", str(max_iter),
        "--init", init,
        "--precision", args.precision,
        "--repeat", str(args.repeat),
    )
    if row_tile is not None:
        cmd += ["--row-tile", str(row_tile)]
    if args.platform:
        cmd += ["--platform", args.platform]
    measured, error = run_isolated_child(
        cmd, args.measure_timeout, "MEASURE_RESULT"
    )
    if error is not None:
        fail(metric, f"measurement child failed: {error}"[:400])
    if measured.get("error"):
        fail(metric, f"fit failed: {measured['error']}"[:400])

    report = measured["report"]
    fit_seconds_all = measured["fit_seconds_all"]
    acc = measured["acc"]
    predict_rows_per_sec = measured["predict_rows_per_sec"]
    parity = bool(acc >= baseline["accuracy"] - args.parity_tol)

    fps = report["fits_per_sec"]
    result = {
        "metric": metric,
        "value": round(fps, 2) if parity else None,
        "unit": "fits/sec",
        "vs_baseline": (
            round(fps / baseline["fits_per_sec"], 1) if parity else None
        ),
        # all-cores sklearn bagging proxy (== serial on a 1-core host;
        # see cpu_cores) so the speedup claim is robust to the
        # "local[*] would use every core" challenge [VERDICT r2 weak#5]
        "vs_baseline_parallel": (
            round(fps / baseline_par["fits_per_sec"], 1) if parity else None
        ),
        "cpu_cores": baseline_par["cpu_cores"],
        "parity": parity,
        "ensemble_accuracy": round(acc, 4),
        "cpu_baseline_accuracy": round(baseline["accuracy"], 4),
        "backend": report["backend"],
        "fit_seconds": round(report["fit_seconds"], 2),
        # best-of-N protocol: every run's fit time is recorded so a
        # best-of-N number is never mistaken for a single-run one
        "repeat": max(1, args.repeat),
        "fit_seconds_all": fit_seconds_all,
        "compile_seconds": round(report["compile_seconds"], 2),
        "h2d_seconds": round(report["h2d_seconds"], 3),
        "fits_per_sec_e2e": round(report["fits_per_sec_e2e"], 2),
        "predict_rows_per_sec": round(predict_rows_per_sec, 0),
        # inference hot path vs the CPU proxy: an R-model sklearn
        # soft-vote pays ~R single-model predicts, so the ensemble-side
        # CPU rate is single-model rows/sec ÷ R [SURVEY §3.2]
        "vs_baseline_predict": round(
            predict_rows_per_sec
            / (baseline["predict"]["predict_rows_per_sec_single"]
               / args.n_replicas), 1
        ),
        "hessian_impl": hessian_impl,
        "chunk_size": chunk_size,
        "max_iter": max_iter,
        "init": init,
        "tuned_from_sweep": tuned_from,
        "compile_cache": measured.get("compile_cache"),
        # full instrument panel of the measured run (spans + registry),
        # written next to the BENCH artifacts; render with
        # `python -m spark_bagging_tpu.telemetry dump <path>`
        "telemetry_jsonl": measured.get("telemetry_jsonl"),
        "telemetry_events": measured.get("telemetry_events"),
    }
    if report.get("mfu") is not None:
        result["achieved_tflops"] = round(report["achieved_tflops"], 1)
        result["mfu"] = round(report["mfu"], 3)
    if args.verbose:
        detail = dict(report)
        detail["cpu_baseline"] = baseline
        print(json.dumps(detail, default=str), file=sys.stderr)
    print(json.dumps(result))
    if not parity:
        sys.exit(1)


if __name__ == "__main__":
    main()
