#!/usr/bin/env python
"""Headline benchmark: 1000-replica LogisticRegression bag on
covtype-shaped data — base-learner fits/sec vs the CPU baseline
[B:2, B:5, BASELINE.md row ★].

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "fits/sec", "vs_baseline": N}

Baseline protocol (BASELINE.md measurement notes): no Spark/JVM exists
in this environment, so the documented CPU proxy is sklearn
LogisticRegression fits on the same data, single process. The CPU
number is measured once and cached in ``bench_baseline_cache.json``
(keyed by config) so driver runs don't re-pay it; delete the file to
re-measure. Accuracy parity is checked at matched hyperparameters —
the benchmark result is only valid if the TPU ensemble's accuracy is
within tolerance of the CPU single-model accuracy (bagging of linear
models matches, not beats, the single linear model).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
CACHE_PATH = os.path.join(REPO, "bench_baseline_cache.json")


def measure_cpu_baseline(X, y, l2: float, n_fits: int = 2) -> dict:
    """sklearn CPU proxy: seconds per base-learner fit."""
    from sklearn.linear_model import LogisticRegression as SkLR

    rng = np.random.default_rng(0)
    times, accs = [], []
    for i in range(n_fits):
        # bootstrap resample, as the reference's loop would
        w = rng.poisson(1.0, len(y))
        idx = np.repeat(np.arange(len(y)), w)
        t0 = time.perf_counter()
        lr = SkLR(max_iter=100, C=1.0 / (l2 * len(idx))).fit(X[idx], y[idx])
        times.append(time.perf_counter() - t0)
        accs.append(lr.score(X, y))
    return {
        "seconds_per_fit": float(np.mean(times)),
        "fits_per_sec": 1.0 / float(np.mean(times)),
        "accuracy": float(np.mean(accs)),
        "n_fits_measured": n_fits,
        "proxy": "sklearn LogisticRegression (no Spark/JVM available)",
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n-replicas", type=int, default=1000)
    p.add_argument("--n-rows", type=int, default=581_012)
    # Tuned on v5e-1 (2026-07-29): chunk=200 is the HBM sweet spot (500
    # OOMs on the (chunk, n, C) softmax temp); 3 damped-Newton iters
    # reach accuracy parity (0.7756 vs CPU 0.7762, tolerance 0.01) —
    # quadratic convergence makes iters 4-5 pure cost; "high"
    # (bf16_3x) matmul precision keeps parity at ~2.7x the fp32 MXU
    # rate. 5-iter/"highest" config: 46 fits/s; this config: ~109.
    p.add_argument("--chunk-size", type=int, default=200)
    p.add_argument("--max-iter", type=int, default=3)
    p.add_argument("--l2", type=float, default=1e-3)
    p.add_argument("--precision", default="high")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    import jax

    from spark_bagging_tpu import BaggingClassifier, LogisticRegression
    from spark_bagging_tpu.utils.datasets import synthetic_covtype

    X, y = synthetic_covtype(args.n_rows)
    mu, sigma = X.mean(0), X.std(0) + 1e-8
    X = ((X - mu) / sigma).astype(np.float32)

    config_key = hashlib.sha1(
        json.dumps(
            ["covtype_synth_v1", args.n_rows, args.l2], sort_keys=True
        ).encode()
    ).hexdigest()[:12]
    cache = {}
    if os.path.exists(CACHE_PATH):
        with open(CACHE_PATH) as f:
            cache = json.load(f)
    if config_key not in cache:
        cache[config_key] = measure_cpu_baseline(X, y, args.l2)
        with open(CACHE_PATH, "w") as f:
            json.dump(cache, f, indent=2)
    baseline = cache[config_key]

    learner = LogisticRegression(
        l2=args.l2, max_iter=args.max_iter, precision=args.precision
    )
    clf = BaggingClassifier(
        base_learner=learner,
        n_estimators=args.n_replicas,
        chunk_size=args.chunk_size,
        seed=0,
    )
    clf.fit(X, y)  # includes compile; fit_report_ separates the two
    report = clf.fit_report_
    acc = clf.score(X[: 100_000], y[: 100_000])

    fps = report["fits_per_sec"]
    result = {
        "metric": "fits_per_sec_logreg_bag1000_covtype581k",
        "value": round(fps, 2),
        "unit": "fits/sec",
        "vs_baseline": round(fps / baseline["fits_per_sec"], 1),
    }
    if args.verbose:
        detail = {
            "backend": report["backend"],
            "fit_seconds": round(report["fit_seconds"], 2),
            "compile_seconds": round(report["compile_seconds"], 2),
            "ensemble_accuracy": round(acc, 4),
            "cpu_baseline_accuracy": round(baseline["accuracy"], 4),
            "cpu_baseline_fits_per_sec": round(
                baseline["fits_per_sec"], 3
            ),
            "accuracy_parity": bool(
                acc >= baseline["accuracy"] - 0.01
            ),
        }
        print(json.dumps(detail), file=sys.stderr)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
