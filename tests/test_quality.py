"""Model-quality plane [ISSUE 9]: sketch math, the fit-time reference
profile and its checkpoint round-trip, the executor tap on BOTH
dispatch paths, ensemble-disagreement parity (served outputs stay
bitwise-identical with the tap enabled), concurrent sketch updates,
and the zero-overhead-when-disabled contract.
"""

import math
import threading
import time

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.telemetry import quality
from spark_bagging_tpu.telemetry.quality import (
    MomentSketch,
    P2Quantile,
    QualityMonitor,
    ReferenceProfile,
    bin_counts,
    disagreement_stats,
    ks_stat,
    psi,
)
from spark_bagging_tpu.serving import (
    EnsembleExecutor,
    ModelRegistry,
    program_cache,
)
from spark_bagging_tpu.serving.batcher import MicroBatcher


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.reset()
    telemetry.enable()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 6)).astype(np.float32)
    y = (X[:, 0] + 0.2 * rng.normal(size=300) > 0).astype(np.int32)
    return X, y


@pytest.fixture(scope="module")
def clf(data):
    X, y = data
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=8, seed=0, oob_score=True,
    ).fit(X, y)


def fresh_executor(model):
    ex = EnsembleExecutor(model, min_bucket_rows=8, max_batch_rows=32)
    ex.warmup()
    return ex


@pytest.fixture(scope="module")
def shared_ex(clf):
    """One warmed executor shared by tests that only attach/detach
    monitors (tier-1 wall-clock: each warmup is 3 bucket compiles on a
    1-CPU host). Tests asserting compile COUNTS build their own."""
    return fresh_executor(clf)


# -- sketch primitives --------------------------------------------------

class TestSketches:
    def test_p2_tracks_quantiles(self):
        rng = np.random.default_rng(3)
        vals = rng.normal(size=4000)
        for q in (0.5, 0.95):
            sk = P2Quantile(q)
            for v in vals:
                sk.update(v)
            true = np.quantile(vals, q)
            assert abs(sk.value() - true) < 0.1, (q, sk.value(), true)

    def test_p2_exact_small_samples_and_empty(self):
        sk = P2Quantile(0.5)
        assert math.isnan(sk.value())
        for v in (5.0, 1.0, 3.0):
            sk.update(v)
        assert sk.value() == 3.0  # exact nearest-rank below 5 samples
        with pytest.raises(ValueError, match="q must be"):
            P2Quantile(1.5)

    def test_moment_sketch_matches_numpy(self):
        rng = np.random.default_rng(4)
        X = rng.normal(size=(500, 3)) * [1.0, 2.0, 0.5] + [0, 1, -1]
        ms = MomentSketch(3)
        for chunk in np.array_split(X, 7):
            ms.update(chunk)
        assert ms.count == 500
        np.testing.assert_allclose(ms.mean(), X.mean(axis=0),
                                   rtol=1e-9)
        np.testing.assert_allclose(ms.std(), X.std(axis=0), rtol=1e-6)

    def test_bin_counts_total_and_edges(self):
        edges = np.array([0.0, 1.0, 2.0])
        counts = bin_counts(np.array([-5, 0.5, 1.5, 3.0, 0.5]), edges)
        assert counts.sum() == 5
        assert list(counts) == [1, 2, 1, 1]

    def test_psi_zero_on_match_large_on_shift(self):
        rng = np.random.default_rng(5)
        ref_sample = rng.normal(size=4000)
        edges = np.quantile(ref_sample, np.arange(1, 10) / 10)
        ref = bin_counts(ref_sample, edges) / 4000
        same = bin_counts(rng.normal(size=4000), edges)
        shifted = bin_counts(rng.normal(size=4000) + 3.0, edges)
        assert psi(ref, same) < 0.05
        assert psi(ref, shifted) > 1.0
        assert ks_stat(ref, same) < 0.05
        assert ks_stat(ref, shifted) > 0.5

    def test_psi_small_sample_noise_is_bounded(self):
        """The Laplace-smoothing property: 20 in-distribution rows
        against 10 reference bins must NOT scream drift (a raw epsilon
        floor scored ~2.0 here purely from empty bins)."""
        rng = np.random.default_rng(6)
        ref_sample = rng.normal(size=4000)
        edges = np.quantile(ref_sample, np.arange(1, 10) / 10)
        ref = bin_counts(ref_sample, edges) / 4000
        small = bin_counts(rng.normal(size=20), edges)
        assert psi(ref, small) < 0.8

    def test_psi_empty_stream_is_zero(self):
        assert psi([0.5, 0.5], [0, 0]) == 0.0
        assert ks_stat([0.5, 0.5], [0, 0]) == 0.0


# -- the reference profile ----------------------------------------------

class TestReferenceProfile:
    def test_fit_computes_profile_with_oob_confidence(self, clf):
        prof = clf.quality_profile_
        assert prof is not None
        assert prof.task == "classification"
        assert prof.n_features == 6
        assert len(prof.feature_edges) == 6
        assert all(len(fr) == 10 for fr in prof.feature_fractions)
        assert prof.class_fractions is not None
        assert abs(sum(prof.class_fractions) - 1.0) < 1e-9
        # oob_score=True filled the held-out confidence reference
        assert prof.confidence_source == "oob"
        assert abs(sum(prof.confidence_fractions) - 1.0) < 1e-9

    def test_regressor_profile_has_prediction_reference(self, data):
        X, _ = data
        y = (X[:, 0] * 2.0 + 0.1).astype(np.float32)
        reg = BaggingRegressor(n_estimators=4, seed=0).fit(X, y)
        prof = reg.quality_profile_
        assert prof.task == "regression"
        assert prof.prediction_fractions is not None
        assert prof.class_fractions is None

    def test_dict_round_trip(self, clf):
        d = clf.quality_profile_.to_dict()
        import json

        json.dumps(d)  # JSON-friendly by construction
        assert ReferenceProfile.from_dict(d).to_dict() == d
        with pytest.raises(ValueError, match="schema"):
            ReferenceProfile.from_dict({**d, "schema": 999})

    def test_checkpoint_round_trips_profile(self, clf, tmp_path):
        path = str(tmp_path / "ckpt")
        clf.save(path)
        loaded = BaggingClassifier.load(path)
        assert loaded.quality_profile_.to_dict() \
            == clf.quality_profile_.to_dict()

    def test_malformed_profile_degrades_load_not_bricks_it(
            self, clf, tmp_path):
        """A truncated/hand-edited profile dict in a checkpoint must
        warn and load the WEIGHTS — monitoring degrades, the model
        does not brick."""
        import json
        import os

        path = str(tmp_path / "ckpt")
        clf.save(path)
        mpath = os.path.join(path, "manifest.json")
        manifest = json.load(open(mpath))
        manifest["fitted"]["quality_profile_"] = {"schema": 1}  # torn
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        with pytest.warns(UserWarning, match="not restored"):
            loaded = BaggingClassifier.load(path)
        assert not hasattr(loaded, "quality_profile_") \
            or loaded.quality_profile_ is None
        assert loaded.n_estimators_ == clf.n_estimators_

    def test_profile_determinism(self, data):
        X, y = data
        a = ReferenceProfile.from_training(
            X, y, task="classification", n_classes=2)
        b = ReferenceProfile.from_training(
            X, y, task="classification", n_classes=2)
        assert a.to_dict() == b.to_dict()


# -- the live monitor ---------------------------------------------------

class TestMonitor:
    def _profile(self, X, y):
        return ReferenceProfile.from_training(
            X, y, task="classification", n_classes=2)

    def test_drift_scores_rise_on_shift(self, data):
        X, y = data
        mon = QualityMonitor(self._profile(X, y), refresh_every=1)
        rng = np.random.default_rng(1)
        mon.observe(rng.normal(size=(200, 6)).astype(np.float32))
        clean = mon.drift()
        assert clean["warmed"] is True
        assert clean["psi_max"] < 0.5
        mon.observe(
            (rng.normal(size=(200, 6)) + 4.0).astype(np.float32))
        assert mon.drift()["psi_max"] > 1.0

    def test_min_rows_gates_gauge_export_not_scores(self, data):
        X, y = data
        mon = QualityMonitor(self._profile(X, y), refresh_every=1,
                             min_rows=100)
        mon.observe((X[:10] + 9.0).astype(np.float32))
        d = mon.drift()
        assert d["warmed"] is False and d["psi_max"] > 0  # raw score
        reg = telemetry.registry()
        assert reg.gauge("sbt_quality_psi_max").value == 0.0  # gated
        mon.observe((np.tile(X[:10], (10, 1)) + 9.0).astype(np.float32))
        assert reg.gauge("sbt_quality_psi_max").value > 0.5

    def test_concurrent_sketch_updates_lose_nothing(self, data):
        """Satellite: quality taps fed simultaneously from the batcher
        worker and a direct-dispatch caller thread must never lose
        updates or deadlock. 8 threads x 50 observes, every row
        accounted for in rows AND bin counts."""
        X, y = data
        mon = QualityMonitor(self._profile(X, y), refresh_every=64,
                             disagreement_every=3)
        n_threads, n_iter, rows = 8, 50, 7
        block = X[:rows]
        out = np.full((rows, 2), 0.5, np.float32)

        def feeder():
            for _ in range(n_iter):
                mon.observe(block, out)
                mon.wants_disagreement()

        threads = [threading.Thread(target=feeder)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads), "deadlocked"
        total = n_threads * n_iter * rows
        summ = mon.summary()
        assert summ["rows_observed"] == total
        assert summ["batches"] == n_threads * n_iter
        assert mon._feat_counts[0].sum() == total
        assert mon._conf_counts.sum() == total

    def test_lock_order_clean_under_debug_locks(self, data):
        """The PR-4 lock-order detector sees the quality/alert locks
        (make_lock): monitor refresh (quality -> registry) and alert
        evaluation (alerts -> registry) from concurrent threads must
        record zero inversions."""
        from spark_bagging_tpu.analysis import locks
        from spark_bagging_tpu.telemetry import alerts as alerts_mod

        X, y = data
        locks.enable(True)
        try:
            mon = QualityMonitor(self._profile(X, y), refresh_every=1)
            eng = alerts_mod.AlertEngine([alerts_mod.AlertRule(
                "t", "sbt_quality_psi_max", threshold=0.5,
                fast_window_s=1, slow_window_s=2,
            )])

            def a():
                for _ in range(50):
                    mon.observe(X[:4])

            def b():
                for i in range(50):
                    eng.evaluate(now=float(i))

            ts = [threading.Thread(target=a),
                  threading.Thread(target=b)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            assert locks.violations() == []
        finally:
            locks.enable(False)


# -- the executor tap ---------------------------------------------------

class TestExecutorTap:
    def test_attach_feeds_and_bitwise_parity(self, clf, shared_ex, data):
        """The acceptance bitwise bar: with the monitor attached AND
        the disagreement tap sampling every batch, served outputs are
        byte-identical to the untapped executor's."""
        X, _ = data
        ex = shared_ex
        ex.detach_quality()
        base = ex.predict_proba(X[:50])
        mon = quality.attach(ex, refresh_every=1,
                             disagreement_every=1)
        tapped = ex.predict_proba(X[:50])
        np.testing.assert_array_equal(base, tapped)
        assert mon.summary()["rows_observed"] == 50
        assert mon.summary()["disagreement_samples"] == 1
        # and the batch API is untouched too
        np.testing.assert_array_equal(
            tapped, np.asarray(clf.predict_proba(X[:50]))
        )

    def test_tap_compiles_count_separately(self, clf, data):
        """Replica-tap compiles must NOT appear in the serving compile
        counter — the zero-post-warmup-compile gate is about the
        serving path."""
        X, _ = data
        # compile-count test: drop unified-cache entries earlier tests
        # compiled for this model, so real compiles happen and land in
        # the right counter
        program_cache.clear()
        ex = fresh_executor(clf)
        reg = telemetry.registry()
        before = reg.counter("sbt_serving_compiles_total").value
        quality.attach(ex, refresh_every=1, disagreement_every=1)
        ex.forward(X[:20])
        assert reg.counter("sbt_serving_compiles_total").value == before
        assert reg.counter(
            "sbt_quality_disagreement_compiles_total").value >= 1
        assert reg.counter(
            "sbt_quality_disagreement_samples_total").value >= 1

    def test_replica_forward_mean_is_the_served_output(self, clf, data):
        X, _ = data
        fn, params, subs = clf.replica_forward()
        rep = np.asarray(fn(params, subs, X[:16].astype(np.float32)))
        assert rep.shape == (8, 16, 2)
        agg = np.asarray(clf.predict_proba(X[:16]))
        np.testing.assert_allclose(rep.mean(axis=0), agg, rtol=1e-5)

    def test_hard_voting_replica_forward_matches_served_output(
            self, data):
        """voting='hard' models serve vote FREQUENCIES; the replica
        tap must emit per-replica one-hot votes (mean == served), not
        softmax probabilities whose argmax can differ from the served
        plurality."""
        X, y = data
        hard = BaggingClassifier(n_estimators=5, seed=0,
                                 voting="hard").fit(X, y)
        fn, params, subs = hard.replica_forward()
        rep = np.asarray(fn(params, subs, X[:16].astype(np.float32)))
        assert rep.shape == (5, 16, 2)
        assert set(np.unique(rep)) <= {0.0, 1.0}  # one-hot votes
        agg = np.asarray(hard.predict_proba(X[:16]))
        np.testing.assert_allclose(rep.mean(axis=0), agg, rtol=1e-6)

    def test_disagreement_stats_shapes(self):
        rep = np.stack([
            np.array([[0.9, 0.1], [0.2, 0.8]]),
            np.array([[0.8, 0.2], [0.9, 0.1]]),  # disagrees on row 1
        ])
        s = disagreement_stats(rep, "classification")
        assert s["rows"] == 2
        assert 0.0 < s["disagreement"] <= 0.5
        r = disagreement_stats(np.array([[1.0, 2.0], [3.0, 2.0]]),
                               "regression")
        assert r["disagreement"] == pytest.approx(
            np.array([[1.0, 2.0], [3.0, 2.0]]).std(axis=0).mean())

    def test_both_dispatch_paths_feed_the_monitor(self, shared_ex, data):
        """The tap seam sits under the coalescing worker AND direct
        dispatch: earn direct mode with a singleton streak, confirm
        feeds; then a pinned-coalesced batcher feeds too."""
        X, _ = data
        ex = shared_ex
        mon = quality.attach(ex, refresh_every=1)
        with MicroBatcher(ex, max_delay_ms=1.0) as b:
            for _ in range(MicroBatcher.DIRECT_AFTER_SINGLETONS + 4):
                b.predict_proba(X[:1], timeout=30)
            direct = telemetry.registry().counter(
                "sbt_serving_direct_dispatch_total").value
            assert direct > 0, "direct mode never earned"
        rows_after_direct = mon.summary()["rows_observed"]
        assert rows_after_direct \
            == MicroBatcher.DIRECT_AFTER_SINGLETONS + 4
        with MicroBatcher(ex, max_delay_ms=1.0,
                          direct_dispatch=False) as b:
            b.predict_proba(X[:5], timeout=30)
        assert mon.summary()["rows_observed"] == rows_after_direct + 5

    def test_monitor_failure_detaches_not_fails_serving(
            self, shared_ex, data):
        X, _ = data
        ex = shared_ex

        class Broken:
            def observe_parts(self, parts, outs):
                raise RuntimeError("sketch exploded")

            def wants_disagreement(self):
                return False

        ex.attach_quality(Broken())
        with pytest.warns(RuntimeWarning, match="detached"):
            out = ex.predict_proba(X[:4])
        assert out.shape == (4, 2)
        assert ex.quality is None  # detached, serving unharmed

    def test_attach_requires_a_profile(self, clf, shared_ex):
        # a model without quality_profile_ (e.g. an old checkpoint)
        saved = clf.quality_profile_
        clf.quality_profile_ = None
        try:
            with pytest.raises(ValueError, match="quality_profile_"):
                quality.attach(shared_ex)
        finally:
            clf.quality_profile_ = saved

    def test_swap_survives_profileless_replacement(self, clf, data):
        """Monitoring re-attach is best-effort: a swap to a model
        without a quality profile COMMITS (new version serves) and
        warns, instead of masquerading as a rejected swap."""
        X, y = data
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
        reg.register("m", clf, warmup=True)
        reg.enable_quality("m", refresh_every=1)
        clf2 = BaggingClassifier(n_estimators=2, seed=1).fit(X, y)
        clf2.quality_profile_ = None  # stream fit / old checkpoint
        with pytest.warns(RuntimeWarning, match="UNMONITORED"):
            reg.swap("m", clf2)
        assert reg.version("m") == 2          # the swap committed
        assert reg.executor("m").quality is None

    def test_profile_override_is_not_sticky_across_swap(
            self, clf, data):
        """An explicit profile= in enable_quality applies to the
        current executor only: the swapped-in model is scored against
        its OWN fit-time reference, never its predecessor's."""
        X, y = data
        custom = quality.ReferenceProfile.from_training(
            X + 100.0, y, task="classification", n_classes=2)
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
        reg.register("m", clf, warmup=True)
        mon1 = reg.enable_quality("m", profile=custom, refresh_every=1)
        assert mon1.profile is custom
        reg.swap("m", clf)
        mon2 = reg.executor("m").quality
        assert mon2.profile is clf.quality_profile_

    def test_fresh_monitor_resets_conditional_gauges(self, clf, data):
        """A re-attached monitor that cannot produce a signal (no
        confidence reference) must export 0.0 for it — a frozen stale
        breaching value would keep an alert alive forever."""
        X, y = data
        reg_t = telemetry.registry()
        ex = fresh_executor(clf)
        mon = quality.attach(ex, refresh_every=1, min_rows=0)
        mon.observe(np.asarray(X[:60] + 9.0),
                    np.full((60, 2), 0.5, np.float32))
        assert reg_t.gauge("sbt_quality_confidence_psi").value > 0.0
        # new model, no OOB confidence reference
        noconf = quality.ReferenceProfile.from_training(
            X, y, task="classification", n_classes=2)
        assert noconf.confidence_fractions is None
        quality.attach(ex, profile=noconf, refresh_every=1)
        assert reg_t.gauge("sbt_quality_confidence_psi").value == 0.0

    def test_profile_n_rows_is_true_training_size(self, clf, data):
        assert clf.quality_profile_.n_rows == len(data[0])

    def test_two_monitored_models_export_separate_series(
            self, clf, data):
        """Registry monitors are per-model labeled: a healthy model's
        refreshes must not clobber (and thereby mask) a drifting
        one's gauges under the alert rules."""
        X, _ = data
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
        reg.register("a", clf, warmup=True)
        reg.register("b", clf, warmup=True)
        mon_a = reg.enable_quality("a", refresh_every=1, min_rows=0)
        mon_b = reg.enable_quality("b", refresh_every=1, min_rows=0)
        assert mon_a.labels == {"model": "a"}
        reg.executor("a").forward(np.asarray(X[:60] + 9.0))  # drifts
        reg.executor("b").forward(np.asarray(X[:60]))        # healthy
        reg_t = telemetry.registry()
        psi_a = reg_t.gauge("sbt_quality_psi_max",
                            {"model": "a"}).value
        psi_b = reg_t.gauge("sbt_quality_psi_max",
                            {"model": "b"}).value
        assert psi_a > 1.0 and psi_b < 0.5

    def test_caller_monitor_is_not_sticky_across_swap(self, clf):
        """A monitor= passthrough installs for the current executor
        only: replaying the instance on swap would re-install the
        predecessor's reference profile AND its accumulated sketch
        counts verbatim."""
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
        reg.register("m", clf, warmup=True)
        mine = QualityMonitor(clf.quality_profile_, refresh_every=1)
        assert reg.enable_quality("m", monitor=mine) is mine
        assert reg.executor("m").quality is mine
        reg.swap("m", clf)
        fresh = reg.executor("m").quality
        assert fresh is not None and fresh is not mine

    def test_attach_prewarms_replica_tap_for_compiled_buckets(
            self, clf, data):
        """The disagreement tap must never absorb an XLA compile stall
        on the serving thread: attach pre-builds the per-replica
        executables for every already-compiled serving bucket."""
        X, _ = data
        # compile-count test: see test_tap_compiles_count_separately
        program_cache.clear()
        ex = fresh_executor(clf)  # serving ladder 8/16/32 compiled
        reg_t = telemetry.registry()
        c0 = reg_t.counter(
            "sbt_quality_disagreement_compiles_total").value
        quality.attach(ex, refresh_every=1, disagreement_every=1)
        prewarmed = reg_t.counter(
            "sbt_quality_disagreement_compiles_total").value - c0
        assert prewarmed == len(ex.compiled_buckets)
        ex.forward(X[:20])  # sampled batch: executable already live
        assert reg_t.counter(
            "sbt_quality_disagreement_compiles_total"
        ).value - c0 == prewarmed

    def test_registry_enable_quality_sticky_across_swap(
            self, clf, data, tmp_path):
        X, _ = data
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
        reg.register("m", clf, warmup=True)
        mon1 = reg.enable_quality("m", refresh_every=1)
        reg.executor("m").forward(X[:8])
        assert mon1.summary()["rows_observed"] == 8
        reg.swap("m", clf)
        mon2 = reg.executor("m").quality
        assert mon2 is not None and mon2 is not mon1
        assert mon2.summary()["rows_observed"] == 0  # fresh sketches
        reg.disable_quality("m")
        reg.swap("m", clf)
        assert reg.executor("m").quality is None


# -- /debug/drift and the zero-overhead contract -----------------------

class TestPlaneContracts:
    def test_debug_summary_lists_live_monitors(self, shared_ex, data):
        X, _ = data
        ex = shared_ex
        ex.detach_quality()
        mon = quality.attach(ex, refresh_every=1)
        ex.forward(X[:8])
        summ = quality.debug_summary()
        assert any(m["rows_observed"] == 8 for m in summ["monitors"])
        assert mon in quality.monitors()

    def test_no_monitor_no_quality_series(self, shared_ex, data):
        """Serving without an attached monitor must register NO
        sbt_quality series — the plane is genuinely off, not idling."""
        X, _ = data
        ex = shared_ex
        ex.detach_quality()
        telemetry.reset()
        ex.forward(X[:20])
        names = {e["name"] for e in telemetry.registry().snapshot()}
        assert not any(n.startswith("sbt_quality") for n in names)

    def test_disabled_tap_overhead_micro_benchmark(self, shared_ex):
        """The acceptance micro-benchmark (PR-1 style): the detached
        tap's hot-path gate is one attribute read — 200k iterations of
        the exact pattern `_forward_packed` runs must stay far under a
        microsecond each."""
        ex = shared_ex
        ex.detach_quality()
        assert ex._quality is None
        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            mon = ex._quality
            if mon is not None:  # pragma: no cover — detached
                raise AssertionError
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 2e-6, f"{per_call * 1e9:.0f}ns per gate"
