"""Deterministic trace replay + SLO gate [ISSUE 6 acceptance]:

- same workload + same seed ⇒ identical batch composition and
  BITWISE-identical outputs (the determinism contract, twice-replayed
  and digest-compared);
- the regression gate passes a clean baseline and trips on an
  injected 2x forward-path slowdown (throttled executor);
- scripted scenarios: burst injection sheds with Overloaded (counted,
  never fatal), hot swaps under fire keep outputs bitwise-identical;
- the CLI smoke (`python -m benchmarks.replay --check`, in-process)
  stays under the 10 s tier-1 budget, like the lint gate.
"""

import time

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.telemetry import workload
from spark_bagging_tpu.telemetry.workload import WorkloadRequest
from spark_bagging_tpu.serving import (
    EnsembleExecutor,
    ModelRegistry,
    program_cache,
)

from benchmarks import replay as R


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.reset()
    telemetry.enable()


@pytest.fixture(scope="module")
def clf():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=4, seed=0,
    ).fit(X, y)


@pytest.fixture(scope="module")
def executor(clf):
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32)
    ex.warmup()
    return ex


@pytest.fixture(scope="module")
def wl():
    return workload.synthetic_workload(
        "poisson", rate_rps=400, duration_s=0.3, seed=7, width=8,
        bucket_bounds=(8, 32),
    )


# -- the planner (pure function) ---------------------------------------

def test_plan_windows_time_rule():
    reqs = [WorkloadRequest(t=t, rows=1, width=2)
            for t in (0.0, 0.001, 0.004, 0.050, 0.051, 0.200)]
    wins = R.plan_windows(reqs, max_delay_s=0.010, idle_flush_s=0.005)
    assert wins == [[0, 1, 2], [3, 4], [5]]
    # idle gap splits inside an open window
    wins = R.plan_windows(reqs, max_delay_s=0.010, idle_flush_s=0.002)
    assert wins[0] == [0, 1]  # 3ms gap to t=0.004 exceeds idle flush
    # degenerate: every request alone when both knobs are ~zero
    wins = R.plan_windows(reqs, max_delay_s=0.0, idle_flush_s=0.0)
    assert [len(w) for w in wins] == [1] * len(reqs)


def test_inject_burst_is_deterministic_and_sorted(wl):
    a = R.inject_burst(wl, 16, at_frac=0.5)
    b = R.inject_burst(wl, 16, at_frac=0.5)
    assert a.n_requests == wl.n_requests + 16
    assert [r.t for r in a.requests] == sorted(r.t for r in a.requests)
    assert R.workload_digest(a) == R.workload_digest(b)
    assert R.workload_digest(a) != R.workload_digest(wl)
    assert R.inject_burst(wl, 0) is wl  # no-op passthrough
    # base requests keep their captured epoch labels; burst requests
    # join the epoch active at the splice point
    base_epochs = [r.epoch for r in wl.requests]
    kept = [r.epoch for r in a.requests
            if r.t in {x.t for x in wl.requests}]
    assert kept == base_epochs


# -- determinism contract ----------------------------------------------

def test_virtual_replay_bitwise_deterministic(executor, wl):
    r1 = R.replay(wl, executor=executor, seed=3)
    r2 = R.replay(wl, executor=executor, seed=3)
    assert r1["composition_digest"] == r2["composition_digest"]
    assert r1["output_digest"] == r2["output_digest"]
    assert r1["served"] == r2["served"] == wl.n_requests
    assert r1["batches"] == r2["batches"]
    # a different payload seed is a different replay
    r3 = R.replay(wl, executor=executor, seed=4)
    assert r3["output_digest"] != r1["output_digest"]
    assert r1["errors"] == 0 and r1["overloads"] == 0


def test_report_carries_the_slo_inputs(executor, wl):
    r = R.replay(wl, executor=executor, seed=3)
    assert r["post_warmup_compiles"] == 0
    assert r["rps"] > 0
    lat = r["latency_ms"]
    assert lat["p50"] is not None
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    pad = r["padding"]
    assert pad["rows_total"] >= wl.total_rows
    assert 0.0 <= pad["waste_rows_frac"] < 1.0
    # CPU XLA reports cost analysis, so the FLOPs denominator is live
    assert pad["waste_flops_frac"] is not None
    assert 0.0 <= pad["waste_flops_frac"] < 1.0
    assert r["workload_digest"] == R.workload_digest(wl)


def test_replay_median_merges_and_asserts_determinism(executor, wl):
    m = R.replay_median(wl, repeats=3, executor=executor, seed=3)
    assert m["repeats"] == 3
    assert len(m["rps_runs"]) == 3
    assert m["rps"] == sorted(m["rps_runs"])[1]
    single = R.replay(wl, executor=executor, seed=3)
    assert m["output_digest"] == single["output_digest"]


# -- scripted scenarios ------------------------------------------------

def test_burst_sheds_with_backpressure_not_failure(executor, wl):
    r = R.replay(wl, executor=executor, seed=3, burst=64, max_queue=16)
    assert r["overloads"] > 0
    assert r["errors"] == 0
    assert r["served"] + r["overloads"] == r["n_requests"]
    # shedding is deterministic too: same replay, same sheds
    r2 = R.replay(wl, executor=executor, seed=3, burst=64, max_queue=16)
    assert r2["overloads"] == r["overloads"]
    assert r2["output_digest"] == r["output_digest"]


def test_deadline_sheds_deterministically(executor):
    """ISSUE 14: the deadline drill — every request carries a tight
    in-queue deadline driven off the VIRTUAL clock, so which requests
    expire while coalescing is a pure function of (workload,
    deadline): same sheds, same survivors, same output bytes, run
    after run."""
    dense = workload.synthetic_workload(
        "poisson", rate_rps=500, duration_s=0.4, seed=6, width=8,
        bucket_bounds=(8, 32),
    )
    r1 = R.replay(dense, executor=executor, seed=3, deadline_ms=0.6)
    r2 = R.replay(dense, executor=executor, seed=3, deadline_ms=0.6)
    assert r1["deadline_sheds"] > 0
    assert r1["deadline_sheds"] == r2["deadline_sheds"]
    assert r1["served"] == r2["served"]
    assert r1["served"] + r1["deadline_sheds"] == r1["n_requests"]
    # shed futures surface as DeadlineExceeded, counted as errors
    assert r1["errors"] == r1["deadline_sheds"]
    assert r1["output_digest"] == r2["output_digest"]
    # replay_median's determinism assertion covers the shed count
    m = R.replay_median(dense, repeats=2, executor=executor, seed=3,
                        deadline_ms=0.6)
    assert m["deadline_sheds"] == r1["deadline_sheds"]
    # a generous deadline sheds nothing and changes no bytes
    loose = R.replay(dense, executor=executor, seed=3,
                     deadline_ms=5000.0)
    assert loose["deadline_sheds"] == 0 and loose["errors"] == 0
    with pytest.raises(ValueError, match="deadline_ms"):
        R.replay(dense, executor=executor, seed=3, deadline_ms=-1.0)


def test_swap_under_fire_keeps_outputs_bitwise(clf, wl):
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=True)
    base = R.replay(wl, registry=reg, model_name="m", seed=3)
    v0 = reg.version("m")
    # drop the unified program cache so the swap's warm pre-compile
    # pass does REAL compiles — the subject here is that those are
    # measured and excluded from post_warmup_compiles (with the cache
    # warm, a same-model swap is legitimately compile-free and there
    # would be nothing to exclude)
    program_cache.clear()
    swapped = R.replay(wl, registry=reg, model_name="m", seed=3,
                       swaps=2)
    assert swapped["swaps"] == 2
    assert reg.version("m") == v0 + 2
    # same fitted params through fresh executors: bitwise equality is
    # the whole point of the swap drill
    assert swapped["output_digest"] == base["output_digest"]
    assert swapped["composition_digest"] == base["composition_digest"]
    # swap warm pre-compiles are deliberate swap cost, not steady-state
    # recompiles: the zero-recompile gate must still pass a swap drill
    assert swapped["swap_compiles"] > 0
    assert swapped["post_warmup_compiles"] == 0
    assert R.check_report(swapped).ok


def test_timed_mode_replays_open_loop(executor):
    tiny = workload.synthetic_workload(
        "poisson", rate_rps=300, duration_s=0.2, seed=1, width=8,
    )
    r = R.replay(tiny, executor=executor, mode="timed", speed=2.0,
                 seed=0)
    assert r["served"] == tiny.n_requests
    assert r["errors"] == 0
    # 0.2 virtual seconds at 2x compression ≈ 0.1 s of wall, plus
    # scheduling slack — the point is speed actually compresses time
    assert r["wall_seconds"] < 2.0


def test_replay_argument_validation(executor, wl):
    reg_err = pytest.raises(ValueError, match="exactly one")
    with reg_err:
        R.replay(wl)
    with pytest.raises(ValueError, match="swaps"):
        R.replay(wl, executor=executor, swaps=1)
    with pytest.raises(ValueError, match="unknown mode"):
        R.replay(wl, executor=executor, mode="warp")


# -- the regression gate -----------------------------------------------

def test_gate_passes_clean_and_trips_on_2x_slowdown(executor, wl):
    """THE acceptance check: a clean re-replay passes the baseline
    gate; a throttled executor (every forward pays a fixed extra
    delay, >= 2x the clean forward path) must exit nonzero."""
    baseline = R.replay_median(wl, repeats=3, executor=executor, seed=3)
    clean = R.replay_median(wl, repeats=3, executor=executor, seed=3)
    res = R.check_report(clean, baseline=baseline,
                         rps_tolerance=0.5, latency_tolerance=1.0)
    assert res.ok, res.render()

    throttled = R.ThrottledExecutor(executor, delay_s=0.003)
    slow = R.replay_median(wl, repeats=3, executor=throttled, seed=3)
    res = R.check_report(slow, baseline=baseline)
    assert not res.ok
    failed = {c["name"] for c in res.failures}
    assert "latency_p50_vs_baseline" in failed
    assert "rps_vs_baseline" in failed
    # the throttle changes timing, NEVER results: determinism survives
    assert slow["output_digest"] == baseline["output_digest"]


def test_absolute_spec_gate(executor, wl):
    from spark_bagging_tpu.telemetry import slo

    r = R.replay(wl, executor=executor, seed=3)
    ok = R.check_report(
        r, spec=slo.SLOSpec(p50_ms=1000.0, min_rps=1.0,
                            max_padding_waste=0.999, max_overloads=0),
    )
    assert ok.ok, ok.render()
    bad = R.check_report(r, spec=slo.SLOSpec(min_rps=1e12))
    assert not bad.ok


def test_exit_code_contract_classification():
    """The shared 0/2/3 contract (benchmarks/BUDGETS.md): band-named
    failures exit 3, anything hard exits 2 — and a band-named check
    that MEASURED NOTHING (actual None, a broken report) is a hard
    breach, never host noise."""
    from spark_bagging_tpu.telemetry import slo

    def res(*checks):
        return slo.SLOResult(list(checks))

    ok = {"name": "rps", "actual": 5.0, "limit": 1.0, "op": ">=",
          "ok": True}
    band = {"name": "latency_p50_vs_baseline", "actual": 9.0,
            "limit": 1.0, "op": "<=", "ok": False}
    hard = {"name": "output_digest_vs_baseline", "actual": "a",
            "limit": "b", "op": "==", "ok": False}
    missing = {"name": "stage_share_queue", "actual": None,
               "limit": 0.5, "op": "<=", "ok": False}
    assert slo.exit_code(res(ok)) == slo.EXIT_OK == 0
    assert slo.exit_code(res(ok, band)) == slo.EXIT_HOST_BAND == 3
    assert slo.exit_code(res(band, hard)) == slo.EXIT_BREACH == 2
    assert slo.exit_code(res(missing)) == slo.EXIT_BREACH
    assert slo.is_host_band_check("rps_vs_baseline")
    assert not slo.is_host_band_check("post_warmup_compiles")


# -- the drift scenario (the model-quality plane's scripted incident) --

def test_drift_scenario_fires_exactly_one_alert(clf, wl):
    """The ISSUE 9 acceptance core, in-process: a covariate-shifted
    payload segment spliced at --drift-at yields byte-identical drift
    scores across repeats (replay_median raises otherwise), exactly
    one alert_fired with the alert left active (no flapping, re-fires
    absorbed), and exactly one flight dump for the incident."""
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=True)
    r = R.replay_median(
        wl, repeats=2, registry=reg, model_name="m",
        drift=True, drift_shift=4.0, seed=3,
    )
    d = r["drift"]
    assert d["alerts_fired"] == 1
    assert d["alerts_resolved"] == 0
    assert d["alert_active"] is True
    assert d["flight_dumps"] == 1
    assert d["scores"]["psi_max"] > 0.5
    assert d["scores"]["warmed"] is True
    # disagreement sampled through the per-replica tap, and the
    # serving compile gate is untouched by its compiles
    assert d["scores"].get("disagreement_samples", 0) > 0
    assert r["post_warmup_compiles"] == 0
    result = R.check_report(r)
    assert result.ok, result.render()


def test_drift_digest_changes_with_seed(clf, wl):
    """Different payload seed ⇒ different sketched bytes ⇒ different
    drift digest — the digest really covers the scores."""
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=True)
    a = R.replay(wl, registry=reg, model_name="m", drift=True, seed=3)
    b = R.replay(wl, registry=reg, model_name="m", drift=True, seed=4)
    assert a["drift"]["digest"] != b["drift"]["digest"]


def test_drift_rejects_swaps_and_requires_profile(clf, wl):
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=True)
    with pytest.raises(ValueError, match="swaps"):
        R.replay(wl, registry=reg, model_name="m", drift=True, swaps=1)
    bare = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32)
    saved = clf.quality_profile_
    clf.quality_profile_ = None
    try:
        with pytest.raises(ValueError, match="quality_profile_"):
            R.replay(wl, executor=bare, drift=True)
    finally:
        clf.quality_profile_ = saved  # restore the shared fixture


def test_plain_replay_carries_no_drift_section(executor, wl):
    r = R.replay(wl, executor=executor, seed=1)
    assert r["drift"] is None
    # and the gate adds no drift checks for it
    names = {c["name"] for c in R.check_report(r).checks}
    assert not any(n.startswith("drift_") for n in names)


# -- the attribution section (ISSUE 13) --------------------------------

def test_attribution_section_deterministic(executor, wl):
    """The report gains an `attribution` section whose digest — the
    deterministic projection: per-path counts, per-bucket forward
    counts + compile-time costs, virtual-clock tail verdicts — is
    byte-identical across replay_median repeats (replay_median raises
    otherwise), while the wall-clock surfaces (stage seconds/shares,
    measured seconds-per-row) ride alongside undigested."""
    m = R.replay_median(wl, repeats=3, executor=executor, seed=3)
    a = m["attribution"]
    assert a is not None and a["clock"] == "virtual"
    single = R.replay(wl, executor=executor, seed=3)
    assert a["digest"] == single["attribution"]["digest"]
    # the wall-clock decomposition partitions the request life
    shares = [v["share"] for v in a["stages"].values()]
    assert all(s is not None for s in shares)
    assert sum(shares) == pytest.approx(1.0)
    # the measured cost model joined compile-time FLOPs (CPU XLA
    # reports cost analysis) with real seconds
    assert a["cost_model"]
    for c in a["cost_model"].values():
        assert c["forwards"] > 0 and c["seconds_per_row"] > 0
        assert c["flops_per_forward"] is not None
        assert c["achieved_flops"] is not None
    assert a["mfu"] is None  # no published peak for CPU — honest None
    # every request got a verdict; a clean drill fails nothing
    assert sum(a["verdicts"].values()) == wl.n_requests
    assert "failed" not in a["verdicts"]
    assert len(a["tail"]) > 0
    # a different seed is a different workload payload but the SAME
    # schedule: verdicts (a pure function of the schedule) hold
    r2 = R.replay(wl, executor=executor, seed=4)
    assert r2["attribution"]["verdicts"] == a["verdicts"]


def test_attribution_stage_share_gate(executor, wl):
    from spark_bagging_tpu.telemetry import slo

    r = R.replay(wl, executor=executor, seed=3)
    ok = R.check_report(
        r, spec=slo.SLOSpec(max_stage_share={"queue": 1.0,
                                             "forward": 1.0})
    )
    assert ok.ok, ok.render()
    bad = R.check_report(
        r, spec=slo.SLOSpec(max_stage_share={"forward": 0.0})
    )
    assert not bad.ok
    assert {c["name"] for c in bad.failures} == {"stage_share_forward"}


def test_attribution_chaos_verdicts_deterministic(executor, wl):
    """Under a chaos plan the tail explainer must attribute the
    injected incidents: transient blips absorbed by retries show up
    as retry-inflated verdicts in exactly the windows the plan fired
    in — and the whole thing stays byte-identical across repeats
    (replay_median asserts the attribution digest)."""
    from spark_bagging_tpu import faults

    spec = faults.builtin_plan_spec("blips", seed=3)
    m = R.replay_median(wl, repeats=2, executor=executor, seed=3,
                        chaos=spec, retries=2)
    a = m["attribution"]
    assert m["chaos"]["retries"] > 0
    assert a["verdicts"].get("retry-inflated", 0) > 0
    assert m["errors"] == 0  # the retries absorbed every blip


def test_attribution_swap_windows_absorb_compiles(clf, wl):
    """A swap drill's scripted model_swapped events are the
    deterministic carrier of compile absorption: requests riding the
    swap windows verdict compile-absorbed (cache-dependent compile
    counters deliberately do NOT feed the digest)."""
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=True)
    r = R.replay(wl, registry=reg, model_name="m", seed=3, swaps=2)
    a = r["attribution"]
    assert a["verdicts"].get("compile-absorbed", 0) > 0
    assert R.check_report(r).ok


def test_fleet_report_carries_no_attribution(clf, wl):
    r = R.replay_fleet(wl, model=clf, fleet=2, seed=3,
                       min_bucket_rows=8, bucket_max_rows=32)
    assert r["attribution"] is None


# -- tier-1 CLI smoke (budgeted like the lint gate) --------------------

def test_fleet_drill_deterministic_and_bitwise(clf, wl):
    """The ISSUE 12 drill, in-process: N virtual peers (own registries
    + stepped batchers) under one aggregator — the skew transcript
    rises during the rolling swap and returns to 0, a convergence
    duration is observed, every digest (merged metrics, skew,
    incidents) is reproducible, and distributing the SAME workload
    over 3 peers serves byte-identical outputs to the single-executor
    replay of the same (workload, seed)."""
    r1 = R.replay_fleet(wl, model=clf, fleet=3, seed=3,
                        min_bucket_rows=8, bucket_max_rows=32)
    r2 = R.replay_fleet(wl, model=clf, fleet=3, seed=3,
                        min_bucket_rows=8, bucket_max_rows=32)
    f1, f2 = r1["fleet"], r2["fleet"]
    for key in ("merged_digest", "skew_digest", "incident_digest",
                "convergence_seconds", "scrapes", "scrape_failures"):
        assert f1[key] == f2[key], key
    assert r1["output_digest"] == r2["output_digest"]
    assert r1["served"] == wl.n_requests and r1["errors"] == 0
    # the version plane moved and converged, and the excursion's
    # duration was measured
    assert f1["skew_max"] >= 1 and f1["skew_final"] == 0
    assert f1["converged"] is True
    assert len(f1["convergence_seconds"]["replay"]) == 1
    # a healthy drill pages nothing
    assert all(a["fired"] == 0 for a in f1["alerts"].values())
    assert f1["incidents"] == [] and f1["flight_dumps"] == 0
    assert f1["health"]["min_fresh"] == 3
    # fleet distribution changes WHERE rows run, never their bytes:
    # the per-request output stream matches the single-executor replay
    single = R.replay(wl, executor=EnsembleExecutor(
        clf, min_bucket_rows=8, max_batch_rows=32
    ), seed=3)
    assert r1["output_digest"] == single["output_digest"]
    # a different payload seed is a different fleet experiment
    r3 = R.replay_fleet(wl, model=clf, fleet=3, seed=4,
                        min_bucket_rows=8, bucket_max_rows=32)
    assert r3["output_digest"] != r1["output_digest"]


def test_fleet_drill_validation(clf, wl):
    with pytest.raises(ValueError, match=">= 2 peers"):
        R.replay_fleet(wl, model=clf, fleet=1)
    # CLI combination guards
    with pytest.raises(SystemExit):
        R.main(["--fleet", "3", "--drift"])
    with pytest.raises(SystemExit):
        R.main(["--fleet", "3", "--swaps", "2"])
    with pytest.raises(SystemExit):
        R.main(["--fleet", "3", "--mode", "timed"])
    with pytest.raises(SystemExit):
        # fleet.scrape can only fire under an aggregator
        R.main(["--chaos", "peer-loss"])


def test_fleet_cli_gate_under_budget(tmp_path):
    """`python -m benchmarks.replay --fleet 3 --check` (in-process,
    scaled down): exit 0 with the fleet checks green — skew rose,
    converged, convergence observed, quorum held — inside a 20 s
    tier-1 allowance."""
    import json

    t0 = time.monotonic()
    out = str(tmp_path / "fleet_report.json")
    rc = R.main([
        "--fleet", "3", "--synthetic", "poisson", "--rate", "300",
        "--duration", "0.4", "--width", "6", "--n-estimators", "4",
        "--bucket-max-rows", "32", "--repeats", "2",
        "--check", "--out", out,
    ])
    elapsed = time.monotonic() - t0
    assert rc == 0
    assert elapsed < 20.0, f"fleet gate took {elapsed:.1f}s"
    report = json.loads(open(out).read())
    assert report["slo"]["ok"] is True
    checks = {c["name"]: c for c in report["slo"]["checks"]}
    assert checks["fleet_skew_rose"]["ok"]
    assert checks["fleet_skew_converged"]["ok"]
    assert checks["fleet_convergence_observed"]["ok"]
    assert checks["fleet_quorum_held"]["ok"]
    assert report["post_warmup_compiles"] == 0


def test_fleet_chaos_peer_loss_cli(tmp_path):
    """`--chaos peer-loss --fleet 3`: scrapes of one peer fail for a
    scripted stretch — fleet health degrades (excluded from quorum,
    never merged as zeros) and recovers, the peer-lost alert fires
    exactly once (with its flight dump), and the whole fault/health/
    incident transcript is byte-identical across repeats (asserted by
    replay_median, or this exits nonzero)."""
    import json

    t0 = time.monotonic()
    out = str(tmp_path / "fleet_chaos_report.json")
    rc = R.main([
        "--fleet", "3", "--chaos", "peer-loss",
        "--synthetic", "poisson", "--rate", "300",
        "--duration", "0.4", "--width", "6", "--n-estimators", "4",
        "--bucket-max-rows", "32", "--repeats", "2",
        "--check", "--out", out,
    ])
    elapsed = time.monotonic() - t0
    assert rc == 0
    assert elapsed < 25.0, f"peer-loss gate took {elapsed:.1f}s"
    report = json.loads(open(out).read())
    checks = {c["name"]: c for c in report["slo"]["checks"]}
    assert checks["fleet_health_degraded"]["ok"]
    assert checks["fleet_health_recovered"]["ok"]
    f = report["fleet"]
    assert f["scrape_failures"]["p2"] == 20
    assert f["health"]["min_fresh"] == 2
    assert f["alerts"]["fleet-peer-lost"]["fired"] == 1
    assert f["alerts"]["fleet-peer-lost"]["resolved"] == 1
    assert f["flight_dumps"] == 1
    # the fired alert is on the incident timeline with its virtual
    # timestamp, attributed to the fleet engine
    kinds = {(i["kind"], i["key"]) for i in f["incidents"]}
    assert ("alert_fired", "fleet-peer-lost") in kinds
    assert report["chaos"]["sites"]["fires"]["fleet.scrape"] == 20


def test_cli_smoke_replay_check_under_budget(tmp_path):
    """`python -m benchmarks.replay --check` end to end (in-process:
    the subprocess would re-pay the JAX import): tiny synthetic
    workload, report written, gate exit 0, all under the same 10 s
    ceiling the lint gate promises."""
    t0 = time.monotonic()
    out = str(tmp_path / "replay_report.json")
    wl_path = str(tmp_path / "tiny.workload.jsonl")
    rc = R.main([
        "--synthetic", "poisson", "--rate", "200",
        "--duration", "0.25", "--width", "6",
        "--n-estimators", "4", "--bucket-max-rows", "32",
        "--repeats", "2", "--check",
        "--out", out, "--save-workload", wl_path,
    ])
    elapsed = time.monotonic() - t0
    assert rc == 0
    assert elapsed < 10.0, f"replay smoke took {elapsed:.1f}s"
    import json

    report = json.loads(open(out).read())
    assert report["slo"]["ok"] is True
    assert report["post_warmup_compiles"] == 0
    # the attribution section rides the gate run (its digest was
    # asserted byte-identical across the repeats by replay_median)
    attr = report["attribution"]
    assert attr["clock"] == "virtual" and attr["digest"]
    assert sum(attr["verdicts"].values()) == report["n_requests"]
    assert attr["cost_model"]
    # the shared exit-code contract end to end (benchmarks/BUDGETS.md),
    # driven through the --workload file path: an injected forward-path
    # slowdown fails ONLY the host-conditional performance bands, so
    # the gate exits 3 (band), not 2 — and the throttle only bends
    # timing, so the report still reproduces the baseline's output
    # bytes from the saved schedule
    rc2 = R.main([
        "--workload", wl_path, "--n-estimators", "4",
        "--bucket-max-rows", "32", "--width", "6",
        "--repeats", "1", "--throttle-ms", "3",
        "--check", "--baseline", out,
        "--out", str(tmp_path / "throttled.json"),
    ])
    assert rc2 == 3
    throttled = json.loads(open(str(tmp_path / "throttled.json")).read())
    assert throttled["output_digest"] == report["output_digest"]
    failed = {c["name"] for c in throttled["slo"]["checks"]
              if not c["ok"]}
    assert "latency_p50_vs_baseline" in failed
    from spark_bagging_tpu.telemetry import slo as slo_mod

    assert all(slo_mod.is_host_band_check(n) for n in failed)
    # a HARD breach — the baseline's output digest corrupted — must
    # still exit 2: digest identity is never a band
    baseline = json.loads(open(out).read())
    baseline["output_digest"] = "0" * 64
    corrupt = str(tmp_path / "corrupt_baseline.json")
    with open(corrupt, "w") as f:
        json.dump(baseline, f)
    rc3 = R.main([
        "--workload", wl_path, "--n-estimators", "4",
        "--bucket-max-rows", "32", "--width", "6",
        "--repeats", "1", "--check", "--baseline", corrupt,
        "--out", str(tmp_path / "breach.json"),
    ])
    assert rc3 == 2


def test_cli_drift_gate_under_budget(tmp_path):
    """The ISSUE 9 acceptance command, in-process and budgeted:
    `python -m benchmarks.replay --drift --check` exits 0 with the
    drift checks green — exactly one alert_fired, one flight dump,
    byte-identical scores across repeats (replay_median asserts) —
    inside the satellite's 15 s tier-1 allowance."""
    import json

    t0 = time.monotonic()
    out = str(tmp_path / "drift_report.json")
    rc = R.main([
        "--synthetic", "poisson", "--rate", "150",
        "--duration", "0.6", "--width", "8",
        "--n-estimators", "4", "--bucket-max-rows", "32",
        "--repeats", "2", "--drift", "--check", "--out", out,
    ])
    elapsed = time.monotonic() - t0
    assert rc == 0
    assert elapsed < 15.0, f"drift gate took {elapsed:.1f}s"
    report = json.loads(open(out).read())
    assert report["slo"]["ok"] is True
    checks = {c["name"]: c for c in report["slo"]["checks"]}
    assert checks["drift_alerts_fired"]["actual"] == 1
    assert checks["drift_flight_dumps"]["actual"] == 1
    assert report["drift"]["scores"]["psi_max"] > 0.5


@pytest.mark.slow
def test_drift_soak_timed_mode(clf):
    """Open-loop drift soak: the scripted incident replayed on the
    REAL threaded batcher with wall-clock pacing — the monitor's
    locks under genuine concurrency, alert evaluation on the arrival
    schedule. Timed mode is documented non-deterministic, so only the
    incident shape is asserted, not byte identity."""
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=True)
    soak = workload.synthetic_workload(
        "poisson", rate_rps=300, duration_s=2.0, seed=13, width=8,
    )
    r = R.replay(soak, registry=reg, model_name="m", mode="timed",
                 drift=True, drift_shift=4.0, seed=5)
    assert r["errors"] == 0
    d = r["drift"]
    assert d["scores"]["psi_max"] > 0.5
    assert d["alerts_fired"] >= 1
    assert d["flight_dumps"] == d["alerts_fired"]


@pytest.mark.slow
def test_burst_soak_timed_mode(executor):
    """Open-loop soak: a bursty schedule replayed on the threaded
    batcher with real pacing — overload and recovery under actual
    concurrency. Heavier than the tier-1 budget allows, hence slow."""
    bursty = workload.synthetic_workload(
        "bursty", rate_rps=300, duration_s=2.0, seed=11, width=8,
        burst_every_s=0.5, burst_size=256,
    )
    r = R.replay(bursty, executor=executor, mode="timed", speed=1.0,
                 seed=0, max_queue=64)
    assert r["served"] > 0
    assert r["errors"] == 0
    assert r["served"] + r["overloads"] == r["n_requests"]
