"""Persistent compilation cache: cross-process reuse [VERDICT r4 ask#2].

The capture machinery's children are freshly spawned interpreters
(benchmarks/isolation.py), so executable reuse across a tunnel window
boundary is exactly "a second process hits entries a first process
wrote". That is what these tests prove on the CPU backend; the TPU-side
evidence rides the ``compile_cache`` counters every benchmark row now
records.
"""
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

import compile_cache  # noqa: E402


def test_cross_process_cache_hits(tmp_path):
    result = compile_cache.probe(str(tmp_path))
    cold, warm = result["cold"], result["warm"]
    # first interpreter: real XLA compiles, entries written to disk
    assert cold["cache"]["misses"] > 0
    assert cold["cache"]["hits"] == 0
    assert cold["cache"]["entries"] > 0
    # second interpreter: the jitted step comes back from disk
    assert warm["cache"]["hits"] > 0
    # and no new compile was paid for the step itself (misses can be
    # nonzero only for trivial sub-0.1s ops excluded by the min-compile
    # knob; the big entry must hit)
    assert warm["cache"]["entries"] == cold["cache"]["entries"]


def test_sweep_purges_only_small_entries(tmp_path):
    """The one-time stale-entry sweep [ADVICE r5 medium]: entries below
    the size floor (written before MIN_COMPILE_SECS rose to 6.0) go,
    along with their -atime LRU-bookkeeping siblings; big entries and
    non-cache files stay."""
    small = tmp_path / "aaa-cache"
    small.write_bytes(b"x" * 1024)
    small_atime = tmp_path / "aaa-atime"
    small_atime.write_bytes(b"t")
    big = tmp_path / "bbb-cache"
    big.write_bytes(b"x" * (compile_cache.SWEEP_MIN_ENTRY_BYTES + 1))
    other = tmp_path / "notes.txt"
    other.write_text("keep me")
    removed = compile_cache.sweep_stale_entries(str(tmp_path))
    assert removed == 1
    assert not small.exists() and not small_atime.exists()
    assert big.exists() and other.exists()
    assert compile_cache.stats().get("swept", 0) >= 1


def test_sweep_once_is_per_cache_dir(tmp_path):
    """enable()'s sweep is marker-gated per DIR, not per process: a
    >=6s-compile entry that happens to serialize under the size floor
    must not be re-deleted by every later child's enable()."""
    first = tmp_path / "aaa-cache"
    first.write_bytes(b"x" * 64)
    assert compile_cache.sweep_stale_entries(str(tmp_path), once=True) == 1
    # a small entry written AFTER the sweep (it passed the compile-time
    # write gate, so it is legitimate) survives subsequent once-sweeps
    legit = tmp_path / "bbb-cache"
    legit.write_bytes(b"x" * 64)
    assert compile_cache.sweep_stale_entries(str(tmp_path), once=True) == 0
    assert legit.exists()


def test_enable_idempotent(tmp_path):
    # enable() in THIS process: the conftest already initialized the
    # CPU backend, so this exercises the real config path
    first = compile_cache.enable(str(tmp_path / "a"))
    again = compile_cache.enable(str(tmp_path / "b"))
    assert first == again, "second enable() must not re-point the cache"
    snap = compile_cache.stats()
    assert set(snap) >= {"hits", "misses", "saved_sec"}


def test_env_var_routes_cache_dir(tmp_path):
    # JAX_COMPILATION_CACHE_DIR is how isolation.py/tpu_watch.sh land
    # children in the shared cache; a fresh interpreter must pick it up
    # when enable() gets no explicit dir. (Fresh subprocess because
    # _enabled_dir is already pinned in this one.)
    import json as _json
    import subprocess
    import sys as _sys

    env_dir = str(tmp_path / "from_env")
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        f"import sys; sys.path.insert(0, {os.path.dirname(os.path.abspath(compile_cache.__file__))!r});"
        "import json, compile_cache;"
        "print('DIR ' + json.dumps(compile_cache.enable()))"
    )
    proc = subprocess.run(
        [_sys.executable, "-c", code],
        env=dict(os.environ, JAX_COMPILATION_CACHE_DIR=env_dir),
        capture_output=True, text=True, timeout=120,
    )
    line = next(ln for ln in proc.stdout.splitlines()
                if ln.startswith("DIR "))
    assert _json.loads(line[len("DIR "):]) == env_dir


def test_enable_degrades_without_aborting(tmp_path, monkeypatch):
    # A cache-infrastructure failure must not kill the measurement it
    # was meant to speed up: point the dir at an uncreatable path in a
    # fresh subprocess and require rc=0 with the warning on stderr.
    import subprocess
    import sys as _sys

    blocker = tmp_path / "not_a_dir"
    blocker.write_text("")
    bad_dir = str(blocker / "child")  # makedirs under a FILE → raises
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        f"import sys; sys.path.insert(0, {os.path.dirname(os.path.abspath(compile_cache.__file__))!r});"
        "import compile_cache;"
        f"assert compile_cache.enable({bad_dir!r}) is None;"
        "print('DEGRADED OK')"
    )
    proc = subprocess.run([_sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "DEGRADED OK" in proc.stdout
    assert "persistent compile cache disabled" in proc.stderr
