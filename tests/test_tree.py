"""Decision-tree learner tests: split correctness, weighted-fit exactness,
sklearn parity, vmap-ability, ensemble integration [SURVEY §4, §7 hard-parts
1-2]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_diabetes, load_iris
from sklearn.preprocessing import StandardScaler
from sklearn.tree import DecisionTreeClassifier as SkTreeClf
from sklearn.tree import DecisionTreeRegressor as SkTreeReg

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)

KEY = jax.random.key(0)


def _iris():
    X, y = load_iris(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y, jnp.int32), X, y


def _breast_cancer():
    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y, jnp.int32), X, y


def _diabetes():
    X, y = load_diabetes(return_X_y=True)
    X = X.astype(np.float32)
    y = ((y - y.mean()) / y.std()).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y), X, y


class TestClassifierTree:
    @pytest.mark.slow  # [PR 16 pyramid] ~2.7s planted-split recovery soak; split recovery stays tier-1 via TestRegressorTree::test_step_function_recovered
    def test_axis_aligned_split_recovered(self):
        """A single perfectly-separating feature must be found at the root."""
        rng = np.random.default_rng(0)
        X = rng.normal(size=(400, 6)).astype(np.float32)
        y = (X[:, 3] > 0.0).astype(np.int32)
        tree = DecisionTreeClassifier(max_depth=1, n_bins=64)
        params, aux = tree.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(400), 2
        )
        assert int(params["feature"][0]) == 3
        acc = (
            np.asarray(tree.predict_scores(params, jnp.asarray(X)).argmax(1))
            == y
        ).mean()
        assert acc > 0.97  # binned threshold ⇒ not always exactly 0.0

    @pytest.mark.slow  # [PR 14 pyramid] ~3.5s sklearn-quality soak; split/vmap parity contracts stay tier-1
    def test_iris_accuracy_matches_sklearn_depth3(self):
        Xj, yj, X, y = _iris()
        tree = DecisionTreeClassifier(max_depth=3, n_bins=32,
                                      hist_dtype="float32")
        params, _ = tree.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)
        acc = (np.asarray(tree.predict_scores(params, Xj).argmax(1)) == y).mean()
        sk = SkTreeClf(max_depth=3).fit(X, y).score(X, y)
        assert acc > 0.93
        assert acc >= sk - 0.05

    @pytest.mark.slow  # ~5.3s: accuracy soak on the full
    # breast-cancer set at depth 5; the depth-3 sklearn comparison
    # above keeps the correctness signal tier-1 [ISSUE 13 budget
    # offset]
    def test_breast_cancer_depth5(self):
        Xj, yj, X, y = _breast_cancer()
        tree = DecisionTreeClassifier(max_depth=5)
        params, aux = tree.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 2)
        acc = (np.asarray(tree.predict_scores(params, Xj).argmax(1)) == y).mean()
        assert acc > 0.95
        assert np.isfinite(float(aux["loss"]))

    @pytest.mark.slow  # [PR 16 pyramid] ~4.2s dual-fit equivalence soak; weight semantics stay tier-1 via test_zero_weight_rows_ignored
    def test_poisson_weights_equal_duplicated_rows(self):
        """Weighted Gini over Poisson counts must equal physically
        duplicating rows [SURVEY §7 hard-part 2]."""
        Xj, yj, X, y = _iris()
        rng = np.random.default_rng(3)
        w = rng.poisson(1.0, len(y)).astype(np.float32)
        tree = DecisionTreeClassifier(max_depth=3, hist_dtype="float32")
        pw, _ = tree.fit(
            tree.init_params(KEY, 4, 3), Xj, yj, jnp.asarray(w), KEY
        )
        Xd = np.repeat(X, w.astype(int), axis=0)
        yd = np.repeat(y, w.astype(int))
        # same binning for both fits: prepare on the original X
        prepared = tree.prepare(Xj)
        pd, _ = tree.fit(
            tree.init_params(KEY, 4, 3),
            jnp.asarray(Xd), jnp.asarray(yd, jnp.int32),
            jnp.ones(len(yd)), KEY,
            prepared={
                "edges": prepared["edges"],
                "T": (jnp.asarray(Xd)[:, :, None]
                      <= prepared["edges"][None]).astype(jnp.int8),
            },
        )
        np.testing.assert_array_equal(
            np.asarray(pw["feature"]), np.asarray(pd["feature"])
        )
        np.testing.assert_allclose(
            np.asarray(pw["threshold"]), np.asarray(pd["threshold"])
        )

    @pytest.mark.slow  # [PR 19 budget offset] ~6.4s zero-weight classifier soak; zero-weight neutrality stays tier-1 via the fuzz representative (same rep the PR 14 moves name)
    def test_zero_weight_rows_ignored(self):
        Xj, yj, _, y = _iris()
        w = np.ones(len(y), np.float32)
        w[y == 2] = 0.0
        tree = DecisionTreeClassifier(max_depth=3)
        params, _ = tree.fit_from_init(KEY, Xj, yj, jnp.asarray(w), 3)
        pred = np.asarray(tree.predict_scores(params, Xj).argmax(1))
        assert not np.any(pred == 2)

    @pytest.mark.slow  # [PR 20 budget offset] ~6.1s iris fit soak; per-row probability normalization stays tier-1 via the predict_proba row-sum asserts in test_bagging.py and test_pipeline.py
    def test_scores_are_log_probabilities(self):
        Xj, yj, _, y = _iris()
        tree = DecisionTreeClassifier(max_depth=2)
        params, _ = tree.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)
        p = np.exp(np.asarray(tree.predict_scores(params, Xj)))
        np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)

    @pytest.mark.slow  # [PR 16 pyramid] ~4.8s 4-replica batched-fit soak; vmapped tree fits stay tier-1 via TestTreeBagging::test_chunked_fit_matches_vmap
    def test_vmap_over_replicas(self):
        Xj, yj, _, y = _iris()
        tree = DecisionTreeClassifier(max_depth=3)
        rng = np.random.default_rng(0)
        ws = jnp.asarray(rng.poisson(1.0, (4, len(y))).astype(np.float32))
        keys = jax.vmap(lambda i: jax.random.fold_in(KEY, i))(jnp.arange(4))
        prepared = tree.prepare(Xj)
        params, aux = jax.vmap(
            lambda k, w: tree.fit_from_init(
                k, Xj, yj, w, 3, prepared=prepared
            )
        )(keys, ws)
        assert params["feature"].shape == (4, 7)
        assert params["leaf_logp"].shape == (4, 8, 3)
        assert not np.array_equal(
            np.asarray(params["feature"][0]), np.asarray(params["feature"][1])
        ) or not np.allclose(
            np.asarray(params["threshold"][0]),
            np.asarray(params["threshold"][1]),
        )


class TestRegressorTree:
    def test_step_function_recovered(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(500, 4)).astype(np.float32)
        y = np.where(X[:, 2] > 0, 2.0, -1.0).astype(np.float32)
        tree = DecisionTreeRegressor(max_depth=1, n_bins=64)
        params, _ = tree.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(500), 1
        )
        assert int(params["feature"][0]) == 2
        pred = np.asarray(tree.predict_scores(params, jnp.asarray(X)))
        assert np.corrcoef(pred, y)[0, 1] > 0.95

    @pytest.mark.slow  # [PR 14 pyramid] ~3.6s sklearn-quality soak; regressor split correctness stays tier-1
    def test_diabetes_r2_near_sklearn(self):
        Xj, yj, X, y = _diabetes()
        tree = DecisionTreeRegressor(max_depth=4, hist_dtype="float32")
        params, _ = tree.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 1)
        pred = np.asarray(tree.predict_scores(params, Xj))
        r2 = 1 - ((pred - y) ** 2).sum() / ((y - y.mean()) ** 2).sum()
        sk_r2 = SkTreeReg(max_depth=4).fit(X, y).score(X, y)
        assert r2 > 0.4
        assert r2 >= sk_r2 - 0.1

    @pytest.mark.slow  # [PR 14 pyramid] ~4s deep-fit numeric-edge soak; cheap empty-leaf NaN guard stays tier-1
    def test_empty_leaf_fallback_is_finite(self):
        # depth 6 on 50 rows guarantees empty leaves
        rng = np.random.default_rng(0)
        X = rng.normal(size=(50, 3)).astype(np.float32)
        y = X[:, 0].astype(np.float32)
        tree = DecisionTreeRegressor(max_depth=6)
        params, _ = tree.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(50), 1
        )
        assert np.isfinite(np.asarray(params["leaf_value"])).all()


class TestTreeBagging:
    @pytest.mark.slow  # [PR 14 pyramid] ~3.3s held-out accuracy soak; bagged-vs-vmap parity stays tier-1
    def test_bagged_trees_match_single_tree_heldout_iris(self):
        Xj, yj, X, y = _iris()
        rng = np.random.default_rng(0)
        idx = rng.permutation(len(y))
        tr, te = idx[:100], idx[100:]
        tree = DecisionTreeClassifier(max_depth=3)
        params, _ = tree.fit_from_init(
            KEY, Xj[tr], yj[tr], jnp.ones(len(tr)), 3
        )
        single_acc = (
            np.asarray(tree.predict_scores(params, Xj[te]).argmax(1)) == y[te]
        ).mean()
        clf = BaggingClassifier(
            base_learner=tree,
            n_estimators=25,
            max_features=0.75,
            seed=0,
        )
        clf.fit(X[tr], y[tr])
        bag_acc = clf.score(X[te], y[te])
        assert bag_acc > 0.9
        assert bag_acc >= single_acc - 0.04  # ensemble ≈/≥ single [SURVEY §4]
        assert clf.predict_proba(X[te]).shape == (len(te), 3)

    @pytest.mark.slow  # [PR 14 pyramid] ~2.1s real-data subspace soak; subspace draw correctness stays tier-1 in faster tests
    def test_bagged_trees_with_subspaces_breast_cancer(self):
        Xj, yj, X, y = _breast_cancer()
        clf = BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=4),
            n_estimators=15,
            max_features=0.5,
            voting="hard",
            seed=1,
        )
        clf.fit(X, y)
        assert clf.score(X, y) > 0.94

    @pytest.mark.slow  # [PR 14 pyramid] ~2s OOB quality soak; OOB computation contracts stay tier-1 in test_bagging
    def test_bagged_regressor_oob(self):
        Xj, yj, X, y = _diabetes()
        reg = BaggingRegressor(
            base_learner=DecisionTreeRegressor(max_depth=3),
            n_estimators=30,
            oob_score=True,
            seed=0,
        )
        reg.fit(X, y)
        assert reg.score(X, y) > 0.3
        assert np.isfinite(reg.oob_score_)
        assert reg.oob_score_ > 0.0

    def test_chunked_fit_matches_vmap(self):
        Xj, yj, X, y = _iris()
        base = dict(
            base_learner=DecisionTreeClassifier(max_depth=2),
            n_estimators=8,
            seed=7,
        )
        a = BaggingClassifier(**base).fit(X, y)
        b = BaggingClassifier(**base, chunk_size=4).fit(X, y)
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), atol=1e-5
        )

    def test_all_padding_shard_keeps_edges_finite(self):
        """n smaller than the data axis ⇒ some shards are pure padding;
        their +inf quantile sentinels must not poison the shared bin
        edges (masked cross-shard average)."""
        from spark_bagging_tpu import make_mesh

        rng = np.random.default_rng(0)
        X = rng.normal(size=(5, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        y[0] = 1 - y[0] if len(set(y)) == 1 else y[0]
        mesh = make_mesh(data=8, replica=1)
        clf = BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=2, n_bins=4),
            n_estimators=2,
            seed=0,
            mesh=mesh,
        )
        clf.fit(X, y)
        thr = np.asarray(clf.ensemble_["threshold"])
        # padding-only shards must not poison the averaged edges with
        # NaN; +inf entries are legitimate leaf-ified nodes (a 5-row
        # fit cannot satisfy min_instances_per_node at every depth)
        assert not np.isnan(thr).any()
        assert np.isfinite(thr[:, 0]).all()  # the root always splits here

    @pytest.mark.slow  # [PR 17 budget offset] ~2.3s mesh twin; sharded tree fits stay tier-1 via tests/test_sharded.py + the sharded-parity scenario digest
    def test_sharded_tree_fit_on_mesh(self):
        from spark_bagging_tpu import make_mesh

        Xj, yj, X, y = _breast_cancer()
        mesh = make_mesh(data=2)
        clf = BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=3),
            n_estimators=8,
            seed=0,
            mesh=mesh,
        )
        clf.fit(X, y)
        assert clf.score(X, y) > 0.9


# ---------------------------------------------------------------------
# feature_importances_ (Spark ML featureImportances analog)
# ---------------------------------------------------------------------


@pytest.mark.slow  # [PR 14 pyramid] ~2.6s statistical-recovery soak; importances API contract stays tier-1
def test_feature_importances_find_informative_features():
    from spark_bagging_tpu import BaggingClassifier
    from spark_bagging_tpu.models import DecisionTreeClassifier

    rng = np.random.default_rng(0)
    n = 2000
    y = rng.integers(0, 2, n)
    X = rng.standard_normal((n, 10)).astype(np.float32)
    X[:, 3] += 2.5 * y  # only features 3 and 7 carry signal
    X[:, 7] -= 2.0 * y
    clf = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3, n_bins=16),
        n_estimators=16, seed=0,
    ).fit(X, y)
    imp = clf.feature_importances_
    assert imp.shape == (10,)
    assert imp.sum() == pytest.approx(1.0)
    assert (imp >= 0).all()
    assert imp[3] + imp[7] > 0.8  # informative features dominate
    # with feature subspaces: global mapping must still hold
    sub = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3, n_bins=16),
        n_estimators=32, max_features=0.5, seed=0,
    ).fit(X, y)
    imp_s = sub.feature_importances_
    assert imp_s.sum() == pytest.approx(1.0)
    assert imp_s[3] + imp_s[7] > 0.6


@pytest.mark.slow  # ~9s: stream-fit importances; the in-memory importance
# tests keep the mapping covered in tier-1
def test_feature_importances_regressor_and_stream():
    from spark_bagging_tpu import ArrayChunks, BaggingRegressor
    from spark_bagging_tpu.models import DecisionTreeRegressor

    rng = np.random.default_rng(1)
    n = 1500
    X = rng.standard_normal((n, 6)).astype(np.float32)
    y = (3.0 * X[:, 2] + rng.standard_normal(n) * 0.1).astype(np.float32)
    reg = BaggingRegressor(
        base_learner=DecisionTreeRegressor(max_depth=3, n_bins=16),
        n_estimators=8, seed=0,
    ).fit(X, y)
    imp = reg.feature_importances_
    assert imp.argmax() == 2 and imp[2] > 0.8
    # streamed tree fit carries gains identically
    sreg = BaggingRegressor(
        base_learner=DecisionTreeRegressor(max_depth=3, n_bins=16),
        n_estimators=8, seed=0,
    ).fit_stream(ArrayChunks(X, y, chunk_rows=512))
    assert sreg.feature_importances_.argmax() == 2


def test_feature_importances_requires_tree():
    from spark_bagging_tpu import BaggingClassifier

    _, _, X, y = _breast_cancer()
    clf = BaggingClassifier(n_estimators=2, seed=0).fit(X, y)
    with pytest.raises(AttributeError, match="tree base learner"):
        _ = clf.feature_importances_


class TestPrePruning:
    """Spark's minInfoGain / minInstancesPerNode / impurity params."""

    def _data(self, n=400, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        return X, y

    @pytest.mark.slow  # [PR 14 pyramid] ~2.5s alternate-criterion fit soak; gini path is the tier-1 representative
    def test_entropy_criterion_trains(self):
        X, y = self._data()
        a = BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=3,
                                                criterion="entropy"),
            n_estimators=4, seed=0,
        ).fit(X, y)
        assert a.score(X, y) > 0.9
        b = BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=3),
            n_estimators=4, seed=0,
        ).fit(X, y)
        # different impurity, (generally) different thresholds
        assert np.isfinite(np.asarray(a.ensemble_["threshold"])).any()
        assert a.score(X, y) == pytest.approx(b.score(X, y), abs=0.05)
        with pytest.raises(ValueError, match="criterion"):
            DecisionTreeClassifier(criterion="logloss")

    @pytest.mark.slow  # [PR 17 budget offset] ~3.7s pruning soak; the pre-pruning knob contract stays tier-1 via TestPrePruning::test_min_instances_blocks_tiny_splits
    def test_min_info_gain_prunes_to_stump(self):
        X, y = self._data()
        # an absurd floor: no split clears it, so the tree is a single
        # leaf (thresholds all +inf route everything left) predicting
        # the majority class
        tree = DecisionTreeClassifier(max_depth=3, min_info_gain=1e9)
        import jax
        import jax.numpy as jnp

        params, _ = tree.fit_from_init(
            jax.random.key(0), jnp.asarray(X),
            jnp.asarray(y, jnp.int32), jnp.ones(len(y)), 2,
        )
        assert np.isinf(np.asarray(params["threshold"])).all()
        assert np.asarray(params["gain"]).sum() == 0.0
        pred = np.asarray(
            tree.predict_scores(params, jnp.asarray(X)).argmax(1)
        )
        assert len(np.unique(pred)) == 1

    def test_min_instances_blocks_tiny_splits(self):
        """With a floor of 40% of rows per side, only near-median
        splits are allowed at the root; deeper nodes (each holding
        < 80% of rows... < 2x40%) become leaves."""
        X, y = self._data(n=200)
        import jax
        import jax.numpy as jnp

        tree = DecisionTreeClassifier(
            max_depth=3, min_instances_per_node=80,
        )
        params, _ = tree.fit_from_init(
            jax.random.key(0), jnp.asarray(X),
            jnp.asarray(y, jnp.int32), jnp.ones(len(y)), 2,
        )
        thr = np.asarray(params["threshold"])
        # root may split (100/100-ish sides); level-2+ nodes hold
        # ~100 rows -> an 80-per-side split is impossible -> leaves
        assert np.isfinite(thr[0])
        assert np.isinf(thr[3:]).all()
        with pytest.raises(ValueError, match="min_instances"):
            DecisionTreeClassifier(min_instances_per_node=-1)
        with pytest.raises(ValueError, match="min_info_gain"):
            DecisionTreeClassifier(min_info_gain=-0.1)

    @pytest.mark.slow  # [PR 14 pyramid] ~3.2s stream-integration soak; pruning knobs + stream parity each stay tier-1 separately
    def test_streamed_fit_inherits_pruning(self):
        from spark_bagging_tpu import ArrayChunks, BaggingClassifier

        X, y = self._data()
        clf = BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=3,
                                                min_info_gain=1e9),
            n_estimators=2, seed=0,
        ).fit_stream(ArrayChunks(X, y, chunk_rows=100), classes=[0, 1])
        assert np.isinf(np.asarray(clf.ensemble_["threshold"])).all()

    @pytest.mark.slow  # [PR 17 budget offset] ~2.9s knob-plumbing fit; knob rejection/enforcement stays tier-1 via TestPrePruning::test_min_instances_blocks_tiny_splits
    def test_forest_exposes_knobs(self):
        from spark_bagging_tpu import RandomForestClassifier

        X, y = self._data()
        rf = RandomForestClassifier(
            n_estimators=8, max_depth=3, criterion="entropy",
            min_instances_per_node=5, seed=0,
        ).fit(X, y)
        assert rf.score(X, y) > 0.9
        assert rf.get_params()["criterion"] == "entropy"


@pytest.mark.slow  # [PR 14 pyramid] ~2.2s weight-gate variant soak; the default gate contract stays tier-1
def test_fractional_weights_unaffected_by_default_gate():
    """The instance gate defaults OFF: normalized fractional
    sample_weight (mass << 1 per side) must fit normal trees, and GBTs
    (whose stats carry Hessian mass, not counts) must keep splitting."""
    import jax
    import jax.numpy as jnp

    from spark_bagging_tpu import BaggingClassifier, GBTClassifier

    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    w = np.full(300, 1.0 / 300, np.float32)  # sums to 1
    clf = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3),
        n_estimators=2, seed=0, bootstrap=False,
    ).fit(X, y, sample_weight=w)
    thr = np.asarray(clf.ensemble_["threshold"])
    assert np.isfinite(thr[:, 0]).all()  # root split happened
    assert clf.score(X, y) > 0.9
    gbt = GBTClassifier(n_rounds=10, max_depth=2, lr=0.5)
    params, _ = gbt.fit_from_init(
        jax.random.key(0), jnp.asarray(X), jnp.asarray(y, jnp.int32),
        jnp.ones(300), 2,
    )
    # late, confident rounds still split (Hessian mass << 1)
    late = np.asarray(params["threshold"]).reshape(10, -1)[-1]
    assert np.isfinite(late[0])


@pytest.mark.slow  # [PR 14 pyramid] ~1.7s render-vs-predict sweep; debug-string split-count check stays tier-1
def test_to_debug_string_matches_predictions():
    """Spark toDebugString analog: the printed rules route a probe row
    to the same prediction predict_scores gives, and the planted split
    feature appears at the root."""
    rng = np.random.default_rng(3)
    X = rng.standard_normal((600, 5)).astype(np.float32)
    y = (X[:, 2] > 0.3).astype(np.int32)
    tree = DecisionTreeClassifier(max_depth=2, n_bins=32)
    params, _ = tree.fit_from_init(
        jax.random.key(0), jnp.asarray(X), jnp.asarray(y),
        jnp.ones(600), 2,
    )
    s = tree.to_debug_string(params)
    assert s.splitlines()[1].startswith(" If (feature 2 <= ")
    assert "Predict: " in s
    # named features render
    s2 = tree.to_debug_string(params, feature_names=list("abcde"))
    assert "If (c <= " in s2
    # manual routing along the printed root rule agrees with predict
    thr = float(np.asarray(params["threshold"])[0])
    probe_left = np.zeros((1, 5), np.float32); probe_left[0, 2] = thr - 1
    probe_right = np.zeros((1, 5), np.float32); probe_right[0, 2] = thr + 1
    pl = int(np.asarray(tree.predict_scores(params, jnp.asarray(probe_left))).argmax())
    pr = int(np.asarray(tree.predict_scores(params, jnp.asarray(probe_right))).argmax())
    assert pl == 0 and pr == 1


@pytest.mark.slow  # [PR 17 budget offset] ~3.9s deep-fit render soak; debug-string rendering stays tier-1 via the shallow debug-string tests in this file
def test_debug_string_split_count_matches_rendered_tree():
    """The header's splits= count must equal the number of rendered
    'If (' lines — phantom finite-threshold nodes inside unreachable
    subtrees must not inflate it (round-4 audit)."""
    from spark_bagging_tpu.models import DecisionTreeClassifier

    # one feature where a pure split at the root leaves empty subtrees
    rng = np.random.default_rng(0)
    X = np.concatenate(
        [np.full((50, 1), -1.0), np.full((50, 1), 1.0)]
    ).astype(np.float32)
    X = np.concatenate([X, rng.standard_normal((100, 2)).astype(np.float32)], 1)
    y = (X[:, 0] > 0).astype(np.int32)
    tree = DecisionTreeClassifier(max_depth=4)
    p, _ = tree.fit_from_init(
        jax.random.key(0), jnp.asarray(X), jnp.asarray(y),
        jnp.ones(100), 2,
    )
    s = tree.to_debug_string(p)
    rendered = s.count("If (")
    import re

    header_count = int(re.search(r"splits=(\d+)", s).group(1))
    assert header_count == rendered


@pytest.mark.slow  # [PR 14 pyramid] ~3.8s all-zero-weight GBT edge soak; zero-weight neutrality stays tier-1 via the fuzz representative
def test_gbt_all_zero_bootstrap_weights_stay_finite():
    """A replica whose Poisson draw is all zeros (probability e^-λ at
    small max_samples) must not NaN-poison the bagged mean vote
    (round-4 audit: f0 was 0/0)."""
    from spark_bagging_tpu import BaggingRegressor, GBTRegressor

    rng = np.random.default_rng(1)
    X = rng.standard_normal((80, 3)).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    reg = BaggingRegressor(
        base_learner=GBTRegressor(n_rounds=3, max_depth=2),
        n_estimators=16, max_samples=0.02, seed=0,
    ).fit(X, y)
    assert np.isfinite(reg.predict(X)).all()
    # classifier path (binary + the clip(0/0) multiclass prior)
    from spark_bagging_tpu import BaggingClassifier, GBTClassifier

    yc = (X[:, 0] > 0).astype(np.int32)
    clf = BaggingClassifier(
        base_learner=GBTClassifier(n_rounds=3, max_depth=2),
        n_estimators=16, max_samples=0.02, seed=0,
    ).fit(X, yc)
    assert np.isfinite(clf.predict_proba(X)).all()
    y3 = rng.integers(0, 3, 80).astype(np.int32)
    clf3 = BaggingClassifier(
        base_learner=GBTClassifier(n_rounds=2, max_depth=2),
        n_estimators=16, max_samples=0.02, seed=0,
    ).fit(X, y3)
    assert np.isfinite(clf3.predict_proba(X)).all()


def test_tree_workset_model_scales_with_features():
    """The (F, B, N, K) histogram + right copy are per-replica temps:
    the bytes model must grow with the feature count, and the dense
    subspace gather must charge the T-slice copies (round-4 audit)."""
    from spark_bagging_tpu.models import DecisionTreeClassifier

    t = DecisionTreeClassifier(max_depth=5, n_bins=32)
    narrow = t.fit_workset_bytes(100_000, 54, 7)
    wide = t.fit_workset_bytes(100_000, 8192, 7)
    assert wide > narrow + 2 * 4 * (8192 - 54) * 32 * 16 * 7 * 0.99
    g = t.subspace_gather_bytes(100_000, 50)
    assert g >= (1 + 2) * 100_000 * 50 * 32  # T int8 + bf16 Tf copy


@pytest.mark.slow  # [PR 14 pyramid] ~1.7s render sweep twin; single-tree render check stays tier-1
def test_gbt_debug_string_binary_and_multiclass():
    from spark_bagging_tpu import GBTClassifier

    rng = np.random.default_rng(4)
    X = rng.standard_normal((400, 4)).astype(np.float32)
    y2 = (X[:, 1] > 0).astype(np.int32)
    gbt = GBTClassifier(n_rounds=2, max_depth=2)
    p, _ = gbt.fit_from_init(
        jax.random.key(0), jnp.asarray(X), jnp.asarray(y2),
        jnp.ones(400), 2,
    )
    s = gbt.to_debug_string(p)
    assert "Tree 0:" in s and "Tree 1:" in s and "rounds=2" in s
    y3 = rng.integers(0, 3, 400).astype(np.int32)
    p3, _ = gbt.fit_from_init(
        jax.random.key(0), jnp.asarray(X), jnp.asarray(y3),
        jnp.ones(400), 3,
    )
    s3 = gbt.to_debug_string(p3)
    assert "Tree 0 (class 0):" in s3 and "Tree 1 (class 2):" in s3


@pytest.mark.slow  # [PR 20 budget offset] ~4.5s zero-smoothing edge soak; leaf finiteness stays tier-1 via the all-finite-leaves fuzz invariants (same pattern as the gbt all-zero-weight demotion above)
def test_classifier_empty_leaves_no_nan_with_zero_smoothing():
    """leaf_smoothing=0 with unpopulated leaves (pure splits upstream)
    must fall back to uniform log-probs, not log(0/0)=NaN."""
    import jax
    import jax.numpy as jnp

    from spark_bagging_tpu.models.tree import DecisionTreeClassifier

    rng = np.random.default_rng(0)
    # one feature perfectly separates two classes: depth-3 tree leaves
    # below the pure split stay empty
    X = np.concatenate([rng.normal(-3, 0.1, (50, 2)),
                        rng.normal(3, 0.1, (50, 2))]).astype(np.float32)
    y = np.array([0] * 50 + [1] * 50)
    t = DecisionTreeClassifier(max_depth=3, leaf_smoothing=0.0)
    params, _ = t.fit(
        t.init_params(jax.random.key(0), 2, 2), jnp.asarray(X),
        jnp.asarray(y), jnp.ones(100), jax.random.key(1),
    )
    logp = np.asarray(params["leaf_logp"])
    # no NaN anywhere (log(0/0) on empty leaves was the bug); -inf is
    # CORRECT for a class absent from a populated leaf at smoothing=0
    assert not np.isnan(logp).any()
    # empty leaves fell back to uniform: some leaf rows are all log(1/C)
    assert (np.isclose(logp, np.log(0.5)).all(axis=1)).any()
    scores = t.predict_scores(params, jnp.asarray(X))
    assert not np.isnan(np.asarray(scores)).any()
    assert (np.asarray(scores).argmax(1) == y).mean() > 0.99
    with pytest.raises(ValueError, match="leaf_smoothing"):
        DecisionTreeClassifier(leaf_smoothing=-1.0)
