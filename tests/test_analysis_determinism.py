"""Determinism dataflow engine tests [ISSUE 19]: per-rule BAD/GOOD
fixture pairs, the clock-seam marker, the timestamp-key sanction, the
sorted() laundering rule, and the suppression grammar — the same
fixture convention as test_analysis.py (a rule without a known-BAD it
flags and a known-GOOD twin it stays silent on is not trusted).
"""

from __future__ import annotations

import pytest

from spark_bagging_tpu.analysis.determinism import (
    DET_RULES,
    analyze_source,
)


def hits(src: str, rule: str) -> list:
    return [f for f in analyze_source(src) if f.rule == rule]


# -- fixture pairs -----------------------------------------------------

BAD_GOOD = {
    "det-wallclock-sink": (
        # BAD: wall clock hashed into a digest — same inputs, different
        # bytes every run
        """
import hashlib
import time


def transcript_digest(events):
    h = hashlib.sha256()
    h.update(str(time.time()).encode())
    for e in events:
        h.update(repr(e).encode())
    return h.hexdigest()
""",
        # GOOD: the clock is an injectable parameter; the digest hashes
        # only what the caller chose to pass
        """
import hashlib


def transcript_digest(events, now):
    h = hashlib.sha256()
    h.update(str(now).encode())
    for e in events:
        h.update(repr(e).encode())
    return h.hexdigest()
""",
    ),
    "det-unseeded-rng-sink": (
        # BAD: module-level RNG (process-seeded) feeds a digest
        """
import hashlib
import random


def sample_digest():
    h = hashlib.sha256()
    h.update(str(random.random()).encode())
    return h.hexdigest()
""",
        # GOOD: an explicitly seeded Random is reproducible by
        # construction
        """
import hashlib
import random


def sample_digest(seed):
    rng = random.Random(seed)
    h = hashlib.sha256()
    h.update(str(rng.random()).encode())
    return h.hexdigest()
""",
    ),
    "det-identity-sink": (
        # BAD: id() as a sort key — memory layout decides the order
        """
def stable_order(objs):
    return sorted(objs, key=id)
""",
        # GOOD: sort by a value the objects carry
        """
def stable_order(objs):
    return sorted(objs, key=lambda o: o.name)
""",
    ),
    "det-unordered-sink": (
        # BAD: set iteration order feeds a digest
        """
import hashlib


def digest(names):
    h = hashlib.sha256()
    for n in set(names):
        h.update(n.encode())
    return h.hexdigest()
""",
        # GOOD: sorted() pins the order — the canonical fix
        """
import hashlib


def digest(names):
    h = hashlib.sha256()
    for n in sorted(set(names)):
        h.update(n.encode())
    return h.hexdigest()
""",
    ),
}


@pytest.mark.parametrize("rule", sorted(BAD_GOOD))
def test_bad_fixture_is_flagged(rule):
    bad, _ = BAD_GOOD[rule]
    found = hits(bad, rule)
    assert found, f"{rule} did not flag its BAD fixture"


@pytest.mark.parametrize("rule", sorted(BAD_GOOD))
def test_good_fixture_is_clean(rule):
    _, good = BAD_GOOD[rule]
    assert not hits(good, rule), (
        f"{rule} flagged its GOOD fixture:\n"
        + "\n".join(f.render() for f in hits(good, rule))
    )


def test_every_registered_rule_has_fixtures():
    """Registry-completeness guard: a determinism rule without its
    BAD/GOOD pair is not trusted."""
    assert set(DET_RULES) == set(BAD_GOOD), (
        "update BAD_GOOD in test_analysis_determinism.py when adding "
        "determinism rules"
    )


# -- sanctions: the legitimate patterns must stay silent ---------------


def test_timestamp_key_in_event_payload_is_sanctioned():
    """Events legitimately carry wall-clock under timestamp-named keys
    (digests hash deterministic projections that strip them) — the
    engine must not cry wolf on the repo's own idiom."""
    src = """
import time


def note(emit_event):
    emit_event({"kind": "drift_alert", "ts": time.time()})
"""
    assert not analyze_source(src)


def test_wallclock_under_value_key_in_snapshot_is_flagged():
    """The sanction is keyed on the NAME: wall clock under a
    non-timestamp key in a snapshot export is a real leak."""
    src = """
import time


def snapshot():
    return {"value": time.time()}
"""
    assert hits(src, "det-wallclock-sink")


def test_clock_seam_marker_sanctions_the_function():
    """`# sbt-lint: clock-seam` marks the injectable-clock pattern used
    by admission/quarantine/alerts: inside it, wall-clock reads are the
    function's PURPOSE."""
    src = """
import time


# sbt-lint: clock-seam
def snapshot():
    return {"value": time.time()}
"""
    assert not analyze_source(src)


def test_now_parameter_default_fill_is_sanctioned():
    """`now = time.time() if now is None else now` is the repo's
    clock-injection idiom — the fill itself must not be flagged."""
    src = """
import time


def snapshot(now=None):
    if now is None:
        now = time.time()
    return {"value": now}
"""
    assert not analyze_source(src)


def test_sorted_launders_unordered_taint():
    src = """
import hashlib


def digest(names):
    h = hashlib.sha256()
    canon = sorted(set(names))
    for n in canon:
        h.update(n.encode())
    return h.hexdigest()
"""
    assert not analyze_source(src)


# -- suppression grammar -----------------------------------------------

_BAD_WALLCLOCK = BAD_GOOD["det-wallclock-sink"][0]


def test_same_line_suppression():
    src = _BAD_WALLCLOCK.replace(
        "h.update(str(time.time()).encode())",
        "h.update(str(time.time()).encode())"
        "  # sbt-lint: disable=det-wallclock-sink",
    )
    assert not analyze_source(src)


def test_comment_above_suppression():
    src = _BAD_WALLCLOCK.replace(
        "    h.update(str(time.time()).encode())",
        "    # sbt-lint: disable=det-wallclock-sink\n"
        "    h.update(str(time.time()).encode())",
    )
    assert not analyze_source(src)


def test_disable_all_wildcard():
    src = _BAD_WALLCLOCK.replace(
        "h.update(str(time.time()).encode())",
        "h.update(str(time.time()).encode())  # sbt-lint: disable=all",
    )
    assert not analyze_source(src)


def test_disabled_kwarg_filters_rule():
    assert not analyze_source(
        _BAD_WALLCLOCK, disabled=("det-wallclock-sink",)
    )


def test_unknown_enabled_rule_raises():
    with pytest.raises(KeyError):
        analyze_source("x = 1\n", enabled=("no-such-rule",))
