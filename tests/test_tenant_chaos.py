"""Tenant blast-radius containment [ISSUE 18]: fault sites for the
tenancy/residency plane (tenant-scoped ``FaultSpec``s, the two-way
site-table invariant), the per-tenant quarantine machine (failure
window, seeded jittered backoff, single-probe recovery), graceful
degradation of corrupt per-tenant AOT cache entries (counted miss,
never an escaping exception), torn demote-path writes that leave the
previous entry loadable, the quarantine telemetry/alert/debug
surfaces, and the ``tenant-chaos`` drill whose contract is that
bystander tenants are provably untouched — bitwise-identical outputs
and zero added recompiles — while one tenant trips, backs off, and
recovers.
"""

import json
import os
import sys
import warnings

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    faults,
    telemetry,
)
from spark_bagging_tpu.serving import ModelRegistry
from spark_bagging_tpu.serving import program_cache as _pc
from spark_bagging_tpu.tenancy import (
    QuarantineMachine,
    TenantQuarantined,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.enable()
    prev_cache = _pc.install(_pc.ProgramCache(capacity=64))
    yield
    faults.disarm()  # no chaos plan may leak into later tests
    _pc.install(prev_cache)
    telemetry.reset()
    telemetry.enable()


def _counter(name, labels=None):
    return telemetry.registry().counter(name, labels=labels).value


def _problem(n=96, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w > 0).astype(np.int32)
    return X, y


def _fit(seed=0, n_estimators=2):
    X, y = _problem(seed=seed)
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=n_estimators, seed=seed,
    ).fit(X, y)


# -- the site table is an invariant, not documentation ------------------

def _site_findings():
    """Thin wrapper [ISSUE 19] over the contracts engine's two-way
    ``contract-fault-sites`` check — the AST walk subsumes the old
    ``faults.fire(`` regex (it also catches aliased ``*.fire("x")``
    forms the regex missed), faults.py itself still excluded."""
    from spark_bagging_tpu.analysis.contracts import check_repo

    return check_repo(REPO, checks=["contract-fault-sites"])


class TestSiteTable:
    def test_every_fired_site_is_registered(self):
        """Satellite [ISSUE 18]: a ``faults.fire("x")`` call with no
        SITES entry is a silent no-op plan key — static analysis, so
        the drift is caught at test time, not mid-incident."""
        unknown = [f for f in _site_findings()
                   if "no faults.SITES entry" in f.message]
        assert not unknown, (
            "fire() call sites not registered in faults.SITES:\n"
            + "\n".join(f.render() for f in unknown)
        )

    def test_every_registered_site_has_a_live_call_site(self):
        """The other direction: a SITES key nobody fires is a dead
        entry in the documented fault surface."""
        dead = [f for f in _site_findings()
                if "no live fire() call" in f.message]
        assert not dead, (
            "faults.SITES entries with no live fire() call:\n"
            + "\n".join(f.render() for f in dead)
        )


# -- tenant-scoped fault specs ------------------------------------------

class TestTenantScopedSpecs:
    def test_roundtrip_and_builtin_plan(self):
        spec = {"schema": 1, "name": "p", "seed": 7, "faults": [
            {"site": "fleet.dispatch", "action": "error",
             "tenant": "t1", "at": [2]},
        ]}
        plan = faults.FaultPlan.from_dict(spec)
        assert plan.to_dict()["faults"][0]["tenant"] == "t1"
        assert (faults.FaultPlan.from_dict(plan.to_dict()).digest()
                == plan.digest())
        builtin = faults.builtin_plan_spec("tenant-chaos", seed=111)
        assert {f["tenant"] for f in builtin["faults"]} == {"t1"}

    def test_tenant_filter_counts_on_its_own_clock(self):
        """A tenant-scoped spec fires on the per-(site, tenant) hit
        counter: heavy traffic from OTHER tenants must not advance —
        or consume — the target's schedule."""
        plan = faults.FaultPlan([
            {"site": "fleet.dispatch", "action": "error",
             "tenant": "t1", "at": [2]},
        ])
        with faults.armed(plan):
            for _ in range(5):  # t0's hits are not t1's hits
                faults.fire("fleet.dispatch", tenant="t0")
            faults.fire("fleet.dispatch", tenant="t1")  # t1 hit 1
            with pytest.raises(faults.FaultInjected):
                faults.fire("fleet.dispatch", tenant="t1")  # hit 2
            faults.fire("fleet.dispatch", tenant="t1")  # hit 3: done
        snap = plan.snapshot()
        assert snap["fired_total"] == 1
        assert snap["tenant_hits"] == {
            "fleet.dispatch|t0": 5, "fleet.dispatch|t1": 3,
        }

    def test_tenant_blind_snapshots_stay_stable(self):
        """No ``tenant=`` info ever passed -> no ``tenant_hits`` key:
        the committed digests of the pre-existing chaos baselines
        (mixed, peer-loss, ...) must not grow a key."""
        plan = faults.FaultPlan([
            {"site": "batcher.submit", "action": "error", "at": [999]},
        ])
        with faults.armed(plan):
            faults.fire("batcher.submit")
        assert "tenant_hits" not in plan.snapshot()


# -- the quarantine machine (jax-free) ----------------------------------

def _drive_cycle(q, now=0.0):
    """threshold failures -> trip; returns the trip event."""
    for i in range(3):
        tripped = q.record_failure("t1", now + i * 0.01, "dispatch")
    assert tripped
    return [e for e in q.events() if e["kind"] == "trip"][-1]


class TestQuarantineMachine:
    def test_trip_shed_probe_recover_cycle(self):
        q = QuarantineMachine(["t0", "t1"], threshold=3, window_s=1.0,
                              backoff_s=0.5, seed=0)
        trip = _drive_cycle(q)
        assert not q.healthy("t1") and q.healthy("t0")
        # inside the backoff: shed with the distinct exception type
        with pytest.raises(TenantQuarantined):
            q.admit("t1", trip["until"] - 1e-6)
        assert q.admit("t0", 0.1) == "healthy"  # bystander untouched
        # past the deadline: exactly one probe, everything else sheds
        t = trip["until"] + 0.01
        assert q.admit("t1", t) == "probe"
        with pytest.raises(TenantQuarantined):
            q.admit("t1", t)
        assert q.probe_result("t1", t, ok=True) is False
        assert q.healthy("t1")
        c = q.counts()
        assert c["trips"] == {"t1": 1} and c["recoveries"] == {"t1": 1}
        assert c["sheds"]["t1"] == 2 and c["probes"] == {"t1": 1}
        assert _counter("sbt_tenant_quarantine_shed_total") == 2.0
        assert _counter("sbt_tenancy_shed_total",
                        {"tenant": "t1", "reason": "quarantine"}) == 2.0

    def test_failed_probe_retrips_with_escalated_backoff(self):
        q = QuarantineMachine(["t1"], threshold=3, window_s=1.0,
                              backoff_s=0.5, backoff_factor=2.0, seed=3)
        first = _drive_cycle(q)
        t = first["until"] + 0.01
        assert q.admit("t1", t) == "probe"
        assert q.probe_result("t1", t, ok=False) is True
        second = [e for e in q.events() if e["kind"] == "trip"][-1]
        # rung 2 of the ladder: nominal 1.0s vs 0.5s; jitter spans
        # [0.75, 1.25), so the escalated rung is strictly longer
        assert second["backoff_s"] > first["backoff_s"]
        assert not q.healthy("t1")

    def test_probe_aborted_keeps_the_deadline(self):
        q = QuarantineMachine(["t1"], threshold=3, seed=0)
        trip = _drive_cycle(q)
        t = trip["until"] + 0.01
        assert q.admit("t1", t) == "probe"
        q.probe_aborted("t1")  # shed upstream: no verdict reached
        assert q.admit("t1", t) == "probe"  # next request re-probes
        assert q.counts()["probes"] == {"t1": 2}
        assert q.counts()["trips"] == {"t1": 1}  # an abort is no trip

    def test_window_prunes_stale_failures(self):
        q = QuarantineMachine(["t1"], threshold=3, window_s=0.5, seed=0)
        assert not q.record_failure("t1", 0.0, "dispatch")
        assert not q.record_failure("t1", 0.2, "dispatch")
        # both earlier failures aged out of the 0.5s window by 0.8:
        # without the prune this third failure would already trip
        assert not q.record_failure("t1", 0.8, "dispatch")
        assert not q.record_failure("t1", 0.85, "dispatch")
        assert q.healthy("t1")
        assert q.record_failure("t1", 0.9, "dispatch")  # 3 in-window
        assert not q.healthy("t1")

    def test_backoff_is_seeded_and_tenant_decorrelated(self):
        def events_for(seed):
            q = QuarantineMachine(["t1", "t2"], threshold=1,
                                  backoff_s=0.5, seed=seed)
            q.record_failure("t1", 0.0, "dispatch")
            q.record_failure("t2", 0.0, "dispatch")
            return q.events()

        a, b = events_for(42), events_for(42)
        assert a == b  # same seed: byte-identical transcript
        c = events_for(43)
        assert [e["backoff_s"] for e in a] != [e["backoff_s"] for e in c]
        # two tenants tripping at the same instant never share a rung
        until = {e["tenant"]: e["until"] for e in a if e["kind"] == "trip"}
        assert until["t1"] != until["t2"]

    def test_unknown_tenant_and_bad_config_rejected(self):
        q = QuarantineMachine(["t1"], seed=0)
        with pytest.raises(KeyError, match="unknown tenant"):
            q.admit("ghost", 0.0)
        with pytest.raises(ValueError, match="threshold"):
            QuarantineMachine(["t1"], threshold=0)
        with pytest.raises(ValueError, match="backoff_factor"):
            QuarantineMachine(["t1"], backoff_factor=0.5)


# -- the unarmed hot path pays nothing ----------------------------------

def test_unarmed_tenancy_paths_never_call_fire(monkeypatch, tmp_path):
    """The new probes follow the framework's founding rule: with no
    plan armed, ``faults.fire`` is never even called (one module-
    attribute read per probe). Patching fire() to raise proves it
    across WFQ pop, the refit budgeter, and a residency demote/restore
    round-trip (jax-free stand-ins)."""
    from spark_bagging_tpu.tenancy import RefitBudgeter, WFQScheduler
    from spark_bagging_tpu.tenancy.residency import ResidencyManager
    from spark_bagging_tpu.tenancy.spec import TenantSpec

    def boom(*a, **k):  # pragma: no cover — reaching it IS the failure
        raise AssertionError("faults.fire called while unarmed")

    monkeypatch.setattr(faults, "fire", boom)
    assert faults.ACTIVE is None

    wfq = WFQScheduler({"a": 2.0, "b": 1.0})
    wfq.enqueue("a", "x")
    assert wfq.pop() == ("a", "x")

    specs = [TenantSpec(name="a", weight=2.0), TenantSpec(name="b")]
    budget = RefitBudgeter(specs, total_per_window=2, window_s=1.0)
    assert budget.allow("a", now=0.0) in (True, False)

    class _Reg:
        def executor(self, name):
            class _Ex:
                compiled_buckets = (8,)

                def release_programs(self):
                    return ()

                def save_executables(self, path):
                    os.makedirs(path, exist_ok=True)
                    return (8,)

                def restore_executables(self, path):
                    return (8,)

            return _Ex()

    r = ResidencyManager(_Reg(), capacity=1, aot_root=str(tmp_path))
    r.adopt("a")
    r.adopt("b")      # demotes "a" (persist path)
    r.touch("a")      # restores "a" (restore path)


# -- graceful degradation: corrupt per-tenant AOT entries ---------------

class TestCorruptAotEntry:
    def test_corrupt_bucket_is_a_counted_miss_not_an_error(self, tmp_path):
        """Satellite [ISSUE 18]: an unreadable/truncated executable
        blob restores as a miss — warning + corrupt counter + lower-
        on-demand — never an exception out of the restore path."""
        path = str(tmp_path / "aot")
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
        reg.register("m", _fit(seed=0), warmup=True)
        ex = reg.executor("m")
        saved = ex.save_executables(path)
        assert saved
        # tear ONE bucket blob; the manifest still promises it
        from spark_bagging_tpu.serving.aot_cache import MANIFEST

        blobs = sorted(f for f in os.listdir(path) if f != MANIFEST)
        with open(os.path.join(path, blobs[0]), "wb") as f:
            f.write(b"\x00garbage\x00")

        _pc.clear()
        reg2 = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
        reg2.register("m", _fit(seed=0), warmup=False)
        ex2 = reg2.executor("m")
        c0 = _counter("sbt_aot_load_corrupt_total")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            restored = ex2.restore_executables(path)
        assert _counter("sbt_aot_load_corrupt_total") == c0 + 1
        assert any("restore" in str(w.message) for w in caught)
        assert len(restored) == len(saved) - 1
        # the miss lowers on demand and still serves
        X = np.zeros((3, 8), np.float32)
        assert np.asarray(ex2.forward(X)).shape[0] == 3

    def test_unreadable_manifest_is_counted(self, tmp_path):
        from spark_bagging_tpu.serving.aot_cache import MANIFEST

        path = str(tmp_path / "aot")
        os.makedirs(path)
        with open(os.path.join(path, MANIFEST), "w") as f:
            f.write("{not json")
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
        reg.register("m", _fit(seed=0), warmup=False)
        c0 = _counter("sbt_aot_load_corrupt_total")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert reg.executor("m").restore_executables(path) == ()
        assert _counter("sbt_aot_load_corrupt_total") == c0 + 1


# -- torn demote-path writes --------------------------------------------

@pytest.mark.parametrize("site", ["residency.demote_persist",
                                  "aot.save"])
def test_torn_demote_persist_leaves_previous_entry_intact(
        site, tmp_path):
    """Satellite [ISSUE 18]: a kill at either seam of the demote-path
    persist — before ``save_executables`` runs, or inside it before
    the atomic install — must leave the PREVIOUS committed per-tenant
    entry on disk, loadable, and the tenant restorable from it."""
    from spark_bagging_tpu.tenancy.residency import ResidencyManager

    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    reg.register("a", _fit(seed=0), warmup=False)
    reg.register("b", _fit(seed=1), warmup=False)
    ex = reg.executor("a")
    X = np.zeros((3, 8), np.float32)
    ex.forward(X)  # compiles bucket 8 only
    mgr = ResidencyManager(reg, capacity=1, aot_root=str(tmp_path))
    dir_a = mgr.tenant_dir("a")
    saved = ex.save_executables(dir_a)  # the previous committed entry
    assert saved == (8,)
    ex.warmup()  # full ladder -> covers() false -> demote re-persists
    mgr.adopt("a")

    plan = faults.FaultPlan([
        {"site": site, "action": "kill", "at": [1]},
    ])
    with faults.armed(plan):
        with pytest.raises(faults.SimulatedKill):
            mgr.adopt("b")  # victim "a": demote persist is killed

    # the previous entry is intact: a fresh process restores and serves
    _pc.clear()
    reg2 = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    reg2.register("a", _fit(seed=0), warmup=False)
    ex2 = reg2.executor("a")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert ex2.restore_executables(dir_a) == (8,)
    np.testing.assert_array_equal(
        np.asarray(ex2.forward(X)), np.asarray(ex.forward(X)))


# -- telemetry, alert rule, debug surface -------------------------------

class TestQuarantineSurfaces:
    def test_series_help_covers_the_quarantine_family(self):
        from spark_bagging_tpu.telemetry.registry import SERIES_HELP

        for name in ("sbt_tenant_quarantine_trips_total",
                     "sbt_tenant_quarantine_shed_total",
                     "sbt_tenant_quarantine_probes_total",
                     "sbt_tenant_quarantine_recoveries_total",
                     "sbt_tenant_quarantine_failures_total",
                     "sbt_tenant_quarantine_active",
                     "sbt_aot_load_corrupt_total"):
            assert name in SERIES_HELP, name

    def test_flapping_rule_needs_two_trips_per_window(self):
        """Satellite [ISSUE 18]: the quarantine-flapping rule burns on
        the trips rate — one isolated trip stays quiet, >= 2 per fast
        window (sustained across the slow window) pages."""
        from spark_bagging_tpu.telemetry import alerts

        rules = {r.name: r for r in alerts.default_capacity_rules(
            fast_window_s=2.0, slow_window_s=5.0, cooldown_s=0.0)}
        rule = rules["tenancy-quarantine-flapping"]
        assert rule.series == "sbt_tenant_quarantine_trips_total"
        assert rule.kind == "rate"
        eng = alerts.AlertEngine([rule])
        assert eng.evaluate(now=0.0) == []
        telemetry.inc("sbt_tenant_quarantine_trips_total")  # one trip
        quiet = [e for t in (2.0, 4.0, 5.5, 7.0)
                 for e in eng.evaluate(now=t)]
        assert [e for e in quiet if e["kind"] == "alert_fired"] == []
        fired = []
        for i in range(1, 12):  # 2 trips per evaluation tick: flapping
            telemetry.inc("sbt_tenant_quarantine_trips_total", 2.0)
            fired += [e for e in eng.evaluate(now=7.0 + i / 2)
                      if e["kind"] == "alert_fired"]
        assert [e["rule"] for e in fired] == [
            "tenancy-quarantine-flapping"]

    def test_debug_tenancy_carries_quarantine_state(self):
        import spark_bagging_tpu.tenancy as tenancy
        from spark_bagging_tpu.telemetry.server import _debug_tenancy
        from spark_bagging_tpu.tenancy import TenantFleet, TenantSpec

        fleet = TenantFleet([TenantSpec(name="t0"),
                             TenantSpec(name="t1")])
        tenancy.install(fleet)
        try:
            fleet.quarantine.record_failure("t1", 0.0, "dispatch")
            body = _debug_tenancy()
            q = body["quarantine"]
            assert q["threshold"] == 3
            assert q["tenants"]["t1"]["state"] == "healthy"
            json.dumps(body)  # the document must stay JSON-clean
        finally:
            tenancy.uninstall()


# -- the tenant-chaos drill ---------------------------------------------

class TestTenantChaosDrill:
    @pytest.mark.slow  # [PR 20 budget offset] ~7.7s in-process drill twin; blast-radius containment stays tier-1 via the tenant-chaos registered scenario in the conformance smoke (committed digests include the fault + quarantine transcripts)
    def test_blast_radius_containment_in_process(self):
        """The tentpole's acceptance gate, in-process: the builtin
        ``tenant-chaos`` plan through ``replay_median(tenants=True,
        repeats=2)`` — cross-repeat byte identity (fault + quarantine
        transcripts included) asserted by the harness — trips t1's
        quarantine and recovers it, while every bystander's output
        digest is bitwise-equal to a no-chaos control run and its
        post-warmup compile count is exactly zero."""
        from benchmarks import replay as R
        from spark_bagging_tpu.telemetry import workload as workload_mod

        wl = workload_mod.synthetic_workload(
            "poisson", rate_rps=300.0, duration_s=0.4, seed=111,
            width=8, bucket_bounds=(8, 32),
        )
        chaos = faults.builtin_plan_spec("tenant-chaos", seed=111)
        kw = dict(n_tenants=6, residency_capacity=4, zipf_s=1.1,
                  width=8, n_estimators=2, seed=111,
                  min_bucket_rows=8, bucket_max_rows=32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            report = R.replay_median(wl, repeats=2, tenants=True,
                                     chaos=chaos, retries=2, **kw)
            control = R.replay_tenants(wl, **kw)

        from spark_bagging_tpu.telemetry import slo

        # the fleet-total compile pin is disabled exactly as the
        # committed scenario does it: the targeted tenant is allowed
        # its recovery recompile; _tenants_checks pins the bystanders
        result = R.check_report(report, spec=slo.SLOSpec(
            max_overloads=0, max_post_warmup_compiles=None))
        assert result.ok, result.render()
        t, c = report["tenants"], report["chaos"]
        assert c["plan"] == "tenant-chaos"
        assert c["sites"]["fired_total"] >= 4
        assert c["shed"]["quarantine"] >= 1
        assert t["quarantine"]["trips"] == {"t1": 1}
        assert t["quarantine"]["recoveries"] == {"t1": 1}
        assert report["errors"] == 0  # contained, not crashed

        # zero ADDED recompiles: only the faulted tenant re-lowers its
        # one corrupt-entry bucket; bystanders pay nothing
        by = t["post_warmup_compiles_by_tenant"]
        assert by["t1"] == 1
        assert all(v == 0 for n, v in by.items() if n != "t1")
        assert control["post_warmup_compiles"] == 0

        # bitwise-unchanged bystander outputs vs the no-chaos control
        dig = t["output_digest_by_tenant"]
        dig0 = control["tenants"]["output_digest_by_tenant"]
        for name in dig0:
            if name == "t1":
                assert dig[name] != dig0[name]  # t1 DID lose requests
            else:
                assert dig[name] == dig0[name], name

    def test_cli_rejects_tenancy_sites_without_tenants(self):
        from benchmarks import replay as R

        with pytest.raises(SystemExit):
            R.main(["--chaos", "tenant-chaos"])
