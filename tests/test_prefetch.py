"""Prefetching chunk pipeline: order preservation, exception
propagation, thread cleanup, engine equivalence [SURVEY §1 L1 analog]."""

import threading
import time

import numpy as np
import pytest

from spark_bagging_tpu import ArrayChunks, BaggingClassifier, BaggingRegressor
from spark_bagging_tpu.utils.prefetch import PrefetchChunks


def _producer_threads():
    return [
        t for t in threading.enumerate()
        if t.is_alive() and t.name == "prefetch-producer"
    ]


class TestPrefetchChunks:
    def test_chunks_identical_and_ordered(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(500, 4)).astype(np.float32)
        y = rng.integers(0, 2, 500).astype(np.float32)
        src = ArrayChunks(X, y, chunk_rows=64)
        pf = PrefetchChunks(src, depth=3)
        assert pf.n_chunks == src.n_chunks
        assert pf.n_features == src.n_features
        a = [(Xc.copy(), yc.copy(), nv) for Xc, yc, nv in src.chunks()]
        b = [(Xc.copy(), yc.copy(), nv) for Xc, yc, nv in pf.chunks()]
        assert len(a) == len(b)
        for (Xa, ya, na), (Xb, yb, nb) in zip(a, b):
            np.testing.assert_array_equal(Xa, Xb)
            np.testing.assert_array_equal(ya, yb)
            assert na == nb

    def test_multiple_epochs(self):
        X = np.arange(40, dtype=np.float32).reshape(20, 2)
        y = np.zeros(20, np.float32)
        pf = PrefetchChunks(ArrayChunks(X, y, chunk_rows=8), depth=2)
        e1 = [Xc.copy() for Xc, _, _ in pf.chunks()]
        e2 = [Xc.copy() for Xc, _, _ in pf.chunks()]
        for a, b in zip(e1, e2):
            np.testing.assert_array_equal(a, b)

    def test_producer_exception_propagates(self):
        class Boom(ArrayChunks):
            def chunks(self):
                yield from super().chunks()
                raise RuntimeError("disk on fire")

        X = np.zeros((16, 2), np.float32)
        y = np.zeros(16, np.float32)
        pf = PrefetchChunks(Boom(X, y, chunk_rows=8), depth=2)
        with pytest.raises(RuntimeError, match="disk on fire"):
            list(pf.chunks())

    def test_abandoned_iterator_stops_producer(self):
        class Slow(ArrayChunks):
            def chunks(self):
                for item in super().chunks():
                    time.sleep(0.01)
                    yield item

        X = np.zeros((10_000, 2), np.float32)
        y = np.zeros(10_000, np.float32)
        pf = PrefetchChunks(Slow(X, y, chunk_rows=16), depth=2)
        before = len(_producer_threads())
        it = pf.chunks()
        next(it)
        assert len(_producer_threads()) == before + 1
        it.close()  # abandon mid-epoch (close() joins the producer)
        assert len(_producer_threads()) == before  # producer exited

    def test_depth_validation(self):
        X = np.zeros((4, 2), np.float32)
        with pytest.raises(ValueError, match="depth"):
            PrefetchChunks(ArrayChunks(X, np.zeros(4), chunk_rows=2), 0)


class TestEngineEquivalence:
    def test_fit_stream_prefetch_matches_no_prefetch(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(600, 6)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        kw = dict(classes=[0, 1], n_epochs=4, lr=0.1)
        a = BaggingClassifier(n_estimators=8, seed=0).fit_stream(
            ArrayChunks(X, y, chunk_rows=128), prefetch=0, **kw
        )
        b = BaggingClassifier(n_estimators=8, seed=0).fit_stream(
            ArrayChunks(X, y, chunk_rows=128), prefetch=2, **kw
        )
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), rtol=1e-6
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~3.4s prefetch engine-equivalence soak; the classifier prefetch parity stays tier-1
    def test_regressor_and_tree_stream_with_prefetch(self):
        from spark_bagging_tpu.models import DecisionTreeRegressor

        rng = np.random.default_rng(2)
        X = rng.normal(size=(512, 5)).astype(np.float32)
        y = (X[:, 0] - X[:, 2] + 0.1 * rng.normal(size=512)).astype(
            np.float32
        )
        reg = BaggingRegressor(n_estimators=4, seed=0).fit_stream(
            ArrayChunks(X, y, chunk_rows=128), n_epochs=6, lr=0.05
        )
        assert np.isfinite(reg.predict(X)).all()
        # multi-pass tree engine re-opens chunks() once per pass — each
        # pass gets its own producer thread
        tr = BaggingRegressor(
            base_learner=DecisionTreeRegressor(max_depth=3),
            n_estimators=4, seed=0,
        ).fit_stream(ArrayChunks(X, y, chunk_rows=128))
        assert tr.score(X, y) > 0.5


def test_double_wrap_unwraps():
    X = np.zeros((8, 2), np.float32)
    src = ArrayChunks(X, np.zeros(8), chunk_rows=4)
    pf = PrefetchChunks(PrefetchChunks(src, 2), 3)
    assert pf._inner is src


def test_exception_not_lost_when_queue_full():
    """The terminal exception must survive a full queue + slow consumer
    (the first-chunk-compile scenario) instead of hanging the stream."""
    class BoomEarly(ArrayChunks):
        def chunks(self):
            it = super().chunks()
            yield next(it)
            yield next(it)
            yield next(it)
            raise RuntimeError("io error after buffer fill")

    X = np.zeros((64, 2), np.float32)
    pf = PrefetchChunks(BoomEarly(X, np.zeros(64), chunk_rows=8), depth=1)
    it = pf.chunks()
    next(it)
    time.sleep(1.5)  # producer has raised while the queue was full
    with pytest.raises(RuntimeError, match="io error"):
        list(it)


def test_scoring_stream_prefetch_knob():
    """Scoring streams: prefetch=0 disables wrapping; an explicitly
    wrapped source keeps its configured depth."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)
    clf = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
    src = ArrayChunks(X, y, chunk_rows=64)
    a = clf.predict_proba_stream(src)
    b = clf.predict_proba_stream(src, prefetch=0)
    np.testing.assert_allclose(a, b, rtol=1e-6)
    wrapped = PrefetchChunks(src, depth=5)
    out = clf._stream_chunks(wrapped)
    assert out is wrapped and out._depth == 5
    acc = clf.score_stream(src, prefetch=0)
    assert acc == clf.score_stream(src)


def test_touch_pages_handles_all_array_kinds():
    """The producer-side page toucher must be safe on every chunk
    shape a source can yield: contiguous views (the zero-copy Arrow
    fast path it exists for), non-contiguous slices, small arrays,
    readonly mmaps, and non-array items."""
    import numpy as np

    from spark_bagging_tpu.utils.prefetch import _touch_pages

    big = np.zeros((600, 600), np.float32)        # > 1 MiB, contiguous
    # every 4 KiB page of the 2-D block must be probed — a row-wise
    # stride once covered 0.02% of pages and silently un-overlapped
    # the I/O (round-5 review)
    assert _touch_pages((big,)) == -(-big.nbytes // 4096)
    assert _touch_pages((big, big[:, :3], np.zeros(4), 7, None)) == \
        -(-big.nbytes // 4096)
    ro = np.zeros((600, 600), np.float32)
    ro.setflags(write=False)
    assert _touch_pages((ro, ro[0])) == -(-ro.nbytes // 4096)


def test_worth_prefetching_gates_on_spare_core(monkeypatch):
    """The engines' default wrap is gated on a spare host core —
    with one core the producer can only steal cycles from the
    consumer (measured 0-25% net cost on 23.7 GiB cold streams)."""
    from spark_bagging_tpu.utils import prefetch as pf

    monkeypatch.setattr(pf, "_SPARE_CORE", False)
    assert not pf.worth_prefetching()
    monkeypatch.setattr(pf, "_SPARE_CORE", True)
    assert pf.worth_prefetching()


@pytest.mark.slow  # [PR 17 budget offset] ~4.7s default-policy end-to-end soak; prefetch equivalence + knob contracts stay tier-1 via TestEngineEquivalence + test_scoring_stream_prefetch_knob
def test_engine_default_wrap_policy(monkeypatch):
    """The engine's prefetch policy, end to end [round-5 review]: the
    None default wraps only with a spare core, an explicit int forces
    the wrap on any host, and an explicitly-constructed PrefetchChunks
    is honored (classifier splices its label encoder INSIDE the wrap
    rather than hiding it)."""
    import numpy as np

    from spark_bagging_tpu import BaggingClassifier
    from spark_bagging_tpu.utils import prefetch as pf
    from spark_bagging_tpu.utils.io import ArrayChunks

    rng = np.random.default_rng(0)
    X = rng.standard_normal((400, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)

    created = []
    real_init = pf.PrefetchChunks.__init__

    def spy_init(self, inner, depth=2):
        created.append(depth)
        real_init(self, inner, depth)

    monkeypatch.setattr(pf.PrefetchChunks, "__init__", spy_init)

    def fit(source, **kw):
        return BaggingClassifier(n_estimators=2, seed=0).fit_stream(
            source, classes=[0, 1], steps_per_chunk=1, lr=0.1, **kw
        )

    # no spare core: the None default must not wrap
    monkeypatch.setattr(pf, "_SPARE_CORE", False)
    fit(ArrayChunks(X, y, 100))
    assert created == []
    # ...but an explicit int forces it at that depth
    fit(ArrayChunks(X, y, 100), prefetch=3)
    assert created == [3]
    # spare core: the default wraps at depth 2
    monkeypatch.setattr(pf, "_SPARE_CORE", True)
    created.clear()
    fit(ArrayChunks(X, y, 100))
    assert created == [2]
    # an explicitly-wrapped source keeps its depth: the encoder is
    # spliced inside (rewrap -> one new wrap at the SAME depth), and
    # the engine adds nothing on top
    created.clear()
    src = pf.PrefetchChunks(ArrayChunks(X, y, 100), depth=5)
    fit(src)
    assert created == [5, 5], (
        "expected construct-at-5 then rewrap-at-5, got " + str(created)
    )
