"""The headline bench's self-tuning machinery (bench.py +
benchmarks/tune_headline.py) — pure-host logic, no device needed.

These scripts run unattended inside the TPU-window watcher, so their
resume/ordering/gating rules are load-bearing: a regression here wastes
a live TPU window or tunes the headline from incomparable numbers.
"""
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (REPO, os.path.join(REPO, "benchmarks")):
    if p not in sys.path:
        sys.path.insert(0, p)

import bench  # noqa: E402
import tune_headline  # noqa: E402
from headline_data import HEADLINE, WORKLOAD  # noqa: E402


def _cell(impl="blocked", chunk=200, row_tile=None, fps=100.0, acc=0.77,
          workload=WORKLOAD, max_iter=3, init="zeros", **extra):
    c = {"impl": impl, "chunk": chunk, "row_tile": row_tile,
         "max_iter": max_iter, "init": init, "fps": fps,
         "acc": acc, "workload": workload}
    c.update(extra)
    return c


def _write_sweep(tmp_path, monkeypatch, cells):
    bdir = tmp_path / "benchmarks"
    bdir.mkdir(exist_ok=True)
    (bdir / "tune_headline.json").write_text(json.dumps(cells))
    monkeypatch.setattr(bench, "REPO", str(tmp_path))


class TestLoadSweepWinner:
    def test_picks_fastest_passing_cell(self, tmp_path, monkeypatch):
        _write_sweep(tmp_path, monkeypatch, [
            _cell(chunk=100, fps=80.0),
            _cell(chunk=200, fps=120.0),
            _cell(chunk=300, fps=150.0, acc=0.50),  # fails the acc bar
        ])
        w = bench.load_sweep_winner(0.76, WORKLOAD)
        assert (w["chunk"], w["fps"]) == (200, 120.0)

    def test_workload_mismatch_cannot_win(self, tmp_path, monkeypatch):
        stale = dict(WORKLOAD, dataset="covtype_synth_v2")
        _write_sweep(tmp_path, monkeypatch, [
            _cell(fps=500.0, workload=stale),
            _cell(fps=90.0),
        ])
        assert bench.load_sweep_winner(0.76, WORKLOAD)["fps"] == 90.0

    def test_unstamped_cells_cannot_win(self, tmp_path, monkeypatch):
        cells = [_cell(fps=500.0)]
        del cells[0]["workload"]
        _write_sweep(tmp_path, monkeypatch, cells)
        assert bench.load_sweep_winner(0.76, WORKLOAD) is None

    def test_error_cells_and_missing_file(self, tmp_path, monkeypatch):
        _write_sweep(tmp_path, monkeypatch, [
            _cell(fps=None, acc=None, error="boom"),
        ])
        assert bench.load_sweep_winner(0.76, WORKLOAD) is None
        monkeypatch.setattr(bench, "REPO", str(tmp_path / "nope"))
        assert bench.load_sweep_winner(0.76, WORKLOAD) is None


class TestSweepOrdering:
    def test_errored_cells_sort_after_unattempted(self):
        errored = {tune_headline.GRID[0], tune_headline.GRID[2]}
        order = tune_headline.order_cells(tune_headline.GRID, errored)
        assert set(order[-2:]) == errored
        # never-errored cells keep grid order apart from the de-risk
        # promotions checked below
        rest = [k for k in tune_headline.GRID if k not in errored]
        assert set(order[:-2]) == set(rest)

    def test_untried_kernel_impls_lead_the_sweep(self):
        """First Mosaic compile of the pallas (then packed) kernels must
        happen at the START of a window, while there's still time to
        fall back [VERDICT r3 ask#1/weak#6]."""
        order = tune_headline.order_cells(tune_headline.GRID, {})
        assert order[0][0] == "pallas"
        assert order[1][0] == "packed"
        # a pallas cell that already errored is NOT re-promoted — the
        # next never-attempted pallas cell takes its place
        first_pallas = order[0]
        order2 = tune_headline.order_cells(
            tune_headline.GRID, {first_pallas: {}}
        )
        assert order2[0][0] == "pallas" and order2[0] != first_pallas
        assert order2[-1] == first_pallas
        # with EVERY pallas cell errored, the packed promotion still
        # leads (the default rank must not tie with a promotion rank)
        all_pallas = {s for s in tune_headline.GRID if s[0] == "pallas"}
        order3 = tune_headline.order_cells(
            tune_headline.GRID, {s: {} for s in all_pallas}
        )
        assert order3[0][0] == "packed"

    def test_watcher_suite_done_checks_cover_all_configs(self):
        # the smoke/full done-checks must demand a row for EVERY config
        # run_configs defines — a new config must not let a shorter
        # capture settle the stage
        import run_configs

        n = len(run_configs.CONFIGS)
        assert n == 8  # 5 BASELINE + forest + bagged GBT + out-of-core
        src = open(os.path.join(REPO, "benchmarks", "tpu_watch.sh")).read()
        assert src.count(f"len(rs) >= {n}") == 2, (
            "smoke_done/full_done thresholds out of step with CONFIGS"
        )
        parser_default = [
            ln for ln in open(
                os.path.join(REPO, "benchmarks", "run_configs.py")
            ) if '"--configs"' in ln
        ][0]
        assert ",".join(str(c) for c in sorted(run_configs.CONFIGS)) \
            in parser_default

    def test_watcher_done_check_derives_from_grid(self):
        # tune_done must stay coupled to the actual grid and workload
        # stamp — a hardcoded count or stamp-blind count would let a
        # stale or shrunken sweep settle the stage forever
        src = open(os.path.join(REPO, "benchmarks", "tpu_watch.sh")).read()
        assert "from tune_headline import GRID" in src
        assert "from headline_data import WORKLOAD" in src
        assert 'c.get("workload") == WORKLOAD' in src

    def test_workload_stamp_carries_problem_constants_only(self):
        # WORKLOAD = the problem (dataset + size + l2 + precision);
        # max_iter/init are tunable solver knobs each cell records for
        # itself and must NOT be in the stamp (a pooled-1-iter winner is
        # a legitimate tuning, not a different workload)
        assert set(WORKLOAD) == {"dataset", "n_rows", "n_replicas",
                                 "l2", "precision"}
        for k in set(WORKLOAD) & set(HEADLINE):
            assert WORKLOAD[k] == HEADLINE[k]
        assert "max_iter" not in WORKLOAD and "init" not in WORKLOAD

    def test_resume_key_defaults_for_pre_pooled_records(self):
        import tune_headline as th
        old = {"impl": "blocked", "chunk": 200, "row_tile": None}
        assert th.cell_key(old) == ("blocked", 200, None, 3, "zeros")


class TestProbeUntil:
    """bench.py's poll-until-deadline probe [VERDICT r3 ask#2] — the
    driver's single invocation must be able to catch a tunnel window
    narrower than the deadline, via injected probe/clock/sleep."""

    def _harness(self, outcomes, attempt_cost=2.0):
        state = {"t": 0.0, "probes": 0, "sleeps": []}

        def probe(timeout_s, retries=0, platform=None):
            state["t"] += attempt_cost
            i = min(state["probes"], len(outcomes) - 1)
            state["probes"] += 1
            return outcomes[i]

        def sleep(s):
            state["sleeps"].append(s)
            state["t"] += s

        return state, probe, sleep, (lambda: state["t"])

    def test_first_attempt_success_returns_immediately(self):
        state, probe, sleep, clock = self._harness([("tpu", "")])
        backend, reason = bench.probe_backend_until(
            1500, 120, 120, _probe=probe, _sleep=sleep, _clock=clock
        )
        assert backend == "tpu" and reason == ""
        assert state["probes"] == 1 and state["sleeps"] == []

    def test_late_window_is_caught(self):
        # tunnel dead for 3 attempts, then alive — a one-shot probe
        # would have failed; the poller catches the window
        state, probe, sleep, clock = self._harness(
            [(None, "down")] * 3 + [("tpu", "")]
        )
        backend, _ = bench.probe_backend_until(
            1500, 120, 120, _probe=probe, _sleep=sleep, _clock=clock
        )
        assert backend == "tpu"
        assert state["probes"] == 4 and len(state["sleeps"]) == 3

    def test_deadline_lapses_with_attempt_count_in_reason(self):
        state, probe, sleep, clock = self._harness(
            [(None, "probe timed out at 120s")], attempt_cost=120.0
        )
        backend, reason = bench.probe_backend_until(
            600, 120, 120, _probe=probe, _sleep=sleep, _clock=clock
        )
        assert backend is None
        # attempts at t=0..120, 240..360, 480..600: the poller stops
        # once the next sleep would cross the deadline
        assert state["probes"] == 3
        assert "3 probe attempt(s)" in reason
        assert "deadline 600s" in reason
        assert "probe timed out" in reason
        assert clock() <= 600 + 120  # bounded overrun

    def test_default_deadline_is_driver_wide(self):
        # the driver runs bench.py with no flags: the polling deadline
        # must be the wide default (not the old two-attempt behavior),
        # while the watcher — which just probed aliveness itself —
        # passes a short one
        src = open(os.path.join(REPO, "bench.py")).read()
        assert '"--probe-deadline", type=float, default=1500.0' in src
        watch = open(
            os.path.join(REPO, "benchmarks", "tpu_watch.sh")
        ).read()
        assert "--probe-deadline 240" in watch


class TestAnalyzeTune:
    def test_stale_and_parity_failing_cells_cannot_win(self, tmp_path):
        """The analyzer's recommendation must apply the same filters as
        bench.py's winner selection: stale workload stamps and cells
        under the parity bar are excluded even when fastest."""
        import shutil
        import subprocess

        bdir = tmp_path / "benchmarks"
        bdir.mkdir()
        for f in ("analyze_tune.py", "headline_data.py"):
            shutil.copy(os.path.join(REPO, "benchmarks", f), bdir / f)
        (tmp_path / "spark_bagging_tpu").symlink_to(
            os.path.join(REPO, "spark_bagging_tpu"))
        (bdir / "tune_headline.json").write_text(json.dumps([
            _cell(chunk=200, fps=100.0, acc=0.77),
            _cell(chunk=300, fps=900.0, acc=0.77,
                  workload=dict(WORKLOAD, dataset="stale")),
            _cell(chunk=400, fps=800.0, acc=0.40),  # under the bar
        ]))
        key = __import__("headline_data").baseline_cache_key()
        (tmp_path / "bench_baseline_cache.json").write_text(json.dumps({
            key: {"accuracy": 0.765}
        }))
        proc = subprocess.run(
            [sys.executable, str(bdir / "analyze_tune.py")],
            capture_output=True, text=True, timeout=120, cwd=tmp_path,
        )
        assert proc.returncode == 0, proc.stderr[-400:]
        winner = json.loads(
            proc.stdout[proc.stdout.index("{"):])["winner"]
        assert (winner["chunk"], winner["fps"]) == (200, 100.0)


class TestDeviceLock:
    @pytest.mark.slow  # ~11s: spawns a real holder process + lock deadline
    def test_serializes_across_processes(self, tmp_path, monkeypatch):
        """Two benchmark parents must not drive the chip concurrently:
        acquire fails within its deadline while another process holds
        the lock, succeeds after the holder exits."""
        import subprocess
        import time

        import isolation
        monkeypatch.setattr(isolation, "LOCK_PATH",
                            str(tmp_path / "tpu_lock"))
        holder = subprocess.Popen(
            [sys.executable, "-u", "-c", f"""
import sys, time, fcntl
f = open({str(tmp_path / "tpu_lock")!r}, "w")
fcntl.flock(f, fcntl.LOCK_EX)
print("HELD", flush=True)
time.sleep(6)
f.close()
"""],
            stdout=subprocess.PIPE, text=True)
        try:
            assert holder.stdout.readline().strip() == "HELD"
            assert isolation._acquire_device_lock(1.0) is None
            got = isolation._acquire_device_lock(60.0)
            assert got is not None
            got.close()
        finally:
            holder.wait(timeout=30)


def _row(config, backend="tpu", version=None, **extra):
    r = {"config": config, "name": f"cfg{config}", "metric": "accuracy",
         "value": 0.9, "fits_per_sec": 1.0, "wall_seconds": 1.0,
         "backend": backend}
    if version is not None:
        r["datasets_version"] = version
    r.update(extra)
    return r


class TestConfigResumePersist:
    """TPU rows are immutable [VERDICT r3 ask#4]: round 3's CPU
    rehearsal overwrote the round-2 TPU capture in place; a non-TPU run
    must now refuse to touch a file holding TPU rows, and the merge
    keeps every unreplaced TPU row across incremental rewrites."""

    def test_cpu_run_refuses_to_overwrite_tpu_rows(self, tmp_path):
        import subprocess

        out = tmp_path / "results.json"
        original = json.dumps({
            "scale": "smoke",
            "results": [_row(6), _row(7)],
            "failures": [],
        })
        out.write_text(original)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "run_configs.py"),
             "--configs", "1", "--platform", "cpu", "--resume",
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=500, cwd=REPO,
        )
        assert proc.returncode == 1
        assert "refusing" in proc.stdout
        # error, not silent skip — and the file is untouched
        assert out.read_text() == original

    @pytest.mark.slow  # [PR 14 pyramid] ~4.2s bench resume-policy drill; artifact-guard contracts stay tier-1 in the faster siblings
    def test_cpu_rows_never_resume(self, tmp_path):
        """A rehearsal file's own CPU rows re-measure on --resume —
        only TPU rows are capture progress worth carrying."""
        import subprocess

        out = tmp_path / "results_cpu.json"
        out.write_text(json.dumps({
            "scale": "smoke",
            "results": [_row(1, backend="cpu", version="v0-stale")],
            "failures": [],
        }))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "run_configs.py"),
             "--configs", "1", "--platform", "cpu", "--resume",
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=500, cwd=REPO,
        )
        assert '"resumed": true' not in proc.stderr.lower()
        data = json.loads(out.read_text())
        row1 = next(r for r in data["results"] if r["config"] == 1)
        assert row1["backend"] == "cpu"
        assert row1["wall_seconds"] != 1.0, "placeholder row resumed"

    def test_merge_keeps_unreplaced_tpu_rows(self):
        """Cross-window accumulation + the off-TPU-fallback backstop:
        stale-generator TPU rows outside the resume set survive every
        rewrite until a TPU run actually replaces them."""
        import run_configs

        prior_tpu = {6: _row(6, version="v0-stale"), 7: _row(7)}
        merged = run_configs.merge_rows(
            [_row(1, backend="tpu", version="v-now")], prior_tpu
        )
        assert {r["config"] for r in merged} == {1, 6, 7}
        # a re-measured config replaces its prior row exactly once
        merged = run_configs.merge_rows(
            [_row(6, backend="tpu", version="v-now")], prior_tpu
        )
        rows6 = [r for r in merged if r["config"] == 6]
        assert len(rows6) == 1 and rows6[0]["datasets_version"] == "v-now"

    def test_cpu_run_refuses_canonical_name_even_when_missing(self, tmp_path):
        """The watcher passes --json-out results_full.json explicitly;
        a CPU-fallback run must refuse the canonical NAME outright —
        a first capture must not be seeded with cpu rows."""
        import subprocess

        out = tmp_path / "results_smoke.json"  # does not exist
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "run_configs.py"),
             "--configs", "1", "--platform", "cpu",
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=500, cwd=REPO,
        )
        assert proc.returncode == 1
        assert "canonical" in proc.stdout
        assert not out.exists()

    def test_cpu_run_refuses_corrupt_artifact(self, tmp_path):
        """An unreadable artifact may be a damaged TPU capture — a
        rehearsal refuses rather than paving over it."""
        import subprocess

        out = tmp_path / "results.json"
        out.write_text('{"scale": "smoke", "results": [{"backe')
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "run_configs.py"),
             "--configs", "1", "--platform", "cpu",
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=500, cwd=REPO,
        )
        assert proc.returncode == 1
        assert "cannot be parsed" in proc.stdout
        assert out.read_text().startswith('{"scale"')  # untouched

    def test_non_tpu_backend_redirects_default_out(self):
        """Without --json-out, a non-TPU run must land in
        results_<scale>_<backend>.json, never the canonical file."""
        src = open(
            os.path.join(REPO, "benchmarks", "run_configs.py")
        ).read()
        assert 'f"results_{args.scale}_{backend}.json"' in src

    def test_canonical_smoke_file_holds_only_tpu_rows(self):
        """The canonical smoke artifact's standing invariant: every row
        is a TPU capture (restored round-2 rows now; re-measured
        current-generator rows once the next window lands)."""
        data = json.load(open(
            os.path.join(REPO, "benchmarks", "results_smoke.json")
        ))
        rows = data["results"]
        assert len(rows) >= 5
        assert all(r["backend"] == "tpu" for r in rows)

    @pytest.mark.slow  # ~4.3s [PR 12 budget offset]: subprocess bench-CLI rewrite drill; artifact-carrying behavior is cold-path tooling, and the config/resume contracts stay tier-1 via the in-process persist tests
    def test_rewrite_carries_unknown_top_level_keys(self, tmp_path):
        """A run over an artifact file must not strip its provenance
        note (or any future top-level metadata) when rewriting."""
        import subprocess

        out = tmp_path / "results_cpu.json"
        out.write_text(json.dumps({
            "scale": "smoke",
            "provenance": "restored from commit e3a1ca6",
            "results": [_row(2, backend="cpu")],
            "failures": [],
        }))
        subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "run_configs.py"),
             "--configs", "1", "--platform", "cpu",
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=500, cwd=REPO,
        )
        data = json.loads(out.read_text())
        assert data.get("provenance") == "restored from commit e3a1ca6"


class TestCellChild:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.8s bench child-process error drill
    def test_bad_impl_reports_error_not_crash(self):
        import subprocess
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "tune_headline.py"),
             "--cell", json.dumps(["bogus", 10, None, 1, "zeros"])],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("CELL_RESULT ")]
        assert len(lines) == 1
        cell = json.loads(lines[0][len("CELL_RESULT "):])
        assert cell["error"].startswith("ValueError")
        assert cell["fps"] is None


class TestPallasFallbackRehearsal:
    """The first real Mosaic compile of ops/gram.py is an untried event
    [VERDICT r4 weak#3/ask#6]: rehearse it failing. A compile failure
    on the promoted pallas cell must be recorded as that cell's error
    and must NOT stop the sweep — the packed/blocked cells still
    measure in the SAME invocation, so the window is not lost."""

    def _run_sweep(self, tmp_path, monkeypatch, fail_impls=("pallas",)):
        import isolation

        attempted = []

        def fake_run_isolated_child(cmd, timeout_s, prefix):
            spec = tuple(json.loads(cmd[cmd.index("--cell") + 1]))
            attempted.append(spec)
            impl, chunk, row_tile, max_iter, init = spec
            if impl in fail_impls:
                # the exact failure shape a Mosaic lowering error
                # produces through the isolation protocol: child exits
                # nonzero with the error on stderr, no CELL_RESULT line
                return None, (
                    "child rc=1, no result: jaxlib.mosaic.MosaicError: "
                    "INTERNAL: Mosaic failed to compile TPU kernel: "
                    "unsupported vector layout"
                )
            return dict(_cell(impl=impl, chunk=chunk, row_tile=row_tile,
                              max_iter=max_iter, init=init)), None

        monkeypatch.setattr(
            isolation, "run_isolated_child", fake_run_isolated_child
        )
        monkeypatch.setattr(
            tune_headline, "OUT", str(tmp_path / "tune_headline.json")
        )
        monkeypatch.setattr(sys, "argv", ["tune_headline.py"])
        tune_headline.main()
        return attempted, json.loads(
            (tmp_path / "tune_headline.json").read_text()
        )

    def test_mosaic_failure_is_recorded_and_sweep_proceeds(
        self, tmp_path, monkeypatch
    ):
        attempted, cells = self._run_sweep(tmp_path, monkeypatch)
        # the de-risk promotion put a pallas cell first, while the
        # window still has time to fall back
        assert attempted[0][0] == "pallas"
        # EVERY grid cell was still attempted after the Mosaic failure
        assert set(attempted) == set(tune_headline.GRID)
        assert len(cells) == len(tune_headline.GRID)
        by_key = {tune_headline.cell_key(c): c for c in cells}
        for spec in tune_headline.GRID:
            c = by_key[spec]
            if spec[0] == "pallas":
                assert c["fps"] is None
                assert "Mosaic" in c["error"]
            else:
                assert c["fps"], f"non-pallas cell {spec} must measure"

    def test_next_invocation_orders_failed_pallas_last(
        self, tmp_path, monkeypatch
    ):
        # after the failure record lands, a RE-invocation must measure
        # the healthy impls before retrying the errored pallas cells —
        # the documented post-failure cell order (tune_headline
        # docstring)
        self._run_sweep(tmp_path, monkeypatch)
        prior_err = {
            tune_headline.cell_key(c)
            for c in json.loads(
                (tmp_path / "tune_headline.json").read_text()
            )
            if c.get("error")
        }
        order = tune_headline.order_cells(
            tune_headline.GRID, prior_err
        )
        n_err = len(prior_err)
        assert all(s[0] == "pallas" for s in order[-n_err:]), (
            "errored pallas cells must retry LAST"
        )
        assert all(s not in prior_err for s in order[:-n_err])


class TestStreamBudget:
    """Config-8 full must size itself to its stage cap from one probed
    chunk instead of burning a TPU window on a stream the 1-core host
    can't feed [VERDICT r4 ask#3]; benchmarks/BUDGETS.md records the
    measured rates the caps were derived from."""

    def test_fits_budget_unchanged(self):
        import run_configs

        # 4 s/chunk end-to-end, 200 chunks -> ~1280 s, budget 1920 s
        rows, pf = run_configs.budget_stream_rows(
            1920.0, 3.7, 0.3, 40_000_000, 200_000, floor_rows=5_000_000
        )
        assert rows == 40_000_000
        assert "rows_shrunk_from" not in pf
        assert pf["projected_stream_seconds"] > 0

    def test_shrinks_to_budget(self):
        import run_configs

        # slow tunnel: 20 s/chunk -> 200 chunks can't fit 1920 s
        rows, pf = run_configs.budget_stream_rows(
            1920.0, 3.7, 16.3, 40_000_000, 200_000, floor_rows=5_000_000
        )
        assert pf["rows_shrunk_from"] == 40_000_000
        assert rows < 40_000_000
        assert rows % 200_000 == 0
        # shrunk stream must still project inside the budget
        per_chunk = (3.7 + 16.3) * 1.3
        assert per_chunk * (rows // 200_000) + 240.0 <= 1920.0

    def test_floor_wins_over_budget(self):
        import run_configs

        # pathological feed rate: floor (out-of-core vs HBM) holds even
        # though it overshoots the budget — the stage timeout decides
        rows, pf = run_configs.budget_stream_rows(
            600.0, 30.0, 30.0, 40_000_000, 200_000, floor_rows=5_000_000
        )
        assert rows == 5_000_000
        assert pf["rows_shrunk_from"] == 40_000_000
