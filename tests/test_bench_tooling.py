"""The headline bench's self-tuning machinery (bench.py +
benchmarks/tune_headline.py) — pure-host logic, no device needed.

These scripts run unattended inside the TPU-window watcher, so their
resume/ordering/gating rules are load-bearing: a regression here wastes
a live TPU window or tunes the headline from incomparable numbers.
"""
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (REPO, os.path.join(REPO, "benchmarks")):
    if p not in sys.path:
        sys.path.insert(0, p)

import bench  # noqa: E402
import tune_headline  # noqa: E402
from headline_data import HEADLINE, WORKLOAD  # noqa: E402


def _cell(impl="blocked", chunk=200, row_tile=None, fps=100.0, acc=0.77,
          workload=WORKLOAD, max_iter=3, init="zeros", **extra):
    c = {"impl": impl, "chunk": chunk, "row_tile": row_tile,
         "max_iter": max_iter, "init": init, "fps": fps,
         "acc": acc, "workload": workload}
    c.update(extra)
    return c


def _write_sweep(tmp_path, monkeypatch, cells):
    bdir = tmp_path / "benchmarks"
    bdir.mkdir(exist_ok=True)
    (bdir / "tune_headline.json").write_text(json.dumps(cells))
    monkeypatch.setattr(bench, "REPO", str(tmp_path))


class TestLoadSweepWinner:
    def test_picks_fastest_passing_cell(self, tmp_path, monkeypatch):
        _write_sweep(tmp_path, monkeypatch, [
            _cell(chunk=100, fps=80.0),
            _cell(chunk=200, fps=120.0),
            _cell(chunk=300, fps=150.0, acc=0.50),  # fails the acc bar
        ])
        w = bench.load_sweep_winner(0.76, WORKLOAD)
        assert (w["chunk"], w["fps"]) == (200, 120.0)

    def test_workload_mismatch_cannot_win(self, tmp_path, monkeypatch):
        stale = dict(WORKLOAD, dataset="covtype_synth_v2")
        _write_sweep(tmp_path, monkeypatch, [
            _cell(fps=500.0, workload=stale),
            _cell(fps=90.0),
        ])
        assert bench.load_sweep_winner(0.76, WORKLOAD)["fps"] == 90.0

    def test_unstamped_cells_cannot_win(self, tmp_path, monkeypatch):
        cells = [_cell(fps=500.0)]
        del cells[0]["workload"]
        _write_sweep(tmp_path, monkeypatch, cells)
        assert bench.load_sweep_winner(0.76, WORKLOAD) is None

    def test_error_cells_and_missing_file(self, tmp_path, monkeypatch):
        _write_sweep(tmp_path, monkeypatch, [
            _cell(fps=None, acc=None, error="boom"),
        ])
        assert bench.load_sweep_winner(0.76, WORKLOAD) is None
        monkeypatch.setattr(bench, "REPO", str(tmp_path / "nope"))
        assert bench.load_sweep_winner(0.76, WORKLOAD) is None


class TestSweepOrdering:
    def test_errored_cells_sort_after_unattempted(self):
        errored = {tune_headline.GRID[0], tune_headline.GRID[2]}
        order = tune_headline.order_cells(tune_headline.GRID, errored)
        assert set(order[-2:]) == errored
        assert order[0] == tune_headline.GRID[1]
        # stable within each group: grid order is preserved
        rest = [k for k in tune_headline.GRID if k not in errored]
        assert order[:-2] == rest

    def test_watcher_done_check_derives_from_grid(self):
        # tune_done must stay coupled to the actual grid and workload
        # stamp — a hardcoded count or stamp-blind count would let a
        # stale or shrunken sweep settle the stage forever
        src = open(os.path.join(REPO, "benchmarks", "tpu_watch.sh")).read()
        assert "from tune_headline import GRID" in src
        assert "from headline_data import WORKLOAD" in src
        assert 'c.get("workload") == WORKLOAD' in src

    def test_workload_stamp_carries_problem_constants_only(self):
        # WORKLOAD = the problem (dataset + size + l2 + precision);
        # max_iter/init are tunable solver knobs each cell records for
        # itself and must NOT be in the stamp (a pooled-1-iter winner is
        # a legitimate tuning, not a different workload)
        assert set(WORKLOAD) == {"dataset", "n_rows", "n_replicas",
                                 "l2", "precision"}
        for k in set(WORKLOAD) & set(HEADLINE):
            assert WORKLOAD[k] == HEADLINE[k]
        assert "max_iter" not in WORKLOAD and "init" not in WORKLOAD

    def test_resume_key_defaults_for_pre_pooled_records(self):
        import tune_headline as th
        old = {"impl": "blocked", "chunk": 200, "row_tile": None}
        assert th.cell_key(old) == ("blocked", 200, None, 3, "zeros")


class TestAnalyzeTune:
    def test_stale_and_parity_failing_cells_cannot_win(self, tmp_path):
        """The analyzer's recommendation must apply the same filters as
        bench.py's winner selection: stale workload stamps and cells
        under the parity bar are excluded even when fastest."""
        import shutil
        import subprocess

        bdir = tmp_path / "benchmarks"
        bdir.mkdir()
        for f in ("analyze_tune.py", "headline_data.py"):
            shutil.copy(os.path.join(REPO, "benchmarks", f), bdir / f)
        (tmp_path / "spark_bagging_tpu").symlink_to(
            os.path.join(REPO, "spark_bagging_tpu"))
        (bdir / "tune_headline.json").write_text(json.dumps([
            _cell(chunk=200, fps=100.0, acc=0.77),
            _cell(chunk=300, fps=900.0, acc=0.77,
                  workload=dict(WORKLOAD, dataset="stale")),
            _cell(chunk=400, fps=800.0, acc=0.40),  # under the bar
        ]))
        key = __import__("headline_data").baseline_cache_key()
        (tmp_path / "bench_baseline_cache.json").write_text(json.dumps({
            key: {"accuracy": 0.765}
        }))
        proc = subprocess.run(
            [sys.executable, str(bdir / "analyze_tune.py")],
            capture_output=True, text=True, timeout=120, cwd=tmp_path,
        )
        assert proc.returncode == 0, proc.stderr[-400:]
        winner = json.loads(
            proc.stdout[proc.stdout.index("{"):])["winner"]
        assert (winner["chunk"], winner["fps"]) == (200, 100.0)


class TestDeviceLock:
    def test_serializes_across_processes(self, tmp_path, monkeypatch):
        """Two benchmark parents must not drive the chip concurrently:
        acquire fails within its deadline while another process holds
        the lock, succeeds after the holder exits."""
        import subprocess
        import time

        import isolation
        monkeypatch.setattr(isolation, "LOCK_PATH",
                            str(tmp_path / "tpu_lock"))
        holder = subprocess.Popen(
            [sys.executable, "-u", "-c", f"""
import sys, time, fcntl
f = open({str(tmp_path / "tpu_lock")!r}, "w")
fcntl.flock(f, fcntl.LOCK_EX)
print("HELD", flush=True)
time.sleep(6)
f.close()
"""],
            stdout=subprocess.PIPE, text=True)
        try:
            assert holder.stdout.readline().strip() == "HELD"
            assert isolation._acquire_device_lock(1.0) is None
            got = isolation._acquire_device_lock(60.0)
            assert got is not None
            got.close()
        finally:
            holder.wait(timeout=30)


class TestConfigResumePersist:
    def test_prior_rows_survive_a_partial_run(self, tmp_path):
        """Cross-window accumulation: prior TPU rows for configs the
        current run has not (re)measured must survive every incremental
        rewrite — a kill mid-suite must not lose captured progress."""
        import subprocess

        from spark_bagging_tpu.utils.datasets import SYNTHETICS_VERSION

        out = tmp_path / "results.json"
        prior = {
            "scale": "smoke",
            "results": [
                {"config": c, "name": f"cfg{c}", "metric": "accuracy",
                 "value": 0.9, "fits_per_sec": 1.0, "wall_seconds": 1.0,
                 "backend": "tpu",
                 "datasets_version": SYNTHETICS_VERSION}
                for c in (6, 7)
            ],
            "failures": [],
        }
        out.write_text(json.dumps(prior))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "run_configs.py"),
             "--configs", "1", "--platform", "cpu", "--resume",
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=500, cwd=REPO,
        )
        data = json.loads(out.read_text())
        configs = {r["config"] for r in data["results"]}
        assert {1, 6, 7} <= configs, (configs, proc.stderr[-500:])
        # the cpu row must NOT poison future resumes
        row1 = next(r for r in data["results"] if r["config"] == 1)
        assert row1["backend"] == "cpu"

    def test_stale_generator_rows_do_not_resume(self, tmp_path):
        import subprocess

        out = tmp_path / "results.json"
        out.write_text(json.dumps({
            "scale": "smoke",
            "results": [{"config": 1, "name": "cfg1",
                         "metric": "accuracy", "value": 0.9,
                         "fits_per_sec": 1.0, "wall_seconds": 1.0,
                         "backend": "tpu",
                         "datasets_version": "v0-stale"}],
            "failures": [],
        }))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "run_configs.py"),
             "--configs", "1", "--platform", "cpu", "--resume",
             "--json-out", str(out)],
            capture_output=True, text=True, timeout=500, cwd=REPO,
        )
        # the stale row was re-measured (backend flips to cpu here),
        # not resumed
        assert '"resumed": true' not in proc.stderr.lower()
        data = json.loads(out.read_text())
        row1 = next(r for r in data["results"] if r["config"] == 1)
        assert row1["backend"] == "cpu"


class TestCellChild:
    def test_bad_impl_reports_error_not_crash(self):
        import subprocess
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "tune_headline.py"),
             "--cell", json.dumps(["bogus", 10, None, 1, "zeros"])],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("CELL_RESULT ")]
        assert len(lines) == 1
        cell = json.loads(lines[0][len("CELL_RESULT "):])
        assert cell["error"].startswith("ValueError")
        assert cell["fps"] is None
