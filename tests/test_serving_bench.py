"""Tier-1 smoke of the serving benchmark [ISSUE 2 acceptance]: the CPU
run must show micro-batched serving >= 3x the throughput of naive
per-request predict at concurrency 16, with ZERO post-warmup recompiles
(the amortization story the serving subsystem exists for), and must
write well-formed BENCH_serving.json + telemetry.jsonl artifacts."""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def test_serving_latency_smoke(tmp_path):
    out = str(tmp_path / "BENCH_serving.json")
    tel = str(tmp_path / "telemetry.jsonl")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "benchmarks", "serving_latency.py"),
            "--smoke", "--concurrency", "16",
            "--out", out, "--telemetry", tel,
        ],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"benchmark failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    result = json.loads(open(out).read())
    assert result["backend"] == "cpu"
    assert result["compiles_post_warmup"] == 0, (
        "steady-state bucketed traffic must not recompile"
    )
    (level,) = result["levels"]
    assert level["concurrency"] == 16
    assert level["speedup_rps"] >= 3.0, (
        f"micro-batched serving should be >= 3x naive at concurrency "
        f"16, got {level['speedup_rps']}x "
        f"(naive {level['naive']}, served {level['served']})"
    )
    # the telemetry artifact is a parseable JSONL run with the serving
    # series present in its final metrics snapshot
    from spark_bagging_tpu.telemetry import (
        last_metrics_snapshot, read_events,
    )

    events = read_events(tel)
    snap = last_metrics_snapshot(events)
    names = {m["name"] for m in snap}
    assert "sbt_serving_requests_total" in names
    assert "sbt_serving_latency_seconds" in names
