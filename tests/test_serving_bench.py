"""Tier-1 smoke of the serving benchmark [ISSUE 2 + ISSUE 7
acceptance]: the CPU run must show micro-batched serving >= 3x the
throughput of naive per-request predict at concurrency 16 AND — the
adaptive-direct-dispatch gate — served >= naive at concurrency 1, on
the SAME run, with ZERO post-warmup recompiles, and must write
well-formed BENCH_serving.json + telemetry.jsonl artifacts. The
measured window discards one warmup run per (path, level), which is
what makes the concurrency-1 gate stable on loaded hosts."""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def test_serving_latency_smoke(tmp_path):
    out = str(tmp_path / "BENCH_serving.json")
    tel = str(tmp_path / "telemetry.jsonl")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "benchmarks", "serving_latency.py"),
            "--smoke", "--concurrency", "1,16",
            "--out", out, "--telemetry", tel,
        ],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"benchmark failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    result = json.loads(open(out).read())
    assert result["backend"] == "cpu"
    assert result["compiles_post_warmup"] == 0, (
        "steady-state bucketed traffic must not recompile"
    )
    assert result["warmup_runs_discarded"] == 1
    c1, c16 = result["levels"]
    assert c1["concurrency"] == 1 and c16["concurrency"] == 16
    # the concurrency-1 gate: adaptive direct dispatch must make the
    # serving tier at least match naive synchronous dispatch when
    # there is nothing to coalesce (ROADMAP item 3)
    assert result["served_vs_naive_concurrency1"] >= 1.0, (
        f"served must not lose to naive at concurrency 1, got "
        f"{result['served_vs_naive_concurrency1']}x "
        f"(naive {c1['naive']}, served {c1['served']})"
    )
    # the traffic actually took the direct path (the ratio could
    # otherwise pass on host noise alone)
    dispatch = c1["served"]["dispatch"]
    assert dispatch["direct"] > dispatch["coalesced"], dispatch
    assert c16["speedup_rps"] >= 3.0, (
        f"micro-batched serving should be >= 3x naive at concurrency "
        f"16, got {c16['speedup_rps']}x "
        f"(naive {c16['naive']}, served {c16['served']})"
    )
    # ... and the concurrency-16 traffic kept coalescing (direct
    # dispatch must not have leaked into contended traffic)
    dispatch16 = c16["served"]["dispatch"]
    assert dispatch16["coalesced"] > dispatch16["direct"], dispatch16
    # the telemetry artifact is a parseable JSONL run with the serving
    # series present in its final metrics snapshot
    from spark_bagging_tpu.telemetry import (
        last_metrics_snapshot, read_events,
    )

    events = read_events(tel)
    snap = last_metrics_snapshot(events)
    names = {m["name"] for m in snap}
    assert "sbt_serving_requests_total" in names
    assert "sbt_serving_latency_seconds" in names
