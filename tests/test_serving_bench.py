"""Tier-1 smoke of the serving benchmark [ISSUE 2 + ISSUE 7
acceptance]: the CPU run must show micro-batched serving >= 3x the
throughput of naive per-request predict at concurrency 16 AND — the
adaptive-direct-dispatch gate — served >= naive at concurrency 1, on
the SAME run, with ZERO post-warmup recompiles, and must write
well-formed BENCH_serving.json + telemetry.jsonl artifacts. The
measured window discards one warmup run per (path, level), which is
what makes the concurrency-1 gate stable on loaded hosts."""

import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.mark.slow  # [PR 17 budget offset] ~9.1s bench smoke; serving-path contracts stay tier-1 via test_serving_fastpath + the scenario conformance smoke; bench numbers trend via the history store
def test_serving_latency_smoke(tmp_path):
    out = str(tmp_path / "BENCH_serving.json")
    tel = str(tmp_path / "telemetry.jsonl")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "benchmarks", "serving_latency.py"),
            "--smoke", "--concurrency", "1,16",
            "--out", out, "--telemetry", tel,
        ],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"benchmark failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    result = json.loads(open(out).read())
    assert result["backend"] == "cpu"
    assert result["compiles_post_warmup"] == 0, (
        "steady-state bucketed traffic must not recompile"
    )
    assert result["warmup_runs_discarded"] == 1
    c1, c16 = result["levels"]
    assert c1["concurrency"] == 1 and c16["concurrency"] == 16
    # the concurrency-1 gate: adaptive direct dispatch must make the
    # serving tier at least match naive synchronous dispatch when
    # there is nothing to coalesce (ROADMAP item 3)
    assert result["served_vs_naive_concurrency1"] >= 1.0, (
        f"served must not lose to naive at concurrency 1, got "
        f"{result['served_vs_naive_concurrency1']}x "
        f"(naive {c1['naive']}, served {c1['served']})"
    )
    # the traffic actually took the direct path (the ratio could
    # otherwise pass on host noise alone)
    dispatch = c1["served"]["dispatch"]
    assert dispatch["direct"] > dispatch["coalesced"], dispatch
    assert c16["speedup_rps"] >= 3.0, (
        f"micro-batched serving should be >= 3x naive at concurrency "
        f"16, got {c16['speedup_rps']}x "
        f"(naive {c16['naive']}, served {c16['served']})"
    )
    # ... and the concurrency-16 traffic kept coalescing (direct
    # dispatch must not have leaked into contended traffic)
    dispatch16 = c16["served"]["dispatch"]
    assert dispatch16["coalesced"] > dispatch16["direct"], dispatch16
    # the telemetry artifact is a parseable JSONL run with the serving
    # series present in its final metrics snapshot
    from spark_bagging_tpu.telemetry import (
        last_metrics_snapshot, read_events,
    )

    events = read_events(tel)
    snap = last_metrics_snapshot(events)
    names = {m["name"] for m in snap}
    assert "sbt_serving_requests_total" in names
    assert "sbt_serving_latency_seconds" in names


@pytest.mark.slow  # [PR 17 budget offset] ~3.9s bench smoke; sharded serving stays tier-1 via test_serving_sharded parity tests + the sharded-parity scenario
def test_serving_sharded_bench_smoke(tmp_path):
    """ISSUE 10 acceptance: ``--devices 8`` (forced-host-device CPU)
    serves the oversized bag through the replica-sharded executor with
    BITWISE parity and zero post-warmup compiles — asserted HARD. The
    >= 1.5x throughput band is asserted via the CLI's own gate (exit
    0) on hosts with the cores to express device parallelism; on
    core-starved CI hosts N virtual devices share one physical core
    and the band is unreachable BY CONSTRUCTION — the CLI reports that
    as the distinct exit 3, tolerated here exactly like the PR 7
    replay gate tolerates host-performance bands while holding the
    host-independent invariants."""
    out = str(tmp_path / "BENCH_serving_sharded.json")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "benchmarks", "serving_latency.py"),
            "--smoke", "--devices", "8", "--repeats", "3",
            "--out", out,
        ],
        capture_output=True, text=True, timeout=420,
        env={k: v for k, v in os.environ.items() if k != "XLA_FLAGS"},
    )
    assert proc.returncode in (0, 3), (
        f"sharded bench invariant failure:\n{proc.stdout[-2000:]}\n"
        f"{proc.stderr[-2000:]}"
    )
    result = json.loads(open(out).read())
    assert result["backend"] == "cpu"
    assert result["devices"] == 8
    # host-independent invariants, asserted hard:
    assert result["parity_bitwise"] is True, (
        "sharded output must be bitwise-identical to single-device"
    )
    assert result["compiles_post_warmup"] == 0
    assert result["shard_forwards"] > 0  # the mesh path actually ran
    # the throughput band: only reachable with real core headroom
    if proc.returncode == 3:
        assert (os.cpu_count() or 1) < result["devices"], (
            f"sharded speedup {result['speedup']}x < 1.5x despite "
            f"{os.cpu_count()} host cores for {result['devices']} "
            "devices — a real regression, not core starvation"
        )
    else:
        assert result["speedup"] >= 1.5
