"""Serving subsystem tests [ISSUE 2]: bucket math, padding hygiene,
micro-batch coalescing, backpressure, hot-swap atomicity, and the
zero-recompile steady-state contract.

The load-bearing property throughout: a served result must be
BITWISE-equal to the batch API's answer for the same rows — padding
rows, bucket choice, and batch-mates must be invisible. Bagging
aggregation is row-local, and the serving executor jits the exact
closure the batch ``predict_proba``/``predict`` jit uses
(``ensemble.classifier_forward``/``regressor_forward``), so equality
is exact, not approximate.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    GeneralizedLinearRegression,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.serving import (
    EnsembleExecutor,
    MicroBatcher,
    ModelRegistry,
    Overloaded,
    bucket_for,
    bucket_ladder,
    next_pow2,
    pad_to_bucket,
)


def _counter(name: str) -> float:
    return telemetry.registry().counter(name).value


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(256, 12)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.1 * rng.normal(size=256) > 0)
    return X, y.astype(np.int64)


@pytest.fixture(scope="module")
def clf(data):
    X, y = data
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=8, seed=0,
    ).fit(X, y)


@pytest.fixture(scope="module")
def executor(clf):
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=64)
    ex.warmup()
    return ex


# -- bucket math -------------------------------------------------------

def test_next_pow2():
    assert [next_pow2(n) for n in (1, 2, 3, 4, 5, 63, 64, 65)] == [
        1, 2, 4, 4, 8, 64, 64, 128,
    ]
    with pytest.raises(ValueError):
        next_pow2(0)


def test_bucket_for_clamps_to_ladder():
    assert bucket_for(1, 8, 64) == 8
    assert bucket_for(8, 8, 64) == 8
    assert bucket_for(9, 8, 64) == 16
    assert bucket_for(64, 8, 64) == 64
    assert bucket_for(1000, 8, 64) == 64  # oversize: executor slabs it
    with pytest.raises(ValueError):
        bucket_for(0)


def test_bucket_ladder():
    assert bucket_ladder(8, 64) == (8, 16, 32, 64)
    assert bucket_ladder(8, 8) == (8,)
    with pytest.raises(ValueError):
        bucket_ladder(16, 8)


def test_non_pow2_bounds_stay_on_the_ladder():
    """Arbitrary bucket bounds normalize to powers of two, so every
    bucket_for() result is a warmup-ladder rung — otherwise a non-pow2
    min/max would break the zero-recompile-after-warmup contract."""
    ladder = bucket_ladder(10, 3000)
    assert ladder == (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
    for n in (1, 3, 10, 17, 2999, 3000, 9999):
        assert bucket_for(n, 10, 3000) in ladder


def test_pad_to_bucket():
    X = np.ones((3, 2), np.float32)
    Xp = pad_to_bucket(X, 8)
    assert Xp.shape == (8, 2)
    np.testing.assert_array_equal(Xp[:3], X)
    assert (Xp[3:] == 0).all()
    assert pad_to_bucket(X, 3) is X  # exact fit: no copy
    with pytest.raises(ValueError):
        pad_to_bucket(X, 2)


# -- executor correctness ----------------------------------------------

def test_padded_rows_never_leak_classifier(clf, executor, data):
    """Every row count in [1, max_batch] pads to SOME bucket; results
    must be bitwise-identical to the batch predict_proba of exactly
    those rows — padding garbage must never reach a caller."""
    X, _ = data
    for n in (1, 2, 7, 8, 9, 23, 33, 64):
        got = executor.predict_proba(X[:n])
        want = clf.predict_proba(X[:n])
        np.testing.assert_array_equal(got, want)
        assert got.shape == (n, 2)


def test_predict_labels_match(clf, executor, data):
    X, _ = data
    np.testing.assert_array_equal(
        executor.predict(X[:19]), clf.predict(X[:19])
    )


def test_oversize_batch_splits_into_slabs(clf, executor, data):
    """Rows beyond max_batch_rows run as top-bucket slabs — same
    answers, bounded compiled-shape set."""
    X, _ = data
    got = executor.predict_proba(X[:200])  # 200 > max_batch_rows=64
    np.testing.assert_array_equal(got, clf.predict_proba(X[:200]))


def test_single_feature_vector_accepted(clf, executor, data):
    X, _ = data
    got = executor.predict_proba(X[0])  # 1-D: one online request
    np.testing.assert_array_equal(got, clf.predict_proba(X[:1]))


@pytest.mark.slow  # [PR 17 budget offset] ~2.1s parity twin; forward-vs-predict parity stays tier-1 via the classifier parity tests in this file
def test_regressor_forward_matches_predict(data):
    """Regressor serving runs the same device closure as the batch
    predict jit (a non-collapsible learner keeps both on the device
    path) — bitwise equality again."""
    X, _ = data
    rng = np.random.default_rng(3)
    yr = np.exp(0.3 * X[:, 0] + 0.1 * rng.normal(size=len(X)))
    reg = BaggingRegressor(
        base_learner=GeneralizedLinearRegression(
            family="poisson", max_iter=4
        ),
        n_estimators=4, seed=0,
    ).fit(X, yr.astype(np.float32))
    ex = EnsembleExecutor(reg, min_bucket_rows=8, max_batch_rows=32)
    for n in (1, 5, 17, 32):
        np.testing.assert_array_equal(
            ex.predict(X[:n]), reg.predict(X[:n])
        )
    with pytest.raises(AttributeError):
        ex.predict_proba(X[:4])


@pytest.mark.slow  # ~7s: fits a forest AND a GBT just to re-prove the
# serving parity the logistic-bag tests already gate every run; the
# model-specific aggregated_forward closures are also jaxpr-audited in
# test_analysis [ISSUE 13 tier-1 budget offset]
def test_forest_and_gbt_models_serve(data):
    """The tentpole covers forest/gbt models too: tree-based ensembles
    go through the same aggregated_forward seam, bitwise-equal."""
    from spark_bagging_tpu import (
        BaggingRegressor, GBTRegressor, RandomForestClassifier,
    )

    X, y = data
    rf = RandomForestClassifier(
        n_estimators=4, max_depth=3, n_bins=8, seed=0
    ).fit(X[:96], y[:96])
    ex = EnsembleExecutor(rf, min_bucket_rows=8, max_batch_rows=32)
    for n in (1, 11, 32):
        np.testing.assert_array_equal(
            ex.predict_proba(X[:n]), rf.predict_proba(X[:n])
        )
    gbt = BaggingRegressor(
        base_learner=GBTRegressor(n_rounds=3, max_depth=2, n_bins=8),
        n_estimators=2, seed=0,
    ).fit(X[:96], X[:96, 0])
    exg = EnsembleExecutor(gbt, min_bucket_rows=8, max_batch_rows=32)
    for n in (1, 11):
        np.testing.assert_array_equal(
            exg.predict(X[:n]), gbt.predict(X[:n])
        )


def test_executor_validates_input(clf, executor):
    with pytest.raises(ValueError, match="must be"):
        executor.forward(np.zeros((4, 5), np.float32))  # wrong width
    with pytest.raises(ValueError, match="no rows"):
        executor.forward(np.zeros((0, clf.n_features_in_), np.float32))


def test_unfitted_and_meshed_models_rejected(data):
    X, y = data
    with pytest.raises(RuntimeError, match="not fitted"):
        EnsembleExecutor(BaggingClassifier(n_estimators=2))
    clf = BaggingClassifier(n_estimators=2, seed=0).fit(X, y)
    clf.mesh = object()  # stand-in: any mesh-bound estimator
    with pytest.raises(ValueError, match="single-device"):
        EnsembleExecutor(clf)


# -- zero-recompile steady state ---------------------------------------

def test_zero_new_compiles_after_warmup(clf, data):
    """THE amortization contract: after warmup over the bucket ladder,
    steady-state traffic of arbitrary row counts records ZERO new
    compiles (sbt_serving_compiles_total is the telemetry witness)."""
    X, _ = data
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=64)
    built = ex.warmup()
    assert built == (8, 16, 32, 64)
    assert ex.compiled_buckets == (8, 16, 32, 64)
    before = _counter("sbt_serving_compiles_total")
    rng = np.random.default_rng(0)
    for _ in range(50):
        n = int(rng.integers(1, 200))
        out = ex.predict_proba(X[:n])
        assert out.shape == (n, 2)
    assert _counter("sbt_serving_compiles_total") == before
    # warmup again is a no-op too
    assert ex.warmup() == ()
    assert _counter("sbt_serving_compiles_total") == before


# -- micro-batcher -----------------------------------------------------

def test_micro_batch_coalesces_waiting_requests(clf, executor, data):
    """Requests submitted within the delay window ride ONE forward:
    far fewer batches than requests, results exact per request."""
    X, _ = data
    before = _counter("sbt_serving_batches_total")
    ref = clf.predict_proba(X[:16])
    # direct dispatch pinned off: this test exercises the coalescing
    # queue, and back-to-back sequential submits from one thread would
    # (correctly) all take the adaptive inline path otherwise
    with MicroBatcher(executor, max_delay_ms=250, idle_flush_ms=250,
                      max_batch_rows=64, max_queue=64,
                      direct_dispatch=False) as b:
        futs = [b.submit(X[i:i + 1]) for i in range(16)]
        results = [f.result(30) for f in futs]
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r, ref[i:i + 1])
    n_batches = _counter("sbt_serving_batches_total") - before
    assert 1 <= n_batches <= 3, f"expected coalescing, got {n_batches}"


def test_concurrent_submitters_all_exact(clf, executor, data):
    X, _ = data
    ref = clf.predict_proba(X)
    with MicroBatcher(executor, max_delay_ms=5, max_batch_rows=64,
                      max_queue=128) as b:
        def one(i):
            return i, b.submit(X[i:i + 1]).result(30)

        with ThreadPoolExecutor(8) as pool:
            for i, r in pool.map(one, range(64)):
                np.testing.assert_array_equal(r, ref[i:i + 1])


def test_predict_mode_scatter(clf, executor, data):
    X, _ = data
    with MicroBatcher(executor, max_delay_ms=5, max_queue=32) as b:
        futs = [b.submit(X[i:i + 1], mode="predict") for i in range(8)]
        got = np.concatenate([f.result(30) for f in futs])
    np.testing.assert_array_equal(got, clf.predict(X[:8]))


class _StallingExecutor:
    """Duck-typed executor whose forward blocks until released — makes
    queue-full behavior deterministic."""

    task = "classification"
    n_features = 12
    classes_ = np.array([0, 1])

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def forward(self, X):
        self.entered.set()
        assert self.release.wait(30)
        return np.zeros((X.shape[0], 2), np.float32)


def test_backpressure_overloaded_is_explicit():
    ex = _StallingExecutor()
    X1 = np.zeros((1, 12), np.float32)
    before = _counter("sbt_serving_overloaded_total")
    # queue-path semantics under test; direct dispatch would run the
    # stalling forward inline on this thread
    b = MicroBatcher(ex, max_delay_ms=0, max_queue=2,
                     direct_dispatch=False)
    try:
        first = b.submit(X1)           # worker takes it, stalls in forward
        assert ex.entered.wait(10)
        b.submit(X1)                   # queue slot 1
        b.submit(X1)                   # queue slot 2
        with pytest.raises(Overloaded):
            b.submit(X1)               # full -> explicit shed, no block
        assert _counter("sbt_serving_overloaded_total") == before + 1
    finally:
        ex.release.set()
        b.close()
    assert first.result(10).shape == (1, 2)


def test_closed_batcher_rejects_and_fails_pending(executor, data):
    X, _ = data
    b = MicroBatcher(executor, max_delay_ms=1)
    b.close()
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(X[:1])


def test_batch_failure_is_per_batch_not_fatal(clf, executor, data):
    """A poison request fails its own batch's futures; the worker keeps
    serving later requests."""
    X, _ = data

    class _Flaky:
        task = "classification"
        n_features = clf.n_features_in_
        classes_ = clf.classes_
        boom = True

        def forward(self, Xb):
            if self.boom:
                self.boom = False
                raise RuntimeError("injected")
            return executor.forward(Xb)

    # worker-path failure isolation under test (direct-path failure
    # delivery has its own test in test_serving_fastpath.py)
    with MicroBatcher(_Flaky(), max_delay_ms=1, max_queue=8,
                      direct_dispatch=False) as b:
        bad = b.submit(X[:2])
        with pytest.raises(RuntimeError, match="injected"):
            bad.result(30)
        good = b.submit(X[:2]).result(30)
        np.testing.assert_array_equal(good, executor.forward(X[:2]))


# -- registry + hot swap -----------------------------------------------

def test_registry_register_swap_versions(clf, data):
    X, y = data
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    ex1 = reg.register("m", clf, warmup=True)
    assert reg.names() == ("m",)
    assert reg.version("m") == 1
    with pytest.raises(ValueError, match="already registered"):
        reg.register("m", clf)

    clf2 = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=8, seed=1,
    ).fit(X, y)
    before = _counter("sbt_serving_compiles_total")
    ex2 = reg.swap("m", clf2)
    assert reg.version("m") == 2
    assert reg.executor("m") is ex2 is not ex1
    # warm swap pre-compiled the live bucket set on the NEW executor
    assert ex2.compiled_buckets == ex1.compiled_buckets
    assert _counter("sbt_serving_compiles_total") > before
    np.testing.assert_array_equal(
        ex2.predict_proba(X[:5]), clf2.predict_proba(X[:5])
    )


def test_swap_contract_violations_rejected(clf, data):
    X, y = data
    reg = ModelRegistry()
    reg.register("m", clf)
    wrong_width = BaggingClassifier(n_estimators=2, seed=0).fit(
        X[:, :5], y
    )
    with pytest.raises(ValueError, match="feature width"):
        reg.swap("m", wrong_width)
    regressor = BaggingRegressor(n_estimators=2, seed=0).fit(
        X, X[:, 0]
    )
    with pytest.raises(ValueError, match="task"):
        reg.swap("m", regressor)
    relabeled = BaggingClassifier(n_estimators=2, seed=0).fit(
        X, np.where(y > 0, "pos", "neg")
    )
    with pytest.raises(ValueError, match="class set"):
        reg.swap("m", relabeled)
    with pytest.raises(KeyError, match="no model"):
        reg.executor("ghost")


def test_swap_with_changed_bounds_warms_new_ladder(clf, data):
    """A swap that changes bucket bounds must pre-compile the OBSERVED
    traffic profile's image in the NEW ladder — otherwise the first
    post-swap request pays a compile stall the docs promise away."""
    X, y = data
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf)
    reg.executor("m").forward(X[:30])  # traffic compiled bucket 32
    clf2 = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=8, seed=3,
    ).fit(X, y)
    new = reg.swap("m", clf2, max_batch_rows=128)
    assert 32 in new.compiled_buckets  # image of the observed bucket
    before = _counter("sbt_serving_compiles_total")
    new.forward(X[:30])  # the same traffic: no post-swap compile
    assert _counter("sbt_serving_compiles_total") == before


def test_rejected_swap_leaves_entry_untouched(clf, data, tmp_path):
    """A swap/load that fails validation must not commit executor
    options (or anything else) to the live entry."""
    X, y = data
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf)
    wrong = BaggingClassifier(n_estimators=2, seed=0).fit(X[:, :5], y)
    p = str(tmp_path / "wrong")
    wrong.save(p)
    with pytest.raises(ValueError, match="feature width"):
        reg.load("m", p, max_batch_rows=4096)
    assert reg.version("m") == 1
    assert reg.executor("m").max_batch_rows == 32
    clf2 = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=8, seed=4,
    ).fit(X, y)
    assert reg.swap("m", clf2).max_batch_rows == 32  # opts unpolluted


def test_hot_swap_atomic_mid_traffic(clf, data):
    """Swaps land mid-traffic without dropping or corrupting a single
    request: every result is exactly model A's or model B's answer —
    never an error, never a mixture."""
    X, y = data
    clf_b = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=8, seed=99,
    ).fit(X, y)
    ref_a = clf.predict_proba(X)
    ref_b = clf_b.predict_proba(X)
    assert not np.array_equal(ref_a, ref_b)  # swap must be observable

    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
    reg.register("m", clf, warmup=True)
    stop = threading.Event()
    errors: list = []
    checked = [0]

    def client(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            i = int(rng.integers(0, len(X)))
            try:
                r = b.submit(X[i:i + 1]).result(30)
            except Exception as e:  # noqa: BLE001 — collected for assert
                errors.append(e)
                return
            if not (np.array_equal(r, ref_a[i:i + 1])
                    or np.array_equal(r, ref_b[i:i + 1])):
                errors.append(AssertionError(f"row {i}: mixed result"))
                return
            checked[0] += 1

    with reg.batcher("m", max_delay_ms=1, max_queue=256) as b:
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        # a FIXED number of swaps, not a wall-clock window: a warm
        # swap's pre-compiles take arbitrarily long on a loaded CI
        # host, and the property under test is per-swap, not per-second
        model = [clf_b, clf]
        n_swaps = 4
        for k in range(n_swaps):
            if errors:
                break
            reg.swap("m", model[k % 2])
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(60)
    assert not errors, errors[:3]
    assert checked[0] > 20, "traffic should have flowed throughout"
    assert reg.version("m") == 1 + n_swaps


def test_registry_load_from_checkpoint(clf, data, tmp_path):
    """The retrain hand-off: load() registers from a checkpoint dir,
    then swaps on subsequent loads of the same name."""
    X, y = data
    p1 = str(tmp_path / "v1")
    clf.save(p1)
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.load("m", p1)
    assert reg.version("m") == 1
    np.testing.assert_allclose(
        reg.executor("m").predict_proba(X[:5]),
        clf.predict_proba(X[:5]), rtol=1e-6,
    )
    clf2 = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=8, seed=5,
    ).fit(X, y)
    p2 = str(tmp_path / "v2")
    clf2.save(p2)
    reg.load("m", p2)
    assert reg.version("m") == 2
    np.testing.assert_allclose(
        reg.executor("m").predict_proba(X[:5]),
        clf2.predict_proba(X[:5]), rtol=1e-6,
    )
