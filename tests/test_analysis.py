"""Static-analysis subsystem tests [ISSUE 4]: per-rule good/bad fixture
pairs, suppression handling, CLI exit codes, the jaxpr audit over the
model zoo + serving path, the lock-order detector, and — the
self-hosting gate — a clean lint of the repo's own tree, enforced here
so tier-1 keeps it clean.

Fixture convention: every rule gets a known-BAD snippet it must flag
and a known-GOOD twin it must stay silent on; a rule without that pair
is not trusted. The good twin is always the sanctioned fix for the bad
pattern (split the key, hoist the jit, rebind the donated carry, take
the lock), so the fixtures double as documentation.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

from spark_bagging_tpu.analysis import (
    AuditError,
    audit_estimator,
    audit_executor,
    audit_fn,
    lint_paths,
    lint_source,
    load_config,
    locks,
)
from spark_bagging_tpu.analysis.__main__ import main as lint_main
from spark_bagging_tpu.analysis.lint import RULES, _load_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def hits(src: str, rule: str) -> list:
    """Findings of ONE rule for a source snippet."""
    return [f for f in lint_source(src, enabled={rule})]


# -- rule fixtures: bad must fire, good twin must not ------------------

BAD_GOOD = {
    "host-sync-in-jit": (
        """
import jax
@jax.jit
def step(x):
    return float(x.sum())
""",
        """
import jax
@jax.jit
def step(x):
    return x.sum()

def outside(x):
    return float(step(x))
""",
    ),
    "host-sync-in-span": (
        """
import numpy as np
from spark_bagging_tpu import telemetry

def serve(compiled, X):
    with telemetry.span("forward"):
        out = compiled(X)
        host = np.asarray(out)
    return host
""",
        """
import numpy as np
from spark_bagging_tpu import telemetry

def serve(compiled, X):
    with telemetry.span("forward"):
        out = compiled(X)
    return np.asarray(out)
""",
    ),
    "jit-in-loop": (
        """
import jax

def fit_all(fns, x):
    outs = []
    for fn in fns:
        outs.append(jax.jit(fn)(x))
    return outs
""",
        """
import jax

def fit_all(fns, x):
    jitted = [jax.jit(fn) for fn in fns]
    outs = []
    for fn in jitted:
        outs.append(fn(x))
    return outs
""",
    ),
    "static-argnums-array": (
        """
import jax

def loss(params, n):
    return params.sum() + n

f = jax.jit(loss, static_argnums=(0,))
""",
        """
import jax

def loss(params, n):
    return params.sum() + n

f = jax.jit(loss, static_argnums=(1,))
""",
    ),
    "loop-constant-capture": (
        """
import jax

def grow(levels, h):
    for level in levels:
        @jax.jit
        def select(hist):
            return hist[level]
        h = select(h)
    return h
""",
        """
import jax

def grow(levels, h):
    for level in levels:
        @jax.jit
        def select(hist, _level=level):
            return hist[_level]
        h = select(h)
    return h
""",
    ),
    "tracer-escape": (
        """
import jax

class Model:
    def fit(self, x):
        @jax.jit
        def step(x):
            self.last = x.sum()
            return x
        return step(x)
""",
        """
import jax

class Model:
    def fit(self, x):
        @jax.jit
        def step(x):
            return x, x.sum()
        x, last = step(x)
        self.last = last
        return x
""",
    ),
    "donated-arg-reuse": (
        """
import jax

def fit(params, x, step_fn):
    step = jax.jit(step_fn, donate_argnums=(0,))
    new = step(params, x)
    return new, params.mean()
""",
        """
import jax

def fit(params, x, step_fn):
    step = jax.jit(step_fn, donate_argnums=(0,))
    params = step(params, x)
    return params, params.mean()
""",
    ),
    "prng-key-reuse": (
        """
import jax

def init(key):
    w = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))
    return w, b
""",
        """
import jax

def init(key):
    kw, kb = jax.random.split(key)
    w = jax.random.normal(kw, (4,))
    b = jax.random.uniform(kb, (4,))
    return w, b
""",
    ),
    "prng-nondeterministic-seed": (
        """
import time
import jax

def make_key():
    return jax.random.PRNGKey(int(time.time()))
""",
        """
import jax

def make_key(seed: int):
    return jax.random.PRNGKey(seed)
""",
    ),
    "hot-path-alloc": (
        """
import os
import logging

log = logging.getLogger("serving")

# sbt-lint: hot-path
def submit(req):
    token = os.urandom(8).hex()
    attrs = {k: str(v) for k, v in req.items()}
    log.debug("request %s %s", token, attrs)
    return token
""",
        """
import itertools
import os

_ids = itertools.count()

# sbt-lint: hot-path
def submit(req):
    return next(_ids), req

def cold_path(req):
    # un-marked functions may allocate freely: the rule is opt-in
    return os.urandom(8).hex(), {k: str(v) for k, v in req.items()}
""",
    ),
    "swallowed-fault": (
        """
def serve_batch(executor, batch):
    try:
        return executor.forward(batch)
    except Exception:
        pass
""",
        """
from spark_bagging_tpu import telemetry

def serve_batch(executor, batch, future):
    try:
        return executor.forward(batch)
    except Exception as e:
        telemetry.inc("sbt_serving_batch_errors_total")
        future.set_exception(e)
    try:
        return executor.forward(batch)
    except OSError:
        return None  # narrow handlers are deliberate-by-construction
    try:
        return executor.forward(batch)
    except Exception:
        raise
""",
    ),
    "shared-state-unlocked": (
        """
import threading

# sbt-lint: shared-state
class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def add(self, k, v):
        self._items[k] = v
""",
        """
import threading

# sbt-lint: shared-state
class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def add(self, k, v):
        with self._lock:
            self._items[k] = v
""",
    ),
}


@pytest.mark.parametrize("rule", sorted(BAD_GOOD))
def test_rule_fires_on_bad_fixture(rule):
    bad, _ = BAD_GOOD[rule]
    found = hits(bad, rule)
    assert found, f"{rule} missed its known-bad fixture"
    assert all(f.rule == rule for f in found)


@pytest.mark.parametrize("rule", sorted(BAD_GOOD))
def test_rule_silent_on_good_twin(rule):
    _, good = BAD_GOOD[rule]
    found = hits(good, rule)
    assert not found, f"{rule} false-positived on its good twin: {found}"


def test_every_registered_rule_has_fixtures():
    _load_rules()
    assert set(RULES) == set(BAD_GOOD), (
        "every rule ships with a bad/good fixture pair; update "
        "BAD_GOOD when adding rules"
    )


# -- targeted rule behaviors -------------------------------------------

def test_prng_branch_exclusive_use_is_clean():
    # the ops/bootstrap.py pattern: one key, consumed in mutually
    # exclusive if-arms — at most one draw per call, not reuse
    src = """
import jax

def draw(key, replacement):
    k = jax.random.fold_in(key, 7)
    if replacement:
        return jax.random.poisson(k, 1.0, (8,))
    return jax.random.uniform(k, (8,))
"""
    assert not hits(src, "prng-key-reuse")


def test_prng_loop_reuse_is_flagged():
    src = """
import jax

def noise(key, n):
    outs = []
    for i in range(n):
        outs.append(jax.random.normal(key, (4,)))
    return outs
"""
    assert hits(src, "prng-key-reuse")


def test_prng_loop_rederive_is_clean():
    src = """
import jax

def noise(key, n):
    outs = []
    for i in range(n):
        k = jax.random.fold_in(key, i)
        outs.append(jax.random.normal(k, (4,)))
    return outs
"""
    assert not hits(src, "prng-key-reuse")


def test_donated_carry_rebind_in_loop_is_clean():
    # the streaming engine's shape: donated carry rebound by the call
    src = """
import jax

def fit(params, opt_state, chunks, step_fn):
    step = jax.jit(step_fn, donate_argnums=(0, 1))
    for c in chunks:
        params, opt_state = step(params, opt_state, c)
    return params, opt_state
"""
    assert not hits(src, "donated-arg-reuse")


def test_jit_decorated_def_in_loop_is_flagged():
    src = """
import jax

def grow(levels, h):
    for level in levels:
        @jax.jit
        def select(hist, _level=level):
            return hist[_level]
        h = select(h)
    return h
"""
    assert hits(src, "jit-in-loop")


def test_host_sync_scalar_builtins_only_flagged_under_jit():
    # int(X.shape[0]) inside a span is host shape math, not a sync
    src = """
from spark_bagging_tpu import telemetry

def report(X):
    with telemetry.span("aggregate"):
        n = int(X.shape[0])
    return n
"""
    assert not hits(src, "host-sync-in-span")


# -- suppressions ------------------------------------------------------

BAD_PRNG = BAD_GOOD["prng-key-reuse"][0]


def test_same_line_suppression():
    src = BAD_PRNG.replace(
        "b = jax.random.uniform(key, (4,))",
        "b = jax.random.uniform(key, (4,))  # sbt-lint: disable=prng-key-reuse",
    )
    assert not hits(src, "prng-key-reuse")


def test_comment_line_above_suppresses_next_line():
    src = BAD_PRNG.replace(
        "    b = jax.random.uniform(key, (4,))",
        "    # sbt-lint: disable=prng-key-reuse — fixture\n"
        "    b = jax.random.uniform(key, (4,))",
    )
    assert not hits(src, "prng-key-reuse")


def test_disable_all_wildcard():
    src = BAD_PRNG.replace(
        "b = jax.random.uniform(key, (4,))",
        "b = jax.random.uniform(key, (4,))  # sbt-lint: disable=all",
    )
    assert not lint_source(src)


def test_suppression_covers_wrapped_multiline_statement():
    """A formatter re-wrap must not orphan a suppression: the comment
    above the STATEMENT covers findings anchored on its later physical
    lines."""
    src = """
import jax

def init(key):
    w = jax.random.normal(key, (4,))
    # sbt-lint: disable=prng-key-reuse — fixture
    b = jax.random.uniform(
        key,
        (4,),
    )
    return w, b
"""
    assert not hits(src, "prng-key-reuse")


def test_suppression_is_rule_specific():
    src = BAD_PRNG.replace(
        "b = jax.random.uniform(key, (4,))",
        "b = jax.random.uniform(key, (4,))  # sbt-lint: disable=jit-in-loop",
    )
    assert hits(src, "prng-key-reuse")


def test_unknown_rule_name_raises():
    with pytest.raises(KeyError):
        lint_source("x = 1\n", enabled={"no-such-rule"})


def test_syntax_error_is_reported_not_raised():
    found = lint_source("def broken(:\n")
    assert [f.rule for f in found] == ["syntax-error"]


# -- CLI ---------------------------------------------------------------

def test_cli_exit_zero_on_clean_file(tmp_path, capsys):
    p = tmp_path / "clean.py"
    p.write_text("x = 1\n")
    assert lint_main([str(p), "--no-config", "--engines", "lint"]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_exit_one_on_findings(tmp_path, capsys):
    p = tmp_path / "bad.py"
    p.write_text(BAD_PRNG)
    assert lint_main([str(p), "--no-config", "--engines", "lint"]) == 1
    out = capsys.readouterr().out
    assert "prng-key-reuse" in out and "bad.py" in out


def test_cli_json_format(tmp_path, capsys):
    p = tmp_path / "bad.py"
    p.write_text(BAD_PRNG)
    assert lint_main([str(p), "--no-config", "--format", "json",
                      "--engines", "lint,determinism,locks"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert data["clean"] is False
    assert data["engines"]["lint"]["findings"] >= 1
    assert any(f["engine"] == "lint" and f["rule"] == "prng-key-reuse"
               for f in data["findings"])


def test_cli_json_schema_is_stable(tmp_path, capsys):
    """Satellite [ISSUE 19]: scenario CI diffs analyzer runs the way
    it diffs digest baselines, so the JSON payload's shape is a
    CONTRACT — top-level keys, per-engine counts, and per-finding
    fields are pinned here; bump `schema` to change them."""
    p = tmp_path / "mixed.py"
    p.write_text(BAD_PRNG)
    assert lint_main([str(p), "--no-config", "--format", "json",
                      "--engines", "lint,determinism,locks"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert sorted(data) == ["clean", "engines", "findings", "schema"]
    assert data["schema"] == 1
    assert isinstance(data["clean"], bool)
    assert list(data["engines"]) == ["lint", "determinism", "locks"]
    for stats in data["engines"].values():
        assert sorted(stats) == ["findings"]
        assert isinstance(stats["findings"], int)
    for f in data["findings"]:
        assert sorted(f) == ["col", "engine", "line", "message",
                             "path", "rule"]


def test_cli_unknown_engine_errors(tmp_path, capsys):
    p = tmp_path / "clean.py"
    p.write_text("x = 1\n")
    with pytest.raises(SystemExit) as exc:
        lint_main([str(p), "--no-config", "--engines", "lint,warp"])
    assert exc.value.code == 2
    capsys.readouterr()


def test_cli_disable_flag(tmp_path, capsys):
    p = tmp_path / "bad.py"
    p.write_text(BAD_PRNG)
    assert lint_main(
        [str(p), "--no-config", "--engines", "lint",
         "--disable", "prng-key-reuse"]
    ) == 0
    capsys.readouterr()


def test_cli_errors_on_missing_path(capsys):
    # a typo'd path must NOT silently lint nothing and exit 0
    with pytest.raises(SystemExit) as exc:
        lint_main(["definitely_not_a_path_xyz", "--no-config"])
    assert exc.value.code == 2
    capsys.readouterr()


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in BAD_GOOD:
        assert rule in out


def test_config_section_roundtrip(tmp_path, monkeypatch):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.sbt-lint]\npaths = ['pkg']\nexclude = ['gen']\n"
        "disable = ['jit-in-loop']\n"
    )
    cfg = load_config(str(tmp_path))
    assert cfg["paths"] == ["pkg"]
    assert cfg["exclude"] == ["gen"]
    assert cfg["disable"] == ["jit-in-loop"]


def test_config_defaults_without_file(tmp_path):
    cfg = load_config(str(tmp_path))
    assert cfg["paths"] == ["spark_bagging_tpu", "benchmarks",
                            "examples"]


# -- the self-hosting gate ---------------------------------------------

# slow: strictly subsumed by test_repo_tree_is_contract_clean below,
# which runs the lint engine over the same tree in the same tier-1
# session (clean=True asserts lint findings == 0); this standalone
# variant only re-proves the direct lint_paths API + its 10 s budget
@pytest.mark.slow
def test_repo_tree_is_lint_clean():
    """The package, benchmarks, and examples stay lint-clean (zero
    unsuppressed findings). Tier-1 carries this via the four-engine
    gate below; this direct-API variant lives in ``slow``. If it
    fails, either fix the finding or add a justified
    `# sbt-lint: disable=<rule>` with a reason."""
    import time

    cfg = load_config(REPO)
    t0 = time.perf_counter()
    findings = lint_paths(
        [os.path.join(REPO, p) for p in cfg["paths"]],
        exclude=cfg["exclude"], disabled=cfg["disable"],
    )
    dt = time.perf_counter() - t0
    assert not findings, "\n".join(f.render() for f in findings)
    assert dt < 10.0, f"full-tree lint took {dt:.1f}s (budget 10s)"


def test_repo_tree_is_contract_clean(monkeypatch, capsys):
    """THE tier-1 gate for ISSUE 19: ALL analysis engines — lint,
    determinism, contracts, locks — run over the tree through the real
    CLI and exit 0. A finding means either fix it or carry a justified
    inline `# sbt-lint: disable=<rule>`; the budget keeps the whole
    inventory cheap enough to gate every run."""
    import time

    monkeypatch.chdir(REPO)
    t0 = time.perf_counter()
    rc = lint_main(["--format", "json"])
    dt = time.perf_counter() - t0
    data = json.loads(capsys.readouterr().out)
    assert rc == 0, "\n".join(
        f"{f['path']}:{f['line']}: [{f['engine']}/{f['rule']}] "
        f"{f['message']}" for f in data["findings"]
    )
    assert data["clean"] is True
    assert set(data["engines"]) == {"lint", "determinism", "contracts",
                                    "locks"}
    assert dt < 15.0, f"full-tree analysis took {dt:.1f}s (budget 15s)"


# -- jaxpr audit -------------------------------------------------------

@pytest.fixture(scope="module")
def cls_data():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(48, 6)).astype(np.float32)
    y = (X[:, 0] - 0.3 * X[:, 1] > 0).astype(np.int64)
    return X, y


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(48, 6)).astype(np.float32)
    y = (X[:, 0] + 0.1 * rng.normal(size=48)).astype(np.float32)
    return X, y


def _zoo():
    """(name, builder) for every estimator family with a serving seam.
    Tiny configs: the audit only needs a FITTED estimator to trace, not
    a good one."""
    from spark_bagging_tpu import (
        BaggingClassifier,
        BaggingRegressor,
        FMClassifier,
        GaussianNB,
        GBTRegressor,
        GeneralizedLinearRegression,
        LinearRegression,
        LinearSVC,
        LogisticRegression,
        MLPClassifier,
        RandomForestClassifier,
        RandomForestRegressor,
    )

    def bag_c(learner):
        return lambda X, y: BaggingClassifier(
            base_learner=learner, n_estimators=2, seed=0
        ).fit(X, y)

    def bag_r(learner):
        return lambda X, y: BaggingRegressor(
            base_learner=learner, n_estimators=2, seed=0
        ).fit(X, y)

    # tier-1 keeps one representative per distinct program structure —
    # forest_cls (tree ensemble gather/scatter + replica vmap), mlp
    # (deep chained matmul/activation), glm (iterative GLM-family
    # solve) — the rest ride in `slow`: they share those jaxpr shapes
    # and the audit rules are structural, not numeric
    slow = pytest.mark.slow
    return [
        # slow: GLM-family iterative solve — glm is the tier-1 rep
        pytest.param("logistic", "cls",
                     bag_c(LogisticRegression(max_iter=3)), marks=slow),
        # slow: same linear-forward family as logistic/glm
        pytest.param("svc", "cls", bag_c(LinearSVC(max_iter=3)),
                     marks=slow),
        # slow: closed-form stats forward, simplest jaxpr in the zoo
        pytest.param("gaussian_nb", "cls", bag_c(GaussianNB()),
                     marks=slow),
        pytest.param("mlp", "cls",
                     bag_c(MLPClassifier(hidden=4, max_iter=3))),
        # slow: factorized linear forward — structurally between
        # linear and mlp, both of which stay covered
        pytest.param("fm", "cls",
                     bag_c(FMClassifier(factor_size=2, max_iter=3)),
                     marks=slow),
        # slow: closed-form linear solve — glm is the tier-1 rep
        pytest.param("linear", "reg", bag_r(LinearRegression()),
                     marks=slow),
        pytest.param("glm", "reg",
                     bag_r(GeneralizedLinearRegression(max_iter=3))),
        # slow: boosted trees share the tree-forward jaxpr family with
        # forest_cls, the tier-1 rep
        pytest.param("gbt", "reg",
                     bag_r(GBTRegressor(n_rounds=2, max_depth=2)),
                     marks=slow),
        pytest.param("forest_cls", "cls",
                     lambda X, y: RandomForestClassifier(
                         n_estimators=2, max_depth=2, n_bins=8,
                         seed=0).fit(X, y)),
        # slow: same tree-forward structure as forest_cls
        pytest.param("forest_reg", "reg",
                     lambda X, y: RandomForestRegressor(
                         n_estimators=2, max_depth=2, n_bins=8,
                         seed=0).fit(X, y), marks=slow),
    ]


@pytest.mark.parametrize(
    "name,kind,build", _zoo(), ids=[z.values[0] for z in _zoo()]
)
def test_jaxpr_audit_model_zoo(name, kind, build, cls_data, reg_data):
    """Acceptance: every zoo member's aggregated forward is TPU-clean —
    no host callbacks, no wide-dtype promotion, bounded consts, and the
    donation request is honored or provably inapplicable."""
    X, y = cls_data if kind == "cls" else reg_data
    est = build(X, y)
    report = audit_estimator(est)  # raises AuditError on violation
    assert report.ok
    assert report.n_eqns > 0
    assert report.donation_checked
    assert report.donation_applied or report.donation_inapplicable
    assert not report.wide_dtypes


def test_jaxpr_audit_serving_executor(cls_data):
    """The serving path itself — the executor's compiled closure at a
    real bucket shape — passes the same audit."""
    from spark_bagging_tpu import BaggingClassifier, LogisticRegression
    from spark_bagging_tpu.serving import EnsembleExecutor

    X, y = cls_data
    clf = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3), n_estimators=2,
        seed=0,
    ).fit(X, y)
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32)
    report = audit_executor(ex)
    assert report.ok and report.n_eqns > 0


def test_audit_flags_host_callback():
    import jax
    import jax.numpy as jnp

    def with_cb(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct((4,), jnp.float32), x,
        )

    report = audit_fn(with_cb, jnp.zeros((4,), jnp.float32),
                      name="cb-fixture")
    assert not report.ok
    assert any("pure_callback" in p for p in report.problems)
    with pytest.raises(AuditError):
        report.raise_if_bad()


def test_audit_flags_oversized_consts():
    import jax.numpy as jnp

    baked = jnp.ones((64, 64), jnp.float32)  # 16 KiB closure capture

    def f(x):
        return x @ baked

    report = audit_fn(f, jnp.zeros((2, 64), jnp.float32),
                      max_const_bytes=1024, name="const-fixture")
    assert any("constant" in p for p in report.problems)


def test_audit_verifies_carry_donation():
    import jax.numpy as jnp

    def step(params, x):
        return params + x.sum()

    report = audit_fn(step, jnp.zeros((8,)), jnp.ones((3, 8)),
                      donate_argnums=(0,), name="carry-fixture")
    assert report.donation_checked and report.donation_applied
    assert report.ok


def test_audit_callback_allowance():
    import jax
    import jax.numpy as jnp

    def with_cb(x):
        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct((4,),
                                                          jnp.float32), x,
        )

    report = audit_fn(with_cb, jnp.zeros((4,), jnp.float32),
                      allow_callbacks=True, name="cb-ok-fixture")
    assert report.ok


# -- lock-order detector -----------------------------------------------

@pytest.fixture()
def lock_debug():
    locks.enable(True, strict=False)
    locks.clear()
    yield
    locks.clear()
    locks.enable(False)


def test_lock_cycle_detected(lock_debug):
    """The canonical repro the detector must catch: two locks taken in
    opposite orders (here sequentially — no deadlock has to happen for
    the ORDER violation to be visible)."""
    a = locks.DebugLock("locks.A")
    b = locks.DebugLock("locks.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    v = locks.violations()
    assert any("cycle" in msg for msg in v), v


def test_lock_cycle_strict_raises(lock_debug):
    locks.enable(True, strict=True)
    a = locks.DebugLock("locks.A2")
    b = locks.DebugLock("locks.B2")
    with a:
        with b:
            pass
    with pytest.raises(locks.LockOrderError):
        with b:
            with a:
                pass


def test_strict_raise_releases_the_lock(lock_debug):
    """A strict-mode LockOrderError must leave the lock RELEASED and
    the held-stack clean — otherwise the failing test suite deadlocks
    on the next acquire instead of reporting the violation."""
    locks.enable(True, strict=True)
    a = locks.DebugLock("locks.A2b")
    b = locks.DebugLock("locks.B2b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locks.LockOrderError):
            a.acquire()
    assert locks.held_locks() == ()
    assert a.acquire(timeout=1.0), "lock leaked by the strict raise"
    a.release()


def test_consistent_order_is_clean(lock_debug):
    a = locks.DebugLock("locks.A3")
    b = locks.DebugLock("locks.B3")
    for _ in range(3):
        with a:
            with b:
                pass
    assert not locks.violations()


def test_cross_thread_cycle_detected(lock_debug):
    """The realistic shape: each ORDER comes from a different thread."""
    a = locks.DebugLock("locks.A4")
    b = locks.DebugLock("locks.B4")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with b:
        with a:
            pass
    assert any("cycle" in msg for msg in locks.violations())


def test_sync_while_locked_hazard(lock_debug):
    a = locks.DebugLock("locks.A5")
    with a:
        locks.note_device_sync("test barrier")
    v = locks.violations()
    assert any("A5" in msg for msg in v), v


def test_telemetry_barrier_reports_held_lock(lock_debug):
    """The adopted integration: the telemetry span device barrier calls
    note_device_sync, so a sync span under a registry lock is caught."""
    from spark_bagging_tpu.telemetry.spans import _device_barrier

    a = locks.DebugLock("serving.registry.test")
    with a:
        _device_barrier()
    assert any("serving.registry.test" in m for m in locks.violations())


def test_same_name_instance_nesting_is_flagged(lock_debug):
    """Two registries nested = two locks with ONE graph name: no a->b
    edge exists, but instances of one class have no defined order —
    the classic symmetric deadlock. Must be flagged anyway."""
    a = locks.DebugLock("serving.registry")
    b = locks.DebugLock("serving.registry")
    with a:
        with b:
            pass
    assert any("serving.registry" in m and "instances" in m
               for m in locks.violations())


def test_rlock_reentry_is_not_a_cycle(lock_debug):
    a = locks.DebugLock("locks.R", rlock=True)
    with a:
        with a:
            pass
    assert not locks.violations()


def test_make_lock_plain_when_disabled():
    locks.enable(False)
    lk = locks.make_lock("plain")
    assert isinstance(lk, type(threading.Lock()))


def test_make_lock_instrumented_when_enabled(lock_debug):
    lk = locks.make_lock("serving.test")
    assert isinstance(lk, locks.DebugLock)


def test_adopted_subsystems_use_factory(lock_debug):
    """Registry/executor/batcher locks come from make_lock, so enabling
    debug instruments the REAL serving stack."""
    from spark_bagging_tpu.serving.registry import ModelRegistry
    from spark_bagging_tpu.telemetry.registry import Registry

    assert isinstance(ModelRegistry()._lock, locks.DebugLock)
    assert isinstance(Registry()._lock, locks.DebugLock)


def test_batcher_double_close_race_fix(cls_data):
    """close() is guarded by a lock now: N racing closers, one drain."""
    from spark_bagging_tpu import BaggingClassifier, LogisticRegression
    from spark_bagging_tpu.serving import EnsembleExecutor, MicroBatcher

    X, y = cls_data
    clf = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3), n_estimators=2,
        seed=0,
    ).fit(X, y)
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32)
    mb = MicroBatcher(ex, max_queue=4)
    threads = [threading.Thread(target=mb.close) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with pytest.raises(RuntimeError):
        mb.submit(X[:1])
