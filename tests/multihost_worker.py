"""Worker process for the real 2-process multihost test
[SURVEY §5 comms backend; VERDICT r1 weak#8 "untested multi-host path"].

Launched by ``test_multihost.py`` as::

    python multihost_worker.py <process_id> <num_processes> <port> <out>

Each worker owns 2 virtual CPU devices (XLA_FLAGS set by the parent,
parsed at interpreter start), joins the others through
``initialize_distributed`` (Gloo collectives over loopback — the CI
stand-in for a TPU pod's ICI/DCN), fits a bagging ensemble on a global
``(data=2, replica=2)`` mesh spanning both processes, and writes its
view of the results to ``<out>.<process_id>`` for the parent to check.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    pid, nprocs = int(sys.argv[1]), int(sys.argv[2])
    port, out_path = sys.argv[3], sys.argv[4]

    from spark_bagging_tpu.parallel.distributed import initialize_distributed

    n_dev = initialize_distributed(f"localhost:{port}", nprocs, pid)
    assert jax.local_device_count() == 2, jax.local_devices()
    assert n_dev == 2 * nprocs, f"expected {2 * nprocs} global devices"

    import numpy as np
    from sklearn.datasets import load_breast_cancer
    from sklearn.preprocessing import StandardScaler

    from spark_bagging_tpu import BaggingClassifier
    from spark_bagging_tpu.parallel import make_mesh

    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)

    mesh = make_mesh(data=2, replica=2)  # spans both processes
    clf = BaggingClassifier(
        n_estimators=8, seed=1, mesh=mesh, max_features=0.8,
        oob_score=True,
    ).fit(X, y)
    proba = clf.predict_proba(X)

    # streamed fit over the same global mesh: every process streams the
    # same chunks; global_put ships only the local shards [B:11]
    from spark_bagging_tpu import ArrayChunks

    sclf = BaggingClassifier(n_estimators=8, seed=1, mesh=mesh)
    sclf.fit_stream(ArrayChunks(X, y, chunk_rows=128), n_epochs=8, lr=0.05)
    stream_acc = float(sclf.score(X, y))

    # tree-structured learner across processes: quantile prepare()
    # psums per-shard bin edges over the process-spanning data axis,
    # per-split feature masks draw from replica fit keys
    from spark_bagging_tpu import RandomForestClassifier

    rf = RandomForestClassifier(
        n_estimators=4, max_depth=3, seed=1, mesh=mesh,
    ).fit(X, y)
    rf_acc = float(rf.score(X, y))

    # aux channel across processes: the censor column global_puts with
    # a P(data) spec exactly like y — each process ships its shard only
    from spark_bagging_tpu import AFTSurvivalRegression, BaggingRegressor

    rng = np.random.default_rng(0)
    T = np.exp(
        X[:, 0] * 0.5 + 0.3 * np.log(rng.exponential(1.0, len(y)))
    ).astype(np.float32)
    cutoff = np.quantile(T, 0.7)
    aft = BaggingRegressor(
        base_learner=AFTSurvivalRegression(max_iter=40),
        n_estimators=4, seed=1, mesh=mesh,
    ).fit(X, np.minimum(T, cutoff), aux=(T <= cutoff).astype(np.float32))
    aft_pred_head = np.asarray(aft.predict(X[:16])).tolist()

    # pooled warm start across processes: the shared pooled solve's row
    # stats psum over the process-spanning data axis; every process
    # must derive the SAME pooled start or replica fits diverge
    from spark_bagging_tpu import LogisticRegression

    pooled = BaggingClassifier(
        base_learner=LogisticRegression(
            l2=1e-3, max_iter=1, init="pooled", precision="high"
        ),
        n_estimators=8, seed=1, mesh=mesh,
    ).fit(X, y)
    pooled_pred_head = np.asarray(pooled.predict_proba(X[:16])).tolist()
    pooled_acc = float(pooled.score(X, y))

    # Arrow file ingestion on the multiprocess mesh (round 5): each
    # process streams an identical row-major fixed-size-list file —
    # the fast-lane zero-copy decode feeding global_put's shard-only
    # transfers, i.e. real file I/O joined to real collectives
    import tempfile

    from spark_bagging_tpu.utils.arrow import (
        ArrowChunks,
        write_row_major_ipc,
    )

    try:
        with tempfile.TemporaryDirectory() as td:
            fpath = os.path.join(td, "rows.arrow")
            # pyarrow import is DEFERRED inside utils.arrow, so a
            # missing pyarrow surfaces here at call time, not above
            write_row_major_ipc(fpath, X, y, chunk_rows=128,
                                label_dtype=np.int32)
            aclf = BaggingClassifier(n_estimators=8, seed=1, mesh=mesh)
            aclf.fit_stream(
                ArrowChunks(fpath, 128), classes=[0, 1],
                n_epochs=4, lr=0.05,
            )
            arrow_acc = float(aclf.score(X, y))
    except ImportError:
        arrow_acc = None

    with open(f"{out_path}.{pid}", "w") as f:
        json.dump({
            "process_id": pid,
            "n_global_devices": n_dev,
            "arrow_stream_accuracy": arrow_acc,
            "accuracy": float(clf.score(X, y)),
            "oob_score": float(clf.oob_score_),
            "proba_head": np.asarray(proba[:16]).tolist(),
            "losses_mean": float(np.mean(clf.fit_report_["loss_mean"])),
            "stream_accuracy": stream_acc,
            "rf_accuracy": rf_acc,
            "aft_pred_head": aft_pred_head,
            "pooled_pred_head": pooled_pred_head,
            "pooled_accuracy": pooled_acc,
        }, f)


if __name__ == "__main__":
    main()
