"""End-to-end request journey [ISSUE 20]: the tenancy fleet mints one
trace per request (tenant on every span), admission/WFQ/residency/
dispatch contribute exact stage timings that TILE the total
(admission + wfq + dispatch + restore + queue + batch == total), sheds
resolve the trace with a terminal ``tenancy_shed`` span instead of
vanishing, traces survive a mid-traffic ``registry.swap()`` and a
demote→restore cycle, and the unarmed journey probe stays one
attribute read.
"""

import time

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.serving import ModelRegistry
from spark_bagging_tpu.serving import program_cache as _pc
from spark_bagging_tpu.telemetry import perf, tracing
from spark_bagging_tpu.tenancy import TenantFleet, TenantSpec
from spark_bagging_tpu.tenancy.admission import (
    QuotaExceeded,
    TenantQuarantined,
)

JOURNEY_KEYS = ("admission_ms", "wfq_ms", "dispatch_ms", "restore_ms")


@pytest.fixture(scope="module", autouse=True)
def _module_clock():
    """Wall-clock anchor for the budget test (module import happens at
    collection, long before the first test runs)."""
    return time.perf_counter()


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.enable()
    # a private unified cache per test (the test_tenancy convention):
    # restored executables must not leak across tests
    prev_cache = _pc.install(_pc.ProgramCache(capacity=64))
    yield
    _pc.install(prev_cache)
    telemetry.reset()
    telemetry.enable()


def _problem(n=96, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w > 0).astype(np.int32)
    return X, y


def _fit(seed=0, n_estimators=2):
    X, y = _problem(seed=seed)
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=n_estimators, seed=seed,
    ).fit(X, y)


def _assert_tiles_exactly(bd, tol_ms=1e-6):
    """The decomposition contract: the six journey + batcher stages
    telescope to the fleet-anchored total (float noise only)."""
    parts = (bd.get("admission_ms", 0.0) + bd.get("wfq_ms", 0.0)
             + bd.get("dispatch_ms", 0.0) + bd.get("restore_ms", 0.0)
             + bd.get("queue_ms", 0.0) + bd.get("batch_ms", 0.0))
    assert parts == pytest.approx(bd["total_ms"], abs=tol_ms), bd


class _BreakdownRecorder:
    """Stand-in perf plane: records every breakdown the probes feed
    (duck-typed — the probe calls only ``observe_breakdown``)."""

    def __init__(self):
        self.breakdowns = []

    def observe_breakdown(self, bd, trace_id=None):
        self.breakdowns.append((dict(bd), trace_id))


# -- the exact-decomposition property ----------------------------------

class TestExactDecomposition:
    @pytest.mark.parametrize("threaded", [False, True])
    def test_served_requests_tile_exactly(self, tmp_path, threaded):
        """Tentpole property [ISSUE 20]: across stepped AND threaded
        drive (restore carved from queue wait vs dispatch interval)
        every served request's breakdown tiles its total exactly, with
        the tenant stamped and every journey stage present."""
        specs = [TenantSpec(name="t0"), TenantSpec(name="t1")]
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
        fleet = TenantFleet(specs, registry=reg, residency_capacity=1,
                            aot_root=str(tmp_path), threaded=threaded)
        try:
            for i in range(2):
                fleet.register(f"t{i}", _fit(seed=i), warmup=True,
                               version=1)
            X = np.asarray(_problem(seed=3)[0][:8])
            futs = []
            # alternating tenants against residency capacity 1: every
            # window restores someone, so restore_ms > 0 paths are
            # exercised in both drive modes
            for step in range(4):
                fleet.submit(f"t{step % 2}", X, now=float(step))
                futs += [r["future"]
                         for r in fleet.dispatch(now=float(step))
                         if r["future"] is not None]
            assert len(futs) == 4
            restored = 0
            for f in futs:
                f.result(30)
                bd = f.trace.breakdown
                assert bd["tenant"] in ("t0", "t1")
                assert bd["path"] in ("direct", "coalesced")
                for k in JOURNEY_KEYS:
                    assert k in bd, k
                if bd["restore_ms"] > 0:
                    restored += 1
                _assert_tiles_exactly(bd)
            assert restored >= 1
        finally:
            fleet.close()

    def test_quota_shed_resolves_trace_with_exact_breakdown(self):
        """A quota shed is a terminal journey outcome: the raised
        exception carries the trace id, the breakdown reaches the perf
        probe with ``path="shed"``, zeroed batcher stages, and an
        exact admission-anchored tiling."""
        rec = _BreakdownRecorder()
        prev = perf.install(rec)
        fleet = TenantFleet([TenantSpec(name="t0", quota_rps=1.0)])
        try:
            fleet.register("t0", _fit(seed=0), warmup=False, version=1)
            X = np.asarray(_problem(seed=3)[0][:4])
            fleet.submit("t0", X, now=0.0)  # takes the burst token
            with pytest.raises(QuotaExceeded) as ei:
                fleet.submit("t0", X, now=0.01)
            assert ei.value.trace_id is not None
            sheds = [(bd, tid) for bd, tid in rec.breakdowns
                     if bd.get("shed")]
            assert len(sheds) == 1
            bd, tid = sheds[0]
            assert tid == ei.value.trace_id
            assert bd["shed"] == "quota"
            assert bd["path"] == "shed"
            assert bd["tenant"] == "t0"
            assert bd["queue_ms"] == 0.0
            assert bd["batch_ms"] == 0.0
            assert bd["batch_size"] == 0
            _assert_tiles_exactly(bd)
        finally:
            fleet.close()
            perf.install(prev)

    def test_quarantine_shed_terminal_span_and_shed_log(self):
        """Quarantine sheds resolve with a terminal ``tenancy_shed``
        span, an exact breakdown, AND a trace id on the quarantine
        machine's shed log (the bugfix satellite: sheds used to be
        joinable only by tenant name)."""
        rec = _BreakdownRecorder()
        prev = perf.install(rec)
        fleet = TenantFleet([TenantSpec(name="t0")],
                            quarantine_threshold=1)
        try:
            fleet.register("t0", _fit(seed=0), warmup=False, version=1)
            fleet.quarantine.record_failure("t0", 0.0, "dispatch")
            X = np.asarray(_problem(seed=3)[0][:4])
            with telemetry.capture() as run:
                with pytest.raises(TenantQuarantined) as ei:
                    fleet.submit("t0", X, now=0.1)
            tid = ei.value.trace_id
            assert tid is not None
            sheds = [(bd, t) for bd, t in rec.breakdowns
                     if bd.get("shed") == "quarantine"]
            assert len(sheds) == 1
            bd, bd_tid = sheds[0]
            assert bd_tid == tid
            assert bd["tenant"] == "t0"
            _assert_tiles_exactly(bd)
            spans = [s for s in run.spans("tenancy_shed")
                     if s.get("trace_id") == tid]
            assert len(spans) == 1
            assert spans[0]["attrs"] == {"tenant": "t0",
                                         "reason": "quarantine"}
            shed_events = [e for e in run.events
                           if e.get("kind") == "tenancy_shed"]
            assert [e["trace_id"] for e in shed_events] == [tid]
            state = fleet.quarantine.state()
            assert any(s["trace_id"] == tid
                       for s in state["recent_sheds"])
        finally:
            fleet.close()
            perf.install(prev)


# -- trace propagation --------------------------------------------------

class TestTracePropagation:
    def test_trace_survives_mid_traffic_swap(self):
        """Satellite [ISSUE 20]: a ``registry.swap()`` between two
        traffic windows must not lose spans or breakdowns — both
        requests keep distinct traces, exact tilings, and exactly one
        admission + one dispatch span each, with the served version
        flipping at the swap boundary."""
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
        fleet = TenantFleet([TenantSpec(name="t0")], registry=reg)
        try:
            fleet.register("t0", _fit(seed=0), warmup=True, version=1)
            X = np.asarray(_problem(seed=3)[0][:8])
            futs = []
            with telemetry.capture() as run:
                fleet.submit("t0", X, now=0.0)
                futs += [r["future"]
                         for r in fleet.dispatch(now=0.0)
                         if r["future"] is not None]
                reg.swap("t0", _fit(seed=1), version=2)
                fleet.submit("t0", X, now=1.0)
                futs += [r["future"]
                         for r in fleet.dispatch(now=1.0)
                         if r["future"] is not None]
                for f in futs:
                    f.result(30)
            assert len(futs) == 2
            tids = [f.trace.trace_id for f in futs]
            assert len(set(tids)) == 2
            for f in futs:
                bd = f.trace.breakdown
                assert bd["tenant"] == "t0"
                _assert_tiles_exactly(bd)
            assert [f.trace.breakdown["model_version"]
                    for f in futs] == [1, 2]
            # zero lost spans: every trace shows its admission and
            # dispatch span exactly once, tenant-attributed
            for tid in tids:
                for name in ("tenancy_admission", "tenancy_dispatch"):
                    spans = [s for s in run.spans(name)
                             if s.get("trace_id") == tid]
                    assert len(spans) == 1, (name, tid)
                    assert spans[0]["attrs"]["tenant"] == "t0"
        finally:
            fleet.close()

    def test_demote_restore_cycle_stamps_restore_exactly_once(
            self, tmp_path):
        """Satellite [ISSUE 20]: a demoted tenant's next request pays
        the AOT restore (``restore_ms > 0``) exactly once; the
        follow-up request (now resident) pays zero, both outputs are
        bitwise-identical to a never-demoted control, and no spans are
        lost across the cycle."""
        specs = [TenantSpec(name="t0"), TenantSpec(name="t1")]
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
        fleet = TenantFleet(specs, registry=reg, residency_capacity=1,
                            aot_root=str(tmp_path))
        try:
            models = [_fit(seed=0), _fit(seed=1)]
            for i in range(2):
                fleet.register(f"t{i}", models[i], warmup=True,
                               version=1)
            # capacity 1: registering t1 demoted t0
            assert fleet.residency.residents() == ("t1",)
            X = np.asarray(_problem(seed=9)[0][:8])
            solo_reg = ModelRegistry(min_bucket_rows=8,
                                     max_batch_rows=16)
            solo_reg.register("solo", models[0], warmup=True)
            with solo_reg.batcher("solo") as b:
                want = np.asarray(b.submit(X).result(30))
            with telemetry.capture() as run:
                fleet.submit("t0", X, now=0.0)
                f1 = fleet.dispatch(now=0.0)[0]["future"]
                out1 = np.asarray(f1.result(30))
                fleet.submit("t0", X, now=1.0)
                f2 = fleet.dispatch(now=1.0)[0]["future"]
                out2 = np.asarray(f2.result(30))
            assert np.array_equal(out1, want)
            assert np.array_equal(out2, want)
            assert f1.trace.breakdown["restore_ms"] > 0
            assert f2.trace.breakdown["restore_ms"] == 0.0
            for f in (f1, f2):
                _assert_tiles_exactly(f.trace.breakdown)
            # the restore evidence event fired once, carrying f1's id
            restores = [e for e in run.events
                        if e.get("kind") == "tenancy_restore"
                        and e.get("tenant") == "t0"]
            assert len(restores) == 1
            assert f1.trace.trace_id in restores[0]["trace_ids"]
            assert restores[0]["restore_ms"] > 0
            for f in (f1, f2):
                for name in ("tenancy_admission", "tenancy_dispatch"):
                    assert len([
                        s for s in run.spans(name)
                        if s.get("trace_id") == f.trace.trace_id
                    ]) == 1
        finally:
            fleet.close()


# -- probe cost ---------------------------------------------------------

class TestUnarmedJourneyProbe:
    def test_unarmed_probe_is_one_attribute_read(self):
        """The journey feed's unarmed probe (exactly what
        ``_resolve_shed`` and ``_finish_breakdown`` run when no perf
        plane is installed) must stay far under a microsecond."""
        perf.disable()
        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            ap = perf.ACTIVE
            if ap is not None:  # pragma: no cover — disabled
                raise AssertionError
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 2e-6, f"{per_call * 1e9:.0f}ns per probe"

    def test_batcher_minted_traces_carry_no_journey(self):
        """A single-model process never pays the journey fix-up: the
        batcher-minted trace's ``journey`` slot is None, so the
        breakdown path gates on one attribute read."""
        assert tracing.request_context().journey is None


# -- the replay journey section -----------------------------------------

class TestReplayJourney:
    def test_virtual_journey_verdicts_and_repeat_identity(self):
        """The tenant-tail-attribution contract at unit scale: a
        skewed-Zipf, tight-residency drive produces ``wfq-starved``
        AND ``restore-absorbed`` verdicts on the virtual clock, and
        the whole journey section (digest included) is byte-identical
        across two independent runs."""
        from benchmarks.replay import replay_tenants
        from spark_bagging_tpu.telemetry import workload as wmod

        w = wmod.synthetic_workload(
            rate_rps=200.0, duration_s=0.3, seed=112, width=8,
            bucket_bounds=(8, 32),
        )
        kwargs = dict(n_tenants=6, residency_capacity=2, zipf_s=1.8,
                      seed=112, min_bucket_rows=8, bucket_max_rows=32)
        j1 = replay_tenants(w, **kwargs)["tenants"]["journey"]
        j2 = replay_tenants(w, **kwargs)["tenants"]["journey"]
        assert j1 == j2
        assert j1["verdicts"].get("restore-absorbed", 0) > 0
        assert j1["verdicts"].get("wfq-starved", 0) > 0
        assert j1["requests"] == sum(
            acc["requests"]
            for acc in j1["stage_ms_by_tenant"].values()
        )
        for entry in j1["tail"]:
            assert entry["verdict"] in perf.VERDICTS


def test_zz_journey_suite_under_budget(_module_clock):
    """Tier-1 allowance for this module (the ratchet discipline): two
    tiny in-process drills plus unit coverage."""
    elapsed = time.perf_counter() - _module_clock
    assert elapsed < 40.0, (
        f"tests/test_journey.py took {elapsed:.1f}s; move the "
        "offender to -m slow or shrink it"
    )
