"""sklearn ecosystem integration [SURVEY §3.4]: the reference's promise
is that bagging is a drop-in Spark ML ``Estimator`` composing with
``Pipeline``; the TPU build keeps the analogous promise for the sklearn
protocol — Pipeline stages, ``clone``, grid search, nested params."""

import numpy as np
import pytest
from sklearn.base import clone as sk_clone
from sklearn.datasets import load_breast_cancer, load_diabetes
from sklearn.model_selection import GridSearchCV
from sklearn.pipeline import Pipeline
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    LogisticRegression,
)


@pytest.fixture(scope="module")
def cancer():
    X, y = load_breast_cancer(return_X_y=True)
    return X.astype(np.float32), y


def test_pipeline_stage(cancer):
    X, y = cancer
    pipe = Pipeline(
        [
            ("scale", StandardScaler()),
            ("bag", BaggingClassifier(n_estimators=8, seed=0)),
        ]
    )
    pipe.fit(X, y)
    assert pipe.score(X, y) > 0.95
    proba = pipe.predict_proba(X[:16])
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-4)


def test_pipeline_regressor():
    X, y = load_diabetes(return_X_y=True)
    pipe = Pipeline(
        [
            ("scale", StandardScaler()),
            ("bag", BaggingRegressor(n_estimators=16, seed=0)),
        ]
    )
    pipe.fit(X.astype(np.float32), y.astype(np.float32))
    assert pipe.score(X.astype(np.float32), y) > 0.4


def test_sklearn_clone_compat(cancer):
    est = BaggingClassifier(
        base_learner=LogisticRegression(l2=0.01, max_iter=7),
        n_estimators=5, max_samples=0.8, seed=3,
    )
    c = sk_clone(est)
    assert c is not est
    assert c.n_estimators == 5
    assert c.max_samples == 0.8
    assert c.base_learner.l2 == 0.01
    assert not hasattr(c, "ensemble_")


def test_nested_param_get_set():
    est = BaggingClassifier(
        base_learner=LogisticRegression(l2=0.01), n_estimators=4
    )
    params = est.get_params()
    assert params["base_learner__l2"] == 0.01
    est.set_params(base_learner__l2=0.5, n_estimators=9)
    assert est.base_learner.l2 == 0.5
    assert est.n_estimators == 9
    with pytest.raises(ValueError, match="Invalid parameter"):
        est.set_params(no_such_param=1)


@pytest.mark.slow  # ~5s [PR 11 budget offset]: full sklearn GridSearchCV sweep (many refits); get/set_params and cross_val_score compatibility stay tier-1
def test_grid_search(cancer):
    X, y = cancer
    X = StandardScaler().fit_transform(X).astype(np.float32)
    grid = GridSearchCV(
        BaggingClassifier(
            base_learner=LogisticRegression(max_iter=8), seed=0
        ),
        {"n_estimators": [2, 4], "base_learner__l2": [1e-3, 1e-1]},
        cv=2,
    )
    grid.fit(X[:200], y[:200])
    assert grid.best_score_ > 0.9
    assert set(grid.best_params_) == {"n_estimators", "base_learner__l2"}


def test_cross_val_score(cancer):
    """sklearn cross-validation over the estimator (Pipeline-style
    composition promise [SURVEY §3.4])."""
    from sklearn.model_selection import cross_val_score

    X, y = cancer
    X = StandardScaler().fit_transform(X).astype(np.float32)
    scores = cross_val_score(
        BaggingClassifier(n_estimators=4, seed=0), X, y, cv=3
    )
    assert scores.shape == (3,)
    assert scores.mean() > 0.9


def test_calibration_and_metrics_interop(cancer):
    """decision_function/predict_proba feed sklearn metrics directly."""
    from sklearn.metrics import log_loss, roc_auc_score

    X, y = cancer
    X = StandardScaler().fit_transform(X).astype(np.float32)
    clf = BaggingClassifier(n_estimators=8, seed=0).fit(X, y)
    auc = roc_auc_score(y, clf.decision_function(X))
    assert auc > 0.99
    assert log_loss(y, clf.predict_proba(X)) < 0.2
