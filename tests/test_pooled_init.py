"""The pooled warm start (LogisticRegression ``init="pooled"``): one
shared unweighted solve per ensemble, per-replica refinement from it.

Why this is sound: each replica's weighted objective is convex with a
unique optimum, so the init changes the solver's path, not its
destination — verified here by running both inits to convergence. The
payoff is fewer per-replica Newton iterations at equal-or-better
ensemble accuracy (the headline's dominant cost) [BASELINE.md].
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import BaggingClassifier, LogisticRegression
from spark_bagging_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def breast_cancer():
    X, y = load_breast_cancer(return_X_y=True)
    return StandardScaler().fit_transform(X).astype(np.float32), y


def _clf(init, max_iter, **kw):
    lr = LogisticRegression(l2=1e-3, max_iter=max_iter, precision="high",
                            init=init)
    return BaggingClassifier(base_learner=lr, n_estimators=16, seed=0, **kw)


class TestPooledInit:
    @pytest.mark.slow  # [PR 14 pyramid] ~2.6s convergence soak; one-pooled-iter==three-cold-iters stays tier-1
    def test_same_optimum_at_convergence(self, breast_cancer):
        """Convexity check: both inits converge to the same predictions
        when given enough iterations."""
        X, y = breast_cancer
        a = _clf("zeros", 25).fit(X, y)
        b = _clf("pooled", 25).fit(X, y)
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), atol=2e-3
        )

    def test_one_pooled_iter_matches_three_cold_iters(self, breast_cancer):
        """The headline lever: 1 refinement iteration from the pooled
        start reaches (here: beats) 3 iterations from zeros."""
        X, y = breast_cancer
        cold3 = _clf("zeros", 3).fit(X, y).score(X, y)
        warm1 = _clf("pooled", 1).fit(X, y).score(X, y)
        assert warm1 >= cold3 - 1e-9

    def test_subspaced_replicas_gather_pooled_rows(self, breast_cancer):
        X, y = breast_cancer
        clf = _clf("pooled", 1, max_features=0.5).fit(X, y)
        assert clf.score(X, y) > 0.9
        # subspace width must match the gathered pooled rows
        assert clf.estimators_features_.shape[1] == X.shape[1] // 2

    @pytest.mark.slow  # [PR 14 pyramid] ~4.2s sharded optimum soak; pooled-iter-equivalence contract stays tier-1
    def test_sharded_pooled_reaches_zeros_init_optimum(self, breast_cancer):
        """Under data sharding each shard draws its own bootstrap
        stream (documented: the realized bootstrap depends on the mesh
        layout), so sharded-vs-unsharded predictions differ by
        realization for ANY init. The pooled-init invariant that must
        hold is: on the SAME mesh (same realized bootstraps), pooled
        and zeros inits converge to the same optima — the pooled solve
        is replicated correctly across shards (psum'd row stats)."""
        X, y = breast_cancer
        mesh = make_mesh(data=2)
        a = _clf("zeros", 25, mesh=mesh).fit(X, y)
        b = _clf("pooled", 25, mesh=mesh).fit(X, y)
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), atol=2e-3
        )

    def test_oob_with_pooled_init(self, breast_cancer):
        X, y = breast_cancer
        clf = _clf("pooled", 1, oob_score=True).fit(X, y)
        assert clf.oob_score_ > 0.9

    @pytest.mark.parametrize("impl,row_tile", [
        # [PR 14 pyramid] the packed rung (~2.8s) is a ladder-sweep
        # soak: packed-vs-blocked parity stays tier-1 in test_learners;
        # the pallas rung stays tier-1 (pre-existing Pallas-on-CPU
        # failure set must remain visible, unchanged)
        pytest.param("packed", 128, marks=pytest.mark.slow),
        ("pallas", None),
    ])
    def test_pooled_under_every_hessian_impl(self, breast_cancer, impl,
                                             row_tile):
        """The sweep grid pairs pooled init with every Hessian ladder
        rung; each must reproduce the blocked+pooled predictions
        (pallas runs in interpreter mode off-TPU)."""
        X, y = breast_cancer
        def clf(impl, rt):
            lr = LogisticRegression(l2=1e-3, max_iter=1, init="pooled",
                                    precision="high", hessian_impl=impl,
                                    row_tile=rt)
            return BaggingClassifier(base_learner=lr, n_estimators=8,
                                     seed=0).fit(X, y)
        np.testing.assert_allclose(
            clf(impl, row_tile).predict_proba(X),
            clf("blocked", None).predict_proba(X), atol=2e-3,
        )

    def test_warm_start_grows_pooled_ensembles(self, breast_cancer):
        """bagging-level warm_start adds replicas; the pooled solve is
        re-derived deterministically, so grown ensembles keep working."""
        X, y = breast_cancer
        lr = LogisticRegression(l2=1e-3, max_iter=1, precision="high",
                                init="pooled")
        clf = BaggingClassifier(base_learner=lr, n_estimators=8, seed=0,
                                warm_start=True).fit(X, y)
        clf.n_estimators = 16
        clf.fit(X, y)
        assert clf.n_estimators_ == 16
        assert clf.score(X, y) > 0.95

    def test_params_roundtrip_and_validation(self):
        lr = LogisticRegression(init="pooled", pooled_iter=7)
        p = lr.get_params()
        assert p["init"] == "pooled" and p["pooled_iter"] == 7
        lr2 = LogisticRegression(**p)
        assert lr == lr2 and hash(lr) == hash(lr2)
        with pytest.raises(ValueError, match="init must be"):
            LogisticRegression(init="warm")

    @pytest.mark.slow  # [PR 14 pyramid] ~3.7s GLM optimum soak; pooled-iter-equivalence contract stays tier-1
    def test_glm_pooled_matches_cold_optimum(self):
        """PooledStartMixin on IRLS: poisson/log deviance is convex in
        beta, so both inits converge to the same fit."""
        from spark_bagging_tpu import BaggingRegressor
        from spark_bagging_tpu.models.glm import GeneralizedLinearRegression

        rng = np.random.default_rng(0)
        X = rng.normal(size=(400, 6)).astype(np.float32)
        beta = rng.normal(size=6).astype(np.float32) * 0.4
        y = rng.poisson(np.exp(X @ beta)).astype(np.float32)

        def reg(init, mi):
            glm = GeneralizedLinearRegression(family="poisson",
                                              max_iter=mi, init=init)
            return BaggingRegressor(base_learner=glm, n_estimators=8,
                                    seed=0).fit(X, y)
        a, b = reg("zeros", 25), reg("pooled", 25)
        np.testing.assert_allclose(a.predict(X), b.predict(X), rtol=2e-3)
        warm = reg("pooled", 2)
        # 2 warm IRLS iters land within a few percent of converged
        np.testing.assert_allclose(
            warm.predict(X), a.predict(X), rtol=0.05
        )

    def test_glm_pooled_rejects_nonconvex_links(self):
        from spark_bagging_tpu.models.glm import GeneralizedLinearRegression

        with pytest.raises(ValueError, match="default link"):
            GeneralizedLinearRegression(family="gaussian", link="log",
                                        init="pooled")
        # the default link spelled explicitly stays allowed
        GeneralizedLinearRegression(family="poisson", link="log",
                                    init="pooled")

    @pytest.mark.slow  # ~7s [PR 11 budget offset]: SVC pooled-vs-cold accuracy sweep; pooled-init optimum parity stays tier-1 via the GLM/logistic variants
    def test_svc_pooled_matches_cold_accuracy(self, breast_cancer):
        from spark_bagging_tpu.models.svm import LinearSVC

        X, y = breast_cancer
        def clf(init, mi):
            svc = LinearSVC(max_iter=mi, init=init)
            return BaggingClassifier(base_learner=svc, n_estimators=8,
                                     seed=0).fit(X, y)
        cold = clf("zeros", 8).score(X, y)
        warm = clf("pooled", 2).score(X, y)
        assert warm >= cold - 0.01

    def test_default_init_is_pooled(self):
        """The shipping default: the on-chip sweep measured pooled at
        2.6x equal-accuracy over zeros (305.8 vs 117.7 fits/s,
        benchmarks/tune_headline.json), so LogisticRegression defaults
        to the measured winner. Reverting this default must fail HERE,
        not in a zeros-path test."""
        lr = LogisticRegression()
        assert lr.init == "pooled"
        assert lr.uses_pooled_init is True

    def test_zeros_init_prepared_stays_none(self, breast_cancer):
        """init='zeros' (opted into explicitly; the default is pooled)
        must not pay the pooled solve: prepared state stays None
        through the engine."""
        lr = LogisticRegression(init="zeros")
        assert lr.uses_pooled_init is False
        assert lr.gather_subspace(None, jnp.arange(3)) is None
        assert jax.tree_util.tree_all(
            jax.tree_util.tree_map(
                lambda a: bool(jnp.all(a == 0.0)),
                lr.initial_params(jax.random.PRNGKey(0), 4, 3, None),
            )
        )
