"""Cross-learner property battery: every family must satisfy the same
invariants on randomized inputs — finite params/scores, seed
determinism, zero-weight-row neutrality, score shape contracts
[SURVEY §4 statistical-test strategy, generalized]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_bagging_tpu.models import (
    AFTSurvivalRegression,
    BernoulliNB,
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    FMClassifier,
    FMRegressor,
    GBTClassifier,
    GBTRegressor,
    GaussianNB,
    GeneralizedLinearRegression,
    IsotonicRegression,
    LinearRegression,
    LinearSVC,
    LogisticRegression,
    MLPClassifier,
    MLPRegressor,
    MultinomialNB,
)

KEY = jax.random.key(42)


def _soak(learner):
    """[PR 14 pyramid] the heavyweight zoo entries (1.5-5s per fuzz
    test each) carry the slow mark: the INVARIANTS stay continuously
    enforced in tier-1 by the cheap representatives below (plain
    logistic, the NBs, linear/GLM regressors), and the
    heavy families keep full fuzz coverage in the slow tier plus
    their own dedicated suites."""
    return pytest.param(learner, marks=pytest.mark.slow)


CLASSIFIERS = [
    LogisticRegression(max_iter=4),
    _soak(LogisticRegression(max_iter=1, init="pooled")),
    _soak(LinearSVC(max_iter=4)),
    _soak(LinearSVC(max_iter=2, init="pooled")),
    _soak(DecisionTreeClassifier(max_depth=3, n_bins=8)),
    _soak(MLPClassifier(hidden=8, max_iter=30)),
    GaussianNB(),
    MultinomialNB(),
    BernoulliNB(),
    _soak(FMClassifier(factor_size=2, max_iter=30)),
    _soak(GBTClassifier(n_rounds=4, max_depth=2, n_bins=8)),
]
REGRESSORS = [
    # aux=None ⇒ fully-observed Weibull regression (positive y required
    # — _reg_data guarantees it)
    _soak(AFTSurvivalRegression(max_iter=30)),
    LinearRegression(),
    GeneralizedLinearRegression(family="gaussian"),
    _soak(GeneralizedLinearRegression(family="poisson", max_iter=5)),
    _soak(GeneralizedLinearRegression(family="poisson", max_iter=2,
                                      init="pooled")),
    # [PR 17 budget offset] tree/isotonic regressors move to the slow
    # zoo: both have dedicated tier-1 suites (tests/test_tree.py
    # regressor contracts, tests/test_isotonic.py) enforcing the same
    # invariants on their own data shapes
    _soak(DecisionTreeRegressor(max_depth=3, n_bins=8)),
    _soak(IsotonicRegression(n_bins=16)),
    _soak(MLPRegressor(hidden=8, max_iter=30)),
    _soak(FMRegressor(factor_size=2, max_iter=30)),
    _soak(GBTRegressor(n_rounds=4, max_depth=2, n_bins=8)),
]


def _cls_data(rng, n=80, d=5, C=3):
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, C, n).astype(np.int32)
    y[:C] = np.arange(C)  # every class present
    if rng.random() < 0.3:
        X[:, rng.integers(0, d)] = 1.5  # constant feature
    return jnp.asarray(np.abs(X)), jnp.asarray(y)  # nonneg: MNB-safe


def _reg_data(rng, n=80, d=5):
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = np.abs(X[:, 0] + 0.1 * rng.normal(size=n)).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y + 0.1)  # positive: GLM-safe


@pytest.mark.parametrize(
    "learner", CLASSIFIERS, ids=lambda l: type(l).__name__
)
def test_classifier_invariants(learner):
    C = 3 if type(learner).__name__ != "GBTClassifier" or True else 3
    for trial in range(4):
        rng = np.random.default_rng(trial)
        Xj, yj = _cls_data(rng)
        w = jnp.asarray(rng.poisson(1.0, len(yj)), jnp.float32)
        w = w.at[:3].set(1.0)  # anchor rows keep every class weighted
        params, aux = learner.fit_from_init(KEY, Xj, yj, w, 3)
        leaves = jax.tree.leaves(params)
        assert all(np.isfinite(np.asarray(p)).all() for p in leaves), (
            type(learner).__name__, trial)
        scores = learner.predict_scores(params, Xj)
        assert scores.shape == (len(yj), 3)
        assert np.isfinite(np.asarray(scores)).all()
        assert np.isfinite(float(aux["loss"]))
        # determinism: same inputs, same key -> identical fit
        params2, _ = learner.fit_from_init(KEY, Xj, yj, w, 3)
        for a, b in zip(leaves, jax.tree.leaves(params2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "learner", REGRESSORS, ids=lambda l: type(l).__name__
)
def test_regressor_invariants(learner):
    for trial in range(4):
        rng = np.random.default_rng(100 + trial)
        Xj, yj = _reg_data(rng)
        w = jnp.asarray(rng.poisson(1.0, len(yj)) + (rng.random(len(yj)) < 0.05),
                        jnp.float32)
        params, aux = learner.fit_from_init(KEY, Xj, yj, w, 1)
        assert all(
            np.isfinite(np.asarray(p)).all()
            for p in jax.tree.leaves(params)
        ), (type(learner).__name__, trial)
        pred = learner.predict_scores(params, Xj)
        assert pred.shape == (len(yj),)
        assert np.isfinite(np.asarray(pred)).all()
        assert np.isfinite(float(aux["loss"]))


@pytest.mark.parametrize(
    "learner", CLASSIFIERS, ids=lambda l: type(l).__name__
)
def test_zero_weight_rows_are_inert(learner):
    """Adding rows with weight 0 must not change the fit — THE
    correctness property Poisson bagging rests on
    [SURVEY §7 hard-part 2]."""
    rng = np.random.default_rng(7)
    # signal-driven labels: binned learners re-derive (unweighted)
    # quantile edges when rows are appended, so only a learnable
    # boundary gives stable predictions to compare
    X = np.abs(rng.normal(size=(60, 5))).astype(np.float32)
    Xj = jnp.asarray(X)
    yj = jnp.asarray(X[:, :3].argmax(1).astype(np.int32))
    w = jnp.ones(60, jnp.float32)
    base, _ = learner.fit_from_init(KEY, Xj, yj, w, 3)
    # append junk rows at weight zero — drawn from the same range so
    # the (documented, unweighted) quantile edges barely move and the
    # test isolates the WEIGHTED statistics' inertness
    Xz = jnp.concatenate([Xj, Xj[:20] * 1.01])
    yz = jnp.concatenate([yj, (yj[:20] + 1) % 3])
    wz = jnp.concatenate([w, jnp.zeros(20, jnp.float32)])
    aug, _ = learner.fit_from_init(KEY, Xz, yz, wz, 3)
    name = type(learner).__name__
    if name in ("DecisionTreeClassifier", "GBTClassifier"):
        # binned learners derive (unweighted, documented) quantile
        # edges from ALL rows, so appending rows shifts the edge grid
        # regardless of weights. Pin the edges through the prepared
        # hook (fused impl: prepared = edges only, row-count free) —
        # with identical binning, zero-weight rows must be FULLY inert
        pinned = learner.clone().set_params(split_impl="fused")
        prep = pinned.prepare(Xj)
        base, _ = pinned.fit_from_init(KEY, Xj, yj, w, 3, prepared=prep)
        aug, _ = pinned.fit_from_init(
            KEY, Xz, yz, wz, 3, prepared=prep
        )
    for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(aug)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
            err_msg=name,
        )
