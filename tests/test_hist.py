"""Fused (Pallas) split-search kernel vs the dense path [SURVEY §7.7].

Runs in interpreter mode on the CPU fake-device backend; the same
kernel compiles natively on TPU (validated in the TPU drive)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_bagging_tpu import BaggingClassifier, BaggingRegressor
from spark_bagging_tpu.models import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
)
from spark_bagging_tpu.ops.hist import binned_left_stats
from spark_bagging_tpu.utils.datasets import (
    make_classification,
    make_regression,
)


def _dense_ref(X, edges, node, S, N):
    n, F = X.shape
    B = edges.shape[1]
    K = S.shape[1]
    T = (X[:, :, None] <= edges[None]).astype(np.float32).reshape(n, F * B)
    R = (
        np.eye(N, dtype=np.float32)[node][:, :, None] * S[:, None, :]
    ).reshape(n, N * K)
    return (T.T @ R).reshape(F, B, N, K)


@pytest.mark.parametrize(
    "n,F,B,N,K", [(700, 13, 8, 4, 3), (512, 8, 16, 1, 2), (130, 3, 4, 8, 7)]
)
def test_kernel_matches_dense_reference(n, F, B, N, K):
    rng = np.random.default_rng(n)
    X = rng.standard_normal((n, F)).astype(np.float32)
    edges = np.sort(rng.standard_normal((F, B - 1)), axis=1).astype(
        np.float32
    )
    edges = np.concatenate(
        [edges, np.full((F, 1), np.inf, np.float32)], axis=1
    )
    node = rng.integers(0, N, n).astype(np.int32)
    S = rng.poisson(1.0, (n, K)).astype(np.float32)
    got = np.asarray(
        binned_left_stats(
            jnp.asarray(X), jnp.asarray(edges), jnp.asarray(node),
            jnp.asarray(S), n_nodes=N, interpret=True,
        )
    )
    np.testing.assert_array_equal(got, _dense_ref(X, edges, node, S, N))


def test_kernel_vmaps_over_replicas():
    rng = np.random.default_rng(1)
    n, F, B, N, K, R = 300, 5, 8, 4, 3, 4
    X = jnp.asarray(rng.standard_normal((n, F)), jnp.float32)
    edges = np.sort(rng.standard_normal((F, B - 1)), axis=1).astype(
        np.float32
    )
    edges = jnp.asarray(
        np.concatenate([edges, np.full((F, 1), np.inf, np.float32)], axis=1)
    )
    nodes = rng.integers(0, N, (R, n)).astype(np.int32)
    Ss = rng.poisson(1.0, (R, n, K)).astype(np.float32)
    got = np.asarray(
        jax.vmap(
            lambda nd, s: binned_left_stats(
                X, edges, nd, s, n_nodes=N, interpret=True
            )
        )(jnp.asarray(nodes), jnp.asarray(Ss))
    )
    for r in range(R):
        np.testing.assert_array_equal(
            got[r],
            _dense_ref(
                np.asarray(X), np.asarray(edges), nodes[r], Ss[r], N
            ),
        )


def test_fused_tree_equals_dense_tree_classifier():
    X, y = make_classification(400, 6, 3, seed=5)
    mu, s = X.mean(0), X.std(0) + 1e-8
    X = ((X - mu) / s).astype(np.float32)
    kw = dict(n_estimators=4, bootstrap=False, max_samples=1.0, seed=0)
    dense = BaggingClassifier(
        base_learner=DecisionTreeClassifier(
            max_depth=4, n_bins=8, split_impl="dense"
        ),
        **kw,
    ).fit(X, y)
    fused = BaggingClassifier(
        base_learner=DecisionTreeClassifier(
            max_depth=4, n_bins=8, split_impl="fused"
        ),
        **kw,
    ).fit(X, y)
    np.testing.assert_array_equal(
        np.asarray(dense.ensemble_["feature"]),
        np.asarray(fused.ensemble_["feature"]),
    )
    np.testing.assert_allclose(
        np.asarray(dense.ensemble_["threshold"]),
        np.asarray(fused.ensemble_["threshold"]),
    )
    np.testing.assert_allclose(
        dense.predict_proba(X), fused.predict_proba(X), rtol=1e-6
    )


def test_fused_tree_equals_dense_tree_regressor():
    X, y = make_regression(350, 5, seed=3)
    mu, s = X.mean(0), X.std(0) + 1e-8
    X = ((X - mu) / s).astype(np.float32)
    kw = dict(n_estimators=3, seed=1)
    dense = BaggingRegressor(
        base_learner=DecisionTreeRegressor(
            max_depth=3, n_bins=8, split_impl="dense"
        ),
        **kw,
    ).fit(X, y)
    fused = BaggingRegressor(
        base_learner=DecisionTreeRegressor(
            max_depth=3, n_bins=8, split_impl="fused"
        ),
        **kw,
    ).fit(X, y)
    np.testing.assert_array_equal(
        np.asarray(dense.ensemble_["feature"]),
        np.asarray(fused.ensemble_["feature"]),
    )
    np.testing.assert_allclose(
        dense.predict(X), fused.predict(X), rtol=1e-5
    )


def test_fused_with_feature_subspaces():
    X, y = make_classification(300, 8, 2, seed=9)
    clf = BaggingClassifier(
        base_learner=DecisionTreeClassifier(
            max_depth=3, n_bins=8, split_impl="fused"
        ),
        n_estimators=4, max_features=0.5, seed=0,
    ).fit(X, y)
    assert clf.subspaces_.shape == (4, 4)
    assert clf.score(X, y) > 0.7


def test_auto_resolves_dense_on_cpu():
    t = DecisionTreeClassifier()
    assert t._resolved_impl(100_000, 54) == "dense"


def test_invalid_split_impl_rejected():
    with pytest.raises(ValueError, match="split_impl"):
        DecisionTreeClassifier(split_impl="magic")
