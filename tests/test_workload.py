"""Workload capture & format [ISSUE 6]: the record half of
record→replay→report. Live capture off the serving arrival stream,
the versioned *.workload.jsonl roundtrip, seeded synthetic generators
(byte-identical per seed), and the SLO spec/verdict machinery the
replay gate evaluates."""

import json

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.telemetry import slo, workload
from spark_bagging_tpu.serving import EnsembleExecutor, MicroBatcher


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.reset()
    telemetry.enable()


@pytest.fixture(scope="module")
def executor():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(96, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    clf = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=4, seed=0,
    ).fit(X, y)
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32)
    ex.warmup()
    ex._test_X = X
    return ex


# -- live capture ------------------------------------------------------

def test_recorder_captures_live_arrival_stream(executor):
    X = executor._test_X
    rec = workload.WorkloadRecorder()
    rec.start()
    try:
        with MicroBatcher(executor, max_delay_ms=1) as b:
            futs = [b.submit(X[i:i + 2]) for i in range(12)]
            for f in futs:
                f.result(30)
    finally:
        wl = rec.stop()
    assert wl.n_requests == 12
    assert wl.total_rows == 24
    ts = [r.t for r in wl.requests]
    assert ts[0] == 0.0  # re-based to the first arrival
    assert ts == sorted(ts)
    # bucket attribution from the executor's ladder snapshot
    assert all(r.bucket == 8 for r in wl.requests)
    assert all(r.width == 6 for r in wl.requests)
    assert all(r.dtype == "float32" for r in wl.requests)
    # stopped recorder is detached: later traffic must not append
    with MicroBatcher(executor, max_delay_ms=1) as b:
        b.submit(X[:2]).result(30)
    assert rec.workload().n_requests == 12


def test_direct_recorder_visible_to_live_view():
    """A directly-constructed recorder (the documented alternative
    when the default is busy) must be visible to active() — and
    therefore to /debug/workload — while it records."""
    assert workload.active() is None
    rec = workload.WorkloadRecorder()
    rec.start()
    try:
        assert workload.active() is rec
    finally:
        rec.stop()
    assert workload.active() is None


def test_instance_restart_begins_fresh_session():
    """start() after stop() is a new session — entries, t0, epochs,
    and aggregates reset (the stale-resume hazard, instance API)."""
    rec = workload.WorkloadRecorder()
    rec.start()
    telemetry.emit_event({"kind": "serving_request", "rows": 5})
    wl1 = rec.stop()
    assert wl1.n_requests == 1
    assert rec.workload().n_requests == 1  # readable until restart
    rec.start()
    try:
        telemetry.emit_event({"kind": "serving_request", "rows": 7})
    finally:
        wl2 = rec.stop()
    assert wl2.n_requests == 1
    assert wl2.requests[0].rows == 7
    assert wl2.requests[0].t == 0.0
    assert rec.summary()["total_rows"] == 7


def test_arrival_events_do_not_flood_the_flight_ring(executor):
    """The flight recorder's forensic window must not ring the
    per-request arrival stream — at production rates it would evict
    the span/error context a dump exists to preserve. Both sinks see
    the stream; only the workload recorder keeps it."""
    from spark_bagging_tpu.telemetry import recorder as flight

    X = executor._test_X
    ring = flight.FlightRecorder(capacity=64)
    ring.arm()
    wrec = workload.WorkloadRecorder()
    wrec.start()
    try:
        with MicroBatcher(executor, max_delay_ms=1) as b:
            futs = [b.submit(X[i:i + 1]) for i in range(8)]
            for f in futs:
                f.result(30)
    finally:
        wl = wrec.stop()
        ring.disarm()
    assert wl.n_requests == 8
    assert ring.events(kind="serving_request") == []
    assert ring.events(kind="span")  # spans still ring


def test_recorder_ignores_nonarrival_events():
    rec = workload.WorkloadRecorder()
    rec.start()
    try:
        telemetry.emit_event({"kind": "serving_batch_error"})
        telemetry.emit_event({"kind": "span", "name": "x"})
        telemetry.emit_event({"kind": "serving_request", "rows": 3})
    finally:
        wl = rec.stop()
    assert wl.n_requests == 1
    assert wl.requests[0].rows == 3


def test_recorder_capacity_bounded_and_counted():
    rec = workload.WorkloadRecorder(capacity=8)
    rec.start()
    try:
        for i in range(20):
            telemetry.emit_event({"kind": "serving_request", "rows": i})
    finally:
        wl = rec.stop()
    assert wl.n_requests == 8
    assert [r.rows for r in wl.requests][-1] == 19  # newest kept
    assert rec.summary()["dropped"] == 12


def test_no_arrival_events_without_a_consumer(executor):
    """The cost contract: arrival events are built only for a sink
    that consumes them. An armed flight recorder alone — the standard
    serving deployment — must not flip the gate."""
    from spark_bagging_tpu.telemetry import recorder as flight

    X = executor._test_X
    assert not telemetry.arrival_events_wanted()
    ring = flight.FlightRecorder(capacity=64)
    ring.arm()
    try:
        assert telemetry.sinks_active()  # a sink, but not a consumer
        assert not telemetry.arrival_events_wanted()
        with MicroBatcher(executor, max_delay_ms=1) as b:
            b.submit(X[:2]).result(30)
    finally:
        ring.disarm()
    # a workload recorder started AFTER the traffic saw nothing, and
    # while recording it IS a consumer
    rec = workload.WorkloadRecorder()
    rec.start()
    try:
        assert telemetry.arrival_events_wanted()
    finally:
        assert rec.stop().n_requests == 0
    assert not telemetry.arrival_events_wanted()


def test_record_warns_when_telemetry_disabled():
    """A capture session opened while telemetry is off would silently
    stay empty — start() must say so."""
    telemetry.disable()
    try:
        rec = workload.WorkloadRecorder()
        with pytest.warns(RuntimeWarning, match="stay EMPTY"):
            rec.start()
        rec.stop()
    finally:
        telemetry.enable()


def test_default_recorder_record_stop_active():
    assert workload.active() is None
    rec = workload.record()
    try:
        assert workload.active() is rec
        with pytest.warns(RuntimeWarning, match="options"):
            workload.record(capacity=5)  # options on a LIVE default warn
        telemetry.emit_event({"kind": "serving_request", "rows": 1})
    finally:
        wl = workload.stop()
    assert workload.active() is None
    assert wl.n_requests == 1
    # stop() RETIRES the default: the next record() is a fresh capture
    # — no entries, t0 anchor, or epochs bleeding across sessions
    assert workload.stop() is None
    rec2 = workload.record()
    try:
        assert rec2 is not rec
        telemetry.emit_event({"kind": "serving_request", "rows": 2})
    finally:
        wl2 = workload.stop()
    assert wl2.n_requests == 1
    assert wl2.requests[0].t == 0.0
    # the INSTANCE-level stop() ends the session just as thoroughly:
    # record() must not hand the stale recorder back
    rec3 = workload.record()
    telemetry.emit_event({"kind": "serving_request", "rows": 3})
    rec3.stop()  # the natural call — it is public and returns the data
    rec4 = workload.record(capacity=64)  # options apply: fresh creation
    try:
        assert rec4 is not rec3
        assert rec4.capacity == 64
    finally:
        assert workload.stop().n_requests == 0


# -- format ------------------------------------------------------------

def test_save_load_roundtrip(tmp_path):
    wl = workload.synthetic_workload(
        "poisson", rate_rps=300, duration_s=0.2, seed=5, width=4,
        bucket_bounds=(8, 32),
    )
    path = wl.save(str(tmp_path / "w.workload.jsonl"))
    back = workload.load_workload(path)
    assert back.source == "synthetic"
    assert back.generator == "poisson"
    assert back.seed == 5
    assert [r.to_dict() for r in back.requests] == [
        r.to_dict() for r in wl.requests
    ]
    # header is the first line and declares the body truthfully
    first = json.loads(open(path).readline())
    assert first["kind"] == "workload_header"
    assert first["schema"] == workload.WORKLOAD_SCHEMA_VERSION
    assert first["n_requests"] == wl.n_requests


def test_load_rejects_bad_files(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text("")
    with pytest.raises(ValueError, match="empty"):
        workload.load_workload(str(p))
    p.write_text('{"kind": "nope"}\n')
    with pytest.raises(ValueError, match="workload_header"):
        workload.load_workload(str(p))
    p.write_text('{"kind": "workload_header", "schema": 999}\n')
    with pytest.raises(ValueError, match="schema"):
        workload.load_workload(str(p))
    # truncated body vs header count must be loud
    wl = workload.synthetic_workload(
        "poisson", rate_rps=200, duration_s=0.2, seed=1
    )
    full = wl.save(str(tmp_path / "full.jsonl"))
    lines = open(full).read().splitlines()
    (tmp_path / "torn.jsonl").write_text("\n".join(lines[:-2]) + "\n")
    with pytest.raises(ValueError, match="truncated"):
        workload.load_workload(str(tmp_path / "torn.jsonl"))


def test_epoch_assignment_marks_traffic_waves():
    reqs = [workload.WorkloadRequest(t=t, rows=1, width=2)
            for t in (0.0, 0.1, 0.2, 5.0, 5.1, 30.0)]
    workload.assign_epochs(reqs, gap_s=1.0)
    assert [r.epoch for r in reqs] == [0, 0, 0, 1, 1, 2]


# -- synthetic generators ----------------------------------------------

def test_synthetic_deterministic_per_seed():
    a = workload.synthetic_workload("bursty", rate_rps=100,
                                    duration_s=0.5, seed=9)
    b = workload.synthetic_workload("bursty", rate_rps=100,
                                    duration_s=0.5, seed=9)
    c = workload.synthetic_workload("bursty", rate_rps=100,
                                    duration_s=0.5, seed=10)
    assert [r.to_dict() for r in a.requests] == [
        r.to_dict() for r in b.requests
    ]
    assert [r.to_dict() for r in a.requests] != [
        r.to_dict() for r in c.requests
    ]


def test_poisson_rate_roughly_honored():
    wl = workload.synthetic_workload("poisson", rate_rps=1000,
                                     duration_s=1.0, seed=0)
    assert 800 <= wl.n_requests <= 1200  # ~4 sigma around 1000


def test_bursty_adds_bursts_on_top_of_base():
    base = workload.synthetic_workload("poisson", rate_rps=50,
                                       duration_s=1.0, seed=2)
    bursty = workload.synthetic_workload(
        "bursty", rate_rps=50, duration_s=1.0, seed=2,
        burst_every_s=0.25, burst_size=40,
    )
    assert bursty.n_requests >= base.n_requests + 4 * 40 - 40
    # a burst is a dense cluster: some 10ms window holds >= burst_size
    ts = np.array([r.t for r in bursty.requests])
    counts = [
        int(((ts >= t0) & (ts < t0 + 0.01)).sum())
        for t0 in np.arange(0.0, 1.0, 0.005)
    ]
    assert max(counts) >= 40


def test_diurnal_rate_swings():
    wl = workload.synthetic_workload(
        "diurnal", rate_rps=2000, duration_s=1.0, seed=4,
        diurnal_depth=0.9,
    )
    ts = np.array([r.t for r in wl.requests])
    # sin peaks in the first half-period and troughs in the second
    first = int(((ts >= 0.0) & (ts < 0.5)).sum())
    second = int((ts >= 0.5).sum())
    assert first > 2 * second


def test_rows_choices_and_bad_kind():
    wl = workload.synthetic_workload(
        "poisson", rate_rps=500, duration_s=0.3, seed=0,
        rows=(1, 2, 4),
    )
    assert {r.rows for r in wl.requests} <= {1, 2, 4}
    with pytest.raises(ValueError, match="unknown workload kind"):
        workload.synthetic_workload("square-wave")
    with pytest.raises(ValueError, match="rate_rps"):
        workload.synthetic_workload("poisson", rate_rps=0)


# -- SLO spec / verdicts -----------------------------------------------

def _report(**over):
    base = {
        "rps": 1000.0,
        "latency_ms": {"p50": 1.0, "p95": 2.0, "p99": 4.0},
        "padding": {"waste_rows_frac": 0.4, "waste_flops_frac": 0.3},
        "overloads": 0,
        "post_warmup_compiles": 0,
    }
    base.update(over)
    return base


def test_slo_spec_roundtrip_and_unknown_fields(tmp_path):
    spec = slo.SLOSpec(p99_ms=5.0, min_rps=100.0)
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec.to_dict()))
    back = slo.SLOSpec.load(str(p))
    assert back.to_dict() == spec.to_dict()
    with pytest.raises(ValueError, match="unknown SLO spec fields"):
        slo.SLOSpec.from_dict({"p42_ms": 1.0})


def test_evaluate_passes_and_fails_per_criterion():
    spec = slo.SLOSpec(p50_ms=2.0, p99_ms=5.0, min_rps=500,
                       max_padding_waste=0.5, max_overloads=0)
    res = slo.evaluate(spec, _report())
    assert res.ok, res.render()
    # FLOPs-weighted waste preferred over the row fraction
    (waste,) = [c for c in res.checks
                if c["name"].startswith("padding_waste")]
    assert waste["name"] == "padding_waste_flops_frac"
    assert waste["actual"] == 0.3

    bad = slo.evaluate(spec, _report(rps=100.0, overloads=3))
    assert not bad.ok
    assert {c["name"] for c in bad.failures} == {"rps", "overloads"}
    assert "SLO VIOLATION" in bad.render()


def test_evaluate_missing_value_fails_loudly():
    spec = slo.SLOSpec(p95_ms=1.0)
    res = slo.evaluate(spec, {"latency_ms": {}})
    (c,) = [x for x in res.checks if x["name"] == "latency_p95_ms"]
    assert not c["ok"] and c["actual"] is None


def test_baseline_compare_bands_and_digest():
    base = _report(workload_digest="wl1", output_digest="out1")
    good = _report(rps=900.0, workload_digest="wl1",
                   output_digest="out1")
    assert slo.compare_to_baseline(good, base).ok
    slow = _report(
        rps=400.0,
        latency_ms={"p50": 3.0, "p95": 6.0, "p99": 30.0},
        workload_digest="wl1", output_digest="out1",
    )
    res = slo.compare_to_baseline(slow, base)
    names = {c["name"] for c in res.failures}
    assert "rps_vs_baseline" in names
    assert "latency_p50_vs_baseline" in names
    # bitwise-determinism breach is its own failure
    mutant = _report(workload_digest="wl1", output_digest="outX")
    res = slo.compare_to_baseline(mutant, base)
    (dig,) = [c for c in res.checks
              if c["name"] == "output_digest_vs_baseline"]
    assert not dig["ok"]
    # different workloads: digests are not comparable, check skipped
    other = _report(workload_digest="wl2", output_digest="outX")
    assert not any(
        c["name"] == "output_digest_vs_baseline"
        for c in slo.compare_to_baseline(other, base).checks
    )
    # timed mode is documented non-deterministic: differing output
    # bytes there are expected, not a breach — check skipped
    timed = _report(mode="timed", workload_digest="wl1",
                    output_digest="outX")
    assert not any(
        c["name"] == "output_digest_vs_baseline"
        for c in slo.compare_to_baseline(timed, base).checks
    )
    # a different payload seed (or batcher config) is a different
    # EXPERIMENT, not a determinism breach — check skipped
    reseeded = _report(seed=1, workload_digest="wl1",
                       output_digest="outX")
    base_seeded = dict(base, seed=0)
    assert not any(
        c["name"] == "output_digest_vs_baseline"
        for c in slo.compare_to_baseline(reseeded, base_seeded).checks
    )
