"""Per-request distributed tracing [ISSUE 5]: trace/request identity
through the serving path, span linkage, timing breakdowns, the
flight recorder's failure dumps, and the disabled-mode cost contract.

The load-bearing property: EVERY served request — coalesced,
slab-split oversize, or in flight across a hot swap — must resolve to
a complete trace: ``future.trace.breakdown`` populated before the
future resolves, and the span log containing its linked
enqueue/batch/forward/scatter spans.
"""

import json
import threading
import time

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.telemetry import recorder, tracing
from spark_bagging_tpu.serving import MicroBatcher, ModelRegistry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.reset()
    telemetry.enable()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(128, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    return X, y


@pytest.fixture(scope="module")
def clf(data):
    X, y = data
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=4, seed=0,
    ).fit(X, y)


@pytest.fixture(scope="module")
def registry(clf):
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=True)
    return reg


# -- context mechanics -------------------------------------------------

def test_context_ids_and_span_nesting():
    ctx = tracing.request_context()
    assert ctx.trace_id and ctx.request_id.startswith("req-")
    with telemetry.capture() as run:
        with tracing.use(ctx):
            with telemetry.span("outer"):
                with telemetry.span("inner"):
                    pass
    inner, outer = run.spans("inner")[0], run.spans("outer")[0]
    assert inner["trace_id"] == outer["trace_id"] == ctx.trace_id
    assert inner["parent_id"] == outer["span_id"]
    assert "parent_id" not in outer
    json.dumps([inner, outer])  # ids must be JSONL-clean


def test_use_restores_previous_context():
    a, b = tracing.request_context(), tracing.request_context()
    with tracing.use(a):
        with tracing.use(b):
            assert tracing.current() is b
        assert tracing.current() is a
    assert tracing.current() is None


def test_annotate_accumulates_lists():
    ctx = tracing.request_context()
    with tracing.use(ctx):
        tracing.annotate(bucket=8)
        tracing.annotate(bucket=16)
    assert ctx.annotations["bucket"] == [8, 16]
    tracing.annotate(bucket=32)  # no context installed: no-op
    assert ctx.annotations["bucket"] == [8, 16]


# -- through the batcher -----------------------------------------------

def test_breakdown_populated_and_sums_to_total(registry, data):
    X, _ = data
    with registry.batcher("m", max_delay_ms=5) as b:
        fut = b.submit(X[:3])
        fut.result(30)
    tr = fut.trace
    bd = tr.breakdown
    for key in ("queue_ms", "batch_ms", "forward_ms", "total_ms",
                "batch_size", "bucket", "model_version"):
        assert key in bd, key
    # the breakdown partitions the request's life: admission wait plus
    # batch processing IS the total, and the device forward is inside
    # the batch segment
    assert bd["queue_ms"] + bd["batch_ms"] == pytest.approx(
        bd["total_ms"], rel=1e-6
    )
    assert 0 <= bd["forward_ms"] <= bd["batch_ms"]
    assert bd["bucket"] == 8
    assert bd["model_version"] == 1


def test_span_log_links_enqueue_batch_forward_scatter(registry, data):
    """The acceptance resolvability contract: from one future's
    trace_id, the span log yields the request's enqueue span (by
    trace_id) and the batch/forward/scatter spans that served it (by
    links), with forward parented under batch."""
    X, _ = data
    with telemetry.capture() as run:
        # the coalesced pipeline is the subject: pin the adaptive
        # direct path off (a lone submit would be served inline)
        with registry.batcher("m", max_delay_ms=5,
                              direct_dispatch=False) as b:
            fut = b.submit(X[:3])
            fut.result(30)
    tid = fut.trace.trace_id

    def linked(name):
        return [
            s for s in run.spans(name)
            if s.get("trace_id") == tid or tid in s.get("links", ())
        ]

    enq = linked("serving_enqueue")
    bat = linked("serving_batch")
    fwd = linked("serving_forward")
    sca = linked("serving_scatter")
    assert len(enq) == len(bat) == len(fwd) == len(sca) == 1
    assert enq[0]["trace_id"] == tid
    assert enq[0]["request_id"] == fut.trace.request_id
    # batch-level spans share ONE batch trace and link the request
    assert bat[0]["trace_id"] == fwd[0]["trace_id"]
    assert fwd[0]["parent_id"] == bat[0]["span_id"]
    assert fut.trace.breakdown["batch_trace_id"] == bat[0]["trace_id"]


def test_concurrent_clients_unique_ids_and_linkage(registry, data):
    """N threads submitting concurrently: every request gets a UNIQUE
    request_id/trace_id, a breakdown whose parts sum to ~its total,
    and resolvable batch linkage — even though many requests share
    one coalesced batch."""
    X, _ = data
    n_threads, per_thread = 8, 6
    futs: dict[int, list] = {i: [] for i in range(n_threads)}

    with telemetry.capture() as run:
        with registry.batcher(
            "m", max_delay_ms=20, max_queue=256,
            direct_dispatch=False,  # batch-linkage contract under test
        ) as b:
            def client(i):
                rng = np.random.default_rng(i)
                for _ in range(per_thread):
                    k = int(rng.integers(0, len(X) - 4))
                    futs[i].append(b.submit(X[k:k + 2]))

            threads = [
                threading.Thread(target=client, args=(i,))
                for i in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            all_futs = [f for fs in futs.values() for f in fs]
            for f in all_futs:
                f.result(30)

    traces = [f.trace for f in all_futs]
    assert len({t.trace_id for t in traces}) == len(traces)
    assert len({t.request_id for t in traces}) == len(traces)
    batch_spans = {
        s["span_id"]: s for s in run.spans("serving_batch")
    }
    for t in traces:
        bd = t.breakdown
        assert bd["queue_ms"] + bd["batch_ms"] == pytest.approx(
            bd["total_ms"], rel=1e-6
        )
        assert bd["total_ms"] >= 0
        # the batch that served this request recorded the link back
        served_by = [
            s for s in batch_spans.values()
            if t.trace_id in s.get("links", ())
        ]
        assert len(served_by) == 1, t.trace_id
    # enqueue spans: exactly one per request, correct identity
    enq_ids = {
        s["trace_id"] for s in run.spans("serving_enqueue")
    }
    assert enq_ids == {t.trace_id for t in traces}


def test_oversize_slab_split_traces_every_bucket(registry, data):
    """A request larger than max_batch_rows runs as slabs (full slabs
    at the top bucket, the tail re-bucketed to its own size); the
    breakdown records EVERY slab's bucket."""
    X, _ = data
    with registry.batcher("m", max_delay_ms=1) as b:
        fut = b.submit(X[:70])  # 70 rows -> slabs of 32 + 32 + 6
        out = fut.result(30)
    assert out.shape == (70, 2)
    # the 6-row tail pads to bucket 8, not the top bucket
    assert fut.trace.breakdown["bucket"] == [32, 32, 8]


def test_trace_survives_hot_swap(registry, clf, data):
    """Requests in flight across a swap stay resolvable and report the
    model_version that actually served them."""
    X, y = data
    clf2 = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=4, seed=1,
    ).fit(X, y)
    versions = set()
    with registry.batcher("m", max_delay_ms=1, max_queue=256) as b:
        stop = threading.Event()

        def client():
            while not stop.is_set():
                f = b.submit(X[:2])
                f.result(30)
                versions.add(f.trace.breakdown["model_version"])

        t = threading.Thread(target=client)
        t.start()
        v_before = registry.version("m")
        registry.swap("m", clf2)
        time.sleep(0.1)
        stop.set()
        t.join(30)
    assert versions <= {v_before, v_before + 1}
    assert registry.version("m") in versions  # post-swap traffic flowed
    registry.swap("m", clf)  # restore for sibling tests


def test_disabled_telemetry_mints_no_trace(registry, data):
    X, _ = data
    telemetry.disable()
    try:
        with registry.batcher("m", max_delay_ms=1) as b:
            fut = b.submit(X[:2])
            fut.result(30)
        assert fut.trace is None
    finally:
        telemetry.enable()


def test_disabled_tracing_hot_path_overhead():
    """The serving-side analog of the telemetry micro-benchmark: the
    per-request tracing hooks (current(), use(None)) must be
    attribute-read cheap when no context rides the thread."""
    telemetry.disable()
    n = 50_000
    t0 = time.perf_counter()
    for _ in range(n):
        with tracing.use(None):
            tracing.current()
            tracing.annotate(bucket=1)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 20e-6, f"{per_call * 1e6:.2f}us per disabled site"


def test_latency_histogram_carries_exemplar_trace(registry, data):
    X, _ = data
    with registry.batcher("m", max_delay_ms=1) as b:
        fut = b.submit(X[:2])
        fut.result(30)
    # the un-labeled series is the overall histogram (the path-labeled
    # twins added by direct dispatch carry no exemplars)
    (entry,) = [
        e for e in telemetry.registry().snapshot()
        if e["name"] == "sbt_serving_latency_seconds"
        and not e["labels"]
    ]
    exemplars = entry.get("exemplars")
    assert exemplars, "latency histogram should carry exemplars"
    assert any(
        ex["trace_id"] == fut.trace.trace_id for ex in exemplars
    )


# -- flight recorder ---------------------------------------------------

class _Flaky:
    task = "classification"
    n_features = 6
    classes_ = np.array([0, 1])

    def __init__(self, executor):
        self._executor = executor
        self.boom = True

    def forward(self, Xb):
        if self.boom:
            self.boom = False
            raise RuntimeError("injected fault")
        return self._executor.forward(Xb)


def test_batch_failure_produces_exactly_one_dump(
    registry, data, tmp_path
):
    """THE black-box contract: an induced batch failure writes exactly
    one flight dump, and the failing request's trace_id is resolvable
    inside it (trigger links + captured enqueue span)."""
    X, _ = data
    rec = recorder.FlightRecorder(dir=str(tmp_path), cooldown_s=60)
    rec.arm()
    try:
        flaky = _Flaky(registry.executor("m"))
        # worker-path incident flow under test; the direct path's
        # error delivery is covered in test_serving_fastpath.py
        with MicroBatcher(flaky, max_delay_ms=1, max_queue=16,
                          direct_dispatch=False) as b:
            bad = b.submit(X[:2])
            with pytest.raises(RuntimeError, match="injected"):
                bad.result(30)
            good = b.submit(X[:2])
            good.result(30)  # the worker survived the failed batch
    finally:
        rec.disarm()
    assert len(rec.dumps) == 1
    dump = json.loads(open(rec.dumps[0]).read())
    assert dump["trigger"]["kind"] == "serving_batch_error"
    assert bad.trace.trace_id in dump["trigger"]["links"]
    assert bad.trace.breakdown["error"].startswith("RuntimeError")
    captured = {
        e.get("trace_id") for e in dump["events"]
        if e.get("kind") == "span"
    }
    assert bad.trace.trace_id in captured  # its enqueue span is there
    assert any(
        m["name"] == "sbt_serving_batch_errors_total"
        for m in dump["metrics"]
    )
    assert {"held", "violations", "edges"} <= set(dump["locks"])


def test_swap_rejection_triggers_dump(registry, data, tmp_path):
    X, y = data
    rec = recorder.FlightRecorder(dir=str(tmp_path), cooldown_s=60)
    rec.arm()
    try:
        wrong = BaggingClassifier(n_estimators=2, seed=0).fit(
            X[:, :3], y
        )
        with pytest.raises(ValueError, match="feature width"):
            registry.swap("m", wrong)
    finally:
        rec.disarm()
    assert len(rec.dumps) == 1
    dump = json.loads(open(rec.dumps[0]).read())
    assert dump["trigger"]["kind"] == "swap_rejected"
    assert dump["trigger"]["model"] == "m"


def test_overload_burst_dumps_once(tmp_path):
    """Single sheds never dump (backpressure working as designed); a
    burst inside the window dumps exactly once (cooldown)."""
    rec = recorder.FlightRecorder(
        dir=str(tmp_path), burst_threshold=5, burst_window_s=5.0,
        cooldown_s=60,
    )
    rec.arm()
    try:
        for _ in range(3):
            telemetry.emit_event({"kind": "serving_overloaded"})
        assert rec.dumps == []
        for _ in range(10):
            telemetry.emit_event({"kind": "serving_overloaded"})
    finally:
        rec.disarm()
    assert len(rec.dumps) == 1
    assert (
        json.loads(open(rec.dumps[0]).read())["trigger"]["kind"]
        == "serving_overloaded"
    )


def test_failed_dump_releases_cooldown(tmp_path):
    """PR-5 edge path: a dump that fails to write (bad dir, full disk)
    must give back the cooldown stamp its trigger consumed — otherwise
    one transient I/O failure silences every further trigger of that
    kind for cooldown_s and the incident yields zero artifacts."""
    blocked = tmp_path / "blocked"
    blocked.write_text("a FILE where the dump dir should be")
    rec = recorder.FlightRecorder(dir=str(blocked), cooldown_s=300)
    rec.arm()
    try:
        with pytest.warns(RuntimeWarning, match="failed to write"):
            telemetry.emit_event({"kind": "swap_rejected", "model": "x"})
        assert rec.dumps == []
        # the disk "recovers"; the SAME kind re-triggers well inside
        # what would have been the cooldown window
        rec.dir = str(tmp_path / "ok")
        telemetry.emit_event({"kind": "swap_rejected", "model": "x"})
        assert len(rec.dumps) == 1
        # and the successful dump re-establishes a REAL cooldown
        telemetry.emit_event({"kind": "swap_rejected", "model": "x"})
        assert len(rec.dumps) == 1
    finally:
        rec.disarm()


def test_ring_buffer_is_bounded(tmp_path):
    rec = recorder.FlightRecorder(capacity=16, dir=str(tmp_path))
    rec.arm()
    try:
        for i in range(100):
            telemetry.emit_event({"kind": "noise", "i": i})
    finally:
        rec.disarm()
    events = rec.events(kind="noise")
    assert len(events) == 16
    assert events[-1]["i"] == 99  # newest kept, oldest evicted
