"""Real multi-process test: 2 OS processes x 2 virtual CPU devices each,
joined via ``jax.distributed`` (Gloo over loopback), fitting on ONE
global ``(data=2, replica=2)`` mesh that spans both processes.

This is the CI analog of a 2-host TPU pod [SURVEY §5 comms backend,
B:11] — the same ``initialize_distributed`` + ``global_put``/``to_host``
seams carry a real pod, with Gloo standing in for ICI/DCN the way the
reference's tests use ``local[*]`` to stand in for a Spark cluster
[SURVEY §4].
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import BaggingClassifier
from spark_bagging_tpu.parallel import make_mesh
from spark_bagging_tpu.parallel.compat import (
    HAS_MULTIPROCESS_CPU,
    HAS_SHARD_MAP,
    MULTIPROCESS_CPU_REASON,
)

pytestmark = [
    pytest.mark.skipif(
        not HAS_SHARD_MAP,
        reason="this jax build has no shard_map implementation "
               "(parallel/compat.py)",
    ),
    # the workers below stand a 2-process CPU Gloo pod in for a TPU
    # pod; on jax builds whose CPU backend cannot run multi-process
    # computations the capability sentinel turns what used to be 7
    # fixture-time XlaRuntimeError walls into skips with this reason
    pytest.mark.skipif(
        not HAS_MULTIPROCESS_CPU,
        reason=MULTIPROCESS_CPU_REASON,
    ),
]

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def worker_results(tmp_path_factory):
    """Run the 2-process fit once; yield both workers' result dicts."""
    out = str(tmp_path_factory.mktemp("mh") / "result")
    port = _free_port()
    env = dict(os.environ)
    # Parsed at interpreter start in the children (before their jax
    # import) — each worker sees exactly 2 local CPU devices.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_PLATFORMS", None)
    # Workers log to files, not PIPEs: an undrained pipe blocking one
    # worker's writes would stall it inside a collective and deadlock
    # the other past its timeout.
    logs = [open(f"{out}.log.{pid}", "w+") for pid in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), "2", str(port), out],
            env=env, stdout=log, stderr=log, text=True,
        )
        for pid, log in enumerate(logs)
    ]
    for p in procs:
        try:
            p.wait(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out (collective deadlock?)")
    for p, log in zip(procs, logs):
        log.seek(0)
        tail = log.read()[-2000:]
        log.close()
        assert p.returncode == 0, f"worker failed:\n{tail}"
    results = []
    for pid in range(2):
        with open(f"{out}.{pid}") as f:
            results.append(json.load(f))
    return results


def test_both_processes_agree(worker_results):
    """process_allgather must hand every process the same full result."""
    r0, r1 = worker_results
    assert r0["n_global_devices"] == r1["n_global_devices"] == 4
    assert r0["accuracy"] == pytest.approx(r1["accuracy"], abs=1e-9)
    assert r0["oob_score"] == pytest.approx(r1["oob_score"], abs=1e-9)
    np.testing.assert_allclose(
        r0["proba_head"], r1["proba_head"], rtol=1e-6, atol=1e-7
    )


def test_matches_single_process_mesh(worker_results):
    """Same (2, 2) mesh shape in ONE process (4 of the suite's 8 virtual
    devices) must reproduce the 2-process fit: the fold_in streams
    depend only on mesh shape, so only reduction order may differ."""
    import jax

    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    mesh = make_mesh(data=2, replica=2, devices=jax.devices()[:4])
    clf = BaggingClassifier(
        n_estimators=8, seed=1, mesh=mesh, max_features=0.8,
        oob_score=True,
    ).fit(X, y)
    r0 = worker_results[0]
    assert clf.score(X, y) == pytest.approx(r0["accuracy"], abs=0.01)
    assert clf.oob_score_ == pytest.approx(r0["oob_score"], abs=0.02)
    np.testing.assert_allclose(
        clf.predict_proba(X)[:16], r0["proba_head"], rtol=1e-3, atol=1e-4
    )


def test_multihost_stream_fit(worker_results):
    """fit_stream over the 2-process mesh: chunks global_put per shard,
    the pjit step's collectives ride the (Gloo) interconnect."""
    r0, r1 = worker_results
    assert r0["stream_accuracy"] == pytest.approx(
        r1["stream_accuracy"], abs=1e-9
    )
    assert r0["stream_accuracy"] > 0.9


def test_multihost_forest_fit(worker_results):
    """Tree growth (quantile prepare + per-split masks) over the
    2-process mesh trains to quality and both processes agree."""
    a, b = worker_results
    assert a["rf_accuracy"] == pytest.approx(b["rf_accuracy"], abs=1e-6)
    assert a["rf_accuracy"] > 0.9


def test_multihost_aft_aux_channel(worker_results):
    """The aux (censor) column shards over the process-spanning data
    axis like y; both processes converge to the same bagged AFT model
    and its predictions are positive survival times."""
    a, b = worker_results
    np.testing.assert_allclose(
        a["aft_pred_head"], b["aft_pred_head"], rtol=1e-6
    )
    assert (np.asarray(a["aft_pred_head"]) > 0).all()


def test_multihost_pooled_warm_start(worker_results):
    """The pooled warm start's shared solve psums row stats across the
    process-spanning data axis: both processes derive identical pooled
    starts (hence identical ensembles), and 1 refinement iteration
    trains to quality."""
    a, b = worker_results
    np.testing.assert_allclose(
        a["pooled_pred_head"], b["pooled_pred_head"], rtol=1e-6
    )
    assert a["pooled_accuracy"] == pytest.approx(
        b["pooled_accuracy"], abs=1e-6
    )
    assert a["pooled_accuracy"] > 0.95


def test_multihost_arrow_stream(worker_results):
    """File-I/O ingestion joined to real collectives: both processes
    stream an identical row-major Arrow file through fit_stream on the
    process-spanning mesh and must land the same ensemble (round 5)."""
    accs = [r["arrow_stream_accuracy"] for r in worker_results]
    if accs[0] is None:
        pytest.skip("pyarrow unavailable in workers")
    assert accs[0] == accs[1]
    assert accs[0] > 0.9
