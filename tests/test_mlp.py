"""MLP learner tests: convergence, weighting, minibatch determinism,
vmap-ability, ensemble + mesh integration [SURVEY §4, B:10]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_diabetes
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    MLPClassifier,
    MLPRegressor,
    make_mesh,
)

KEY = jax.random.key(0)


def _breast_cancer():
    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y, jnp.int32), X, y


def _two_moons(n=400, seed=0):
    """XOR-ish nonlinear problem a linear model cannot solve."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 2)).astype(np.float32)
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.int64)
    return X, y


class TestMLPClassifier:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.6s convergence quality soak; forward/param contracts stay tier-1
    def test_solves_xor(self):
        X, y = _two_moons()
        mlp = MLPClassifier(hidden=32, max_iter=400, lr=3e-3)
        params, aux = mlp.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(len(y)), 2,
        )
        acc = (
            np.asarray(mlp.predict_scores(params, jnp.asarray(X)).argmax(1))
            == y
        ).mean()
        assert acc > 0.95  # a linear model caps at ~0.5 here

    @pytest.mark.slow  # [PR 14 pyramid] ~1s real-data quality soak
    def test_breast_cancer(self):
        Xj, yj, X, y = _breast_cancer()
        mlp = MLPClassifier(hidden=32, max_iter=300, lr=3e-3)
        params, aux = mlp.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 2)
        acc = (np.asarray(mlp.predict_scores(params, Xj).argmax(1)) == y).mean()
        assert acc > 0.96
        curve = np.asarray(aux["loss_curve"])
        assert curve[-1] < curve[0]

    @pytest.mark.slow  # [PR 17 budget offset] ~2.4s minibatch soak; minibatch solver path stays exercised via test_property_fuzz MLP params; fullbatch contracts stay tier-1 here
    def test_minibatch_mode(self):
        Xj, yj, X, y = _breast_cancer()
        mlp = MLPClassifier(hidden=32, max_iter=400, batch_size=64, lr=3e-3)
        params, _ = mlp.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 2)
        acc = (np.asarray(mlp.predict_scores(params, Xj).argmax(1)) == y).mean()
        assert acc > 0.95

    def test_seed_determinism(self):
        X, y = _two_moons()
        mlp = MLPClassifier(hidden=8, max_iter=50, batch_size=32)
        a, _ = mlp.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32), jnp.ones(len(y)), 2
        )
        b, _ = mlp.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32), jnp.ones(len(y)), 2
        )
        np.testing.assert_allclose(np.asarray(a["W1"]), np.asarray(b["W1"]))

    def test_zero_weight_rows_ignored_fullbatch(self):
        X, y = _two_moons()
        # class-1 rows zero-weighted: the net must not predict class 1
        w = np.where(y == 1, 0.0, 1.0).astype(np.float32)
        mlp = MLPClassifier(hidden=16, max_iter=200, lr=3e-3)
        params, _ = mlp.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32), jnp.asarray(w), 2
        )
        pred = np.asarray(mlp.predict_scores(params, jnp.asarray(X)).argmax(1))
        assert (pred == 1).mean() < 0.02

    def test_invalid_activation_raises(self):
        with pytest.raises(ValueError, match="activation"):
            MLPClassifier(activation="sigmoidal")

    @pytest.mark.slow  # [PR 17 budget offset] ~2s batched-fit soak; vmapped MLP fits are exercised by every bagged MLP fit (fuzz zoo); seed determinism stays tier-1 here
    def test_vmap_over_replicas(self):
        X, y = _two_moons(200)
        mlp = MLPClassifier(hidden=8, max_iter=30)
        ws = jnp.asarray(
            np.random.default_rng(0).poisson(1.0, (4, len(y))).astype(np.float32)
        )
        keys = jax.vmap(lambda i: jax.random.fold_in(KEY, i))(jnp.arange(4))
        params, aux = jax.vmap(
            lambda k, w: mlp.fit_from_init(
                k, jnp.asarray(X), jnp.asarray(y, jnp.int32), w, 2
            )
        )(keys, ws)
        assert params["W1"].shape == (4, 2, 8)
        assert not np.allclose(
            np.asarray(params["W1"][0]), np.asarray(params["W1"][1])
        )


class TestMLPRegressor:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.3s convergence quality soak; regressor contracts stay tier-1
    def test_fits_nonlinear_function(self):
        rng = np.random.default_rng(0)
        X = rng.uniform(-2, 2, size=(500, 1)).astype(np.float32)
        y = np.sin(2 * X[:, 0]).astype(np.float32)
        mlp = MLPRegressor(hidden=64, max_iter=600, lr=1e-2, l2=1e-6)
        params, _ = mlp.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(500), 1
        )
        pred = np.asarray(mlp.predict_scores(params, jnp.asarray(X)))
        mse = ((pred - y) ** 2).mean()
        assert mse < 0.05  # var(y) ≈ 0.5 ⇒ this is a real fit

    @pytest.mark.slow  # [PR 14 pyramid] ~1s real-data quality soak
    def test_diabetes(self):
        X, y = load_diabetes(return_X_y=True)
        X = StandardScaler().fit_transform(X).astype(np.float32)
        y = ((y - y.mean()) / y.std()).astype(np.float32)
        mlp = MLPRegressor(hidden=16, max_iter=300, lr=3e-3)
        params, _ = mlp.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        pred = np.asarray(mlp.predict_scores(params, jnp.asarray(X)))
        r2 = 1 - ((pred - y) ** 2).sum() / (y**2).sum()
        assert r2 > 0.4


class TestMLPBagging:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.7s real-data quality soak; MLP fit invariants stay tier-1 via xor-free fast tests
    def test_bagged_mlps_breast_cancer(self):
        Xj, yj, X, y = _breast_cancer()
        clf = BaggingClassifier(
            base_learner=MLPClassifier(hidden=16, max_iter=150, lr=3e-3),
            n_estimators=10,
            seed=0,
        )
        clf.fit(X, y)
        assert clf.score(X, y) > 0.95
        proba = clf.predict_proba(X)
        np.testing.assert_allclose(proba.sum(1), 1.0, rtol=1e-4)

    @pytest.mark.slow  # [PR 14 pyramid] ~1.5s mesh integration soak; replica-mesh parity stays tier-1 generic
    def test_bagged_mlp_regressor_on_mesh(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 4)).astype(np.float32)
        y = (np.sin(X[:, 0]) + X[:, 1] ** 2).astype(np.float32)
        mesh = make_mesh(data=2)
        reg = BaggingRegressor(
            base_learner=MLPRegressor(hidden=16, max_iter=150, lr=1e-2),
            n_estimators=8,
            seed=0,
            mesh=mesh,
        )
        reg.fit(X, y)
        assert reg.score(X, y) > 0.5


@pytest.mark.slow  # [PR 14 pyramid] ~1.8s batch-size degenerate sweep; minibatch engine contracts stay tier-1
def test_full_batch_size_degenerates_to_exact_path():
    """batch_size >= n must use the exact full-batch branch, not
    with-replacement draws of n rows."""
    import jax

    from spark_bagging_tpu.models.mlp import MLPClassifier

    rng = np.random.default_rng(0)
    X = rng.normal(size=(60, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    k = jax.random.key(0)

    def fit(bs):
        m = MLPClassifier(hidden=4, max_iter=10, batch_size=bs)
        p = m.init_params(jax.random.key(1), 4, 2)
        return m.fit(p, jnp.asarray(X), jnp.asarray(y),
                     jnp.ones(60), k)

    pa, _ = fit(None)
    pb, _ = fit(60)      # == n
    pc, _ = fit(1000)    # > n
    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, c in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pc)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_mlp_rejects_nonpositive_max_iter():
    import pytest

    from spark_bagging_tpu.models import MLPClassifier

    with pytest.raises(ValueError, match="max_iter"):
        MLPClassifier(max_iter=0)
