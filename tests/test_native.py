"""Native C++ loader vs the pure-Python parsers [SURVEY §2b]."""

import numpy as np
import pytest

from spark_bagging_tpu.utils import native
from spark_bagging_tpu.utils.datasets import load_csv, parse_libsvm
from spark_bagging_tpu.utils.io import CSVChunks, LibsvmChunks


@pytest.fixture(scope="module")
def lib():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("native loader unavailable (no g++?)")
    return lib


@pytest.fixture(scope="module")
def svm_file(tmp_path_factory):
    rng = np.random.default_rng(0)
    path = tmp_path_factory.mktemp("d") / "data.svm"
    X = rng.standard_normal((53, 7)).astype(np.float32)
    y = rng.integers(0, 2, 53)
    with open(path, "w") as f:
        f.write("# leading comment\n\n")
        for i in range(53):
            # sparse-ify: drop ~half the entries
            feats = " ".join(
                f"{j + 1}:{X[i, j]:.6g}"
                for j in range(7)
                if (i + j) % 2 == 0
            )
            f.write(f"{y[i]} {feats}  # trailing comment\n")
    return str(path)


@pytest.fixture(scope="module")
def csv_file(tmp_path_factory):
    rng = np.random.default_rng(1)
    path = tmp_path_factory.mktemp("d") / "data.csv"
    data = rng.standard_normal((41, 5)).astype(np.float32)
    with open(path, "w") as f:
        f.write("a,b,c,d,label\n")
        for row in data:
            f.write(",".join(f"{v:.6g}" for v in row) + "\n")
    return str(path)


def _py_parse_libsvm(path, n_features=None, zero_based=False):
    """The pure-Python fallback body, bypassing the native fast path."""
    labels, rows, max_idx = [], [], -1
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            entries = {}
            for item in parts[1:]:
                idx_s, val_s = item.split(":")
                idx = int(idx_s) - (0 if zero_based else 1)
                entries[idx] = float(val_s)
                max_idx = max(max_idx, idx)
            rows.append(entries)
    d = n_features if n_features is not None else max_idx + 1
    X = np.zeros((len(rows), d), np.float32)
    for i, entries in enumerate(rows):
        for j, v in entries.items():
            if j < d:
                X[i, j] = v
    return X, np.asarray(labels, np.float32)


def test_native_libsvm_matches_python(lib, svm_file):
    Xn, yn = native.parse_libsvm_native(svm_file)
    Xp, yp = _py_parse_libsvm(svm_file)
    np.testing.assert_array_equal(Xn, Xp)
    np.testing.assert_array_equal(yn, yp)


def test_native_libsvm_n_features_override(lib, svm_file):
    Xn, _ = native.parse_libsvm_native(svm_file, n_features=3)
    Xp, _ = _py_parse_libsvm(svm_file, n_features=3)
    np.testing.assert_array_equal(Xn, Xp)


def test_native_csv_matches_numpy(lib, csv_file):
    Xn, yn = native.load_csv_native(csv_file, skip_header=True)
    data = np.genfromtxt(
        csv_file, delimiter=",", skip_header=1, dtype=np.float32
    )
    np.testing.assert_allclose(Xn, data[:, :-1], rtol=1e-6)
    np.testing.assert_allclose(yn, data[:, -1], rtol=1e-6)


def test_native_csv_label_col(lib, csv_file):
    Xn, yn = native.load_csv_native(
        csv_file, label_col=1, skip_header=True
    )
    data = np.genfromtxt(
        csv_file, delimiter=",", skip_header=1, dtype=np.float32
    )
    np.testing.assert_allclose(yn, data[:, 1], rtol=1e-6)
    np.testing.assert_allclose(
        Xn, np.delete(data, 1, axis=1), rtol=1e-6
    )


def test_public_parsers_use_native_transparently(svm_file, csv_file):
    # public API must give identical results whether or not the native
    # path kicked in
    X1, y1 = parse_libsvm(svm_file)
    X2, y2 = _py_parse_libsvm(svm_file)
    np.testing.assert_array_equal(X1, X2)
    np.testing.assert_array_equal(y1, y2)
    Xc, yc = load_csv(csv_file, skip_header=True)
    assert Xc.shape == (41, 4) and yc.shape == (41,)


def test_streaming_reader_matches_whole_file(lib, svm_file, csv_file):
    Xf, yf = parse_libsvm(svm_file, n_features=7)
    src = LibsvmChunks(svm_file, n_features=7, chunk_rows=10)
    parts = [(X[:n], y[:n]) for X, y, n in src.chunks()]
    np.testing.assert_array_equal(
        np.concatenate([p[0] for p in parts]), Xf
    )
    np.testing.assert_array_equal(
        np.concatenate([p[1] for p in parts]), yf
    )

    Xc, yc = load_csv(csv_file, skip_header=True)
    srcc = CSVChunks(csv_file, chunk_rows=7, skip_header=True)
    partsc = [(X[:n], y[:n]) for X, y, n in srcc.chunks()]
    np.testing.assert_allclose(
        np.concatenate([p[0] for p in partsc]), Xc, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.concatenate([p[1] for p in partsc]), yc, rtol=1e-6
    )


def test_missing_file_raises_or_falls_back(lib):
    with pytest.raises(OSError):
        native.parse_libsvm_native("/nonexistent/file.svm")


@pytest.fixture(params=["native", "fallback"])
def maybe_native(request, monkeypatch):
    """Run a test twice: with the native lib and with the pure-Python
    fallback (native.get_lib forced to None)."""
    if request.param == "native":
        if native.get_lib() is None:
            pytest.skip("native loader unavailable (no g++?)")
    else:
        monkeypatch.setattr(native, "get_lib", lambda: None)
    return request.param


def test_out_of_range_label_col_rejected(csv_file, maybe_native):
    # native reader must refuse (heap-overflow guard), and the chunk
    # source must fail at construction for both native and fallback
    with pytest.raises(ValueError):
        native.NativeReader.open_csv(csv_file, 5, 10, label_col=5)
    with pytest.raises(ValueError):
        native.NativeReader.open_csv(csv_file, 5, 10, label_col=-6)
    for bad in (5, -6):
        with pytest.raises(ValueError):
            CSVChunks(csv_file, chunk_rows=7, label_col=bad,
                      skip_header=True)


def test_leading_blank_line_with_header(tmp_path, maybe_native):
    # a blank line before the header must not absorb skip_header:
    # dims and the streaming path must agree on row count in both the
    # native and the pure-Python implementation
    path = tmp_path / "blank.csv"
    with open(path, "w") as f:
        f.write("\na,b,label\n1,2,3\n4,5,6\n")
    src = CSVChunks(str(path), chunk_rows=10, skip_header=True)
    assert src.n_rows == 2
    chunks = [(X[:n], y[:n]) for X, y, n in src.chunks()]
    X = np.concatenate([c[0] for c in chunks])
    y = np.concatenate([c[1] for c in chunks])
    np.testing.assert_allclose(X, [[1, 2], [4, 5]])
    np.testing.assert_allclose(y, [3, 6])


def test_one_column_csv_rejected(tmp_path, maybe_native):
    path = tmp_path / "one.csv"
    with open(path, "w") as f:
        f.write("1\n2\n3\n")
    with pytest.raises(ValueError):
        CSVChunks(str(path), chunk_rows=2)


# ---------------------------------------------------------------------
# Differential fuzzing: native parser vs Python fallback on randomized
# inputs (the round-1 advisor found a heap overflow in exactly this
# loader — this guards the whole class of divergence bugs).
# ---------------------------------------------------------------------


def _random_csv(rng, path):
    """Random numeric CSV with the loader's documented edge cases:
    optional header, blank lines, varied column counts/precision."""
    n_rows = int(rng.integers(1, 40))
    n_cols = int(rng.integers(2, 9))
    header = bool(rng.integers(0, 2))
    data = rng.standard_normal((n_rows, n_cols)).astype(np.float32)
    data[rng.random(data.shape) < 0.1] = 0.0
    with open(path, "w") as f:
        if rng.integers(0, 3) == 0:
            f.write("\n")  # leading blank line
        if header:
            f.write(",".join(f"c{j}" for j in range(n_cols)) + "\n")
        for i, row in enumerate(data):
            f.write(",".join(f"{v:.7g}" for v in row) + "\n")
            if rng.integers(0, 10) == 0:
                f.write("\n")  # interior blank line
    return n_rows, n_cols, header


def test_fuzz_csv_native_matches_python(lib, tmp_path):
    from spark_bagging_tpu.utils import io as io_mod

    rng = np.random.default_rng(42)
    for trial in range(40):
        path = tmp_path / f"fuzz_{trial}.csv"
        n_rows, n_cols, header = _random_csv(rng, path)
        label_col = int(rng.integers(-n_cols, n_cols))
        chunk_rows = int(rng.integers(1, n_rows + 4))

        def collect(use_native, monkey=None):
            if not use_native:
                # force the pure-Python fallback: with no lib, both
                # _native_dims and NativeReader.open_csv return None
                monkey.setattr(native, "get_lib", lambda: None)
            src = io_mod.CSVChunks(
                str(path), chunk_rows=chunk_rows, label_col=label_col,
                skip_header=header,
            )
            Xs, ys = [], []
            for Xc, yc, n in src.chunks():
                Xs.append(Xc[:n])
                ys.append(yc[:n])
            return np.concatenate(Xs), np.concatenate(ys)

        Xn, yn = collect(True)
        with pytest.MonkeyPatch.context() as mp:
            Xp, yp = collect(False, mp)
        np.testing.assert_allclose(
            Xn, Xp, rtol=1e-6, atol=1e-7,
            err_msg=f"trial {trial} (rows={n_rows} cols={n_cols} "
                    f"header={header} label_col={label_col})",
        )
        np.testing.assert_allclose(yn, yp, rtol=1e-6, atol=1e-7)


def test_csv_chunks_supplied_n_rows_skips_counting(csv_file):
    """With n_rows supplied the init reads only the first line (for
    n_cols) — and the stream still yields identical chunks."""
    full = CSVChunks(csv_file, chunk_rows=7, skip_header=True)
    fast = CSVChunks(csv_file, chunk_rows=7, skip_header=True,
                     n_rows=full.n_rows)
    assert fast.n_rows == full.n_rows
    assert fast.n_features == full.n_features
    for (Xa, ya, na), (Xb, yb, nb) in zip(full.chunks(), fast.chunks()):
        np.testing.assert_array_equal(Xa, Xb)
        np.testing.assert_array_equal(ya, yb)
        assert na == nb


def test_embedded_nul_falls_back_to_python_parsers(tmp_path):
    """The C parsers work on NUL-terminated line buffers; a NUL byte
    must route the whole file to the Python fallback rather than
    silently truncating rows (round-4 audit)."""
    import warnings

    from spark_bagging_tpu.utils.datasets import load_csv
    from spark_bagging_tpu.utils.native import get_lib, load_csv_native

    if get_lib() is None:
        pytest.skip("native toolchain unavailable")
    p = tmp_path / "nul.csv"
    p.write_bytes(b"1.0,2.0,0\n3.0,4.5,1\n")
    clean = load_csv_native(str(p))
    assert clean is not None
    p.write_bytes(b"1.0,2.0,0\n3.0,4\x005,1\n")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert load_csv_native(str(p)) is None
    assert any("NUL" in str(x.message) for x in w)
    # ...and the public loader routes through the PYTHON parser, which
    # surfaces the malformed field visibly (error or NaN) instead of
    # silently training on a truncated row
    try:
        X, _ = load_csv(str(p))
    except Exception:
        pass
    else:
        assert np.isnan(X).any()


def test_label_only_libsvm_degrades_like_fallback(tmp_path):
    from spark_bagging_tpu.utils.datasets import parse_libsvm
    from spark_bagging_tpu.utils.native import get_lib

    if get_lib() is None:
        pytest.skip("native toolchain unavailable")
    p = tmp_path / "labels.svm"
    p.write_text("1\n0\n1\n")
    X, y = parse_libsvm(str(p))
    assert X.shape == (3, 0)
    np.testing.assert_array_equal(y, [1.0, 0.0, 1.0])
