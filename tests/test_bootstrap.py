"""Property tests for the bootstrap engine [SURVEY §4]: Poisson mean,
OOB fraction ~ e^-1, determinism under fold_in, subspace invariants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_bagging_tpu.ops import (
    bootstrap_weights,
    feature_subspaces,
    oob_mask,
    replica_keys,
)

KEY = jax.random.key(42)
IDS = jnp.arange(16)


def test_poisson_weights_mean_matches_ratio():
    w = bootstrap_weights(KEY, IDS, 4096, ratio=1.0)
    assert w.shape == (16, 4096)
    assert abs(float(w.mean()) - 1.0) < 0.02
    w2 = bootstrap_weights(KEY, IDS, 4096, ratio=0.5)
    assert abs(float(w2.mean()) - 0.5) < 0.02


def test_oob_fraction_is_about_exp_minus_one():
    w = bootstrap_weights(KEY, IDS, 8192, ratio=1.0)
    frac = float(oob_mask(w).mean())
    assert abs(frac - np.exp(-1)) < 0.01


def test_weights_deterministic_and_shard_invariant():
    w_all = bootstrap_weights(KEY, jnp.arange(8), 100)
    # Generating replicas 4..7 alone must reproduce rows 4..7 exactly —
    # the shard-local regeneration property.
    w_back = bootstrap_weights(KEY, jnp.arange(4, 8), 100)
    np.testing.assert_array_equal(np.asarray(w_all[4:]), np.asarray(w_back))


def test_replicas_are_distinct():
    w = bootstrap_weights(KEY, jnp.arange(4), 1000)
    assert not np.array_equal(np.asarray(w[0]), np.asarray(w[1]))


def test_without_replacement_exact_count():
    w = bootstrap_weights(KEY, IDS, 1000, ratio=0.6, replacement=False)
    counts = np.asarray(w.sum(axis=1))
    np.testing.assert_array_equal(counts, np.full(16, 600.0))
    assert set(np.unique(np.asarray(w))) <= {0.0, 1.0}


def test_without_replacement_full_ratio_is_all_ones():
    w = bootstrap_weights(KEY, IDS, 50, ratio=1.0, replacement=False)
    np.testing.assert_array_equal(np.asarray(w), np.ones((16, 50)))


def test_without_replacement_tiny_ratio_floors_at_one_row():
    # a positive ratio always selects >= 1 row (int max_samples=1 is
    # valid); only non-positive ratios are rejected
    w = np.asarray(
        bootstrap_weights(KEY, IDS, 100, ratio=0.001, replacement=False)
    )
    assert (w.sum(axis=1) == 1).all()
    with pytest.raises(ValueError):
        bootstrap_weights(KEY, IDS, 100, ratio=0.0, replacement=False)


def test_subspace_without_replacement_unique_and_in_range():
    idx = np.asarray(feature_subspaces(KEY, IDS, 20, 5))
    assert idx.shape == (16, 5)
    assert idx.min() >= 0 and idx.max() < 20
    for row in idx:
        assert len(set(row.tolist())) == 5


def test_subspace_degenerate_is_identity():
    idx = np.asarray(feature_subspaces(KEY, jnp.arange(3), 7, 7))
    np.testing.assert_array_equal(idx, np.tile(np.arange(7), (3, 1)))


def test_subspace_with_replacement_in_range():
    idx = np.asarray(
        feature_subspaces(KEY, IDS, 10, 30, replacement=True)
    )
    assert idx.shape == (16, 30)
    assert idx.min() >= 0 and idx.max() < 10


def test_subspace_stream_independent_of_row_stream():
    w = bootstrap_weights(KEY, IDS, 100)
    idx = feature_subspaces(KEY, IDS, 100, 10)
    # Row weights and feature draws for the same replica must differ
    # (independent fold_in streams).
    assert not np.array_equal(
        np.asarray(w[0, :10]), np.asarray(idx[0]).astype(np.float32)
    )


def test_replica_keys_fold_in():
    ks = replica_keys(KEY, jnp.arange(4))
    expected = jax.random.fold_in(KEY, 2)
    np.testing.assert_array_equal(
        jax.random.key_data(ks[2]), jax.random.key_data(expected)
    )


def test_subsample_count_rounds_exactly():
    """round(ratio·n) keeps an int max_samples exact through its
    count/n ratio representation (15/22 must select 15, not 14), and
    tiny ratios floor at one row instead of crashing."""
    import jax

    key = jax.random.key(0)
    from spark_bagging_tpu.ops.bootstrap import bootstrap_weights_one

    w = bootstrap_weights_one(key, 0, 22, ratio=15 / 22, replacement=False)
    assert int(np.asarray(w).sum()) == 15
    w1 = bootstrap_weights_one(key, 0, 49, ratio=1 / 49, replacement=False)
    assert int(np.asarray(w1).sum()) == 1


def test_nonpositive_ratio_rejected_both_branches():
    """Poisson(0) with replacement silently produced all-zero weights
    for every replica (round-4 audit) — both branches now reject."""
    from spark_bagging_tpu.ops.bootstrap import bootstrap_weights_one

    key = jax.random.key(0)
    for repl in (True, False):
        with pytest.raises(ValueError, match="positive"):
            bootstrap_weights_one(key, 0, 100, ratio=0.0, replacement=repl)


def test_row_stream_is_tagged():
    """Row draws derive via the tagged _ROW_STREAM fold — an untagged
    fold_in(key, replica_id) collided with the fit-stream base at
    replica_id 0xF17 = 3863 (round-4 audit)."""
    from spark_bagging_tpu.ops.bootstrap import (
        _ROW_STREAM,
        bootstrap_weights_one,
    )

    key = jax.random.key(7)
    w = bootstrap_weights_one(key, 3863, 64, ratio=1.0)
    manual_key = jax.random.fold_in(
        jax.random.fold_in(key, _ROW_STREAM), 3863
    )
    from spark_bagging_tpu.ops.bootstrap import poisson_counts

    np.testing.assert_array_equal(
        np.asarray(w), np.asarray(poisson_counts(manual_key, 1.0, 64))
    )
    # ...and the fit-stream base no longer shares its counter blocks
    from spark_bagging_tpu.ops.bootstrap import _FIT_STREAM

    colliding = jax.random.fold_in(key, _FIT_STREAM)
    assert not np.array_equal(
        np.asarray(jax.random.key_data(manual_key)),
        np.asarray(jax.random.key_data(colliding)),
    )
