"""Fault-tolerance layer tests [ISSUE 11]: the deterministic
fault-injection framework (``spark_bagging_tpu/faults.py``) and the
serving plane's responses to what it injects — deadline sheds, bounded
retries, bisect-on-poison, worker supervision + crash-loop degraded
mode, crash-safe registry swap/save, degraded-quorum mesh serving, and
the ``--chaos`` replay scenario.

Contract anchors:

- a chaos experiment is a pure function of ``(plan, seed)`` — two
  fresh plans from the same dict fire identically;
- the UNARMED hot path pays nothing: no ``faults.fire`` call at all
  (proven by patching ``fire`` to raise), zero compiles, no new locks;
- a kill injected at any ``save()`` step leaves a checkpoint that
  LOADS — partial artifacts are counted misses, never wrong answers;
- degraded-quorum output is bitwise-equal to an offline recompute of
  the surviving-subset aggregate.
"""

import json
import os
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

from spark_bagging_tpu import faults, telemetry
from spark_bagging_tpu.serving import (
    DeadlineExceeded,
    Degraded,
    EnsembleExecutor,
    MicroBatcher,
    ModelRegistry,
)
from spark_bagging_tpu.serving import program_cache
from spark_bagging_tpu.telemetry.recorder import FlightRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


@pytest.fixture(autouse=True)
def _telemetry_on():
    telemetry.enable()
    yield
    faults.disarm()  # no chaos plan may leak into later tests


def _counter(name, labels=None):
    return telemetry.registry().counter(name, labels=labels).value


class _DummyExecutor:
    """Jax-free executor stand-in: batcher robustness tests must not
    pay XLA compiles for queueing semantics."""

    task = "regression"
    n_features = 4
    classes_ = None

    def __init__(self):
        self.calls = 0
        self.fail_next = 0

    def forward(self, X):
        self.calls += 1
        if self.fail_next > 0:
            self.fail_next -= 1
            raise faults.TransientFault("injected blip")
        return X.sum(axis=1)


def _fitted(seed=0, width=4, n_estimators=2):
    from benchmarks.replay import _default_model

    return _default_model(width, n_estimators, seed=seed)


@pytest.fixture(scope="module")
def models():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return _fitted(seed=0), _fitted(seed=1)


# -- plan grammar and determinism --------------------------------------


class TestFaultPlan:
    def test_unknown_site_and_action_rejected(self):
        with pytest.raises(ValueError, match="unknown injection site"):
            faults.FaultSpec("nope.nope")
        with pytest.raises(ValueError, match="unknown fault action"):
            faults.FaultSpec("batcher.submit", "explode", at=[1])
        with pytest.raises(ValueError, match="needs a trigger"):
            faults.FaultSpec("batcher.submit", "error")
        with pytest.raises(ValueError, match="unknown fault-spec keys"):
            faults.FaultSpec.from_dict(
                {"site": "batcher.submit", "at": [1], "typo": 1}
            )
        with pytest.raises(ValueError, match="poison"):
            faults.FaultSpec("batcher.worker", "poison", at=[1])
        with pytest.raises(ValueError, match="at least one spec"):
            faults.FaultPlan([])

    def test_scheduled_triggers(self):
        plan = faults.FaultPlan([
            {"site": "batcher.worker", "action": "error", "at": [2, 4]},
        ])
        fired = []
        for hit in range(1, 6):
            try:
                plan.fire("batcher.worker")
            except faults.FaultInjected:
                fired.append(hit)
        assert fired == [2, 4]
        snap = plan.snapshot()
        assert snap["hits"] == {"batcher.worker": 5}
        assert snap["fires"] == {"batcher.worker": 2}

    def test_every_and_times_cap(self):
        plan = faults.FaultPlan([
            {"site": "batcher.worker", "action": "error", "every": 3,
             "times": 2},
        ])
        fired = []
        for hit in range(1, 13):
            try:
                plan.fire("batcher.worker")
            except faults.FaultInjected:
                fired.append(hit)
        assert fired == [3, 6]  # times=2 caps the every-3 schedule

    def test_probabilistic_draws_are_seeded(self):
        spec = {"site": "batcher.worker", "action": "error", "p": 0.3}

        def transcript(seed):
            plan = faults.FaultPlan([spec], seed=seed)
            out = []
            for _ in range(64):
                try:
                    plan.fire("batcher.worker")
                    out.append(0)
                except faults.FaultInjected:
                    out.append(1)
            return out

        assert transcript(7) == transcript(7)  # same seed, same faults
        assert transcript(7) != transcript(8)  # a seed is a schedule

    def test_roundtrip_and_digest(self, tmp_path):
        plan = faults.builtin_plan("mixed", seed=3)
        p = str(tmp_path / "plan.json")
        plan.save(p)
        again = faults.FaultPlan.load(p)
        assert again.digest() == plan.digest()
        assert again.to_dict() == plan.to_dict()
        with pytest.raises(ValueError, match="unknown builtin"):
            faults.builtin_plan("nope")

    def test_actions_raise_their_types(self):
        for action, exc in (
            ("error", faults.FaultInjected),
            ("transient", faults.TransientFault),
            ("kill", faults.SimulatedKill),
        ):
            plan = faults.FaultPlan([
                {"site": "batcher.worker", "action": action, "at": [1]},
            ])
            with pytest.raises(exc):
                plan.fire("batcher.worker")
        plan = faults.FaultPlan([
            {"site": "executor.mesh_forward", "action": "shard",
             "at": [1], "shard": 2},
        ])
        with pytest.raises(faults.ShardFault) as ei:
            plan.fire("executor.mesh_forward")
        assert ei.value.shard == 2
        assert faults.TransientFault("x").transient
        assert not faults.FaultInjected("x").transient


# -- the zero-cost-unarmed contract ------------------------------------


def test_unarmed_hot_paths_never_even_call_fire(monkeypatch):
    """The acceptance gate's 'pays nothing' half: with no plan armed,
    the probe call itself is skipped (one module-attribute read, no
    lock, no allocation). Patching fire() to raise proves no hot path
    reaches it."""

    def boom(*a, **k):  # pragma: no cover — reaching it IS the failure
        raise AssertionError("faults.fire called while unarmed")

    monkeypatch.setattr(faults, "fire", boom)
    assert faults.ACTIVE is None
    ex = _DummyExecutor()
    # coalesced path
    b = MicroBatcher(ex, threaded=False)
    f = b.submit(np.ones((2, 4), np.float32))
    b.run_pending()
    assert f.result(0).shape == (2,)
    # direct-dispatch path (white-box: force the earned mode)
    b2 = MicroBatcher(ex, threaded=True)
    b2._mode_direct = True
    assert b2.submit(np.ones((1, 4), np.float32)).result(1).shape == (1,)
    b2.close()


def test_unarmed_executor_forward_is_probe_free(monkeypatch, models):
    m1, _ = models
    ex = EnsembleExecutor(m1, min_bucket_rows=8, max_batch_rows=16)
    ex.warmup()
    monkeypatch.setattr(faults, "fire", lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("faults.fire called while unarmed")))
    X = np.zeros((3, 4), np.float32)
    c0 = _counter("sbt_serving_compiles_total")
    ex.forward(X)
    assert _counter("sbt_serving_compiles_total") == c0  # and no compiles


# -- batcher robustness ------------------------------------------------


def test_deadline_expiry_sheds_distinctly():
    """In-queue expiry is DeadlineExceeded + reason="deadline" — not
    Overloaded, and batch-mates without deadlines still serve."""
    vt = [100.0]
    b = MicroBatcher(_DummyExecutor(), threaded=False,
                     clock=lambda: vt[0])
    shed0 = _counter("sbt_serving_shed_total", {"reason": "deadline"})
    f_dead = b.submit(np.ones((1, 4), np.float32), deadline_ms=5)
    f_live = b.submit(np.ones((1, 4), np.float32))
    vt[0] += 1.0  # a full virtual second passes before the claim
    b.run_pending()
    assert isinstance(f_dead.exception(0), DeadlineExceeded)
    assert f_live.result(0).shape == (1,)
    assert _counter("sbt_serving_shed_total",
                    {"reason": "deadline"}) == shed0 + 1
    with pytest.raises(ValueError, match="deadline_ms"):
        b.submit(np.ones((1, 4), np.float32), deadline_ms=0)


def test_deadline_not_expired_serves():
    vt = [100.0]
    b = MicroBatcher(_DummyExecutor(), threaded=False,
                     clock=lambda: vt[0])
    f = b.submit(np.ones((1, 4), np.float32), deadline_ms=50)
    vt[0] += 0.01  # 10ms < 50ms: still fresh at claim
    b.run_pending()
    assert f.result(0).shape == (1,)


def test_transient_failures_retry_with_bounded_budget():
    ex = _DummyExecutor()
    ex.fail_next = 2
    b = MicroBatcher(ex, threaded=False, retries=3, retry_backoff_ms=0)
    r0 = _counter("sbt_serving_retries_total")
    f = b.submit(np.ones((2, 4), np.float32))
    b.run_pending()
    assert f.result(0).shape == (2,)  # absorbed by the retry budget
    assert _counter("sbt_serving_retries_total") == r0 + 2

    # budget exhausted -> the failure is delivered
    ex.fail_next = 5
    f2 = b.submit(np.ones((1, 4), np.float32))
    b.run_pending()
    assert isinstance(f2.exception(0), faults.TransientFault)


def test_permanent_failure_does_not_consume_retries():
    class _Perm(_DummyExecutor):
        def forward(self, X):
            self.calls += 1
            raise RuntimeError("permanent")

    perm = _Perm()
    b = MicroBatcher(perm, threaded=False, retries=5,
                     retry_backoff_ms=0)
    r0 = _counter("sbt_serving_retries_total")
    f = b.submit(np.ones((1, 4), np.float32))
    b.run_pending()
    assert isinstance(f.exception(0), RuntimeError)
    assert _counter("sbt_serving_retries_total") == r0  # not transient
    assert perm.calls == 1  # no blind re-forwarding of permanent errors


def test_poisoned_request_fails_alone_via_bisect():
    """One marked request in a 4-request coalesced batch: bisection
    isolates it; the three batch-mates serve with exact results."""
    b = MicroBatcher(_DummyExecutor(), threaded=False)
    plan = faults.FaultPlan([
        {"site": "batcher.submit", "action": "poison", "at": [2]},
    ])
    b0 = _counter("sbt_serving_batch_bisects_total")
    rf0 = _counter("sbt_serving_request_failures_total")
    with faults.armed(plan):
        futs = [b.submit(np.full((1, 4), i, np.float32))
                for i in range(4)]
        b.run_pending()
    assert isinstance(futs[1].exception(0), faults.PoisonedRequest)
    for i in (0, 2, 3):
        assert float(futs[i].result(0)[0]) == i * 4.0
    assert _counter("sbt_serving_batch_bisects_total") > b0
    assert _counter("sbt_serving_request_failures_total") == rf0 + 1


def test_bisect_disabled_fails_whole_batch_together():
    b = MicroBatcher(_DummyExecutor(), threaded=False,
                     bisect_on_error=False)
    plan = faults.FaultPlan([
        {"site": "batcher.submit", "action": "poison", "at": [1]},
    ])
    with faults.armed(plan):
        futs = [b.submit(np.ones((1, 4), np.float32)) for _ in range(3)]
        b.run_pending()
    for f in futs:
        assert isinstance(f.exception(0), faults.PoisonedRequest)


def test_direct_dispatch_honors_the_retry_contract():
    """retries= applies on the adaptive direct path too — the path
    that serves most low-concurrency traffic must not silently skip
    the recovery ladder (review finding)."""
    ex = _DummyExecutor()
    ex.fail_next = 2
    b = MicroBatcher(ex, threaded=True, retries=3, retry_backoff_ms=0)
    b._mode_direct = True  # white-box: the earned mode
    r0 = _counter("sbt_serving_retries_total")
    try:
        f = b.submit(np.ones((1, 4), np.float32))
        assert f.result(5).shape == (1,)
        assert _counter("sbt_serving_retries_total") == r0 + 2
        # terminal direct-path failures count as request failures too
        ex.fail_next = 9
        rf0 = _counter("sbt_serving_request_failures_total")
        b._mode_direct = True
        f2 = b.submit(np.ones((1, 4), np.float32))
        assert isinstance(f2.exception(5), faults.TransientFault)
        assert _counter("sbt_serving_request_failures_total") == rf0 + 1
    finally:
        b.close()


def test_worker_crash_mid_batch_never_strands_claimed_futures(
        monkeypatch):
    """A crash escaping even the batch guards (a dying sink, not just
    the injected worker probe) must fail the futures that batch had
    claimed BEFORE the supervisor takes over — a restarted worker
    never revisits them (review finding)."""
    b = MicroBatcher(_DummyExecutor(), threaded=True,
                     direct_dispatch=False)

    def boom(live, token):
        raise RuntimeError("sink died in the scatter span")

    monkeypatch.setattr(b, "_run_batch_held", boom)
    try:
        f = b.submit(np.ones((1, 4), np.float32))
        err = f.exception(10)  # NOT a hang
        assert isinstance(err, RuntimeError)
        assert "crashed mid-batch" in str(err)
    finally:
        b.close()


def test_worker_crash_is_supervised_and_restarted():
    b = MicroBatcher(_DummyExecutor(), threaded=True,
                     direct_dispatch=False)
    c0 = _counter("sbt_serving_worker_crashes_total")
    s0 = _counter("sbt_serving_worker_restarts_total")
    plan = faults.FaultPlan([
        {"site": "batcher.worker", "action": "error", "at": [1]},
    ])
    try:
        with faults.armed(plan):
            f = b.submit(np.ones((1, 4), np.float32))
            assert isinstance(f.exception(10), RuntimeError)
        # the supervisor restarts a fresh worker; traffic resumes
        f2 = b.submit(np.ones((1, 4), np.float32))
        assert f2.result(10).shape == (1,)
        assert _counter("sbt_serving_worker_crashes_total") == c0 + 1
        assert _counter("sbt_serving_worker_restarts_total") == s0 + 1
        assert b.health()["worker_alive"]
    finally:
        b.close()


def test_crash_loop_trips_degraded_reject_mode():
    """N crashes inside the window => degraded reject: /healthz goes
    unhealthy, submits shed with Degraded, exactly ONE flight dump for
    the incident, revive() recovers."""
    rec = FlightRecorder(cooldown_s=120)
    rec.arm()
    b = MicroBatcher(_DummyExecutor(), threaded=True,
                     direct_dispatch=False,
                     crash_loop_threshold=2, crash_loop_window_s=60)
    plan = faults.FaultPlan([
        {"site": "batcher.worker", "action": "error", "every": 1,
         "times": 16},
    ])
    loops0 = _counter("sbt_serving_crash_loops_total")
    shed0 = _counter("sbt_serving_shed_total", {"reason": "degraded"})
    try:
        with faults.armed(plan):
            for _ in range(2):
                f = b.submit(np.ones((1, 4), np.float32))
                f.exception(10)  # each claim crashes the worker once
            deadline = time.monotonic() + 10
            while (not b.health()["degraded"]
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            health = b.health()
            assert health["degraded"] and not health["healthy"]
            with pytest.raises(Degraded):
                b.submit(np.ones((1, 4), np.float32))
        assert _counter("sbt_serving_crash_loops_total") == loops0 + 1
        assert _counter("sbt_serving_shed_total",
                        {"reason": "degraded"}) > shed0
        # one incident, one dump (cooldown covers the whole window).
        # The dump is written synchronously on the WORKER thread and a
        # full-session registry snapshot is large — poll rather than
        # racing the write
        deadline = time.monotonic() + 15
        crash_dumps: list = []
        while time.monotonic() < deadline:
            crash_dumps = [
                p for p in rec.dumps
                if json.load(open(p)).get("trigger", {}).get("kind")
                == "serving_crash_loop"
            ]
            if crash_dumps:
                break
            time.sleep(0.05)
        assert len(crash_dumps) == 1
        # plan disarmed by the context manager: revive and serve again
        b.revive()
        assert b.health()["healthy"]
        f = b.submit(np.ones((1, 4), np.float32))
        assert f.result(10).shape == (1,)
    finally:
        rec.disarm()
        b.close()


# -- crash-safe registry -----------------------------------------------


def test_swap_rolls_back_on_precompile_failure(models):
    m1, m2 = models
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    ex1 = reg.register("m", m1, warmup=True)
    X = np.zeros((3, 4), np.float32)
    before = ex1.predict_proba(X)
    f0 = _counter("sbt_serving_swap_failed_total")
    plan = faults.FaultPlan([
        {"site": "registry.swap.precompile", "action": "error",
         "at": [1]},
    ])
    with faults.armed(plan):
        with pytest.raises(RuntimeError, match="rolled back"):
            reg.swap("m", m2, warm=True)
    # the prior executor keeps serving, version unbumped, failure
    # counted as its own incident kind (not a contract rejection)
    assert reg.executor("m") is ex1
    assert reg.version("m") == 1
    np.testing.assert_array_equal(reg.executor("m").predict_proba(X),
                                  before)
    assert _counter("sbt_serving_swap_failed_total") == f0 + 1
    # and a clean swap afterwards works
    reg.swap("m", m2)
    assert reg.version("m") == 2


def test_swap_rolls_back_on_program_cache_fault(models):
    m1, m2 = models
    # cold unified cache: the warm pre-compile must actually reach
    # cache().put for the armed fault to land there
    program_cache.clear()
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    ex1 = reg.register("m", m1, warmup=True)
    plan = faults.FaultPlan([
        {"site": "program_cache.put", "action": "error", "at": [1]},
    ])
    with faults.armed(plan):
        with pytest.raises(RuntimeError, match="rolled back"):
            reg.swap("m", m2, warm=True)
    assert reg.executor("m") is ex1 and reg.version("m") == 1


@pytest.mark.parametrize("site", [
    "checkpoint.write",
    "registry.save.checkpoint",
    "registry.save.aot",
    "registry.save.manifest",
])
def test_torn_save_always_leaves_a_loadable_checkpoint(
        site, models, tmp_path):
    """Kill save() at every injected point between checkpoint write,
    AOT dir write, and serve_config rename: load() must always
    succeed, serve answers bitwise-consistent with whatever weights it
    loaded, and treat partial artifacts as counted misses — never
    wrong answers."""
    m1, m2 = models
    path = str(tmp_path / "ckpt")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    reg.register("m", m1, warmup=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        reg.save("m", path)  # clean publish of version 1 (m1)
        reg.swap("m", m2)
    plan = faults.FaultPlan([
        {"site": site, "action": "kill", "at": [1]},
    ])
    with faults.armed(plan):
        with pytest.raises(faults.SimulatedKill):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                reg.save("m", path)
    # fresh-process simulation: cold program cache, fresh registry
    program_cache.clear()
    reg2 = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ex = reg2.load("m", path)  # must not raise at ANY kill point
    loaded = reg2.model("m")
    fp = program_cache.fingerprint_model(loaded)
    if site == "checkpoint.write":
        # the kill landed before the checkpoint's atomic swap: the
        # prior version (m1, the clean v1 publish) is fully intact —
        # weights, AOT, and manifest all still consistent
        assert fp == program_cache.fingerprint_model(m1)
        assert reg2.version("m") == 1
    else:
        # the checkpoint itself completed (m2) and everything after
        # it is partial; whatever loaded must be m2's weights
        assert fp == program_cache.fingerprint_model(m2)
    # the never-wrong-answers gate: served output is bitwise-equal to
    # the loaded weights' own batch predict
    X = np.asarray(
        np.random.default_rng(5).normal(size=(4, 4)), np.float32
    )
    np.testing.assert_array_equal(
        np.asarray(ex.predict_proba(X)),
        np.asarray(loaded.predict_proba(X)),
    )


def test_stale_serve_config_detected_by_fingerprint(models, tmp_path):
    """The manifest binds itself to its weights: a serve_config left
    next to DIFFERENT weights (the torn-save signature, or an operator
    copying checkpoints by hand) is ignored with a warning instead of
    publishing a wrong version number."""
    import shutil

    m1, m2 = models
    p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    reg.register("m", m1, warmup=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        reg.save("m", p1)
        reg.swap("m", m2)
        reg.save("m", p1)  # clean v2 publish at p1
        # hand-build the torn state at p2: m1's weights under m2's
        # serve_config
        reg3 = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
        reg3.register("m", m1, warmup=True)
        reg3.save("m", p2)
    shutil.copy(os.path.join(p1, "serve_config.json"),
                os.path.join(p2, "serve_config.json"))
    # poison the stale manifest's executor section too: neither its
    # version NOR its config may be adopted (review finding)
    cfg_path = os.path.join(p2, "serve_config.json")
    cfg = json.load(open(cfg_path))
    cfg["executor"]["max_batch_rows"] = 999
    json.dump(cfg, open(cfg_path, "w"))
    program_cache.clear()
    reg2 = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    with pytest.warns(UserWarning, match="does not match the checkpoint"):
        with warnings.catch_warnings():
            warnings.simplefilter("always")
            reg2.load("m", p2)
    # the stale manifest's version (2) was NOT adopted
    assert reg2.version("m") == 1
    assert (program_cache.fingerprint_model(reg2.model("m"))
            == program_cache.fingerprint_model(m1))
    # ...and neither was its executor config: the caller's (registry
    # default) ladder won, not the stale manifest's 999
    assert reg2.executor("m").max_batch_rows == 16


# -- degraded-quorum mesh serving --------------------------------------


@pytest.fixture(scope="module")
def mesh_setup():
    import jax

    from spark_bagging_tpu.parallel import make_mesh

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = _fitted(seed=0, width=8, n_estimators=8)
    mesh = make_mesh(data=1, replica=4, devices=jax.devices()[:4])
    return model, mesh


def test_shard_loss_degrades_to_surviving_quorum_bitwise(mesh_setup):
    """An injected shard failure drops the shard, serving continues on
    the surviving-replica aggregate with degraded=true telemetry, and
    the output is BITWISE-equal to a fresh offline recompute of the
    surviving-subset aggregate. reset_degraded() heals bitwise."""
    import jax
    import jax.numpy as jnp

    from spark_bagging_tpu.parallel.sharded import replica_subset_serving

    model, mesh = mesh_setup
    ex = EnsembleExecutor(model, mesh=mesh, min_bucket_rows=8,
                          max_batch_rows=32)
    X = np.asarray(
        np.random.default_rng(1).normal(size=(5, 8)), np.float32
    )
    healthy = np.asarray(ex.forward(X))
    assert not ex.degraded and ex.surviving_replicas is None
    sf0 = _counter("sbt_serving_shard_failures_total")
    df0 = _counter("sbt_serving_degraded_forwards_total")
    sc0 = _counter("sbt_serving_compiles_total")
    plan = faults.FaultPlan([
        {"site": "executor.mesh_forward", "action": "shard", "at": [1],
         "shard": 1},
    ])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.armed(plan):
            served = np.asarray(ex.forward(X))
    assert ex.degraded and ex.failed_shards == (1,)
    assert ex.surviving_replicas == 6  # 8 replicas, shard of 2 lost
    assert _counter("sbt_serving_shard_failures_total") == sf0 + 1
    assert _counter("sbt_serving_degraded_forwards_total") > df0
    # degraded compiles are their own counter — the serving
    # zero-post-warmup-compile gate is untouched by the fault response
    assert _counter("sbt_serving_compiles_total") == sc0
    assert telemetry.registry().gauge("sbt_serving_degraded").value == 1.0

    # the bitwise contract: fresh offline recompute of the surviving
    # subset aggregate, same construction, padded to the same bucket
    survivors = [i for i in range(8) if i // 2 != 1]
    fn, _, p, s = replica_subset_serving(model, survivors)
    Xp = np.zeros((8, 8), np.float32)
    Xp[:5] = X
    compiled = jax.jit(fn).lower(
        p, s, jnp.zeros((8, 8), jnp.float32)
    ).compile()
    offline = np.asarray(compiled(p, s, Xp))[:5]
    np.testing.assert_array_equal(served, offline)
    assert not np.array_equal(served, healthy)  # 6 != 8 replicas

    # healing restores the exact healthy bits
    assert ex.reset_degraded()
    np.testing.assert_array_equal(np.asarray(ex.forward(X)), healthy)
    assert not ex.degraded
    assert telemetry.registry().gauge("sbt_serving_degraded").value == 0.0


def test_degrade_api_validates(mesh_setup, models):
    model, mesh = mesh_setup
    ex = EnsembleExecutor(model, mesh=mesh, min_bucket_rows=8,
                          max_batch_rows=32)
    with pytest.raises(ValueError, match="shard must be in"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ex.degrade_shards([9])
    single = EnsembleExecutor(models[0], min_bucket_rows=8,
                              max_batch_rows=16)
    with pytest.raises(ValueError, match="mesh-serving only"):
        single.degrade_shards([0])
    assert not single.reset_degraded()  # healthy no-op


# -- chaos replay ------------------------------------------------------


def test_chaos_replay_is_deterministic_across_repeats(models):
    """The acceptance drill in-process: a mixed chaos plan over the
    deterministic replay — identical fault/retry/shed/failure counts
    and byte-identical digests across repeats (replay_median raises on
    any divergence), zero post-warmup compiles."""
    from benchmarks.replay import replay_median
    from spark_bagging_tpu.telemetry import workload as workload_mod

    m1, _ = models
    wl = workload_mod.synthetic_workload(
        "poisson", rate_rps=200, duration_s=0.4, seed=0, rows=1,
        width=4,
    )
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
    reg.register("replay", m1, warmup=True)
    spec = faults.builtin_plan_spec("mixed", seed=0)
    report = replay_median(
        wl, repeats=2, registry=reg, model_name="replay",
        chaos=spec, retries=2,
    )
    chaos = report["chaos"]
    assert chaos["plan"] == "mixed"
    assert chaos["sites"]["fired_total"] > 0
    # every injected transient was retried; every poisoned request
    # failed alone and is accounted as an error, nothing else is
    assert chaos["retries"] > 0
    assert report["errors"] == chaos["request_failures"] > 0
    assert report["served"] + report["errors"] == report["n_requests"]
    assert report["post_warmup_compiles"] == 0
    assert chaos["shed"] == {"overload": 0, "deadline": 0,
                             "degraded": 0}
    assert faults.ACTIVE is None  # replay disarmed on the way out


@pytest.mark.slow  # [PR 20 budget offset] ~3.9s subprocess CLI gate; chaos-replay semantics stay tier-1 via the in-process chaos tests above plus the chaos-mixed registered scenario in the conformance smoke
def test_chaos_replay_cli_gate(tmp_path):
    """`python -m benchmarks.replay --chaos mixed --check` exits 0:
    byte-identical digests + identical fault transcripts across
    repeats, SLO gate green. Budget-asserted like the other replay CLI
    smokes."""
    out = str(tmp_path / "report.json")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.replay",
         "--chaos", "mixed", "--check",
         "--duration", "0.4", "--rate", "150",
         "--n-estimators", "4", "--width", "8",
         "--out", out],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=240,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, (
        f"chaos replay gate failed:\n{proc.stdout[-2000:]}\n"
        f"{proc.stderr[-2000:]}"
    )
    assert elapsed < 60, (
        f"chaos CLI smoke took {elapsed:.1f}s — budget is 60s; move "
        "it to slow or shrink the workload"
    )
    report = json.load(open(out))
    assert report["chaos"]["sites"]["fired_total"] > 0
    assert report["slo"]["ok"]


def test_chaos_rejects_unknown_plan_and_drift_combo():
    from benchmarks.replay import main

    with pytest.raises(SystemExit):
        main(["--chaos", "not-a-plan"])
    with pytest.raises(SystemExit):
        main(["--chaos", "mixed", "--drift"])
    # worker-only plans never fire in virtual mode (stepped batchers
    # run no worker): the CLI must reject the vacuous combination
    # rather than exit 0 having tested nothing (review finding)
    for plan in ("worker-crash", "crash-loop"):
        with pytest.raises(SystemExit):
            main(["--chaos", plan])
