"""Profiling/tracing hook tests [SURVEY §5 tracing]."""

import logging
import os

import jax
import jax.numpy as jnp
import pytest

from spark_bagging_tpu.utils.profiling import log_timing, named_scope, trace


def test_log_timing_emits(caplog):
    with caplog.at_level(logging.INFO, logger="spark_bagging_tpu"):
        with log_timing("phase-x"):
            pass
    assert any("phase-x" in r.message for r in caplog.records)


def test_named_scope_traces():
    @jax.jit
    def f(x):
        with named_scope("my_phase"):
            return jnp.sin(x) * 2  # non-foldable so the op survives

    assert abs(float(f(jnp.float32(3.0))) - 2 * 0.14112) < 1e-4
    lowered = f.lower(jnp.float32(3.0)).as_text()
    # Scope names appear in op metadata when the compiler keeps them;
    # assert only when present to avoid over-constraining XLA versions.
    assert "sine" in lowered or "sin" in lowered


@pytest.mark.slow  # ~9s: spins the real XLA profiler; artifact-only coverage
def test_trace_writes_profile(tmp_path):
    d = str(tmp_path / "prof")
    with trace(d):
        jnp.sum(jnp.arange(100.0)).block_until_ready()
    # A profile directory with at least one event file appears.
    found = []
    for root, _, files in os.walk(d):
        found.extend(files)
    assert found, "no trace files written"
