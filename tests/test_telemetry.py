"""Unified telemetry subsystem [SURVEY §5]: registry thread-safety,
span nesting, JSONL schema round-trip, Prometheus rendering,
disabled-mode overhead, and the fit_report key-compatibility contract.
"""

import json
import threading
import time

import numpy as np
import pytest

from spark_bagging_tpu import telemetry
from spark_bagging_tpu.telemetry.registry import Registry, render_prometheus


@pytest.fixture(autouse=True)
def _clean_registry():
    """Each test sees a fresh registry and the default switches."""
    telemetry.reset()
    telemetry.enable()
    telemetry.set_device_sync(False)
    yield
    telemetry.reset()
    telemetry.enable()
    telemetry.set_device_sync(False)


@pytest.fixture(scope="module")
def small_data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(120, 6)).astype(np.float32)
    y = (X[:, 0] + 0.2 * rng.normal(size=120) > 0).astype(np.int32)
    return X, y


class TestRegistry:
    def test_counter_gauge_histogram(self):
        r = Registry()
        r.inc("sbt_x_total", 2)
        r.inc("sbt_x_total", 3)
        r.set("sbt_depth", 7)
        r.observe("sbt_lat_seconds", 0.05)
        r.observe("sbt_lat_seconds", 5.0)
        snap = {e["name"]: e for e in r.snapshot()}
        assert snap["sbt_x_total"]["value"] == 5
        assert snap["sbt_depth"]["value"] == 7
        assert snap["sbt_lat_seconds"]["count"] == 2
        assert snap["sbt_lat_seconds"]["sum"] == pytest.approx(5.05)

    def test_labels_key_separate_series(self):
        r = Registry()
        r.inc("sbt_x_total", 1, {"k": "a"})
        r.inc("sbt_x_total", 2, {"k": "b"})
        snap = r.snapshot()
        assert {tuple(e["labels"].items()): e["value"] for e in snap} == {
            (("k", "a"),): 1, (("k", "b"),): 2,
        }

    def test_counter_rejects_negative(self):
        r = Registry()
        with pytest.raises(ValueError, match=">= 0"):
            r.counter("sbt_x_total").inc(-1)

    def test_kind_collision_raises(self):
        r = Registry()
        r.counter("sbt_x")
        with pytest.raises(TypeError, match="already registered"):
            r.gauge("sbt_x")

    def test_thread_safety(self):
        """N threads hammering one counter/histogram must lose no
        updates — the engines emit from fit, prefetch-producer, and
        jax-listener threads concurrently."""
        r = Registry()
        n_threads, n_iter = 8, 2000

        def work():
            for _ in range(n_iter):
                r.inc("sbt_x_total")
                r.observe("sbt_h_seconds", 0.01)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = {e["name"]: e for e in r.snapshot()}
        assert snap["sbt_x_total"]["value"] == n_threads * n_iter
        assert snap["sbt_h_seconds"]["count"] == n_threads * n_iter


class TestSpans:
    def test_nesting_and_ordering(self):
        with telemetry.capture() as run:
            with telemetry.span("outer"):
                with telemetry.span("inner"):
                    pass
            with telemetry.span("second"):
                pass
        spans = run.spans()
        # children complete (and are recorded) before their parents
        assert [(s["name"], s["path"]) for s in spans] == [
            ("inner", "outer/inner"),
            ("outer", "outer"),
            ("second", "second"),
        ]
        assert all(s["seconds"] >= 0 for s in spans)

    def test_span_metric_histogram(self):
        with telemetry.span("step", metric="sbt_chunk_seconds"):
            pass
        snap = {e["name"]: e for e in telemetry.registry().snapshot()}
        assert snap["sbt_chunk_seconds"]["count"] == 1

    def test_span_attrs_serializable(self):
        with telemetry.capture() as run:
            with telemetry.span("s", epoch=2, tag=object()):
                pass
        (s,) = run.spans("s")
        json.dumps(s)  # everything must be JSON-clean
        assert s["attrs"]["epoch"] == 2

    def test_device_sync_flag_recorded(self):
        telemetry.set_device_sync(True)
        with telemetry.capture() as run:
            with telemetry.span("synced"):
                pass
        assert run.spans("synced")[0]["sync"] is True

    def test_exception_still_records_and_unwinds(self):
        with telemetry.capture() as run:
            with pytest.raises(RuntimeError):
                with telemetry.span("boom"):
                    raise RuntimeError("x")
            with telemetry.span("after"):
                pass
        assert run.spans("boom")[0]["path"] == "boom"
        # the stack unwound: the next span is NOT nested under "boom"
        assert run.spans("after")[0]["path"] == "after"


class TestJsonlRoundTrip:
    def test_schema_round_trip(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        with telemetry.capture(path, label="t") as run:
            with telemetry.span("phase"):
                telemetry.inc("sbt_x_total", 4)
        events = telemetry.read_events(path)
        assert [e["kind"] for e in events] == [
            "run_start", "span", "metrics", "run_end",
        ]
        assert all(e["schema"] == telemetry.SCHEMA_VERSION for e in events)
        assert all(e["run"] == run.run_id for e in events)
        # the on-disk log and the in-memory run agree event-for-event
        assert len(events) == len(run.events)
        snap = telemetry.last_metrics_snapshot(events)
        by_name = {e["name"]: e for e in snap}
        assert by_name["sbt_x_total"]["value"] == 4
        # and the recovered snapshot renders as Prometheus text
        assert "sbt_x_total 4" in telemetry.render_prometheus(snap)

    def test_cli_dump_from_jsonl(self, tmp_path, capsys):
        from spark_bagging_tpu.telemetry.__main__ import main

        path = str(tmp_path / "ev.jsonl")
        with telemetry.capture(path):
            telemetry.inc("sbt_x_total")
        assert main(["dump", path]) == 0
        out = capsys.readouterr().out
        assert "# TYPE sbt_x_total counter" in out

    def test_capture_restores_prior_switches(self):
        telemetry.disable()
        with telemetry.capture() as run:
            assert telemetry.enabled()  # capture force-enables
            with telemetry.span("s"):
                pass
        assert not telemetry.enabled()  # restored
        telemetry.enable()
        assert run.spans("s")


class TestPrometheus:
    def test_histogram_rendering_cumulative(self):
        r = Registry()
        r.observe("sbt_h_seconds", 0.05)
        r.observe("sbt_h_seconds", 50.0)
        text = render_prometheus(r.snapshot())
        assert "# TYPE sbt_h_seconds histogram" in text
        assert 'sbt_h_seconds_bucket{le="0.1"} 1' in text
        assert 'sbt_h_seconds_bucket{le="100.0"} 2' in text
        assert 'sbt_h_seconds_bucket{le="+Inf"} 2' in text
        assert "sbt_h_seconds_count 2" in text

    def test_labels_rendered_sorted(self):
        r = Registry()
        r.inc("sbt_x_total", 1, {"b": 2, "a": 1})
        assert 'sbt_x_total{a="1",b="2"} 1' in render_prometheus(r.snapshot())

    def test_nonfinite_values_render_not_crash(self):
        """A diverged fit exports loss_mean=NaN (and fits_per_sec can
        be inf): the dump is the tool you reach for EXACTLY then, so it
        must render the Prometheus spellings instead of raising."""
        r = Registry()
        r.set("sbt_fit_loss_mean", float("nan"))
        r.set("sbt_fit_fits_per_sec", float("inf"))
        r.set("sbt_neg", float("-inf"))
        text = render_prometheus(r.snapshot())
        assert "sbt_fit_loss_mean NaN" in text
        assert "sbt_fit_fits_per_sec +Inf" in text
        assert "sbt_neg -Inf" in text


class TestQuantiles:
    def test_log_bucket_interpolation_brackets_truth(self):
        """Quantile estimates from decade buckets must land inside the
        bucket that truly contains the quantile (interpolation can't
        do better than the grid, but must never leave the bucket)."""
        r = Registry()
        rng_vals = [0.002, 0.003, 0.004, 0.005, 0.05, 0.06, 0.5, 2.0]
        for v in rng_vals:
            r.observe("sbt_h_seconds", v)
        h = r.histogram("sbt_h_seconds")
        assert 0.001 < h.quantile(0.5) <= 0.1
        assert 0.1 < h.quantile(0.99) <= 10.0
        qs = h.quantiles()
        assert set(qs) == {"p50", "p95", "p99"}
        assert qs["p50"] <= qs["p95"] <= qs["p99"]

    def test_quantile_edge_cases(self):
        import math

        from spark_bagging_tpu.telemetry.registry import Histogram

        h = Histogram()
        assert math.isnan(h.quantile(0.5))  # empty
        h.observe(1e9)  # beyond the grid: +Inf bucket
        assert h.quantile(0.5) == h.bounds[-2]  # clamps to last finite
        with pytest.raises(ValueError, match="quantile"):
            h.quantile(1.5)

    def test_snapshot_and_offline_reconstruction_agree(self):
        from spark_bagging_tpu.telemetry.registry import (
            snapshot_quantiles,
        )

        r = Registry()
        for v in (0.01, 0.02, 0.5, 3.0):
            r.observe("sbt_h_seconds", v)
        (entry,) = r.snapshot(quantiles=True)
        direct = entry["quantiles"]
        entry_no_q = {k: v for k, v in entry.items() if k != "quantiles"}
        rebuilt = snapshot_quantiles(entry_no_q)  # the old-JSONL path
        for k in direct:
            assert rebuilt[k] == pytest.approx(direct[k])

    def test_cli_dump_emits_quantile_comments(self, tmp_path, capsys):
        from spark_bagging_tpu.telemetry.__main__ import main

        telemetry.observe("sbt_chunk_seconds", 0.02)
        assert main(["dump"]) == 0
        out = capsys.readouterr().out
        assert "# quantiles sbt_chunk_seconds p50=" in out
        assert main(["dump", "--no-quantiles"]) == 0
        assert "# quantiles" not in capsys.readouterr().out

    def test_exemplar_recorded_and_snapshotted(self):
        r = Registry()
        r.observe("sbt_lat_seconds", 0.05, exemplar="tr-1")
        r.observe("sbt_lat_seconds", 0.06, exemplar="tr-2")
        r.observe("sbt_lat_seconds", 40.0, exemplar="tr-slow")
        (entry,) = r.snapshot()
        by_bucket = {e["le"]: e["trace_id"] for e in entry["exemplars"]}
        assert by_bucket[0.1] == "tr-2"  # last write wins per bucket
        assert by_bucket[100.0] == "tr-slow"


class TestSeriesHelpCompleteness:
    def test_every_series_in_the_tree_has_help(self):
        """THE completeness gate: every ``sbt_*`` series name the
        package, benchmarks, or bench.py registers must carry a
        ``SERIES_HELP`` entry (or ride the ``sbt_fit_*`` dynamic
        prefix) — a scraper's UI shows these next to the graph, and a
        help-less series is an undocumented instrument. Since ISSUE 19
        this is a thin wrapper over the contracts engine's
        ``contract-series-help`` check, which walks the same literal
        scope AND adds the reverse direction (no dead SERIES_HELP
        entries) — strictly stronger than the original grep."""
        import os

        from spark_bagging_tpu.analysis.contracts import check_repo

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        findings = check_repo(repo, checks=["contract-series-help"])
        assert not findings, "\n".join(f.render() for f in findings)


class TestHelpAndEscaping:
    def test_help_lines_from_series_table(self):
        from spark_bagging_tpu.telemetry.registry import SERIES_HELP

        r = Registry()
        r.inc("sbt_serving_requests_total", 3)
        text = render_prometheus(r.snapshot())
        expected = SERIES_HELP["sbt_serving_requests_total"]
        assert f"# HELP sbt_serving_requests_total {expected}" in text
        # HELP precedes TYPE, each exactly once
        assert text.index("# HELP") < text.index("# TYPE")
        assert text.count("# HELP sbt_serving_requests_total") == 1

    def test_fit_gauges_get_prefix_help(self):
        r = Registry()
        r.set("sbt_fit_fits_per_sec", 8.0)
        text = render_prometheus(r.snapshot())
        assert "# HELP sbt_fit_fits_per_sec" in text

    def test_unknown_series_get_no_help(self):
        r = Registry()
        r.inc("sbt_mystery_total")
        text = render_prometheus(r.snapshot())
        assert "# HELP" not in text
        assert "# TYPE sbt_mystery_total counter" in text

    def test_label_values_escaped(self):
        r = Registry()
        r.set("sbt_serving_model_version", 1.0,
              {"model": 'a"b\\c\nd'})
        text = render_prometheus(r.snapshot())
        assert r'{model="a\"b\\c\nd"}' in text
        # and the line count survives: the newline did NOT split a
        # sample across two lines
        sample_lines = [
            ln for ln in text.splitlines()
            if ln.startswith("sbt_serving_model_version")
        ]
        assert len(sample_lines) == 1


class TestEmitEvent:
    def test_reaches_open_capture_with_ts(self):
        with telemetry.capture() as run:
            telemetry.emit_event({"kind": "serving_overloaded"})
        evs = [e for e in run.events
               if e["kind"] == "serving_overloaded"]
        assert len(evs) == 1 and "ts" in evs[0]

    def test_noop_when_disabled_or_unobserved(self):
        telemetry.emit_event({"kind": "nobody_listening"})  # no sink
        telemetry.disable()
        with telemetry.capture() as run:
            telemetry.disable()  # capture force-enabled; flip back
            telemetry.emit_event({"kind": "while_disabled"})
            telemetry.enable()
        assert not [e for e in run.events
                    if e["kind"] == "while_disabled"]


class TestDisabledOverhead:
    def test_disabled_span_is_noop_singleton(self):
        telemetry.disable()
        a = telemetry.span("x")
        b = telemetry.span("y")
        assert a is b  # shared no-op: no allocation on the hot path

    def test_disabled_mode_overhead_micro_benchmark(self):
        """The acceptance bar: with telemetry disabled, an
        instrumented hot path adds no measurable overhead. 50k
        span+counter+gauge sites must cost well under a microsecond-
        scale budget each (generous bound — CI machines vary)."""
        telemetry.disable()
        n = 50_000
        t0 = time.perf_counter()
        for i in range(n):
            with telemetry.span("hot"):
                telemetry.inc("sbt_x_total")
                telemetry.set_gauge("sbt_g", i)
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 20e-6, f"{per_call * 1e6:.2f}us per disabled site"
        # and nothing was recorded
        telemetry.enable()
        assert telemetry.registry().snapshot() == []


class TestFitReportCompatibility:
    # the pre-telemetry fit_report key set, frozen byte-for-byte: the
    # report became a registry-backed view and every consumer
    # (BENCH tooling, checkpoints, tests) reads these exact keys
    BASE_KEYS = [
        "n_replicas", "fit_seconds", "fits_per_sec", "compile_seconds",
        "h2d_seconds", "loss_mean", "loss_std", "n_rows", "n_features",
        "n_subspace", "backend", "n_devices",
    ]
    FLOPS_KEYS = [
        "fits_per_sec_e2e", "model_flops_per_fit", "achieved_tflops",
        "peak_tflops_bf16", "mfu",
    ]

    def _report(self):
        from spark_bagging_tpu.utils.metrics import fit_report

        return fit_report(
            n_replicas=4, fit_seconds=0.5, losses=np.ones(4),
            n_rows=100, n_features=10, n_subspace=10, backend="cpu",
            n_devices=1, compile_seconds=1.5, h2d_seconds=0.01,
            flops_per_fit=1e9, flops_fit_seconds=None,
        )

    def test_keys_byte_identical(self):
        assert list(self._report().keys()) == self.BASE_KEYS + self.FLOPS_KEYS

    def test_keys_identical_when_disabled(self):
        telemetry.disable()
        assert list(self._report().keys()) == self.BASE_KEYS + self.FLOPS_KEYS

    def test_report_is_plain_dict_to_consumers(self):
        rep = self._report()
        assert isinstance(rep, dict)
        json.dumps(rep)  # checkpoint manifests dump it verbatim
        rep["chunk_size_resolved"] = 16  # estimator mutates it post-hoc
        assert rep["chunk_size_resolved"] == 16

    def test_report_feeds_registry(self):
        self._report()
        snap = {e["name"]: e for e in telemetry.registry().snapshot()}
        assert snap["sbt_replicas_fitted_total"]["value"] == 4
        assert snap["sbt_compile_seconds"]["count"] == 1
        assert snap["sbt_fit_fits_per_sec"]["value"] == pytest.approx(8.0)


class TestEndToEnd:
    @pytest.mark.slow  # [PR 20 budget offset] ~4.4s full-fit e2e soak; event-log/prometheus surfaces stay tier-1 via the recorder/render unit tests here plus the conformance smoke's live-registry asserts
    def test_cpu_fit_produces_event_log_and_prometheus(
        self, tmp_path, small_data
    ):
        """The acceptance scenario [ISSUE 1]: a CPU-only
        BaggingClassifier().fit() under telemetry.capture() yields a
        parseable JSONL log with bootstrap/compile/fit/aggregate spans,
        and the Prometheus dump carries sbt_replicas_fitted_total and
        sbt_compile_seconds."""
        from spark_bagging_tpu import BaggingClassifier, clear_compiled_caches

        X, y = small_data
        clear_compiled_caches()  # force a fresh trace: phase spans fire
        path = str(tmp_path / "telemetry.jsonl")
        with telemetry.capture(path) as run:
            clf = BaggingClassifier(n_estimators=5, seed=0).fit(X, y)
        assert clf.score(X, y) > 0.7
        events = telemetry.read_events(path)
        assert all(
            isinstance(json.dumps(e), str) for e in events
        )
        names = {e["name"] for e in run.spans()}
        for required in ("bootstrap", "compile", "fit", "aggregate"):
            assert required in names, (required, sorted(names))
        prom = telemetry.render_prometheus()
        assert "sbt_replicas_fitted_total" in prom
        assert "sbt_compile_seconds" in prom

    def test_oob_and_h2d_counters(self, small_data):
        from spark_bagging_tpu import BaggingClassifier

        X, y = small_data
        BaggingClassifier(n_estimators=8, seed=1, oob_score=True).fit(X, y)
        snap = {
            (e["name"], tuple(e["labels"].items()))
            for e in telemetry.registry().snapshot()
        }
        names = {n for n, _ in snap}
        assert "sbt_oob_evaluations_total" in names
        assert "sbt_h2d_bytes_total" in names

    def test_stream_fit_counters_and_chunk_spans(self, small_data):
        from spark_bagging_tpu import BaggingClassifier

        X, y = small_data
        with telemetry.capture() as run:
            BaggingClassifier(n_estimators=4, seed=0).fit_stream(
                (X, y), classes=[0, 1], chunk_rows=48, n_epochs=2,
            )
        snap = {e["name"]: e for e in telemetry.registry().snapshot()}
        assert snap["sbt_stream_epochs_total"]["value"] == 2
        # 120 rows / 48-row chunks = 3 chunks x 2 epochs
        assert snap["sbt_stream_chunks_total"]["value"] == 6
        assert snap["sbt_chunk_seconds"]["count"] == 6
        assert len(run.spans("chunk_step")) == 6
        # producer-side count includes the padded tail chunk: the
        # source yields the same 3-per-pass the engine consumes
        yielded = [
            e for e in telemetry.registry().snapshot()
            if e["name"] == "sbt_chunks_yielded_total"
        ]
        assert sum(e["value"] for e in yielded) == 6

    def test_span_exception_with_device_sync_unwinds_stack(self):
        telemetry.set_device_sync(True)
        with telemetry.capture() as run:
            with pytest.raises(RuntimeError, match="body"):
                with telemetry.span("outer"):
                    raise RuntimeError("body")
            with telemetry.span("clean"):
                pass
        assert run.spans("clean")[0]["path"] == "clean"

    def test_disabled_fit_still_works(self, small_data):
        from spark_bagging_tpu import BaggingClassifier

        X, y = small_data
        telemetry.disable()
        clf = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
        assert clf.score(X, y) > 0.7
        assert telemetry.registry().snapshot() == []

    def test_bench_smoke_tiny_fit_writes_parseable_log(
        self, tmp_path, small_data
    ):
        """CI-tier smoke for the bench wiring: a tiny fit captured the
        way bench.py captures produces a log the CLI can render."""
        from spark_bagging_tpu import BaggingClassifier
        from spark_bagging_tpu.telemetry.__main__ import main

        X, y = small_data
        path = str(tmp_path / "telemetry.jsonl")
        with telemetry.capture(path, label="bench_headline"):
            BaggingClassifier(n_estimators=3, seed=0).fit(X, y)
        assert main(["dump", path]) == 0
        events = telemetry.read_events(path)
        assert events[0]["label"] == "bench_headline"
        assert telemetry.last_metrics_snapshot(events) is not None
