"""Debug/sanitizer mode [SURVEY §5 sanitizers, VERDICT r1 #7/#10]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_bagging_tpu import BaggingClassifier, LogisticRegression
from spark_bagging_tpu.utils.debug import (
    check_bootstrap_weights,
    debug_active,
    debug_mode,
)


def test_debug_mode_toggles_flags():
    assert not debug_active()
    with debug_mode():
        assert debug_active()
        assert jax.config.jax_debug_nans
    assert not debug_active()
    assert not jax.config.jax_debug_nans


def test_check_is_noop_when_inactive():
    # negative weights pass silently with debug off (zero overhead path)
    jax.jit(lambda w: (check_bootstrap_weights(w), w * 2)[1])(
        jnp.asarray([-1.0, 2.0])
    )


def test_check_raises_on_bad_weights_under_jit():
    with debug_mode():

        @jax.jit
        def f(w):
            check_bootstrap_weights(w)
            return w * 2

        f(jnp.asarray([0.0, 1.0, 3.0]))  # valid: fine
        with pytest.raises(Exception, match="finite and >= 0"):
            jax.block_until_ready(f(jnp.asarray([1.0, -2.0])))


def test_fit_runs_clean_under_debug_mode():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((200, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(int)
    with debug_mode():
        # fresh hyperparams => fresh trace, so the checks are compiled in
        clf = BaggingClassifier(
            base_learner=LogisticRegression(max_iter=4, l2=0.0123),
            n_estimators=4, seed=0,
        ).fit(X, y)
    assert clf.score(X, y) > 0.8


def test_debug_mode_restores_directly_enabled_nan_flag():
    """A user enabling jax_debug_nans via jax.config (not
    enable_debug) must keep it after a debug_mode() scope exits
    (round-4 audit)."""
    import jax

    from spark_bagging_tpu.utils.debug import debug_mode

    jax.config.update("jax_debug_nans", True)
    try:
        with debug_mode():
            pass
        assert bool(jax.config.jax_debug_nans) is True
    finally:
        jax.config.update("jax_debug_nans", False)
