"""Examples are user-facing documentation — they must actually run.

Each fast example executes in a subprocess on the virtual-CPU backend.
The mesh/streaming/multihost examples (02-04) are excluded: their
machinery has dedicated suites (test_sharded/test_streaming/
test_multihost) and running the scripts too would double CI time for
no new coverage.
"""

import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

FAST_EXAMPLES = [
    "01_quickstart.py",
    # [PR 14 pyramid] examples are doc smokes, not contract tests:
    # tier-1 keeps only the quickstart (~4s, THE user-facing path);
    # the rest (2-6s of subprocess jax import + fit each) run under
    # -m slow / full runs, and their subsystems keep dedicated tier-1
    # suites (custom learners: test_learners; AFT: test_aft;
    # out-of-core: test_arrow/test_prefetch; serving: test_serving*)
    pytest.param("05_custom_learner.py", marks=pytest.mark.slow),
    # 06_learner_zoo fits all 11 learner families (~70s of compiles) —
    # the single biggest tier-1 sink; it runs under -m slow / full runs
    pytest.param("06_learner_zoo.py", marks=pytest.mark.slow),
    pytest.param("07_survival_aft.py", marks=pytest.mark.slow),
    pytest.param("08_out_of_core.py", marks=pytest.mark.slow),
    pytest.param("09_serving.py", marks=pytest.mark.slow),
    # 10_online_refit drives 400 batched requests through the whole
    # closed loop (~15s of subprocess serving); the loop's contract
    # coverage lives tier-1 in test_online + the online-refit scenario
    pytest.param("10_online_refit.py", marks=pytest.mark.slow),
]


@pytest.mark.parametrize("name", FAST_EXAMPLES)
def test_example_runs(name):
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    path = os.path.join(REPO, "examples", name)
    # force the CPU backend via jax.config BEFORE the example runs: an
    # ambient TPU plugin with a dead tunnel hangs forever in client
    # init, and a JAX_PLATFORMS env var is too late once the site's
    # sitecustomize has imported jax (tests/conftest.py pattern)
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        f"g = {{'__file__': {path!r}, '__name__': '__main__'}}; "
        f"exec(compile(open({path!r}).read(), {path!r}, 'exec'), g)"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, (
        f"{name} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    assert proc.stdout.strip(), f"{name} printed nothing"
