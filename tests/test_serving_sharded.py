"""Mesh-sharded serving: the replica-parallel ``EnsembleExecutor``,
the unified compiled-program cache, and the N-process serving seam.

The contracts under test (ISSUE 10):

- a sharded executor's output is BITWISE-identical to the single-device
  executor and to batch ``predict_proba``/``predict`` on every ladder
  bucket and every ragged ``pack_plan`` decomposition (the established
  serving parity discipline, extended over the mesh);
- the unified program cache makes a compile paid anywhere (executor
  warmup, batch predict, AOT restore) a reuse everywhere, keyed so a
  mesh program can never masquerade as a single-device one;
- hot swaps land mid-traffic on the sharded path exactly as on the
  single-device path (the PR 2 drill, re-run over the mesh);
- ``registry.save()``'s ``serve_config.json`` lets a peer registry
  ``load()`` into the same version + executor config, with stale
  rolling swaps rejected (two in-process registries stand in for two
  serving processes behind a load balancer).

Wall-clock budget: the whole module must stay under 20 s on a warm
loaded host (tier-1 is at its ceiling — asserted by the final test).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.analysis import locks
from spark_bagging_tpu.parallel import make_mesh
from spark_bagging_tpu.parallel.compat import HAS_SHARD_MAP
from spark_bagging_tpu.serving import (
    EnsembleExecutor,
    ModelRegistry,
    program_cache,
)

pytestmark = pytest.mark.skipif(
    not HAS_SHARD_MAP,
    reason="this jax build has no shard_map implementation "
           "(parallel/compat.py)",
)


@pytest.fixture(scope="module", autouse=True)
def _module_clock():
    """Wall-clock anchor for the budget test: created when the FIRST
    test of this module runs (module import happens at collection,
    long before)."""
    return time.perf_counter()


def _counter(name: str) -> float:
    return telemetry.registry().counter(name).value


@pytest.fixture(scope="module")
def data():
    # the established serving-parity fixture data (tests/test_serving):
    # the executor-vs-batch-API bitwise discipline compares a PADDED
    # bucket program against the exact-n batch program, which is only
    # bit-stable when XLA's shape-dependent codegen happens to agree —
    # this data is the verified-stable instance the suite standardizes
    # on (sharded-vs-single-device parity, the property THIS module
    # introduces, is construction-guaranteed and data-independent)
    rng = np.random.default_rng(7)
    X = rng.normal(size=(256, 12)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.1 * rng.normal(size=256) > 0)
    return X, y.astype(np.int64)


@pytest.fixture(scope="module")
def clf(data):
    X, y = data
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=16, seed=0,
    ).fit(X, y)


@pytest.fixture(scope="module")
def clf_b(data):
    X, y = data
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=16, seed=42,
    ).fit(X, y)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(data=1, replica=8)


# -- construction contracts --------------------------------------------

def test_mesh_requires_divisible_replicas(data, mesh):
    X, y = data
    odd = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=6, seed=0,
    ).fit(X, y)
    with pytest.raises(ValueError, match="not divisible"):
        EnsembleExecutor(odd, mesh=mesh)


def test_mesh_requires_replica_only_layout(clf):
    with pytest.raises(ValueError, match="data-axis size 1"):
        EnsembleExecutor(clf, mesh=make_mesh(data=2, replica=4))


# -- bitwise parity: ladder + ragged decompositions --------------------

def test_sharded_parity_every_bucket_and_ragged_plan(clf, data, mesh):
    """The acceptance bitwise gate: sharded == single-device ==
    batch predict_proba on every ladder rung, on ragged pack_plan
    decompositions (20 -> 16+8, 48 -> 32+16), and on oversize
    top-rung splits."""
    X, _ = data
    single = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32)
    sharded = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32,
                               mesh=mesh)
    assert sharded.mesh_shape == (1, 8)
    for n in (1, 5, 8, 9, 16, 20, 24, 32, 33, 40, 48, 70):
        Xn = X[:n]
        got = sharded.forward(Xn)
        np.testing.assert_array_equal(got, single.forward(Xn))
        np.testing.assert_array_equal(got, clf.predict_proba(Xn))


@pytest.mark.slow  # [PR 17 budget offset] ~1.8s parity variant; representative coverage stays tier-1 via test_sharded_parity_every_bucket_and_ragged_plan
def test_sharded_parity_hard_voting(data, mesh):
    """Hard voting serves vote FREQUENCIES; the sharded one-hot gather
    must reproduce them exactly."""
    X, y = data
    hard = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=8, voting="hard", seed=3,
    ).fit(X, y)
    single = EnsembleExecutor(hard, min_bucket_rows=8, max_batch_rows=16)
    sharded = EnsembleExecutor(hard, min_bucket_rows=8,
                               max_batch_rows=16, mesh=mesh)
    for n in (1, 9, 16, 25):
        np.testing.assert_array_equal(
            sharded.forward(X[:n]), single.forward(X[:n])
        )


@pytest.mark.slow  # [PR 17 budget offset] ~1.6s parity variant; representative coverage stays tier-1 via test_sharded_parity_every_bucket_and_ragged_plan
def test_sharded_parity_regressor(data, mesh):
    X, y = data
    rgr = BaggingRegressor(n_estimators=16, seed=1).fit(
        X, (X[:, 0] * 2 + X[:, 1]).astype(np.float32)
    )
    single = EnsembleExecutor(rgr, min_bucket_rows=8, max_batch_rows=16)
    sharded = EnsembleExecutor(rgr, min_bucket_rows=8,
                               max_batch_rows=16, mesh=mesh)
    for n in (1, 9, 20, 33):
        np.testing.assert_array_equal(
            sharded.forward(X[:n]), single.forward(X[:n])
        )


def test_sharded_forward_parts_matches_blockwise(clf, data, mesh):
    """The micro-batcher's ragged scatter seam over the mesh: packed
    blocks come back bitwise-equal to serving each block alone."""
    X, _ = data
    sharded = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32,
                               mesh=mesh)
    parts = [X[:1], X[1:6], X[6:15], X[15:31]]
    outs = sharded.forward_parts(parts)
    for part, out in zip(parts, outs):
        np.testing.assert_array_equal(out, clf.predict_proba(part))


def test_sharded_zero_postwarmup_compiles_and_shard_counter(
    clf, data, mesh
):
    X, _ = data
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32,
                          mesh=mesh)
    ex.warmup()
    c0 = _counter("sbt_serving_compiles_total")
    s0 = _counter("sbt_serving_shard_forwards_total")
    rng = np.random.default_rng(0)
    for _ in range(10):
        n = int(rng.integers(1, 60))
        out = ex.forward(X[:n])
        assert out.shape == (n, 2)
    assert _counter("sbt_serving_compiles_total") == c0
    assert _counter("sbt_serving_shard_forwards_total") > s0


# -- the unified compiled-program cache --------------------------------

def test_program_cache_twin_executor_compiles_nothing(clf, mesh):
    """A second executor for the SAME model (same fingerprint, same
    mesh key) warms up entirely from the unified cache — the compile
    someone already paid, reused."""
    a = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=16,
                         mesh=mesh)
    a.warmup()
    c0 = _counter("sbt_serving_compiles_total")
    h0 = _counter("sbt_program_cache_hits_total")
    b = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=16,
                         mesh=mesh)
    assert b.warmup() == (8, 16)  # installed on THIS executor...
    assert _counter("sbt_serving_compiles_total") == c0  # ...no compile
    assert _counter("sbt_program_cache_hits_total") >= h0 + 2


def test_program_cache_unifies_batch_predict_and_serving(data):
    """A batch ``predict_proba`` whose row count is a ladder rung
    compiles ONE program that serving then adopts: executor warmup
    over (8, 16) pays exactly one compile — the rung batch predict
    already owns."""
    X, y = data
    model = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=8, seed=77,
    ).fit(X, y)
    model.predict_proba(X[:16])  # compiles the 16-row program
    c0 = _counter("sbt_serving_compiles_total")
    ex = EnsembleExecutor(model, min_bucket_rows=8, max_batch_rows=16)
    ex.warmup()
    assert _counter("sbt_serving_compiles_total") - c0 == 1  # bucket 8
    # and the executor's 16-rung output is the batch API's, bit for bit
    np.testing.assert_array_equal(
        ex.forward(X[:16]), model.predict_proba(X[:16])
    )


def test_program_cache_mesh_key_isolation(clf, mesh):
    """A single-device program must NEVER satisfy a mesh executor's
    lookup (or vice versa): same model, different mesh component,
    disjoint entries."""
    program_cache.clear()  # drop entries earlier tests compiled
    single = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=8)
    c0 = _counter("sbt_serving_compiles_total")
    single.warmup()
    assert _counter("sbt_serving_compiles_total") - c0 == 1
    sharded = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=8,
                               mesh=mesh)
    sharded.warmup()  # the single-device entry must NOT satisfy this
    assert _counter("sbt_serving_compiles_total") - c0 == 2
    assert single._program_key(8) != sharded._program_key(8)


def test_program_cache_lru_eviction():
    cache = program_cache.ProgramCache(capacity=2)
    keys = [
        program_cache.ProgramKey(f"fp{i}", "v", 8, None, False,
                                 "j", "cpu", "cpu")
        for i in range(3)
    ]
    for i, k in enumerate(keys):
        cache.put(k, f"prog{i}")
    assert len(cache) == 2
    assert cache.get(keys[0]) is None      # LRU-evicted
    assert cache.get(keys[2]) == "prog2"
    # put is insert-if-absent: the first program wins
    assert cache.put(keys[2], "other") == "prog2"


# -- AOT disk cache: mesh shape + device kind in the key ---------------

def test_aot_restore_same_mesh_hits(clf, data, tmp_path, mesh):
    """Good half of the key pair: a cache saved by a mesh executor
    restores into a same-mesh peer process with zero compiles."""
    X, _ = data
    ckpt = str(tmp_path / "mesh_warm")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16, mesh=mesh)
    reg.register("m", clf, warmup=True)
    reg.save("m", ckpt)
    assert os.path.isdir(os.path.join(ckpt, "serving_aot"))

    program_cache.clear()  # simulate the fresh peer process
    c0 = _counter("sbt_serving_compiles_total")
    r0 = _counter("sbt_serving_aot_restored_total")
    peer = ModelRegistry(min_bucket_rows=8, max_batch_rows=16, mesh=mesh)
    ex = peer.load("m2", ckpt, warm=True)
    assert ex.mesh is not None
    assert _counter("sbt_serving_compiles_total") == c0
    assert _counter("sbt_serving_aot_restored_total") - r0 == 2
    np.testing.assert_array_equal(
        ex.forward(X[:9]), clf.predict_proba(X[:9])
    )


def test_aot_single_device_cache_into_mesh_is_counted_miss(
    clf, data, tmp_path, mesh
):
    """Bad half: a SINGLE-DEVICE cache restored into a mesh process is
    a counted miss — never a crash, and never a silently single-device
    executor."""
    X, _ = data
    ckpt = str(tmp_path / "flat_warm")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    reg.register("m", clf, warmup=True)
    reg.save("m", ckpt)
    # the saved manifest key records mesh=None + this chip kind
    with open(os.path.join(ckpt, "serving_aot",
                           "aot_manifest.json")) as f:
        key = json.load(f)["key"]
    assert key["mesh"] is None
    assert key["device_kind"]

    program_cache.clear()
    m0 = _counter("sbt_serving_aot_misses_total")
    peer = ModelRegistry(min_bucket_rows=8, max_batch_rows=16, mesh=mesh)
    with pytest.warns(UserWarning, match="different key"):
        ex = peer.load("m2", ckpt, warm=True)
    assert _counter("sbt_serving_aot_misses_total") > m0
    assert ex.mesh is not None          # still sharded, not silently flat
    assert ex.mesh_shape == (1, 8)
    np.testing.assert_array_equal(
        ex.forward(X[:9]), clf.predict_proba(X[:9])
    )


# -- swap-under-shard: the PR 2 drill over the mesh --------------------

def test_hot_swap_atomic_mid_traffic_on_sharded_executor(
    clf, clf_b, data, mesh
):
    """Every mid-swap result is exactly model A's or model B's answer,
    served by the replica-sharded program — never an error, never a
    mixture."""
    X, _ = data
    pool = 48  # rows the clients draw from (refs served per-row below)
    # refs are served PER ROW through single-device executors: the
    # sharded executor is construction-guaranteed bitwise-equal to
    # these (the parity tests above), so any mid-swap mixture or
    # corruption — the property under test — shows up exactly
    exa = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=16)
    exb = EnsembleExecutor(clf_b, min_bucket_rows=8, max_batch_rows=16)
    ref_a = np.vstack([exa.forward(X[i:i + 1]) for i in range(pool)])
    ref_b = np.vstack([exb.forward(X[i:i + 1]) for i in range(pool)])
    assert not np.array_equal(ref_a, ref_b)

    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16, mesh=mesh)
    reg.register("m", clf, warmup=True)
    assert reg.executor("m").mesh is not None
    stop = threading.Event()
    errors: list = []
    checked = [0]

    def client(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            i = int(rng.integers(0, pool))
            try:
                r = b.submit(X[i:i + 1]).result(30)
            except Exception as e:  # noqa: BLE001 — collected for assert
                errors.append(e)
                return
            if not (np.array_equal(r, ref_a[i:i + 1])
                    or np.array_equal(r, ref_b[i:i + 1])):
                errors.append(AssertionError(f"row {i}: mixed result"))
                return
            checked[0] += 1

    with reg.batcher("m", max_delay_ms=1, max_queue=256) as b:
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(3)]
        for t in threads:
            t.start()
        model = [clf_b, clf]
        for k in range(2):
            if errors:
                break
            new = reg.swap("m", model[k % 2])
            assert new.mesh is not None  # the mesh opt is sticky
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(60)
    assert not errors, errors[:3]
    assert checked[0] > 0


# -- serve_config: the N-process seam ----------------------------------

def test_serve_config_round_trip_two_registries(
    clf, clf_b, data, tmp_path, mesh
):
    """Two in-process registries stand in for two serving processes
    behind a load balancer: B loads A's checkpoint into the same
    version + executor config; a rolling swap moves both forward; a
    stale manifest is rejected loudly; a same-version re-load is an
    idempotent no-op."""
    X, _ = data
    ckpt_v1 = str(tmp_path / "v1")
    ckpt_v2 = str(tmp_path / "v2")

    a = ModelRegistry()
    a.register("m", clf, warmup=True, min_bucket_rows=8,
               max_batch_rows=16, mesh=mesh)
    a.save("m", ckpt_v1)
    cfg = json.load(open(os.path.join(ckpt_v1, "serve_config.json")))
    assert cfg["version"] == 1
    assert cfg["executor"]["mesh"] == [1, 8]
    assert cfg["executor"]["min_bucket_rows"] == 8

    b = ModelRegistry()
    ex_b = b.load("m", ckpt_v1, warm=True)
    # the peer adopted the saver's whole serving shape, zero-config
    assert b.version("m") == a.version("m") == 1
    assert ex_b.mesh_shape == (1, 8)
    assert ex_b.min_bucket_rows == 8 and ex_b.max_batch_rows == 16
    np.testing.assert_array_equal(
        ex_b.forward(X[:5]), a.executor("m").forward(X[:5])
    )
    assert b.health()["models"] == a.health()["models"]

    # same-version re-load: idempotent no-op, same live executor
    assert b.load("m", ckpt_v1) is ex_b

    # rolling swap: A ships version 2, B converges on load
    a.swap("m", clf_b)
    a.save("m", ckpt_v2)
    ex_b2 = b.load("m", ckpt_v2, warm=True)
    assert b.version("m") == a.version("m") == 2
    assert ex_b2.mesh is not None
    np.testing.assert_array_equal(
        ex_b2.forward(X[:5]), clf_b.predict_proba(X[:5])
    )

    # the stale manifest (v1) over the live v2 is a loud rejection
    with pytest.raises(ValueError, match="stale"):
        b.load("m", ckpt_v1)
    assert b.version("m") == 2


def test_equal_version_race_converges_without_incident(clf, data):
    """Two peers racing to install the same manifest version must
    CONVERGE: the loser gets the winner's live executor back — no
    ValueError, no spurious swap-rejected incident (the load() path
    passes _equal_version_ok for manifest-versioned swaps)."""
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    ex = reg.register("m", clf, version=3)
    r0 = _counter("sbt_serving_swap_rejected_total")
    # the loser's swap: same manifest version the winner installed
    got = reg.swap("m", clf, version=3, _equal_version_ok=True)
    assert got is ex
    assert reg.version("m") == 3
    assert _counter("sbt_serving_swap_rejected_total") == r0
    # without the convergence flag, equal version is the loud stale
    # rejection the rolling-swap rules promise
    with pytest.raises(ValueError, match="stale"):
        reg.swap("m", clf, version=3)
    assert _counter("sbt_serving_swap_rejected_total") == r0 + 1


def test_serve_config_mesh_smaller_than_host_builds_prefix(
    clf, data, tmp_path
):
    """A peer with MORE devices than the manifest mesh builds the
    recorded shape over a device prefix — the rolling-upgrade case
    must not silently lose replica parallelism."""
    import jax

    X, _ = data
    small = make_mesh(data=1, replica=4, devices=jax.devices()[:4])
    ckpt = str(tmp_path / "small_mesh")
    a = ModelRegistry(min_bucket_rows=8, max_batch_rows=16, mesh=small)
    a.register("m", clf, warmup=True)
    a.save("m", ckpt)

    program_cache.clear()
    c0 = _counter("sbt_serving_compiles_total")
    b = ModelRegistry()  # this "host" has 8 devices
    ex = b.load("m", ckpt, warm=True)
    assert ex.mesh_shape == (1, 4)
    assert _counter("sbt_serving_compiles_total") == c0  # AOT warm
    np.testing.assert_array_equal(
        ex.forward(X[:9]), a.executor("m").forward(X[:9])
    )


def test_serve_config_malformed_mesh_degrades(clf, tmp_path):
    """A truncated "mesh" entry in a hand-edited manifest degrades to
    single-device with a warning — corrupt manifests never crash a
    load."""
    ckpt = str(tmp_path / "torn_mesh")
    a = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
    a.register("m", clf, warmup=True)
    a.save("m", ckpt)
    cfg_path = os.path.join(ckpt, "serve_config.json")
    cfg = json.load(open(cfg_path))
    cfg["executor"]["mesh"] = [8]  # truncated
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    b = ModelRegistry()
    with pytest.warns(UserWarning, match="cannot build"):
        ex = b.load("m", ckpt, warm=True)
    assert ex.mesh is None


def test_serve_config_mesh_degrades_with_warning(
    clf, data, tmp_path, mesh, monkeypatch
):
    """A peer without the devices for the persisted mesh serves
    single-device with a warning — mesh-mismatched AOT entries are
    counted misses, never wrong answers."""
    X, _ = data
    ckpt = str(tmp_path / "big_mesh")
    a = ModelRegistry(min_bucket_rows=8, max_batch_rows=16, mesh=mesh)
    a.register("m", clf, warmup=True)
    a.save("m", ckpt)
    cfg_path = os.path.join(ckpt, "serve_config.json")
    cfg = json.load(open(cfg_path))
    cfg["executor"]["mesh"] = [1, 16]  # devices this host lacks
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    b = ModelRegistry()
    m0 = _counter("sbt_serving_aot_misses_total")
    with pytest.warns(UserWarning, match="cannot build"):
        ex = b.load("m", ckpt, warm=True)
    assert ex.mesh is None
    assert _counter("sbt_serving_aot_misses_total") > m0
    np.testing.assert_array_equal(
        ex.forward(X[:5]), clf.predict_proba(X[:5])
    )


# -- deterministic replay over the sharded path ------------------------

@pytest.mark.slow  # [PR 17 budget offset] ~2.5s replay twin; the sharded-parity scenario reproduces steady-poisson's committed output digest bitwise in the conformance smoke
def test_replay_devices_mode_serves_sharded_deterministically():
    """``benchmarks/replay.py --devices 8``: the deterministic replay
    gate covers the sharded path — virtual-mode digests are stable and
    post-warmup compiles are zero (in-process; the conftest already
    forces 8 devices)."""
    from benchmarks import replay as replay_mod

    out = os.path.join(
        telemetry.telemetry_dir(), "replay_sharded_test.json"
    )
    rc = replay_mod.main([
        "--devices", "8", "--rate", "60", "--duration", "0.3",
        "--repeats", "2", "--n-estimators", "8", "--out", out,
    ])
    assert rc == 0
    report = json.load(open(out))
    assert report["post_warmup_compiles"] == 0
    assert report["served"] == report["n_requests"]
    os.unlink(out)


# -- lock discipline over the new shard-cache locks --------------------

def test_no_lock_order_violations_across_cache_and_registry(
    clf, data, mesh
):
    """The PR 4 detector over the new edges: program-cache lock vs
    executor build lock vs registry lock, exercised through warmup,
    swap, and save/load — no inversions."""
    X, _ = data
    locks.enable(True, strict=False)
    locks.clear()
    try:
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16,
                            mesh=mesh)
        reg.register("m", clf, warmup=True)
        reg.executor("m").forward(X[:5])
        program_cache.cache().stats()
        program_cache.cache().get(
            reg.executor("m")._program_key(8)
        )
        assert not locks.violations()
    finally:
        locks.clear()
        locks.enable(False)


def test_module_wall_clock_budget(_module_clock):
    """Tier-1 is at its ceiling: this module promised to stay cheap
    (the quality-suite discipline)."""
    elapsed = time.perf_counter() - _module_clock
    assert elapsed < 20.0, (
        f"sharded-serving suite took {elapsed:.1f}s — over its 20s "
        "budget; shrink fixtures or mark the heavy test slow"
    )
