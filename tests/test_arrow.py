"""Arrow ingestion [B:5 north star "via Arrow", VERDICT r1 #5]:
parquet/feather → (X, y), streaming chunks, sharded device placement."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import pyarrow.parquet as pq

from spark_bagging_tpu import BaggingClassifier, LogisticRegression
from spark_bagging_tpu.parallel import device_put_rows, make_mesh
from spark_bagging_tpu.utils.arrow import ArrowChunks, load_arrow
from spark_bagging_tpu.utils.datasets import load_dataset


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((600, 5)).astype(np.float32)
    y = (X[:, 0] - X[:, 3] > 0).astype(np.int64)
    return X, y


@pytest.fixture(scope="module", params=["parquet", "feather"])
def arrow_file(request, xy, tmp_path_factory):
    X, y = xy
    table = pa.table(
        {f"f{j}": X[:, j] for j in range(X.shape[1])} | {"label": y}
    )
    path = tmp_path_factory.mktemp("a") / f"data.{request.param}"
    if request.param == "parquet":
        pq.write_table(table, path, row_group_size=128)
    else:
        with pa.OSFile(str(path), "wb") as sink:
            with pa.ipc.new_file(sink, table.schema) as writer:
                for batch in table.to_batches(max_chunksize=128):
                    writer.write_batch(batch)
    return str(path)


def test_load_arrow_roundtrip(arrow_file, xy):
    X, y = xy
    Xl, yl = load_arrow(arrow_file, label_col="label")
    np.testing.assert_array_equal(Xl, X)
    np.testing.assert_array_equal(yl, y)
    # index addressing (label is the last column)
    Xi, yi = load_arrow(arrow_file, label_col=-1)
    np.testing.assert_array_equal(Xi, X)
    np.testing.assert_array_equal(yi, y)


def test_load_arrow_bad_label(arrow_file):
    with pytest.raises(ValueError, match="not in schema"):
        load_arrow(arrow_file, label_col="nope")
    with pytest.raises(ValueError, match="out of range"):
        load_arrow(arrow_file, label_col=17)


def test_load_dataset_dispatches_arrow(arrow_file, xy):
    X, y = xy
    Xl, yl = load_dataset(arrow_file, label_col="label")
    np.testing.assert_array_equal(Xl, X)
    np.testing.assert_array_equal(yl, y)


def test_arrow_chunks_match_whole_file(arrow_file, xy):
    X, y = xy
    src = ArrowChunks(arrow_file, chunk_rows=100, label_col="label")
    assert src.n_rows == 600
    assert src.n_features == 5
    parts = [(Xc[:n], yc[:n]) for Xc, yc, n in src.chunks()]
    np.testing.assert_array_equal(np.concatenate([p[0] for p in parts]), X)
    np.testing.assert_array_equal(np.concatenate([p[1] for p in parts]), y)
    # fixed shapes: every chunk is (chunk_rows, F)
    for Xc, _, _ in src.chunks():
        assert Xc.shape == (100, 5)


def test_arrow_chunks_column_subset(arrow_file, xy):
    X, _ = xy
    src = ArrowChunks(
        arrow_file, chunk_rows=256, label_col="label",
        columns=["f0", "f2"],
    )
    assert src.n_features == 2
    Xc, _, n = next(iter(src.chunks()))
    np.testing.assert_array_equal(Xc[:n], X[:256][:, [0, 2]])


def test_fit_stream_from_parquet_on_mesh(arrow_file, xy):
    """The VERDICT done-criterion: a parquet file round-trips through
    fit_stream on the CPU mesh."""
    X, y = xy
    src = ArrowChunks(arrow_file, chunk_rows=200, label_col="label")
    clf = BaggingClassifier(
        base_learner=LogisticRegression(solver="adam", max_iter=30),
        n_estimators=8,
        seed=0,
        mesh=make_mesh(),
    )
    clf.fit_stream(src, classes=[0, 1], n_epochs=3, lr=0.1)
    assert clf.score(X, y) > 0.85


def test_device_put_rows_sharding(xy):
    import jax

    X, _ = xy
    mesh = make_mesh(data=4)
    Xd = device_put_rows(X[:400], mesh)
    assert Xd.shape == (400, 5)
    # each device holds a (100, 5) row shard
    shard_shapes = {s.data.shape for s in Xd.addressable_shards}
    assert shard_shapes == {(100, 5)}
    with pytest.raises(ValueError, match="divisible"):
        device_put_rows(X[:401], mesh)
    np.testing.assert_array_equal(np.asarray(Xd), X[:400])
