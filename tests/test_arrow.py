"""Arrow ingestion [B:5 north star "via Arrow", VERDICT r1 #5]:
parquet/feather → (X, y), streaming chunks, sharded device placement."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import pyarrow.parquet as pq

from spark_bagging_tpu import BaggingClassifier, LogisticRegression
from spark_bagging_tpu.parallel import device_put_rows, make_mesh
from spark_bagging_tpu.utils.arrow import ArrowChunks, load_arrow
from spark_bagging_tpu.utils.datasets import load_dataset


@pytest.fixture(scope="module")
def xy():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((600, 5)).astype(np.float32)
    y = (X[:, 0] - X[:, 3] > 0).astype(np.int64)
    return X, y


@pytest.fixture(scope="module", params=["parquet", "feather"])
def arrow_file(request, xy, tmp_path_factory):
    X, y = xy
    table = pa.table(
        {f"f{j}": X[:, j] for j in range(X.shape[1])} | {"label": y}
    )
    path = tmp_path_factory.mktemp("a") / f"data.{request.param}"
    if request.param == "parquet":
        pq.write_table(table, path, row_group_size=128)
    else:
        with pa.OSFile(str(path), "wb") as sink:
            with pa.ipc.new_file(sink, table.schema) as writer:
                for batch in table.to_batches(max_chunksize=128):
                    writer.write_batch(batch)
    return str(path)


def test_load_arrow_roundtrip(arrow_file, xy):
    X, y = xy
    Xl, yl = load_arrow(arrow_file, label_col="label")
    np.testing.assert_array_equal(Xl, X)
    np.testing.assert_array_equal(yl, y)
    # index addressing (label is the last column)
    Xi, yi = load_arrow(arrow_file, label_col=-1)
    np.testing.assert_array_equal(Xi, X)
    np.testing.assert_array_equal(yi, y)


def test_load_arrow_bad_label(arrow_file):
    with pytest.raises(ValueError, match="not in schema"):
        load_arrow(arrow_file, label_col="nope")
    with pytest.raises(ValueError, match="out of range"):
        load_arrow(arrow_file, label_col=17)


def test_load_dataset_dispatches_arrow(arrow_file, xy):
    X, y = xy
    Xl, yl = load_dataset(arrow_file, label_col="label")
    np.testing.assert_array_equal(Xl, X)
    np.testing.assert_array_equal(yl, y)


def test_arrow_chunks_match_whole_file(arrow_file, xy):
    X, y = xy
    src = ArrowChunks(arrow_file, chunk_rows=100, label_col="label")
    assert src.n_rows == 600
    assert src.n_features == 5
    parts = [(Xc[:n], yc[:n]) for Xc, yc, n in src.chunks()]
    np.testing.assert_array_equal(np.concatenate([p[0] for p in parts]), X)
    np.testing.assert_array_equal(np.concatenate([p[1] for p in parts]), y)
    # fixed shapes: every chunk is (chunk_rows, F)
    for Xc, _, _ in src.chunks():
        assert Xc.shape == (100, 5)


def test_arrow_chunks_column_subset(arrow_file, xy):
    X, _ = xy
    src = ArrowChunks(
        arrow_file, chunk_rows=256, label_col="label",
        columns=["f0", "f2"],
    )
    assert src.n_features == 2
    Xc, _, n = next(iter(src.chunks()))
    np.testing.assert_array_equal(Xc[:n], X[:256][:, [0, 2]])


def test_fit_stream_from_parquet_on_mesh(arrow_file, xy):
    """The VERDICT done-criterion: a parquet file round-trips through
    fit_stream on the CPU mesh."""
    X, y = xy
    src = ArrowChunks(arrow_file, chunk_rows=200, label_col="label")
    clf = BaggingClassifier(
        base_learner=LogisticRegression(solver="adam", max_iter=30),
        n_estimators=8,
        seed=0,
        mesh=make_mesh(),
    )
    clf.fit_stream(src, classes=[0, 1], n_epochs=3, lr=0.1)
    assert clf.score(X, y) > 0.85


def test_device_put_rows_sharding(xy):
    import jax

    X, _ = xy
    mesh = make_mesh(data=4)
    Xd = device_put_rows(X[:400], mesh)
    assert Xd.shape == (400, 5)
    # each device holds a (100, 5) row shard
    shard_shapes = {s.data.shape for s in Xd.addressable_shards}
    assert shard_shapes == {(100, 5)}
    with pytest.raises(ValueError, match="divisible"):
        device_put_rows(X[:401], mesh)
    np.testing.assert_array_equal(np.asarray(Xd), X[:400])


class TestFixedSizeListFeatures:
    """Row-major feature blocks: ONE fixed-size-list column is the
    (n, d) matrix already, so decode is a reshape instead of a
    column→row transpose (round 5 — the transpose capped wide-data
    scans at ~150 MB/s and would starve a TPU stream)."""

    @pytest.fixture(scope="class", params=["feather", "parquet"])
    def fsl_file(self, request, xy, tmp_path_factory):
        X, y = xy
        path = tmp_path_factory.mktemp("fsl") / f"d.{request.param}"
        if request.param == "parquet":
            fsl = pa.FixedSizeListArray.from_arrays(
                pa.array(np.ascontiguousarray(X).reshape(-1)), X.shape[1]
            )
            pq.write_table(pa.table({"features": fsl, "label": y}),
                           path, row_group_size=128)
        else:
            from spark_bagging_tpu.utils.arrow import write_row_major_ipc

            write_row_major_ipc(str(path), X, y, chunk_rows=128)
        return str(path)

    def test_load_arrow_fsl(self, fsl_file, xy):
        X, y = xy
        Xl, yl = load_arrow(fsl_file, label_col="label")
        np.testing.assert_array_equal(Xl, X)
        np.testing.assert_array_equal(yl, y.astype(np.float32))
        assert Xl.dtype == np.float32

    def test_chunks_match_wide_layout(self, fsl_file, arrow_file, xy):
        X, _ = xy
        fsl_src = ArrowChunks(fsl_file, chunk_rows=100)
        assert fsl_src.n_features == X.shape[1]
        assert fsl_src.n_rows == X.shape[0]
        wide_src = ArrowChunks(arrow_file, chunk_rows=100)
        for (Xa, ya, na), (Xb, yb, nb) in zip(
            fsl_src.chunks(), wide_src.chunks()
        ):
            assert na == nb
            np.testing.assert_array_equal(Xa[:na], Xb[:nb])
            np.testing.assert_array_equal(ya[:na], yb[:nb])

    def test_sliced_batch_respects_offset(self, xy):
        # flatten() must honor slice offsets — .values would silently
        # return the WHOLE buffer for a sliced batch
        from spark_bagging_tpu.utils.arrow import _batch_to_xy

        X, y = xy
        fsl = pa.FixedSizeListArray.from_arrays(
            pa.array(np.ascontiguousarray(X).reshape(-1)), X.shape[1]
        )
        batch = pa.record_batch(
            {"features": fsl, "label": pa.array(y)}
        ).slice(37, 200)
        Xs, ys = _batch_to_xy(batch, ["features"], "label")
        np.testing.assert_array_equal(Xs, X[37:237])
        np.testing.assert_array_equal(ys, y[37:237].astype(np.float32))

    def test_null_rows_rejected(self, xy):
        from spark_bagging_tpu.utils.arrow import _batch_to_xy

        X, y = xy
        fsl = pa.FixedSizeListArray.from_arrays(
            pa.array(np.ascontiguousarray(X[:4]).reshape(-1)), X.shape[1]
        )
        with_null = pa.concat_arrays(
            [fsl, pa.array([None], fsl.type)]
        )
        batch = pa.record_batch(
            {"features": with_null, "label": pa.array(y[:5])}
        )
        with pytest.raises(ValueError, match="null rows"):
            _batch_to_xy(batch, ["features"], "label")

    def test_fsl_plus_other_features_rejected(self, xy, tmp_path):
        X, y = xy
        fsl = pa.FixedSizeListArray.from_arrays(
            pa.array(np.ascontiguousarray(X).reshape(-1)), X.shape[1]
        )
        table = pa.table(
            {"features": fsl, "extra": X[:, 0], "label": y}
        )
        path = str(tmp_path / "mixed.arrow")
        with pa.OSFile(path, "wb") as sink:
            with pa.ipc.new_file(sink, table.schema) as writer:
                writer.write_table(table)
        with pytest.raises(ValueError, match="ONLY"):
            ArrowChunks(path, chunk_rows=100)

    def test_fit_stream_from_fsl(self, fsl_file, xy):
        X, y = xy
        clf = BaggingClassifier(
            base_learner=LogisticRegression(max_iter=5),
            n_estimators=4, seed=0,
        ).fit_stream(
            ArrowChunks(fsl_file, chunk_rows=150), classes=[0, 1],
            lr=0.05, steps_per_chunk=2,
        )
        assert clf.n_features_in_ == X.shape[1]
        assert clf.score(X, y) > 0.8

    def test_load_arrow_mixed_fsl_rejected(self, xy, tmp_path):
        # the guard is shared with ArrowChunks: same clear error, not a
        # cryptic np.stack failure
        X, y = xy
        fsl = pa.FixedSizeListArray.from_arrays(
            pa.array(np.ascontiguousarray(X).reshape(-1)), X.shape[1]
        )
        table = pa.table(
            {"features": fsl, "extra": X[:, 0], "label": y}
        )
        path = str(tmp_path / "mixed2.arrow")
        with pa.OSFile(path, "wb") as sink:
            with pa.ipc.new_file(sink, table.schema) as writer:
                writer.write_table(table)
        with pytest.raises(ValueError, match="ONLY"):
            load_arrow(path, label_col="label")


@pytest.mark.parametrize("chunk_rows", [100, 97])
def test_chunks_from_seek_exact(arrow_file, chunk_rows):
    """Row-exact seek: chunks_from(k) must reproduce chunks()[k:] even
    when chunk boundaries don't align with the file's 128-row record
    batches (round 5 — IPC random access / parquet row-group skip
    replaces the consume-and-discard fallback)."""
    src = ArrowChunks(arrow_file, chunk_rows=chunk_rows)
    full = list(src.chunks())
    for k in (0, 1, 3, src.n_chunks - 1, src.n_chunks):
        tail = list(src.chunks_from(k))
        assert len(tail) == len(full) - k
        for (Xa, ya, na), (Xb, yb, nb) in zip(tail, full[k:]):
            assert na == nb
            np.testing.assert_array_equal(Xa, Xb)
            np.testing.assert_array_equal(ya, yb)
