"""Behavioral tests for the bagging estimators — the reference's suite
strategy [SURVEY §4]: accuracy vs single learner, degenerate-ensemble
equivalence, seed determinism, param round-trips, sklearn parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_diabetes, load_iris
from sklearn.ensemble import BaggingClassifier as SkBagging
from sklearn.linear_model import LogisticRegression as SkLogReg
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import BaggingClassifier, BaggingRegressor
from spark_bagging_tpu.models import LinearRegression, LogisticRegression


@pytest.fixture(scope="module")
def breast_cancer():
    X, y = load_breast_cancer(return_X_y=True)
    return StandardScaler().fit_transform(X).astype(np.float32), y


@pytest.fixture(scope="module")
def iris():
    X, y = load_iris(return_X_y=True)
    return StandardScaler().fit_transform(X).astype(np.float32), y


@pytest.fixture(scope="module")
def diabetes():
    X, y = load_diabetes(return_X_y=True)
    return (
        StandardScaler().fit_transform(X).astype(np.float32),
        y.astype(np.float32),
    )


class TestBaggingClassifier:
    @pytest.mark.slow  # [PR 14 pyramid] ~2.1s accuracy soak; aggregation correctness stays tier-1 via exact tests
    def test_accuracy_close_to_single_learner(self, breast_cancer):
        """Bagged accuracy ≈/≥ single base learner [SURVEY §4]."""
        X, y = breast_cancer
        clf = BaggingClassifier(n_estimators=10, seed=7).fit(X, y)
        lr = LogisticRegression()
        params, _ = lr.fit_from_init(
            jax.random.key(0), jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(len(y)), 2,
        )
        single = (np.asarray(lr.predict_scores(params, jnp.asarray(X)).argmax(1)) == y).mean()
        assert clf.score(X, y) >= single - 0.01

    @pytest.mark.slow  # [PR 17 budget offset] ~2s n_estimators=1 equivalence soak; ensemble correctness stays tier-1 via test_sklearn_parity + test_oob_score
    def test_degenerate_ensemble_equals_base_learner(self, breast_cancer):
        """n_estimators=1, no bootstrap, full features ⇒ exactly the base
        learner [SURVEY §4]."""
        X, y = breast_cancer
        clf = BaggingClassifier(
            n_estimators=1, bootstrap=False, max_samples=1.0
        ).fit(X, y)
        lr = LogisticRegression()
        params, _ = lr.fit_from_init(
            jax.random.key(0), jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(len(y)), 2,
        )
        direct = np.asarray(lr.predict_scores(params, jnp.asarray(X)).argmax(1))
        np.testing.assert_array_equal(clf.predict(X), direct)

    def test_seed_determinism(self, iris):
        X, y = iris
        a = BaggingClassifier(n_estimators=8, max_features=0.5, seed=3).fit(X, y)
        b = BaggingClassifier(n_estimators=8, max_features=0.5, seed=3).fit(X, y)
        np.testing.assert_array_equal(a.predict(X), b.predict(X))
        np.testing.assert_array_equal(
            np.asarray(a.subspaces_), np.asarray(b.subspaces_)
        )

    def test_different_seeds_differ(self, iris):
        X, y = iris
        a = BaggingClassifier(n_estimators=4, max_features=0.5, seed=0).fit(X, y)
        b = BaggingClassifier(n_estimators=4, max_features=0.5, seed=1).fit(X, y)
        assert not np.array_equal(np.asarray(a.subspaces_), np.asarray(b.subspaces_))

    @pytest.mark.slow  # [PR 14 pyramid] ~1.9s normalization soak; covered by the fuzz score-shape invariants tier-1
    def test_predict_proba_normalized(self, iris):
        X, y = iris
        for voting in ("soft", "hard"):
            clf = BaggingClassifier(n_estimators=5, voting=voting).fit(X, y)
            proba = clf.predict_proba(X)
            assert proba.shape == (len(y), 3)
            np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)

    def test_hard_vote_matches_manual_majority(self, iris):
        X, y = iris
        clf = BaggingClassifier(n_estimators=7, voting="hard", seed=2).fit(X, y)
        from spark_bagging_tpu.ensemble import predict_scores_ensemble

        scores = predict_scores_ensemble(
            clf._fitted_learner, clf.ensemble_, clf.subspaces_, jnp.asarray(X)
        )
        manual = np.asarray(scores.argmax(-1))  # (R, n)
        expected = np.array(
            [np.bincount(manual[:, i], minlength=3).argmax() for i in range(len(y))]
        )
        np.testing.assert_array_equal(clf.predict(X), expected)

    def test_oob_score(self, breast_cancer):
        X, y = breast_cancer
        clf = BaggingClassifier(n_estimators=20, oob_score=True, seed=5).fit(X, y)
        assert 0.9 < clf.oob_score_ <= 1.0
        assert clf.oob_score_ <= clf.score(X, y) + 0.02  # OOB is held-out-ish
        assert clf.oob_decision_function_.shape == (len(y), 2)

    def test_string_labels(self, iris):
        X, y = iris
        names = np.array(["setosa", "versicolor", "virginica"])[y]
        clf = BaggingClassifier(n_estimators=5).fit(X, names)
        assert set(clf.predict(X)) <= set(names)
        assert clf.score(X, names) > 0.9

    @pytest.mark.slow  # [PR 16 pyramid] ~3.7s chunked-vs-unchunked parity soak; chunking parity stays tier-1 via test_tree.py::TestTreeBagging::test_chunked_fit_matches_vmap
    def test_chunked_equals_unchunked(self, iris):
        X, y = iris
        a = BaggingClassifier(n_estimators=8, seed=4).fit(X, y)
        b = BaggingClassifier(n_estimators=8, seed=4, chunk_size=3).fit(X, y)
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), atol=1e-5
        )

    def test_max_features_int_and_float(self, iris):
        X, y = iris
        a = BaggingClassifier(n_estimators=4, max_features=2).fit(X, y)
        b = BaggingClassifier(n_estimators=4, max_features=0.5).fit(X, y)
        assert a.subspaces_.shape == (4, 2)
        assert b.subspaces_.shape == (4, 2)

    def test_subsampling_without_replacement(self, iris):
        X, y = iris
        clf = BaggingClassifier(
            n_estimators=6, bootstrap=False, max_samples=0.7
        ).fit(X, y)
        assert clf.score(X, y) > 0.85

    def test_sklearn_parity(self, breast_cancer):
        """Accuracy within tolerance of sklearn's BaggingClassifier at
        matched hyperparameters — the CI proxy for 'ensemble acc vs
        Spark-CPU' [B:2, SURVEY §4]."""
        X, y = breast_cancer
        ours = BaggingClassifier(n_estimators=10, seed=0).fit(X, y)
        sk = SkBagging(
            estimator=SkLogReg(max_iter=2000),
            n_estimators=10,
            random_state=0,
        ).fit(X, y)
        assert abs(ours.score(X, y) - sk.score(X, y)) < 0.02

    def test_errors(self, iris):
        X, y = iris
        with pytest.raises(ValueError, match="n_estimators"):
            BaggingClassifier(n_estimators=0).fit(X, y)
        with pytest.raises(ValueError, match="classification"):
            BaggingClassifier(base_learner=LinearRegression()).fit(X, y)
        with pytest.raises(RuntimeError, match="not fitted"):
            BaggingClassifier().predict(X)
        with pytest.raises(ValueError, match="single class"):
            BaggingClassifier().fit(X, np.zeros(len(y)))
        with pytest.raises(ValueError, match="row counts"):
            BaggingClassifier().fit(X, y[:-1])
        with pytest.raises(ValueError, match="out-of-bag"):
            BaggingClassifier(bootstrap=False, oob_score=True).fit(X, y)

    def test_predict_rejects_wrong_feature_count(self, iris):
        X, y = iris
        clf = BaggingClassifier(n_estimators=3, max_features=0.5).fit(X, y)
        with pytest.raises(ValueError, match="features"):
            clf.predict(X[:, :2])

    def test_set_params_after_fit_does_not_corrupt_predict(self, iris):
        X, y = iris
        clf = BaggingClassifier(n_estimators=6).fit(X, y)
        before = clf.predict_proba(X)
        clf.set_params(n_estimators=12)  # e.g. grid-search reuse
        np.testing.assert_allclose(clf.predict_proba(X), before)
        np.testing.assert_allclose(before.sum(axis=1), 1.0, rtol=1e-5)


class TestBaggingRegressor:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.7s regressor quality soak; mean-aggregation exactness stays tier-1
    def test_r2_and_oob(self, diabetes):
        X, y = diabetes
        reg = BaggingRegressor(n_estimators=20, oob_score=True, seed=1).fit(X, y)
        assert reg.score(X, y) > 0.45
        assert 0.3 < reg.oob_score_ <= reg.score(X, y) + 0.02
        assert reg.oob_prediction_.shape == (len(y),)

    def test_degenerate_equals_base(self, diabetes):
        X, y = diabetes
        reg = BaggingRegressor(n_estimators=1, bootstrap=False).fit(X, y)
        lin = LinearRegression()
        params, _ = lin.fit_from_init(
            jax.random.key(0), jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        direct = np.asarray(lin.predict_scores(params, jnp.asarray(X)))
        # float32 reduction-order noise between vmapped and direct fits
        np.testing.assert_allclose(reg.predict(X), direct, rtol=1e-4, atol=1e-3)

    def test_mean_aggregation(self, diabetes):
        X, y = diabetes
        reg = BaggingRegressor(n_estimators=5, seed=2).fit(X, y)
        from spark_bagging_tpu.ensemble import predict_scores_ensemble

        scores = predict_scores_ensemble(
            reg._fitted_learner, reg.ensemble_, reg.subspaces_, jnp.asarray(X)
        )
        np.testing.assert_allclose(
            reg.predict(X), np.asarray(scores).mean(axis=0), rtol=1e-5
        )

    def test_column_vector_y_is_ravelled(self, diabetes):
        X, y = diabetes
        a = BaggingRegressor(n_estimators=3).fit(X, y.reshape(-1, 1))
        b = BaggingRegressor(n_estimators=3).fit(X, y)
        assert a.predict(X).shape == (len(y),)
        np.testing.assert_allclose(a.predict(X), b.predict(X), rtol=1e-5)
        assert a.fit_report_["loss_mean"] == pytest.approx(
            b.fit_report_["loss_mean"], rel=1e-5
        )
        with pytest.raises(ValueError, match="1-D"):
            BaggingRegressor().fit(X, np.stack([y, y], axis=1))

    def test_fit_report(self, diabetes):
        X, y = diabetes
        reg = BaggingRegressor(n_estimators=8).fit(X, y)
        rep = reg.fit_report_
        assert rep["n_replicas"] == 8
        assert rep["fits_per_sec"] > 0
        assert rep["backend"] == "cpu" and rep["n_devices"] == 8


class TestParamsProtocol:
    def test_roundtrip_and_nested(self):
        clf = BaggingClassifier(
            base_learner=LogisticRegression(l2=0.5), n_estimators=3
        )
        params = clf.get_params()
        assert params["base_learner__l2"] == 0.5
        clf.set_params(base_learner__l2=0.9, n_estimators=4)
        assert clf.base_learner.l2 == 0.9 and clf.n_estimators == 4

    def test_clone_is_unfitted(self, iris=None):
        clf = BaggingClassifier(n_estimators=2)
        X, y = load_iris(return_X_y=True)
        clf.fit(X.astype(np.float32), y)
        c = clf.clone()
        assert not hasattr(c, "ensemble_")
        assert c.get_params(deep=False) == clf.get_params(deep=False)


class TestSampleWeight:
    """User sample_weight = the reference's weight-column semantics:
    weights multiply every replica's bootstrap counts."""

    def test_weighted_equals_duplicated_rows(self, breast_cancer):
        X, y = breast_cancer
        X, y = X[:120], y[:120]
        k = np.asarray([1, 2, 3] * 40)
        # degenerate ensemble (no resampling) isolates weight handling
        base = dict(n_estimators=1, bootstrap=False, max_samples=1.0, seed=0)
        w_fit = BaggingClassifier(**base).fit(X, y, sample_weight=k)
        dup = BaggingClassifier(**base).fit(
            np.repeat(X, k, axis=0), np.repeat(y, k)
        )
        np.testing.assert_allclose(
            w_fit.predict_proba(X), dup.predict_proba(X), rtol=1e-3,
            atol=1e-4,
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~2.8s zero-weight soak; the property stays tier-1 via the fuzz representative
    def test_zero_weight_rows_ignored(self, breast_cancer):
        X, y = breast_cancer
        n = len(y)
        y_bad = y.copy()
        w = np.ones(n, np.float32)
        w[: n // 4] = 0.0
        y_bad[: n // 4] = 1 - y_bad[: n // 4]  # corrupt zero-weight rows
        base = dict(n_estimators=4, seed=0)
        a = BaggingClassifier(**base).fit(X, y_bad, sample_weight=w)
        b = BaggingClassifier(**base).fit(X[n // 4:], y[n // 4:])
        assert a.score(X[n // 4:], y[n // 4:]) == pytest.approx(
            b.score(X[n // 4:], y[n // 4:]), abs=0.02
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~2.9s mesh twin; single-device weighted-fit exactness stays tier-1
    def test_mesh_weighted_fit(self, breast_cancer):
        from spark_bagging_tpu.parallel import make_mesh

        X, y = breast_cancer
        w = np.random.default_rng(0).uniform(0.5, 2.0, len(y)).astype(
            np.float32
        )
        mesh = make_mesh(data=2)
        m = BaggingClassifier(n_estimators=8, seed=0, mesh=mesh).fit(
            X, y, sample_weight=w
        )
        s = BaggingClassifier(n_estimators=8, seed=0).fit(
            X, y, sample_weight=w
        )
        assert m.score(X, y) == pytest.approx(s.score(X, y), abs=0.02)

    def test_regressor_weighted(self, diabetes):
        X, y = diabetes
        w = np.ones(len(y), np.float32)
        reg = BaggingRegressor(n_estimators=8, seed=0).fit(
            X, y, sample_weight=w
        )
        ref = BaggingRegressor(n_estimators=8, seed=0).fit(X, y)
        np.testing.assert_allclose(
            reg.predict(X), ref.predict(X), rtol=1e-4, atol=1e-4
        )

    def test_bad_weights_raise(self, breast_cancer):
        X, y = breast_cancer
        with pytest.raises(ValueError, match="sample_weight"):
            BaggingClassifier().fit(X, y, sample_weight=np.ones(3))
        with pytest.raises(ValueError, match="non-negative"):
            BaggingClassifier().fit(
                X, y, sample_weight=-np.ones(len(y), np.float32)
            )


@pytest.mark.slow  # [PR 14 pyramid] ~2.5s API-surface soak; predict/proba parity is continuously gated by the serving bitwise suites
def test_predict_log_proba_and_decision_function(breast_cancer):
    X, y = breast_cancer
    clf = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
    lp = clf.predict_log_proba(X)
    np.testing.assert_allclose(np.exp(lp), clf.predict_proba(X), rtol=1e-5)
    df = clf.decision_function(X)
    assert df.shape == (len(y),)
    assert ((df > 0) == (clf.predict(X) == clf.classes_[1])).all()

    Xi, yi = load_iris(return_X_y=True)
    Xi = StandardScaler().fit_transform(Xi).astype(np.float32)
    clf3 = BaggingClassifier(n_estimators=4, seed=0).fit(Xi, yi)
    assert clf3.decision_function(Xi).shape == (len(yi), 3)


def test_score_sample_weight(breast_cancer):
    X, y = breast_cancer
    clf = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
    w = np.where(y == 1, 2.0, 1.0)
    s = clf.score(X, y, sample_weight=w)
    correct = (clf.predict(X) == y).astype(float)
    assert s == pytest.approx((correct * w).sum() / w.sum())
    assert clf.score(X, y) == pytest.approx(correct.mean())


def test_regressor_score_sample_weight(diabetes):
    X, y = diabetes
    reg = BaggingRegressor(n_estimators=4, seed=0).fit(X, y)
    w = np.ones(len(y))
    assert reg.score(X, y, sample_weight=w) == pytest.approx(
        reg.score(X, y), abs=1e-9
    )


def test_score_column_vector_y_and_zero_weights(breast_cancer):
    X, y = breast_cancer
    clf = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
    # column-vector y must not silently broadcast to (n, n)
    assert clf.score(X, y.reshape(-1, 1)) == pytest.approx(clf.score(X, y))
    with pytest.raises(ValueError, match="sums to zero"):
        clf.score(X, y, sample_weight=np.zeros(len(y)))


class TestWarmStart:
    """warm_start grows a fitted ensemble; id-keyed replica streams make
    the result EXACTLY a cold fit of the larger ensemble."""

    @pytest.mark.slow  # [PR 17 budget offset] ~3.9s warm==cold dual-fit soak; warm-start contracts stay tier-1 via the rejection tests here + streaming resume parity
    def test_equals_cold_fit(self, breast_cancer):
        X, y = breast_cancer
        cold = BaggingClassifier(
            n_estimators=16, seed=0, max_features=0.8
        ).fit(X, y)
        warm = BaggingClassifier(
            n_estimators=8, seed=0, max_features=0.8, warm_start=True
        ).fit(X, y)
        warm.set_params(n_estimators=16).fit(X, y)
        assert warm.n_estimators_ == 16
        assert warm.fit_report_["warm_started_from"] == 8
        np.testing.assert_array_equal(
            np.asarray(warm.subspaces_), np.asarray(cold.subspaces_)
        )
        np.testing.assert_allclose(
            warm.predict_proba(X), cold.predict_proba(X),
            rtol=1e-5, atol=1e-6,
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~3.5s mesh twin of the warm-start parity kept tier-1 single-device
    def test_equals_cold_fit_on_mesh(self, breast_cancer):
        from spark_bagging_tpu.parallel import make_mesh

        X, y = breast_cancer
        mesh = make_mesh(data=2)  # (2, 4): delta must divide 4
        cold = BaggingClassifier(n_estimators=16, seed=0, mesh=mesh).fit(X, y)
        warm = BaggingClassifier(
            n_estimators=8, seed=0, mesh=mesh, warm_start=True
        ).fit(X, y)
        warm.set_params(n_estimators=16).fit(X, y)
        np.testing.assert_allclose(
            warm.predict_proba(X), cold.predict_proba(X),
            rtol=1e-5, atol=1e-6,
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~2s warm-start regressor soak; classifier warm-start parity stays tier-1
    def test_regressor_and_oob(self, diabetes):
        X, y = diabetes
        cold = BaggingRegressor(n_estimators=12, seed=1, oob_score=True).fit(X, y)
        warm = BaggingRegressor(
            n_estimators=4, seed=1, oob_score=True, warm_start=True
        ).fit(X, y)
        warm.set_params(n_estimators=12).fit(X, y)
        assert warm.oob_score_ == pytest.approx(cold.oob_score_, abs=1e-6)
        np.testing.assert_allclose(
            warm.predict(X), cold.predict(X), rtol=1e-5, atol=1e-5
        )

    def test_validation(self, breast_cancer):
        X, y = breast_cancer
        warm = BaggingClassifier(
            n_estimators=4, seed=0, warm_start=True
        ).fit(X, y)
        with pytest.raises(ValueError, match="shrink"):
            warm.set_params(n_estimators=2).fit(X, y)
        warm.set_params(n_estimators=4)
        with pytest.raises(ValueError, match="max_samples"):
            warm.set_params(max_samples=0.5, n_estimators=8).fit(X, y)
        warm.set_params(max_samples=1.0)
        with pytest.raises(ValueError, match="class set"):
            warm.set_params(n_estimators=8).fit(X, np.where(y == 0, 7, y))
        with pytest.raises(ValueError, match="seed"):
            warm.set_params(seed=5, n_estimators=8).fit(X, y)
        warm.set_params(seed=0)
        # same n_estimators: warns, ensemble unchanged
        before = np.asarray(warm.ensemble_["W"])
        with pytest.warns(UserWarning, match="without increasing"):
            warm.set_params(n_estimators=4).fit(X, y)
        np.testing.assert_array_equal(before, np.asarray(warm.ensemble_["W"]))

    def test_stream_fit_not_extendable(self, breast_cancer):
        X, y = breast_cancer
        warm = BaggingClassifier(
            n_estimators=4, seed=0, warm_start=True
        ).fit_stream((X, y), chunk_rows=256)
        with pytest.raises(ValueError, match="in-memory fit"):
            warm.set_params(n_estimators=8).fit(X, y)


@pytest.mark.slow  # [PR 14 pyramid] ~2.8s max_samples API variant soak; fractional path stays tier-1
def test_int_max_samples(breast_cancer):
    """sklearn semantics: int max_samples = absolute expected sample
    count, equivalent to the float ratio count/n."""
    X, y = breast_cancer
    n = len(y)
    a = BaggingClassifier(n_estimators=8, max_samples=n // 2, seed=0).fit(X, y)
    b = BaggingClassifier(
        n_estimators=8, max_samples=(n // 2) / n, seed=0
    ).fit(X, y)
    np.testing.assert_allclose(
        a.predict_proba(X), b.predict_proba(X), rtol=1e-6, atol=1e-7
    )
    # subsampling without replacement leaves OOB rows even at int count
    c = BaggingClassifier(
        n_estimators=16, max_samples=n // 2, bootstrap=False,
        oob_score=True, seed=0,
    ).fit(X, y)
    assert 0.8 < c.oob_score_ <= 1.0
    with pytest.raises(ValueError, match="max_samples"):
        BaggingClassifier(max_samples=n + 1).fit(X, y)
    with pytest.raises(ValueError, match="max_samples"):
        BaggingClassifier(max_samples=1.5).fit(X, y)
    with pytest.raises(ValueError, match="max_samples"):
        BaggingClassifier(max_samples=0).fit(X, y)


@pytest.mark.slow  # [PR 17 budget offset] ~2.3s per-replica slice soak; the estimators_ view contract stays tier-1 via test_estimators_features_alias
def test_replica_params_slices_match_ensemble(breast_cancer):
    """Per-replica access (estimators_[i] analog): averaging the
    single-replica probabilities must reproduce soft-vote
    predict_proba."""
    import jax

    X, y = breast_cancer
    clf = BaggingClassifier(n_estimators=6, seed=0, max_features=0.8).fit(X, y)
    probs = []
    for i in range(6):
        params_i, idx = clf.replica_params(i)
        scores = clf.base_learner_.predict_scores(
            params_i, jnp.asarray(X)[:, idx]
        )
        probs.append(np.asarray(jax.nn.softmax(scores, axis=-1)))
    np.testing.assert_allclose(
        np.mean(probs, axis=0), clf.predict_proba(X), rtol=1e-4, atol=1e-5
    )
    with pytest.raises(IndexError):
        clf.replica_params(6)


def test_replica_weights_reproduce_replica_fit(breast_cancer):
    """estimators_samples_ analog: the regenerated weight vector for
    replica i, fed through the base learner directly, must reproduce
    the stored replica EXACTLY — the weights ARE the bootstrap."""
    from spark_bagging_tpu.ops.bootstrap import fit_key

    X, y = breast_cancer
    clf = BaggingClassifier(n_estimators=4, seed=3).fit(X, y)
    w = clf.replica_weights(2)
    assert w.shape == (X.shape[0],)
    assert (w >= 0).all() and w.sum() > 0
    assert abs(w.mean() - 1.0) < 0.15  # Poisson(1) counts
    y_enc = np.searchsorted(clf.classes_, y).astype(np.int32)
    params, _ = clf.base_learner_.fit_from_init(
        fit_key(jax.random.key(3), jnp.asarray(2, jnp.int32)),
        jnp.asarray(X), jnp.asarray(y_enc), jnp.asarray(w),
        clf.n_classes_,
    )
    stored, _ = clf.replica_params(2)
    # vmapped vs single-replica fits compile to different reduction
    # orders (fp reassociation) — agreement is ~1e-4; a WRONG weight
    # vector would produce O(1)-different params
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(stored)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4
        )
    # negative control: a different replica's weights give a visibly
    # different model
    w_other = clf.replica_weights(0)
    assert not np.array_equal(w, w_other)
    params_other, _ = clf.base_learner_.fit_from_init(
        fit_key(jax.random.key(3), jnp.asarray(2, jnp.int32)),
        jnp.asarray(X), jnp.asarray(y_enc), jnp.asarray(w_other),
        clf.n_classes_,
    )
    diff = max(
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(
            jax.tree.leaves(params_other), jax.tree.leaves(stored)
        )
    )
    assert diff > 0.01
    with pytest.raises(IndexError):
        clf.replica_weights(4)


def test_estimators_features_alias(breast_cancer):
    X, y = breast_cancer
    clf = BaggingClassifier(
        n_estimators=3, seed=0, max_features=0.5
    ).fit(X, y)
    feats = clf.estimators_features_
    assert feats.shape == (3, int(0.5 * X.shape[1]))
    np.testing.assert_array_equal(feats, np.asarray(clf.subspaces_))


def test_replica_weights_rejects_stream_fit(breast_cancer):
    X, y = breast_cancer
    sclf = BaggingClassifier(n_estimators=2, seed=0).fit_stream(
        (X, y), chunk_rows=200, n_epochs=2, lr=0.05
    )
    with pytest.raises(ValueError, match="replayable"):
        sclf.replica_weights(0)


@pytest.mark.slow  # [PR 17 budget offset] ~3.2s mesh-detach rejection twin; the replica-weights rejection contract stays tier-1 via test_replica_weights_rejects_stream_fit
def test_replica_weights_data_sharded_rejected_even_after_mesh_detach(
    breast_cancer,
):
    """Data-sharded draws fold the shard index into the key; the
    refusal is snapshotted at FIT time, so detaching the mesh
    afterwards must not un-reject it."""
    from spark_bagging_tpu import make_mesh

    X, y = breast_cancer
    clf = BaggingClassifier(
        n_estimators=8, seed=0, mesh=make_mesh(data=2)
    ).fit(X, y)
    clf.mesh = None
    with pytest.raises(ValueError, match="data-sharded"):
        clf.replica_weights(0)
    # replica-only mesh draws ARE globally replayable
    rclf = BaggingClassifier(
        n_estimators=8, seed=0, mesh=make_mesh()
    ).fit(X, y)
    assert rclf.replica_weights(0).shape == (X.shape[0],)


def test_warm_start_rejects_different_row_count(breast_cancer):
    X, y = breast_cancer
    clf = BaggingClassifier(
        n_estimators=4, seed=0, warm_start=True
    ).fit(X, y)
    clf.set_params(n_estimators=6)
    with pytest.raises(ValueError, match="row count"):
        clf.fit(X[:-10], y[:-10])


def test_warm_start_rejects_mutated_base_learner(breast_cancer):
    """set_params(base_learner__x=...) mutates the same instance the
    fit snapshotted, so the guard must compare a fingerprint taken at
    fit time, not object identity (round-4 audit)."""
    from spark_bagging_tpu import LogisticRegression

    X, y = breast_cancer
    clf = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=10),
        n_estimators=4, seed=0, warm_start=True,
    ).fit(X, y)
    clf.set_params(n_estimators=6, base_learner__max_iter=2)
    with pytest.raises(ValueError, match="hyperparameters"):
        clf.fit(X, y)


@pytest.mark.slow  # [PR 19 budget offset] ~3.1s warm-start rejection soak; the warm-start fingerprint guard stays tier-1 via TestLibraryAuditFixes::test_warm_start_rejects_mesh_layout_change
def test_warm_start_rejects_changed_sample_weight(breast_cancer):
    """A warm fit must use the same per-row weights as the original —
    splicing replicas trained on a different weighted objective would
    silently break the exact-cold-fit contract (round-4 audit)."""
    X, y = breast_cancer
    sw = np.linspace(0.5, 2.0, len(y)).astype(np.float32)
    clf = BaggingClassifier(
        n_estimators=4, seed=0, warm_start=True
    ).fit(X, y, sample_weight=sw)
    clf.set_params(n_estimators=6)
    with pytest.raises(ValueError, match="sample_weight"):
        clf.fit(X, y)  # forgot the weights
    with pytest.raises(ValueError, match="sample_weight"):
        clf.fit(X, y, sample_weight=sw * 2)
    clf.fit(X, y, sample_weight=sw)  # identical weights: extends
    assert clf.n_estimators_ == 6


def test_warm_start_cannot_extend_via_fit_stream(breast_cancer):
    from spark_bagging_tpu import ArrayChunks

    X, y = breast_cancer
    clf = BaggingClassifier(
        n_estimators=4, seed=0, warm_start=True
    ).fit(X, y)
    clf.set_params(n_estimators=8)
    with pytest.raises(ValueError, match="fit_stream"):
        clf.fit_stream(ArrayChunks(X, y, 128))


@pytest.mark.slow  # ~9s: extreme-edge ensemble (all-zero draws) fits a big bag
def test_all_zero_bootstrap_draws_stay_finite(breast_cancer):
    """max_samples small enough that some replicas draw all-zero
    Poisson weights: predictions must stay finite for every learner
    family that divides by the weight total (round-4 audit)."""
    from spark_bagging_tpu import BaggingRegressor, LinearRegression
    from spark_bagging_tpu.models import FMClassifier

    X, y = breast_cancer
    clf = BaggingClassifier(
        n_estimators=32, max_samples=0.005, seed=0
    ).fit(X, y)
    assert np.isfinite(clf.predict_proba(X)).all()
    reg = BaggingRegressor(
        base_learner=LinearRegression(),
        n_estimators=32, max_samples=0.005, seed=0,
    ).fit(X, y.astype(np.float32))
    assert np.isfinite(reg.predict(X)).all()
    fm = BaggingClassifier(
        base_learner=FMClassifier(max_iter=5),
        n_estimators=16, max_samples=0.005, seed=0,
    ).fit(X, y)
    assert np.isfinite(fm.predict_proba(X)).all()
    from spark_bagging_tpu.models import GaussianNB, LinearSVC

    svc = BaggingClassifier(
        base_learner=LinearSVC(max_iter=5),
        n_estimators=16, max_samples=0.005, seed=0,
    ).fit(X, y)
    assert np.isfinite(svc.decision_function(X)).all()
    nb = BaggingClassifier(
        base_learner=GaussianNB(),
        n_estimators=16, max_samples=0.005, seed=0,
    ).fit(X, y)
    assert np.isfinite(nb.predict_proba(X)).all()


def test_learner_hash_eq_consistent():
    """equal ⇒ equal hash (the lru-cache invariant); numerically equal
    but repr-distinct params are deliberately NOT equal (round-4
    audit)."""
    from spark_bagging_tpu import LinearRegression

    a, b = LinearRegression(l2=0), LinearRegression(l2=0)
    assert a == b and hash(a) == hash(b)
    c = LinearRegression(l2=0.0)
    assert (a == c) == (hash(a) == hash(c))


def test_clear_compiled_caches(breast_cancer):
    from spark_bagging_tpu import clear_compiled_caches

    X, y = breast_cancer
    BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
    assert clear_compiled_caches() > 0
    assert clear_compiled_caches() == 0


class TestLinearCollapseInference:
    """Bagged-mean prediction of params-linear learners collapses to
    ONE model with scatter-meaned coefficients — must match the
    R-replica device path exactly (same math, fp rounding only)."""

    def _device_pred(self, reg, X):
        reg.__dict__["_collapsed_beta_cache"] = None  # force device path
        pred = reg.predict(X)
        del reg.__dict__["_collapsed_beta_cache"]
        return pred

    @pytest.mark.slow  # [PR 17 budget offset] ~2.1s subspace variant; linear-collapse device parity stays tier-1 via the base TestLinearCollapseInference tests
    def test_ridge_with_subspaces_matches_device_path(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 12)).astype(np.float32)
        y = (X @ rng.normal(size=12) + 0.1 * rng.normal(size=300)).astype(
            np.float32
        )
        reg = BaggingRegressor(
            n_estimators=24, seed=0, max_features=0.5,
            bootstrap_features=True,  # duplicated columns must add
        ).fit(X, y)
        assert reg._linear_collapse() is not None
        np.testing.assert_allclose(
            reg.predict(X), self._device_pred(reg, X), rtol=2e-4,
            atol=2e-4,
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~2.2s collapse-decision variant; the ridge collapse parity stays tier-1
    def test_glm_identity_collapses_log_does_not(self):
        from spark_bagging_tpu.models import GeneralizedLinearRegression

        rng = np.random.default_rng(1)
        X = rng.normal(size=(200, 5)).astype(np.float32)
        y = np.abs(X[:, 0] + 0.1 * rng.normal(size=200)).astype(np.float32)
        a = BaggingRegressor(
            base_learner=GeneralizedLinearRegression(family="gaussian"),
            n_estimators=8, seed=0,
        ).fit(X, y)
        assert a._linear_collapse() is not None
        b = BaggingRegressor(
            base_learner=GeneralizedLinearRegression(
                family="poisson", max_iter=4
            ),
            n_estimators=8, seed=0,
        ).fit(X, y)
        assert b._linear_collapse() is None  # log link: not linear

    def test_refit_invalidates_cache(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(100, 4)).astype(np.float32)
        y = X[:, 0].astype(np.float32)
        reg = BaggingRegressor(n_estimators=4, seed=0).fit(X, y)
        p1 = reg.predict(X)
        y2 = (2 * X[:, 1]).astype(np.float32)
        reg.fit(X, y2)
        p2 = reg.predict(X)
        assert not np.allclose(p1, p2)
        r2 = 1 - np.var(p2 - y2) / np.var(y2)
        assert r2 > 0.9


def test_repr_elides_defaults():
    """sklearn-style repr: only non-default params appear."""
    from spark_bagging_tpu import RandomForestClassifier

    assert repr(LogisticRegression()) == "LogisticRegression()"
    r = repr(BaggingClassifier(base_learner=LogisticRegression(l2=0.5)))
    assert r == "BaggingClassifier(base_learner=LogisticRegression(l2=0.5))"
    r2 = repr(RandomForestClassifier(n_estimators=32, criterion="entropy"))
    assert "n_estimators=32" in r2 and "criterion='entropy'" in r2
    assert "max_depth" not in r2  # default elided


class TestLibraryAuditFixes:
    """Regression tests for the round-3 core-library audit findings."""

    def test_classifier_column_vector_y(self, breast_cancer):
        X, y = breast_cancer
        a = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
        b = BaggingClassifier(n_estimators=4, seed=0).fit(
            X, y.reshape(-1, 1)
        )
        np.testing.assert_array_equal(a.predict(X), b.predict(X))
        with pytest.raises(ValueError, match="1-D"):
            BaggingClassifier(n_estimators=2).fit(
                X, np.stack([y, y], axis=1)
            )

    def test_warm_start_rejects_mesh_layout_change(self, breast_cancer):
        from spark_bagging_tpu.parallel import make_mesh

        X, y = breast_cancer
        clf = BaggingClassifier(
            n_estimators=4, seed=0, warm_start=True,
            mesh=make_mesh(data=2),
        ).fit(X, y)
        clf.mesh = None
        clf.n_estimators = 8
        with pytest.raises(ValueError, match="mesh layout"):
            clf.fit(X, y)

    def test_without_replacement_rejects_bad_ratio_even_tiny_n(self):
        from spark_bagging_tpu.ops.bootstrap import bootstrap_weights_one

        import jax

        with pytest.raises(ValueError, match="positive"):
            bootstrap_weights_one(
                jax.random.key(0), 0, n_rows=1, ratio=0.0,
                replacement=False,
            )

    def test_predict_quantiles_jit_is_cached(self):
        from spark_bagging_tpu import AFTSurvivalRegression, BaggingRegressor
        from spark_bagging_tpu.bagging import _jitted_predict_quantiles

        rng = np.random.default_rng(0)
        X = rng.normal(size=(120, 4)).astype(np.float32)
        y = np.exp(X[:, 0] * 0.3 + 0.1 * rng.normal(size=120)).astype(
            np.float32
        )
        reg = BaggingRegressor(
            base_learner=AFTSurvivalRegression(max_iter=30),
            n_estimators=3, seed=0,
        ).fit(X, y)
        before = _jitted_predict_quantiles.cache_info().misses
        q1 = reg.predict_quantiles(X[:10])
        q2 = reg.predict_quantiles(X[10:20])
        assert q1.shape == (10, 3) and q2.shape == (10, 3)
        after = _jitted_predict_quantiles.cache_info()
        assert after.misses == before + 1 and after.hits >= 1

    def test_distributed_args_validated(self):
        from spark_bagging_tpu.parallel.distributed import (
            initialize_distributed,
        )

        with pytest.raises(ValueError, match="coordinator_address"):
            initialize_distributed(num_processes=2)
