"""Capacity & residency plane [ISSUE 16]: the per-(model, version)
memory ledger (params / compiled-executable / AOT-disk bytes with
honest ``unmeasured`` instead of fabricated zeros), exact
reconciliation against the program cache's own totals, demand
accounting behind the one-attribute-read probe, owner-attributed
eviction accounting, the ``/debug/capacity`` explainer, the starter
alert rules, and the swap-rollback no-leak regression."""

import time

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    faults,
    telemetry,
)
from spark_bagging_tpu.serving import EnsembleExecutor, ModelRegistry
from spark_bagging_tpu.serving import program_cache as _pc
from spark_bagging_tpu.telemetry import alerts, capacity
from spark_bagging_tpu.telemetry.registry import SERIES_HELP


@pytest.fixture(scope="module", autouse=True)
def _module_clock():
    return time.perf_counter()


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.enable()
    capacity.disable()
    prev_cache = _pc.install(_pc.ProgramCache(capacity=64))
    yield
    _pc.install(prev_cache)
    capacity.disable()
    telemetry.reset()
    telemetry.enable()


def _fitted(seed=0, width=6, n_estimators=2):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(64, width)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=n_estimators, seed=seed,
    ).fit(X, y)


@pytest.fixture(scope="module")
def clf():
    return _fitted(seed=0)


@pytest.fixture(scope="module")
def clf_b():
    return _fitted(seed=7)


def _registry(clf, name="a", **kw):
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16, **kw)
    reg.register(name, clf, warmup=False, version=1)
    return reg


def _rows(width=6, n=4, seed=1):
    return np.random.default_rng(seed).normal(
        size=(n, width)).astype(np.float32)


# -- the byte ladder ---------------------------------------------------

class TestExecutableBytes:
    def test_real_compiled_program_measures_honestly(self):
        import jax
        import jax.numpy as jnp

        compiled = jax.jit(lambda x: x * 2.0).lower(
            jnp.zeros((4,), jnp.float32)
        ).compile()
        nbytes, source = capacity.executable_bytes(compiled)
        assert source in ("memory_analysis", "serialized")
        assert nbytes is not None and nbytes > 0

    def test_unmeasurable_object_is_none_never_zero(self):
        nbytes, source = capacity.executable_bytes(object())
        assert nbytes is None
        assert source == "unmeasured"


class TestClassifyRate:
    def test_thresholds_and_hysteresis(self):
        kw = dict(hot_rps=50.0, warm_rps=10.0, hysteresis=0.5)
        assert capacity.classify_rate(None, 60.0, **kw) == "hot"
        assert capacity.classify_rate(None, 20.0, **kw) == "warm"
        assert capacity.classify_rate(None, 1.0, **kw) == "cold"
        # hot holds down to hysteresis * hot_rps, then demotes
        assert capacity.classify_rate("hot", 30.0, **kw) == "hot"
        assert capacity.classify_rate("hot", 20.0, **kw) == "warm"
        # warm holds down to hysteresis * warm_rps, then cold
        assert capacity.classify_rate("warm", 6.0, **kw) == "warm"
        assert capacity.classify_rate("warm", 4.0, **kw) == "cold"
        # a cold model needs the full threshold to come back
        assert capacity.classify_rate("cold", 6.0, **kw) == "cold"
        assert capacity.classify_rate("cold", 10.0, **kw) == "warm"


# -- ledger reconciliation ---------------------------------------------

class TestLedger:
    def test_reconciles_exactly_against_cache_totals(self, clf, clf_b):
        """The acceptance assertion: sum of per-owner entries/bytes/
        unmeasured equals the cache's own totals — including an
        anonymous (never registry-committed) executor's programs,
        which roll up under the unattributed label instead of
        vanishing from the sums."""
        plane = capacity.enable()
        reg = _registry(clf, "a")
        reg.register("b", clf_b, warmup=False, version=1)
        reg.executor("a").forward(_rows())
        reg.executor("b").forward(_rows(seed=2))
        # a DIFFERENT fitted model, never committed: its programs
        # must roll up unattributed (an executor over a registered
        # model's exact fit shares its fingerprint and attributes)
        anon = EnsembleExecutor(_fitted(seed=42), min_bucket_rows=4,
                                max_batch_rows=8)
        anon.forward(_rows(n=3, seed=3))

        led = plane.ledger()
        assert led["reconciled"] is True
        stats = _pc.cache().stats()
        assert sum(o["entries"] for o in led["owners"].values()) \
            == stats["entries"]
        assert sum(o["bytes"] for o in led["owners"].values()) \
            == stats["bytes"]
        assert sum(o["unmeasured"] for o in led["owners"].values()) \
            == stats["unmeasured"]
        assert "a" in led["owners"] and "b" in led["owners"]
        assert capacity.UNATTRIBUTED in led["owners"]
        assert led["committed"]["a@1"]["params_bytes"] > 0
        assert led["committed"]["a@1"]["live"] is True

    def test_params_bytes_and_placement_are_commit_facts(self, clf):
        plane = capacity.enable()
        reg = _registry(clf, "a")
        rec = led = plane.ledger()["committed"]["a@1"]
        assert rec["params_bytes"] == capacity.params_nbytes(
            reg.executor("a"))
        assert rec["placement"] in ("cpu", "host", "tpu", "gpu")
        assert telemetry.registry().peek(
            "sbt_capacity_params_bytes",
            {"model": "a", "version": "1"},
        ).value == float(rec["params_bytes"])
        del led


# -- the demand plane --------------------------------------------------

class TestDemand:
    def test_forward_feeds_labeled_demand_counters(self, clf):
        plane = capacity.enable()
        reg = _registry(clf, "a")
        reg.executor("a").forward(_rows(n=4))
        reg.executor("a").forward(_rows(n=3, seed=2))
        s = plane.demand_summary()
        assert s["a"]["requests"] == 2
        assert s["a"]["rows"] == 7
        assert telemetry.registry().peek(
            "sbt_capacity_demand_requests_total", {"model": "a"}
        ).value == 2.0
        assert telemetry.registry().peek(
            "sbt_capacity_demand_rows_total", {"model": "a"}
        ).value == 7.0

    def test_anonymous_executors_stay_out_of_the_table(self, clf):
        plane = capacity.enable()
        EnsembleExecutor(clf, min_bucket_rows=4,
                         max_batch_rows=8).forward(_rows(n=2))
        assert plane.demand_summary() == {}

    def test_classify_ranks_by_cumulative_demand(self, clf, clf_b):
        plane = capacity.enable(hot_rps=50.0, warm_rps=5.0)
        reg = _registry(clf, "a")
        reg.register("b", clf_b, warmup=False, version=1)
        reg.executor("a").forward(_rows())
        reg.executor("b").forward(_rows(seed=2))
        plane.classify(now=0.0)  # baseline window: rates start here
        for _ in range(3):
            reg.executor("a").forward(_rows())
        view = plane.classify(now=0.01)  # a: 300 rps, b: idle
        assert view["a"]["rank"] == 1
        assert view["b"]["rank"] == 2
        assert view["a"]["class"] == "hot"
        assert view["b"]["class"] == "cold"

    def test_unarmed_probe_is_one_attribute_read(self, clf,
                                                 monkeypatch):
        """The zero-overhead contract, both halves: (1) an unarmed
        forward must never even CALL the plane (a booby-trapped
        observe_demand proves the probe short-circuits on the module
        attribute), and (2) the probe itself — exactly what
        _forward_packed runs — stays far under a microsecond."""
        capacity.disable()

        def boom(*a, **kw):  # pragma: no cover — must never run
            raise AssertionError("unarmed forward touched the plane")

        monkeypatch.setattr(capacity.CapacityPlane, "observe_demand",
                            boom)
        reg = _registry(clf, "a")
        reg.executor("a").forward(_rows())

        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            cap = capacity.ACTIVE
            if cap is not None:  # pragma: no cover — disabled
                raise AssertionError
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 2e-6, f"{per_call * 1e9:.0f}ns per probe"

    def test_demand_table_is_fixed_memory(self, clf):
        plane = capacity.enable()
        plane.max_models = 2
        plane.observe_demand("m1", 1, 1, 1)
        plane.observe_demand("m2", 1, 1, 1)
        plane.observe_demand("m3", 1, 1, 1)  # over the cap: dropped
        assert sorted(plane.demand_summary()) == ["m1", "m2"]
        assert telemetry.registry().counter(
            "sbt_capacity_demand_dropped_total").value == 1.0


# -- eviction attribution ----------------------------------------------

class TestEvictionAttribution:
    def test_evictions_charge_the_owner(self, clf, clf_b):
        plane = capacity.enable()
        small = _pc.install(_pc.ProgramCache(capacity=1))
        try:
            reg = _registry(clf, "a")
            reg.register("b", clf_b, warmup=False, version=1)
            reg.executor("a").forward(_rows())
            reg.executor("b").forward(_rows(seed=2))  # evicts a's
            counts = plane.eviction_counts()
            assert counts.get("a") == 1
            (ev,) = plane.recent_evictions()
            assert ev["owner"] == "a"
            assert telemetry.registry().peek(
                "sbt_program_cache_evictions_total", {"model": "a"}
            ).value == 1.0
        finally:
            _pc.install(small)

    def test_labeled_cache_counters_keep_unlabeled_totals(self, clf):
        """Satellite 1: hit/miss counters gain model= labels while the
        unlabeled totals keep counting everything (dashboards keyed on
        the old names must not go dark)."""
        capacity.enable()
        reg_t = telemetry.registry()
        m0 = reg_t.counter("sbt_program_cache_misses_total").value
        h0 = reg_t.counter("sbt_program_cache_hits_total").value
        reg = _registry(clf, "a")
        reg.executor("a").forward(_rows())  # miss + put
        # a second executor over the SAME fitted model shares the
        # fingerprint: its build is the labeled cache HIT
        twin = EnsembleExecutor(clf, min_bucket_rows=8,
                                max_batch_rows=16)
        twin.forward(_rows(seed=2))
        assert reg_t.counter(
            "sbt_program_cache_misses_total").value > m0
        assert reg_t.counter("sbt_program_cache_hits_total").value > h0
        assert reg_t.peek("sbt_program_cache_misses_total",
                          {"model": "a"}).value >= 1.0
        assert reg_t.peek("sbt_program_cache_hits_total",
                          {"model": "a"}).value >= 1.0


# -- the swap-rollback regression --------------------------------------

class TestSwapAccounting:
    def test_failed_swap_leaks_no_ledger_entries(self, clf, clf_b):
        """Satellite 3 regression: ownership is written ONLY at
        registry commit, so a swap that dies pre-commit must leave
        the ledger exactly as it was — no orphaned (model, version)
        rows, reconciliation still exact."""
        plane = capacity.enable()
        reg = _registry(clf, "a")
        reg.executor("a").forward(_rows())
        plan = faults.FaultPlan([{
            "site": "registry.swap.precompile",
            "action": "error", "at": [1],
        }])
        with faults.armed(plan):
            with pytest.raises(Exception):
                reg.swap("a", clf_b)
        led = plane.ledger()
        assert sorted(led["committed"]) == ["a@1"]
        assert led["reconciled"] is True

    def test_committed_swap_retires_the_old_version(self, clf, clf_b):
        plane = capacity.enable()
        reg = _registry(clf, "a")
        reg.swap("a", clf_b)
        led = plane.ledger()
        assert led["committed"]["a@1"]["live"] is False
        assert led["committed"]["a@2"]["live"] is True

    def test_degraded_variant_still_reconciles(self):
        """The degraded-quorum fault response compiles a NEW program
        variant under the same fingerprint — it must attribute to the
        same owner and keep the ledger sums exact."""
        import warnings

        import jax

        from spark_bagging_tpu.parallel import make_mesh

        if jax.device_count() < 4:
            pytest.skip("needs 4 forced host devices")
        plane = capacity.enable()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model = _fitted(seed=0, width=8, n_estimators=8)
        mesh = make_mesh(data=1, replica=4,
                         devices=jax.devices()[:4])
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16,
                            mesh=mesh)
        reg.register("m", model, warmup=False, version=1)
        ex = reg.executor("m")
        X = _rows(width=8, n=5)
        ex.forward(X)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ex.degrade_shards([1])
        ex.forward(X)  # degraded-variant compile, same owner
        led = plane.ledger()
        assert led["reconciled"] is True
        assert led["owners"]["m"]["entries"] >= 2
        assert capacity.UNATTRIBUTED not in led["owners"]


# -- surfaces: series, route, rules, device stats ----------------------

class TestSurfaces:
    def test_series_help_covers_the_new_series(self):
        for name in (
            "sbt_program_cache_bytes",
            "sbt_capacity_params_bytes",
            "sbt_capacity_compiled_bytes",
            "sbt_capacity_resident_entries",
            "sbt_capacity_unmeasured_entries",
            "sbt_capacity_aot_disk_bytes",
            "sbt_capacity_models",
            "sbt_capacity_demand_requests_total",
            "sbt_capacity_demand_rows_total",
            "sbt_capacity_demand_rate_rps",
            "sbt_capacity_demand_rank",
            "sbt_capacity_demand_class",
            "sbt_capacity_demand_dropped_total",
            "sbt_capacity_cache_headroom_ratio",
            "sbt_capacity_cold_resident_entries",
            "sbt_process_device_bytes_in_use",
            "sbt_process_device_bytes_limit",
            "sbt_process_device_peak_bytes",
        ):
            assert name in SERIES_HELP, name

    def test_debug_capacity_route(self, clf):
        from spark_bagging_tpu.telemetry import server

        body = server._debug_capacity({})
        assert body["enabled"] is False  # honest when unarmed
        plane = capacity.enable()
        reg = _registry(clf, "a")
        reg.executor("a").forward(_rows())
        body = server._debug_capacity({"limit": ["8"]})
        assert body["enabled"] is True
        assert body["reconciled"] is True
        (resident,) = [r for r in body["residents"]
                       if r["owner"] == "a"]
        for key in ("lru_position", "bytes_reclaimable", "hits",
                    "demand_rank", "demand_class", "last_hit_age_s"):
            assert key in resident, key
        assert body["demand"]["a"]["requests"] == 1
        del plane

    def test_default_capacity_rules_grammar_and_fire(self):
        rules = alerts.default_capacity_rules(
            fast_window_s=2.0, slow_window_s=5.0, cooldown_s=0.0,
        )
        assert [r.name for r in rules] == [
            "capacity-headroom-low",
            "capacity-cold-model-resident",
            "capacity-eviction-churn",
            "tenancy-tail-latency-burn",
            "tenancy-quota-shed-rate",
            "tenancy-pin-violation",
            "tenancy-quarantine-flapping",
        ]
        assert [r.name for r in alerts.default_capacity_rules(
            tenancy=False)] == [
            "capacity-headroom-low",
            "capacity-cold-model-resident",
            "capacity-eviction-churn",
        ]
        for r in rules:
            # round-trip through the wire grammar (config files)
            assert alerts.AlertRule.from_dict(
                r.to_dict()).to_dict() == r.to_dict()
        headroom = rules[0]
        assert headroom.op == "<" and headroom.kind == "value"
        assert rules[2].kind == "rate"
        eng = alerts.AlertEngine([headroom])
        telemetry.set_gauge("sbt_capacity_cache_headroom_ratio", 0.02)
        assert eng.evaluate(now=0.0) == []
        assert eng.evaluate(now=2.0) == []
        assert eng.evaluate(now=4.0) == []
        evs = eng.evaluate(now=5.5)
        assert [e["kind"] for e in evs] == ["alert_fired"]

    def test_export_gauges_headroom_and_cold_residents(self, clf):
        plane = capacity.enable(hot_rps=50.0, warm_rps=5.0)
        reg = _registry(clf, "a")
        reg.executor("a").forward(_rows())
        plane.export_gauges()
        snap = _pc.cache().snapshot()
        expect = (snap["capacity"] - snap["entries_total"]) \
            / snap["capacity"]
        assert telemetry.registry().gauge(
            "sbt_capacity_cache_headroom_ratio"
        ).value == pytest.approx(expect)
        # never classified -> cold by default: resident cold entries
        assert telemetry.registry().gauge(
            "sbt_capacity_cold_resident_entries"
        ).value >= 1.0

    def test_device_memory_stats_contract(self):
        """Satellite 2: honest None on backends that report nothing
        (CPU), and when present every entry carries the full key
        set; the scrape-time mirror must never raise either way."""
        from spark_bagging_tpu.telemetry import server
        from spark_bagging_tpu.utils.memory import device_memory_stats

        stats = device_memory_stats()
        if stats is not None:
            assert stats, "empty list must collapse to None"
            for d in stats:
                for key in ("id", "platform", "bytes_in_use",
                            "bytes_limit", "peak_bytes_in_use"):
                    assert key in d, key
        server._refresh_process_gauges()  # mirror path never raises

    def test_fleet_digest_includes_demand_counters(self):
        from spark_bagging_tpu.telemetry.fleet import (
            FLEET_DIGEST_SERIES,
        )

        assert "sbt_capacity_demand_requests_total" \
            in FLEET_DIGEST_SERIES
        assert "sbt_capacity_demand_rows_total" in FLEET_DIGEST_SERIES


# -- the churn drill's gate --------------------------------------------

class TestChurnChecks:
    def test_churn_checks_on_synthetic_report(self):
        from benchmarks.replay import _churn_checks

        good = {
            "errors": 0,
            "churn": {"evictions": 3, "unattributed_final": 0,
                      "reconciled": True, "models_tracked": 6,
                      "models": 6},
        }
        assert all(c["ok"] for c in _churn_checks(good))
        bad = {
            "errors": 0,
            "churn": {"evictions": 0, "unattributed_final": 1,
                      "reconciled": False, "models_tracked": 5,
                      "models": 6},
        }
        failed = {c["name"] for c in _churn_checks(bad)
                  if not c["ok"]}
        assert failed == {"churn_evictions",
                          "churn_unattributed_final",
                          "churn_ledger_reconciled",
                          "churn_models_tracked"}

    def test_churn_is_mutually_exclusive_with_other_drills(self):
        from benchmarks.replay import replay_median

        with pytest.raises(ValueError, match="separate drills"):
            replay_median(object(), repeats=1, churn=True, fleet=3)
        with pytest.raises(ValueError, match="separate drills"):
            replay_median(object(), repeats=1, churn=True, online=True)


def test_zz_capacity_suite_under_budget(_module_clock):
    """Tier-1 allowance for this module (the PR-11 ratchet
    discipline): unit-sized throughout — the only compiles are a
    handful of tiny width-6 programs plus the one 4-device mesh
    drill."""
    elapsed = time.perf_counter() - _module_clock
    assert elapsed < 30.0, (
        f"tests/test_capacity.py took {elapsed:.1f}s; move the "
        "offender to -m slow or shrink it"
    )
