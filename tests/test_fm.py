"""Factorization-machine tests: interaction recovery, weighted
exactness, bagging/mesh/stream integration [SURVEY §4]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    FMClassifier,
    FMRegressor,
    make_mesh,
)

KEY = jax.random.key(0)


def _xor_interaction(n=1200, seed=0):
    """Labels driven purely by a pairwise product — linear models fail,
    FMs must capture it through the factor term."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] > 0).astype(np.int32)
    return X, y


class TestFMClassifier:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.5s interaction-recovery quality soak
    def test_learns_pairwise_interaction(self):
        X, y = _xor_interaction()
        fm = FMClassifier(factor_size=4, max_iter=300, lr=0.1)
        params, aux = fm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 2
        )
        acc = (np.asarray(fm.predict_scores(params, jnp.asarray(X)).argmax(1))
               == y).mean()
        assert acc > 0.9  # a linear model sits at ~0.5 here
        curve = np.asarray(aux["loss_curve"])
        assert curve[-1] < curve[0]

    def test_linear_baseline_fails_same_data(self):
        """Sanity: the task really requires interactions."""
        from spark_bagging_tpu.models import LogisticRegression

        X, y = _xor_interaction()
        lr = LogisticRegression(max_iter=10)
        params, _ = lr.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 2
        )
        acc = (np.asarray(lr.predict_scores(params, jnp.asarray(X)).argmax(1))
               == y).mean()
        assert acc < 0.65

    @pytest.mark.slow  # [PR 14 pyramid] ~1.1s real-data quality soak
    def test_real_data_accuracy(self):
        X, y = load_breast_cancer(return_X_y=True)
        X = StandardScaler().fit_transform(X).astype(np.float32)
        fm = FMClassifier(factor_size=4, max_iter=200, lr=0.05)
        params, _ = fm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(len(y)), 2,
        )
        acc = (np.asarray(fm.predict_scores(params, jnp.asarray(X)).argmax(1))
               == y).mean()
        assert acc > 0.95

    def test_weighted_equals_duplicated(self):
        X, y = _xor_interaction(n=300)
        k = np.asarray([1, 2] * 150)
        fm = FMClassifier(factor_size=2, max_iter=40, lr=0.05)
        pw, _ = fm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y),
            jnp.asarray(k, jnp.float32), 2,
        )
        pd, _ = fm.fit_from_init(
            KEY, jnp.asarray(np.repeat(X, k, axis=0)),
            jnp.asarray(np.repeat(y, k)),
            jnp.ones(int(k.sum())), 2,
        )
        # identical Adam trajectory => near-identical params (f32 sums
        # over reordered rows differ in rounding only)
        np.testing.assert_allclose(
            np.asarray(pw["W"]), np.asarray(pd["W"]), rtol=1e-3, atol=1e-4
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~3.4s FM integration soak; FM fit invariants stay tier-1 via the fuzz battery
    def test_in_bagging_and_mesh(self):
        X, y = _xor_interaction()
        clf = BaggingClassifier(
            base_learner=FMClassifier(factor_size=4, max_iter=150, lr=0.1),
            n_estimators=8, seed=0,
        ).fit(X, y)
        assert clf.score(X, y) > 0.9
        mesh = make_mesh(data=8)
        a = BaggingClassifier(
            base_learner=FMClassifier(factor_size=2, max_iter=30),
            n_estimators=1, bootstrap=False, seed=0, mesh=mesh,
        ).fit(X, y)
        b = BaggingClassifier(
            base_learner=FMClassifier(factor_size=2, max_iter=30),
            n_estimators=1, bootstrap=False, seed=0,
        ).fit(X, y)
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), rtol=1e-3, atol=1e-4
        )

    def test_param_validation(self):
        with pytest.raises(ValueError, match="factor_size"):
            FMClassifier(factor_size=0)
        with pytest.raises(ValueError, match="max_iter"):
            FMClassifier(max_iter=0)


class TestFMRegressor:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.4s interaction-recovery quality soak
    def test_learns_interaction_regression(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(1000, 5)).astype(np.float32)
        y = (2.0 * X[:, 0] * X[:, 1] + X[:, 2]
             + 0.1 * rng.normal(size=1000)).astype(np.float32)
        fm = FMRegressor(factor_size=4, max_iter=400, lr=0.1)
        params, _ = fm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(1000), 1
        )
        pred = np.asarray(fm.predict_scores(params, jnp.asarray(X)))
        r2 = 1 - np.var(pred - y) / np.var(y)
        assert r2 > 0.8

    @pytest.mark.slow  # [PR 14 pyramid] ~2.7s FM stream integration soak; stream engine parity stays tier-1 generic
    def test_bagged_and_streaming(self):
        from spark_bagging_tpu import ArrayChunks

        rng = np.random.default_rng(2)
        X = rng.normal(size=(800, 4)).astype(np.float32)
        y = (X[:, 0] * X[:, 1] + 0.1 * rng.normal(size=800)).astype(
            np.float32
        )
        reg = BaggingRegressor(
            base_learner=FMRegressor(factor_size=4, max_iter=300, lr=0.1),
            n_estimators=8, seed=0,
        ).fit(X, y)
        assert reg.score(X, y) > 0.7
        src = ArrayChunks(X, y, chunk_rows=200)
        rs = BaggingRegressor(
            base_learner=FMRegressor(factor_size=4), n_estimators=4,
            seed=0,
        ).fit_stream(src, n_epochs=60, lr=0.05)
        assert np.isfinite(rs.predict(X)).all()

    @pytest.mark.slow  # [PR 14 pyramid] ~2s per-model checkpoint twin; generic round-trip stays tier-1 in test_checkpoint
    def test_checkpoint_roundtrip(self, tmp_path):
        from spark_bagging_tpu import load_model, save_model

        X = np.random.default_rng(3).normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] * X[:, 1]).astype(np.float32)
        reg = BaggingRegressor(
            base_learner=FMRegressor(factor_size=2, max_iter=20),
            n_estimators=4, seed=0,
        ).fit(X, y)
        save_model(reg, str(tmp_path / "fm"))
        reg2 = load_model(str(tmp_path / "fm"))
        np.testing.assert_allclose(
            reg.predict(X[:50]), reg2.predict(X[:50]), rtol=1e-6
        )
