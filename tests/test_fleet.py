"""Fleet observability plane [ISSUE 12]: exact cross-process metric
federation (counters sum, gauges get process labels + min/max/sum,
histograms merge bucket-wise so fleet quantiles are EXACT — never
averaged percentiles), scrape staleness and quorum health, swap
convergence (version skew rise -> 0), the correlated incident
timeline, the `/fleet/*` scrape routes over real HTTP, and the
offline `dump --merge` CLI sharing the live merge code path.
"""

import io
import json
import time
import urllib.request
from contextlib import redirect_stdout

import numpy as np
import pytest

from spark_bagging_tpu import telemetry
from spark_bagging_tpu.telemetry import fleet
from spark_bagging_tpu.telemetry import server as tserver
from spark_bagging_tpu.telemetry.recorder import FlightRecorder
from spark_bagging_tpu.telemetry.registry import (
    Histogram,
    Registry,
    histogram_from_entry,
)

@pytest.fixture(scope="module", autouse=True)
def _module_clock():
    """Wall-clock anchor for the budget test: created when the FIRST
    test of this module runs (module import happens at collection,
    long before)."""
    return time.perf_counter()


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.enable()
    fleet.uninstall()
    # earlier suites leave weakly-registered health sources behind
    # (e.g. a closed batcher awaiting GC); the self-scrape test reads
    # this process's real /healthz, which must start from a clean slate
    tserver.clear_health_sources()
    yield
    tserver.stop_server()
    telemetry.recorder.disarm()
    fleet.uninstall()
    tserver.clear_health_sources()
    telemetry.reset()
    telemetry.enable()


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# -- Histogram.merge: the exact primitive ------------------------------

class TestHistogramMerge:
    def test_merged_quantiles_equal_concatenated_observations(self):
        """THE no-percentile-averaging guarantee: merging two
        histograms bucket-wise is indistinguishable from one histogram
        that observed both streams, so every quantile of the merge
        equals the quantile of the union — not the average of the two
        peers' quantiles."""
        rng = np.random.default_rng(7)
        obs_a = list(rng.lognormal(mean=-3.0, sigma=1.0, size=700))
        obs_b = list(rng.lognormal(mean=0.5, sigma=2.0, size=300))
        a, b, union = Histogram(), Histogram(), Histogram()
        for v in obs_a:
            a.observe(v)
            union.observe(v)
        for v in obs_b:
            b.observe(v)
            union.observe(v)
        a.merge(b)
        assert a.counts == union.counts
        assert a.count == union.count == 1000
        assert a.sum == pytest.approx(union.sum)
        for q in (0.5, 0.95, 0.99):
            assert a.quantile(q) == union.quantile(q)
        # and the merged p99 is NOT the average of the peers' p99s
        # (the skewed mixture makes the difference visible)
        fresh_a = Histogram()
        for v in obs_a:
            fresh_a.observe(v)
        avg_p99 = (fresh_a.quantile(0.99) + b.quantile(0.99)) / 2
        assert union.quantile(0.99) != pytest.approx(avg_p99, rel=1e-6)

    def test_count_sum_invariants_and_empty_merge(self):
        a, b = Histogram(), Histogram()
        for v in (0.01, 0.5, 3.0):
            a.observe(v)
        a.merge(b)  # empty right side: identity
        assert a.count == 3 and sum(a.counts) == 3
        b.merge(a)  # empty left side: copy
        assert b.counts == a.counts and b.sum == a.sum

    def test_bounds_mismatch_raises(self):
        a = Histogram()
        b = Histogram(buckets=[1.0, 2.0])
        with pytest.raises(ValueError, match="bounds"):
            a.merge(b)

    def test_exemplars_newest_wins(self):
        a, b = Histogram(), Histogram()
        a.observe(0.05, exemplar="old")
        b.observe(0.05, exemplar="new")
        b.exemplars[next(iter(b.exemplars))]["ts"] += 10.0
        a.merge(b)
        (ex,) = a.exemplars.values()
        assert ex["trace_id"] == "new"

    def test_roundtrip_through_snapshot_entry(self):
        reg = Registry()
        for v in (0.002, 0.02, 4.0):
            reg.observe("sbt_chunk_seconds", v, exemplar="t1")
        (entry,) = reg.snapshot()
        h = histogram_from_entry(entry)
        live = reg.histogram("sbt_chunk_seconds")
        assert h.counts == live.counts and h.count == live.count
        assert h.exemplars  # exemplar folded back by bucket


# -- snapshot merge ----------------------------------------------------

class TestMergeSnapshots:
    def _two_regs(self):
        r1, r2 = Registry(), Registry()
        r1.inc("sbt_serving_requests_total", 3)
        r2.inc("sbt_serving_requests_total", 5)
        r1.set("sbt_serving_queue_depth", 2.0)
        r2.set("sbt_serving_queue_depth", 7.0)
        r1.observe("sbt_serving_latency_seconds", 0.01)
        r2.observe("sbt_serving_latency_seconds", 1.5)
        return r1, r2

    def test_counters_sum_gauges_label_hists_merge(self):
        r1, r2 = self._two_regs()
        merged, dropped = fleet.merge_snapshots(
            [("p0", r1.snapshot()), ("p1", r2.snapshot())]
        )
        assert dropped == []
        by = {(e["name"], tuple(sorted(e["labels"].items()))): e
              for e in merged}
        assert by[("sbt_serving_requests_total", ())]["value"] == 8
        assert by[(
            "sbt_serving_queue_depth", (("process", "p0"),)
        )]["value"] == 2.0
        assert by[(
            "sbt_serving_queue_depth", (("fleet", "min"),)
        )]["value"] == 2.0
        assert by[(
            "sbt_serving_queue_depth", (("fleet", "max"),)
        )]["value"] == 7.0
        assert by[(
            "sbt_serving_queue_depth", (("fleet", "sum"),)
        )]["value"] == 9.0
        hist = by[("sbt_serving_latency_seconds", ())]
        assert hist["count"] == 2 and hist["sum"] == pytest.approx(1.51)

    def test_gauge_with_reserved_label_is_a_conflict_not_a_collision(self):
        # the merge owns 'process'/'fleet' on gauges: a pre-labeled
        # series (e.g. re-merging an already-merged snapshot) must be
        # dropped-and-reported, never silently collapsed into
        # duplicate-label entries
        r1, r2 = Registry(), Registry()
        r1.set("sbt_serving_queue_depth", 1.0,
               labels={"process": "x"})
        r2.set("sbt_serving_queue_depth", 2.0,
               labels={"process": "y"})
        r2.set("sbt_serving_shard_devices", 4.0)
        merged, dropped = fleet.merge_snapshots(
            [("p0", r1.snapshot()), ("p1", r2.snapshot())]
        )
        assert dropped == ["sbt_serving_queue_depth"]
        names = {e["name"] for e in merged}
        assert "sbt_serving_queue_depth" not in names
        assert "sbt_serving_shard_devices" in names

    def test_kind_conflict_drops_series_whole(self):
        r1, r2 = Registry(), Registry()
        r1.inc("sbt_x_total", 1)
        r2.set("sbt_x_total", 5.0)  # same name, different kind
        r2.inc("sbt_serving_requests_total", 2)
        merged, dropped = fleet.merge_snapshots(
            [("p0", r1.snapshot()), ("p1", r2.snapshot())]
        )
        assert dropped == ["sbt_x_total"]
        names = {e["name"] for e in merged}
        assert "sbt_x_total" not in names
        assert "sbt_serving_requests_total" in names

    def test_merged_digest_inclusion_and_exemplar_stripping(self):
        import copy

        r1, r2 = self._two_regs()
        snaps = [("p0", r1.snapshot()), ("p1", r2.snapshot())]
        merged, _ = fleet.merge_snapshots(snaps)
        d1 = fleet.merged_digest(merged)
        # a deterministic-plane series shifts the digest...
        r1.observe("sbt_serving_batch_fill_ratio", 0.5,
                   exemplar="trace-xyz")
        merged2, _ = fleet.merge_snapshots(
            [("p0", r1.snapshot()), ("p1", r2.snapshot())]
        )
        d2 = fleet.merged_digest(merged2)
        assert d2 != d1
        # ...but its exemplars (wall-clock ts, process-global trace
        # ids) are stripped: mutating one leaves the digest unchanged
        mutated = copy.deepcopy(merged2)
        for e in mutated:
            for ex in e.get("exemplars", ()):
                ex["ts"] = 12345.0
                ex["trace_id"] = "other"
        assert fleet.merged_digest(mutated) == d2
        # wall-clock series stay outside the deterministic plane
        r2.observe("sbt_serving_latency_seconds", 0.25)
        r2.set("sbt_process_rss_bytes", 12345.0)
        merged3, _ = fleet.merge_snapshots(
            [("p0", r1.snapshot()), ("p1", r2.snapshot())]
        )
        assert fleet.merged_digest(merged3) == d2
        # the no-filter digest sees everything
        assert fleet.merged_digest(merged3, series=None) != \
            fleet.merged_digest(merged2, series=None)


# -- the aggregator ----------------------------------------------------

class _FlakyPeer:
    """Scripted peer: fails while ``down`` is set."""

    def __init__(self, name, registry):
        self.name = name
        self._reg = registry
        self.down = False

    def scrape(self):
        if self.down:
            raise RuntimeError("scripted outage")
        return {"metrics": self._reg.snapshot()}


class TestAggregator:
    def test_stale_peer_freezes_counters_drops_gauges_never_zeros(self):
        r1, r2 = Registry(), Registry()
        r1.inc("sbt_serving_requests_total", 10)
        r2.inc("sbt_serving_requests_total", 32)
        r2.set("sbt_serving_queue_depth", 7.0)
        flaky = _FlakyPeer("p1", r2)
        agg = fleet.FleetAggregator(
            [fleet.RegistryPeer("p0", r1), flaky],
            interval_s=0.0, clock=lambda: 0.0,
        )
        agg.scrape_all(now=1.0)
        assert agg.peek("sbt_serving_requests_total").value == 42
        flaky.down = True
        r2.inc("sbt_serving_requests_total", 100)  # unseen progress
        agg.scrape_all(now=2.0)
        # the stale peer's counter FREEZES at its last-known value —
        # never zeroed (which would make the merged sum non-monotonic
        # and read as a failure spike to rate rules on recovery) —
        # while its gauges drop out and the staleness is visible
        assert agg.peek("sbt_serving_requests_total").value == 42
        assert agg.peek("sbt_serving_queue_depth",
                        {"process": "p1"}) is None
        assert agg.peek("sbt_fleet_peers_stale").value == 1
        assert agg.peek("sbt_fleet_scrape_failures_total",
                        {"process": "p1"}).value == 1
        age = agg.peek("sbt_fleet_scrape_age_seconds",
                       {"process": "p1"})
        assert age.value == pytest.approx(1.0)
        flaky.down = False
        agg.scrape_all(now=3.0)
        assert agg.peek("sbt_serving_requests_total").value == 142
        assert agg.peek("sbt_serving_queue_depth",
                        {"process": "p1"}).value == 7.0
        assert agg.peek("sbt_fleet_peers_stale").value == 0

    def test_never_scraped_peer_has_no_age_series(self):
        flaky = _FlakyPeer("p0", Registry())
        flaky.down = True
        agg = fleet.FleetAggregator(
            [flaky], interval_s=0.0, clock=lambda: 0.0,
        )
        agg.scrape_all(now=1.0)
        # absent, not zero — and not +Inf, which is not JSON: a strict
        # /fleet/varz consumer must never see a bare Infinity token
        assert agg.peek("sbt_fleet_scrape_age_seconds",
                        {"process": "p0"}) is None
        body = json.dumps(
            {"metrics": agg.merged_snapshot()}, allow_nan=False
        )
        assert "Infinity" not in body

    def test_quorum_health_degrades_then_loses(self):
        regs = [Registry() for _ in range(3)]
        flakies = [_FlakyPeer(f"p{i}", r) for i, r in enumerate(regs)]
        agg = fleet.FleetAggregator(flakies, interval_s=0.0,
                                    clock=lambda: 0.0)
        agg.scrape_all(now=1.0)
        h = agg.fleet_health(now=1.0)
        assert h["healthy"] and not h["degraded"]
        flakies[2].down = True
        agg.scrape_all(now=2.0)
        h = agg.fleet_health(now=2.0)
        assert h["healthy"] and h["degraded"]  # 2/3 >= majority
        flakies[1].down = True
        agg.scrape_all(now=3.0)
        h = agg.fleet_health(now=3.0)
        assert not h["healthy"]  # 1/3 < majority: quorum lost
        assert agg.peek("sbt_fleet_quorum").value == 0.0

    def test_peer_reported_unhealthz_counts_against_quorum(self):
        r = Registry()
        sick = fleet.RegistryPeer(
            "p0", r, health=lambda: {"healthy": False, "reason": "x"}
        )
        agg = fleet.FleetAggregator([sick], interval_s=0.0,
                                    clock=lambda: 0.0)
        agg.scrape_all(now=1.0)
        h = agg.fleet_health(now=1.0)
        assert h["peers"]["p0"]["fresh"] is True
        assert not h["healthy"]  # fresh but unhealthy: no quorum of 1

    def test_version_skew_rise_and_convergence_histogram(self):
        r1, r2 = Registry(), Registry()
        for r in (r1, r2):
            r.set("sbt_serving_model_version", 1.0,
                  labels={"model": "m"})
        agg = fleet.FleetAggregator(
            [fleet.RegistryPeer("p0", r1), fleet.RegistryPeer("p1", r2)],
            interval_s=0.0, clock=lambda: 0.0,
        )
        agg.scrape_all(now=0.0)
        assert agg.version_skew() == {"m": 0.0}
        r1.set("sbt_serving_model_version", 2.0, labels={"model": "m"})
        agg.scrape_all(now=1.0)
        assert agg.version_skew() == {"m": 1.0}
        assert agg.peek("sbt_fleet_version",
                        {"model": "m", "process": "p0"}).value == 2.0
        assert agg.peek("sbt_fleet_version_skew").value == 1.0
        r2.set("sbt_serving_model_version", 2.0, labels={"model": "m"})
        agg.scrape_all(now=3.5)
        assert agg.version_skew() == {"m": 0.0}
        # the excursion's duration landed in the convergence histogram
        assert agg.convergence_observations() == {"m": [2.5]}
        entry = next(
            e for e in agg.merged_snapshot()
            if e["name"] == "sbt_fleet_convergence_seconds"
        )
        assert entry["count"] == 1

    def test_skew_holds_open_when_lagging_peer_goes_stale(self):
        """A peer that wedges mid-upgrade at the OLD version and stops
        answering scrapes is exactly the stalled roll the skew metric
        exists to expose: skew is computed over LAST-KNOWN versions,
        so the excursion stays open through the outage (no spurious
        convergence) and closes only when the peer actually reports
        the new version."""
        r1, r2 = Registry(), Registry()
        for r in (r1, r2):
            r.set("sbt_serving_model_version", 1.0,
                  labels={"model": "m"})
        flaky = _FlakyPeer("p1", r2)
        agg = fleet.FleetAggregator(
            [fleet.RegistryPeer("p0", r1), flaky],
            interval_s=0.0, clock=lambda: 0.0,
        )
        agg.scrape_all(now=0.0)
        r1.set("sbt_serving_model_version", 2.0, labels={"model": "m"})
        agg.scrape_all(now=1.0)
        assert agg.version_skew() == {"m": 1.0}
        flaky.down = True  # p1 wedges, still at v1
        agg.scrape_all(now=2.0)
        agg.scrape_all(now=3.0)
        assert agg.version_skew() == {"m": 1.0}  # NOT fake-converged
        assert agg.convergence_observations() == {}
        assert agg.peek("sbt_fleet_version",
                        {"model": "m", "process": "p1"}).value == 1.0
        flaky.down = False
        r2.set("sbt_serving_model_version", 2.0, labels={"model": "m"})
        agg.scrape_all(now=5.0)
        assert agg.version_skew() == {"m": 0.0}
        # the excursion spans the whole outage: opened at 1.0
        assert agg.convergence_observations() == {"m": [4.0]}

    def test_alert_engine_over_merged_series(self):
        r = Registry()
        flaky = _FlakyPeer("p1", Registry())
        rules = fleet.default_fleet_rules(
            peer_fast_s=1.0, peer_slow_s=2.0, cooldown_s=100.0,
        )
        agg = fleet.FleetAggregator(
            [fleet.RegistryPeer("p0", r), flaky],
            interval_s=0.0, rules=rules, clock=lambda: 0.0,
        )
        flaky.down = True
        for t in range(6):
            agg.scrape_all(now=float(t))
        state = {s["name"]: s for s in agg.alerts.state()["rules"]}
        assert state["fleet-peer-lost"]["fired"] == 1
        assert state["fleet-peer-lost"]["active"] is True
        flaky.down = False
        agg.scrape_all(now=6.0)
        state = {s["name"]: s for s in agg.alerts.state()["rules"]}
        assert state["fleet-peer-lost"]["active"] is False
        assert state["fleet-peer-lost"]["resolved"] == 1
        # the other rules stayed quiet
        assert state["fleet-skew-stalled"]["fired"] == 0
        assert state["fleet-burn-rate"]["fired"] == 0
        # the firing reached the PRODUCTION (wall-clock) incident
        # timeline even though no telemetry sink was subscribed —
        # alert events are ts-stamped at creation, not at emission
        timeline = agg.incident_timeline()
        assert [(i["kind"], i["key"]) for i in timeline["incidents"]
                if i["kind"] == "alert_fired"] == \
            [("alert_fired", "fleet-peer-lost")]

    def test_interval_rate_limits_ticks(self):
        calls = []

        class CountingPeer:
            name = "p0"

            def scrape(self):
                calls.append(1)
                return {"metrics": []}

        agg = fleet.FleetAggregator([CountingPeer()], interval_s=5.0,
                                    clock=lambda: 0.0)
        assert agg.tick(now=0.0) is True
        assert agg.tick(now=1.0) is False  # inside the interval
        assert agg.tick(now=1.0, force=True) is True
        assert agg.tick(now=6.0) is True
        assert len(calls) == 3

    def test_peek_absent_is_none_and_validation(self):
        r = Registry()
        agg = fleet.FleetAggregator([fleet.RegistryPeer("p0", r)],
                                    interval_s=0.0, clock=lambda: 0.0)
        assert agg.peek("sbt_never_written_total") is None
        with pytest.raises(ValueError, match="at least one peer"):
            fleet.FleetAggregator([])
        with pytest.raises(ValueError, match="duplicate"):
            fleet.FleetAggregator([fleet.RegistryPeer("a", r),
                                   fleet.RegistryPeer("a", r)])
        with pytest.raises(ValueError, match="quorum"):
            fleet.FleetAggregator([fleet.RegistryPeer("a", r)],
                                  quorum=5)


# -- incident correlation ----------------------------------------------

class TestIncidents:
    def test_same_trigger_groups_inside_window(self):
        feeds = [
            ("p0", {"dumps": [], "events": [
                {"kind": "alert_fired", "rule": "burn", "ts": 100.0},
            ]}),
            ("p1", {"dumps": [
                {"kind": "serving_batch_error", "ts": 101.0,
                 "path": "flight_1.json"},
            ], "events": [
                {"kind": "alert_fired", "rule": "burn", "ts": 102.0},
            ]}),
            ("p2", {"dumps": [], "events": [
                {"kind": "alert_fired", "rule": "burn", "ts": 300.0},
            ]}),
        ]
        incidents, events = fleet.correlate_incidents(
            feeds, window_s=5.0
        )
        assert [e["t"] for e in events] == [100.0, 101.0, 102.0, 300.0]
        # two same-trigger alert firings 2s apart -> ONE incident
        # spanning two peers; the 300s one is a separate incident;
        # the flight dump is its own trigger kind
        kinds = [(i["kind"], i["count"], sorted(i["peers"]))
                 for i in incidents]
        assert ("alert_fired", 2, ["p0", "p1"]) in kinds
        assert ("alert_fired", 1, ["p2"]) in kinds
        assert ("serving_batch_error", 1, ["p1"]) in kinds
        assert fleet.timeline_digest(incidents) == \
            fleet.timeline_digest(incidents)

    def test_clock_key_selects_and_filters(self):
        feeds = [("p0", {"dumps": [], "events": [
            {"kind": "alert_fired", "rule": "r", "ts": 1e9,
             "now": 0.25},
            {"kind": "model_swapped", "model": "m", "ts": 1e9},
        ]})]
        incidents, events = fleet.correlate_incidents(
            feeds, window_s=1.0, clock_key="now"
        )
        # only the virtually-stamped event survives on the virtual
        # clock (never mix wall and virtual timestamps in one order)
        assert len(events) == 1 and events[0]["t"] == 0.25
        incidents_w, events_w = fleet.correlate_incidents(
            feeds, window_s=1.0, clock_key="ts"
        )
        assert len(events_w) == 2

    def test_recorder_timeline_feed_records_dumps(self, tmp_path):
        rec = FlightRecorder(dir=str(tmp_path), cooldown_s=0.0)
        rec.arm()
        try:
            telemetry.emit_event({"kind": "model_swapped",
                                  "model": "m", "version": 2})
            telemetry.emit_event({"kind": "serving_batch_error",
                                  "error": "boom"})
            telemetry.emit_event({"kind": "span", "name": "noise"})
        finally:
            rec.disarm()
        feed = rec.timeline_feed()
        assert [d["kind"] for d in feed["dumps"]] == \
            ["serving_batch_error"]
        assert feed["dumps"][0]["path"].endswith(".json")
        kinds = [e["kind"] for e in feed["events"]]
        assert kinds == ["model_swapped", "serving_batch_error"]


# -- /fleet/* routes over real HTTP ------------------------------------

class TestFleetRoutes:
    def test_routes_404_without_aggregator(self):
        port = tserver.start_server(0)
        code, body = _get(port, "/fleet/varz")
        assert code == 404 and "no fleet aggregator" in body
        code, body = _get(port, "/")
        assert "/fleet/incidents" in body

    def test_fleet_varz_quantiles_are_exact_union(self):
        """THE acceptance assertion: /fleet/varz p50/p95/p99 equal the
        quantiles computed from the union of the peers' bucket counts
        — no percentile averaging anywhere."""
        rng = np.random.default_rng(3)
        r1, r2 = Registry(), Registry()
        union = Histogram()
        for v in rng.lognormal(mean=-4, sigma=1.5, size=400):
            r1.observe("sbt_serving_latency_seconds", float(v))
            union.observe(float(v))
        for v in rng.lognormal(mean=-1, sigma=1.0, size=250):
            r2.observe("sbt_serving_latency_seconds", float(v))
            union.observe(float(v))
        fleet.install(fleet.FleetAggregator(
            [fleet.RegistryPeer("p0", r1), fleet.RegistryPeer("p1", r2)],
            interval_s=0.0,
        ))
        port = tserver.start_server(0)
        code, body = _get(port, "/fleet/varz")
        assert code == 200
        varz = json.loads(body)
        entry = next(e for e in varz["metrics"]
                     if e["name"] == "sbt_serving_latency_seconds")
        assert entry["count"] == 650
        assert [c for _, c in entry["buckets"]] == union.counts
        for q, want in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            assert entry["quantiles"][q] == pytest.approx(
                union.quantile(want), rel=0, abs=0
            )

    def test_fleet_metrics_healthz_and_incidents(self):
        r1 = Registry()
        r1.inc("sbt_serving_requests_total", 4)
        r1.set("sbt_serving_queue_depth", 1.0)
        flaky = _FlakyPeer("p1", Registry())
        flaky.down = True
        agg = fleet.FleetAggregator(
            [fleet.RegistryPeer("p0", r1), flaky], interval_s=0.0,
        )
        fleet.install(agg)
        port = tserver.start_server(0)
        code, body = _get(port, "/fleet/metrics")
        assert code == 200
        assert "sbt_serving_requests_total 4" in body
        assert 'sbt_serving_queue_depth{process="p0"} 1' in body
        assert 'sbt_fleet_scrape_failures_total{process="p1"} 1' in body
        # 1/2 fresh+healthy < majority(2)=2 -> quorum lost -> 503
        code, body = _get(port, "/fleet/healthz")
        assert code == 503
        report = json.loads(body)
        assert report["healthy"] is False
        assert report["peers"]["p1"]["fresh"] is False
        flaky.down = False
        code, body = _get(port, "/fleet/healthz")
        assert code == 200
        code, body = _get(port, "/fleet/incidents")
        assert code == 200
        timeline = json.loads(body)
        assert {"incidents", "events", "digest"} <= set(timeline)

    def test_http_peer_scrapes_a_real_varz(self):
        """An HTTPPeer pointed at this process's own exposition server
        — the loopback transport the production fleet uses — merges
        alongside an in-process peer, and a dead URL is a counted
        failure, not zeros."""
        telemetry.registry().inc("sbt_serving_requests_total", 6)
        port = tserver.start_server(0)
        other = Registry()
        other.inc("sbt_serving_requests_total", 10)
        agg = fleet.FleetAggregator(
            [
                fleet.HTTPPeer("self", f"http://127.0.0.1:{port}"),
                fleet.RegistryPeer("mem", other),
                fleet.HTTPPeer("ghost", "http://127.0.0.1:1",
                               timeout_s=0.2),
            ],
            interval_s=0.0,
        )
        agg.scrape_all()
        assert agg.peek("sbt_serving_requests_total").value == 16
        assert agg.peek("sbt_fleet_scrape_failures_total",
                        {"process": "ghost"}).value == 1
        h = agg.fleet_health()
        assert h["healthy"] and h["degraded"]
        # the self peer's varz carried its flight feed section
        st = agg._status["self"]
        assert "flight" in (st.snapshot or {})


# -- use_registry (the virtual-peer seam) ------------------------------

def test_use_registry_swaps_and_restores():
    main_reg = telemetry.registry()
    peer = Registry()
    with fleet.use_registry(peer):
        telemetry.inc("sbt_serving_requests_total", 3)
        assert telemetry.registry() is peer
    assert telemetry.registry() is main_reg
    assert peer.counter("sbt_serving_requests_total").value == 3
    assert main_reg.peek("sbt_serving_requests_total") is None
    with pytest.raises(RuntimeError):
        with fleet.use_registry(peer):
            raise RuntimeError("x")
    assert telemetry.registry() is main_reg


# -- faults: the fleet.scrape site -------------------------------------

def test_peer_loss_fault_site_fires_deterministically():
    from spark_bagging_tpu import faults

    regs = [Registry() for _ in range(3)]
    agg = fleet.FleetAggregator(
        [fleet.RegistryPeer(f"p{i}", r) for i, r in enumerate(regs)],
        interval_s=0.0, clock=lambda: 0.0,
    )
    plan = faults.builtin_plan("peer-loss")
    with faults.armed(plan):
        for t in range(25):
            agg.scrape_all(now=float(t))
    # every=3, times=20 over 3 peers scraped in order: the LAST peer
    # fails on the first 20 ticks, then recovers
    assert agg.peek("sbt_fleet_scrape_failures_total",
                    {"process": "p2"}).value == 20
    assert agg.peek("sbt_fleet_scrape_failures_total",
                    {"process": "p0"}).value == 0
    assert agg.peek("sbt_fleet_peers_stale").value == 0  # recovered
    snap = plan.snapshot()
    assert snap["hits"]["fleet.scrape"] == 75  # 25 ticks x 3 peers
    assert snap["fires"]["fleet.scrape"] == 20


# -- offline merge CLI -------------------------------------------------

class TestDumpMergeCLI:
    def _capture_log(self, path, n):
        telemetry.reset()
        with telemetry.capture(str(path)):
            telemetry.inc("sbt_serving_requests_total", n)
            telemetry.set_gauge("sbt_serving_queue_depth", float(n))
            telemetry.observe("sbt_serving_latency_seconds", 0.01 * n)

    def test_merge_two_logs_into_one_fleet_dump(self, tmp_path):
        from spark_bagging_tpu.telemetry.__main__ import main

        a, b = tmp_path / "peer_a.jsonl", tmp_path / "peer_b.jsonl"
        self._capture_log(a, 2)
        self._capture_log(b, 5)
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = main(["dump", "--merge", str(a), str(b)])
        assert rc == 0
        out = buf.getvalue()
        assert "sbt_serving_requests_total 7" in out
        assert 'sbt_serving_queue_depth{process="peer_a"} 2' in out
        assert 'sbt_serving_queue_depth{fleet="sum"} 7' in out
        # merged histogram: 2 observations, quantiles from the union
        assert "sbt_serving_latency_seconds_count 2" in out
        assert "# quantiles sbt_serving_latency_seconds" in out

    def test_merge_validations(self, tmp_path):
        from spark_bagging_tpu.telemetry.__main__ import main

        a, b = tmp_path / "x.jsonl", tmp_path / "y.jsonl"
        self._capture_log(a, 1)
        self._capture_log(b, 1)
        with pytest.raises(SystemExit):
            main(["dump", str(a), str(b)])  # several need --merge
        with pytest.raises(SystemExit):
            main(["dump", "--merge"])  # --merge needs files
        # duplicate basenames stay distinguishable
        sub = tmp_path / "sub"
        sub.mkdir()
        c = sub / "x.jsonl"
        self._capture_log(c, 3)
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = main(["dump", "--merge", str(a), str(c)])
        assert rc == 0
        out = buf.getvalue()
        assert 'process="x"' in out and 'process="x#1"' in out


def test_zz_fleet_suite_under_budget(_module_clock):
    """Tier-1 allowance for this module (the PR-11 ratchet
    discipline): the whole fleet suite must stay a lightweight unit
    suite — the heavyweight end-to-end drill lives in test_replay's
    budgeted CLI gate."""
    elapsed = time.perf_counter() - _module_clock
    assert elapsed < 20.0, (
        f"tests/test_fleet.py took {elapsed:.1f}s; move the offender "
        "to -m slow or shrink it"
    )
