"""The continuous-learning plane [ISSUE 15]: streaming Poisson-weight
updates (batch-fit parity bit for bit, streaming OOB vs batch
``oob_score_``, key-stream determinism), the labeled-traffic buffer,
the drift-triggered trainer's state machine (publish / reject+flight /
skip / supervised fault absorption) over real registry swaps, the
alert-engine trigger bus and workload drain seams, the lock-order
detector over the trainer→registry→recorder edges, and the in-process
closed-loop gate (one alert → one refit → one swap → recovery).
"""

import time

import jax
import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    LogisticRegression,
    faults,
    telemetry,
)
from spark_bagging_tpu.online import (
    LabeledBuffer,
    OnlineTrainer,
    OnlineUpdater,
)
from spark_bagging_tpu.serving import EnsembleExecutor, ModelRegistry
from spark_bagging_tpu.telemetry import alerts
from spark_bagging_tpu.telemetry import workload as workload_mod
from spark_bagging_tpu.telemetry.recorder import FlightRecorder


@pytest.fixture(scope="module", autouse=True)
def _module_clock():
    """Wall-clock anchor for the budget test (module import happens at
    collection, long before the first test runs)."""
    return time.perf_counter()


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.recorder.disarm()
    telemetry.reset()
    telemetry.enable()


def _problem(n=256, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w > 0).astype(np.int32)
    return X, y, w


def _fit(X, y, *, n_estimators=4, seed=3, **kw):
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=n_estimators, seed=seed, **kw,
    ).fit(X, y)


# -- the updater --------------------------------------------------------

class TestOnlineUpdater:
    def test_partial_fit_matches_batch_fit_bitwise(self):
        """Satellite [ISSUE 15]: a partial_fit pass over the full
        dataset under all-ones weights (an estimator fitted
        bootstrap=False) must reproduce the batch fit BIT FOR BIT on
        the served forward — the anchor pinning the online path to
        the batch semantics."""
        X, y, _ = _problem()
        est = _fit(X, y, bootstrap=False)
        upd = OnlineUpdater(est, warm=False)
        upd.partial_fit(X, y)
        for a, b in zip(jax.tree.leaves(est.ensemble_),
                        jax.tree.leaves(upd._params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        cand = upd.to_estimator()
        ex_a = EnsembleExecutor(est, min_bucket_rows=8,
                                max_batch_rows=32)
        ex_b = EnsembleExecutor(cand, min_bucket_rows=8,
                                max_batch_rows=32)
        out_a = ex_a.forward(X[:19])
        out_b = ex_b.forward(X[:19])
        assert out_a.tobytes() == out_b.tobytes()

    @pytest.mark.slow  # [PR 17 budget offset] ~4.6s OOB-tolerance soak (clf); the bitwise batch anchor test_partial_fit_matches_batch_fit_bitwise + test_regressor_stream_r2 stay tier-1
    def test_streaming_oob_tracks_batch_oob(self):
        """Satellite [ISSUE 15]: the prequential streaming OOB
        estimate over a seeded workload agrees with the batch
        ``oob_score_`` within the declared tolerance (0.1 — the
        streaming estimate is test-then-train while params move, so
        exact equality is not the contract)."""
        X, y, _ = _problem(n=512)
        est = _fit(X, y, n_estimators=16, seed=0, oob_score=True)
        upd = OnlineUpdater(est, seed=7)
        for lo in range(0, 512, 128):
            upd.partial_fit(X[lo:lo + 128], y[lo:lo + 128])
        assert upd.oob_rows > 100
        assert abs(upd.oob_estimate() - est.oob_score_) <= 0.1

    @pytest.mark.slow  # [PR 17 budget offset] ~3.2s key-schedule soak; online determinism stays tier-1 via the online-refit scenario transcript digest in the conformance smoke
    def test_key_stream_determinism(self):
        """Same (seed, example order) -> byte-identical params and OOB
        estimate; a different seed draws a different Poisson stream."""
        X, y, _ = _problem()
        est = _fit(X, y, oob_score=True)

        def run(seed):
            upd = OnlineUpdater(est, seed=seed)
            for lo in range(0, 256, 64):
                upd.partial_fit(X[lo:lo + 64], y[lo:lo + 64])
            return upd

        a, b, c = run(7), run(7), run(8)
        for la, lb in zip(jax.tree.leaves(a._params),
                          jax.tree.leaves(b._params)):
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))
        assert a.oob_estimate() == b.oob_estimate()
        assert any(
            not np.array_equal(np.asarray(la), np.asarray(lc))
            for la, lc in zip(jax.tree.leaves(a._params),
                              jax.tree.leaves(c._params))
        )

    def test_rejects_non_streamable_and_unknown_labels(self):
        X, y, _ = _problem(n=128)
        from spark_bagging_tpu import DecisionTreeClassifier

        tree_bag = BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=2),
            n_estimators=2, seed=0,
        ).fit(X, y)
        with pytest.raises(ValueError, match="not an SGD-able"):
            OnlineUpdater(tree_bag)
        est = _fit(X, y)
        upd = OnlineUpdater(est)
        with pytest.raises(ValueError, match="outside the fitted"):
            upd.partial_fit(X[:4], np.array([0, 1, 2, 1]))
        with pytest.raises(ValueError, match="must be"):
            upd.partial_fit(X[:4, :5], y[:4])

    @pytest.mark.slow  # [PR 19 budget offset] ~2.3s accuracy-band soak; stream-fit correctness stays tier-1 via test_partial_fit_matches_batch_fit_bitwise
    def test_regressor_stream_r2(self):
        """The regression half of the streaming OOB estimate: R² over
        OOB-voted rows on a stationary stream lands near the batch
        score."""
        rng = np.random.default_rng(1)
        X = rng.normal(size=(384, 6)).astype(np.float32)
        w = rng.normal(size=6)
        y = (X @ w + 0.1 * rng.normal(size=384)).astype(np.float32)
        from spark_bagging_tpu import LinearRegression

        est = BaggingRegressor(
            base_learner=LinearRegression(),
            n_estimators=8, seed=0, oob_score=True,
        ).fit(X, y)
        upd = OnlineUpdater(est, seed=5)
        for lo in range(0, 384, 128):
            upd.partial_fit(X[lo:lo + 128], y[lo:lo + 128])
        assert upd.oob_estimate() == pytest.approx(est.oob_score_,
                                                   abs=0.1)


# -- the buffer ---------------------------------------------------------

class TestLabeledBuffer:
    def test_capacity_eviction_and_drain(self):
        buf = LabeledBuffer(capacity_rows=64)
        for k in range(4):
            buf.add(np.full((32, 3), k, np.float32),
                    np.full(32, k, np.int32))
        # 128 rows added into a 64-row reservoir: the oldest blocks
        # evicted whole, the RECENT window retained
        assert buf.rows == 64
        assert buf.dropped_rows == 64
        X, y = buf.drain()
        assert X.shape == (64, 3)
        assert set(np.unique(y)) == {2, 3}
        # order preserved within the window
        assert y[0] == 2 and y[-1] == 3
        assert buf.drain() is None
        assert buf.rows == 0

    def test_shape_validation(self):
        buf = LabeledBuffer()
        with pytest.raises(ValueError, match="2-D"):
            buf.add(np.zeros(4, np.float32), np.zeros(4))
        with pytest.raises(ValueError, match="row counts"):
            buf.add(np.zeros((4, 2), np.float32), np.zeros(3))


# -- the seams ----------------------------------------------------------

class TestSeams:
    def test_alert_engine_trigger_bus(self):
        """subscribe() delivers alert events in subscription order,
        isolates a broken listener, and unsubscribe() detaches."""
        telemetry.set_gauge("sbt_quality_psi_max", 9.0)
        eng = alerts.AlertEngine([alerts.AlertRule(
            "r", "sbt_quality_psi_max", threshold=0.5,
            fast_window_s=1.0, slow_window_s=1.0, cooldown_s=100.0,
        )])
        got: list = []

        def boom(ev):
            raise RuntimeError("broken consumer")

        eng.subscribe(boom)
        eng.subscribe(got.append)
        with pytest.raises(TypeError):
            eng.subscribe("not callable")
        eng.evaluate(now=0.0)
        with pytest.warns(RuntimeWarning, match="alert listener"):
            events = eng.evaluate(now=2.0)
        assert [e["kind"] for e in events] == ["alert_fired"]
        assert [e["kind"] for e in got] == ["alert_fired"]
        assert got[0]["rule"] == "r"
        eng.unsubscribe(got.append)
        telemetry.set_gauge("sbt_quality_psi_max", 0.0)
        eng.evaluate(now=3.0)  # resolves; detached listener silent
        assert len(got) == 1

    def test_workload_recorder_drain(self):
        rec = workload_mod.WorkloadRecorder()
        rec.start()
        try:
            for i in range(6):
                rec.emit({"kind": "serving_request", "rows": i + 1,
                          "t_mono": float(i)})
            first = rec.drain(max_requests=4)
            assert [r.rows for r in first] == [3, 4, 5, 6]
            # drained entries are consumed; the earlier ones remain
            rest = rec.drain()
            assert [r.rows for r in rest] == [1, 2]
            assert rec.drain() == []
            # aggregates still cover the whole seen stream
            assert rec.summary()["n_seen"] == 6
        finally:
            rec.stop()


# -- the trainer --------------------------------------------------------

def _serving_stack(X, y, **est_kw):
    est = _fit(X, y, **est_kw)
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", est, warmup=False)
    return est, reg


class TestOnlineTrainer:
    @pytest.mark.slow  # [PR 19 budget offset] ~3.4s trigger->publish soak; the path stays tier-1 via the online-refit scenario in the conformance smoke (test_scenarios), plus the validation and min-rows tests here
    def test_publishes_on_trigger(self, tmp_path):
        X, y, _ = _problem()
        est, reg = _serving_stack(X, y)
        reg.enable_quality("m", refresh_every=1)
        buf = LabeledBuffer()
        buf.add(X[:128], y[:128])
        trainer = OnlineTrainer(
            reg, "m", buf, epochs=1, min_refit_rows=32,
            margin=0.05, seed=0, publish_dir=str(tmp_path / "pub"),
        )
        trainer.trigger(reason="manual", now=1.0)
        (rec,) = trainer.run_pending(now=1.0)
        assert rec["action"] == "published"
        assert rec["version"] == 2
        assert rec["manifest_version"] == 2
        assert reg.version("m") == 2
        # sticky quality monitoring re-attached to the candidate (the
        # recovery seam): fresh sketches, the candidate's own profile
        mon = reg.executor("m").quality
        assert mon is not None
        assert mon.profile is reg.model("m").quality_profile_
        # published checkpoint converges a peer registry by load()
        peer = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
        peer.load("m", str(tmp_path / "pub"), warm=False)
        assert peer.version("m") == 2
        snap = telemetry.registry().counter(
            "sbt_online_refits_published_total",
            labels={"model": "m"},
        ).value
        assert snap == 1.0

    def test_rejects_worse_candidate_and_flight_records(self):
        """A candidate scoring under the incumbent is rejected —
        counted, flight-recorded (refit_rejected is a trigger kind),
        and NEVER published. The incumbent is pinned unbeatable via
        the scoring seam so the reject branch is deterministic."""

        class _Unbeatable(OnlineTrainer):
            @staticmethod
            def _score(estimator, X, y):
                return 1.1  # > any achievable OOB estimate

        X, y, _ = _problem()
        est, reg = _serving_stack(X, y)
        buf = LabeledBuffer()
        buf.add(X[:128], y[:128])
        flight = FlightRecorder(cooldown_s=0.0)
        flight.arm()
        try:
            trainer = _Unbeatable(reg, "m", buf, min_refit_rows=32,
                                  seed=0)
            trainer.trigger(now=1.0)
            (rec,) = trainer.run_pending(now=1.0)
        finally:
            flight.disarm()
        assert rec["action"] == "rejected"
        assert reg.version("m") == 1
        assert trainer.rejected == 1 and trainer.published == 0
        assert len(flight.dumps) == 1
        assert flight.dump_records[0]["kind"] == "refit_rejected"

    def test_skips_below_min_rows_without_draining(self):
        """A premature trigger (labels still in flight) must leave the
        window ACCUMULATING — the rule cooldown means no second
        trigger comes for this incident, so a drain here would
        permanently discard its labeled rows."""
        X, y, _ = _problem(n=256)
        est, reg = _serving_stack(X, y)
        buf = LabeledBuffer()
        buf.add(X[:8], y[:8])
        trainer = OnlineTrainer(reg, "m", buf, min_refit_rows=32,
                                margin=0.05, seed=0)
        trainer.trigger(now=0.0)
        (rec,) = trainer.run_pending()
        assert rec["action"] == "skipped"
        assert rec["buffered_rows"] == 8
        assert trainer.skipped == 1
        assert reg.version("m") == 1
        assert buf.rows == 8  # retained, not discarded
        # once the labels catch up, the SAME incident's window refits
        buf.add(X[8:136], y[8:136])
        trainer.trigger(now=1.0)
        (rec2,) = trainer.run_pending()
        assert rec2["action"] == "published"
        assert rec2["drained_rows"] == 136

    def test_supervision_absorbs_injected_faults(self):
        """The daemon contract: a refit killed at any hand-off site is
        absorbed (counted, transcribed) and the NEXT trigger still
        publishes — a trainer crash never takes the loop down."""
        X, y, _ = _problem()
        est, reg = _serving_stack(X, y)
        buf = LabeledBuffer()
        buf.add(X[:128], y[:128])
        trainer = OnlineTrainer(reg, "m", buf, min_refit_rows=32,
                                margin=0.05, seed=0)
        plan = faults.FaultPlan(
            [{"site": "trainer.refit", "action": "error", "at": [1]}]
        )
        with faults.armed(plan):
            trainer.trigger(now=0.0)
            (rec,) = trainer.run_pending()
        assert rec["action"] == "error"
        assert "injected" in rec["error"]
        assert trainer.errors == 1
        assert reg.version("m") == 1
        # drained rows were consumed by the dead refit (the window is
        # gone — a crashed refit must not replay stale data); refill
        # and the daemon publishes normally
        buf.add(X[:128], y[:128])
        trainer.trigger(now=1.0)
        (rec2,) = trainer.run_pending()
        assert rec2["action"] == "published"
        assert reg.version("m") == 2

    def test_alert_filter_and_threaded_daemon(self):
        X, y, _ = _problem()
        est, reg = _serving_stack(X, y)
        buf = LabeledBuffer()
        buf.add(X[:128], y[:128])
        trainer = OnlineTrainer(reg, "m", buf, min_refit_rows=32,
                                margin=0.05, seed=0,
                                trigger_rules=("the-rule",))
        # the trigger bus filter: foreign rules and resolutions pass
        trainer.on_alert({"kind": "alert_fired", "rule": "other"})
        trainer.on_alert({"kind": "alert_resolved", "rule": "the-rule"})
        assert trainer.pending == 0
        trainer.start()
        try:
            trainer.on_alert({"kind": "alert_fired", "rule": "the-rule",
                              "now": 2.0})
            deadline = time.time() + 20.0
            while trainer.published == 0 and time.time() < deadline:
                if trainer.errors:
                    break
                time.sleep(0.02)
        finally:
            trainer.stop()
        assert trainer.published == 1
        assert reg.version("m") == 2

    def test_lock_order_clean_over_refit(self):
        """Satellite [ISSUE 15]: the lock-order detector over the
        trainer→registry→recorder edges — a full publish cycle under
        instrumented locks (trainer lock, buffer lock, registry lock,
        recorder lock, telemetry quality lock) must close no cycle."""
        from spark_bagging_tpu.analysis import locks

        locks.clear()
        locks.enable(True)
        try:
            X, y, _ = _problem()
            est, reg = _serving_stack(X, y)
            reg.enable_quality("m", refresh_every=1)
            flight = FlightRecorder(cooldown_s=0.0)
            flight.arm()
            try:
                buf = LabeledBuffer()
                buf.add(X[:128], y[:128])
                trainer = OnlineTrainer(reg, "m", buf,
                                        min_refit_rows=32,
                                        margin=0.05, seed=0)
                trainer.trigger(now=0.0)
                (rec,) = trainer.run_pending()
            finally:
                flight.disarm()
            assert rec["action"] == "published"
            assert locks.violations() == [], locks.violations()
            edges = locks.acquisition_edges()
            assert ("online.trainer", "online.trainer") not in edges
        finally:
            locks.enable(False)
            locks.clear()

    def test_validation_errors(self):
        X, y, _ = _problem(n=64)
        est, reg = _serving_stack(X, y)
        buf = LabeledBuffer()
        with pytest.raises(KeyError):
            OnlineTrainer(reg, "nope", buf)
        with pytest.raises(ValueError, match="epochs"):
            OnlineTrainer(reg, "m", buf, epochs=0)
        with pytest.raises(ValueError, match="margin"):
            OnlineTrainer(reg, "m", buf, margin=-1.0)


# -- the closed-loop gate ----------------------------------------------

class TestClosedLoop:
    @pytest.mark.slow  # [PR 17 budget offset] ~3.2s in-process drill; the same gate runs tier-1 as the online-refit scenario (digest + SLO) in the conformance smoke
    def test_online_drill_gate(self):
        """The in-process acceptance drill: one alert → one refit →
        one fleet-converged swap → drift-gauge recovery, repeats
        byte-identical (replay_median asserts the online transcript
        digest across them), every gate check green."""
        from benchmarks import replay as R

        model, label_fn = R._default_problem(8, 4, seed=0)
        wl = workload_mod.synthetic_workload(
            "poisson", rate_rps=300.0, duration_s=1.4, seed=108,
            rows=1, width=8, bucket_bounds=(8, 32),
        )
        report = R.replay_median(
            wl, repeats=2, online=True, model=model,
            label_fn=label_fn, seed=108, drift_at=0.3,
            buffer_rows=128, min_bucket_rows=8, bucket_max_rows=32,
        )
        result = R.check_report(report)
        assert result.ok, result.render()
        o = report["online"]
        assert o["refits"] == {"triggered": 1, "published": 1,
                               "rejected": 0, "skipped": 0,
                               "errors": 0}
        assert o["version_final"] == 2
        assert o["manifest_version"] == 2
        assert report["drift"]["alerts_fired"] == 1
        assert report["drift"]["flight_dumps"] == 1
        assert o["recovery"]["alert_resolved"] is True
        # warmed recovery: the post-swap monitor saw enough tail rows
        # to score honestly, and the gauge sits back under the rule
        assert o["recovery"]["final_warmed"] is True
        assert o["recovery"]["final_psi_gauge"] < 0.5

    def test_online_cli_flag_validation(self):
        from benchmarks import replay as R

        with pytest.raises(SystemExit):
            R.main(["--online"])  # needs --drift
        with pytest.raises(SystemExit):
            R.main(["--online", "--drift", "--fleet", "3"])
        with pytest.raises(SystemExit):
            R.main(["--online", "--drift", "--mode", "timed"])


def test_zz_online_suite_under_budget(_module_clock):
    """Tier-1 allowance for this module (the ratchet discipline): the
    closed-loop drill is already covered by the budgeted scenario
    conformance smoke; this suite must stay a lightweight unit+gate
    suite."""
    elapsed = time.perf_counter() - _module_clock
    assert elapsed < 35.0, (
        f"tests/test_online.py took {elapsed:.1f}s; move the offender "
        "to -m slow or shrink it"
    )
