"""Cross-artifact contract engine tests [ISSUE 19]: a miniature repo
skeleton that satisfies every contract, plus one BAD mutation per
check — the BAD/GOOD fixture convention of test_analysis.py applied to
whole-repo artifacts instead of single sources. The real-tree gate
lives in test_analysis.py (test_repo_tree_is_contract_clean); here we
prove each check actually fires on the drift it claims to catch.
"""

from __future__ import annotations

import os

import pytest

from spark_bagging_tpu.analysis.contracts import (
    CONTRACT_CHECKS,
    check_repo,
)

# -- miniature repo skeleton -------------------------------------------

_SKELETON = {
    "spark_bagging_tpu/telemetry/registry.py": '''\
SERIES_HELP = {
    "sbt_requests_total": "requests (unlabeled total + label tenant)",
    "sbt_queue_depth": "queue depth",
}
''',
    "spark_bagging_tpu/faults.py": '''\
SITES = {
    "serving.submit": "the submit path",
}
''',
    "spark_bagging_tpu/telemetry/recorder.py": '''\
TRIGGER_KINDS = ("drift_alert",)
TIMELINE_KINDS = TRIGGER_KINDS + ("model_swapped",)
''',
    "spark_bagging_tpu/telemetry/alerts.py": '''\
def default_drift_rules():
    return [AlertRule("queue-deep", "sbt_queue_depth", 10.0)]
''',
    "spark_bagging_tpu/telemetry/server.py": '''\
def do_GET(self, url):
    if url.path == "/metrics":
        return self._metrics()
    return {"endpoints": ["/metrics"]}
''',
    "spark_bagging_tpu/telemetry/perf.py": '''\
VERDICTS = ("failed", "queue-dominated")
''',
    "spark_bagging_tpu/app.py": '''\
def work(telemetry, faults):
    telemetry.inc("sbt_requests_total")
    telemetry.inc("sbt_requests_total", labels={"tenant": "a"})
    telemetry.set_gauge("sbt_queue_depth", 1)
    faults.fire("serving.submit")
    return [{"kind": "drift_alert"}, {"kind": "model_swapped"}]
''',
    "benchmarks/scenarios/__init__.py": '''\
def _register_all(register, Scenario):
    register(Scenario(name="smoke"))
''',
    "benchmarks/baselines/scenarios/smoke.json": "{}\n",
    "ARCHITECTURE.md": """\
# mini

| route | serves | semantics |
|---|---|---|
| `/metrics` | text | the scrape endpoint |

| verdict | evidence |
|---|---|
| `failed` | the record carries an error |
| `queue-dominated` | queue wait dominates |
""",
}


def build_repo(root, overrides=None):
    files = dict(_SKELETON)
    files.update(overrides or {})
    for rel, content in files.items():
        path = os.path.join(str(root), rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(content)
    return str(root)


def findings_of(root, check):
    return check_repo(root, checks=[check])


# -- GOOD: the skeleton satisfies every contract -----------------------


def test_skeleton_is_clean_under_every_check(tmp_path):
    root = build_repo(tmp_path)
    findings = check_repo(root)
    assert not findings, "\n".join(f.render() for f in findings)


# -- BAD: one mutation per check ---------------------------------------

# check name -> (overrides, expected message fragment)
BAD_CASES = {
    "contract-series-help": [
        # an emitted series with no help entry
        ({"spark_bagging_tpu/app.py":
          _SKELETON["spark_bagging_tpu/app.py"]
          + '\n\ndef more(telemetry):\n'
            '    telemetry.inc("sbt_ghost_total")\n'},
         "no SERIES_HELP entry"),
        # a help entry nothing emits — dead documentation
        ({"spark_bagging_tpu/telemetry/registry.py": '''\
SERIES_HELP = {
    "sbt_requests_total": "requests (unlabeled total + label tenant)",
    "sbt_queue_depth": "queue depth",
    "sbt_dead_series": "documented, never emitted",
}
'''},
         "no emit site"),
    ],
    "contract-series-twins": [
        # labeled emit gone: the per-key breakdown the help promises
        ({"spark_bagging_tpu/app.py": '''\
def work(telemetry, faults):
    telemetry.inc("sbt_requests_total")
    telemetry.set_gauge("sbt_queue_depth", 1)
    faults.fire("serving.submit")
    return [{"kind": "drift_alert"}, {"kind": "model_swapped"}]
'''},
         "no LABELED emit site"),
        # unlabeled emit gone: the fleet-merge total reads 0
        ({"spark_bagging_tpu/app.py": '''\
def work(telemetry, faults):
    telemetry.inc("sbt_requests_total", labels={"tenant": "a"})
    telemetry.set_gauge("sbt_queue_depth", 1)
    faults.fire("serving.submit")
    return [{"kind": "drift_alert"}, {"kind": "model_swapped"}]
'''},
         "no UNLABELED emit site"),
    ],
    "contract-fault-sites": [
        # fire() of an unregistered site — a silent no-op plan key
        ({"spark_bagging_tpu/app.py":
          _SKELETON["spark_bagging_tpu/app.py"]
          + '\n\ndef more(faults):\n'
            '    faults.fire("serving.ghost")\n'},
         "no faults.SITES entry"),
        # a SITES entry nobody fires — dead fault surface
        ({"spark_bagging_tpu/faults.py": '''\
SITES = {
    "serving.submit": "the submit path",
    "serving.dead": "registered, never fired",
}
'''},
         "no live fire() call"),
    ],
    "contract-recorder-kinds": [
        ({"spark_bagging_tpu/telemetry/recorder.py": '''\
TRIGGER_KINDS = ("drift_alert", "ghost_kind")
TIMELINE_KINDS = TRIGGER_KINDS + ("model_swapped",)
'''},
         "never emitted"),
    ],
    "contract-alert-rules": [
        ({"spark_bagging_tpu/telemetry/alerts.py": '''\
def default_drift_rules():
    return [AlertRule("ghost", "sbt_missing_series", 1.0)]
'''},
         "does not exist"),
    ],
    "contract-http-routes": [
        # served but neither documented nor index-advertised
        ({"spark_bagging_tpu/telemetry/server.py": '''\
def do_GET(self, url):
    if url.path == "/metrics":
        return self._metrics()
    if url.path == "/hidden":
        return self._hidden()
    return {"endpoints": ["/metrics"]}
'''},
         "missing from the ARCHITECTURE.md route table"),
        # documented but 404s
        ({"ARCHITECTURE.md": _SKELETON["ARCHITECTURE.md"].replace(
            "| `/metrics` | text | the scrape endpoint |",
            "| `/metrics` | text | the scrape endpoint |\n"
            "| `/ghost` | json | promised, never dispatched |")},
         "not dispatched"),
        # advertised on / but 404s
        ({"spark_bagging_tpu/telemetry/server.py": '''\
def do_GET(self, url):
    if url.path == "/metrics":
        return self._metrics()
    return {"endpoints": ["/metrics", "/phantom"]}
'''},
         "advertises an endpoint"),
    ],
    "contract-tail-verdicts": [
        # a verdict the ladder emits but the docs never explain
        ({"spark_bagging_tpu/telemetry/perf.py": '''\
VERDICTS = ("failed", "queue-dominated", "wfq-starved")
'''},
         "missing from the ARCHITECTURE.md verdict-ladder table"),
        # a documented verdict correlate_tail can never emit
        ({"ARCHITECTURE.md": _SKELETON["ARCHITECTURE.md"]
          + "| `ghost-verdict` | promised, never emitted |\n"},
         "is not in"),
    ],
    "contract-scenario-baselines": [
        # registered with no committed baseline
        ({"benchmarks/scenarios/__init__.py": '''\
def _register_all(register, Scenario):
    register(Scenario(name="smoke"))
    register(Scenario(name="orphan"))
'''},
         "no committed baseline"),
        # a baseline matching no scenario — stale artifact
        ({"benchmarks/baselines/scenarios/stale.json": "{}\n"},
         "matches no registered scenario"),
    ],
}

_CASES = [(check, i) for check in sorted(BAD_CASES)
          for i in range(len(BAD_CASES[check]))]


@pytest.mark.parametrize(
    "check,i", _CASES, ids=[f"{c}-{i}" for c, i in _CASES]
)
def test_bad_mutation_is_flagged(tmp_path, check, i):
    overrides, fragment = BAD_CASES[check][i]
    root = build_repo(tmp_path, overrides)
    found = findings_of(root, check)
    assert found, f"{check} missed its BAD mutation #{i}"
    assert any(fragment in f.message for f in found), (
        f"{check} fired, but not for the expected reason:\n"
        + "\n".join(f.render() for f in found)
    )


def test_every_registered_check_has_bad_fixture():
    """Registry-completeness guard: a contract check that never proved
    it fires is not trusted."""
    assert set(CONTRACT_CHECKS) == set(BAD_CASES), (
        "update BAD_CASES in test_analysis_contracts.py when adding "
        "contract checks"
    )


def test_unknown_check_name_raises(tmp_path):
    build_repo(tmp_path)
    with pytest.raises(KeyError):
        check_repo(str(tmp_path), checks=["no-such-check"])


def test_disabled_check_is_skipped(tmp_path):
    overrides, _ = BAD_CASES["contract-fault-sites"][0]
    root = build_repo(tmp_path, overrides)
    assert findings_of(root, "contract-fault-sites")
    assert not check_repo(root, disabled=set(CONTRACT_CHECKS))
