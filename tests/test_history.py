"""Longitudinal history store + trend verdicts [ISSUE 14]:

- append/read round-trip (torn lines tolerated, never fatal);
- ``compare_trend``: a digest flip FIRES (exact, no noise band), an
  SLO ok->failed transition fires, numeric wobble inside the CI-noise
  band does NOT, movement beyond it is advisory drift;
- the surfaces: ``/debug/history`` on the scrape server and the
  ``python -m benchmarks.scenarios history`` CLI both render appended
  runs with the correct flip verdict.
"""

import json
import urllib.request

import pytest

from spark_bagging_tpu import telemetry
from spark_bagging_tpu.telemetry import history
from spark_bagging_tpu.telemetry import server as tserver


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    # every test gets its own telemetry dir: the history store under
    # test must never read the repo's real run artifacts
    monkeypatch.setenv("SBT_TELEMETRY_DIR", str(tmp_path))
    telemetry.reset()
    telemetry.enable()
    yield
    tserver.stop_server()
    telemetry.recorder.disarm()
    telemetry.reset()
    telemetry.enable()


def test_append_and_read_roundtrip(tmp_path):
    r1 = history.append_record(
        "scenario", "steady", digests={"output": "aa"},
        numbers={"rps": 100.0}, slo_ok=True, ts=1000.0,
    )
    r2 = history.append_record(
        "scenario", "steady", digests={"output": "aa"},
        numbers={"rps": 101.0}, slo_ok=True, ts=1001.0,
        run_id="explicit-id",
    )
    assert r1["schema"] == history.HISTORY_SCHEMA_VERSION
    assert r2["run_id"] == "explicit-id"
    path = history.history_path()
    assert path.startswith(str(tmp_path))
    back = history.read_history()
    assert [r["ts"] for r in back] == [1000.0, 1001.0]
    assert back[0]["digests"] == {"output": "aa"}
    assert back[1]["numbers"] == {"rps": 101.0}
    # limit keeps the newest; limit=0 means NONE (not records[-0:],
    # which would slice the whole store)
    assert [r["ts"] for r in history.read_history(limit=1)] == [1001.0]
    assert history.read_history(limit=0) == []
    assert history.history_report(limit=0)["records"] == []
    # the append counter moved
    assert telemetry.registry().counter(
        "sbt_history_appends_total").value == 2


def test_torn_and_garbage_lines_are_skipped():
    history.append_record("tier", "tier1", numbers={"elapsed_s": 400.0})
    with open(history.history_path(), "a") as f:
        f.write("not json at all\n")
        f.write('{"kind": "tier", "key": "tier1", "truncat')  # torn
    history.append_record("tier", "tier1", numbers={"elapsed_s": 410.0})
    back = history.read_history()
    assert len(back) == 2
    assert all(r["kind"] == "tier" for r in back)


def _rec(key, ts, digest=None, rps=None, slo_ok=None, kind="scenario"):
    r = {"schema": 1, "ts": ts, "run_id": f"{key}-{ts}", "kind": kind,
         "key": key}
    if digest is not None:
        r["digests"] = {"output": digest}
    if rps is not None:
        r["numbers"] = {"rps": rps}
    if slo_ok is not None:
        r["slo_ok"] = slo_ok
    return r


def test_digest_flip_fires_exactly():
    trend = history.compare_trend([
        _rec("a", 1, digest="X", rps=100.0),
        _rec("a", 2, digest="X", rps=99.0),
        _rec("a", 3, digest="Y", rps=101.0),
    ])
    assert trend["ok"] is False
    (flip,) = trend["flips"]
    assert flip["class"] == "digest"
    assert flip["field"] == "output"
    assert (flip["from"], flip["to"]) == ("X", "Y")
    assert flip["run_to"] == "a-3"
    assert trend["groups"]["scenario:a"]["flips"] == 1
    # the noise-band rps wobble (±1%) raised no drift
    assert trend["drift"] == []
    # and the gauges mirror the verdict
    reg = telemetry.registry()
    assert reg.gauge("sbt_history_digest_flips").value == 1.0
    assert reg.gauge("sbt_history_records").value == 3.0


def test_noise_band_wobble_does_not_fire():
    recs = [_rec("a", t, digest="X", rps=rps)
            for t, rps in ((1, 100.0), (2, 108.0), (3, 95.0),
                           (4, 103.0))]
    trend = history.compare_trend(recs)
    assert trend["ok"] is True
    assert trend["flips"] == [] and trend["drift"] == []
    # beyond the band: the latest run collapses to 30 rps (-70%)
    trend = history.compare_trend(recs + [_rec("a", 5, digest="X",
                                               rps=30.0)])
    assert trend["ok"] is True  # drift is advisory, not a flip
    (d,) = trend["drift"]
    assert d["field"] == "rps" and d["relative"] < -history.NOISE_TOLERANCE
    # a single run has no trend to judge
    assert history.compare_trend([_rec("b", 1, rps=1.0)])["drift"] == []


def test_slo_regression_is_a_flip():
    trend = history.compare_trend([
        _rec("a", 1, digest="X", slo_ok=True),
        _rec("a", 2, digest="X", slo_ok=False),
    ])
    assert trend["ok"] is False
    (flip,) = trend["flips"]
    assert flip["class"] == "slo" and flip["field"] == "slo_ok"
    # flips compare against the LAST-KNOWN value: a record carrying no
    # slo_ok (a `record`/`run` append) or omitting a digest field
    # interleaved between two checks must not mask the regression
    trend = history.compare_trend([
        _rec("a", 1, digest="X", slo_ok=True),
        _rec("a", 2, rps=1.0),  # no slo_ok, no digests
        _rec("a", 3, digest="Y", slo_ok=False),
    ])
    assert {f["class"] for f in trend["flips"]} == {"digest", "slo"}
    assert all(f["run_from"] == "a-1" and f["run_to"] == "a-3"
               for f in trend["flips"])
    # groups are independent: a flip in one never marks another
    trend2 = history.compare_trend([
        _rec("a", 1, digest="X"), _rec("a", 2, digest="Y"),
        _rec("b", 1, digest="Z"), _rec("b", 2, digest="Z"),
    ])
    assert trend2["groups"]["scenario:b"]["flips"] == 0
    assert trend2["groups"]["scenario:a"]["flips"] == 1


def test_history_report_and_render():
    history.append_record("scenario", "s", digests={"output": "A"},
                          ts=1.0)
    history.append_record("scenario", "s", digests={"output": "B"},
                          ts=2.0)
    report = history.history_report(limit=1)
    assert report["runs"] == 2
    assert len(report["records"]) == 1  # limit trims the listing...
    assert len(report["trend"]["flips"]) == 1  # ...but not the scan
    text = history.render_history(report)
    assert "FLIP" in text and "scenario:s" in text
    assert "DIGEST FLIP" in text


def test_debug_history_route_renders_appended_runs():
    """ISSUE 14 acceptance: /debug/history renders >= 2 appended runs
    with a correct digest-flip verdict."""
    history.append_record("scenario", "steady",
                          digests={"output": "aaa"}, ts=10.0)
    history.append_record("scenario", "steady",
                          digests={"output": "bbb"}, ts=11.0)
    port = tserver.start_server(0)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/debug/history", timeout=10
    ) as resp:
        assert resp.status == 200
        body = json.loads(resp.read().decode())
    assert body["runs"] == 2
    assert len(body["records"]) == 2
    assert body["trend"]["ok"] is False
    (flip,) = body["trend"]["flips"]
    assert flip["field"] == "output"
    assert (flip["from"], flip["to"]) == ("aaa", "bbb")
    # the route is on the index
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=10
    ) as resp:
        assert "/debug/history" in json.loads(resp.read().decode())[
            "endpoints"]


def test_history_cli_renders_and_exits_on_flip(capsys):
    """`python -m benchmarks.scenarios history` (in-process): renders
    the appended runs and exits 2 on a digest flip, 0 when stable."""
    from benchmarks.scenarios.__main__ import main

    history.append_record("scenario", "s", digests={"output": "A"},
                          ts=1.0)
    assert main(["history"]) == 0
    history.append_record("scenario", "s", digests={"output": "B"},
                          ts=2.0)
    rc = main(["history"])
    out = capsys.readouterr().out
    assert rc == 2
    assert "FLIP" in out and "2 runs" in out
