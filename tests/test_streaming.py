"""Out-of-core streaming fit tests [SURVEY §7 step 8, §4].

Runs under the 8-device CPU fake topology (conftest.py)."""

import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import (
    ArrayChunks,
    BaggingClassifier,
    BaggingRegressor,
    CSVChunks,
    LibsvmChunks,
    LogisticRegression,
    SyntheticChunks,
    make_mesh,
)
from spark_bagging_tpu.models import (
    DecisionTreeClassifier,
    LinearRegression,
    MLPClassifier,
)
from spark_bagging_tpu.utils.datasets import (
    make_classification,
    make_regression,
)


@pytest.fixture(scope="module")
def cancer():
    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------
# Chunk sources
# ---------------------------------------------------------------------


def test_array_chunks_cover_all_rows_fixed_shape():
    X = np.arange(23 * 3, dtype=np.float32).reshape(23, 3)
    y = np.arange(23, dtype=np.float32)
    src = ArrayChunks(X, y, chunk_rows=10)
    assert src.n_chunks == 3
    got_X, got_y = [], []
    for Xc, yc, n_valid in src.chunks():
        assert Xc.shape == (10, 3) and yc.shape == (10,)
        got_X.append(Xc[:n_valid])
        got_y.append(yc[:n_valid])
    np.testing.assert_array_equal(np.concatenate(got_X), X)
    np.testing.assert_array_equal(np.concatenate(got_y), y)


def test_array_chunks_epochs_are_identical():
    X, y = make_classification(57, 4, 2, seed=3)
    src = ArrayChunks(X, y, chunk_rows=16)
    first = list(src.chunks())
    second = list(src.chunks())
    for (Xa, ya, na), (Xb, yb, nb) in zip(first, second):
        np.testing.assert_array_equal(Xa, Xb)
        np.testing.assert_array_equal(ya, yb)
        assert na == nb


def test_synthetic_chunks_deterministic_and_out_of_core():
    src = SyntheticChunks(
        lambda n, seed, structure_seed=None: make_classification(
            n, 5, 2, seed=seed, structure_seed=structure_seed
        ),
        n_rows=95, chunk_rows=40, seed=1,
    )
    assert src.n_features == 5
    chunks = list(src.chunks())
    assert len(chunks) == 3
    assert chunks[-1][2] == 15  # final partial chunk padded
    again = list(src.chunks())
    np.testing.assert_array_equal(chunks[0][0], again[0][0])


def test_synthetic_chunks_share_structure_across_chunks():
    # every chunk must come from the SAME mixture (structure pinned to
    # the source seed), or a streamed "dataset" is nonstationary
    src = SyntheticChunks(
        make_classification_5d, n_rows=4000, chunk_rows=1000, seed=1
    )
    class_means = []
    for Xc, yc, n in src.chunks():
        class_means.append(Xc[:n][yc[:n] == 0].mean(axis=0))
    spread = np.ptp(np.stack(class_means), axis=0).max()
    assert spread < 0.5, f"chunk class centers drifted: {spread}"


def make_classification_5d(n, seed=0, structure_seed=None):
    return make_classification(
        n, 5, 2, seed=seed, structure_seed=structure_seed, class_sep=2.0
    )


def test_stream_classes_validation(cancer):
    X, y = cancer
    # unsorted classes are sorted internally — result matches sorted
    a = BaggingClassifier(n_estimators=2, seed=0).fit_stream(
        (X, y), classes=[1, 0], n_epochs=2, chunk_rows=256
    )
    np.testing.assert_array_equal(a.classes_, [0, 1])
    with pytest.raises(ValueError, match="duplicate"):
        BaggingClassifier(n_estimators=2).fit_stream(
            (X, y), classes=[0, 1, 1], chunk_rows=256
        )
    with pytest.raises(ValueError, match="not in classes"):
        BaggingClassifier(n_estimators=2).fit_stream(
            (X, np.where(y == 0, 7, y)), classes=[0, 1], chunk_rows=256
        )


def test_libsvm_and_csv_chunks_match_full_parse(tmp_path):
    from spark_bagging_tpu.utils.datasets import load_csv, parse_libsvm

    rng = np.random.default_rng(0)
    X = rng.standard_normal((17, 4)).astype(np.float32)
    y = rng.integers(0, 2, 17)

    svm = tmp_path / "d.svm"
    with open(svm, "w") as f:
        for i in range(17):
            feats = " ".join(f"{j+1}:{X[i, j]:.6f}" for j in range(4))
            f.write(f"{y[i]} {feats}\n")
    Xf, yf = parse_libsvm(str(svm))
    src = LibsvmChunks(str(svm), n_features=4, chunk_rows=5)
    assert src.n_rows == 17
    parts = [(Xc[:n], yc[:n]) for Xc, yc, n in src.chunks()]
    np.testing.assert_allclose(np.concatenate([p[0] for p in parts]), Xf)
    np.testing.assert_allclose(np.concatenate([p[1] for p in parts]), yf)

    csv = tmp_path / "d.csv"
    with open(csv, "w") as f:
        for i in range(17):
            f.write(",".join(f"{v:.6f}" for v in X[i]) + f",{y[i]}\n")
    Xc_full, yc_full = load_csv(str(csv))
    src = CSVChunks(str(csv), chunk_rows=6)
    assert src.n_rows == 17 and src.n_features == 4
    parts = [(Xc[:n], yc[:n]) for Xc, yc, n in src.chunks()]
    np.testing.assert_allclose(
        np.concatenate([p[0] for p in parts]), Xc_full, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.concatenate([p[1] for p in parts]), yc_full, rtol=1e-5
    )


# ---------------------------------------------------------------------
# Streaming fits
# ---------------------------------------------------------------------


@pytest.mark.slow  # [PR 14 pyramid] ~2.9s accuracy band soak; chunked==unchunked exactness stays tier-1 in test_bagging
def test_stream_classifier_accuracy_close_to_inmemory(cancer):
    X, y = cancer
    clf = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=25), n_estimators=16, seed=0
    ).fit(X, y)
    acc_mem = clf.score(X, y)

    sclf = BaggingClassifier(
        base_learner=LogisticRegression(), n_estimators=16, seed=0
    ).fit_stream(ArrayChunks(X, y, chunk_rows=128), n_epochs=30, lr=0.05)
    acc_stream = sclf.score(X, y)
    assert acc_stream >= acc_mem - 0.03
    # fitted attrs identical in kind to in-memory fit
    assert sclf.n_estimators_ == 16
    assert sclf.fit_report_["n_chunks"] == 5
    assert np.isfinite(sclf.fit_report_["loss_mean"])


@pytest.mark.slow  # [PR 14 pyramid] ~2.1s accounting soak; FLOPs counters are continuously gated by the serving cost gauges
def test_stream_sgd_flops_accounting(cancer):
    """SGD streams report analytic FLOPs: per-step matmul model × steps
    actually executed [VERDICT r2 ask#6]. Exact bookkeeping check."""
    X, y = cancer
    n_epochs, steps_per_chunk, chunk_rows = 3, 2, 128
    sclf = BaggingClassifier(
        base_learner=LogisticRegression(), n_estimators=4, seed=0
    ).fit_stream(
        ArrayChunks(X, y, chunk_rows=chunk_rows), n_epochs=n_epochs,
        steps_per_chunk=steps_per_chunk, lr=0.05,
    )
    rep = sclf.fit_report_
    n_chunks = rep["n_chunks"]
    assert rep["opt_steps"] == n_chunks * n_epochs * steps_per_chunk
    d, C = X.shape[1], 2
    per_step = 6 * chunk_rows * (d + 1) * C
    assert rep["model_flops_per_fit"] == per_step * rep["opt_steps"]
    assert rep["achieved_tflops"] > 0
    # tree streams keep their full-fit model; MLP streams report too
    smlp = BaggingClassifier(
        base_learner=MLPClassifier(hidden=8, max_iter=5),
        n_estimators=2, seed=0,
    ).fit_stream(
        ArrayChunks(X, y, chunk_rows=256), n_epochs=2, lr=0.01
    )
    assert smlp.fit_report_["model_flops_per_fit"] > 0


def test_stream_classifier_discovers_classes(cancer):
    X, y = cancer
    sclf = BaggingClassifier(n_estimators=4, seed=0).fit_stream(
        ArrayChunks(X, y, chunk_rows=256), n_epochs=3, lr=0.05
    )
    np.testing.assert_array_equal(sclf.classes_, np.unique(y))
    proba = sclf.predict_proba(X[:32])
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-4)


def test_stream_accepts_xy_tuple(cancer):
    X, y = cancer
    sclf = BaggingClassifier(n_estimators=4, seed=0).fit_stream(
        (X, y), n_epochs=3, lr=0.05, chunk_rows=200
    )
    assert sclf.predict(X[:8]).shape == (8,)


def test_stream_seed_determinism(cancer):
    X, y = cancer
    a = BaggingClassifier(n_estimators=6, seed=9).fit_stream(
        (X, y), n_epochs=2, chunk_rows=200
    )
    b = BaggingClassifier(n_estimators=6, seed=9).fit_stream(
        (X, y), n_epochs=2, chunk_rows=200
    )
    np.testing.assert_array_equal(
        np.asarray(a.ensemble_["W"]), np.asarray(b.ensemble_["W"])
    )


def test_stream_regressor():
    X, y = make_regression(600, 6, seed=2)
    mu, s = X.mean(0), X.std(0) + 1e-8
    X = ((X - mu) / s).astype(np.float32)
    reg = BaggingRegressor(
        base_learner=LinearRegression(), n_estimators=8, seed=0
    ).fit_stream((X, y), n_epochs=60, lr=0.1, chunk_rows=128)
    assert reg.score(X, y) > 0.7


@pytest.mark.slow  # [PR 14 pyramid] ~1.6s convergence-quality soak; steps_per_chunk knob plumbing stays tier-1
def test_stream_steps_per_chunk_speeds_convergence(cancer):
    X, y = cancer
    few = BaggingClassifier(n_estimators=4, seed=0).fit_stream(
        (X, y), n_epochs=2, lr=0.05, chunk_rows=256
    )
    many = BaggingClassifier(n_estimators=4, seed=0).fit_stream(
        (X, y), n_epochs=2, steps_per_chunk=20, lr=0.05, chunk_rows=256
    )
    assert many.fit_report_["loss_mean"] < few.fit_report_["loss_mean"]
    assert many.score(X, y) > 0.9


@pytest.mark.slow  # [PR 14 pyramid] ~2.3s SGD-learner stream soak; stream engine contracts stay tier-1
def test_stream_mlp(cancer):
    X, y = cancer
    sclf = BaggingClassifier(
        base_learner=MLPClassifier(hidden=8, max_iter=10),
        n_estimators=4, seed=0,
    ).fit_stream((X, y), n_epochs=20, lr=0.02, chunk_rows=256)
    assert sclf.score(X, y) > 0.9


@pytest.mark.slow  # ~12s: single-chunk==in-memory parity; the multi-chunk
# accuracy + determinism tests keep the stream path covered in tier-1
def test_stream_tree_single_chunk_matches_inmemory_exactly(cancer):
    """With one chunk covering all rows the streamed tree fit must be
    bit-identical to an in-memory fit on the regenerated chunk weights
    (same edges — global quantiles — same split math) [VERDICT r1 #9]."""
    import jax
    import jax.numpy as jnp

    from spark_bagging_tpu.ops.bootstrap import bootstrap_weights_one
    from spark_bagging_tpu.streaming import _CHUNK_STREAM

    X, y = cancer
    n = X.shape[0]
    learner = DecisionTreeClassifier(max_depth=3)
    seed, R = 2, 4
    clf = BaggingClassifier(
        base_learner=learner, n_estimators=R, seed=seed
    ).fit_stream((X, y), classes=[0, 1], chunk_rows=n)

    key = jax.random.key(seed)
    chunk_key = jax.random.fold_in(
        jax.random.fold_in(key, _CHUNK_STREAM), 0
    )
    Xd = jnp.asarray(X)
    yd = jnp.asarray(y, jnp.int32)
    prepared = learner.prepare(Xd)

    def fit_one(rid):
        w = bootstrap_weights_one(chunk_key, rid, n)
        p0 = learner.init_params(None, X.shape[1], 2)
        params, _ = learner.fit(p0, Xd, yd, w, None, prepared=prepared)
        return params

    expected = jax.vmap(fit_one)(jnp.arange(R, dtype=jnp.int32))
    for k in expected:
        np.testing.assert_array_equal(
            np.asarray(expected[k]), np.asarray(clf.ensemble_[k])
        )


@pytest.mark.slow  # ~6s [PR 11 budget offset]: multi-chunk accuracy band; the multi-chunk parity + determinism contracts stay tier-1 via faster tree-stream tests
def test_stream_tree_multi_chunk_accuracy(cancer):
    X, y = cancer
    mem = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=4),
        n_estimators=8, seed=0,
    ).fit(X, y)
    stream = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=4),
        n_estimators=8, seed=0,
    ).fit_stream((X, y), classes=[0, 1], chunk_rows=128)
    assert stream.score(X, y) == pytest.approx(mem.score(X, y), abs=0.03)
    assert stream.score(X, y) > 0.93
    r = stream.fit_report_
    assert r["fits_per_sec"] > 0 and r["n_chunks"] == 5


@pytest.mark.slow  # ~6s [PR 11 budget offset]: same-seed repeat determinism; byte-determinism is continuously enforced by the replay digests in tier-1
def test_stream_tree_deterministic(cancer):
    X, y = cancer
    kw = dict(
        base_learner=DecisionTreeClassifier(max_depth=3),
        n_estimators=4, seed=7,
    )
    a = BaggingClassifier(**kw).fit_stream(
        (X, y), classes=[0, 1], chunk_rows=128
    )
    b = BaggingClassifier(**kw).fit_stream(
        (X, y), classes=[0, 1], chunk_rows=128
    )
    for k in a.ensemble_:
        np.testing.assert_array_equal(
            np.asarray(a.ensemble_[k]), np.asarray(b.ensemble_[k])
        )


@pytest.mark.slow  # [PR 14 pyramid] ~5.6s stream-tree fit soak; stream-tree parity contracts stay tier-1
def test_stream_tree_regressor():
    from spark_bagging_tpu.models import DecisionTreeRegressor

    X, y = make_regression(800, 8, seed=3)
    mem = BaggingRegressor(
        base_learner=DecisionTreeRegressor(max_depth=4),
        n_estimators=8, seed=0,
    ).fit(X, y)
    stream = BaggingRegressor(
        base_learner=DecisionTreeRegressor(max_depth=4),
        n_estimators=8, seed=0,
    ).fit_stream((X, y), chunk_rows=200)
    assert stream.score(X, y) == pytest.approx(mem.score(X, y), abs=0.05)


@pytest.mark.slow  # ~12s: subspace draw coverage rides the faster tree tests
def test_stream_tree_with_subspaces(cancer):
    X, y = cancer
    clf = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3),
        n_estimators=8, max_features=0.5, seed=1,
    ).fit_stream((X, y), classes=[0, 1], chunk_rows=128)
    assert clf.subspaces_.shape == (8, 15)
    assert clf.score(X, y) > 0.9


def test_stream_tree_rejects_sgd_knobs(cancer):
    X, y = cancer
    with pytest.raises(ValueError, match="SGD-stream knobs"):
        BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=3),
            n_estimators=2,
        ).fit_stream((X, y), classes=[0, 1], chunk_rows=128, n_epochs=3)


@pytest.mark.slow  # [PR 14 pyramid] ~2.6s mesh twin; unsharded stream OOB stays tier-1
def test_stream_oob_on_mesh_matches_unsharded(cancer):
    """SGD streams never fold the shard index into draws, so streamed
    OOB under a mesh replays the exact fit membership."""
    X, y = cancer
    kw = dict(n_estimators=8, oob_score=True, seed=0)
    m = BaggingClassifier(mesh=make_mesh(data=2), **kw).fit_stream(
        (X, y), chunk_rows=128, n_epochs=5, lr=0.05
    )
    u = BaggingClassifier(**kw).fit_stream(
        (X, y), chunk_rows=128, n_epochs=5, lr=0.05
    )
    assert m.oob_score_ == pytest.approx(u.oob_score_, abs=0.02)


@pytest.mark.slow  # ~9s [PR 11 budget offset]: data-mesh rejection drill fits a full stream bag to reach one ValueError; the replica-mesh OOB parity stays tier-1
def test_stream_oob_tree_data_mesh_rejected(cancer):
    """Data-sharded tree streams fold the shard index into draws — OOB
    regeneration cannot replay them; replica-only meshes are fine."""
    import jax

    X, y = cancer
    with pytest.raises(ValueError, match="data-sharded tree"):
        BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=2, n_bins=16),
            n_estimators=8, oob_score=True, mesh=make_mesh(data=2),
        ).fit_stream((X, y), chunk_rows=128, classes=[0, 1])
    ok = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=2, n_bins=16),
        n_estimators=8, oob_score=True, seed=0,
        mesh=make_mesh(data=1, replica=4, devices=jax.devices()[:4]),
    ).fit_stream((X, y), chunk_rows=128, classes=[0, 1])
    ref = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=2, n_bins=16),
        n_estimators=8, oob_score=True, seed=0,
    ).fit_stream((X, y), chunk_rows=128, classes=[0, 1])
    assert ok.oob_score_ == pytest.approx(ref.oob_score_, abs=1e-9)


@pytest.mark.slow  # [PR 17 budget offset] ~1.5s subspace stream soak; subspace semantics stay tier-1 via test_property_fuzz subspace params + bagging subspace tests
def test_stream_subspaces(cancer):
    X, y = cancer
    sclf = BaggingClassifier(
        n_estimators=8, max_features=0.5, seed=0
    ).fit_stream((X, y), n_epochs=10, lr=0.05, chunk_rows=256)
    assert sclf.subspaces_.shape == (8, 15)
    assert sclf.score(X, y) > 0.85


@pytest.mark.slow  # [PR 14 pyramid] ~1.7s mesh twin; single-device stream parity stays tier-1
def test_stream_sharded_mesh_matches_unsharded(cancer):
    X, y = cancer
    # chunk_rows divisible by data axis; n_estimators by replica axis
    mesh = make_mesh(data=2)
    a = BaggingClassifier(n_estimators=8, seed=4, mesh=mesh).fit_stream(
        (X, y), n_epochs=4, lr=0.05, chunk_rows=128
    )
    b = BaggingClassifier(n_estimators=8, seed=4).fit_stream(
        (X, y), n_epochs=4, lr=0.05, chunk_rows=128
    )
    np.testing.assert_allclose(
        np.asarray(a.ensemble_["W"]), np.asarray(b.ensemble_["W"]),
        rtol=2e-4, atol=2e-5,
    )
    assert a.score(X, y) == pytest.approx(b.score(X, y), abs=0.01)


def test_stream_then_save_load_roundtrip(cancer, tmp_path):
    X, y = cancer
    sclf = BaggingClassifier(n_estimators=4, seed=0).fit_stream(
        (X, y), n_epochs=3, chunk_rows=256
    )
    path = str(tmp_path / "m")
    sclf.save(path)
    loaded = BaggingClassifier.load(path)
    np.testing.assert_allclose(
        loaded.predict_proba(X[:64]), sclf.predict_proba(X[:64]), rtol=1e-5
    )


# ---------------------------------------------------------------------
# Mid-training checkpoint / resume [SURVEY §5 checkpoint, VERDICT r1 #7]
# ---------------------------------------------------------------------


from spark_bagging_tpu.utils.io import ChunkSource as _ChunkSource


class _KillAfter(_ChunkSource):
    """ChunkSource wrapper that raises after N chunks — a simulated
    process kill mid-stream."""

    def __init__(self, inner, n_before_kill):
        self._inner = inner
        self._n = n_before_kill
        self._seen = 0  # persists across epochs (chunks() re-calls)
        self.n_features = inner.n_features
        self.n_rows = inner.n_rows
        self.chunk_rows = inner.chunk_rows

    @property
    def n_chunks(self):
        return self._inner.n_chunks

    def chunks(self):
        for chunk in self._inner.chunks():
            if self._seen == self._n:
                raise KeyboardInterrupt("simulated kill")
            self._seen += 1
            yield chunk


def _stream_kw(**extra):
    return dict(classes=[0, 1], n_epochs=3, steps_per_chunk=2, lr=0.05,
                **extra)


@pytest.mark.slow  # [PR 17 budget offset] ~2.9s kill/resume soak; resume determinism stays tier-1 via test_stream_seed_determinism + test_tree_stream_resume_rejects_config_change
def test_stream_kill_and_resume_reproduces_uninterrupted(cancer, tmp_path):
    X, y = cancer
    ckpt = str(tmp_path / "snap")
    make = lambda: BaggingClassifier(
        base_learner=LogisticRegression(), n_estimators=8, seed=4
    )

    ref = make().fit_stream(ArrayChunks(X, y, 128), **_stream_kw())

    # run with snapshots every 2 steps, killed mid-epoch
    with pytest.raises(KeyboardInterrupt):
        make().fit_stream(
            _KillAfter(ArrayChunks(X, y, 128), 7), **_stream_kw(
                checkpoint_dir=ckpt, checkpoint_every=2,
            )
        )
    # resume from the snapshot with the intact source
    res = make().fit_stream(ArrayChunks(X, y, 128), **_stream_kw(
        resume_from=ckpt,
    ))
    np.testing.assert_allclose(
        ref.predict_proba(X), res.predict_proba(X), rtol=1e-5, atol=1e-6
    )
    import jax

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        ref.ensemble_, res.ensemble_,
    )


def test_stream_resume_rejects_config_mismatch(cancer, tmp_path):
    X, y = cancer
    ckpt = str(tmp_path / "snap")
    BaggingClassifier(
        base_learner=LogisticRegression(), n_estimators=8, seed=4
    ).fit_stream(ArrayChunks(X, y, 128), **_stream_kw(
        checkpoint_dir=ckpt, checkpoint_every=2,
    ))
    with pytest.raises(ValueError, match="different fit configuration"):
        BaggingClassifier(
            base_learner=LogisticRegression(), n_estimators=8, seed=5
        ).fit_stream(ArrayChunks(X, y, 128), **_stream_kw(
            resume_from=ckpt,
        ))


def test_stream_resume_rejects_different_length_source(cancer, tmp_path):
    """The fingerprint includes the stream length: resuming against a
    shorter source would silently train zero further steps (round-4
    audit finding)."""
    X, y = cancer
    ckpt = str(tmp_path / "snap")
    make = lambda: BaggingClassifier(
        base_learner=LogisticRegression(), n_estimators=8, seed=4
    )
    make().fit_stream(ArrayChunks(X, y, 128), **_stream_kw(
        checkpoint_dir=ckpt, checkpoint_every=2,
    ))
    with pytest.raises(ValueError, match="different fit configuration"):
        make().fit_stream(
            ArrayChunks(X[:300], y[:300], 128), **_stream_kw(
                resume_from=ckpt,
            )
        )


def test_stream_rejects_miscounting_source(cancer):
    """A source that yields a different chunk count than its declared
    n_chunks corrupts the resume cursor's epoch rollover — the fit
    fails loudly instead (round-4 audit finding)."""
    X, y = cancer

    class Undercounts(ArrayChunks):
        @property
        def n_chunks(self):
            return super().n_chunks - 1

    with pytest.raises(ValueError, match="miscounted source"):
        BaggingClassifier(
            base_learner=LogisticRegression(), n_estimators=4, seed=0
        ).fit_stream(Undercounts(X, y, 128), **_stream_kw())


def test_snapshot_old_slot_survives_until_next_install(tmp_path):
    """After a crash mid-swap (path missing, only path.old left), the
    next snapshot must keep .old alive until ITS install completes —
    and clean it plus dead-pid tmp debris afterwards."""
    import os
    import shutil
    import subprocess
    import sys as _sys

    from spark_bagging_tpu.streaming import (
        _load_stream_checkpoint,
        save_snapshot,
    )

    path = str(tmp_path / "snap")
    save_snapshot(path, {"v": np.arange(3)}, {"n": 1})
    # simulate the crash window: only .old remains
    shutil.move(path, path + ".old")
    # dead-pid tmp debris from the killed writer
    dead = subprocess.Popen([_sys.executable, "-c", ""])
    dead.wait()
    os.makedirs(f"{path}.tmp.{dead.pid}")
    # load falls back to .old
    meta, tree = _load_stream_checkpoint(path)
    assert meta["n"] == 1
    # next snapshot installs, then cleans both
    save_snapshot(path, {"v": np.arange(4)}, {"n": 2})
    assert not os.path.isdir(path + ".old")
    assert not os.path.isdir(f"{path}.tmp.{dead.pid}")
    meta, tree = _load_stream_checkpoint(path)
    assert meta["n"] == 2


def test_synthetic_chunks_nearby_seeds_do_not_collide():
    """Additive chunk seeds made train chunk c+k row-identical to an
    eval source's chunk c at base-seed offset k; seeds are now
    SeedSequence-mixed (round-4 audit finding)."""
    train = SyntheticChunks(make_classification_2f, 2000, 500, seed=0)
    evals = SyntheticChunks(make_classification_2f, 2000, 500, seed=1)
    tr = [X for X, _, _ in train.chunks()]
    ev = [X for X, _, _ in evals.chunks()]
    for a in tr:
        for b in ev:
            assert not np.array_equal(a, b)


def make_classification_2f(n, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, 2)).astype(np.float32)
    return X, (X[:, 0] > 0).astype(np.int32)


def test_chunks_from_seeks_equal_suffix():
    """chunks_from(k) must yield exactly list(chunks())[k:] on every
    source shape — the checkpoint-resume seek fast path."""
    from spark_bagging_tpu.utils.io import DropColumnChunks
    from spark_bagging_tpu.utils.prefetch import PrefetchChunks

    rng = np.random.default_rng(0)
    X = rng.standard_normal((1030, 5)).astype(np.float32)  # ragged tail
    y = rng.integers(0, 2, 1030).astype(np.int32)
    sources = [
        ArrayChunks(X, y, 128),
        SyntheticChunks(make_classification_2f, 1030, 128, seed=3),
        DropColumnChunks(ArrayChunks(X, y, 128), 2),
        PrefetchChunks(ArrayChunks(X, y, 128), depth=2),
    ]
    for src in sources:
        full = list(src.chunks())
        for k in (0, 3, len(full) - 1, len(full)):
            suffix = list(src.chunks_from(k))
            assert len(suffix) == len(full) - k, type(src).__name__
            for (Xa, ya, na), (Xb, yb, nb) in zip(suffix, full[k:]):
                np.testing.assert_array_equal(Xa, Xb)
                np.testing.assert_array_equal(ya, yb)
                assert na == nb


@pytest.mark.slow  # [PR 14 pyramid] ~2.2s mesh twin of the resume contract kept tier-1 single-device
def test_stream_checkpoint_resume_on_mesh(cancer, tmp_path):
    """Snapshots gather sharded state to host; resume re-shards onto the
    mesh — the sharded resumed fit must equal the sharded straight-through
    fit."""
    X, y = cancer
    ckpt = str(tmp_path / "snap")
    mesh = make_mesh(data=2)
    make = lambda: BaggingClassifier(
        base_learner=LogisticRegression(), n_estimators=8, seed=4,
        mesh=mesh,
    )
    ref = make().fit_stream(ArrayChunks(X, y, 128), **_stream_kw())
    with pytest.raises(KeyboardInterrupt):
        make().fit_stream(
            _KillAfter(ArrayChunks(X, y, 128), 5), **_stream_kw(
                checkpoint_dir=ckpt, checkpoint_every=1,
            )
        )
    res = make().fit_stream(ArrayChunks(X, y, 128), **_stream_kw(
        resume_from=ckpt,
    ))
    np.testing.assert_allclose(
        ref.predict_proba(X), res.predict_proba(X), rtol=1e-4, atol=1e-5
    )


# ---------------------------------------------------------------------
# Streamed OOB (one extra pass; chunk-keyed membership regeneration)
# ---------------------------------------------------------------------


@pytest.mark.slow  # [PR 17 budget offset] ~2.2s streaming-OOB quality band; OOB-on-stream stays tier-1 via test_stream_regressor + test_online OOB anchors
def test_stream_oob_classifier(cancer):
    X, y = cancer
    clf = BaggingClassifier(
        base_learner=LogisticRegression(), n_estimators=32, seed=0,
        oob_score=True,
    ).fit_stream(ArrayChunks(X, y, chunk_rows=128), n_epochs=20, lr=0.05)
    assert clf.oob_score_ > 0.9
    df = clf.oob_decision_function_
    assert df.shape == (len(y), 2)
    voted = ~np.isnan(df[:, 0])
    # λ=1 Poisson per chunk: OOB fraction per (row, replica) ≈ e⁻¹, so
    # nearly every row gets some OOB vote across 32 replicas
    assert voted.mean() > 0.99
    np.testing.assert_allclose(df[voted].sum(axis=1), 1.0, rtol=1e-5)


@pytest.mark.slow  # [PR 14 pyramid] ~1.3s OOB regressor twin; the classifier representative stays tier-1
def test_stream_oob_regressor():
    X, y = make_regression(600, 6, seed=2)
    mu, s = X.mean(0), X.std(0) + 1e-8
    X = ((X - mu) / s).astype(np.float32)
    reg = BaggingRegressor(
        base_learner=LinearRegression(), n_estimators=32, seed=0,
        oob_score=True,
    ).fit_stream((X, y), n_epochs=60, lr=0.1, chunk_rows=128)
    assert reg.oob_score_ > 0.6
    assert reg.oob_prediction_.shape == (len(y),)


@pytest.mark.slow  # [PR 14 pyramid] ~3.9s stream-OOB tree soak; stream OOB classifier representative stays tier-1
def test_stream_oob_tree(cancer):
    from spark_bagging_tpu.models import DecisionTreeClassifier

    X, y = cancer
    clf = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3, n_bins=16),
        n_estimators=16, seed=0, oob_score=True,
    ).fit_stream(ArrayChunks(X, y, chunk_rows=128))
    assert clf.oob_score_ > 0.85


def test_stream_oob_without_oob_rows_raises(cancer):
    X, y = cancer
    with pytest.raises(ValueError, match="out-of-bag"):
        BaggingClassifier(
            n_estimators=4, oob_score=True, bootstrap=False,
            max_samples=1.0,
        ).fit_stream(ArrayChunks(X, y, chunk_rows=128))


# ---------------------------------------------------------------------
# Tree-stream checkpoint/resume (pass-boundary snapshots)
# ---------------------------------------------------------------------


from spark_bagging_tpu.utils.io import ChunkSource as _ChunkSource


class _KillAfterScans(_ChunkSource):
    """ChunkSource wrapper that raises after N full scans — simulates a
    crash mid-pass for the multi-pass tree engine."""

    def __init__(self, inner, n_scans):
        self._inner = inner
        self._n = n_scans
        self._scans = 0
        self.n_features = inner.n_features
        self.n_rows = inner.n_rows
        self.chunk_rows = inner.chunk_rows

    @property
    def n_chunks(self):
        return self._inner.n_chunks

    def chunks(self):
        self._scans += 1
        if self._scans > self._n:
            raise RuntimeError("simulated crash")
        yield from self._inner.chunks()


@pytest.mark.slow  # ~7s [PR 11 budget offset]: full interrupt+resume stream fit; checkpoint round-trip correctness stays tier-1 in test_checkpoint
def test_tree_stream_checkpoint_resume(cancer, tmp_path):
    from spark_bagging_tpu.models import DecisionTreeClassifier

    X, y = cancer
    ckpt = str(tmp_path / "tree_ckpt")
    # classes passed explicitly: the discovery pre-scan would otherwise
    # consume one _KillAfterScans scan and shift the crash point
    mk = lambda: BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3, n_bins=16),
        n_estimators=8, seed=0,
    )
    # uninterrupted reference
    ref = mk().fit_stream(ArrayChunks(X, y, chunk_rows=128), classes=[0, 1])

    # crash during the level-2 pass: edge + level-0 + level-1 scans
    # completed, so resume must restore TWO levels of splits
    killer = _KillAfterScans(ArrayChunks(X, y, chunk_rows=128), 3)
    with pytest.raises(RuntimeError, match="simulated crash"):
        mk().fit_stream(killer, checkpoint_dir=ckpt, classes=[0, 1])

    # resume replays only the in-flight pass onward; result identical
    import json

    with open(f"{ckpt}/meta.json") as f:
        assert json.load(f)["next_pass"] == 3  # two levels snapshotted
    resumed = mk().fit_stream(
        ArrayChunks(X, y, chunk_rows=128), resume_from=ckpt, classes=[0, 1]
    )
    np.testing.assert_allclose(
        resumed.predict_proba(X), ref.predict_proba(X), rtol=1e-6, atol=1e-7
    )


def test_tree_stream_resume_rejects_config_change(cancer, tmp_path):
    from spark_bagging_tpu.models import DecisionTreeClassifier

    X, y = cancer
    ckpt = str(tmp_path / "tree_ckpt2")
    BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=2, n_bins=16),
        n_estimators=4, seed=0,
    ).fit_stream(ArrayChunks(X, y, chunk_rows=256), checkpoint_dir=ckpt)
    with pytest.raises(ValueError, match="different fit configuration"):
        BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=2, n_bins=16),
            n_estimators=4, seed=1,  # different seed
        ).fit_stream(ArrayChunks(X, y, chunk_rows=256), resume_from=ckpt)


# ---------------------------------------------------------------------
# Data-parallel streamed trees (shard_map level passes)
# ---------------------------------------------------------------------


@pytest.mark.slow  # ~8s [PR 11 budget offset]: replica-mesh streamed-tree parity re-fits two full stream bags; serving-side mesh parity stays tier-1 in test_serving_sharded
def test_tree_stream_replica_mesh_matches_unsharded(cancer):
    """Replica-only mesh: no data fold_in, so the streamed tree fit is
    numerically identical to the unsharded stream fit."""
    X, y = cancer
    mk = lambda mesh: BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3, n_bins=16),
        n_estimators=8, seed=0, mesh=mesh,
    ).fit_stream(ArrayChunks(X, y, chunk_rows=128), classes=[0, 1])
    ref = mk(None)
    import jax

    sharded = mk(make_mesh(data=1, replica=4, devices=jax.devices()[:4]))
    np.testing.assert_allclose(
        sharded.predict_proba(X), ref.predict_proba(X), rtol=1e-5,
        atol=1e-6,
    )


@pytest.mark.slow  # ~9s: mesh stream covered by the replica-mesh parity test
def test_tree_stream_data_mesh_accuracy(cancer):
    """Data-sharded streamed trees: per-shard draws differ (documented),
    accuracy must match statistically; chunk_rows must divide."""
    X, y = cancer
    mesh = make_mesh(data=4, replica=2)
    clf = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3, n_bins=16),
        n_estimators=8, seed=0, mesh=mesh,
    ).fit_stream(ArrayChunks(X, y, chunk_rows=128), classes=[0, 1])
    ref = BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=3, n_bins=16),
        n_estimators=8, seed=0,
    ).fit_stream(ArrayChunks(X, y, chunk_rows=128), classes=[0, 1])
    assert abs(clf.score(X, y) - ref.score(X, y)) < 0.04
    with pytest.raises(ValueError, match="divisible"):
        BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=2),
            n_estimators=8, mesh=make_mesh(data=8),
        ).fit_stream(ArrayChunks(X, y, chunk_rows=100), classes=[0, 1])


@pytest.mark.slow  # ~6s [PR 12 budget offset]: resume-under-changed-mesh rejection; the resume config-change rejection contract stays tier-1 via test_tree_stream_resume_rejects_config_change
def test_tree_stream_resume_rejects_mesh_change(cancer, tmp_path):
    """The weight stream folds the data-shard index — resuming under a
    different data-axis size must be refused."""
    X, y = cancer
    ckpt = str(tmp_path / "tree_ckpt3")
    BaggingClassifier(
        base_learner=DecisionTreeClassifier(max_depth=2, n_bins=16),
        n_estimators=8, seed=0, mesh=make_mesh(data=4, replica=2),
    ).fit_stream(
        ArrayChunks(X, y, chunk_rows=128), classes=[0, 1],
        checkpoint_dir=ckpt,
    )
    with pytest.raises(ValueError, match="different fit configuration"):
        BaggingClassifier(
            base_learner=DecisionTreeClassifier(max_depth=2, n_bins=16),
            n_estimators=8, seed=0,  # no mesh: data_size 1 != 4
        ).fit_stream(
            ArrayChunks(X, y, chunk_rows=128), classes=[0, 1],
            resume_from=ckpt,
        )


# ---------------------------------------------------------------------
# Out-of-core prediction/scoring (the transform analog at scale)
# ---------------------------------------------------------------------


def test_predict_stream_matches_in_memory(cancer):
    X, y = cancer
    clf = BaggingClassifier(n_estimators=8, seed=0).fit(X, y)
    src = ArrayChunks(X, y, chunk_rows=100)
    np.testing.assert_allclose(
        clf.predict_proba_stream(src), clf.predict_proba(X),
        rtol=1e-6, atol=1e-7,
    )
    np.testing.assert_array_equal(clf.predict_stream(src), clf.predict(X))
    assert clf.score_stream(src) == pytest.approx(clf.score(X, y))
    with pytest.raises(ValueError, match="features"):
        clf.predict_stream(ArrayChunks(X[:, :5], y, chunk_rows=100))


def test_regressor_predict_stream_matches_in_memory():
    X, y = make_regression(500, 6, seed=0)
    mu, s = X.mean(0), X.std(0) + 1e-8
    X = ((X - mu) / s).astype(np.float32)
    reg = BaggingRegressor(n_estimators=8, seed=0).fit(X, y)
    src = ArrayChunks(X, y.astype(np.float32), chunk_rows=128)
    np.testing.assert_allclose(
        reg.predict_stream(src), reg.predict(X), rtol=1e-5, atol=1e-5
    )
    assert reg.score_stream(src) == pytest.approx(
        reg.score(X, y), abs=1e-6
    )


def test_regressor_score_stream_large_mean_targets():
    """Shifted one-pass moments must agree with the centered r2_score
    even when the stream's targets carry a huge constant offset (the
    raw sum-of-squares formula cancels catastrophically there)."""
    from spark_bagging_tpu.utils.metrics import r2_score

    X, y = make_regression(2000, 5, seed=3)
    mu, s = X.mean(0), X.std(0) + 1e-8
    X = ((X - mu) / s).astype(np.float32)
    y_norm = (y / (y.std() + 1e-8)).astype(np.float32)
    reg = BaggingRegressor(n_estimators=8, seed=0).fit(X, y_norm)
    pred = reg.predict(X)
    for offset in (0.0, 3e7):
        y_stream = y_norm.astype(np.float64) + offset
        src = ArrayChunks(X, y_stream, chunk_rows=256)
        assert reg.score_stream(src) == pytest.approx(
            r2_score(y_stream, pred), rel=1e-9, abs=1e-9
        )
    with pytest.raises(ValueError, match="no chunks"):
        reg.score_stream(ArrayChunks(
            np.empty((0, X.shape[1]), np.float32),
            np.empty(0, np.float32), chunk_rows=16,
        ))


def test_tree_stream_engine_rejects_gbt():
    """The public engine must enforce tree_streamable itself — a GBT
    would otherwise return single-tree params its own predict rejects
    far from the cause."""
    import jax

    from spark_bagging_tpu import ArrayChunks
    from spark_bagging_tpu.models.gbt import GBTRegressor
    from spark_bagging_tpu.tree_stream import fit_tree_ensemble_stream

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 3)).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    with pytest.raises(ValueError, match="not tree-streamable"):
        fit_tree_ensemble_stream(
            GBTRegressor(n_rounds=2, max_depth=2), ArrayChunks(X, y, 32),
            jax.random.key(0), n_replicas=2, n_outputs=1,
        )
