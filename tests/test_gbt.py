"""GBT tests: boosting beats single trees, sklearn-quality parity,
weighted exactness, bagging/mesh integration [SURVEY §4]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    GBTClassifier,
    GBTRegressor,
    make_mesh,
)

KEY = jax.random.key(0)


def _friedman(n=800, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(size=(n, 10)).astype(np.float32)
    y = (10 * np.sin(np.pi * X[:, 0] * X[:, 1]) + 20 * (X[:, 2] - 0.5) ** 2
         + 10 * X[:, 3] + 5 * X[:, 4]
         + rng.normal(size=n)).astype(np.float32)
    return X, y


class TestGBTRegressor:
    @pytest.mark.slow  # ~5.5s: quality-of-fit soak (boosting beats a
    # single tree + monotone loss); GBT correctness/parity coverage
    # stays tier-1 [ISSUE 13 tier-1 budget offset]
    def test_beats_single_tree_and_loss_decreases(self):
        from spark_bagging_tpu.models import DecisionTreeRegressor

        X, y = _friedman()
        gbt = GBTRegressor(n_rounds=50, max_depth=3, lr=0.2)
        params, aux = gbt.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        pred = np.asarray(gbt.predict_scores(params, jnp.asarray(X)))
        r2 = 1 - np.var(pred - y) / np.var(y)
        tree = DecisionTreeRegressor(max_depth=3)
        tp, _ = tree.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        tr2 = 1 - np.var(
            np.asarray(tree.predict_scores(tp, jnp.asarray(X))) - y
        ) / np.var(y)
        assert r2 > 0.9 and r2 > tr2 + 0.1
        curve = np.asarray(aux["loss_curve"])
        assert np.all(np.diff(curve) <= 1e-5)

    @pytest.mark.slow  # [PR 14 pyramid] ~2.4s sklearn-quality soak; boosting-step exactness stays tier-1
    def test_matches_sklearn_quality(self):
        from sklearn.ensemble import GradientBoostingRegressor

        X, y = _friedman()
        gbt = GBTRegressor(n_rounds=100, max_depth=3, lr=0.1)
        params, _ = gbt.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        pred = np.asarray(gbt.predict_scores(params, jnp.asarray(X)))
        r2 = 1 - np.var(pred - y) / np.var(y)
        sk = GradientBoostingRegressor(
            n_estimators=100, max_depth=3, learning_rate=0.1
        ).fit(X, y)
        sk_r2 = sk.score(X, y)
        assert r2 > sk_r2 - 0.05  # binned splits vs exact: near parity

    @pytest.mark.slow  # [PR 20 budget offset] ~3.5s Poisson-weight dual-fit soak; the weight-column semantics stay tier-1 via test_bagging's test_weighted_equals_duplicated_rows
    def test_weighted_equals_duplicated(self):
        X, y = _friedman(n=300)
        rng = np.random.default_rng(1)
        k = rng.poisson(1.0, len(y))
        k[0] = max(k[0], 1)
        gbt = GBTRegressor(n_rounds=10, max_depth=3, n_bins=16)
        pw, _ = gbt.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y),
            jnp.asarray(k, jnp.float32), 1,
        )
        # duplicated rows shift the quantile edges; compare via
        # predictions on a tree grown from the same integer weights,
        # which must agree closely despite f32 resummation
        pd_, _ = gbt.fit_from_init(
            KEY, jnp.asarray(np.repeat(X, k, axis=0)),
            jnp.asarray(np.repeat(y, k)),
            jnp.ones(int(k.sum())), 1,
        )
        a = np.asarray(gbt.predict_scores(pw, jnp.asarray(X)))
        b = np.asarray(gbt.predict_scores(pd_, jnp.asarray(X)))
        # duplicating rows shifts the (unweighted) quantile bin edges,
        # and boosting compounds split differences across rounds — the
        # same accepted semantic as the tree tests; the two models must
        # still agree closely
        assert np.corrcoef(a, b)[0, 1] > 0.95

    def test_vmap_over_replicas(self):
        X, y = _friedman(n=200)
        gbt = GBTRegressor(n_rounds=5, max_depth=2, n_bins=8)
        keys = jax.random.split(KEY, 3)
        ps = jax.vmap(
            lambda kk: gbt.fit_from_init(
                kk, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
            )[0]
        )(keys)
        assert ps["leaf"].shape == (3, 5, 4)
        assert np.isfinite(np.asarray(ps["leaf"])).all()


class TestGBTClassifier:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.5s layout sweep soak; param-layout contracts stay tier-1
    def test_accuracy_and_param_layouts(self):
        X, y = load_breast_cancer(return_X_y=True)
        X = StandardScaler().fit_transform(X).astype(np.float32)
        gbt = GBTClassifier(n_rounds=30, max_depth=3, lr=0.2)
        params, aux = gbt.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(len(y)), 2,
        )
        scores = np.asarray(gbt.predict_scores(params, jnp.asarray(X)))
        assert scores.shape == (len(y), 2)
        assert (scores.argmax(1) == y).mean() > 0.97
        curve = np.asarray(aux["loss_curve"])
        assert np.all(np.diff(curve) <= 1e-5)
        # 3-class init allocates the multiclass (R, C, L) layout
        p3 = gbt.init_params(KEY, 5, 3)
        assert p3["leaf"].shape == (30, 3, 8)

    @pytest.mark.slow  # [PR 14 pyramid] ~1.9s bagged integration soak; GBT fit invariants stay tier-1
    def test_bagged_gbt_and_importances(self):
        X, y = load_breast_cancer(return_X_y=True)
        X = StandardScaler().fit_transform(X).astype(np.float32)
        clf = BaggingClassifier(
            base_learner=GBTClassifier(n_rounds=10, max_depth=2),
            n_estimators=8, seed=0, oob_score=True,
        ).fit(X, y)
        assert clf.score(X, y) > 0.95
        assert clf.oob_score_ > 0.9
        imp = clf.feature_importances_
        assert imp.shape == (X.shape[1],)
        assert imp.sum() == pytest.approx(1.0, abs=1e-5)

    @pytest.mark.slow  # [PR 14 pyramid] ~2s mesh twin; replica-mesh parity stays tier-1 generic
    def test_mesh_fit_close_to_single_device(self):
        """Sharded prepare averages per-shard quantile edges (the
        documented tree semantic), so boosted splits can differ from
        the single-device fit; both must train to the same quality."""
        X, y = load_breast_cancer(return_X_y=True)
        X = StandardScaler().fit_transform(X).astype(np.float32)
        mesh = make_mesh(data=2)
        a = BaggingClassifier(
            base_learner=GBTClassifier(n_rounds=5, max_depth=2),
            n_estimators=4, bootstrap=False, seed=0, mesh=mesh,
        ).fit(X, y)
        b = BaggingClassifier(
            base_learner=GBTClassifier(n_rounds=5, max_depth=2),
            n_estimators=4, bootstrap=False, seed=0,
        ).fit(X, y)
        acc_a, acc_b = a.score(X, y), b.score(X, y)
        assert acc_a > 0.93 and acc_b > 0.93
        assert abs(acc_a - acc_b) < 0.03
        agree = (a.predict(X) == b.predict(X)).mean()
        assert agree > 0.95

    @pytest.mark.slow  # [PR 14 pyramid] ~1.4s per-model checkpoint twin; generic round-trip stays tier-1 in test_checkpoint
    def test_checkpoint_roundtrip(self, tmp_path):
        from spark_bagging_tpu import load_model, save_model

        X, y = load_breast_cancer(return_X_y=True)
        X = StandardScaler().fit_transform(X).astype(np.float32)
        clf = BaggingClassifier(
            base_learner=GBTClassifier(n_rounds=5, max_depth=2),
            n_estimators=4, seed=0,
        ).fit(X, y)
        save_model(clf, str(tmp_path / "gbt"))
        clf2 = load_model(str(tmp_path / "gbt"))
        np.testing.assert_allclose(
            clf.predict_proba(X[:64]), clf2.predict_proba(X[:64]),
            rtol=1e-6,
        )




    def test_fit_stream_rejected_cleanly(self):
        """GBT must NOT route into the single-tree stream engine (its
        params are R stacked trees); the SGD engine's streamable=False
        TypeError is the correct refusal."""
        from spark_bagging_tpu import ArrayChunks

        X, y = _friedman(n=128)
        src = ArrayChunks(X, y, chunk_rows=64)
        reg = BaggingRegressor(
            base_learner=GBTRegressor(n_rounds=3, max_depth=2),
            n_estimators=2, seed=0,
        )
        with pytest.raises(TypeError, match="stream"):
            reg.fit_stream(src)


def test_n_rounds_validation():
    """Shared _GBTBase validation, outside either task's test class so
    class-filtered runs still cover it."""
    with pytest.raises(ValueError, match="n_rounds"):
        GBTRegressor(n_rounds=0)
    with pytest.raises(ValueError, match="n_rounds"):
        GBTClassifier(n_rounds=-1)


@pytest.mark.slow  # [PR 14 pyramid] ~2.4s stochastic-round soak; subsample determinism stays tier-1
def test_subsample_stochastic_rounds():
    """subsample<1 draws an independent Bernoulli row subset per round:
    the fit must differ from the deterministic one, stay finite, and
    still train well; subsample outside (0,1] is rejected."""
    X, y = _friedman(n=400)
    full = GBTRegressor(n_rounds=20, max_depth=3, lr=0.2)
    sub = GBTRegressor(n_rounds=20, max_depth=3, lr=0.2, subsample=0.6)
    pf, _ = full.fit_from_init(
        KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
    )
    ps, _ = sub.fit_from_init(
        KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
    )
    a = np.asarray(full.predict_scores(pf, jnp.asarray(X)))
    b = np.asarray(sub.predict_scores(ps, jnp.asarray(X)))
    assert not np.allclose(a, b)
    r2 = 1 - np.var(b - y) / np.var(y)
    assert r2 > 0.85
    with pytest.raises(ValueError, match="subsample"):
        GBTRegressor(subsample=0.0)
    with pytest.raises(ValueError, match="subsample"):
        GBTRegressor(subsample=1.5)


def test_subsample_keyless_fit_rejected():
    X, y = _friedman(n=64)
    gbt = GBTRegressor(n_rounds=2, max_depth=2, subsample=0.5)
    p0 = gbt.init_params(KEY, X.shape[1], 1)
    with pytest.raises(ValueError, match="key"):
        gbt.fit(p0, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)),
                None)


@pytest.mark.slow  # [PR 14 pyramid] ~1.3s sharded subsample soak
def test_subsample_sharded_decorrelated():
    """Each data shard must draw its own keep mask (sharded fit would
    otherwise bias the round subsets by local row position)."""
    from spark_bagging_tpu import BaggingRegressor, make_mesh

    X, y = _friedman(n=256)
    mesh = make_mesh(data=8)
    reg = BaggingRegressor(
        base_learner=GBTRegressor(n_rounds=20, max_depth=2, subsample=0.5),
        n_estimators=1, bootstrap=False, seed=0, mesh=mesh,
    ).fit(X, y)
    pred = reg.predict(X)
    assert np.isfinite(pred).all()
    r2 = 1 - np.var(pred - y) / np.var(y)
    assert r2 > 0.5


class TestGBTMulticlass:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.7s accuracy quality soak
    def test_iris_accuracy_and_loss(self):
        from sklearn.datasets import load_iris

        X, y = load_iris(return_X_y=True)
        X = StandardScaler().fit_transform(X).astype(np.float32)
        gbt = GBTClassifier(n_rounds=25, max_depth=3, lr=0.2)
        params, aux = gbt.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(len(y)), 3,
        )
        scores = np.asarray(gbt.predict_scores(params, jnp.asarray(X)))
        assert scores.shape == (len(y), 3)
        assert (scores.argmax(1) == y).mean() > 0.95
        curve = np.asarray(aux["loss_curve"])
        assert np.all(np.diff(curve) <= 1e-5)

    @pytest.mark.slow  # [PR 14 pyramid] ~2s multiclass integration soak
    def test_bagged_multiclass_with_importances(self):
        from sklearn.datasets import load_iris

        X, y = load_iris(return_X_y=True)
        X = X.astype(np.float32)
        clf = BaggingClassifier(
            base_learner=GBTClassifier(n_rounds=10, max_depth=2),
            n_estimators=8, seed=0, oob_score=True,
        ).fit(X, y)
        assert clf.score(X, y) > 0.9
        assert np.isfinite(clf.oob_score_)
        imp = clf.feature_importances_
        assert imp.shape == (4,)
        assert imp.sum() == pytest.approx(1.0, abs=1e-5)
        # petal features dominate iris
        assert imp[2] + imp[3] > 0.5

    @pytest.mark.slow  # [PR 14 pyramid] ~1.9s multiclass checkpoint soak; generic round-trip stays tier-1 in test_checkpoint
    def test_multiclass_subsample_and_checkpoint(self, tmp_path):
        from sklearn.datasets import load_iris

        from spark_bagging_tpu import load_model, save_model

        X, y = load_iris(return_X_y=True)
        X = X.astype(np.float32)
        clf = BaggingClassifier(
            base_learner=GBTClassifier(n_rounds=8, max_depth=2,
                                       subsample=0.7),
            n_estimators=4, seed=0,
        ).fit(X, y)
        assert clf.score(X, y) > 0.85
        save_model(clf, str(tmp_path / "mc"))
        clf2 = load_model(str(tmp_path / "mc"))
        np.testing.assert_allclose(
            clf.predict_proba(X[:32]), clf2.predict_proba(X[:32]),
            rtol=1e-6,
        )


def test_multiclass_guards():
    gbt = GBTClassifier(n_rounds=2, max_depth=2)
    with pytest.raises(ValueError, match="2 classes"):
        gbt.init_params(KEY, 4, 1)
    # keyless multiclass fit with feature_subset must refuse (a zeros
    # placeholder key would give every class tree identical draws)
    fs = GBTClassifier(n_rounds=2, max_depth=2, feature_subset=2)
    X = np.random.default_rng(0).normal(size=(30, 4)).astype(np.float32)
    y = np.arange(30) % 3
    p0 = fs.init_params(KEY, 4, 3)
    with pytest.raises(ValueError, match="fit key"):
        fs.fit(p0, jnp.asarray(X), jnp.asarray(y, jnp.int32),
               jnp.ones(30), None)


@pytest.mark.slow  # [PR 14 pyramid] ~1.2s subset decorrelation soak
def test_multiclass_feature_subset_trees_differ():
    """With a real key, per-class trees draw DIFFERENT feature masks."""
    from sklearn.datasets import load_iris

    X, y = load_iris(return_X_y=True)
    X = X.astype(np.float32)
    gbt = GBTClassifier(n_rounds=4, max_depth=2, feature_subset=2)
    params, _ = gbt.fit_from_init(
        KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32),
        jnp.ones(len(y)), 3,
    )
    feats = np.asarray(params["feature"]).reshape(4, 3, 3)
    assert not (feats[:, 0] == feats[:, 1]).all()


def test_lr_validated():
    with pytest.raises(ValueError, match="lr must be"):
        GBTRegressor(lr=0.0)
    with pytest.raises(ValueError, match="lr must be"):
        GBTClassifier(lr=-0.1)
