"""Dataset registry, parsers, and synthetic-generator tests [SURVEY §4]."""

import numpy as np
import pytest

from spark_bagging_tpu.utils.datasets import (
    load_csv,
    load_dataset,
    make_classification,
    make_regression,
    parse_libsvm,
    synthetic_covtype,
)


def test_registry_bundled():
    X, y = load_dataset("breast_cancer")
    assert X.shape == (569, 30) and X.dtype == np.float32


def test_registry_unknown():
    with pytest.raises(KeyError, match="available"):
        load_dataset("no_such_thing")


def test_make_classification_deterministic():
    a = make_classification(100, 5, 3, seed=1)
    b = make_classification(100, 5, 3, seed=1)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    c = make_classification(100, 5, 3, seed=2)
    assert not np.array_equal(a[0], c[0])


def test_make_classification_labels_cover_classes():
    _, y = make_classification(1000, 4, 5, seed=0)
    assert set(np.unique(y)) == set(range(5))


def test_make_regression_shapes():
    X, y = make_regression(50, 7, seed=0)
    assert X.shape == (50, 7) and y.shape == (50,)
    assert X.dtype == np.float32 and y.dtype == np.float32


def test_synthetic_covtype_signature():
    X, y = synthetic_covtype(n_rows=1000)
    assert X.shape == (1000, 54)
    assert y.max() == 6  # 7 classes


def test_parse_libsvm(tmp_path):
    p = tmp_path / "d.svm"
    p.write_text("1 1:0.5 3:2.0\n-1 2:1.5  # comment\n\n0 1:1 2:2 3:3\n")
    X, y = parse_libsvm(str(p))
    np.testing.assert_allclose(y, [1, -1, 0])
    np.testing.assert_allclose(
        X, [[0.5, 0, 2.0], [0, 1.5, 0], [1, 2, 3]]
    )


def test_parse_libsvm_fixed_width(tmp_path):
    p = tmp_path / "d.svm"
    p.write_text("1 1:1.0\n")
    X, y = parse_libsvm(str(p), n_features=5)
    assert X.shape == (1, 5)


def test_load_csv(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("a,b,label\n1.0,2.0,0\n3.0,4.0,1\n")
    X, y = load_csv(str(p), skip_header=True)
    np.testing.assert_allclose(X, [[1, 2], [3, 4]])
    np.testing.assert_allclose(y, [0, 1])


def test_load_dataset_from_file(tmp_path):
    p = tmp_path / "d.svm"
    p.write_text("1 1:1.0 2:2.0\n0 1:3.0 2:4.0\n")
    X, y = load_dataset(str(p))
    assert X.shape == (2, 2)


class TestLoaderEdgeCases:
    def test_csv_single_column_rejected_not_transposed(self, tmp_path):
        """A multi-row single-column CSV must error, not silently load
        as one transposed row."""
        p = tmp_path / "col.csv"
        p.write_text("1\n2\n3\n4\n5\n")
        with pytest.raises(ValueError, match=">= 2 columns"):
            load_csv(str(p))

    def test_csv_header_after_blank_line(self, tmp_path):
        """The header is the first NON-blank line (the native parser's
        rule); the fallback must not parse it into an all-NaN row."""
        p = tmp_path / "blank.csv"
        p.write_text("\na,b,label\n1,2,3\n4,5,6\n")
        X, y = load_csv(str(p), skip_header=True)
        assert X.shape == (2, 2)
        assert np.isfinite(X).all() and np.isfinite(y).all()
        np.testing.assert_array_equal(y, [3.0, 6.0])

    def test_libsvm_qid_clear_error(self, tmp_path):
        p = tmp_path / "rank.svm"
        p.write_text("3 qid:1 1:0.5 2:1.0\n")
        from spark_bagging_tpu.utils.datasets import parse_libsvm

        with pytest.raises(ValueError, match="qid"):
            # force the Python fallback path deterministically
            import spark_bagging_tpu.utils.native as nat
            orig = nat.parse_libsvm_native
            nat.parse_libsvm_native = lambda *a, **k: None
            try:
                parse_libsvm(str(p))
            finally:
                nat.parse_libsvm_native = orig


def test_debug_mode_restores_prior_state():
    from spark_bagging_tpu.utils import debug

    debug.enable_debug()
    try:
        with debug.debug_mode():
            assert debug.debug_active()
        # a scoped block inside a process-wide enable must NOT turn
        # the user's debugging off
        assert debug.debug_active()
    finally:
        debug.disable_debug()
    with debug.debug_mode():
        assert debug.debug_active()
    assert not debug.debug_active()
