"""HBM-aware auto chunk_size [VERDICT r2 ask#8]: estimate, downshift,
and keep the vmap-all fast path when everything fits."""

import numpy as np
import pytest

from spark_bagging_tpu import BaggingClassifier, LogisticRegression
from spark_bagging_tpu.models import DecisionTreeClassifier, MLPClassifier
from spark_bagging_tpu.utils.memory import auto_chunk_size
from spark_bagging_tpu.utils.datasets import make_classification


def test_small_problem_keeps_vmap_all():
    # CI-sized fit: estimate is far under any budget → None (vmap-all)
    assert auto_chunk_size(
        LogisticRegression(), 1000, 30, 2, 16
    ) is None


def test_downshifts_when_budget_small():
    learner = LogisticRegression()
    per = learner.fit_workset_bytes(100_000, 54, 7)
    chunk = auto_chunk_size(
        learner, 100_000, 54, 7, 1000, budget_bytes=per * 50
    )
    assert chunk == 50


def test_headline_calibration_v5e():
    """The v5e calibration point [bench.py tuning notes]: 16 GB chip,
    1000-replica logreg on covtype-581k — chunk=200 fit, 500 OOMed.
    The model + 0.35 safety must land in the working range."""
    learner = LogisticRegression()
    free = 16 * 2**30
    chunk = auto_chunk_size(
        learner, 581_012, 54, 7, 1000, budget_bytes=free * 0.35
    )
    assert chunk is not None and 100 <= chunk < 500


def test_replica_mesh_budget_bounds_per_device_chunk():
    """chunk_size batches replicas INSIDE the shard_map body, after
    the replica axis is sharded — so a tight budget must bound the
    per-DEVICE chunk with no replica-axis scale-up (regression for the
    round-3 advisor's over-admission finding)."""
    import jax

    from spark_bagging_tpu.parallel.mesh import make_mesh

    learner = LogisticRegression()
    per = learner.fit_workset_bytes(100_000, 54, 7)
    mesh = make_mesh(data=1, replica=4, devices=jax.devices()[:4])
    # budget admits exactly 12 replicas' worksets per device
    chunk = auto_chunk_size(
        learner, 100_000, 54, 7, 1000, mesh=mesh, budget_bytes=per * 12
    )
    assert chunk == 12
    # chunk never exceeds the local replica count (vmap-all beyond it)
    chunk = auto_chunk_size(
        learner, 100_000, 54, 7, 16, mesh=mesh, budget_bytes=per * 12
    )
    assert chunk is None or chunk <= 4


def test_unmodeled_learner_stays_legacy():
    class Custom(LogisticRegression):
        def fit_workset_bytes(self, n_rows, n_features, n_outputs):
            return None

    assert auto_chunk_size(Custom(), 10**9, 54, 7, 10**6) is None


def test_tree_and_mlp_models_positive():
    t = DecisionTreeClassifier(max_depth=5)
    m = MLPClassifier(hidden=32, batch_size=1024)
    assert t.fit_workset_bytes(20_000, 54, 7) > 0
    assert m.fit_workset_bytes(20_000, 54, 7) > 0


@pytest.mark.slow  # [PR 20 budget offset] ~4.2s forced-auto-chunk fit soak; the workset-size model itself stays tier-1 via the pure unit tests above
def test_fit_resolves_and_reports_chunk(monkeypatch):
    X, y = make_classification(800, 10, 3, seed=0)
    # force a tiny budget so auto-chunking actually engages
    import spark_bagging_tpu.utils.memory as mem

    learner = LogisticRegression(max_iter=5)
    per = learner.fit_workset_bytes(800, 10, 3)
    monkeypatch.setattr(
        mem, "device_memory_budget", lambda safety=0.35: per * 4
    )
    auto = BaggingClassifier(
        base_learner=learner, n_estimators=16, seed=0
    ).fit(X, y)
    assert auto.fit_report_["chunk_size_resolved"] == 4
    # chunked and vmap-all fits agree (chunking is scan-of-vmap —
    # pure batching, not math)
    monkeypatch.setattr(
        mem, "device_memory_budget", lambda safety=0.35: 2**40
    )
    full = BaggingClassifier(
        base_learner=learner, n_estimators=16, seed=0
    ).fit(X, y)
    assert full.fit_report_["chunk_size_resolved"] is None
    np.testing.assert_allclose(
        auto.predict_proba(X[:64]), full.predict_proba(X[:64]),
        rtol=1e-5, atol=1e-6,
    )
