"""Scenario-conformance plane [ISSUE 14 acceptance]:

- registry shape: >= 6 scenarios, each with a COMMITTED digest
  baseline whose SLO spec round-trips ``SLOSpec`` exactly (unknown
  fields loud, ``max_stage_share`` included);
- THE tier-1 smoke: the full ``check`` pass in-process — every
  registered scenario re-runs through the replay machinery and
  byte-matches its committed baseline, under an asserted budget;
- breach detection: a corrupted baseline digest is a hard breach
  (exit 2), a missing baseline is loud, a scenario whose device needs
  this host cannot meet is the host-conditional band (exit 3).
"""

import json
import os
import shutil
import time

import pytest

from spark_bagging_tpu import telemetry
from spark_bagging_tpu.telemetry.slo import SLOSpec

from benchmarks import scenarios as S
from benchmarks.scenarios import runner


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    # runs append to the history store: keep it off the repo's dir
    monkeypatch.setenv("SBT_TELEMETRY_DIR", str(tmp_path))
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.reset()
    telemetry.enable()


def test_registry_shape_and_committed_baselines():
    assert len(S.SCENARIOS) >= 6
    S.validate_registry()  # every SLO dict round-trips SLOSpec
    root = runner.baselines_dir()
    for name, sc in S.SCENARIOS.items():
        assert sc.name == name
        assert sc.workload["kind"] and "seed" in sc.workload
        b = runner.load_baseline(name)
        assert b is not None, (
            f"scenario {name!r} has no committed baseline under "
            f"{root}; run `python -m benchmarks.scenarios record "
            f"--only {name}`"
        )
        assert b["schema"] == runner.BASELINE_SCHEMA_VERSION
        assert b["scenario"] == name
        assert b["digests"]["output"]
        assert b["environment"]["device_count"] == S.SCENARIO_DEVICES
    # the parity pair shares (workload, model) by construction — the
    # whole contract is "same bytes through a different executor"
    sp = S.get("sharded-parity")
    ref = S.get(sp.parity_with)
    assert sp.workload == ref.workload
    assert sp.model == ref.model
    # committed artifacts: baselines are the ONLY scenario files in
    # the tree (reports/history live in telemetry_dir())
    assert sorted(os.listdir(root)) == sorted(
        f"{n}.json" for n in S.SCENARIOS
    )


def test_slo_spec_roundtrips_through_baseline_files():
    """Satellite [ISSUE 14]: the committed baseline JSON carries the
    spec verbatim — SLOSpec.from_dict(file) -> to_dict() is the
    identity, unknown-field rejection is preserved, and
    max_stage_share survives the trip."""
    saw_stage_share = False
    for name in S.names():
        b = runner.load_baseline(name)
        spec = SLOSpec.from_dict(b["slo"])
        assert spec.to_dict() == b["slo"], name
        if b["slo"].get("max_stage_share"):
            saw_stage_share = True
            assert spec.max_stage_share == b["slo"]["max_stage_share"]
        bogus = dict(b["slo"])
        bogus["max_warp_factor"] = 9
        with pytest.raises(ValueError, match="unknown SLO spec"):
            SLOSpec.from_dict(bogus)
    assert saw_stage_share, (
        "at least one committed scenario SLO must exercise "
        "max_stage_share (the round-trip this test exists to pin)"
    )


def test_registration_validation():
    with pytest.raises(ValueError, match="already registered"):
        S.register(S.get("steady-poisson"))
    with pytest.raises(ValueError, match="kind"):
        S.register(S.Scenario(name="x", description="d",
                              workload={"seed": 1}))
    with pytest.raises(ValueError, match="not registered"):
        S.register(S.Scenario(
            name="y", description="d",
            workload={"kind": "poisson", "seed": 1},
            parity_with="no-such-scenario",
        ))
    with pytest.raises(KeyError, match="unknown scenario"):
        S.get("no-such-scenario")


@pytest.mark.scenario
def test_scenario_conformance_check_smoke(tmp_path):
    """THE tier-1 scenario-conformance smoke [ISSUE 14 acceptance]:
    the full `check` over every registered scenario, in-process —
    each digest byte-identical to its committed baseline (cross-repeat
    identity already asserted inside replay_median), every SLO green,
    exit 0 — under an asserted budget (the point of the pyramid: all
    eight incident drills cost less than two of the old soak tests)."""
    from benchmarks.scenarios.__main__ import main

    t0 = time.monotonic()
    out = str(tmp_path / "conformance.json")
    rc = main(["check", "--out", out])
    elapsed = time.monotonic() - t0
    assert rc == 0
    assert elapsed < 60.0, f"scenario check took {elapsed:.1f}s"
    report = json.loads(open(out).read())
    assert report["ok"] is True
    assert report["registered"] >= 6
    by_name = {r["scenario"]: r for r in report["scenarios"]}
    assert len(by_name) == report["registered"]
    assert all(r["status"] == "pass" for r in by_name.values())
    # the incident sections ride the conformance report
    assert by_name["chaos-mixed"]["chaos"]["retries"] > 0
    assert by_name["drift-onset"]["drift"]["alerts_fired"] == 1
    assert by_name["fleet-peer-loss"]["fleet"]["converged"] is True
    assert by_name["deadline-shed"]["counts"]["deadline_sheds"] > 0
    assert by_name["sharded-parity"]["digests"]["output"] == \
        by_name["steady-poisson"]["digests"]["output"]
    # the conformance plane is itself observable
    reg = telemetry.registry()
    assert reg.counter("sbt_scenario_runs_total",
                       labels={"scenario": "steady-poisson"}).value >= 1
    assert reg.gauge("sbt_scenario_digest_match",
                     labels={"scenario": "steady-poisson"}).value == 1.0
    # and every run landed in the history store with its digests
    from spark_bagging_tpu.telemetry import history

    recs = history.read_history()
    assert {r["key"] for r in recs} == set(S.names())
    assert all(r["digests"]["output"] for r in recs)
    assert all(r["slo_ok"] is True for r in recs)


def test_digest_breach_is_hard_exit_2(tmp_path):
    root = str(tmp_path / "baselines")
    os.makedirs(root)
    shutil.copy(runner.baseline_path("steady-poisson"),
                runner.baseline_path("steady-poisson", root))
    b = runner.load_baseline("steady-poisson", root)
    b["digests"]["output"] = "0" * 64
    with open(runner.baseline_path("steady-poisson", root), "w") as f:
        json.dump(b, f)
    report = runner.run_conformance(
        "check", ["steady-poisson"], baselines_root=root,
        history_path=str(tmp_path / "h.jsonl"),
    )
    (row,) = report["scenarios"]
    assert row["status"] == "digest-breach"
    assert report["exit_code"] == 2 and report["ok"] is False
    (mm,) = [m for m in row["mismatches"]
             if m["field"] == "digest.output"]
    assert mm["expected"] == "0" * 64
    # the failure is counted and the match gauge drops
    reg = telemetry.registry()
    assert reg.counter(
        "sbt_scenario_failures_total",
        labels={"scenario": "steady-poisson", "kind": "digest"},
    ).value == 1
    assert reg.gauge("sbt_scenario_digest_match",
                     labels={"scenario": "steady-poisson"}).value == 0.0
    # and the history record carries the breach run's digests so the
    # trend store flags the flip on the next scan
    from spark_bagging_tpu.telemetry import history

    recs = history.read_history(str(tmp_path / "h.jsonl"))
    assert len(recs) == 1
    assert recs[0]["digests"]["output"] != "0" * 64
    assert recs[0]["detail"]["status"] == "digest-breach"


def test_missing_baseline_is_loud(tmp_path):
    report = runner.run_conformance(
        "check", ["burst-shed"],
        baselines_root=str(tmp_path / "empty"),
        history_path=str(tmp_path / "h.jsonl"),
    )
    (row,) = report["scenarios"]
    assert row["status"] == "no-baseline"
    assert "record" in row["note"]
    assert report["exit_code"] == 2
    # counted under its own failure kind (not masquerading as an SLO
    # breach), and NO digest_match verdict was exported — nothing was
    # compared
    reg = telemetry.registry()
    assert reg.counter(
        "sbt_scenario_failures_total",
        labels={"scenario": "burst-shed", "kind": "baseline-missing"},
    ).value == 1
    assert reg.peek("sbt_scenario_digest_match",
                    {"scenario": "burst-shed"}) is None


def test_run_and_record_export_no_digest_verdict(tmp_path):
    """`run`/`record` compare nothing: sbt_scenario_digest_match must
    not light up green without a check having happened."""
    runner.run_conformance(
        "run", ["deadline-shed"],
        history_path=str(tmp_path / "h.jsonl"),
    )
    reg = telemetry.registry()
    assert reg.peek("sbt_scenario_digest_match",
                    {"scenario": "deadline-shed"}) is None
    assert reg.counter("sbt_scenario_runs_total",
                       labels={"scenario": "deadline-shed"}).value == 1


def test_unmeetable_device_need_is_host_band(tmp_path):
    sc = S.Scenario(
        name="needs-64-devices", description="d",
        workload={"kind": "poisson", "rate_rps": 100.0,
                  "duration_s": 0.1, "seed": 1, "width": 4},
        devices=64,
    )
    S.register(sc)
    try:
        report = runner.run_conformance(
            "check", ["needs-64-devices"],
            baselines_root=str(tmp_path),
            history_path=str(tmp_path / "h.jsonl"),
        )
    finally:
        del S.SCENARIOS["needs-64-devices"]
    (row,) = report["scenarios"]
    assert row["status"] == "skipped"
    assert "host-conditional" in row["note"]
    assert report["exit_code"] == 3


def test_cli_list_is_light(capsys):
    from benchmarks.scenarios.__main__ import main

    assert main(["list"]) == 0
    out = capsys.readouterr().out
    for name in S.names():
        assert name in out
    with pytest.raises(SystemExit):
        main(["check", "--only", "no-such-scenario"])
