"""Static lock-graph engine tests [ISSUE 19]: per-rule BAD/GOOD
fixture pairs, call-graph propagation, the reentrant-lock carve-out,
suppression, and — the cross-validation the engine exists for — the
agreement test proving every edge the dynamic detector observes on a
real drive is present in the statically extracted graph
(``observed ⊆ static``; the static graph may prove more, never less).
"""

from __future__ import annotations

import os

import pytest

from spark_bagging_tpu.analysis import locks
from spark_bagging_tpu.analysis.locks_static import (
    LOCK_RULES,
    analyze_source,
    edge_sites,
    static_edges,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "spark_bagging_tpu")


def hits(src: str, rule: str) -> list:
    return [f for f in analyze_source(src, "fixture.py")
            if f.rule == rule]


# -- fixture pairs -----------------------------------------------------

BAD_GOOD = {
    "static-lock-inversion": (
        # BAD: two methods take the same pair in opposite orders
        """
from spark_bagging_tpu.analysis.locks import make_lock


class Pair:
    def __init__(self):
        self._a = make_lock("fix.a")
        self._b = make_lock("fix.b")

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._b:
            with self._a:
                pass
""",
        # GOOD: one global order
        """
from spark_bagging_tpu.analysis.locks import make_lock


class Pair:
    def __init__(self):
        self._a = make_lock("fix.a")
        self._b = make_lock("fix.b")

    def forward(self):
        with self._a:
            with self._b:
                pass

    def also_forward(self):
        with self._a:
            with self._b:
                pass
""",
    ),
    "static-nested-same-lock": (
        # BAD: helper re-acquires the lock the caller already holds —
        # found through one level of call-graph propagation
        """
from spark_bagging_tpu.analysis.locks import make_lock


class Box:
    def __init__(self):
        self._lock = make_lock("fix.box")

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
""",
        # GOOD: rlock=True makes re-entry legal
        """
from spark_bagging_tpu.analysis.locks import make_lock


class Box:
    def __init__(self):
        self._lock = make_lock("fix.box", rlock=True)

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
""",
    ),
    "static-unlocked-check-then-act": (
        # BAD: the MicroBatcher.close() bug class — test-then-write on
        # a guarded attribute with no lock held
        """
from spark_bagging_tpu.analysis.locks import make_lock


class Once:
    def __init__(self):
        self._lock = make_lock("fix.once")
        self._closed = False

    def poke(self):
        with self._lock:
            self._closed = False

    def close(self):
        if self._closed:
            return
        self._closed = True
""",
        # GOOD: the check and the write share the guarding lock
        """
from spark_bagging_tpu.analysis.locks import make_lock


class Once:
    def __init__(self):
        self._lock = make_lock("fix.once")
        self._closed = False

    def poke(self):
        with self._lock:
            self._closed = False

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
""",
    ),
}


@pytest.mark.parametrize("rule", sorted(BAD_GOOD))
def test_bad_fixture_is_flagged(rule):
    bad, _ = BAD_GOOD[rule]
    assert hits(bad, rule), f"{rule} did not flag its BAD fixture"


@pytest.mark.parametrize("rule", sorted(BAD_GOOD))
def test_good_fixture_is_clean(rule):
    _, good = BAD_GOOD[rule]
    assert not hits(good, rule), (
        f"{rule} flagged its GOOD fixture:\n"
        + "\n".join(f.render() for f in hits(good, rule))
    )


def test_every_registered_rule_has_fixtures():
    """Registry-completeness guard."""
    assert set(LOCK_RULES) == set(BAD_GOOD), (
        "update BAD_GOOD in test_analysis_locks_static.py when adding "
        "lock rules"
    )


def test_direct_same_lock_nesting_is_flagged():
    src = """
from spark_bagging_tpu.analysis.locks import make_lock

_l = make_lock("fix.mod")


def f():
    with _l:
        with _l:
            pass
"""
    assert hits(src, "static-nested-same-lock")


def test_suppression_grammar_applies():
    bad, _ = BAD_GOOD["static-unlocked-check-then-act"]
    src = bad.replace(
        "if self._closed:",
        "if self._closed:"
        "  # sbt-lint: disable=static-unlocked-check-then-act",
    )
    assert not analyze_source(src, "fixture.py")


def test_nested_def_does_not_inherit_held_locks():
    """A closure defined under a lock runs LATER, under its caller's
    locks — its acquisitions are not nesting at definition time."""
    src = """
from spark_bagging_tpu.analysis.locks import make_lock

_a = make_lock("fix.na")
_b = make_lock("fix.nb")


def f():
    with _a:
        def worker():
            with _b:
                pass
        return worker
"""
    findings = analyze_source(src, "fixture.py")
    assert not findings, "\n".join(f.render() for f in findings)


# -- the real tree -----------------------------------------------------


@pytest.fixture(scope="module")
def repo_edges():
    # one whole-package scan shared by the three real-tree tests: the
    # parse is the cost, and the graph is the same for all of them
    return set(static_edges([PKG]))


def test_repo_static_graph_proves_known_seams(repo_edges):
    """The cross-file resolution the engine exists for: the executor's
    ``_build`` holds its build lock while going through the module
    alias + return annotation chain into the program cache."""
    assert ("serving.executor.build",
            "serving.program_cache") in repo_edges
    assert ("telemetry.fleet.scrape", "telemetry.fleet") in repo_edges


def test_static_graph_is_cwd_independent(tmp_path, monkeypatch,
                                         repo_edges):
    """Regression: module names used to come from ``os.path.relpath``,
    so running the engine from outside the repo silently dropped every
    cross-module edge (the alias-resolution tier never matched). The
    graph must be identical whatever the caller's working directory
    is."""
    monkeypatch.chdir(tmp_path)
    assert set(static_edges([PKG])) == repo_edges
    assert ("serving.executor.build",
            "serving.program_cache") in repo_edges


def test_edge_sites_name_real_files():
    sites = edge_sites([PKG])
    for (a, b), (path, line) in sites.items():
        assert os.path.isfile(path), (a, b, path)
        assert line > 0


def test_static_vs_dynamic_agreement(repo_edges):
    """observed ⊆ static: drive the real FleetAggregator scrape path
    under the dynamic detector and require every observed edge to be
    present in the statically extracted graph. The static graph may
    prove MORE orders than one run exercises — never fewer."""
    from spark_bagging_tpu.telemetry.fleet import FleetAggregator

    class _Peer:
        # lock-free scrape double: keeps the observed graph inside the
        # aggregator's own locks, which is the seam under test
        name = "p0"

        def scrape(self):
            return {"metrics": []}

    # enable BEFORE construction: make_lock picks plain vs instrumented
    # locks at creation time
    locks.clear()
    locks.enable(True, strict=False)
    try:
        agg = FleetAggregator([_Peer()], interval_s=0.0)
        agg.scrape_all(now=0.0)
        agg.scrape_all(now=10.0)
        observed = set(locks.acquisition_edges())
    finally:
        locks.enable(False)
        locks.clear()
    assert ("telemetry.fleet.scrape", "telemetry.fleet") in observed, (
        "the drive did not exercise the scrape->merge nesting; "
        "the agreement test would be vacuous"
    )
    static = repo_edges
    assert observed <= static, (
        f"dynamically observed lock edges missing from the static "
        f"graph: {sorted(observed - static)}"
    )
