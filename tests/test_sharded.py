"""Sharding tests on the 8-virtual-device CPU mesh — the `local[*]`
analog [SURVEY §4]: replica sharding, data sharding, and the combined
2-D mesh must reproduce (or statistically match) single-device results."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_diabetes
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import BaggingClassifier, BaggingRegressor
from spark_bagging_tpu.parallel import make_mesh
from spark_bagging_tpu.parallel.compat import HAS_SHARD_MAP
from spark_bagging_tpu.parallel.sharded import pad_rows

pytestmark = pytest.mark.skipif(
    not HAS_SHARD_MAP,
    reason="this jax build has no shard_map implementation "
           "(parallel/compat.py)",
)


@pytest.fixture(scope="module")
def breast_cancer():
    X, y = load_breast_cancer(return_X_y=True)
    return StandardScaler().fit_transform(X).astype(np.float32), y


@pytest.fixture(scope="module")
def diabetes():
    X, y = load_diabetes(return_X_y=True)
    return (
        StandardScaler().fit_transform(X).astype(np.float32),
        y.astype(np.float32),
    )


def test_make_mesh_shapes():
    m = make_mesh()  # all-replica default
    assert m.shape == {"data": 1, "replica": 8}
    m2 = make_mesh(data=4)
    assert m2.shape == {"data": 4, "replica": 2}
    with pytest.raises(ValueError, match="divisible"):
        make_mesh(data=3)
    with pytest.raises(ValueError, match="devices"):
        make_mesh(data=2, replica=2)


def test_pad_rows():
    X = jnp.ones((10, 3))
    y = jnp.arange(10.0)
    Xp, yp, mask = pad_rows(X, y, 8)
    assert Xp.shape == (16, 3) and yp.shape == (16,)
    np.testing.assert_array_equal(np.asarray(mask), [1.0] * 10 + [0.0] * 6)
    Xn, yn, mn = pad_rows(X, y, 5)
    assert Xn.shape == (10, 3) and float(mn.sum()) == 10


def test_replica_sharded_fit_matches_unsharded(breast_cancer):
    """Pure replica sharding is bit-compatible with single-device vmap:
    replica identity derives only from (seed, replica_id)."""
    X, y = breast_cancer
    mesh = make_mesh()  # (1, 8)
    a = BaggingClassifier(n_estimators=16, seed=3, mesh=mesh).fit(X, y)
    b = BaggingClassifier(n_estimators=16, seed=3).fit(X, y)
    np.testing.assert_array_equal(
        np.asarray(a.subspaces_), np.asarray(b.subspaces_)
    )
    # Compare the gauge-invariant part of W (softmax is invariant to
    # adding a per-feature constant across classes; the bias-jitter
    # near-null direction amplifies float32 noise in raw W).
    Wa = np.asarray(a.ensemble_["W"])
    Wb = np.asarray(b.ensemble_["W"])
    np.testing.assert_allclose(
        Wa - Wa.mean(-1, keepdims=True),
        Wb - Wb.mean(-1, keepdims=True),
        rtol=0, atol=1e-4,
    )
    np.testing.assert_allclose(
        a.predict_proba(X), b.predict_proba(X), atol=2e-4
    )


def test_data_sharded_fit_exact_with_deterministic_weights(breast_cancer):
    """With bootstrap=False + max_samples=1.0 the weights are all-ones,
    so the psum'd data-parallel Newton must reproduce the single-device
    fit exactly (up to float32 noise)."""
    X, y = breast_cancer
    n = (len(y) // 8) * 8  # avoid padding so draws are comparable
    X, y = X[:n], y[:n]
    kw = dict(n_estimators=8, bootstrap=False, max_samples=1.0, seed=0)
    a = BaggingClassifier(**kw, mesh=make_mesh(data=8)).fit(X, y)
    b = BaggingClassifier(**kw).fit(X, y)
    assert a.fit_report_["loss_mean"] == pytest.approx(
        b.fit_report_["loss_mean"], rel=1e-5
    )
    np.testing.assert_allclose(
        a.predict_proba(X), b.predict_proba(X), atol=1e-5
    )


@pytest.mark.slow  # [PR 20 budget offset] ~4.6s statistical-accuracy soak; sharded-fit parity stays tier-1 via test_replica_sharded_fit_matches_unsharded (bitwise) and the sharded proba row-sum check
def test_data_sharded_fit_classifier(breast_cancer):
    """Data-parallel bootstrap fit: draws differ by shard layout
    (documented) but accuracy must match statistically."""
    X, y = breast_cancer
    mesh = make_mesh(data=8)  # (8, 1)
    clf = BaggingClassifier(n_estimators=10, seed=0, mesh=mesh).fit(X, y)
    ref = BaggingClassifier(n_estimators=10, seed=0).fit(X, y)
    assert abs(clf.score(X, y) - ref.score(X, y)) < 0.02


def test_2d_mesh_fit_and_predict(breast_cancer):
    """The full (data=2, replica=4) rectangle [SURVEY §2c mesh design]."""
    X, y = breast_cancer
    mesh = make_mesh(data=2)
    clf = BaggingClassifier(
        n_estimators=8, seed=1, mesh=mesh, max_features=0.8
    ).fit(X, y)
    assert clf.score(X, y) > 0.95
    proba = clf.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)


@pytest.mark.slow  # [PR 14 pyramid] ~1.9s 2d-mesh regressor twin; 2d-mesh fit+predict classifier stays tier-1
def test_2d_mesh_regressor(diabetes):
    X, y = diabetes
    mesh = make_mesh(data=2)
    reg = BaggingRegressor(n_estimators=12, seed=2, mesh=mesh).fit(X, y)
    ref = BaggingRegressor(n_estimators=12, seed=2).fit(X, y)
    assert abs(reg.score(X, y) - ref.score(X, y)) < 0.05
    assert reg.predict(X).shape == (len(y),)


def test_indivisible_replicas_raises(breast_cancer):
    X, y = breast_cancer
    mesh = make_mesh()  # replica axis 8
    with pytest.raises(ValueError, match="divisible"):
        BaggingClassifier(n_estimators=10, mesh=mesh).fit(X, y)


@pytest.mark.slow  # ~6s [PR 11 budget offset]: data-sharded OOB regeneration drill; the replica-mesh OOB parity and the weight-replay contract stay tier-1
def test_oob_on_data_sharded_mesh(breast_cancer):
    """Data-sharded OOB regenerates per-shard weight streams and psums
    vote counts over the replica axis [VERDICT r1 #8]. The realized
    bootstrap differs from the unsharded one (documented: fold_in on
    the shard index), so scores match statistically, not exactly."""
    X, y = breast_cancer
    ref = BaggingClassifier(n_estimators=32, oob_score=True, seed=3).fit(X, y)
    for mesh in (make_mesh(data=2), make_mesh(data=8)):
        clf = BaggingClassifier(
            n_estimators=32, oob_score=True, seed=3, mesh=mesh
        ).fit(X, y)
        assert clf.oob_score_ == pytest.approx(ref.oob_score_, abs=0.05)
        # every row got at least one OOB vote at 32 replicas (P_miss ~
        # (1 - e^-1)^32 ~ 1e-7), so the decision function is finite
        assert np.isfinite(clf.oob_decision_function_).all()
        rowsum = clf.oob_decision_function_.sum(axis=1)
        np.testing.assert_allclose(rowsum, 1.0, rtol=1e-5)


def test_oob_data_sharded_deterministic(breast_cancer):
    X, y = breast_cancer
    mesh = make_mesh(data=2)
    kw = dict(n_estimators=16, oob_score=True, seed=9, mesh=mesh)
    a = BaggingClassifier(**kw).fit(X, y)
    b = BaggingClassifier(**kw).fit(X, y)
    np.testing.assert_array_equal(
        a.oob_decision_function_, b.oob_decision_function_
    )
    assert a.oob_score_ == b.oob_score_


@pytest.mark.slow  # [PR 14 pyramid] ~3.2s data-sharded OOB regressor twin; replica-mesh OOB parity stays tier-1
def test_oob_regressor_on_data_sharded_mesh(diabetes):
    X, y = diabetes
    ref = BaggingRegressor(n_estimators=32, oob_score=True, seed=3).fit(X, y)
    clf = BaggingRegressor(
        n_estimators=32, oob_score=True, seed=3, mesh=make_mesh(data=2)
    ).fit(X, y)
    assert clf.oob_score_ == pytest.approx(ref.oob_score_, abs=0.07)
    assert np.isfinite(clf.oob_prediction_).all()


def test_oob_on_replica_mesh_matches_unsharded(breast_cancer):
    """Replica-only meshes draw weights from the unfolded key over global
    rows — identical stream to the OOB regeneration path."""
    X, y = breast_cancer
    a = BaggingClassifier(
        n_estimators=16, oob_score=True, seed=5, mesh=make_mesh()
    ).fit(X, y)
    b = BaggingClassifier(n_estimators=16, oob_score=True, seed=5).fit(X, y)
    assert a.oob_score_ == pytest.approx(b.oob_score_, abs=1e-6)


def test_hard_vote_on_mesh(breast_cancer):
    X, y = breast_cancer
    mesh = make_mesh()
    clf = BaggingClassifier(
        n_estimators=16, voting="hard", seed=5, mesh=mesh
    ).fit(X, y)
    assert clf.score(X, y) > 0.95


def test_make_mesh_rejects_nonpositive_axes():
    with pytest.raises(ValueError, match=">= 1"):
        make_mesh(data=0)
    with pytest.raises(ValueError, match=">= 1"):
        make_mesh(data=1, replica=-1)
