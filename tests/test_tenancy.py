"""The tenancy plane [ISSUE 17]: priority admission control (quota
token buckets, the pressure state machine, per-class shed ordering),
deterministic weighted fair queuing (weight-proportional service,
no starvation, reproducible pop order), demand-driven residency
(demote → AOT restore round-trips that never recompile and never
change answers, pin policies over the unified cache), per-tenant
refit budgeting wired into the online trainer, the tenancy alert
rules, the /debug/tenancy surface, the lock-order detector over the
tenancy→registry→program-cache edges, and the in-process replay
drill gate (``--tenants``).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.serving import ModelRegistry
from spark_bagging_tpu.serving import program_cache as _pc
from spark_bagging_tpu.telemetry import alerts
from spark_bagging_tpu.telemetry import capacity as capacity_mod
from spark_bagging_tpu.tenancy import (
    AdmissionController,
    AdmissionShed,
    QuotaExceeded,
    RefitBudgeter,
    TenantFleet,
    TenantSpec,
    WFQScheduler,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _module_clock():
    """Wall-clock anchor for the budget test (module import happens at
    collection, long before the first test runs)."""
    return time.perf_counter()


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.enable()
    # a private unified cache per test: the GLOBAL cache shares
    # compiled (and, after a restore, DESERIALIZED) executables across
    # identical model fingerprints — a later test warming from a
    # deserialized entry would save_executables() payloads that are
    # not round-trip stable (see aot_cache.covers)
    prev_cache = _pc.install(_pc.ProgramCache(capacity=64))
    yield
    _pc.install(prev_cache)
    telemetry.reset()
    telemetry.enable()


def _counter(name, labels=None):
    return telemetry.registry().counter(name, labels=labels).value


def _problem(n=96, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y = (X @ w > 0).astype(np.int32)
    return X, y


def _fit(seed=0, n_estimators=2):
    X, y = _problem(seed=seed)
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5),
        n_estimators=n_estimators, seed=seed,
    ).fit(X, y)


# -- specs --------------------------------------------------------------

class TestTenantSpec:
    def test_validation(self):
        with pytest.raises(ValueError, match="priority"):
            TenantSpec(name="t", priority="urgent")
        with pytest.raises(ValueError, match="weight"):
            TenantSpec(name="t", weight=0.0)
        with pytest.raises(ValueError, match="quota_rps"):
            TenantSpec(name="t", quota_rps=-1.0)
        with pytest.raises(ValueError, match="name"):
            TenantSpec(name="")

    def test_refit_weight_falls_back_to_weight(self):
        s = TenantSpec(name="t", weight=3.0)
        assert s.effective_refit_weight == 3.0
        s2 = TenantSpec(name="u", weight=3.0, refit_weight=0.5)
        assert s2.effective_refit_weight == 0.5

    def test_priority_levels_ordered(self):
        assert (TenantSpec(name="a", priority="interactive").priority_level
                < TenantSpec(name="b", priority="standard").priority_level
                < TenantSpec(name="c", priority="batch").priority_level)


# -- weighted fair queuing ---------------------------------------------

class TestWFQ:
    def test_weight_proportional_service_under_saturation(self):
        """Tentpole invariant [ISSUE 17]: with both tenants saturating
        the queue, a 2:1 weight ratio yields 2:1 service in every
        drained prefix (SCFQ virtual finish times), not just at the
        end."""
        wfq = WFQScheduler({"a": 2.0, "b": 1.0})
        for i in range(30):
            wfq.enqueue("a", ("a", i))
            wfq.enqueue("b", ("b", i))
        order = []
        for _ in range(30):
            order.append(wfq.pop()[0])
        # every prefix of length 3k serves exactly 2k a's and k b's
        for k in range(1, 11):
            prefix = order[: 3 * k]
            assert prefix.count("a") == 2 * k, prefix
            assert prefix.count("b") == k, prefix
        # mid-drain (both still backlogged): served cost tracks weight
        served = wfq.service_totals()
        assert served["a"] == pytest.approx(2 * served["b"])
        list(wfq.drain())
        assert len(wfq) == 0

    def test_no_starvation_under_extreme_weights(self):
        """A 100:1 weight ratio delays the light tenant, it never
        starves it: finite backlog ⇒ finite finish tag ⇒ served."""
        wfq = WFQScheduler({"heavy": 100.0, "light": 1.0})
        for i in range(50):
            wfq.enqueue("heavy", i)
        wfq.enqueue("light", "x")
        order = [t for t, _ in wfq.drain()]
        assert "light" in order
        assert wfq.backlog() == {"heavy": 0, "light": 0}

    def test_deterministic_pop_order(self):
        """Batch composition is a pure function of the submit
        sequence: two schedulers fed identically pop identically
        (ties broken by (finish, tenant, seq), nothing reads a
        clock)."""

        def run():
            wfq = WFQScheduler({"a": 1.5, "b": 1.0, "c": 0.5})
            rng = np.random.default_rng(7)
            picks = rng.choice(["a", "b", "c"], size=60)
            for i, t in enumerate(picks):
                wfq.enqueue(str(t), i, cost=float(1 + i % 3))
            return [(t, item) for t, item in wfq.drain()]

        assert run() == run()

    def test_costs_weight_the_finish_tags(self):
        """Row cost divides through the weight: one 4-row request from
        a weight-1 tenant finishes with four 1-row requests from an
        equal-weight peer."""
        wfq = WFQScheduler({"a": 1.0, "b": 1.0})
        wfq.enqueue("a", "big", cost=4.0)
        for i in range(4):
            wfq.enqueue("b", i, cost=1.0)
        order = [t for t, _ in wfq.drain()]
        # b's tags land at 1,2,3,4; a's single tag at 4 — the finish-
        # tag tie at 4 breaks on tenant name, so "a" precedes b's 4th
        assert order == ["b", "b", "b", "a", "b"]
        totals = wfq.service_totals()
        assert totals["a"] == totals["b"] == 4.0

    def test_unknown_tenant_is_loud(self):
        wfq = WFQScheduler({"a": 1.0})
        with pytest.raises(KeyError):
            wfq.enqueue("nope", 1)


# -- admission ----------------------------------------------------------

class TestAdmission:
    def test_quota_token_bucket_deterministic(self):
        """quota_rps=2 with one-second burst: two admits at t=0, the
        third sheds with reason "quota"; by t=1 the bucket refilled
        exactly two tokens."""
        ctl = AdmissionController(
            [TenantSpec(name="t", quota_rps=2.0)])
        assert ctl.admit("t", 1, now=0.0) is None
        assert ctl.admit("t", 1, now=0.0) is None
        assert ctl.admit("t", 1, now=0.0) == "quota"
        assert ctl.admit("t", 1, now=1.0) is None
        assert ctl.admit("t", 1, now=1.0) is None
        assert ctl.admit("t", 1, now=1.0) == "quota"
        assert ctl.admitted_counts() == {"t": 4}
        assert ctl.shed_counts() == {"t": {"quota": 2}}
        # the alert-facing unlabeled total AND the attribution twin
        assert _counter("sbt_tenancy_shed_total") == 2.0
        assert _counter("sbt_tenancy_shed_total",
                        {"tenant": "t", "reason": "quota"}) == 2.0

    def test_rows_quota_binds_on_row_cost(self):
        ctl = AdmissionController(
            [TenantSpec(name="t", quota_rows_ps=8.0)])
        assert ctl.admit("t", 8, now=0.0) is None
        assert ctl.admit("t", 1, now=0.0) == "quota"

    def test_priority_shed_ordering(self):
        """Satellite [ISSUE 17]: the pressure machine sheds batch
        first, standard on escalation, interactive never."""
        specs = [TenantSpec(name="i", priority="interactive"),
                 TenantSpec(name="s", priority="standard"),
                 TenantSpec(name="b", priority="batch")]
        ctl = AdmissionController(specs, pressure_window_s=1.0,
                                  escalate_after=3)
        # normal: everyone admitted
        for n in ("i", "s", "b"):
            assert ctl.admit(n, 1, now=0.0) is None
        # one overload -> level 1: batch sheds, standard survives
        ctl.observe_overload(0.1)
        assert ctl.pressure_level(0.1) == 1
        assert ctl.admit("b", 1, now=0.1) == "priority"
        assert ctl.admit("s", 1, now=0.1) is None
        assert ctl.admit("i", 1, now=0.1) is None
        # escalation -> level 2: standard sheds too; interactive never
        ctl.observe_overload(0.2)
        ctl.observe_overload(0.3)
        assert ctl.pressure_level(0.3) == 2
        assert ctl.admit("b", 1, now=0.3) == "priority"
        assert ctl.admit("s", 1, now=0.3) == "priority"
        assert ctl.admit("i", 1, now=0.3) is None
        # the window passes with no new overload: back to normal
        assert ctl.pressure_level(1.5) == 0
        assert ctl.admit("b", 1, now=1.5) is None
        state = ctl.state(now=1.5)
        assert state["pressure_level"] == 0
        assert state["overloads_total"] == 3
        assert state["tenants"]["b"]["shed"] == {"priority": 2}

    def test_check_raises_typed_sheds(self):
        ctl = AdmissionController(
            [TenantSpec(name="q", quota_rps=1.0),
             TenantSpec(name="b", priority="batch")])
        ctl.check("q", 1, now=0.0)
        with pytest.raises(QuotaExceeded) as ei:
            ctl.check("q", 1, now=0.0)
        assert ei.value.tenant == "q" and ei.value.reason == "quota"
        ctl.observe_overload(0.0)
        with pytest.raises(AdmissionShed) as ei:
            ctl.check("b", 1, now=0.0)
        assert ei.value.reason == "priority"

    def test_unknown_and_duplicate_tenants_loud(self):
        ctl = AdmissionController([TenantSpec(name="t")])
        with pytest.raises(KeyError):
            ctl.admit("nope", 1, now=0.0)
        with pytest.raises(ValueError, match="already"):
            ctl.add_tenant(TenantSpec(name="t"))


# -- refit budgeting ----------------------------------------------------

class TestRefitBudget:
    def test_weight_proportional_quota_with_floor(self):
        b = RefitBudgeter(
            [TenantSpec(name="hot", weight=3.0),
             TenantSpec(name="tail", weight=1.0)],
            total_per_window=4, window_s=60.0,
        )
        assert b.quota("hot") == 3
        assert b.quota("tail") == 1
        # the floor: a tiny weight never rounds to zero refits
        b2 = RefitBudgeter(
            [TenantSpec(name="hog", weight=100.0),
             TenantSpec(name="tail", weight=0.01)],
            total_per_window=2,
        )
        assert b2.quota("tail") == 1

    def test_window_reset_and_denial_counts(self):
        b = RefitBudgeter([TenantSpec(name="t", weight=1.0)],
                          total_per_window=1, window_s=10.0)
        assert b.allow("t", now=0.0) is True
        assert b.allow("t", now=1.0) is False
        assert b.allow("t", now=9.9) is False
        # the window turns: allowance resets
        assert b.allow("t", now=10.0) is True
        assert b.counts() == {"allowed": {"t": 2}, "denied": {"t": 2}}
        assert _counter("sbt_tenancy_refit_denied_total",
                        {"tenant": "t"}) == 2.0

    def test_online_trainer_honors_budget_hook(self):
        """Satellite [ISSUE 17]: ``OnlineTrainer(refit_budget=...)``
        consults the budgeter at trigger time — a denied trigger is
        dropped (counted, no refit enqueued), an allowed one
        proceeds."""
        from spark_bagging_tpu.online import LabeledBuffer, OnlineTrainer

        X, y = _problem(n=192)
        est = _fit()
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
        reg.register("m", est, warmup=False)
        buf = LabeledBuffer()
        buf.add(X[:128], y[:128])
        budget = RefitBudgeter([TenantSpec(name="m", weight=1.0)],
                               total_per_window=1, window_s=100.0)
        trainer = OnlineTrainer(reg, "m", buf, min_refit_rows=32,
                                margin=0.5, seed=0,
                                refit_budget=budget.for_tenant("m"))
        trainer.trigger(now=0.0)
        assert trainer.pending == 1
        # second trigger in the same window: budget-denied, dropped
        trainer.trigger(now=1.0)
        assert trainer.pending == 1
        assert trainer.budget_denied == 1
        assert _counter("sbt_online_refits_budget_denied_total",
                        {"model": "m"}) == 1.0
        assert trainer.summary()["budget_denied"] == 1

    def test_trainer_rejects_non_callable_budget(self):
        from spark_bagging_tpu.online import LabeledBuffer, OnlineTrainer

        est = _fit()
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
        reg.register("m", est, warmup=False)
        with pytest.raises(ValueError, match="refit_budget"):
            OnlineTrainer(reg, "m", LabeledBuffer(), refit_budget=42)


# -- program-cache pin policy ------------------------------------------

class _FakePlane:
    """A stand-in demand plane: fixed owner + class maps."""

    def __init__(self, owners=None, classes=None):
        self.owners = owners or {}
        self.classes = classes or {}

    def owner_label(self, fingerprint):
        return self.owners.get(fingerprint)

    def demand_class(self, owner):
        return self.classes.get(owner, "cold")


class TestCachePinPolicy:
    @staticmethod
    def _key(fp, bucket=8):
        return _pc.ProgramKey(fp, "predict", bucket, None, False,
                              "j", "cpu", "cpu")

    def _fill(self, cache, keys):
        for k in keys:
            cache.put(self._key(k), object())

    def test_none_policy_keeps_strict_lru(self):
        """The committed churn baselines were recorded under strict
        LRU; the default (no policy) must evict in exactly that
        order."""
        cache = _pc.ProgramCache(capacity=2)
        self._fill(cache, ["a", "b", "c"])
        assert [e["fingerprint"] for e in cache.snapshot()["entries"]] \
            == ["b", "c"]

    def test_pinned_entries_skipped(self):
        from spark_bagging_tpu.tenancy.residency import cache_pin_policy

        plane = _FakePlane(owners={"a": "ta", "b": "tb", "c": "tc"},
                           classes={"ta": "hot"})
        cache = _pc.ProgramCache(capacity=2,
                                 pin_policy=cache_pin_policy(plane))
        self._fill(cache, ["a", "b", "c"])
        # LRU head "a" is hot-pinned: "b" evicts instead
        assert [e["fingerprint"] for e in cache.snapshot()["entries"]] \
            == ["a", "c"]
        assert _counter("sbt_tenancy_pin_violations_total") == 0.0

    def test_all_pinned_falls_back_counted(self):
        from spark_bagging_tpu.tenancy.residency import cache_pin_policy

        plane = _FakePlane(owners={"a": "ta", "b": "tb", "c": "tc"},
                           classes={"ta": "hot", "tb": "hot",
                                    "tc": "hot"})
        cache = _pc.ProgramCache(capacity=2,
                                 pin_policy=cache_pin_policy(plane))
        self._fill(cache, ["a", "b", "c"])
        # every candidate pinned: strict LRU wins, violation counted
        assert [e["fingerprint"] for e in cache.snapshot()["entries"]] \
            == ["b", "c"]
        assert _counter("sbt_tenancy_pin_violations_total") == 1.0
        assert _counter("sbt_tenancy_pin_violations_total",
                        {"level": "cache"}) == 1.0

    def test_drop_fingerprint_removes_and_counts(self):
        cache = _pc.ProgramCache(capacity=8)
        self._fill(cache, ["a", "b"])
        cache.put(self._key("a", 16), object())
        before = _counter("sbt_program_cache_evictions_total")
        assert cache.drop_fingerprint("a") == 2
        assert cache.drop_fingerprint("a") == 0
        assert [e["fingerprint"] for e in cache.snapshot()["entries"]] \
            == ["b"]
        assert _counter("sbt_program_cache_evictions_total") \
            == before + 2


# -- residency: the demote/restore round-trip ---------------------------

class TestResidency:
    def test_round_trip_bitwise_and_compile_free(self, tmp_path):
        """The tentpole's core claim [ISSUE 17]: with a residency
        budget below the fleet size, a demoted tenant's first hit
        restores from its AOT cache — counted, ZERO compiles, and the
        answer bitwise-equal to a never-demoted solo executor. Three
        full demote/restore cycles also pin the covers() regression:
        re-serializing restored executables is skipped, so later
        restores keep loading."""
        plane = capacity_mod.CapacityPlane()
        prev = capacity_mod.install(plane)
        try:
            specs = [TenantSpec(name=f"t{i}") for i in range(2)]
            reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=16)
            fleet = TenantFleet(specs, registry=reg,
                                residency_capacity=1,
                                aot_root=str(tmp_path), plane=plane)
            models = [_fit(seed=s) for s in (0, 1)]
            for i in range(2):
                fleet.register(f"t{i}", models[i], warmup=True,
                               version=1)
            # capacity 1: registering t1 demoted t0
            assert fleet.residency.residents() == ("t1",)
            X = np.asarray(_problem(seed=9)[0][:8])
            # the never-demoted control: the same fitted model behind
            # a solo registry that keeps its programs resident
            solo_reg = ModelRegistry(min_bucket_rows=8,
                                     max_batch_rows=16)
            solo_reg.register("solo", models[0], warmup=True)
            solo = np.asarray(solo_reg.executor("solo").predict(X))
            compiles0 = _counter("sbt_serving_compiles_total")
            for _ in range(3):
                assert fleet.residency.touch("t0") == "restored"
                got = np.asarray(reg.executor("t0").predict(X))
                assert np.array_equal(got, solo)
                assert fleet.residency.touch("t1") == "restored"
            assert _counter("sbt_serving_compiles_total") == compiles0
            counts = fleet.residency.counts()
            assert counts["restores"] == {"t0": 3, "t1": 3}
            assert counts["demotions"]["t0"] >= 3
            assert _counter("sbt_tenancy_restores_total",
                            {"tenant": "t0"}) == 3.0
            assert _counter("sbt_serving_programs_released_total") > 0
            events = fleet.residency.events()
            assert [e["seq"] for e in events] == \
                list(range(1, len(events) + 1))
            fleet.close()
        finally:
            capacity_mod.install(prev)

    def test_hot_tenants_pinned_cold_evicted(self, tmp_path):
        """Victim selection consults the demand plane: the LRU head
        survives while classified hot; only an all-hot candidate set
        falls back to LRU with a counted pin violation."""
        from spark_bagging_tpu.tenancy.residency import ResidencyManager

        class _Reg:
            def __init__(self):
                self.released = []

            def executor(self, name):
                reg = self

                class _Ex:
                    compiled_buckets = ()

                    def release_programs(self):
                        reg.released.append(name)
                        return ()

                    def restore_executables(self, path):
                        return ()

                return _Ex()

        plane = _FakePlane(classes={"a": "hot"})
        r = ResidencyManager(_Reg(), capacity=2,
                             aot_root=str(tmp_path), plane=plane)
        r.adopt("a")
        r.adopt("b")
        r.adopt("c")  # over budget: "a" is hot-pinned, "b" evicts
        assert r.residents() == ("a", "c")
        assert r.counts()["pin_violations"] == {}
        plane.classes = {"a": "hot", "c": "hot"}
        r.adopt("d")  # all candidates hot: LRU head "a", counted
        assert r.residents() == ("c", "d")
        assert r.counts()["pin_violations"] == {"a": 1}
        assert _counter("sbt_tenancy_pin_violations_total",
                        {"tenant": "a"}) == 1.0

    def test_tenant_dir_rejects_path_separators(self, tmp_path):
        from spark_bagging_tpu.tenancy.residency import ResidencyManager

        r = ResidencyManager(object(), capacity=1,
                             aot_root=str(tmp_path))
        with pytest.raises(ValueError, match="safe"):
            r.tenant_dir("../escape")


# -- alert rules --------------------------------------------------------

class TestTenancyAlerts:
    def test_tenancy_rules_fire(self):
        """Satellite [ISSUE 17]: the tenant-aware capacity rules burn
        on the tail-tenant p99 gauge and the fleet-level quota-shed
        rate (the unlabeled counter twin — the engine samples exact
        label sets)."""
        rules = {r.name: r for r in alerts.default_capacity_rules(
            fast_window_s=2.0, slow_window_s=5.0, cooldown_s=0.0)}
        tail = rules["tenancy-tail-latency-burn"]
        assert tail.kind == "value" and tail.op == ">"
        eng = alerts.AlertEngine([tail])
        telemetry.set_gauge("sbt_tenancy_tail_p99_ms", 400.0)
        assert eng.evaluate(now=0.0) == []
        for t in (2.0, 4.0):
            eng.evaluate(now=t)
        evs = eng.evaluate(now=5.5)
        assert [e["kind"] for e in evs] == ["alert_fired"]

        shed = rules["tenancy-quota-shed-rate"]
        assert shed.kind == "rate"
        assert shed.series == "sbt_tenancy_shed_total"
        eng2 = alerts.AlertEngine([shed])
        assert eng2.evaluate(now=0.0) == []
        fired = []
        for i in range(1, 12):
            # 5 sheds per half-second tick: 10/s, well over the 1/s
            # threshold — fires once BOTH windows have coverage
            telemetry.inc("sbt_tenancy_shed_total", 5.0)
            fired += [e for e in eng2.evaluate(now=float(i) / 2)
                      if e["kind"] == "alert_fired"]
        assert [e["rule"] for e in fired] == ["tenancy-quota-shed-rate"]


# -- the /debug/tenancy surface ----------------------------------------

class TestDebugRoute:
    def test_install_seam_and_route_document(self, tmp_path):
        import spark_bagging_tpu.tenancy as tenancy
        from spark_bagging_tpu.telemetry.server import _debug_tenancy

        body = _debug_tenancy()
        assert body["enabled"] is False
        specs = [TenantSpec(name="t0"), TenantSpec(name="t1")]
        fleet = TenantFleet(specs)
        tenancy.install(fleet)
        try:
            assert tenancy.get() is fleet
            body = _debug_tenancy()
            assert body["enabled"] is True
            for key in ("tenants", "registered", "admission", "wfq",
                        "residency", "refit_budget",
                        "downstream_sheds", "served_rows"):
                assert key in body, key
            json.dumps(body)  # the document must be JSON-clean
        finally:
            tenancy.uninstall()
        assert _debug_tenancy()["enabled"] is False


# -- lock order ---------------------------------------------------------

class TestLockOrder:
    def test_clean_over_fleet_cycle(self, tmp_path):
        """Satellite [ISSUE 17]: the lock-order detector over a full
        fleet cycle — admission, WFQ dispatch, residency demote AND
        restore (which takes registry → executor → program-cache
        under the residency lock) — must close no cycle."""
        from spark_bagging_tpu.analysis import locks

        locks.clear()
        locks.enable(True)
        try:
            plane = capacity_mod.CapacityPlane()
            prev = capacity_mod.install(plane)
            try:
                specs = [
                    TenantSpec(name="t0", quota_rps=100.0),
                    TenantSpec(name="t1", priority="batch"),
                ]
                reg = ModelRegistry(min_bucket_rows=8,
                                    max_batch_rows=16)
                fleet = TenantFleet(specs, registry=reg,
                                    residency_capacity=1,
                                    aot_root=str(tmp_path),
                                    plane=plane)
                for i in range(2):
                    fleet.register(f"t{i}", _fit(seed=i),
                                   warmup=True, version=1)
                X = np.asarray(_problem(seed=3)[0][:8])
                for step, name in enumerate(("t0", "t1", "t0")):
                    fleet.submit(name, X, now=float(step))
                    fleet.dispatch(now=float(step))
                fleet.refit_allowed("t0", 3.0)
                fleet.close()
            finally:
                capacity_mod.install(prev)
            assert locks.violations() == [], locks.violations()
            edges = locks.acquisition_edges()
            # the documented residency-first order: downstream locks
            # never wrap back around the tenancy locks
            for down in ("serving.registry", "serving.executor.build",
                         "serving.program_cache"):
                assert (down, "tenancy.residency") not in edges
        finally:
            locks.enable(False)
            locks.clear()


# -- the replay drill gate ---------------------------------------------

class TestTenantsDrill:
    @pytest.mark.slow  # [PR 20 budget offset] ~4.1s in-process drill twin; the fleet drill gate stays tier-1 via the multi-tenant-zipf registered scenario in the conformance smoke
    def test_drill_gate_in_process(self):
        """The scenario gate's in-process twin: a tiny fleet through
        ``replay_median(tenants=True, repeats=2)`` — cross-repeat byte
        identity asserted by the harness — must pass ``check_report``
        with demote/restore round-trips, zero post-warmup compiles,
        and a reconciled ledger."""
        from benchmarks import replay as R
        from spark_bagging_tpu.telemetry import workload as workload_mod

        wl = workload_mod.synthetic_workload(
            "poisson", rate_rps=150.0, duration_s=0.3, seed=110,
            width=8, bucket_bounds=(8, 32),
        )
        report = R.replay_median(
            wl, repeats=2, tenants=True,
            n_tenants=3, residency_capacity=2, zipf_s=1.1,
            width=8, n_estimators=2, seed=110,
            min_bucket_rows=8, bucket_max_rows=32,
        )
        result = R.check_report(report)
        assert result.ok, result.render()
        t = report["tenants"]
        assert t["demotions"] >= 1 and t["restores"] >= 1
        assert t["served_tenants"] == 3
        assert report["post_warmup_compiles"] == 0
        assert t["reconciled"] is True
        # the head tenant's quota sheds are its problem alone
        for name in t["sheds_by_tenant"]:
            assert name == "t0"

    def test_cli_flag_validation(self):
        from benchmarks import replay as R

        with pytest.raises(SystemExit):
            R.main(["--tenants", "4", "--churn"])
        with pytest.raises(SystemExit):
            R.main(["--tenants", "4", "--fleet", "2"])
        with pytest.raises(SystemExit):
            R.main(["--tenants", "4", "--mode", "timed"])
        with pytest.raises(SystemExit):
            R.main(["--tenants", "4", "--model-checkpoint", "/x"])


# -- the two-process soak ----------------------------------------------

_PEER_SCRIPT = """
import sys, time
sys.path.insert(0, {repo!r})
from spark_bagging_tpu.serving import ModelRegistry

reg = ModelRegistry()
deadline = time.time() + 90.0
ver = None
while time.time() < deadline:
    try:
        reg.load("m", {ckpt!r}, warm=False)
        ver = reg.version("m")
        if ver == 2:
            break
    except Exception:
        pass  # mid-publish: retry until the manifest commits
    time.sleep(0.2)
print("CONVERGED", ver)
sys.exit(0 if ver == 2 else 1)
"""


@pytest.mark.slow  # ~20s: a REAL second jax process (the PR 15
# follow-on soak) poll-load()ing the published manifests — process
# startup + two fits dominate, nothing here belongs in tier-1
def test_two_process_manifest_soak(tmp_path):
    """Satellite [ISSUE 17, PR 15 follow-on]: registry.save publishes
    a manifest a PEER PROCESS converges on by polling load() — v1
    adopted, the v2 re-publish picked up (idempotent re-loads in
    between), the peer exiting only once it serves the published
    version."""
    ckpt = str(tmp_path / "ckpt")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", _fit(seed=0), warmup=False)
    reg.save("m", ckpt, executables=False)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _PEER_SCRIPT.format(repo=REPO, ckpt=ckpt)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env, cwd=str(tmp_path),
    )
    try:
        # let the peer adopt v1 (idempotent re-loads), then publish v2
        time.sleep(2.0)
        reg.swap("m", _fit(seed=1), warm=False)
        assert reg.version("m") == 2
        reg.save("m", ckpt, executables=False)
        out, err = proc.communicate(timeout=120)
    except Exception:
        proc.kill()
        raise
    assert proc.returncode == 0, (out, err)
    assert "CONVERGED 2" in out


def test_zz_tenancy_suite_under_budget(_module_clock):
    """Tier-1 allowance for this module (the ratchet discipline): the
    heavyweight soak is slow-marked; what remains is unit coverage
    plus one tiny in-process drill."""
    elapsed = time.perf_counter() - _module_clock
    assert elapsed < 40.0, (
        f"tests/test_tenancy.py took {elapsed:.1f}s; move the "
        "offender to -m slow or shrink it"
    )
