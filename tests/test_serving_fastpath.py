"""Serving hot-path tests [ISSUE 7]: ragged pack planning, the
row-offset scatter, adaptive direct dispatch (bitwise parity, the
direct->coalesced->direct flip, error delivery), AOT executable
persistence (save / fresh reload / zero compiles without tracing), and
the replay padding-waste gate against the committed pre-change
baseline.

The invariant carried over from ISSUE 2: whatever path a request takes
— direct inline, coalesced worker, single slab or a ragged multi-slab
pack — its result must be BITWISE-equal to the batch
``predict``/``predict_proba`` of exactly its rows.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.serving import (
    EnsembleExecutor,
    MicroBatcher,
    ModelRegistry,
    pack_plan,
)
from spark_bagging_tpu.serving import program_cache

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
BASELINE = os.path.join(REPO, "benchmarks", "baselines",
                        "replay_smoke_baseline.json")


def _counter(name: str) -> float:
    return telemetry.registry().counter(name).value


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(256, 10)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int64)
    return X, y


@pytest.fixture(scope="module")
def clf(data):
    X, y = data
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=4),
        n_estimators=6, seed=0,
    ).fit(X, y)


@pytest.fixture(scope="module")
def executor(clf):
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=64)
    ex.warmup()
    return ex


# -- ragged pack planning ----------------------------------------------

def test_pack_plan_rungs_and_padding():
    # decomposition engages when it saves >= a quarter of the single
    # bucket's rows; ties and near-ties keep the single launch
    assert pack_plan(20, 8, 64) == (16, 8)   # pad 4, not 12
    assert pack_plan(24, 8, 64) == (16, 8)   # pad 0, not 8
    assert pack_plan(17, 8, 64) == (16, 8)   # pad 7, not 15
    assert pack_plan(13, 8, 64) == (16,)     # tie -> one launch
    assert pack_plan(5, 8, 64) == (8,)
    assert pack_plan(64, 8, 64) == (64,)
    # equal tail rungs re-merge ([32, 8, 8] -> [32, 16])
    assert pack_plan(44, 8, 64) == (32, 16)
    # ... cascading all the way back to the single bucket
    assert pack_plan(60, 8, 64) == (64,)
    # oversize rows still emit full top-rung slabs first
    assert pack_plan(100, 8, 64) == (64, 32, 8)
    assert pack_plan(130, 8, 64) == (64, 64, 8)
    with pytest.raises(ValueError):
        pack_plan(0)


def test_pack_plan_invariants_exhaustive():
    """Every plan uses ladder rungs only (the zero-recompile universe),
    covers n, never pads more than the single-bucket plan, and keeps
    only its last slab partial."""
    from spark_bagging_tpu.serving.buckets import bucket_ladder

    for lo, hi in ((8, 64), (1, 128), (16, 16)):
        ladder = set(bucket_ladder(lo, hi))
        top = max(ladder)
        for n in range(1, 400):
            plan = pack_plan(n, lo, hi)
            assert all(b in ladder for b in plan), (n, plan)
            assert sum(plan) >= n
            naive_pad = (-n) % top if n > top else (
                min(b for b in ladder if b >= n) - n
            )
            assert sum(plan) - n <= naive_pad, (n, plan)
            # fill rule: all slabs except the last are full
            remaining = n
            for b in plan[:-1]:
                assert remaining >= b, (n, plan)
                remaining -= b


def test_ragged_parts_bitwise_parity(clf, executor, data):
    """forward_parts packs blocks into shared slabs (some spanning slab
    boundaries); every block's output must equal its own batch
    predict_proba bitwise."""
    X, _ = data
    for sizes in ((1,), (3, 5), (12, 8), (5, 7, 20), (20, 44),
                  (1, 1, 1, 1, 1), (30, 40, 50)):
        parts, off = [], 0
        for s in sizes:
            parts.append(X[off:off + s])
            off += s
        outs = executor.forward_parts(parts)
        assert len(outs) == len(parts)
        for p, o in zip(parts, outs):
            np.testing.assert_array_equal(o, clf.predict_proba(p))


def test_ragged_pack_reduces_padding(clf, data):
    """The waste counter is the point: a 20-row batch must pad 4 rows
    ([16, 8]), not 12 ([32])."""
    X, _ = data
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=64)
    ex.warmup()
    before = _counter("sbt_serving_padding_rows_total")
    ex.forward(X[:20])
    assert _counter("sbt_serving_padding_rows_total") - before == 4


def test_forward_parts_empty_and_single(clf, executor, data):
    X, _ = data
    assert executor.forward_parts([]) == []
    (out,) = executor.forward_parts([X[:9]])
    np.testing.assert_array_equal(out, clf.predict_proba(X[:9]))


# -- adaptive direct dispatch ------------------------------------------

def test_direct_dispatch_bitwise_parity(clf, executor, data):
    """Closed-loop sequential submits earn direct mode; results stay
    bitwise-equal to batch predict_proba/predict, and the breakdown
    names the path."""
    X, _ = data
    d0 = _counter("sbt_serving_direct_dispatch_total")
    with MicroBatcher(executor, max_delay_ms=2) as b:
        futs = []
        for i in range(16):
            f = b.submit(X[i:i + 3])
            np.testing.assert_array_equal(
                f.result(30), clf.predict_proba(X[i:i + 3])
            )
            futs.append(f)
        np.testing.assert_array_equal(
            b.predict(X[:5]), clf.predict(X[:5])
        )
    assert _counter("sbt_serving_direct_dispatch_total") > d0
    # once direct mode engaged, breakdowns carry the path + bucket
    direct_bds = [
        f.trace.breakdown for f in futs
        if f.trace is not None
        and f.trace.breakdown.get("path") == "direct"
    ]
    assert direct_bds, "no request took the direct path"
    for bd in direct_bds:
        assert bd["batch_size"] == 1
        assert bd["bucket"] == 8  # 3 rows -> bucket 8
        assert bd["queue_ms"] >= 0 and bd["total_ms"] > 0


def test_direct_mode_is_earned_not_assumed(executor, data):
    """A fresh batcher must NOT serve inline before the singleton
    streak proves there is nobody to coalesce with — a single-threaded
    async dispatcher would be serialized otherwise."""
    X, _ = data
    with MicroBatcher(executor, max_delay_ms=2) as b:
        streak_needed = b.DIRECT_AFTER_SINGLETONS
        d0 = _counter("sbt_serving_direct_dispatch_total")
        c0 = _counter("sbt_serving_coalesced_total")
        for i in range(streak_needed):
            b.submit(X[i:i + 1]).result(30)
        # the earn-in window went through the coalescer...
        assert _counter("sbt_serving_coalesced_total") - c0 == streak_needed
        assert _counter("sbt_serving_direct_dispatch_total") == d0
        # ...and the request after it is served inline
        b.submit(X[:1]).result(30)
        assert _counter("sbt_serving_direct_dispatch_total") == d0 + 1


def test_direct_coalesced_direct_flip_under_contention(executor, data):
    """The adaptive loop end to end: sequential traffic earns direct,
    a concurrent burst revokes it (and coalesces), and a quiet period
    re-earns it."""
    X, _ = data
    with MicroBatcher(executor, max_delay_ms=20, max_queue=256) as b:
        # phase A: earn direct
        for i in range(b.DIRECT_AFTER_SINGLETONS + 2):
            b.submit(X[i:i + 1]).result(30)
        d_a = _counter("sbt_serving_direct_dispatch_total")
        c_a = _counter("sbt_serving_coalesced_total")
        assert b._mode_direct

        # phase B: concurrent burst -> contention revokes the mode
        gate = threading.Barrier(8)

        def client(k):
            gate.wait()
            for j in range(6):
                b.submit(X[(k * 6 + j) % 200:(k * 6 + j) % 200 + 1]) \
                    .result(30)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        c_b = _counter("sbt_serving_coalesced_total")
        assert c_b > c_a, "contended burst should coalesce"

        # phase C: quiet sequential traffic re-earns direct mode
        for i in range(b.DIRECT_AFTER_SINGLETONS + 4):
            b.submit(X[i:i + 1]).result(30)
        assert b._mode_direct
        assert _counter("sbt_serving_direct_dispatch_total") > d_a


def test_direct_path_error_delivery(clf, executor, data):
    """An inline forward failure is delivered via the future (with the
    error breakdown), counted, and does not poison the next request."""
    X, _ = data

    class _Flaky:
        task = "classification"
        n_features = clf.n_features_in_
        classes_ = clf.classes_
        boom = True

        def forward(self, Xb):
            if self.boom:
                self.boom = False
                raise RuntimeError("injected direct fault")
            return executor.forward(Xb)

    flaky = _Flaky()
    with MicroBatcher(flaky, max_delay_ms=2) as b:
        # earn direct mode on the healthy path
        flaky.boom = False
        for i in range(b.DIRECT_AFTER_SINGLETONS):
            b.submit(X[i:i + 1]).result(30)
        flaky.boom = True
        e0 = _counter("sbt_serving_batch_errors_total")
        bad = b.submit(X[:2])
        with pytest.raises(RuntimeError, match="injected direct"):
            bad.result(30)
        assert _counter("sbt_serving_batch_errors_total") == e0 + 1
        if bad.trace is not None:
            assert bad.trace.breakdown["path"] == "direct"
            assert bad.trace.breakdown["error"].startswith("RuntimeError")
        # the path survives: next submit serves fine
        good = b.submit(X[:2]).result(30)
        np.testing.assert_array_equal(good, clf.predict_proba(X[:2]))


def test_stepped_mode_rejects_direct_dispatch(executor):
    with pytest.raises(ValueError, match="direct_dispatch"):
        MicroBatcher(executor, threaded=False, direct_dispatch=True)


def test_worker_batch_holds_occupancy_slot():
    """A worker batch in flight occupies the dispatch gate: a submit
    landing mid-forward on an (empty-again) queue must never be served
    inline alongside the worker's forward — the occupancy slot is what
    lets contention revoke direct mode at concurrency 2."""

    class _Stalling:
        task = "classification"
        n_features = 10
        classes_ = np.array([0, 1])

        def __init__(self):
            self.release = threading.Event()
            self.entered = threading.Event()

        def forward(self, Xb):
            self.entered.set()
            assert self.release.wait(30)
            return np.zeros((Xb.shape[0], 2), np.float32)

    ex = _Stalling()
    b = MicroBatcher(ex, max_delay_ms=0, max_queue=8)
    try:
        fut = b.submit(np.zeros((1, 10), np.float32))
        assert ex.entered.wait(10)  # worker is mid-forward, queue empty
        with b._occ_lock:
            assert b._occupancy == 1, (
                "a worker batch must hold an occupancy slot"
            )
    finally:
        ex.release.set()
        b.close()
    assert fut.result(10).shape == (1, 2)


# -- AOT executable persistence ----------------------------------------

def test_executable_persistence_roundtrip_zero_compiles(
    clf, data, tmp_path
):
    """The instant-warm contract: save a warmed entry, load it into a
    fresh registry, and serve the whole ladder with ZERO compiles and
    no lowering (asserted by making _build explode)."""
    X, _ = data
    ckpt = str(tmp_path / "warm")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
    reg.register("m", clf, warmup=True)
    reg.save("m", ckpt)
    assert os.path.isdir(os.path.join(ckpt, "serving_aot"))

    r0 = _counter("sbt_serving_aot_restored_total")
    c0 = _counter("sbt_serving_compiles_total")
    fresh = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
    ex = fresh.load("m", ckpt, warm=True)
    assert _counter("sbt_serving_compiles_total") == c0, (
        "a warm start from a persisted cache must not compile"
    )
    assert _counter("sbt_serving_aot_restored_total") - r0 == 4
    assert ex.compiled_buckets == (8, 16, 32, 64)

    # no silent lowering either: any _build call from here is a bug
    def _no_build(bucket):
        raise AssertionError(f"_build({bucket}) called on a warm start")

    ex._build = _no_build
    for n in (1, 8, 9, 33, 64, 100):
        np.testing.assert_array_equal(
            ex.predict_proba(X[:n]), clf.predict_proba(X[:n])
        )
    assert _counter("sbt_serving_compiles_total") == c0


def test_executable_cache_key_mismatch_falls_back(clf, data, tmp_path):
    """A cache built under a different key (here: different bucket
    ladder) must be IGNORED — the executor lowers as if no cache
    existed, with a warning and a miss counted."""
    ckpt = str(tmp_path / "warm2")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
    reg.register("m", clf, warmup=True)
    reg.save("m", ckpt)

    m0 = _counter("sbt_serving_aot_misses_total")
    c0 = _counter("sbt_serving_compiles_total")
    # simulate the fresh process the disk cache exists for: the
    # in-process unified program cache would otherwise hand the
    # executables over without lowering (its job — tested elsewhere)
    program_cache.clear()
    other = ModelRegistry()
    # the serve_config manifest would hand the peer the saver's ladder
    # (the zero-config path); an EXPLICIT caller override beats it —
    # and changes the cache key, so the disk executables must be
    # ignored with a warning and a counted miss
    with pytest.warns(UserWarning, match="different key"):
        ex = other.load("m", ckpt, warm=True, max_batch_rows=128)
    assert _counter("sbt_serving_aot_misses_total") > m0
    # fell back to lowering the (8..128) ladder
    assert _counter("sbt_serving_compiles_total") - c0 == 5
    X, _ = data
    np.testing.assert_array_equal(
        ex.predict_proba(X[:9]), clf.predict_proba(X[:9])
    )


def test_corrupt_aot_manifest_is_a_miss_not_a_crash(clf, data, tmp_path):
    """Every failure mode of the executable cache is a counted MISS:
    a mangled manifest (non-dict key, malformed buckets section) must
    fall back to lowering, never crash a serving process at startup."""
    ckpt = str(tmp_path / "mangled")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
    reg.register("m", clf, warmup=True)
    reg.save("m", ckpt)
    manifest_path = os.path.join(ckpt, "serving_aot", "aot_manifest.json")
    for payload in (
        {"key": None, "buckets": {}},
        {"key": json.loads(open(manifest_path).read())["key"],
         "buckets": ["bucket_8.bin"]},
        {"key": json.loads(open(manifest_path).read())["key"],
         "buckets": {"not-a-number": "bucket_8.bin"}},
    ):
        with open(manifest_path, "w") as f:
            json.dump(payload, f)
        m0 = _counter("sbt_serving_aot_misses_total")
        fresh = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
        with pytest.warns(UserWarning):
            ex = fresh.load(f"m{m0}", ckpt, warm=True)
        assert _counter("sbt_serving_aot_misses_total") > m0
        X, _ = data
        np.testing.assert_array_equal(
            ex.predict_proba(X[:5]), clf.predict_proba(X[:5])
        )


def test_save_requires_compiled_buckets(clf, tmp_path):
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=64)
    with pytest.raises(ValueError, match="no compiled buckets"):
        ex.save_executables(str(tmp_path / "empty"))


def test_registry_save_without_executables(clf, tmp_path):
    """executables=False keeps the checkpoint weights-only; load still
    works (it just warms up by lowering)."""
    ckpt = str(tmp_path / "bare")
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
    reg.register("m", clf, warmup=True)
    reg.save("m", ckpt, executables=False)
    assert not os.path.isdir(os.path.join(ckpt, "serving_aot"))
    fresh = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
    c0 = _counter("sbt_serving_compiles_total")
    # a genuinely fresh process has no unified program cache either
    program_cache.clear()
    fresh.load("m", ckpt, warm=True)
    assert _counter("sbt_serving_compiles_total") - c0 == 4


# -- the replay padding gate vs the committed baseline -----------------

def test_replay_gate_padding_drops_vs_committed_baseline(tmp_path):
    """ISSUE 7 acceptance, both halves in one CLI run: the PR-6 replay
    gate passes against the committed PRE-change baseline (bitwise
    output digest, compile/latency/rps bands), and the padding-FLOPs
    waste ratio is STRICTLY below the baseline's (ragged packing at
    work). Budget: one subprocess, same scale as the test_replay CLI
    smoke."""
    out = str(tmp_path / "replay_report.json")
    proc = subprocess.run(
        [
            sys.executable, "-m", "benchmarks.replay",
            "--synthetic", "poisson", "--rate", "150",
            "--duration", "1.0", "--rows", "20", "--seed", "0",
            "--check", "--baseline", BASELINE, "--out", out,
        ],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        # the baseline was generated by the plain CLI: single-device
        # CPU. conftest's 8-virtual-device XLA_FLAGS would compile a
        # different program and (correctly) fail the bitwise gate, so
        # the subprocess gets the baseline's device world back.
        env={**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
             "SBT_TELEMETRY_DIR": str(tmp_path)},
    )
    # exit 0 = every gate check passed. exit 3 is the shared gate
    # contract's host-conditional band (benchmarks/BUDGETS.md): the
    # ONLY failed checks are performance bands (rps/latency vs a
    # baseline authored on a different, differently-loaded host) —
    # those bands are the CLI gate's job on a stable perf host, not
    # this tier-1 test's. A hard breach now exits 2 and fails here.
    # The change-relevant invariants (bitwise output digest, zero
    # compiles, strict padding drop) are host-independent and
    # asserted hard below.
    assert proc.returncode in (0, 3), (
        f"replay gate hard-failed:\n{proc.stdout[-3000:]}\n"
        f"{proc.stderr[-2000:]}"
    )
    report = json.loads(open(out).read())
    baseline = json.loads(open(BASELINE).read())
    host_bands = {"rps_vs_baseline", "latency_p50_vs_baseline",
                  "latency_p95_vs_baseline", "latency_p99_vs_baseline"}
    hard_failures = [
        c for c in report["slo"]["checks"]
        if not c["ok"] and c["name"] not in host_bands
    ]
    assert not hard_failures, (
        f"non-host-band gate checks failed: {hard_failures}\n"
        f"{proc.stdout[-2000:]}"
    )
    # the virtual-mode contract: identical schedule+seed+knobs ->
    # bitwise-identical outputs, before and after ragged packing
    assert report["output_digest"] == baseline["output_digest"]
    assert report["post_warmup_compiles"] == 0
    got = report["padding"]["waste_flops_frac"]
    ref = baseline["padding"]["waste_flops_frac"]
    assert got is not None and ref is not None
    assert got < ref, (
        f"padding waste must drop strictly below the pre-change "
        f"baseline ({ref}), got {got}"
    )
