"""Test harness: CPU backend as the fake device [SURVEY §4].

The analog of the reference's `local[*]` SparkSession trick: force the
CPU backend with 8 virtual XLA devices so every `shard_map`/`psum` path
is exercised without TPU hardware. The axon sitecustomize imports jax at
interpreter start, so the platform must be flipped via jax.config (env
vars are too late), and XLA_FLAGS must be appended before first backend
init (conftest import time is early enough — no device has been queried
yet).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import time

import numpy as np
import pytest


# per-module wall-clock accumulator (setup + call + teardown), fed by
# pytest_runtest_logreport below; tests/test_zz_tier_budget.py writes
# it out as telemetry_dir()/tier1_timings.json so tier-restructuring
# work (ROADMAP item 5) starts from measured data instead of
# rediscovering where the seconds go with --durations runs
_MODULE_TIMES: dict[str, float] = {}

# per-module ran/skipped counts plus the `slow`-deselected split: the
# pyramid's shape per module, so a future move-to-slow decision reads
# the artifact instead of grepping markers. A test counts once — at
# its call phase, or at setup when a skip/xfail kept call from running
_MODULE_STATS: dict[str, dict[str, int]] = {}


def _module_stats(mod: str) -> dict[str, int]:
    return _MODULE_STATS.setdefault(
        mod, {"tests": 0, "skipped": 0, "slow_deselected": 0}
    )


def pytest_configure(config):
    # session wall-clock anchor for the tier-1 budget ratchet
    # (tests/test_zz_tier_budget.py): recorded as early as pytest
    # allows so the measured elapsed covers collection + every test
    # that ran before the ratchet (which sorts last by filename under
    # the tier's -p no:randomly ordering)
    config._sbt_tier_t0 = time.monotonic()
    config._sbt_module_times = _MODULE_TIMES
    config._sbt_module_stats = _MODULE_STATS


def pytest_runtest_logreport(report):
    mod = report.nodeid.split("::", 1)[0]
    _MODULE_TIMES[mod] = (
        _MODULE_TIMES.get(mod, 0.0) + getattr(report, "duration", 0.0)
    )
    stats = _module_stats(mod)
    if report.when == "call" or (report.when == "setup"
                                 and report.skipped):
        stats["tests"] += 1
    if report.skipped:
        stats["skipped"] += 1


def pytest_deselected(items):
    # `-m 'not slow'` lands here: count the slow-marked weight each
    # module keeps OUT of the tier (other deselection reasons — -k
    # filters — are not slow weight and stay uncounted)
    for item in items:
        if item.get_closest_marker("slow") is not None:
            mod = item.nodeid.split("::", 1)[0]
            _module_stats(mod)["slow_deselected"] += 1


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session", autouse=True)
def _assert_fake_device_config():
    assert jax.default_backend() == "cpu"
    assert jax.device_count() == 8, "tests expect 8 virtual XLA CPU devices"
    yield
