"""Live exposition server [ISSUE 5]: /metrics, /healthz, /varz and the
debug endpoints scraped over real HTTP during live serving traffic —
the tier-1 smoke for the observability plane. (The sbt-lint
cleanliness of the new telemetry modules is enforced by the PR-4
self-hosting gate in tests/test_analysis.py, which lints the whole
tree.)
"""

import json
import urllib.request

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.telemetry import server as tserver
from spark_bagging_tpu.serving import ModelRegistry


def _get(port: int, path: str):
    """(status, body) — never raises on HTTP error codes."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.enable()
    tserver.clear_health_sources()
    yield
    tserver.stop_server()
    # start_server() armed the default flight recorder (dir=None →
    # ./telemetry/ under the test cwd); detach it so later test
    # modules that deliberately induce serving faults don't write
    # stray flight_*.json on every run
    telemetry.recorder.disarm()
    tserver.clear_health_sources()
    telemetry.reset()
    telemetry.enable()


@pytest.fixture(scope="module")
def clf():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(96, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    clf = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=4, seed=0,
    ).fit(X, y)
    clf._test_X = X  # stash the matching request pool on the model
    return clf


def test_server_lifecycle_and_routes():
    port = tserver.start_server(0)
    assert tserver.server_address() == ("127.0.0.1", port)
    assert tserver.start_server(0) == port  # idempotent while running
    status, body = _get(port, "/")
    assert status == 200 and "/metrics" in body
    status, _ = _get(port, "/nope")
    assert status == 404
    tserver.stop_server()
    tserver.stop_server()  # idempotent
    assert tserver.server_address() is None


def test_scrape_during_live_serving_traffic(clf):
    """The acceptance scenario: during sustained traffic a scrape
    returns live sbt_serving_* series (HELP lines included), /varz
    carries latency quantiles, /debug/spans resolves a request's
    trace, and /healthz flips unhealthy when the batcher closes."""
    X = clf._test_X
    port = tserver.start_server(0)
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=True)
    # coalesced-path series (sbt_serving_batches_total) are asserted
    # below, so pin the adaptive direct path off for this traffic
    with reg.batcher("m", max_delay_ms=2, max_queue=256,
                     direct_dispatch=False) as b:
        futs = [b.submit(X[i:i + 2]) for i in range(24)]
        # scrape WHILE requests are in flight (some may already be
        # done — "during traffic" means the process is serving)
        status, metrics = _get(port, "/metrics")
        for f in futs:
            f.result(30)
        assert status == 200
        status2, metrics2 = _get(port, "/metrics")
        assert status2 == 200
        assert "# TYPE sbt_serving_requests_total counter" in metrics2
        assert ("# HELP sbt_serving_requests_total Requests admitted"
                in metrics2)
        assert "sbt_serving_batches_total" in metrics2
        assert 'sbt_serving_model_version{model="m"} 1' in metrics2

        status, healthz = _get(port, "/healthz")
        assert status == 200
        health = json.loads(healthz)
        assert health["healthy"] is True
        batcher_sources = [
            v for k, v in health["sources"].items()
            if k.startswith("batcher")
        ]
        assert batcher_sources and batcher_sources[0]["max_queue"] == 256
        assert batcher_sources[0]["last_batch_age_s"] is not None
        registry_sources = [
            v for k, v in health["sources"].items()
            if k.startswith("model_registry")
        ]
        assert registry_sources[0]["models"] == {"m": 1}

        status, varz = _get(port, "/varz")
        v = json.loads(varz)
        assert v["health"]["healthy"] is True
        lat = [
            m for m in v["metrics"]
            if m["name"] == "sbt_serving_latency_seconds"
        ]
        assert lat and set(lat[0]["quantiles"]) == {"p50", "p95", "p99"}
        assert lat[0]["exemplars"]  # trace-id exemplars ride the scrape

        tid = futs[0].trace.trace_id
        status, spans = _get(port, f"/debug/spans?trace_id={tid}")
        names = {s["name"] for s in json.loads(spans)["spans"]}
        assert "serving_enqueue" in names
        assert "serving_batch" in names

    # batcher closed: /healthz must flip unhealthy (503 for LBs)
    status, healthz = _get(port, "/healthz")
    assert status == 503
    health = json.loads(healthz)
    assert health["healthy"] is False
    closed = [
        v for k, v in health["sources"].items()
        if k.startswith("batcher")
    ]
    assert closed[0]["closed"] is True


def test_debug_runs_lists_captures():
    port = tserver.start_server(0)
    with telemetry.capture(label="window") as run:
        with telemetry.span("x"):
            pass
        status, body = _get(port, "/debug/runs")
    runs = json.loads(body)["runs"]
    mine = [r for r in runs if r["run_id"] == run.run_id]
    assert mine and mine[0]["label"] == "window"
    assert mine[0]["active"] is True


def test_retire_leaves_healthz_while_close_poisons_it(clf):
    """close() keeps the batcher in the health set reporting unhealthy
    (the LB drain signal); retire() removes it so a same-process
    rollover to a fresh batcher doesn't 503 a healthy node."""
    X = clf._test_X
    reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
    reg.register("m", clf, warmup=False)
    old = reg.batcher("m", max_delay_ms=2, max_queue=16)
    old.submit(X[:2]).result(30)
    old.retire()  # close + leave /healthz
    fresh = reg.batcher("m", max_delay_ms=2, max_queue=16)
    try:
        report = tserver.health_report()
        assert report["healthy"] is True  # retired batcher is gone
        batcher_sources = [
            k for k in report["sources"] if k.startswith("batcher")
        ]
        assert len(batcher_sources) == 1  # only the fresh one
    finally:
        fresh.close()
    assert tserver.health_report()["healthy"] is False  # drain signal


def test_dead_health_source_disappears():
    class Box:
        def health(self):
            return {"healthy": False}

    box = Box()
    tserver.register_health_source("box", box, Box.health)
    assert tserver.health_report()["healthy"] is False
    del box  # owner collected: the ghost must not haunt /healthz
    import gc

    gc.collect()
    report = tserver.health_report()
    assert report["healthy"] is True and report["sources"] == {}


def test_broken_health_probe_reports_unhealthy_not_500():
    class Bad:
        def health(self):
            raise RuntimeError("probe broke")

    bad = Bad()
    tserver.register_health_source("bad", bad, Bad.health)
    port = tserver.start_server(0)
    status, body = _get(port, "/healthz")
    assert status == 503
    (detail,) = json.loads(body)["sources"].values()
    assert "probe broke" in detail["error"]


def test_env_opt_in(monkeypatch):
    monkeypatch.delenv("SBT_METRICS_PORT", raising=False)
    assert tserver.maybe_start_from_env() is None  # unset: no server
    assert tserver.server_address() is None
    monkeypatch.setenv("SBT_METRICS_PORT", "0")
    port = tserver.maybe_start_from_env()
    assert port is not None
    status, _ = _get(port, "/metrics")
    assert status == 200


def test_bad_env_port_warns_not_raises(monkeypatch):
    monkeypatch.setenv("SBT_METRICS_PORT", "not-a-port")
    with pytest.warns(RuntimeWarning, match="failed to start"):
        assert tserver.maybe_start_from_env() is None


def test_debug_spans_trace_filter_under_concurrent_writers():
    """PR-5 edge path: the ?trace_id= filter must never leak another
    request's spans while the span ring is being written concurrently
    — every span a filtered scrape returns belongs to the queried
    trace (by trace_id or by batch links), under sustained writes."""
    import threading

    from spark_bagging_tpu.telemetry import tracing

    port = tserver.start_server(0)  # arms the default flight recorder
    ctxs = [tracing.request_context() for _ in range(4)]
    stop = threading.Event()

    def writer(ctx):
        while not stop.is_set():
            with tracing.use(ctx):
                with telemetry.span("writer_span"):
                    pass

    threads = [threading.Thread(target=writer, args=(c,))
               for c in ctxs]
    for t in threads:
        t.start()
    try:
        tid = ctxs[0].trace_id
        saw_mine = 0
        for _ in range(25):
            status, body = _get(port, f"/debug/spans?trace_id={tid}")
            assert status == 200
            spans = json.loads(body)["spans"]
            for s in spans:
                assert (
                    s.get("trace_id") == tid
                    or tid in (s.get("links") or ())
                ), f"foreign span leaked through the filter: {s}"
            saw_mine += len(spans)
    finally:
        stop.set()
        for t in threads:
            t.join(10)
    assert saw_mine > 0, "filter returned nothing for a live writer"


def test_varz_reports_rss_and_uptime():
    port = tserver.start_server(0)
    # /metrics FIRST: a Prometheus deployment that never touches
    # /varz must still get fresh process gauges (the scrape itself
    # samples them — they cannot depend on a prior /varz call)
    status, metrics = _get(port, "/metrics")
    assert status == 200
    assert "sbt_process_rss_bytes" in metrics
    assert "sbt_process_uptime_seconds" in metrics
    assert ("# HELP sbt_process_rss_bytes Resident set size"
            in metrics)
    status, body = _get(port, "/varz")
    assert status == 200
    v = json.loads(body)
    assert v["uptime_seconds"] >= 0
    assert v["rss_bytes"] and v["rss_bytes"] > 1024 * 1024  # > 1 MiB


def test_debug_workload_route(clf):
    X = clf._test_X
    port = tserver.start_server(0)
    status, body = _get(port, "/debug/workload")
    assert status == 200
    assert json.loads(body)["recording"] is False

    telemetry.workload.record()
    try:
        reg = ModelRegistry(min_bucket_rows=8, max_batch_rows=32)
        reg.register("m", clf, warmup=True)
        with reg.batcher("m", max_delay_ms=2) as b:
            futs = [b.submit(X[i:i + 2]) for i in range(6)]
            for f in futs:
                f.result(30)
        status, body = _get(port, "/debug/workload")
    finally:
        wl = telemetry.workload.stop()
    summary = json.loads(body)
    assert summary["recording"] is True
    assert summary["n_requests"] == 6
    assert summary["total_rows"] == 12
    assert wl.n_requests == 6
    # stopped: the route reports idle again
    status, body = _get(port, "/debug/workload")
    assert json.loads(body)["recording"] is False


def test_metrics_endpoint_renders_escaped_labels():
    telemetry.set_gauge(
        "sbt_serving_model_version", 3.0,
        labels={"model": 'he said "v2"\\final'},
    )
    port = tserver.start_server(0)
    status, body = _get(port, "/metrics")
    assert status == 200
    assert (
        r'sbt_serving_model_version{model="he said \"v2\"\\final"} 3'
        in body
    )
