"""IsotonicRegression: sklearn-PAV exactness on <=B distinct values,
antitonic fits, weighted exactness, bagging integration [SURVEY §4]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_bagging_tpu import BaggingRegressor, IsotonicRegression

KEY = jax.random.key(0)


def _fit(iso, x, y, w=None):
    n = len(y)
    w = np.ones(n, np.float32) if w is None else w
    X = np.asarray(x, np.float32)[:, None]
    params, aux = iso.fit_from_init(
        KEY, jnp.asarray(X), jnp.asarray(y, np.float32),
        jnp.asarray(w, jnp.float32), 1,
    )
    return params, aux


class TestExactness:
    def test_matches_sklearn_pav_on_distinct_values(self):
        """<= n_bins distinct x values: each gets its own bin, so the
        minimax formula IS exact PAV — predictions at the training
        points must match sklearn's to fp tolerance."""
        from sklearn.isotonic import IsotonicRegression as SkIso

        rng = np.random.default_rng(0)
        xvals = np.sort(rng.choice(1000, 60, replace=False)).astype(
            np.float32
        )
        x = np.repeat(xvals, 3)
        y = (0.01 * x + rng.normal(0, 0.5, len(x))).astype(np.float32)
        iso = IsotonicRegression(n_bins=128)
        params, _ = _fit(iso, x, y)
        ours = np.asarray(
            iso.predict_scores(params, jnp.asarray(x[:, None]))
        )
        sk = SkIso().fit(x, y).predict(x)
        np.testing.assert_allclose(ours, sk, rtol=1e-4, atol=1e-4)

    def test_output_is_monotone(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=500).astype(np.float32)
        y = (np.tanh(x) + 0.3 * rng.normal(size=500)).astype(np.float32)
        iso = IsotonicRegression(n_bins=64)
        params, _ = _fit(iso, x, y)
        grid = np.linspace(x.min(), x.max(), 400, dtype=np.float32)
        pred = np.asarray(
            iso.predict_scores(params, jnp.asarray(grid[:, None]))
        )
        assert np.all(np.diff(pred) >= -1e-5)

    def test_antitonic(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=400).astype(np.float32)
        y = (-x + 0.2 * rng.normal(size=400)).astype(np.float32)
        iso = IsotonicRegression(n_bins=64, increasing=False)
        params, _ = _fit(iso, x, y)
        grid = np.linspace(-2, 2, 200, dtype=np.float32)
        pred = np.asarray(
            iso.predict_scores(params, jnp.asarray(grid[:, None]))
        )
        assert np.all(np.diff(pred) <= 1e-5)
        assert np.corrcoef(pred, -grid)[0, 1] > 0.99

    def test_weighted_equals_duplicated(self):
        rng = np.random.default_rng(3)
        xvals = np.arange(40, dtype=np.float32)
        y = (xvals * 0.1 + rng.normal(0, 0.3, 40)).astype(np.float32)
        k = rng.poisson(1.0, 40) + 1
        # n_bins >= the duplicated row count: every distinct value gets
        # its own bin in BOTH fits (edge positions are unweighted order
        # statistics, the documented binning semantic), isolating the
        # weighted-statistics exactness being tested
        iso = IsotonicRegression(n_bins=256)
        pw, _ = _fit(iso, xvals, y, k.astype(np.float32))
        pd, _ = _fit(
            iso, np.repeat(xvals, k), np.repeat(y, k)
        )
        grid = jnp.asarray(xvals[:, None])
        np.testing.assert_allclose(
            np.asarray(iso.predict_scores(pw, grid)),
            np.asarray(iso.predict_scores(pd, grid)),
            rtol=1e-4, atol=1e-4,
        )

    def test_validation(self):
        with pytest.raises(ValueError, match="n_bins"):
            IsotonicRegression(n_bins=1)


class TestIntegration:
    @pytest.mark.slow  # [PR 14 pyramid] ~1.8s isotonic integration soak; PAV exactness stays tier-1
    def test_bagged_isotonic(self):
        rng = np.random.default_rng(4)
        X = rng.normal(size=(600, 1)).astype(np.float32)
        y = (np.tanh(2 * X[:, 0]) + 0.3 * rng.normal(size=600)).astype(
            np.float32
        )
        reg = BaggingRegressor(
            base_learner=IsotonicRegression(n_bins=64),
            n_estimators=16, seed=0, oob_score=True,
        ).fit(X, y)
        assert reg.score(X, y) > 0.7
        assert np.isfinite(reg.oob_score_)

    def test_vmap_over_replicas(self):
        rng = np.random.default_rng(5)
        X = jnp.asarray(rng.normal(size=(100, 1)).astype(np.float32))
        y = jnp.asarray(X[:, 0] * 2)
        iso = IsotonicRegression(n_bins=32)
        keys = jax.random.split(KEY, 4)
        vals = jax.vmap(
            lambda kk: iso.fit_from_init(
                kk, X, y, jnp.ones(100), 1
            )[0]["values"]
        )(keys)
        assert vals.shape == (4, 32)
        assert np.isfinite(np.asarray(vals)).all()

    @pytest.mark.slow  # [PR 14 pyramid] ~1.1s per-model checkpoint twin; generic round-trip stays tier-1 in test_checkpoint
    def test_checkpoint_roundtrip(self, tmp_path):
        from spark_bagging_tpu import load_model, save_model

        rng = np.random.default_rng(6)
        X = rng.normal(size=(200, 1)).astype(np.float32)
        y = np.abs(X[:, 0]).astype(np.float32)
        reg = BaggingRegressor(
            base_learner=IsotonicRegression(n_bins=32),
            n_estimators=4, seed=0,
        ).fit(X, y)
        save_model(reg, str(tmp_path / "iso"))
        reg2 = load_model(str(tmp_path / "iso"))
        np.testing.assert_allclose(
            reg.predict(X[:50]), reg2.predict(X[:50]), rtol=1e-6
        )
