"""Base-learner tests: weighted-fit exactness, sklearn parity, vmap-ability
[SURVEY §4, §7 hard-part 2]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_iris
from sklearn.linear_model import LogisticRegression as SkLogReg
from sklearn.linear_model import Ridge
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu.models import LinearRegression, LogisticRegression

KEY = jax.random.key(0)


def _breast_cancer():
    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y, jnp.int32), X, y


def _iris():
    X, y = load_iris(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(y, jnp.int32), X, y


class TestLogisticRegression:
    def test_binary_matches_sklearn(self):
        Xj, yj, X, y = _breast_cancer()
        lr = LogisticRegression(l2=1e-3, max_iter=15)
        params, aux = lr.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 2)
        acc = (np.asarray(lr.predict_scores(params, Xj).argmax(1)) == y).mean()
        sk_acc = SkLogReg(C=1 / (1e-3 * len(y)), max_iter=2000).fit(X, y).score(X, y)
        assert acc > 0.97
        assert abs(acc - sk_acc) < 0.01

    def test_multiclass(self):
        Xj, yj, X, y = _iris()
        lr = LogisticRegression()
        params, aux = lr.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)
        acc = (np.asarray(lr.predict_scores(params, Xj).argmax(1)) == y).mean()
        assert acc > 0.95

    def test_loss_curve_decreases(self):
        Xj, yj, X, y = _iris()
        lr = LogisticRegression()
        _, aux = lr.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)
        curve = np.asarray(aux["loss_curve"])
        assert curve[0] == pytest.approx(np.log(3), rel=1e-3)  # zero-init NLL
        assert np.all(np.diff(curve) <= 1e-6)

    def test_poisson_weights_equal_duplicated_rows(self):
        """The weighted-fit exactness requirement [SURVEY §7 hard-part 2]:
        Poisson counts as weights must equal physically duplicating rows."""
        Xj, yj, X, y = _iris()
        rng = np.random.default_rng(3)
        w = rng.poisson(1.0, len(y)).astype(np.float32)
        lr = LogisticRegression(max_iter=25)
        pw, _ = lr.fit_from_init(KEY, Xj, yj, jnp.asarray(w), 3)
        Xd = np.repeat(X, w.astype(int), axis=0)
        yd = np.repeat(y, w.astype(int))
        pdup, _ = lr.fit_from_init(
            KEY, jnp.asarray(Xd), jnp.asarray(yd, jnp.int32),
            jnp.ones(len(yd)), 3,
        )
        pred_w = np.asarray(lr.predict_scores(pw, Xj).argmax(1))
        pred_d = np.asarray(lr.predict_scores(pdup, Xj).argmax(1))
        np.testing.assert_array_equal(pred_w, pred_d)

    def test_zero_weight_rows_are_ignored(self):
        Xj, yj, X, y = _iris()
        w = np.ones(len(y), np.float32)
        w[y == 2] = 0.0  # drop class 2 entirely
        lr = LogisticRegression(max_iter=25)
        params, _ = lr.fit_from_init(KEY, Xj, yj, jnp.asarray(w), 3)
        pred = np.asarray(lr.predict_scores(params, Xj).argmax(1))
        assert not np.any(pred == 2)

    def test_adam_solver(self):
        Xj, yj, X, y = _breast_cancer()
        lr = LogisticRegression(solver="adam", max_iter=150, lr=0.3)
        params, aux = lr.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 2)
        acc = (np.asarray(lr.predict_scores(params, Xj).argmax(1)) == y).mean()
        assert acc > 0.95

    def test_unknown_solver_raises(self):
        Xj, yj, _, y = _iris()
        lr = LogisticRegression(solver="sgd")
        with pytest.raises(ValueError, match="solver"):
            lr.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)

    def test_vmap_over_replicas(self):
        Xj, yj, X, y = _iris()
        lr = LogisticRegression(max_iter=5)
        rng = np.random.default_rng(0)
        ws = jnp.asarray(rng.poisson(1.0, (4, len(y))).astype(np.float32))
        keys = jax.vmap(lambda i: jax.random.fold_in(KEY, i))(jnp.arange(4))
        params, aux = jax.vmap(
            lambda k, w: lr.fit_from_init(k, Xj, yj, w, 3)
        )(keys, ws)
        assert params["W"].shape == (4, Xj.shape[1] + 1, 3)
        assert aux["loss"].shape == (4,)
        # replicas differ
        assert not np.allclose(np.asarray(params["W"][0]), np.asarray(params["W"][1]))


class TestLinearRegression:
    def test_matches_ridge_closed_form(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 12)).astype(np.float32)
        beta = rng.normal(size=12)
        y = (X @ beta + 0.1 * rng.normal(size=300)).astype(np.float32)
        lin = LinearRegression(l2=1e-6)
        params, aux = lin.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(300), 1
        )
        sk = Ridge(alpha=1e-6 * 300).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(params["beta"][:-1]), sk.coef_, atol=1e-3
        )
        np.testing.assert_allclose(
            float(params["beta"][-1]), sk.intercept_, atol=1e-3
        )

    def test_weighted_equals_duplicated(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(100, 5)).astype(np.float32)
        y = (X.sum(1) + 0.1 * rng.normal(size=100)).astype(np.float32)
        w = rng.poisson(1.0, 100).astype(np.float32)
        lin = LinearRegression()
        pw, _ = lin.fit_from_init(KEY, jnp.asarray(X), jnp.asarray(y), jnp.asarray(w), 1)
        Xd = np.repeat(X, w.astype(int), axis=0)
        yd = np.repeat(y, w.astype(int))
        pdup, _ = lin.fit_from_init(
            KEY, jnp.asarray(Xd), jnp.asarray(yd), jnp.ones(len(yd)), 1
        )
        np.testing.assert_allclose(
            np.asarray(pw["beta"]), np.asarray(pdup["beta"]), atol=1e-3
        )

    def test_predict_scores_shape(self):
        X = jnp.ones((7, 3))
        lin = LinearRegression()
        params = {"beta": jnp.arange(4.0)}
        assert lin.predict_scores(params, X).shape == (7,)


class TestLearnerProtocol:
    def test_hash_eq_by_hyperparams(self):
        assert LogisticRegression(l2=0.1) == LogisticRegression(l2=0.1)
        assert LogisticRegression(l2=0.1) != LogisticRegression(l2=0.2)
        assert hash(LogisticRegression()) == hash(LogisticRegression())

    def test_get_set_params(self):
        lr = LogisticRegression()
        lr.set_params(l2=0.5, max_iter=3)
        assert lr.get_params()["l2"] == 0.5
        clone = lr.clone()
        assert clone == lr and clone is not lr

    def test_invalid_param_raises(self):
        with pytest.raises(ValueError, match="Invalid parameter"):
            LogisticRegression().set_params(bogus=1)


def test_newton_row_tile_matches_single_pass():
    """row_tile bounds peak memory; the accumulated statistics must be
    bitwise-equivalent math (same update, same loss) [VERDICT r1 #3]."""
    rng = np.random.default_rng(7)
    n, F, C = 500, 9, 3
    X = rng.standard_normal((n, F)).astype(np.float32)
    y = (X @ rng.standard_normal((F, C))).argmax(1)
    w = rng.poisson(1.0, n).astype(np.float32)
    key = jax.random.key(0)
    base = LogisticRegression(max_iter=4)
    tiled = LogisticRegression(max_iter=4, row_tile=128)  # pads 500->512
    p0 = base.init_params(key, F, C)
    pb, ab = jax.jit(
        lambda p: base.fit(p, jnp.asarray(X), jnp.asarray(y),
                           jnp.asarray(w), key)
    )(p0)
    pt, at = jax.jit(
        lambda p: tiled.fit(p, jnp.asarray(X), jnp.asarray(y),
                            jnp.asarray(w), key)
    )(p0)
    np.testing.assert_allclose(pb["W"], pt["W"], rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(ab["loss"], at["loss"], rtol=1e-5)


@pytest.mark.slow  # [PR 14 pyramid] ~2.4s tiling integration soak; row-tile kernel correctness stays tier-1 direct
def test_row_tile_in_ensemble():
    from spark_bagging_tpu import BaggingClassifier

    rng = np.random.default_rng(8)
    X = rng.standard_normal((300, 6)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    a = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3), n_estimators=8,
        seed=0,
    ).fit(X, y)
    b = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3, row_tile=64),
        n_estimators=8, seed=0,
    ).fit(X, y)
    np.testing.assert_allclose(
        a.predict_proba(X), b.predict_proba(X), rtol=1e-3, atol=1e-5
    )


def test_flops_models_exist():
    from spark_bagging_tpu.models import (
        DecisionTreeClassifier,
        DecisionTreeRegressor,
        MLPClassifier,
        MLPRegressor,
    )

    for learner, n_out in [
        (LogisticRegression(), 3),
        (LogisticRegression(solver="adam"), 3),
        (LinearRegression(), 1),
        (MLPClassifier(), 3),
        (MLPRegressor(), 1),
        (DecisionTreeClassifier(), 3),
        (DecisionTreeRegressor(), 1),
    ]:
        f = learner.flops_per_fit(1000, 10, n_out)
        assert f is not None and f > 0


def test_fused_hessian_matches_blocked():
    """One rank-factorized (C·d, n)@(n, C·d) matmul must assemble the
    exact Hessian the C²/2-block loop does (same FLOPs, O(1) program
    size for large C) [VERDICT r1 weak#9]."""
    Xj, yj, _, y = _iris()
    w = jnp.asarray(np.random.default_rng(0).poisson(1.0, len(y)), jnp.float32)
    for row_tile in (None, 64):
        blocked = LogisticRegression(hessian_impl="blocked", row_tile=row_tile)
        fused = LogisticRegression(hessian_impl="fused", row_tile=row_tile)
        pb, ab = blocked.fit_from_init(KEY, Xj, yj, w, 3)
        pf, af = fused.fit_from_init(KEY, Xj, yj, w, 3)
        np.testing.assert_allclose(
            np.asarray(pb["W"]), np.asarray(pf["W"]), rtol=1e-3, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(ab["loss"]), np.asarray(af["loss"]), rtol=1e-5
        )


@pytest.mark.slow  # [PR 14 pyramid] ~1.7s wide-class sweep; fused-vs-blocked parity stays tier-1
def test_fused_hessian_many_classes():
    """auto resolves to fused past C=8; a 12-class fit must train and
    match the blocked assembly."""
    rng = np.random.default_rng(1)
    C, n, F = 12, 600, 10
    centers = rng.normal(0, 3.0, (C, F)).astype(np.float32)
    y = np.repeat(np.arange(C), n // C)
    X = centers[y] + rng.normal(0, 1.0, (n, F)).astype(np.float32)
    Xj, yj = jnp.asarray(X), jnp.asarray(y, jnp.int32)
    auto = LogisticRegression(max_iter=8)
    assert auto._resolved_hessian(C) == "fused"
    pa, _ = auto.fit_from_init(KEY, Xj, yj, jnp.ones(n), C)
    pb, _ = LogisticRegression(max_iter=8, hessian_impl="blocked").fit_from_init(
        KEY, Xj, yj, jnp.ones(n), C
    )
    acc = (np.asarray(auto.predict_scores(pa, Xj).argmax(1)) == y).mean()
    assert acc > 0.9
    np.testing.assert_allclose(
        np.asarray(pa["W"]), np.asarray(pb["W"]), rtol=2e-3, atol=2e-4
    )


def test_invalid_hessian_impl_raises():
    with pytest.raises(ValueError, match="hessian_impl"):
        LogisticRegression(hessian_impl="bogus")


class TestGaussianNB:
    def test_matches_sklearn(self):
        from sklearn.naive_bayes import GaussianNB as SkGNB

        from spark_bagging_tpu.models import GaussianNB

        Xj, yj, X, y = _iris()
        nb = GaussianNB()
        params, aux = nb.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)
        sk = SkGNB().fit(X, y)
        np.testing.assert_allclose(
            np.asarray(params["shift"][None, :] + params["mean"]),
            sk.theta_, rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(params["var"]), sk.var_, rtol=1e-3, atol=1e-5
        )
        pred = np.asarray(nb.predict_scores(params, Xj).argmax(1))
        assert (pred == sk.predict(X)).mean() > 0.99
        assert np.isfinite(float(aux["loss"]))

    def test_weighted_equals_duplicated(self):
        from spark_bagging_tpu.models import GaussianNB

        Xj, yj, X, y = _iris()
        k = np.asarray([1, 2, 3] * 50)
        nb = GaussianNB()
        pw, _ = nb.fit_from_init(KEY, Xj, yj, jnp.asarray(k, jnp.float32), 3)
        pd, _ = nb.fit_from_init(
            KEY, jnp.asarray(np.repeat(X, k, axis=0)),
            jnp.asarray(np.repeat(y, k), jnp.int32),
            jnp.ones(int(k.sum())), 3,
        )
        np.testing.assert_allclose(
            np.asarray(pw["shift"][None, :] + pw["mean"]),
            np.asarray(pd["shift"][None, :] + pd["mean"]),
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(pw["var"]), np.asarray(pd["var"]), rtol=1e-3,
            atol=1e-6,
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~2.9s GaussianNB bagging+mesh integration soak; NB fit invariants stay tier-1 via the fuzz battery
    def test_in_bagging_ensemble_and_mesh(self):
        from spark_bagging_tpu import BaggingClassifier, make_mesh
        from spark_bagging_tpu.models import GaussianNB

        Xj, yj, X, y = _breast_cancer()
        clf = BaggingClassifier(
            base_learner=GaussianNB(), n_estimators=16, seed=0,
            oob_score=True, max_features=0.7,
        ).fit(X, y)
        assert clf.score(X, y) > 0.9
        assert clf.oob_score_ > 0.88
        # data-sharded fit must reproduce single-device stats exactly
        # with deterministic weights (bootstrap=False, full sample)
        mesh = make_mesh(data=8)
        a = BaggingClassifier(
            base_learner=GaussianNB(), n_estimators=1, bootstrap=False,
            seed=0, mesh=mesh,
        ).fit(X, y)
        b = BaggingClassifier(
            base_learner=GaussianNB(), n_estimators=1, bootstrap=False,
            seed=0,
        ).fit(X, y)
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), rtol=1e-4, atol=1e-5
        )

    def test_large_offset_variance_stable(self):
        """Raw E[x²]−μ² cancels catastrophically in f32 at offset ~1e6;
        the shifted-moment form must keep variances accurate."""
        from spark_bagging_tpu.models import GaussianNB

        rng = np.random.default_rng(0)
        n = 400
        y = np.repeat(np.array([0, 1]), n // 2)
        X = (1e6 + 2.0 * y[:, None]
             + rng.standard_normal((n, 3))).astype(np.float32)
        nb = GaussianNB()
        params, _ = nb.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(n), 2,
        )
        var = np.asarray(params["var"])
        np.testing.assert_allclose(var, 1.0, rtol=0.35)
        pred = np.asarray(nb.predict_scores(params, jnp.asarray(X)).argmax(1))
        assert (pred == y).mean() > 0.8


class TestLinearSVC:
    def test_binary_matches_sklearn(self):
        from sklearn.svm import LinearSVC as SkSVC

        from spark_bagging_tpu.models import LinearSVC

        Xj, yj, X, y = _breast_cancer()
        l2 = 1e-3
        svc = LinearSVC(l2=l2, max_iter=8)
        params, aux = svc.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 2)
        sk = SkSVC(loss="squared_hinge", dual=False,
                   C=1.0 / (l2 * len(y))).fit(X, y)
        ours = np.asarray(svc.predict_scores(params, Xj).argmax(1))
        assert (ours == sk.predict(X)).mean() > 0.98
        assert np.isfinite(float(aux["loss"]))

    def test_multiclass_ovr(self):
        from spark_bagging_tpu.models import LinearSVC

        Xj, yj, X, y = _iris()
        svc = LinearSVC(l2=1e-3, max_iter=8)
        params, _ = svc.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)
        acc = (np.asarray(svc.predict_scores(params, Xj).argmax(1)) == y).mean()
        assert acc > 0.9

    def test_loss_curve_monotone(self):
        from spark_bagging_tpu.models import LinearSVC

        Xj, yj, _, y = _iris()
        svc = LinearSVC(l2=1e-3, max_iter=6)
        _, aux = svc.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)
        curve = np.asarray(aux["loss_curve"])
        assert np.all(np.diff(curve) <= 1e-6)
        assert float(aux["loss"]) <= curve[0] + 1e-6

    @pytest.mark.slow  # 100 sequential tiny fits ≈ 50s: the single
    # largest non-example sink in the tier-1 window; full runs keep it
    def test_no_newton_cycling_on_tiny_bags(self):
        """Full undamped Newton steps on the squared hinge can cycle
        permanently on tiny problems (active-set flips) — the regime
        small Poisson bootstrap bags produce. The line search must keep
        every iterate monotone and the result independent of max_iter
        parity."""
        from spark_bagging_tpu.models import LinearSVC

        rng = np.random.default_rng(0)
        for trial in range(100):
            Xs = rng.normal(0, 3, (12, 3)).astype(np.float32)
            ys = rng.integers(0, 2, 12).astype(np.int32)
            if len(np.unique(ys)) < 2:
                continue
            svc = LinearSVC(l2=1e-4, max_iter=12)
            _, aux = svc.fit_from_init(
                KEY, jnp.asarray(Xs), jnp.asarray(ys), jnp.ones(12), 2
            )
            curve = np.asarray(aux["loss_curve"])
            assert np.all(np.diff(curve) <= 1e-5), (trial, curve)

    @pytest.mark.slow  # [PR 14 pyramid] ~2.5s SVC weight-duplication soak; the weighted==duplicated property stays tier-1 via cheaper reps
    def test_poisson_weights_equal_duplicated_rows(self):
        from spark_bagging_tpu.models import LinearSVC

        Xj, yj, X, y = _iris()
        rng = np.random.default_rng(1)
        k = rng.poisson(1.0, len(y))
        k[:3] = [1, 2, 3]  # nonzero rows exist
        svc = LinearSVC(l2=1e-3, max_iter=8)
        pw, _ = svc.fit_from_init(
            KEY, Xj, yj, jnp.asarray(k, jnp.float32), 3
        )
        pd, _ = svc.fit_from_init(
            KEY, jnp.asarray(np.repeat(X, k, axis=0)),
            jnp.asarray(np.repeat(y, k), jnp.int32),
            jnp.ones(int(k.sum())), 3,
        )
        np.testing.assert_allclose(
            np.asarray(pw["W"]), np.asarray(pd["W"]), rtol=1e-3, atol=1e-4
        )

    def test_vmap_over_replicas(self):
        from spark_bagging_tpu.models import LinearSVC

        Xj, yj, _, y = _iris()
        svc = LinearSVC(max_iter=3)
        keys = jax.random.split(KEY, 4)
        W = jax.vmap(
            lambda kk: svc.fit_from_init(
                kk, Xj, yj, jnp.ones(len(y)), 3
            )[0]["W"]
        )(keys)
        assert W.shape == (4, Xj.shape[1] + 1, 3)
        assert np.isfinite(np.asarray(W)).all()

    @pytest.mark.slow  # [PR 14 pyramid] ~4.9s SVC bagging+mesh integration soak; SVC kernel correctness stays tier-1 direct
    def test_in_bagging_ensemble_and_mesh(self):
        from spark_bagging_tpu import BaggingClassifier, make_mesh
        from spark_bagging_tpu.models import LinearSVC

        Xj, yj, X, y = _breast_cancer()
        clf = BaggingClassifier(
            base_learner=LinearSVC(max_iter=6), n_estimators=16, seed=0,
            oob_score=True,
        ).fit(X, y)
        assert clf.score(X, y) > 0.95
        assert clf.oob_score_ > 0.9
        mesh = make_mesh(data=8)
        a = BaggingClassifier(
            base_learner=LinearSVC(max_iter=6), n_estimators=1,
            bootstrap=False, seed=0, mesh=mesh,
        ).fit(X, y)
        b = BaggingClassifier(
            base_learner=LinearSVC(max_iter=6), n_estimators=1,
            bootstrap=False, seed=0,
        ).fit(X, y)
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), rtol=1e-4, atol=1e-5
        )

    def test_streaming_fit(self):
        from spark_bagging_tpu import ArrayChunks, BaggingClassifier
        from spark_bagging_tpu.models import LinearSVC

        _, _, X, y = _breast_cancer()
        src = ArrayChunks(X, y, chunk_rows=128)
        clf = BaggingClassifier(
            base_learner=LinearSVC(), n_estimators=8, seed=0,
        ).fit_stream(src, classes=[0, 1], n_epochs=8, lr=0.05)
        assert clf.score(X, y) > 0.9

    def test_invalid_max_iter_raises(self):
        from spark_bagging_tpu.models import LinearSVC

        with pytest.raises(ValueError, match="max_iter"):
            LinearSVC(max_iter=0)


class TestMultinomialNB:
    def _count_data(self):
        rng = np.random.default_rng(0)
        n, F, C = 600, 20, 3
        y = rng.integers(0, C, n).astype(np.int32)
        base = rng.dirichlet(np.ones(F), C)  # per-class topic
        X = np.stack([
            rng.multinomial(40, base[c]) for c in y
        ]).astype(np.float32)
        return X, y

    def test_matches_sklearn(self):
        from sklearn.naive_bayes import MultinomialNB as SkMNB

        from spark_bagging_tpu.models import MultinomialNB

        X, y = self._count_data()
        nb = MultinomialNB(alpha=1.0)
        params, aux = nb.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 3
        )
        sk = SkMNB(alpha=1.0).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(params["log_theta"]), sk.feature_log_prob_,
            rtol=1e-4, atol=1e-5,
        )
        ours = np.asarray(nb.predict_scores(params, jnp.asarray(X)).argmax(1))
        assert (ours == sk.predict(X)).mean() > 0.99
        assert np.isfinite(float(aux["loss"]))

    def test_weighted_equals_duplicated(self):
        from spark_bagging_tpu.models import MultinomialNB

        X, y = self._count_data()
        k = np.asarray([1, 2, 3] * 200)
        nb = MultinomialNB()
        pw, _ = nb.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y),
            jnp.asarray(k, jnp.float32), 3,
        )
        pd, _ = nb.fit_from_init(
            KEY, jnp.asarray(np.repeat(X, k, axis=0)),
            jnp.asarray(np.repeat(y, k), jnp.int32),
            jnp.ones(int(k.sum())), 3,
        )
        np.testing.assert_allclose(
            np.asarray(pw["log_theta"]), np.asarray(pd["log_theta"]),
            rtol=1e-4, atol=1e-5,
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~1.3s NB mesh integration soak; NB fit invariants stay tier-1 via the fuzz battery
    def test_in_bagging_and_mesh(self):
        from spark_bagging_tpu import BaggingClassifier, make_mesh
        from spark_bagging_tpu.models import MultinomialNB

        X, y = self._count_data()
        clf = BaggingClassifier(
            base_learner=MultinomialNB(), n_estimators=16, seed=0,
        ).fit(X, y)
        assert clf.score(X, y) > 0.9
        mesh = make_mesh(data=8)
        a = BaggingClassifier(
            base_learner=MultinomialNB(), n_estimators=1,
            bootstrap=False, seed=0, mesh=mesh,
        ).fit(X, y)
        b = BaggingClassifier(
            base_learner=MultinomialNB(), n_estimators=1,
            bootstrap=False, seed=0,
        ).fit(X, y)
        np.testing.assert_allclose(
            a.predict_proba(X), b.predict_proba(X), rtol=1e-4, atol=1e-5
        )

    def test_invalid_alpha_raises(self):
        from spark_bagging_tpu.models import MultinomialNB

        with pytest.raises(ValueError, match="alpha"):
            MultinomialNB(alpha=-1.0)


class TestBernoulliNB:
    def test_matches_sklearn(self):
        from sklearn.naive_bayes import BernoulliNB as SkBNB

        from spark_bagging_tpu.models import BernoulliNB

        Xj, yj, X, y = _breast_cancer()
        nb = BernoulliNB(alpha=1.0, binarize=0.0)
        params, _ = nb.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 2)
        sk = SkBNB(alpha=1.0, binarize=0.0).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(params["log_theta"]), sk.feature_log_prob_,
            rtol=1e-4, atol=1e-5,
        )
        ours = np.asarray(nb.predict_scores(params, Xj).argmax(1))
        assert (ours == sk.predict(X)).mean() > 0.99

    def test_weighted_equals_duplicated(self):
        from spark_bagging_tpu.models import BernoulliNB

        Xj, yj, X, y = _breast_cancer()
        rng = np.random.default_rng(2)
        k = rng.poisson(1.0, len(y))
        k[0] = 1
        nb = BernoulliNB()
        pw, _ = nb.fit_from_init(
            KEY, Xj, yj, jnp.asarray(k, jnp.float32), 2
        )
        pd, _ = nb.fit_from_init(
            KEY, jnp.asarray(np.repeat(X, k, axis=0)),
            jnp.asarray(np.repeat(y, k), jnp.int32),
            jnp.ones(int(k.sum())), 2,
        )
        np.testing.assert_allclose(
            np.asarray(pw["log_theta"]), np.asarray(pd["log_theta"]),
            rtol=1e-4, atol=1e-5,
        )

    @pytest.mark.slow  # [PR 14 pyramid] ~1.4s NB integration soak; NB fit invariants stay tier-1 via the fuzz battery
    def test_in_bagging_and_checkpoint(self, tmp_path):
        from spark_bagging_tpu import BaggingClassifier, load_model, save_model
        from spark_bagging_tpu.models import BernoulliNB

        Xj, yj, X, y = _breast_cancer()
        clf = BaggingClassifier(
            base_learner=BernoulliNB(), n_estimators=16, seed=0,
            max_features=0.7,
        ).fit(X, y)
        assert clf.score(X, y) > 0.85
        save_model(clf, str(tmp_path / "bnb"))
        clf2 = load_model(str(tmp_path / "bnb"))
        np.testing.assert_allclose(
            clf.predict_proba(X[:64]), clf2.predict_proba(X[:64]),
            rtol=1e-6,
        )


def test_count_nb_alpha_zero_finite():
    """alpha=0 with a zero (class, feature) count must stay finite —
    a huge-negative log score, never NaN from 0·(−inf)."""
    from spark_bagging_tpu.models import BernoulliNB, MultinomialNB

    X = np.array([[3.0, 0.0], [2.0, 0.0], [0.0, 4.0]], np.float32)
    y = np.array([0, 0, 1], np.int32)
    for nb in (MultinomialNB(alpha=0.0), BernoulliNB(alpha=0.0)):
        params, aux = nb.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(3), 2
        )
        scores = np.asarray(nb.predict_scores(params, jnp.asarray(X)))
        assert np.isfinite(scores).all(), type(nb).__name__
        assert np.isfinite(float(aux["loss"])), type(nb).__name__
        assert (scores.argmax(1) == y).all(), type(nb).__name__


def test_bernoulli_nb_negative_binarize_loss_sane():
    """The reported fit loss must come from the once-binarized matrix;
    re-binarizing {0,1} against a negative threshold corrupted it."""
    from spark_bagging_tpu.models import BernoulliNB

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int32)
    nb = BernoulliNB(binarize=-0.5)
    params, aux = nb.fit_from_init(
        KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(200), 2
    )
    # loss should match an explicit NLL on the binarized matrix
    Xb = (X > -0.5).astype(np.float32)
    scores = np.asarray(nb._scores_from_binary(params, jnp.asarray(Xb)))
    logp = scores - np.log(np.exp(scores).sum(1, keepdims=True))
    nll = -logp[np.arange(200), y].mean()
    assert float(aux["loss"]) == pytest.approx(nll, rel=1e-4)


def test_packed_hessian_matches_blocked():
    """'packed' concatenates the blocked scaled copies into one wide
    matmul — identical math, so fits must agree to fp tolerance, with
    and without row tiling."""
    Xj, yj, _, y = _iris()
    w = jnp.asarray(np.random.default_rng(0).poisson(1.0, len(y)),
                    jnp.float32)
    base = LogisticRegression(max_iter=4, hessian_impl="blocked")
    pb, ab = base.fit_from_init(KEY, Xj, yj, w, 3)
    for rt in (None, 64):
        packed = LogisticRegression(max_iter=4, hessian_impl="packed",
                                    row_tile=rt)
        pp, ap = packed.fit_from_init(KEY, Xj, yj, w, 3)
        np.testing.assert_allclose(
            np.asarray(pp["W"]), np.asarray(pb["W"]), rtol=2e-4,
            atol=2e-5,
        )
        np.testing.assert_allclose(
            float(ap["loss"]), float(ab["loss"]), rtol=1e-5
        )


@pytest.mark.slow  # [PR 14 pyramid] ~2.1s packed-impl integration soak; packed-vs-blocked parity stays tier-1
def test_packed_hessian_in_ensemble_and_sharded():
    from spark_bagging_tpu import BaggingClassifier, make_mesh

    Xj, yj, X, y = _breast_cancer()
    lr = LogisticRegression(max_iter=5, hessian_impl="packed")
    clf = BaggingClassifier(base_learner=lr, n_estimators=8, seed=0)
    clf.fit(X, y)
    assert clf.score(X, y) > 0.95
    mesh = make_mesh(data=8)
    a = BaggingClassifier(base_learner=lr, n_estimators=1,
                          bootstrap=False, seed=0, mesh=mesh).fit(X, y)
    b = BaggingClassifier(base_learner=lr, n_estimators=1,
                          bootstrap=False, seed=0).fit(X, y)
    np.testing.assert_allclose(
        a.predict_proba(X), b.predict_proba(X), rtol=1e-4, atol=1e-5
    )


def test_pallas_hessian_matches_blocked():
    """The Pallas scaled-gram path computes the packed math with the
    wide operand built in VMEM — must agree with blocked (interpret
    mode on the CPU backend)."""
    Xj, yj, _, y = _iris()
    w = jnp.asarray(np.random.default_rng(1).poisson(1.0, len(y)),
                    jnp.float32)
    base = LogisticRegression(max_iter=3, hessian_impl="blocked")
    pb, ab = base.fit_from_init(KEY, Xj, yj, w, 3)
    pal = LogisticRegression(max_iter=3, hessian_impl="pallas")
    pp, ap = pal.fit_from_init(KEY, Xj, yj, w, 3)
    np.testing.assert_allclose(
        np.asarray(pp["W"]), np.asarray(pb["W"]), rtol=2e-4, atol=2e-5
    )
    np.testing.assert_allclose(
        float(ap["loss"]), float(ab["loss"]), rtol=1e-5
    )


def test_scaled_grams_kernel_direct():
    from spark_bagging_tpu.ops.gram import scaled_grams

    rng = np.random.default_rng(0)
    n, d, P = 700, 9, 6  # non-multiple of the row tile: pads
    X = rng.standard_normal((n, d)).astype(np.float32)
    S = rng.standard_normal((n, P)).astype(np.float32)
    out = scaled_grams(jnp.asarray(X), jnp.asarray(S), interpret=True)
    assert out.shape == (P, d, d)
    for p in range(P):
        ref = X.T @ (S[:, p : p + 1] * X)
        np.testing.assert_allclose(
            np.asarray(out[p]), ref, rtol=1e-4, atol=1e-4
        )


def _jaxprs_in_param_value(v):
    """Sub-jaxprs reachable from one eqn param value.

    Prefers ``jax.core.jaxprs_in_params`` (a private surface — works on
    the pinned jax but is a likely casualty of an upgrade, the same
    risk class as jax._src.monitoring [ADVICE r5 low]); falls back to a
    manual walk yielding the Jaxpr/ClosedJaxpr instances a param can
    carry (directly, or inside the tuples/lists that ``cond`` branches
    and custom-call closures use), so the precision regression test
    degrades gracefully instead of erroring out of the suite."""
    fn = getattr(jax.core, "jaxprs_in_params", None)
    if fn is not None:
        try:
            return list(fn({"_": v}))
        except Exception:  # noqa: BLE001 — fall through to manual walk
            pass

    def walk(x, acc):
        closed = getattr(jax.core, "ClosedJaxpr", ())
        plain = getattr(jax.core, "Jaxpr", ())
        if isinstance(x, closed):
            acc.append(x.jaxpr)
        elif isinstance(x, plain):
            acc.append(x)
        elif isinstance(x, (tuple, list)):
            for item in x:
                walk(item, acc)
        return acc

    return walk(v, [])


def test_pallas_dot_precision_pinned_against_ambient_context():
    """Mosaic lowers only DEFAULT/HIGHEST dot precision; an ambient
    jax.default_matmul_precision("high") leaking into the kernel trace
    killed the first on-chip compile ("Unsupported dot precision:
    HIGH"). Both kernels must pin an explicit supported precision so
    the solver's precision context (logistic.py applies it around the
    whole fit) can never reach the pallas dot."""
    from spark_bagging_tpu.ops.gram import scaled_grams
    from spark_bagging_tpu.ops.hist import binned_left_stats

    def dot_precisions(jaxpr, acc):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "dot_general":
                acc.append(eqn.params.get("precision"))
            for v in eqn.params.values():
                for j in _jaxprs_in_param_value(v):
                    dot_precisions(j, acc)
        return acc

    unsupported = {jax.lax.Precision.HIGH}
    with jax.default_matmul_precision("high"):
        X = jnp.ones((256, 8), jnp.float32)
        S = jnp.ones((256, 3), jnp.float32)
        jx = jax.make_jaxpr(
            lambda X, S: scaled_grams(X, S, interpret=True)
        )(X, S)
        precs = dot_precisions(jx.jaxpr, [])
        assert precs, "no dot_general found in scaled_grams trace"
        for p in precs:
            assert p is not None and not (set(p) & unsupported), p

        edges = jnp.tile(
            jnp.asarray([0.0, 0.5, jnp.inf], jnp.float32), (8, 1)
        )
        node = jnp.zeros((256,), jnp.int32)
        St = jnp.ones((256, 2), jnp.float32)
        jh = jax.make_jaxpr(
            lambda X, e, nd, S: binned_left_stats(
                X, e, nd, S, n_nodes=1, interpret=True
            )
        )(X, edges, node, St)
        precs = dot_precisions(jh.jaxpr, [])
        assert precs, "no dot_general found in binned_left_stats trace"
        for p in precs:
            assert p is not None and not (set(p) & unsupported), p


def test_pallas_hessian_in_ensemble_vmap():
    """The kernel's accumulate-at-grid-0 pattern must survive vmap's
    grid extension — a full bagged ensemble fit over the pallas path
    (the ops/gram.py docstring contract)."""
    from spark_bagging_tpu import BaggingClassifier

    Xj, yj, X, y = _iris()
    clf = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5,
                                        hessian_impl="pallas"),
        n_estimators=8, seed=0,
    ).fit(X, y)
    assert clf.score(X, y) > 0.9
    ref = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=5,
                                        hessian_impl="blocked"),
        n_estimators=8, seed=0,
    ).fit(X, y)
    np.testing.assert_allclose(
        clf.predict_proba(X), ref.predict_proba(X), rtol=1e-3,
        atol=1e-4,
    )


def test_pallas_row_tile_rounds_to_kernel_grid():
    """The pallas path DOES row-tile (its (tile, P) scale-matrix input
    is a per-replica HBM temp that must be bounded — round-4 audit),
    but the outer tile rounds UP to a multiple of the kernel's 512-row
    grid tile so no grid step runs zero-padded."""
    lr = LogisticRegression(hessian_impl="pallas", row_tile=64)
    Xj, yj, _, y = _iris()
    # iris (150 rows) is under one rounded tile: single pass
    assert lr._row_tiles(Xj, yj, jnp.ones(len(y))) is None
    p, aux = lr.fit_from_init(KEY, Xj, yj, jnp.ones(len(y)), 3)
    assert np.isfinite(float(aux["loss"]))
    # at scale the rounded tiling engages, in 512-multiples...
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.standard_normal((1200, 4)), jnp.float32)
    yb = jnp.asarray(rng.integers(0, 3, 1200), jnp.int32)
    tiles = lr._row_tiles(Xb, yb, jnp.ones(1200))
    assert tiles is not None and tiles[0].shape[1] == 512
    # ...and the tiled fit matches blocked exactly (same math)
    w = jnp.ones(1200)
    pp, _ = LogisticRegression(
        max_iter=3, hessian_impl="pallas", row_tile=512
    ).fit_from_init(KEY, Xb, yb, w, 3)
    pb, _ = LogisticRegression(
        max_iter=3, hessian_impl="blocked"
    ).fit_from_init(KEY, Xb, yb, w, 3)
    np.testing.assert_allclose(
        np.asarray(pp["W"]), np.asarray(pb["W"]), rtol=2e-4, atol=1e-4
    )


class TestKernelEnvelopeGuards:
    def test_pallas_gram_rejects_oversized_vmem(self):
        import jax.numpy as jnp

        from spark_bagging_tpu.ops.gram import (
            _MAX_VMEM_BYTES,
            _kernel_vmem_bytes,
            scaled_grams,
        )

        # the (d, P·d) f32 accumulator alone exceeds the envelope, so
        # no row-tile shrink can save it — must raise, not hand Mosaic
        # an impossible block
        X = jnp.ones((64, 500))
        S = jnp.ones((64, 26))
        assert _kernel_vmem_bytes(64, 500, 26) > _MAX_VMEM_BYTES
        with pytest.raises(ValueError, match="VMEM"):
            scaled_grams(X, S, interpret=False)
        # headline shape (d=55, P=28) must fit WITHOUT shrinking below
        # the full 512-row grid tile — the envelope model must not
        # regress the known-good config
        assert _kernel_vmem_bytes(512, 55, 28) <= _MAX_VMEM_BYTES

    def test_fused_hist_rejects_oversized_out_block(self):
        import jax
        import jax.numpy as jnp

        from spark_bagging_tpu.ops.hist import binned_left_stats

        X = jnp.ones((64, 64))
        edges = jnp.ones((64, 32))
        node = jnp.zeros((64,), jnp.int32)
        S = jnp.ones((64, 7))
        with pytest.raises(ValueError, match="envelope"):
            binned_left_stats(X, edges, node, S, n_nodes=2048,
                              interpret=True)

    def test_fused_hist_shrinks_tiles_for_deep_levels(self):
        """A depth that the old output-block guard hard-rejected must
        now run at shrunken (f_tile, rows) tiles — and still match the
        brute-force left-stats computation (round-4 audit)."""
        import jax.numpy as jnp

        from spark_bagging_tpu.ops.hist import (
            _MAX_VMEM_BYTES,
            _kernel_vmem_bytes,
            binned_left_stats,
        )

        n_nodes, K, B, F = 1024, 7, 32, 8
        # infeasible at the default tiles, feasible at minimal ones
        assert _kernel_vmem_bytes(512, 64, B, n_nodes, K) > _MAX_VMEM_BYTES
        assert _kernel_vmem_bytes(64, 1, B, n_nodes, K) <= _MAX_VMEM_BYTES
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.standard_normal((96, F)), jnp.float32)
        edges = jnp.sort(
            jnp.asarray(rng.standard_normal((F, B)), jnp.float32), axis=1
        ).at[:, -1].set(jnp.inf)
        node = jnp.asarray(rng.integers(0, n_nodes, 96), jnp.int32)
        S = jnp.asarray(rng.random((96, K)), jnp.float32)
        out = binned_left_stats(
            X, edges, node, S, n_nodes=n_nodes, hist_dtype="float32",
            interpret=True,
        )
        assert out.shape == (F, B, n_nodes, K)
        # brute-force check on a few (f, b) cells
        Xn, En, Nn, Sn = map(np.asarray, (X, edges, node, S))
        for f, b in [(0, 0), (3, 17), (7, 31)]:
            ind = (Xn[:, f] <= En[f, b]).astype(np.float32)
            ref = np.zeros((n_nodes, K), np.float32)
            for i in range(96):
                ref[Nn[i]] += ind[i] * Sn[i]
            np.testing.assert_allclose(
                np.asarray(out[f, b]), ref, rtol=1e-4, atol=1e-4
            )

    def test_logistic_workset_models_wide_hessians(self):
        from spark_bagging_tpu.models.logistic import LogisticRegression

        n, d, C = 10_000, 54, 10
        blocked = LogisticRegression(hessian_impl="blocked")
        fused = LogisticRegression(hessian_impl="fused")
        packed = LogisticRegression(hessian_impl="packed")
        b = blocked.fit_workset_bytes(n, d, C)
        # the wide assemblies' HBM temps must be modeled, not free
        assert fused.fit_workset_bytes(n, d, C) > b + 4 * n * C * d * 0.9
        assert packed.fit_workset_bytes(n, d, C) > b
        # auto resolves to fused at C=10 and must be modeled identically
        auto = LogisticRegression(hessian_impl="auto")
        assert auto.fit_workset_bytes(n, d, C) == \
            fused.fit_workset_bytes(n, d, C)

    def test_fm_workset_modeled(self):
        from spark_bagging_tpu.models.fm import FMClassifier

        fm = FMClassifier(factor_size=8)
        small = fm.fit_workset_bytes(1_000, 54, 3)
        big = fm.fit_workset_bytes(100_000, 54, 3)
        assert small > 0 and big > 50 * small
