"""Unit tests for vote/mean aggregation on hand-built arrays [SURVEY §4]."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from spark_bagging_tpu.ops import hard_vote_counts, mean_aggregate, soft_vote_proba
from spark_bagging_tpu.parallel.compat import shard_map


def test_mean_aggregate():
    preds = jnp.array([[1.0, 2.0], [3.0, 4.0]])
    out = mean_aggregate(preds, n_total=2)
    np.testing.assert_allclose(np.asarray(out), [2.0, 3.0])


def test_soft_vote_proba():
    probs = jnp.array(
        [[[0.9, 0.1]], [[0.2, 0.8]], [[0.4, 0.6]]]
    )  # (R=3, n=1, C=2)
    out = soft_vote_proba(probs, n_total=3)
    np.testing.assert_allclose(np.asarray(out), [[0.5, 0.5]], atol=1e-6)


def test_hard_vote_majority():
    labels = jnp.array([[0, 1], [0, 2], [1, 2]])  # (R=3, n=2)
    counts = hard_vote_counts(labels, 3)
    np.testing.assert_allclose(np.asarray(counts), [[2, 1, 0], [0, 1, 2]])
    assert np.asarray(counts.argmax(axis=1)).tolist() == [0, 2]


def test_hard_vote_tie_breaks_low():
    labels = jnp.array([[1], [0]])
    counts = hard_vote_counts(labels, 2)
    assert int(counts.argmax(axis=1)[0]) == 0


def test_aggregation_under_replica_sharding():
    """psum-based aggregation over a sharded replica axis matches the
    unsharded result — the reduction the north star names [B:5]."""
    mesh = jax.make_mesh((8,), ("replica",))
    preds = jnp.arange(32.0).reshape(8, 4)  # 8 replicas, 4 rows

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("replica"), out_specs=P()
    )
    def sharded_mean(p):
        return mean_aggregate(p, n_total=8, axis_name="replica")

    np.testing.assert_allclose(
        np.asarray(sharded_mean(preds)), np.asarray(preds.mean(axis=0)), rtol=1e-6
    )

    labels = jnp.tile(jnp.array([[0, 1, 1, 2]]), (8, 1))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("replica"), out_specs=P()
    )
    def sharded_vote(l):
        return hard_vote_counts(l, 3, axis_name="replica")

    np.testing.assert_allclose(
        np.asarray(sharded_vote(labels)),
        np.asarray(hard_vote_counts(labels, 3)),
    )


def test_shard_map_compat_sentinel(monkeypatch):
    """On a jax build with NO shard_map implementation the compat
    resolver must skip inside a running test (environment property,
    not a bug) but raise the catchable ShardMapUnavailable elsewhere —
    never leak pytest's BaseException-derived Skipped into production
    error handling."""
    from spark_bagging_tpu.parallel import compat

    monkeypatch.setattr(compat, "_impl", None)
    body = lambda x: x  # noqa: E731

    with pytest.raises(pytest.skip.Exception):
        compat.shard_map(body, mesh=None, in_specs=None, out_specs=None)

    monkeypatch.delenv("PYTEST_CURRENT_TEST", raising=False)
    with pytest.raises(compat.ShardMapUnavailable, match="neither"):
        compat.shard_map(body, mesh=None, in_specs=None, out_specs=None)
