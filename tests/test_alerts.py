"""Alert engine [ISSUE 9]: rule grammar, multi-window burn-rate
fire/resolve lifecycle, per-rule cooldown, flight-recorder triggering
on alert_fired, the sbt_alerts_* series, and the /alerts +
/debug/drift scrape endpoints.
"""

import json
import urllib.request

import numpy as np
import pytest

from spark_bagging_tpu import telemetry
from spark_bagging_tpu.telemetry import alerts
from spark_bagging_tpu.telemetry.alerts import AlertEngine, AlertRule


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.enable()
    alerts.uninstall()
    yield
    telemetry.reset()
    telemetry.enable()
    alerts.uninstall()


def gauge_rule(**kw):
    base = dict(threshold=1.0, kind="value", op=">",
                fast_window_s=2.0, slow_window_s=5.0, cooldown_s=10.0)
    base.update(kw)
    return AlertRule("g", "sbt_test_gauge", **base)


def set_gauge(v):
    telemetry.set_gauge("sbt_test_gauge", v)


class TestRuleGrammar:
    def test_round_trip_and_validation(self):
        r = gauge_rule(description="d", severity="ticket")
        assert AlertRule.from_dict(r.to_dict()).to_dict() == r.to_dict()
        with pytest.raises(ValueError, match="unknown alert rule"):
            AlertRule.from_dict({**r.to_dict(), "bogus": 1})
        with pytest.raises(ValueError, match="at least"):
            AlertRule.from_dict({"name": "x"})
        with pytest.raises(ValueError, match="kind"):
            gauge_rule(kind="magic")
        with pytest.raises(ValueError, match="op"):
            gauge_rule(op=">=")
        with pytest.raises(ValueError, match="fast_window_s"):
            gauge_rule(fast_window_s=10.0, slow_window_s=1.0)

    def test_duplicate_rule_name_rejected(self):
        eng = AlertEngine([gauge_rule()])
        with pytest.raises(ValueError, match="already installed"):
            eng.add_rule(gauge_rule())

    def test_default_drift_rules_cover_the_quality_gauges(self):
        names = {r.series for r in alerts.default_drift_rules()}
        assert "sbt_quality_psi_max" in names
        assert "sbt_quality_confidence_psi" in names


class TestLifecycle:
    def test_fire_requires_both_windows_and_coverage(self):
        """One breaching sample must not page: the fast AND slow
        windows must be fully covered by breaching samples."""
        eng = AlertEngine([gauge_rule()])
        set_gauge(5.0)
        assert eng.evaluate(now=0.0) == []   # no slow-window coverage
        assert eng.evaluate(now=2.0) == []
        assert eng.evaluate(now=4.0) == []
        evs = eng.evaluate(now=5.5)          # watched > slow_window now
        assert [e["kind"] for e in evs] == ["alert_fired"]
        assert eng.active() == ("g",)
        # active: further breaches emit nothing (one incident, one alert)
        assert eng.evaluate(now=6.0) == []

    def test_transient_blip_does_not_fire(self):
        eng = AlertEngine([gauge_rule()])
        set_gauge(0.0)
        for t in range(6):
            assert eng.evaluate(now=float(t)) == []
        set_gauge(5.0)                        # blip
        assert eng.evaluate(now=6.0) == []    # slow window not all-breach
        set_gauge(0.0)
        assert eng.evaluate(now=7.0) == []
        assert eng.active() == ()

    def test_resolve_and_cooldown_suppression(self):
        eng = AlertEngine([gauge_rule(cooldown_s=100.0)])
        set_gauge(5.0)
        for t in (0.0, 2.0, 4.0, 5.5):
            evs = eng.evaluate(now=t)
        assert [e["kind"] for e in evs] == ["alert_fired"]
        set_gauge(0.5)
        (resolved,) = eng.evaluate(now=6.0)
        assert resolved["kind"] == "alert_resolved"
        # re-breach inside the cooldown: suppressed, counted, no event
        set_gauge(5.0)
        for t in (7.0, 9.0, 12.0, 13.0):
            assert eng.evaluate(now=t) == []
        st = eng.state()["rules"][0]
        assert st["fired"] == 1 and st["resolved"] == 1
        assert st["suppressed"] >= 1
        reg = telemetry.registry()
        assert reg.counter("sbt_alerts_suppressed_total",
                           {"rule": "g"}).value >= 1
        # past the cooldown the same sustained breach fires again
        evs = [e for t in (104.0, 106.0, 110.0)
               for e in eng.evaluate(now=t)]
        assert [e["kind"] for e in evs] == ["alert_fired"]
        assert reg.counter("sbt_alerts_fired_total",
                           {"rule": "g"}).value == 2

    def test_rate_rule_on_counter(self):
        """kind=rate: windowed per-second rate of a counter."""
        eng = AlertEngine([AlertRule(
            "errs", "sbt_test_errors_total", threshold=2.0,
            kind="rate", op=">", fast_window_s=2.0, slow_window_s=4.0,
        )])
        reg = telemetry.registry()
        for t in range(5):   # 1/s — under threshold
            reg.inc("sbt_test_errors_total", 1.0)
            assert eng.evaluate(now=float(t)) == []
        for t in range(5, 11):  # 10/s — burn
            reg.inc("sbt_test_errors_total", 10.0)
            evs = eng.evaluate(now=float(t))
            if evs:
                break
        assert [e["kind"] for e in evs] == ["alert_fired"]
        # errors stop entirely: the WINDOWED rate falls back under the
        # threshold and the alert must resolve — comparing the raw
        # cumulative counter (still 65 > 2.0) would pin it active
        # forever and swallow every later genuine burst
        resolved = [e for t in range(11, 20)
                    for e in eng.evaluate(now=float(t))]
        assert [e["kind"] for e in resolved] == ["alert_resolved"]
        assert eng.active() == ()

    def test_kind_mismatched_series_skips_not_poisons(self):
        """A value rule aimed at a histogram (metric-kind collision)
        must not take down the evaluation pass for every OTHER rule."""
        telemetry.observe("sbt_test_hist_seconds", 0.1)
        eng = AlertEngine([
            AlertRule("bad", "sbt_test_hist_seconds", threshold=1.0,
                      fast_window_s=2.0, slow_window_s=5.0),
            gauge_rule(),
        ])
        set_gauge(5.0)
        evs = [e for t in (0.0, 2.0, 4.0, 5.5)
               for e in eng.evaluate(now=t)]
        assert [e["rule"] for e in evs] == ["g"]  # good rule still fires
        bad = next(r for r in eng.state()["rules"]
                   if r["name"] == "bad")
        assert bad["last_value"] is None and bad["active"] is False

    def test_absent_series_is_no_evidence_even_for_lt_rules(self):
        """A series nobody wrote must not be sampled at all: an
        op "<" rule (e.g. 'confidence median below 0.4') would
        otherwise fire on an auto-created 0.0 from a service that
        served zero traffic."""
        eng = AlertEngine([AlertRule(
            "low-conf", "sbt_never_written", threshold=0.4, op="<",
            fast_window_s=1.0, slow_window_s=2.0,
        )])
        for t in range(6):
            assert eng.evaluate(now=float(t)) == []
        st = eng.state()["rules"][0]
        assert st["last_value"] is None and st["fired"] == 0
        # the series was NOT materialized by the sampling
        assert telemetry.registry().peek("sbt_never_written") is None
        # once real data arrives and genuinely breaches, it can fire
        telemetry.set_gauge("sbt_never_written", 0.1)
        evs = [e for t in (10.0, 11.0, 12.0, 13.0)
               for e in eng.evaluate(now=t)]
        assert [e["kind"] for e in evs] == ["alert_fired"]

    def test_metrics_and_state_shape(self):
        eng = AlertEngine([gauge_rule()])
        set_gauge(5.0)
        for t in (0.0, 2.0, 4.0, 5.5):
            eng.evaluate(now=t)
        reg = telemetry.registry()
        assert reg.counter("sbt_alerts_evaluations_total").value == 4
        assert reg.gauge("sbt_alerts_active").value == 1.0
        st = eng.state()
        assert st["active"] == ["g"]
        (rule,) = st["rules"]
        assert rule["last_value"] == 5.0
        json.dumps(st)  # /alerts serves this verbatim


class TestEventPlumbing:
    def _fire(self, eng):
        set_gauge(5.0)
        for t in (0.0, 2.0, 4.0, 5.5):
            evs = eng.evaluate(now=t)
        return evs

    def test_alert_fired_reaches_open_capture(self):
        eng = AlertEngine([gauge_rule()])
        with telemetry.capture() as run:
            self._fire(eng)
        evs = [e for e in run.events if e["kind"] == "alert_fired"]
        assert len(evs) == 1
        assert evs[0]["rule"] == "g" and "ts" in evs[0]

    def test_alert_fired_triggers_flight_recorder(self, tmp_path):
        """The quality plane's incident contract: an alert arrives
        with the black box. alert_fired is a TRIGGER kind; per-kind
        cooldown still guarantees one dump per incident."""
        from spark_bagging_tpu.telemetry.recorder import FlightRecorder

        rec = FlightRecorder(dir=str(tmp_path), cooldown_s=3600)
        rec.arm()
        try:
            eng = AlertEngine([gauge_rule(cooldown_s=0.0)])
            self._fire(eng)
            assert len(rec.dumps) == 1
            dump = json.loads(open(rec.dumps[0]).read())
            assert dump["trigger"]["kind"] == "alert_fired"
            assert dump["trigger"]["rule"] == "g"
            # flap: resolve + immediate re-fire (cooldown_s=0 on the
            # RULE) — the recorder's own cooldown suppresses dump #2
            set_gauge(0.5)
            eng.evaluate(now=6.0)
            set_gauge(5.0)
            for t in (6.5, 8.0, 10.0, 12.0):
                eng.evaluate(now=t)
            assert len(rec.dumps) == 1
        finally:
            rec.disarm()


class TestEndpoints:
    def test_alerts_and_drift_routes(self):
        from spark_bagging_tpu.telemetry import server as tserver

        port = tserver.start_server(0)
        try:
            def get(path):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                    return r.status, json.loads(r.read())

            # no engine installed -> note, not error
            status, body = get("/alerts")
            assert status == 200 and "note" in body
            # install + breach -> scrapes drive the evaluation ticks
            alerts.install([AlertRule(
                "scrape", "sbt_test_gauge", threshold=1.0,
                fast_window_s=0.001, slow_window_s=0.001,
            )])
            set_gauge(5.0)
            get("/alerts")
            import time

            time.sleep(0.02)
            status, body = get("/alerts")
            assert status == 200
            (rule,) = body["rules"]
            assert rule["last_value"] == 5.0
            assert body["active"] == ["scrape"]
            # /debug/drift with no monitor: the discoverable note
            status, body = get("/debug/drift")
            assert status == 200 and "note" in body
            # the route index advertises both
            status, body = get("/")
            assert "/alerts" in body["endpoints"]
            assert "/debug/drift" in body["endpoints"]
        finally:
            tserver.stop_server()
            from spark_bagging_tpu.telemetry import recorder

            recorder.disarm()  # start_server armed the default

    def test_debug_drift_serves_live_monitor(self):
        from spark_bagging_tpu import BaggingClassifier
        from spark_bagging_tpu.telemetry import quality
        from spark_bagging_tpu.telemetry import server as tserver
        from spark_bagging_tpu.serving import EnsembleExecutor

        rng = np.random.default_rng(0)
        X = rng.normal(size=(80, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        clf = BaggingClassifier(n_estimators=2, seed=0).fit(X, y)
        ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32)
        quality.attach(ex, refresh_every=1)
        ex.forward(X[:8])
        port = tserver.start_server(0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/drift",
                    timeout=5) as r:
                body = json.loads(r.read())
            assert any(m["rows_observed"] == 8
                       for m in body["monitors"])
        finally:
            tserver.stop_server()
            from spark_bagging_tpu.telemetry import recorder

            recorder.disarm()
