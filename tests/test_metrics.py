"""Metric helper tests against sklearn references."""

import numpy as np
from sklearn import metrics as skm

from spark_bagging_tpu.utils.metrics import (
    accuracy,
    fit_report,
    r2_score,
    rmse,
    roc_auc,
)


def test_accuracy():
    assert accuracy([1, 2, 3], [1, 2, 0]) == 2 / 3


def test_rmse_and_r2():
    rng = np.random.default_rng(0)
    y = rng.normal(size=100)
    p = y + 0.1 * rng.normal(size=100)
    assert rmse(y, p) == np.sqrt(skm.mean_squared_error(y, p))
    assert abs(r2_score(y, p) - skm.r2_score(y, p)) < 1e-12


def test_r2_constant_target():
    assert r2_score([1.0, 1.0], [1.0, 2.0]) == 0.0


def test_roc_auc_matches_sklearn():
    rng = np.random.default_rng(1)
    y = (rng.random(500) < 0.3).astype(int)
    s = rng.normal(size=500) + y
    assert abs(roc_auc(y, s) - skm.roc_auc_score(y, s)) < 1e-9


def test_roc_auc_with_ties():
    y = np.array([0, 0, 1, 1, 0, 1])
    s = np.array([0.5, 0.5, 0.5, 0.8, 0.2, 0.8])
    assert abs(roc_auc(y, s) - skm.roc_auc_score(y, s)) < 1e-12


def test_roc_auc_degenerate():
    assert roc_auc(np.ones(5), np.arange(5)) == 0.5


def test_fit_report_fields():
    rep = fit_report(
        n_replicas=10, fit_seconds=2.0, losses=np.ones(10), n_rows=5,
        n_features=3, n_subspace=2, backend="cpu", n_devices=1,
    )
    assert rep["fits_per_sec"] == 5.0
    assert rep["loss_mean"] == 1.0
