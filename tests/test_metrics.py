"""Metric helper tests against sklearn references."""

import numpy as np
import pytest
from sklearn import metrics as skm

from spark_bagging_tpu.utils.metrics import (
    accuracy,
    f1_score,
    fit_report,
    mae,
    pr_auc,
    r2_score,
    rmse,
    roc_auc,
)


def test_accuracy():
    assert accuracy([1, 2, 3], [1, 2, 0]) == 2 / 3


def test_mae_matches_sklearn():
    import pytest

    rng = np.random.default_rng(3)
    y, p = rng.normal(size=200), rng.normal(size=200)
    assert mae(y, p) == pytest.approx(skm.mean_absolute_error(y, p))


def test_pr_auc_matches_sklearn_average_precision():
    import pytest

    rng = np.random.default_rng(4)
    y = (rng.random(500) < 0.3).astype(int)
    s = rng.normal(size=500) + y  # informative scores
    assert pr_auc(y, s) == pytest.approx(skm.average_precision_score(y, s))
    # with heavy ties
    st = np.round(s)
    assert pr_auc(y, st) == pytest.approx(
        skm.average_precision_score(y, st)
    )
    assert pr_auc(np.zeros(10, int), rng.normal(size=10)) == 0.0


def test_f1_matches_sklearn():
    import pytest

    rng = np.random.default_rng(5)
    y = rng.integers(0, 4, 300)
    p = np.where(rng.random(300) < 0.6, y, rng.integers(0, 4, 300))
    assert f1_score(y, p) == pytest.approx(
        skm.f1_score(y, p, average="weighted")
    )
    assert f1_score(y, p, average="macro") == pytest.approx(
        skm.f1_score(y, p, average="macro")
    )


def test_rmse_and_r2():
    rng = np.random.default_rng(0)
    y = rng.normal(size=100)
    p = y + 0.1 * rng.normal(size=100)
    assert rmse(y, p) == np.sqrt(skm.mean_squared_error(y, p))
    assert abs(r2_score(y, p) - skm.r2_score(y, p)) < 1e-12


def test_r2_constant_target():
    assert r2_score([1.0, 1.0], [1.0, 2.0]) == 0.0


def test_roc_auc_matches_sklearn():
    rng = np.random.default_rng(1)
    y = (rng.random(500) < 0.3).astype(int)
    s = rng.normal(size=500) + y
    assert abs(roc_auc(y, s) - skm.roc_auc_score(y, s)) < 1e-9


def test_roc_auc_with_ties():
    y = np.array([0, 0, 1, 1, 0, 1])
    s = np.array([0.5, 0.5, 0.5, 0.8, 0.2, 0.8])
    assert abs(roc_auc(y, s) - skm.roc_auc_score(y, s)) < 1e-12


def test_roc_auc_degenerate():
    assert roc_auc(np.ones(5), np.arange(5)) == 0.5


def test_fit_report_fields():
    rep = fit_report(
        n_replicas=10, fit_seconds=2.0, losses=np.ones(10), n_rows=5,
        n_features=3, n_subspace=2, backend="cpu", n_devices=1,
    )
    assert rep["fits_per_sec"] == 5.0
    assert rep["loss_mean"] == 1.0


def test_roc_auc_heavy_ties_matches_sklearn():
    rng = np.random.default_rng(3)
    y = rng.integers(0, 2, 20_000)
    s = np.round(rng.standard_normal(20_000), 1)  # ~80 unique values
    ours = roc_auc(y, s)
    ref = skm.roc_auc_score(y, s)
    assert abs(ours - ref) < 1e-12


def test_roc_auc_large_input_is_fast():
    import time

    rng = np.random.default_rng(4)
    n = 1_000_000
    y = rng.integers(0, 2, n)
    s = rng.standard_normal(n)  # continuous scores: n unique values
    t0 = time.perf_counter()
    roc_auc(y, s)
    # O(n log n); the old per-unique-value scan took hours here
    assert time.perf_counter() - t0 < 10.0


def test_fit_report_flops_fields(monkeypatch):
    from spark_bagging_tpu.utils import profiling
    from spark_bagging_tpu.utils.metrics import fit_report

    # pin the ambient-device peak so the assertions hold on any host
    monkeypatch.setattr(profiling, "device_peak_tflops", lambda: 100.0)
    r = fit_report(
        n_replicas=10, fit_seconds=2.0, losses=np.ones(10), n_rows=100,
        n_features=5, n_subspace=5, backend="cpu", n_devices=1,
        compile_seconds=1.0, h2d_seconds=0.5, flops_per_fit=1e9,
    )
    assert r["fits_per_sec"] == 5.0
    assert r["fits_per_sec_e2e"] == 10 / 2.5
    assert r["achieved_tflops"] == 1e9 * 10 / 2.0 / 1e12
    assert r["peak_tflops_bf16"] == 100.0
    assert r["mfu"] == r["achieved_tflops"] / 100.0


def test_device_peak_tflops_known_kinds():
    from spark_bagging_tpu.utils.profiling import device_peak_tflops

    class FakeDev:
        def __init__(self, kind):
            self.device_kind = kind

    assert device_peak_tflops(FakeDev("TPU v5 lite")) == 197.0
    assert device_peak_tflops(FakeDev("TPU v5p")) == 459.0
    assert device_peak_tflops(FakeDev("TPU v6 lite")) == 918.0
    assert device_peak_tflops(FakeDev("TPU v4")) == 275.0
    assert device_peak_tflops(FakeDev("cpu")) is None


def test_roc_auc_accepts_column_vectors():
    from spark_bagging_tpu.utils.metrics import roc_auc

    rng = np.random.default_rng(0)
    y = (rng.random(200) > 0.5).astype(int)
    s = rng.random(200) + 0.5 * y
    flat = roc_auc(y, s)
    assert roc_auc(y.reshape(-1, 1), s.reshape(-1, 1)) == flat
    assert roc_auc(y.reshape(-1, 1), s) == flat


def test_r2_constant_target_matches_sklearn():
    """Perfect predictions on a constant target score 1.0, not 0.0
    (round-4 audit)."""
    from spark_bagging_tpu.utils.metrics import r2_score

    assert r2_score([3.0, 3.0, 3.0], [3.0, 3.0, 3.0]) == 1.0
    assert r2_score([3.0, 3.0, 3.0], [2.0, 3.0, 4.0]) == 0.0


def test_accuracy_rejects_length_mismatch():
    from spark_bagging_tpu.utils.metrics import accuracy

    with pytest.raises(ValueError, match="samples"):
        accuracy([0, 1, 1, 0], [1])


def test_binary_metrics_reject_noncanonical_labels():
    """{1,2}-coded labels would silently score INVERTED (label!=1 is
    treated negative) — reject them (round-4 audit)."""
    from spark_bagging_tpu.utils.metrics import pr_auc, roc_auc

    s = [0.1, 0.9, 0.4, 0.7]
    assert roc_auc([0, 1, 0, 1], s) == 1.0
    assert roc_auc([-1, 1, -1, 1], s) == 1.0
    with pytest.raises(ValueError, match="labels"):
        roc_auc([1, 2, 1, 2], s)
    with pytest.raises(ValueError, match="labels"):
        pr_auc([1, 2, 1, 2], s)
