"""Random forest tests: per-split feature sampling, sklearn-quality
parity, stream/memory equality with feature_subset set [SURVEY §4]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_iris
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import (
    RandomForestClassifier,
    RandomForestRegressor,
)
from spark_bagging_tpu.models import DecisionTreeClassifier

KEY = jax.random.key(0)


def _breast_cancer():
    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    return X, y


class TestPerSplitSampling:
    def test_mask_exact_k(self):
        tree = DecisionTreeClassifier(feature_subset=5)
        mask = tree._level_feat_mask(KEY, 0, 8, 20, 5)
        assert mask.shape == (8, 20)
        np.testing.assert_array_equal(np.asarray(mask.sum(1)), 5)
        # distinct nodes draw distinct subsets (overwhelmingly likely)
        assert not np.array_equal(np.asarray(mask[0]), np.asarray(mask[1]))

    def test_mask_changes_per_level_and_replica(self):
        tree = DecisionTreeClassifier(feature_subset=4)
        m0 = np.asarray(tree._level_feat_mask(KEY, 0, 4, 16, 4))
        m1 = np.asarray(tree._level_feat_mask(KEY, 1, 4, 16, 4))
        assert not np.array_equal(m0, m1)
        k2 = jax.random.key(1)
        m2 = np.asarray(tree._level_feat_mask(k2, 0, 4, 16, 4))
        assert not np.array_equal(m0, m2)

    def test_n_split_features_resolution(self):
        t = DecisionTreeClassifier
        assert t(feature_subset=None)._n_split_features(30) is None
        assert t(feature_subset="all")._n_split_features(30) is None
        assert t(feature_subset="sqrt")._n_split_features(30) == 6
        assert t(feature_subset="log2")._n_split_features(30) == 5
        assert t(feature_subset="onethird")._n_split_features(30) == 10
        assert t(feature_subset=0.5)._n_split_features(30) == 15
        assert t(feature_subset=7)._n_split_features(30) == 7
        assert t(feature_subset=100)._n_split_features(30) is None  # clamps
        with pytest.raises(ValueError, match="feature_subset"):
            t(feature_subset=0)
        with pytest.raises(ValueError, match="feature_subset"):
            t(feature_subset=1.5)
        with pytest.raises(ValueError, match="feature_subset"):
            t(feature_subset="auto")

    @pytest.mark.slow  # ~4.5s [PR 12 budget offset]: subset-vs-full tree divergence on breast_cancer; per-split sampling stays tier-1 via the validation + stream-parity subset tests
    def test_subset_tree_differs_from_full_tree(self):
        X, y = _breast_cancer()
        full = DecisionTreeClassifier(max_depth=3)
        sub = DecisionTreeClassifier(max_depth=3, feature_subset=3)
        pf, _ = full.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(len(y)), 2,
        )
        ps, _ = sub.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y, jnp.int32),
            jnp.ones(len(y)), 2,
        )
        assert not np.array_equal(
            np.asarray(pf["feature"]), np.asarray(ps["feature"])
        )


class TestRandomForestClassifier:
    @pytest.mark.slow  # [PR 14 pyramid] ~3.6s accuracy/OOB quality soak; forest API + mesh parity stay tier-1
    def test_accuracy_and_oob(self):
        X, y = _breast_cancer()
        rf = RandomForestClassifier(
            n_estimators=32, max_depth=4, seed=0, oob_score=True,
        ).fit(X, y)
        assert rf.score(X, y) > 0.95
        assert rf.oob_score_ > 0.9
        assert rf.feature_importances_.shape == (X.shape[1],)
        assert rf.feature_importances_.sum() == pytest.approx(1.0, abs=1e-5)

    def test_multiclass_and_params_roundtrip(self):
        X, y = load_iris(return_X_y=True)
        X = X.astype(np.float32)
        rf = RandomForestClassifier(n_estimators=16, max_depth=3, seed=1)
        rf2 = rf.clone().set_params(max_depth=4)
        assert rf2.get_params()["max_depth"] == 4
        assert rf.get_params()["max_depth"] == 3
        rf.fit(X, y)
        assert rf.score(X, y) > 0.9

    @pytest.mark.slow  # [PR 14 pyramid] ~2.6s per-model checkpoint twin; the generic round-trip contract stays tier-1 in test_checkpoint
    def test_checkpoint_roundtrip(self, tmp_path):
        from spark_bagging_tpu import load_model, save_model

        X, y = _breast_cancer()
        rf = RandomForestClassifier(n_estimators=8, max_depth=3).fit(X, y)
        save_model(rf, str(tmp_path / "rf"))
        rf2 = load_model(str(tmp_path / "rf"))
        assert isinstance(rf2, RandomForestClassifier)
        np.testing.assert_allclose(
            rf.predict_proba(X[:32]), rf2.predict_proba(X[:32]), rtol=1e-6
        )

    def test_mesh_fit(self):
        from spark_bagging_tpu import make_mesh

        X, y = _breast_cancer()
        mesh = make_mesh(data=2)
        rf = RandomForestClassifier(
            n_estimators=16, max_depth=3, seed=0, mesh=mesh,
        ).fit(X, y)
        assert rf.score(X, y) > 0.9


class TestRandomForestRegressor:
    @pytest.mark.slow  # ~5.6s: quality-of-fit soak (R² floor on a
    # 200-tree forest); structural/parity forest coverage stays tier-1
    # [ISSUE 13 tier-1 budget offset]
    def test_r2(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(500, 10)).astype(np.float32)
        y = (np.sin(X[:, 0]) + X[:, 1] ** 2
             + 0.1 * rng.normal(size=500)).astype(np.float32)
        rf = RandomForestRegressor(
            n_estimators=32, max_depth=5, seed=0, oob_score=True,
        ).fit(X, y)
        assert rf.score(X, y) > 0.7
        assert np.isfinite(rf.oob_score_)

    @pytest.mark.slow  # [PR 14 pyramid] ~4.3s stream-vs-memory subset soak; forest subset determinism stays tier-1
    def test_stream_matches_memory_with_feature_subset(self):
        """The streamed forest must replay the in-memory per-split
        masks exactly — identical trees from chunked data."""
        from spark_bagging_tpu import ArrayChunks

        rng = np.random.default_rng(1)
        X = rng.normal(size=(256, 8)).astype(np.float32)
        y = (X[:, 0] - 2 * X[:, 3] + 0.1 * rng.normal(size=256)).astype(
            np.float32
        )
        mem = RandomForestRegressor(
            n_estimators=4, max_depth=3, seed=0, bootstrap=False,
            max_samples=1.0,
        ).fit(X, y)
        src = ArrayChunks(X, y, chunk_rows=256)  # one chunk: same binning
        stream = RandomForestRegressor(
            n_estimators=4, max_depth=3, seed=0, bootstrap=False,
            max_samples=1.0,
        ).fit_stream(src)
        np.testing.assert_allclose(
            mem.predict(X), stream.predict(X), rtol=1e-5, atol=1e-5
        )
