"""AFTSurvivalRegression + the per-row aux channel [VERDICT r2 ask#7].

The reference's plugin slot takes any Spark Predictor, including
AFTSurvivalRegression with its censorCol; these tests cover the Weibull
AFT learner (parameter recovery, censoring correctness, quantiles) and
the aux threading through the ensemble engine (validation, bagging,
replica-mesh equality, persistence).
"""

import warnings

import jax
import numpy as np
import pytest

from spark_bagging_tpu import (
    AFTSurvivalRegression,
    BaggingRegressor,
    LinearRegression,
    make_mesh,
    load_model,
    save_model,
)

SIGMA_TRUE = 0.5
BETA_TRUE = np.array([1.0, -0.5, 0.8, 0.0], np.float32)
BIAS_TRUE = 0.7


def _weibull_data(n=3000, seed=0, censor_frac=0.0):
    """log T = Xβ + b + σ·ε, ε = log E, E ~ Exp(1) (standard minimum
    extreme value) ⇒ T is Weibull. Administrative right-censoring at
    the empirical (1 − censor_frac) time quantile."""
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, len(BETA_TRUE))).astype(np.float32)
    eps = np.log(rng.exponential(1.0, n)).astype(np.float32)
    T = np.exp(X @ BETA_TRUE + BIAS_TRUE + SIGMA_TRUE * eps)
    if censor_frac <= 0.0:
        return X, T.astype(np.float32), np.ones(n, np.float32)
    c = np.quantile(T, 1.0 - censor_frac)
    y = np.minimum(T, c).astype(np.float32)
    delta = (T <= c).astype(np.float32)
    return X, y, delta


def _direct_fit(learner, X, y, delta):
    params = learner.init_params(jax.random.key(0), X.shape[1], 1)
    params, aux = learner.fit(
        params, X, y, np.ones(len(y), np.float32), jax.random.key(1),
        aux=delta,
    )
    return params, aux


def test_aft_recovers_coefficients_uncensored():
    X, y, delta = _weibull_data()
    learner = AFTSurvivalRegression(max_iter=500, lr=0.05, l2=0.0)
    params, aux = _direct_fit(learner, X, y, delta)
    beta = np.asarray(params["beta"])
    np.testing.assert_allclose(beta[:-1], BETA_TRUE, atol=0.07)
    assert abs(beta[-1] - BIAS_TRUE) < 0.07
    assert abs(float(np.exp(params["log_sigma"])) - SIGMA_TRUE) < 0.07
    assert np.isfinite(float(aux["loss"]))


def test_aft_censoring_handled_not_ignored():
    """With 40% right-censoring, the censor-aware fit recovers β;
    treating censored rows as observed events biases μ down."""
    X, y, delta = _weibull_data(censor_frac=0.4, seed=3)
    learner = AFTSurvivalRegression(max_iter=500, lr=0.05, l2=0.0)
    p_aware, _ = _direct_fit(learner, X, y, delta)
    p_naive, _ = _direct_fit(learner, X, y, np.ones_like(delta))
    err_aware = np.abs(np.asarray(p_aware["beta"])[:-1] - BETA_TRUE).max()
    err_naive = np.abs(np.asarray(p_naive["beta"])[:-1] - BETA_TRUE).max()
    assert err_aware < 0.1
    # the naive fit is measurably worse on the bias/scale front: its
    # location must undershoot (censored times read as early events)
    assert np.asarray(p_naive["beta"])[-1] < np.asarray(p_aware["beta"])[-1]
    assert err_aware <= err_naive + 1e-6


def test_aft_quantiles():
    X, y, delta = _weibull_data(n=500)
    learner = AFTSurvivalRegression(max_iter=200)
    params, _ = _direct_fit(learner, X, y, delta)
    q = np.asarray(
        learner.predict_quantiles(params, X[:16], [0.1, 0.5, 0.9])
    )
    assert q.shape == (16, 3)
    assert (np.diff(q, axis=1) > 0).all()  # monotone in p
    # median: t_.5 = exp(mu + sigma*log(log 2))
    mu = np.log(np.asarray(learner.predict_scores(params, X[:16])))
    sigma = float(np.exp(params["log_sigma"]))
    np.testing.assert_allclose(
        q[:, 1], np.exp(mu + sigma * np.log(np.log(2.0))), rtol=1e-4
    )


def test_bagged_aft_fit_predict():
    X, y, delta = _weibull_data(censor_frac=0.3, seed=5)
    reg = BaggingRegressor(
        base_learner=AFTSurvivalRegression(max_iter=300),
        n_estimators=8, seed=0,
    ).fit(X, y, aux=delta)
    pred = reg.predict(X)
    assert pred.shape == y.shape and (pred > 0).all()
    # predicted e^mu tracks the underlying time scale
    corr = np.corrcoef(np.log(pred), X @ BETA_TRUE + BIAS_TRUE)[0, 1]
    assert corr > 0.95
    assert np.isfinite(reg.fit_report_["loss_mean"])


def test_aux_rejected_for_non_aux_learner():
    X, y, delta = _weibull_data(n=200)
    reg = BaggingRegressor(
        base_learner=LinearRegression(), n_estimators=2, seed=0
    )
    with pytest.raises(ValueError, match="uses_aux"):
        reg.fit(X, y, aux=delta)


def test_aux_shape_validated():
    X, y, delta = _weibull_data(n=200)
    reg = BaggingRegressor(
        base_learner=AFTSurvivalRegression(max_iter=10),
        n_estimators=2, seed=0,
    )
    with pytest.raises(ValueError, match="aux shape"):
        reg.fit(X, y, aux=delta[:-5])


@pytest.mark.slow  # [PR 14 pyramid] ~1.8s mesh twin; replica-mesh parity stays tier-1 generic
def test_bagged_aft_replica_mesh_matches_unsharded():
    """Replica-sharded aux fit ≡ unsharded (the test_sharded.py:53
    equality contract, now with the aux channel in the program)."""
    X, y, delta = _weibull_data(n=512, censor_frac=0.3, seed=7)
    kw = dict(
        base_learner=AFTSurvivalRegression(max_iter=60),
        n_estimators=8, seed=2,
    )
    a = BaggingRegressor(**kw).fit(X, y, aux=delta)
    b = BaggingRegressor(**kw, mesh=make_mesh()).fit(X, y, aux=delta)
    np.testing.assert_allclose(
        a.predict(X[:64]), b.predict(X[:64]), rtol=2e-5, atol=2e-5
    )


def test_bagged_aft_data_mesh_runs():
    X, y, delta = _weibull_data(n=512, censor_frac=0.3, seed=9)
    reg = BaggingRegressor(
        base_learner=AFTSurvivalRegression(max_iter=60),
        n_estimators=8, seed=2, mesh=make_mesh(data=2),
    ).fit(X, y, aux=delta)
    pred = reg.predict(X[:64])
    assert np.isfinite(pred).all() and (pred > 0).all()


@pytest.mark.slow  # [PR 14 pyramid] ~1.7s quantile API quality soak; AFT fit invariants stay tier-1
def test_bagged_aft_predict_quantiles():
    X, y, delta = _weibull_data(n=400, censor_frac=0.2, seed=4)
    reg = BaggingRegressor(
        base_learner=AFTSurvivalRegression(max_iter=100),
        n_estimators=4, seed=0,
    ).fit(X, y, aux=delta)
    q = reg.predict_quantiles(X[:32], probs=(0.25, 0.5, 0.75))
    assert q.shape == (32, 3)
    assert (np.diff(q, axis=1) > 0).all()
    with pytest.raises(AttributeError, match="predict_quantiles"):
        BaggingRegressor(
            base_learner=LinearRegression(), n_estimators=2, seed=0
        ).fit(X, y).predict_quantiles(X[:4])


def test_aft_checkpoint_roundtrip(tmp_path):
    X, y, delta = _weibull_data(n=400, censor_frac=0.2, seed=11)
    reg = BaggingRegressor(
        base_learner=AFTSurvivalRegression(max_iter=50),
        n_estimators=4, seed=0,
    ).fit(X, y, aux=delta)
    path = str(tmp_path / "aft_ckpt")
    save_model(reg, path)
    loaded = load_model(path)
    np.testing.assert_allclose(
        reg.predict(X[:32]), loaded.predict(X[:32]), rtol=1e-6
    )


@pytest.mark.slow  # [PR 14 pyramid] ~2.3s AFT stream soak; aux-col convention guard stays tier-1
def test_streamed_aft_aux_col():
    """AFT streams out-of-core with the censor indicator carried as a
    designated column (Spark's censorCol-as-a-column convention):
    streamed quality ≈ in-memory quality, feature space excludes the
    aux column, and streamed OOB runs on the same source."""
    X, y, delta = _weibull_data(n=2000, censor_frac=0.3, seed=17)
    mem = BaggingRegressor(
        base_learner=AFTSurvivalRegression(max_iter=300),
        n_estimators=4, seed=0,
    ).fit(X, y, aux=delta)

    Xs = np.concatenate([X, delta[:, None]], axis=1)  # aux as last col
    stream = BaggingRegressor(
        base_learner=AFTSurvivalRegression(),
        n_estimators=4, seed=0, oob_score=True,
    ).fit_stream(
        (Xs, y), chunk_rows=256, n_epochs=40, steps_per_chunk=2,
        lr=0.05, aux_col=-1,
    )
    assert stream.n_features_in_ == X.shape[1]
    p_mem, p_stream = mem.predict(X[:200]), stream.predict(X[:200])
    corr = np.corrcoef(np.log(p_mem), np.log(p_stream))[0, 1]
    assert corr > 0.97
    assert np.isfinite(stream.oob_prediction_[
        ~np.isnan(stream.oob_prediction_)
    ]).all()
    rep = stream.fit_report_
    assert rep["model_flops_per_fit"] > 0  # streamed MFU accounting


def test_streamed_aux_col_rejected_for_non_aux_learner():
    X, y, delta = _weibull_data(n=300)
    Xs = np.concatenate([X, delta[:, None]], axis=1)
    with pytest.raises(ValueError, match="uses_aux"):
        BaggingRegressor(
            base_learner=LinearRegression(), n_estimators=2, seed=0
        ).fit_stream((Xs, y), chunk_rows=128, aux_col=-1)


@pytest.mark.slow  # [PR 19 budget offset] ~2.0s aux-col warning-path soak; the stream-fit seam stays tier-1 via test_streamed_aft_scores_its_own_training_source
def test_streamed_aft_without_aux_col_warns():
    """Streaming a uses_aux learner with no aux_col is legal (genuinely
    fully-observed data) but easy to do by accident — it must warn."""
    X, y, delta = _weibull_data(n=300)
    with pytest.warns(UserWarning, match="aux_col"):
        BaggingRegressor(
            base_learner=AFTSurvivalRegression(), n_estimators=2, seed=0
        ).fit_stream((X, y), chunk_rows=128, n_epochs=2, lr=0.05)


def test_aft_sample_weight_and_aux_coexist():
    X, y, delta = _weibull_data(n=400, censor_frac=0.2, seed=13)
    sw = np.ones(len(y), np.float32)
    sw[: len(y) // 2] = 2.0
    reg = BaggingRegressor(
        base_learner=AFTSurvivalRegression(max_iter=50),
        n_estimators=4, seed=0,
    ).fit(X, y, sample_weight=sw, aux=delta)
    assert np.isfinite(reg.predict(X[:16])).all()


def test_streamed_aft_scores_its_own_training_source():
    """A stream-fitted AFT model must consume the SAME wide source it
    was trained on: predict_stream/score_stream drop the fitted aux
    column exactly as the fit and OOB passes do."""
    X, y, delta = _weibull_data(n=1200, censor_frac=0.3, seed=5)
    Xs = np.concatenate([X, delta[:, None]], axis=1)
    reg = BaggingRegressor(
        base_learner=AFTSurvivalRegression(),
        n_estimators=3, seed=0,
    ).fit_stream((Xs, y), chunk_rows=256, n_epochs=5, aux_col=-1)

    # the width-heuristic auto-drop warns when it engages (round-3
    # advisor: a genuinely-wider different dataset would otherwise be
    # silently mis-scored); drop_aux_col=True opts in silently
    with pytest.warns(UserWarning, match="dropping column"):
        preds = reg.predict_stream((Xs, y), chunk_rows=256)
    assert preds.shape == (len(y),)
    # matches predicting on the narrow matrix directly
    np.testing.assert_allclose(preds, reg.predict(X), rtol=1e-5)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        np.testing.assert_allclose(
            reg.predict_stream((Xs, y), chunk_rows=256,
                               drop_aux_col=True),
            preds, rtol=1e-5,
        )
        assert np.isfinite(reg.score_stream((Xs, y), chunk_rows=256,
                                            drop_aux_col=True))
    # the escape hatch: a caller scoring a dataset that HAPPENS to be
    # one column wider gets the width error, not a silent column drop
    with pytest.raises(ValueError, match="features"):
        reg.predict_stream((Xs, y), chunk_rows=256, drop_aux_col=False)
    # ...and force-drop on a narrow source is an explicit error too
    with pytest.raises(ValueError, match="drop_aux_col"):
        reg.predict_stream((X, y), chunk_rows=256, drop_aux_col=True)
    # a narrow (already aux-free) source keeps working too
    np.testing.assert_allclose(
        reg.predict_stream((X, y), chunk_rows=256), preds, rtol=1e-5
    )
    # the contract must not depend on whether the caller prefetch-
    # wrapped first: the aux drop splices inside the wrap
    from spark_bagging_tpu import ArrayChunks
    from spark_bagging_tpu.utils.prefetch import PrefetchChunks

    wrapped = PrefetchChunks(ArrayChunks(Xs, y, chunk_rows=256), depth=3)
    np.testing.assert_allclose(
        reg.predict_stream(wrapped), preds, rtol=1e-5
    )


@pytest.mark.slow  # [PR 17 budget offset] ~1.7s stream/refit isolation soak; the aux-column convention stays tier-1 via test_streamed_aft_scores_its_own_training_source
def test_stream_aux_convention_does_not_leak_into_memory_refit():
    """An in-memory refit clears the prior fit_stream's aux column, so
    a later (D+1)-wide stream source gets the honest width error, not a
    silent column drop computed for the OLD fit (round-4 audit)."""
    X, y, delta = _weibull_data(n=800, censor_frac=0.3, seed=7)
    wide = np.concatenate([X, delta[:, None]], axis=1)
    reg = BaggingRegressor(
        base_learner=AFTSurvivalRegression(), n_estimators=2, seed=0,
    ).fit_stream((wide, y), chunk_rows=256, n_epochs=2, aux_col=-1)
    # refit in-memory on the WIDE matrix as plain features
    reg2 = BaggingRegressor(
        base_learner=AFTSurvivalRegression(), n_estimators=2, seed=0,
    )
    reg2.__dict__.update(reg.__dict__)  # same instance state
    reg2.fit(wide, y, aux=delta)
    # a (D+2)-wide source is now a genuine mismatch — must raise
    wider = np.concatenate([wide, delta[:, None]], axis=1)
    with pytest.raises(ValueError, match="features"):
        reg2.predict_stream((wider, y), chunk_rows=256)


def test_aft_reports_final_loss_and_curve():
    """The reported loss is evaluated AT the final params (not one Adam
    step stale) and the curve rides along like every other learner."""
    import jax
    import jax.numpy as jnp

    X, y, delta = _weibull_data(n=300, censor_frac=0.2, seed=3)
    aft = AFTSurvivalRegression(max_iter=50)
    p0 = aft.init_params(jax.random.key(0), X.shape[1], 1)
    params, aux = aft.fit(
        p0, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)),
        jax.random.key(1), aux=jnp.asarray(delta),
    )
    assert aux["loss_curve"].shape == (50,)
    # final loss should not exceed the last pre-update evaluation
    assert float(aux["loss"]) <= float(aux["loss_curve"][-1]) + 1e-5
