"""Persistence round-trip tests — save → load → identical transform
output [SURVEY §4, §3.3]."""

import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_diabetes, load_iris
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    LogisticRegression,
    load_model,
    save_model,
)


@pytest.fixture(scope="module")
def iris():
    X, y = load_iris(return_X_y=True)
    return StandardScaler().fit_transform(X).astype(np.float32), y


@pytest.mark.slow  # [PR 17 budget offset] ~3.6s full classifier roundtrip soak; the roundtrip contract stays tier-1 via test_string_label_roundtrip + test_auto_chunk_resolution_survives_roundtrip + test_aft_checkpoint_roundtrip
def test_classifier_roundtrip(tmp_path, iris):
    X, y = iris
    clf = BaggingClassifier(
        base_learner=LogisticRegression(l2=0.01, max_iter=10),
        n_estimators=6,
        max_features=0.5,
        voting="hard",
        seed=4,
        oob_score=True,
    ).fit(X, y)
    clf.save(str(tmp_path / "m"))
    loaded = BaggingClassifier.load(str(tmp_path / "m"))
    np.testing.assert_array_equal(loaded.predict(X), clf.predict(X))
    np.testing.assert_allclose(loaded.predict_proba(X), clf.predict_proba(X))
    assert loaded.n_estimators_ == 6
    assert loaded.oob_score_ == clf.oob_score_
    np.testing.assert_allclose(
        loaded.oob_decision_function_, clf.oob_decision_function_
    )
    assert loaded.base_learner.l2 == 0.01
    assert loaded._fitted_learner == clf._fitted_learner
    np.testing.assert_array_equal(loaded.classes_, clf.classes_)
    # the bootstrap replays through the checkpoint: the loaded model's
    # regenerated per-replica weights match the original's
    np.testing.assert_array_equal(
        loaded.replica_weights(3), clf.replica_weights(3)
    )


def test_string_label_roundtrip(tmp_path, iris):
    X, y = iris
    names = np.array(["a", "b", "c"])[y]
    clf = BaggingClassifier(n_estimators=3).fit(X, names)
    save_model(clf, str(tmp_path / "m"))
    loaded = load_model(str(tmp_path / "m"))
    np.testing.assert_array_equal(loaded.predict(X), clf.predict(X))
    assert loaded.classes_.tolist() == ["a", "b", "c"]


def test_regressor_roundtrip(tmp_path):
    X, y = load_diabetes(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    reg = BaggingRegressor(n_estimators=5, seed=2).fit(X, y)
    reg.save(str(tmp_path / "r"))
    loaded = BaggingRegressor.load(str(tmp_path / "r"))
    np.testing.assert_allclose(loaded.predict(X), reg.predict(X))
    assert loaded.fit_report_["n_replicas"] == 5


def test_load_wrong_class_raises(tmp_path, iris):
    X, y = iris
    BaggingClassifier(n_estimators=2).fit(X, y).save(str(tmp_path / "m"))
    with pytest.raises(TypeError, match="BaggingRegressor"):
        BaggingRegressor.load(str(tmp_path / "m"))


def test_save_unfitted_raises(tmp_path):
    with pytest.raises(RuntimeError, match="not fitted"):
        save_model(BaggingClassifier(), str(tmp_path / "m"))


def test_future_format_version_rejected(tmp_path, iris):
    import json
    import os

    X, y = iris
    BaggingClassifier(n_estimators=2).fit(X, y).save(str(tmp_path / "m"))
    mf = os.path.join(tmp_path, "m", "manifest.json")
    with open(mf) as f:
        manifest = json.load(f)
    manifest["format_version"] = 99
    with open(mf, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="newer"):
        load_model(str(tmp_path / "m"))


@pytest.mark.slow  # [PR 14 pyramid] ~1.7s OOB-through-checkpoint soak; the round-trip + OOB contracts each stay tier-1 separately
def test_loaded_model_oob_reproducible(tmp_path):
    """The fit key is persisted, so OOB weights can be regenerated after
    load (shard-local regeneration property)."""
    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)
    clf = BaggingClassifier(n_estimators=8, seed=3).fit(X, y)
    clf.save(str(tmp_path / "m"))
    loaded = BaggingClassifier.load(str(tmp_path / "m"))
    counts_a, votes_a = clf._oob_scores(X, clf.n_classes_)
    counts_b, votes_b = loaded._oob_scores(X, loaded.n_classes_)
    np.testing.assert_array_equal(votes_a, votes_b)
    np.testing.assert_allclose(counts_a, counts_b)


def test_checkpoint_zstd_compression(tmp_path, iris):
    """zstd payload compression [SURVEY §2b codec analog]: auto mode
    writes .zst when zstandard is available; load auto-detects both."""
    pytest.importorskip("zstandard")
    import os

    X, y = iris
    clf = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)

    p_auto = str(tmp_path / "auto")
    clf.save(p_auto)
    assert os.path.exists(os.path.join(p_auto, "arrays.msgpack.zst"))
    assert not os.path.exists(os.path.join(p_auto, "arrays.msgpack"))
    loaded = BaggingClassifier.load(p_auto)
    np.testing.assert_allclose(
        clf.predict_proba(X), loaded.predict_proba(X), rtol=1e-6
    )

    p_raw = str(tmp_path / "raw")
    clf.save(p_raw, compress=False)
    assert os.path.exists(os.path.join(p_raw, "arrays.msgpack"))
    loaded_raw = BaggingClassifier.load(p_raw)
    np.testing.assert_allclose(
        clf.predict_proba(X), loaded_raw.predict_proba(X), rtol=1e-6
    )


def test_checkpoint_zlib_fallback_without_zstandard(tmp_path, iris,
                                                    monkeypatch):
    """zstandard is a SOFT dependency: with the module missing, auto
    and compress=True both degrade to the stdlib zlib codec (one-time
    warning, .z suffix) instead of raising or silently writing raw —
    and load auto-detects the fallback format."""
    import os
    import warnings

    from spark_bagging_tpu.utils import checkpoint as ckpt, io as sbt_io

    monkeypatch.setattr(ckpt, "_zstd", lambda: None)
    monkeypatch.setattr(sbt_io, "_WARNED_NO_ZSTD", False)

    X, y = iris
    clf = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
    p = str(tmp_path / "m")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        clf.save(p, compress=True)
    assert any("zlib" in str(x.message) for x in w), "fallback must warn"
    assert os.path.exists(os.path.join(p, "arrays.msgpack.z"))
    assert not os.path.exists(os.path.join(p, "arrays.msgpack"))
    loaded = BaggingClassifier.load(p)
    np.testing.assert_allclose(
        clf.predict_proba(X), loaded.predict_proba(X), rtol=1e-6
    )
    # the warning is one-time per process
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        clf.save(str(tmp_path / "m2"))
    assert not any("zlib" in str(x.message) for x in w2)


def test_auto_chunk_resolution_survives_roundtrip(tmp_path, iris):
    """An auto-chunked fit's resolved chunk must survive save/load, or
    the loaded model's predict/OOB maps vmap all replicas at once —
    the OOM the HBM-aware resolution exists to avoid."""
    X, y = iris
    clf = BaggingClassifier(n_estimators=8, seed=0).fit(X, y)
    clf._chunk_resolved = 3  # as the fit's auto resolution would set
    save_model(clf, str(tmp_path / "m"))
    loaded = load_model(str(tmp_path / "m"))
    assert loaded._eff_chunk() == 3
    np.testing.assert_array_equal(loaded.predict(X), clf.predict(X))


def test_crash_mid_swap_recovers_previous_checkpoint(tmp_path, iris):
    """The save swap is two renames; a crash between them leaves the
    previous complete checkpoint at the pid-INDEPENDENT ``path.old``,
    which load_model falls back to (round-3 advisor finding) and the
    next successful save cleans up along with any stale tmp debris."""
    import os
    import shutil

    X, y = iris
    path = str(tmp_path / "m")
    a = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
    save_model(a, path)
    # simulate the crash window: path renamed away, replacement not in
    shutil.move(path, path + ".old")
    # plus tmp debris from a DEAD process (reaping is pid-liveness
    # gated so a live concurrent saver's tmp is never pulled away)
    import subprocess
    import sys

    proc = subprocess.Popen([sys.executable, "-c", ""])
    proc.wait()
    debris = f"{path}.tmp.{proc.pid}"
    os.makedirs(debris)
    # and tmp debris from a LIVE process, which must survive the save
    live = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(30)"])
    live_tmp = f"{path}.tmp.{live.pid}"
    os.makedirs(live_tmp)
    with pytest.warns(UserWarning, match="mid-swap"):
        loaded = load_model(path)
    np.testing.assert_array_equal(loaded.predict(X), a.predict(X))
    # a later save from ANY process heals the slot and clears dead
    # debris — but only AFTER its own install (the recovery slot must
    # survive a crash during the new save's build), and never a live
    # process's tmp
    b = BaggingClassifier(n_estimators=4, seed=1).fit(X, y)
    try:
        save_model(b, path)
        assert not os.path.exists(path + ".old")
        assert not os.path.exists(debris)
        assert os.path.exists(live_tmp)
    finally:
        live.kill()
        live.wait()
    np.testing.assert_array_equal(load_model(path).predict(X), b.predict(X))


def test_resave_under_other_compression_never_loads_stale(tmp_path, iris):
    """A re-save must atomically replace the whole checkpoint dir: the
    old run's arrays file in the OTHER compression format must not
    survive to shadow the new weights at load time."""
    X, y = iris
    path = str(tmp_path / "m")
    a = BaggingClassifier(n_estimators=4, seed=0).fit(X, y)
    save_model(a, path, compress=True)   # writes arrays.msgpack.zst
    b = BaggingClassifier(n_estimators=4, seed=1).fit(X, y)
    save_model(b, path, compress=False)  # raw msgpack, same dir
    import os
    assert not os.path.exists(os.path.join(path, "arrays.msgpack.zst"))
    loaded = load_model(path)
    np.testing.assert_array_equal(loaded.predict(X), b.predict(X))


def test_stale_rng_schema_disables_weight_replay(tmp_path, iris):
    """A checkpoint saved under an older (or unrecorded) bootstrap
    key-derivation schema must not silently replay weights that don't
    match what its replicas were trained on [ADVICE r4 medium]: load
    warns, replica_weights() raises, predictions are unaffected."""
    import json
    import os

    X, y = iris
    clf = BaggingClassifier(n_estimators=4, seed=1).fit(X, y)
    path = str(tmp_path / "m")
    clf.save(path)

    # current-schema load replays fine, no warning
    loaded = BaggingClassifier.load(path)
    np.testing.assert_array_equal(
        loaded.replica_weights(0), clf.replica_weights(0)
    )

    # simulate a pre-retag save: older schema number, then absent key
    mpath = os.path.join(path, "manifest.json")
    manifest = json.load(open(mpath))
    for stale in (1, None):
        if stale is None:
            manifest["fitted"].pop("rng_schema", None)
        else:
            manifest["fitted"]["rng_schema"] = stale
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        with pytest.warns(UserWarning, match="RNG schema"):
            stale_model = BaggingClassifier.load(path)
        np.testing.assert_array_equal(stale_model.predict(X), clf.predict(X))
        with pytest.raises(ValueError, match="replayable"):
            stale_model.replica_weights(0)
