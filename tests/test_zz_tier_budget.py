"""Tier-1 wall-clock budget ratchet [ROADMAP item 5, ISSUE 11].

The tier-1 ceiling (the 870 s ``timeout`` in the verify command) used
to be rediscovered the hard way: the tree grew until a run hit RC 124.
This file IS the continuous enforcement — it sorts last by filename
(the tier runs with ``-p no:randomly``, so collection order is file
order), measures the session's own elapsed wall-clock against the
allocation, and fails with an actionable message while the run still
finishes under the hard timeout.

The allocation is deliberately BELOW the ceiling (90%): the ratchet
must fire before the cliff, not be killed by it. When it trips, the
fix is the PR-9/PR-11 discipline — move an equivalent amount of
existing heavyweight tests to ``slow`` (with per-test reason comments)
or restructure the tier — never raising the allocation to make the
light turn green.
"""

import time

import pytest

#: the tier-1 verify command's hard timeout (ROADMAP)
TIER1_CEILING_S = 870.0
#: the ratchet fires at 90% — early warning, not post-mortem
TIER1_ALLOCATION_S = 0.9 * TIER1_CEILING_S

#: a session smaller than this is a targeted run (-k, one file), not
#: the tier — the ratchet only means something over the full suite
FULL_TIER_MIN_ITEMS = 600


def test_tier1_wall_clock_within_allocation(request):
    collected = request.session.testscollected
    if collected < FULL_TIER_MIN_ITEMS:
        pytest.skip(
            f"partial session ({collected} items): the budget ratchet "
            "gates only full tier-1 runs"
        )
    elapsed = time.monotonic() - request.config._sbt_tier_t0
    assert elapsed < TIER1_ALLOCATION_S, (
        f"tier-1 measured {elapsed:.0f}s against its "
        f"{TIER1_ALLOCATION_S:.0f}s allocation ({TIER1_CEILING_S:.0f}s "
        "hard ceiling): move heavyweight tests to -m slow (with "
        "per-test reason comments) or split the tier — do NOT raise "
        "the allocation"
    )
