"""Tier-1 wall-clock budget ratchet [ROADMAP item 5, ISSUE 11/14].

The tier-1 ceiling (the 870 s ``timeout`` in the verify command) used
to be rediscovered the hard way: the tree grew until a run hit RC 124.
This file IS the continuous enforcement — it sorts last by filename
(the tier runs with ``-p no:randomly``, so collection order is file
order), measures the session's own elapsed wall-clock against the
allocation, and fails with an actionable message while the run still
finishes under the hard timeout.

Since the ISSUE 14 pyramid restructure the allocation is the tier's
own budget — tier-1 is a sub-450 s set of contract/parity/gate tests
plus the scenario-conformance smoke, with heavyweight soaks living in
``slow`` — not a fraction of the driver timeout. When the ratchet
trips, the fix is the standing discipline: move an equivalent amount
of existing heavyweight tests to ``slow`` (with per-test reason
comments), or turn suite weight into a registered scenario
(``benchmarks/scenarios``) whose digests carry the coverage for
pennies — never raising the allocation to make the light turn green.

The ratchet also WRITES what it measured: a per-module artifact
(``telemetry_dir()/tier1_timings.json`` — wall-clock seconds plus the
ran/skipped/``slow``-deselected split per module, heaviest first) and,
for full-tier sessions, one longitudinal record in the history store
(``telemetry/history.py``) so tier wall-clock is a trended series,
not a rediscovery.
"""

import json
import os
import time

import pytest

#: the tier-1 verify command's hard timeout (ROADMAP)
TIER1_CEILING_S = 870.0
#: the tier's own budget since the ISSUE 14 pyramid restructure:
#: tier-1 is a sub-450 s set BY CONSTRUCTION, and the ratchet enforces
#: that construction continuously (the 870 s driver timeout is the
#: cliff far behind it)
TIER1_ALLOCATION_S = 450.0

#: a session smaller than this is a targeted run (-k, one file), not
#: the tier — the ratchet only means something over the full suite
FULL_TIER_MIN_ITEMS = 600

TIMINGS_SCHEMA_VERSION = 2

#: per-module artifact entry fields (the round-trip test pins these)
MODULE_FIELDS = ("seconds", "tests", "skipped", "slow_deselected")


def build_timings_artifact(
    module_times: dict[str, float],
    module_stats: dict[str, dict],
    collected: int,
    elapsed: float,
) -> dict:
    """The artifact dict, pure (testable without a pytest session):
    per-module wall-clock seconds joined with the ran/skipped/slow
    split, heaviest module first."""
    modules = {}
    for mod in sorted(module_times, key=lambda m: -module_times[m]):
        stats = module_stats.get(mod, {})
        modules[mod] = {
            "seconds": round(module_times[mod], 3),
            "tests": int(stats.get("tests", 0)),
            "skipped": int(stats.get("skipped", 0)),
            "slow_deselected": int(stats.get("slow_deselected", 0)),
        }
    return {
        "schema": TIMINGS_SCHEMA_VERSION,
        "ts": time.time(),
        "collected": collected,
        "full_tier": collected >= FULL_TIER_MIN_ITEMS,
        "elapsed_s": round(elapsed, 3),
        "allocation_s": TIER1_ALLOCATION_S,
        "ceiling_s": TIER1_CEILING_S,
        "modules": modules,
    }


def validate_timings_artifact(artifact: dict) -> None:
    """Loud schema check for the artifact (used by the round-trip test
    and by any future consumer that wants to fail fast on drift)."""
    for key, typ in (("schema", int), ("ts", float),
                     ("collected", int), ("full_tier", bool),
                     ("elapsed_s", float), ("allocation_s", float),
                     ("ceiling_s", float), ("modules", dict)):
        if not isinstance(artifact.get(key), typ):
            raise ValueError(
                f"timings artifact field {key!r} missing or not "
                f"{typ.__name__}: {artifact.get(key)!r}"
            )
    if artifact["schema"] != TIMINGS_SCHEMA_VERSION:
        raise ValueError(
            f"timings artifact schema {artifact['schema']} != "
            f"{TIMINGS_SCHEMA_VERSION}"
        )
    for mod, entry in artifact["modules"].items():
        if not isinstance(entry, dict):
            raise ValueError(f"module entry {mod!r} is not a dict")
        for f in MODULE_FIELDS:
            if not isinstance(entry.get(f), (int, float)):
                raise ValueError(
                    f"module entry {mod!r} field {f!r} missing or "
                    f"non-numeric: {entry.get(f)!r}"
                )


def _write_timings_artifact(config, collected: int,
                            elapsed: float) -> None:
    """Write the artifact + (full sessions only) the longitudinal
    history record. Best-effort: measurement must never fail the tier
    it measures."""
    modules = getattr(config, "_sbt_module_times", None)
    if not modules:
        return
    stats = getattr(config, "_sbt_module_stats", None) or {}
    try:
        from spark_bagging_tpu.telemetry import telemetry_dir

        artifact = build_timings_artifact(modules, stats, collected,
                                          elapsed)
        path = os.path.join(telemetry_dir(), "tier1_timings.json")
        with open(path, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")
        if artifact["full_tier"]:
            # one trended record per FULL tier session (partial -k
            # runs would pollute the elapsed_s series with noise)
            from spark_bagging_tpu import telemetry
            from spark_bagging_tpu.telemetry import history

            telemetry.enable()
            history.append_record(
                "tier", "tier1",
                numbers={"elapsed_s": artifact["elapsed_s"],
                         "collected": float(collected)},
                detail={
                    "allocation_s": TIER1_ALLOCATION_S,
                    "modules": {m: e["seconds"]
                                for m, e in artifact["modules"].items()},
                },
            )
    except Exception as e:  # noqa: BLE001 — observability only
        import warnings

        warnings.warn(f"tier1_timings.json not written: {e!r}",
                      RuntimeWarning)


def test_timings_artifact_roundtrip(tmp_path):
    """Satellite [ISSUE 14]: the artifact schema round-trips — what
    the builder writes, a JSON reader gets back with the per-module
    seconds AND the ran/skipped/slow split intact, and the validator
    accepts it (and rejects the schema-less v1 shape)."""
    times = {"tests/test_a.py": 12.345678, "tests/test_b.py": 0.5}
    stats = {
        "tests/test_a.py": {"tests": 10, "skipped": 2,
                            "slow_deselected": 3},
        # test_b deliberately absent: modules with no stats entry
        # must degrade to zeros, not KeyError
    }
    artifact = build_timings_artifact(times, stats, collected=700,
                                      elapsed=123.456789)
    path = tmp_path / "tier1_timings.json"
    path.write_text(json.dumps(artifact, indent=2))
    back = json.loads(path.read_text())
    validate_timings_artifact(back)
    assert back["schema"] == TIMINGS_SCHEMA_VERSION
    assert back["full_tier"] is True
    assert back["elapsed_s"] == 123.457
    # heaviest first, split preserved
    assert list(back["modules"]) == ["tests/test_a.py",
                                     "tests/test_b.py"]
    a = back["modules"]["tests/test_a.py"]
    assert a == {"seconds": 12.346, "tests": 10, "skipped": 2,
                 "slow_deselected": 3}
    b = back["modules"]["tests/test_b.py"]
    assert b == {"seconds": 0.5, "tests": 0, "skipped": 0,
                 "slow_deselected": 0}
    # the v1 shape (flat seconds map) is rejected, loudly
    v1 = dict(back)
    v1["modules"] = {"tests/test_a.py": 12.3}
    with pytest.raises(ValueError, match="not a dict"):
        validate_timings_artifact(v1)
    v1 = dict(back)
    v1.pop("schema")
    with pytest.raises(ValueError, match="schema"):
        validate_timings_artifact(v1)


def test_conftest_accumulators_are_live(request):
    """The conftest hooks really feed the artifact's inputs: this very
    session has module times for this module, and the stats dict
    carries the counter keys the artifact schema expects."""
    times = getattr(request.config, "_sbt_module_times", None)
    stats = getattr(request.config, "_sbt_module_stats", None)
    assert times is not None and stats is not None
    mod = "tests/test_zz_tier_budget.py"
    assert mod in times  # the round-trip test above already reported
    assert set(stats[mod]) == {"tests", "skipped", "slow_deselected"}
    assert stats[mod]["tests"] >= 1


def test_zz_tier1_wall_clock_within_allocation(request):
    collected = request.session.testscollected
    elapsed = time.monotonic() - request.config._sbt_tier_t0
    # write the artifact BEFORE any skip/assert: partial sessions
    # still record what they measured (flagged full_tier=false)
    _write_timings_artifact(request.config, collected, elapsed)
    if collected < FULL_TIER_MIN_ITEMS:
        pytest.skip(
            f"partial session ({collected} items): the budget ratchet "
            "gates only full tier-1 runs"
        )
    assert elapsed < TIER1_ALLOCATION_S, (
        f"tier-1 measured {elapsed:.0f}s against its "
        f"{TIER1_ALLOCATION_S:.0f}s allocation ({TIER1_CEILING_S:.0f}s "
        "hard ceiling): move heavyweight tests to -m slow (with "
        "per-test reason comments) or turn the weight into a "
        "registered benchmarks/scenarios scenario — do NOT raise "
        "the allocation"
    )
