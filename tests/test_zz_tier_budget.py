"""Tier-1 wall-clock budget ratchet [ROADMAP item 5, ISSUE 11].

The tier-1 ceiling (the 870 s ``timeout`` in the verify command) used
to be rediscovered the hard way: the tree grew until a run hit RC 124.
This file IS the continuous enforcement — it sorts last by filename
(the tier runs with ``-p no:randomly``, so collection order is file
order), measures the session's own elapsed wall-clock against the
allocation, and fails with an actionable message while the run still
finishes under the hard timeout.

The allocation is deliberately BELOW the ceiling (90%): the ratchet
must fire before the cliff, not be killed by it. When it trips, the
fix is the PR-9/PR-11 discipline — move an equivalent amount of
existing heavyweight tests to ``slow`` (with per-test reason comments)
or restructure the tier — never raising the allocation to make the
light turn green.

The ratchet also WRITES what it measured: a per-module wall-clock
artifact (``telemetry_dir()/tier1_timings.json``, modules sorted
heaviest first) — test-suite observability for ROADMAP item 5, so the
tier-restructuring PR starts from data this run already paid for.
"""

import json
import os
import time

import pytest

#: the tier-1 verify command's hard timeout (ROADMAP)
TIER1_CEILING_S = 870.0
#: the ratchet fires at 90% — early warning, not post-mortem
TIER1_ALLOCATION_S = 0.9 * TIER1_CEILING_S

#: a session smaller than this is a targeted run (-k, one file), not
#: the tier — the ratchet only means something over the full suite
FULL_TIER_MIN_ITEMS = 600


def _write_timings_artifact(config, collected: int,
                            elapsed: float) -> None:
    """Write the per-module wall-clock JSON artifact. Best-effort:
    measurement must never fail the tier it measures."""
    modules = getattr(config, "_sbt_module_times", None)
    if not modules:
        return
    try:
        from spark_bagging_tpu.telemetry import telemetry_dir

        path = os.path.join(telemetry_dir(), "tier1_timings.json")
        ordered = dict(sorted(modules.items(),
                              key=lambda kv: -kv[1]))
        with open(path, "w") as f:
            json.dump({
                "ts": time.time(),
                "collected": collected,
                "full_tier": collected >= FULL_TIER_MIN_ITEMS,
                "elapsed_s": round(elapsed, 3),
                "allocation_s": TIER1_ALLOCATION_S,
                "ceiling_s": TIER1_CEILING_S,
                "modules": {m: round(s, 3)
                            for m, s in ordered.items()},
            }, f, indent=2)
            f.write("\n")
    except Exception as e:  # noqa: BLE001 — observability only
        import warnings

        warnings.warn(f"tier1_timings.json not written: {e!r}",
                      RuntimeWarning)


def test_tier1_wall_clock_within_allocation(request):
    collected = request.session.testscollected
    elapsed = time.monotonic() - request.config._sbt_tier_t0
    # write the artifact BEFORE any skip/assert: partial sessions
    # still record what they measured (flagged full_tier=false)
    _write_timings_artifact(request.config, collected, elapsed)
    if collected < FULL_TIER_MIN_ITEMS:
        pytest.skip(
            f"partial session ({collected} items): the budget ratchet "
            "gates only full tier-1 runs"
        )
    assert elapsed < TIER1_ALLOCATION_S, (
        f"tier-1 measured {elapsed:.0f}s against its "
        f"{TIER1_ALLOCATION_S:.0f}s allocation ({TIER1_CEILING_S:.0f}s "
        "hard ceiling): move heavyweight tests to -m slow (with "
        "per-test reason comments) or split the tier — do NOT raise "
        "the allocation"
    )
