"""Performance attribution plane [ISSUE 13]: per-stage cost
accounting fed from the trace breakdowns, the measured per-bucket cost
model (seconds-per-row / achieved FLOP/s / MFU), the deterministic
tail-latency explainer (`correlate_tail` + /debug/tail), on-demand
live device profiling (/debug/profile, single-flight + auto-stop),
the latency-histogram slow-exemplar reservoir, and the zero-overhead
contract of the new hot-path probes."""

import json
import os
import time

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    LogisticRegression,
    telemetry,
)
from spark_bagging_tpu.serving import EnsembleExecutor, MicroBatcher
from spark_bagging_tpu.telemetry import perf, recorder
from spark_bagging_tpu.telemetry.registry import (
    Histogram,
    Registry,
    SERIES_HELP,
    histogram_entry,
    histogram_from_entry,
)
from spark_bagging_tpu.utils import profiling


@pytest.fixture(scope="module", autouse=True)
def _module_clock():
    return time.perf_counter()


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.enable()
    perf.disable()
    yield
    perf.disable()
    profiling.stop_profile()  # never leak the single-flight guard
    telemetry.reset()
    telemetry.enable()


@pytest.fixture(scope="module")
def clf():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(96, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    return BaggingClassifier(
        base_learner=LogisticRegression(max_iter=3),
        n_estimators=4, seed=0,
    ).fit(X, y)


@pytest.fixture(scope="module")
def warmed_ex(clf):
    ex = EnsembleExecutor(clf, min_bucket_rows=8, max_batch_rows=32)
    ex.warmup()
    return ex


def _bd(total=10.0, queue=2.0, forward=6.0, batch=8.0,
        path="coalesced", **extra):
    bd = {"total_ms": total, "queue_ms": queue, "forward_ms": forward,
          "batch_ms": batch, "path": path, "batch_size": 1,
          "bucket": 8}
    bd.update(extra)
    return bd


# -- stage rollups -----------------------------------------------------

class TestStageRollups:
    def test_shares_partition_the_wall_clock(self):
        p = perf.PerfAttribution(refresh_every=0)
        p.observe_breakdown(_bd(total=10, queue=2, forward=6, batch=8))
        p.observe_breakdown(_bd(total=20, queue=10, forward=8, batch=10))
        s = p.summary()
        assert s["requests"] == 2
        st = s["stages"]
        # queue 12ms, forward 14ms, scatter (8-6)+(10-8)=4ms, total 30
        assert st["queue"]["seconds"] == pytest.approx(0.012)
        assert st["forward"]["seconds"] == pytest.approx(0.014)
        assert st["scatter"]["seconds"] == pytest.approx(0.004)
        assert sum(v["share"] for v in st.values()) == pytest.approx(1.0)

    def test_keys_split_by_path_and_model(self):
        p = perf.PerfAttribution(refresh_every=0)
        p.observe_breakdown(_bd(path="direct", model_name="m"))
        p.observe_breakdown(_bd(path="coalesced", model_name="m"))
        p.observe_breakdown(_bd(path="coalesced", model_name="m"))
        keys = {(e["path"], e["model"]): e["requests"]
                for e in p.summary()["by_key"]}
        assert keys == {("direct", "m"): 1, ("coalesced", "m"): 2}

    def test_fixed_memory_key_cap_counts_drops(self):
        p = perf.PerfAttribution(refresh_every=0, max_keys=2)
        for i in range(5):
            p.observe_breakdown(_bd(model_name=f"m{i}"))
        s = p.summary()
        assert len(s["by_key"]) == 2
        assert s["dropped_keys"] == 3
        assert s["requests"] == 5  # observations still counted
        p.export()
        assert telemetry.registry().counter(
            "sbt_perf_dropped_total").value == 3

    def test_key_cap_also_bounds_registry_series(self):
        """A label-cardinality accident (many distinctly-named models)
        must not grow the REGISTRY either: dropped keys export no
        sbt_perf_stage_seconds series — the fixed-memory contract
        covers the instrument panel, not just the accumulators."""
        p = perf.PerfAttribution(refresh_every=0, max_keys=2)
        for i in range(40):
            p.observe_breakdown(_bd(model_name=f"m{i}"))
        models = {
            e["labels"].get("model")
            for e in telemetry.registry().snapshot()
            if e["name"] == "sbt_perf_stage_seconds"
        }
        assert len(models) == 2  # the capped key set, nothing more

    def test_stage_histograms_exported_with_labels(self):
        p = perf.PerfAttribution(refresh_every=0)
        p.observe_breakdown(_bd(path="direct"), trace_id="tr-1")
        snap = {(e["name"], tuple(sorted(e["labels"].items())))
                for e in telemetry.registry().snapshot()}
        for stage in ("queue", "forward", "scatter"):
            assert ("sbt_perf_stage_seconds",
                    (("path", "direct"), ("stage", stage))) in snap

    def test_share_gauges_exported_on_refresh_cadence(self):
        p = perf.PerfAttribution(refresh_every=2)
        p.observe_breakdown(_bd())
        names = {e["name"] for e in telemetry.registry().snapshot()}
        assert "sbt_perf_stage_share" not in names
        p.observe_breakdown(_bd())  # 2nd observation: cadence fires
        entries = {
            e["labels"]["stage"]: e["value"]
            for e in telemetry.registry().snapshot()
            if e["name"] == "sbt_perf_stage_share"
        }
        assert set(entries) == {"queue", "forward", "scatter"}
        assert sum(entries.values()) == pytest.approx(1.0)


class TestSlowReservoir:
    def test_retains_top_k_by_duration_deterministically(self):
        p = perf.PerfAttribution(refresh_every=0, slow_k=3)
        for i, total in enumerate([5, 50, 1, 30, 2, 40, 7]):
            p.observe_breakdown(_bd(total=total), trace_id=f"t{i}")
        slow = p.slow_records()
        assert [r["total_ms"] for r in slow] == [50, 40, 30]
        # ties keep the incumbent: a second 30ms request does not evict
        p.observe_breakdown(_bd(total=30), trace_id="late-tie")
        assert {r["trace_id"] for r in p.slow_records()} == \
            {"t1", "t5", "t3"}

    def test_record_carries_the_breakdown_facts(self):
        p = perf.PerfAttribution(refresh_every=0)
        p.observe_breakdown(
            _bd(total=9, path="direct", model_version=3,
                error="RuntimeError('x')"),
            trace_id="tr-err",
        )
        (r,) = p.slow_records()
        assert r["trace_id"] == "tr-err"
        assert r["path"] == "direct"
        assert r["model_version"] == 3
        assert r["error"].startswith("RuntimeError")
        assert r["ts"] > 0


# -- the measured cost model -------------------------------------------

class TestCostModel:
    def test_joins_measured_seconds_with_compiled_cost(self):
        p = perf.PerfAttribution(refresh_every=0)
        cost = {"flops": 1e6, "bytes": 2e5}
        p.observe_forward(32, 32, 0.010, cost)
        p.observe_forward(32, 16, 0.006, cost)
        cm = p.cost_model()["32"]
        assert cm["forwards"] == 2 and cm["rows"] == 48
        assert cm["seconds_per_row"] == pytest.approx(0.016 / 48)
        assert cm["achieved_flops"] == pytest.approx(2e6 / 0.016)
        assert cm["flops_per_forward"] == 1e6
        assert cm["bytes_per_forward"] == 2e5
        # CPU host: no published peak, MFU honestly None
        assert cm["mfu"] is None
        assert p.summary()["peak_tflops_bf16"] is None

    def test_mfu_against_a_known_peak(self):
        p = perf.PerfAttribution(refresh_every=0)
        p._peak_tflops, p._peak_resolved = 100.0, True  # fake a chip
        p.observe_forward(8, 8, 0.001, {"flops": 5e9, "bytes": None})
        cm = p.cost_model()["8"]
        assert cm["achieved_flops"] == pytest.approx(5e12)
        assert cm["mfu"] == pytest.approx(0.05)
        s = p.summary()
        assert s["mfu"] == pytest.approx(0.05)
        p.export()
        reg = telemetry.registry()
        assert reg.gauge("sbt_perf_mfu").value == pytest.approx(0.05)
        assert reg.gauge("sbt_perf_bucket_seconds_per_row",
                         labels={"bucket": "8"}).value == \
            pytest.approx(0.001 / 8)

    def test_executor_probe_feeds_installed_plane_only(self, warmed_ex,
                                                      clf):
        X = np.random.default_rng(1).normal(size=(8, 6)).astype(
            np.float32)
        warmed_ex.forward(X)  # no plane installed: nothing recorded
        plane = perf.enable(refresh_every=0)
        warmed_ex.forward(X)
        warmed_ex.forward(X[:4])
        cm = plane.cost_model()
        assert cm["8"]["forwards"] == 2
        assert cm["8"]["rows"] == 12
        assert cm["8"]["seconds"] > 0
        # CPU XLA reports cost analysis: the join is live
        assert cm["8"]["flops_per_forward"] is not None
        assert cm["8"]["achieved_flops"] is not None

    def test_batcher_probe_rides_the_breakdown(self, warmed_ex):
        X = np.random.default_rng(2).normal(size=(4, 6)).astype(
            np.float32)
        plane = perf.enable(refresh_every=0)
        with MicroBatcher(warmed_ex, max_delay_ms=1,
                          direct_dispatch=False) as b:
            futs = [b.submit(X) for _ in range(6)]
            for f in futs:
                f.result(30)
        s = plane.summary()
        assert s["requests"] == 6
        assert s["stages"]["forward"]["seconds"] > 0
        assert any(e["path"] == "coalesced" for e in s["by_key"])

    def test_disabled_probe_is_one_attribute_read(self):
        """PR-1-style micro-benchmark: the uninstalled plane's probe
        (exactly what _forward_piece and _finish_breakdown run) must
        stay far under a microsecond."""
        perf.disable()
        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            ap = perf.ACTIVE
            if ap is not None:  # pragma: no cover — disabled
                raise AssertionError
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 2e-6, f"{per_call * 1e9:.0f}ns per probe"


# -- the tail explainer ------------------------------------------------

class TestCorrelateTail:
    def test_verdict_priority_ladder(self):
        base = {"ts": 100.0, "total_ms": 50.0, "queue_ms": 40.0}
        ev = lambda kind, t=100.0: {"kind": kind, "ts": t}  # noqa: E731
        cases = [
            ({"error": "boom"}, [ev("serving_retry")], "failed"),
            ({}, [ev("serving_shard_failed")], "degraded-path"),
            ({}, [ev("serving_retry")], "retry-inflated"),
            ({}, [ev("model_swapped")], "compile-absorbed"),
            ({}, [], "queue-dominated"),          # 40/50 >= 0.5
            ({"queue_ms": 1.0}, [], "genuinely-slow-forward"),
        ]
        for patch, events, want in cases:
            (out,) = perf.correlate_tail([{**base, **patch}], events)
            assert out["verdict"] == want, (patch, events)

    def test_compile_span_events_join(self):
        rec = {"ts": 10.0, "total_ms": 5.0, "queue_ms": 0.0}
        (out,) = perf.correlate_tail(
            [rec],
            [{"kind": "span", "name": "serving_compile", "ts": 10.2}],
        )
        assert out["verdict"] == "compile-absorbed"
        assert out["evidence"] == [{"t": 10.2,
                                    "kind": "serving_compile"}]
        # a non-compile span is not evidence
        (out,) = perf.correlate_tail(
            [rec], [{"kind": "span", "name": "serving_batch", "ts": 10.2}]
        )
        assert out["verdict"] == "genuinely-slow-forward"

    def test_window_bounds_the_join(self):
        rec = {"ts": 100.0, "total_ms": 5.0, "queue_ms": 0.0}
        far = [{"kind": "serving_retry", "ts": 200.0}]
        (out,) = perf.correlate_tail([rec], far, window_s=1.0)
        assert out["verdict"] == "genuinely-slow-forward"
        assert out["events_in_window"] == 0
        (out,) = perf.correlate_tail([rec], far, window_s=150.0)
        assert out["verdict"] == "retry-inflated"

    def test_queue_threshold_rule_for_totals_unknown(self):
        recs = [{"ts": 1.0, "queue_ms": 3.0},
                {"ts": 2.0, "queue_ms": 0.5}]
        out = perf.correlate_tail(recs, [], queue_threshold_ms=1.0)
        assert [o["verdict"] for o in out] == [
            "queue-dominated", "genuinely-slow-forward",
        ]

    def test_overload_burst_is_a_queue_factor(self):
        (out,) = perf.correlate_tail(
            [{"ts": 5.0, "total_ms": 4.0, "queue_ms": 0.1}],
            [{"kind": "serving_overloaded", "ts": 5.1}],
        )
        assert out["verdict"] == "queue-dominated"
        assert "overload-burst" in out["factors"]

    def test_tail_report_joins_reservoir_with_flight_ring(self,
                                                         warmed_ex):
        X = np.random.default_rng(3).normal(size=(4, 6)).astype(
            np.float32)
        plane = perf.enable(refresh_every=0)
        rec = recorder.FlightRecorder(capacity=64)
        rec.arm()
        try:
            with MicroBatcher(warmed_ex, max_delay_ms=1) as b:
                for _ in range(4):
                    b.submit(X).result(30)
            report = perf.tail_report(limit=4, window_s=5.0)
        finally:
            rec.disarm()
        assert report["source"] == "perf-reservoir"
        assert report["perf_plane_active"] is True
        assert len(report["tail"]) == 4
        assert all(r["verdict"] in perf.VERDICTS
                   for r in report["tail"])
        # slowest first, and the stage rollup rides along
        totals = [r["total_ms"] for r in report["tail"]]
        assert totals == sorted(totals, reverse=True)
        assert set(report["stages"]) == {"queue", "forward", "scatter"}
        assert plane.summary()["requests"] == 4

    def test_tail_report_falls_back_to_latency_exemplars(self):
        perf.disable()
        telemetry.observe("sbt_serving_latency_seconds", 0.05,
                          exemplar="tr-fast")
        telemetry.observe("sbt_serving_latency_seconds", 4.0,
                          exemplar="tr-slow")
        report = perf.tail_report(limit=4)
        assert report["source"] == "latency-exemplars"
        assert report["perf_plane_active"] is False
        ids = [r["trace_id"] for r in report["tail"]]
        assert ids[0] == "tr-slow"  # slowest first

    def test_tail_report_empty_carries_a_note(self):
        perf.disable()
        report = perf.tail_report()
        assert report["tail"] == []
        assert "note" in report


# -- the latency-histogram slow-exemplar reservoir ---------------------

class TestSlowExemplarReservoir:
    def test_top_k_survive_newest_wins_eviction(self):
        h = Histogram()
        h.observe(3.0, exemplar="tr-slowest")
        # a stream of fast requests in the same bucket as each other:
        # newest-wins per bucket forgets everything but the last one
        for i in range(50):
            h.observe(0.01 + i * 1e-6, exemplar=f"tr-{i}")
        fast_bucket_exemplars = {
            ex["trace_id"] for ex in h.exemplars.values()
        }
        reservoir = {ex["trace_id"] for ex in h.slow_exemplars}
        assert "tr-slowest" in reservoir
        assert len(h.slow_exemplars) == Histogram.RESERVOIR_K
        # the reservoir keeps the K largest, not the K newest
        assert "tr-0" not in reservoir or "tr-slowest" in reservoir
        assert "tr-slowest" in fast_bucket_exemplars | reservoir

    def test_ties_keep_the_incumbent(self):
        h = Histogram()
        for i in range(Histogram.RESERVOIR_K):
            h.observe(1.0, exemplar=f"first-{i}")
        h.observe(1.0, exemplar="tie-later")
        assert {e["trace_id"] for e in h.slow_exemplars} == {
            f"first-{i}" for i in range(Histogram.RESERVOIR_K)
        }

    def test_merge_takes_the_fleet_wide_k_largest(self):
        a, b = Histogram(), Histogram()
        for v in (1.0, 2.0, 3.0, 4.0):
            a.observe(v, exemplar=f"a-{v}")
        for v in (10.0, 0.5, 5.0, 0.1):
            b.observe(v, exemplar=f"b-{v}")
        a.merge(b)
        got = sorted(e["value"] for e in a.slow_exemplars)
        assert got == [3.0, 4.0, 5.0, 10.0]

    def test_entry_round_trip_preserves_reservoir(self):
        r = Registry()
        r.observe("sbt_lat_seconds", 2.0, exemplar="tr-big")
        r.observe("sbt_lat_seconds", 0.01, exemplar="tr-small")
        (entry,) = r.snapshot()
        assert entry["slow_exemplars"][0]["trace_id"] == "tr-big"
        h2 = histogram_from_entry(entry)
        assert {e["trace_id"] for e in h2.slow_exemplars} == \
            {"tr-big", "tr-small"}
        # and re-serializing is stable
        assert histogram_entry(
            "sbt_lat_seconds", {}, h2
        )["slow_exemplars"] == entry["slow_exemplars"]

    def test_fleet_digest_strips_the_reservoir(self):
        from spark_bagging_tpu.telemetry.fleet import merged_digest

        r = Registry()
        r.observe("sbt_serving_latency_seconds", 1.0, exemplar="tr-1")
        (entry,) = r.snapshot()
        bare = {k: v for k, v in entry.items()
                if k not in ("exemplars", "slow_exemplars")}
        assert merged_digest([entry], series=None) == \
            merged_digest([bare], series=None)


# -- on-demand live device profiling -----------------------------------

class _FakeProfiler:
    """Stand-in for jax.profiler so the single-flight/auto-stop
    contract tests don't pay the ~4s real-profiler spin-up (the real
    artifact is covered once by the budgeted route test below)."""

    def __init__(self):
        self.started: list[str] = []
        self.stopped = 0

    def start_trace(self, d):
        self.started.append(d)

    def stop_trace(self):
        self.stopped += 1


@pytest.fixture()
def fake_profiler(monkeypatch):
    fake = _FakeProfiler()
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    return fake


class TestProfileSingleFlight:
    def test_second_capture_rejected_cleanly(self, fake_profiler,
                                             tmp_path):
        info = profiling.start_profile(str(tmp_path / "p1"))
        assert profiling.profile_active()["dir"] == info["dir"]
        with pytest.raises(profiling.ProfilerBusy):
            profiling.start_profile(str(tmp_path / "p2"))
        reg = telemetry.registry()
        assert reg.counter("sbt_profile_rejected_total").value == 1
        out = profiling.stop_profile()
        assert out["dir"] == info["dir"] and out["seconds"] >= 0
        assert profiling.profile_active() is None
        assert fake_profiler.started == [str(tmp_path / "p1")]
        assert fake_profiler.stopped == 1
        assert profiling.stop_profile() is None  # idempotent

    def test_trace_cm_shares_the_guard(self, fake_profiler, tmp_path):
        with profiling.trace(str(tmp_path / "t")):
            with pytest.raises(profiling.ProfilerBusy):
                with profiling.trace(str(tmp_path / "nested")):
                    pass  # pragma: no cover
        assert profiling.profile_active() is None
        assert fake_profiler.stopped == 1  # the outer one, exactly once

    def test_default_dir_under_telemetry_profiles(self, fake_profiler,
                                                  tmp_path,
                                                  monkeypatch):
        monkeypatch.setenv("SBT_TELEMETRY_DIR", str(tmp_path))
        info = profiling.start_profile()
        profiling.stop_profile()
        assert info["dir"].startswith(
            os.path.join(str(tmp_path), "profiles")
        )

    def test_auto_stop_at_max_duration(self, fake_profiler, tmp_path):
        info = profiling.start_profile(str(tmp_path / "a"),
                                       max_seconds=0.2)
        assert info["stops_at"] is not None
        deadline = time.monotonic() + 5.0
        while (profiling.profile_active() is not None
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert profiling.profile_active() is None
        assert fake_profiler.stopped == 1
        # max_seconds is clamped to the hard ceiling
        info = profiling.start_profile(str(tmp_path / "b"),
                                       max_seconds=1e9)
        assert info["max_seconds"] == profiling.PROFILE_MAX_SECONDS
        profiling.stop_profile()

    def test_bad_durations_rejected(self, fake_profiler):
        with pytest.raises(ValueError):
            profiling.start_profile(max_seconds=0)
        assert profiling.profile_active() is None

    def test_stale_auto_stop_cannot_kill_the_next_capture(
        self, fake_profiler, tmp_path
    ):
        """The lost-cancel race: capture 1's auto-stop timer fires
        AFTER capture 1 was stopped manually and capture 2 began —
        its generation check must make it a no-op instead of stopping
        capture 2 milliseconds in."""
        profiling.start_profile(str(tmp_path / "c1"),
                                max_seconds=30.0)
        stale_gen = profiling._profile["seq"]
        assert profiling.stop_profile() is not None  # manual stop
        profiling.start_profile(str(tmp_path / "c2"),
                                max_seconds=30.0)
        # the stale timer callback, replayed by hand
        assert profiling.stop_profile(_gen=stale_gen) is None
        active = profiling.profile_active()
        assert active is not None and active["dir"].endswith("c2")
        # capture 2's OWN generation still stops it
        assert profiling.stop_profile(
            _gen=profiling._profile["seq"]
        ) is not None
        assert profiling.profile_active() is None


class TestProfileRouteAndCLI:
    @pytest.fixture()
    def server_port(self):
        port = telemetry.start_server(0)
        yield port
        telemetry.stop_server()
        recorder.disarm()

    def _get(self, port, path):
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_route_contract_busy_stop_and_validation(
        self, server_port, fake_profiler, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("SBT_TELEMETRY_DIR", str(tmp_path))
        code, body = self._get(server_port,
                               "/debug/profile?seconds=30")
        assert code == 200 and body["started"] is True
        # single-flight: a second concurrent capture is a 409
        code, body2 = self._get(server_port,
                                "/debug/profile?seconds=1")
        assert code == 409
        assert body2["active"]["dir"] == body["dir"]
        code, stopped = self._get(server_port,
                                  "/debug/profile?action=stop")
        assert code == 200 and stopped["stopped"] is True
        code, _ = self._get(server_port,
                            "/debug/profile?action=stop")
        assert code == 200  # idempotent
        code, err = self._get(server_port,
                              "/debug/profile?seconds=bogus")
        assert code == 400
        code, err = self._get(server_port,
                              "/debug/profile?seconds=-1")
        assert code == 400

    def test_cli_drives_a_remote_process(self, server_port,
                                         fake_profiler, tmp_path,
                                         monkeypatch, capsys):
        from spark_bagging_tpu.telemetry.__main__ import main

        monkeypatch.setenv("SBT_TELEMETRY_DIR", str(tmp_path))
        rc = main(["profile", "--seconds", "30",
                   "--port", str(server_port)])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["started"] is True
        # busy process: CLI exits 1 with the 409 body on stderr
        rc = main(["profile", "--seconds", "1",
                   "--port", str(server_port)])
        assert rc == 1
        rc = main(["profile", "--stop", "--port", str(server_port)])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["stopped"] is True

    @pytest.mark.slow  # [PR 19 budget offset] ~5.3s end-to-end profiler capture/render soak; the profiler control plane stays tier-1 via the route-contract, single-flight, and CLI-remote tests in this class
    def test_real_capture_produces_viewable_artifact(
        self, server_port, tmp_path, monkeypatch
    ):
        """THE acceptance drill, real profiler: /debug/profile starts
        a capture, the auto-stop timer ends it at the requested max
        duration, and a trace artifact lands under
        telemetry_dir()/profiles/. Budget-asserted (~5s: one-time
        profiler spin-up dominates)."""
        import jax.numpy as jnp

        monkeypatch.setenv("SBT_TELEMETRY_DIR", str(tmp_path))
        t0 = time.perf_counter()
        code, body = self._get(server_port,
                               "/debug/profile?seconds=0.8")
        assert code == 200 and body["started"] is True
        assert body["dir"].startswith(
            os.path.join(str(tmp_path), "profiles")
        )
        jnp.sum(jnp.arange(512.0)).block_until_ready()  # traced work
        deadline = time.monotonic() + 15.0
        while (profiling.profile_active() is not None
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert profiling.profile_active() is None, \
            "auto-stop never fired"
        found = []
        for root, _, files in os.walk(body["dir"]):
            found.extend(files)
        assert found, "no trace artifact written"
        reg = telemetry.registry()
        assert reg.counter("sbt_profile_captures_total").value >= 1
        assert reg.gauge("sbt_profile_active").value == 0.0
        assert time.perf_counter() - t0 < 20.0


# -- SLO stage-share ceilings ------------------------------------------

class TestStageShareSLO:
    def test_spec_validation(self):
        from spark_bagging_tpu.telemetry import slo

        with pytest.raises(ValueError, match="unknown stages"):
            slo.SLOSpec(max_stage_share={"gpu": 0.5})
        with pytest.raises(ValueError, match="0, 1"):
            slo.SLOSpec(max_stage_share={"queue": 1.5})
        spec = slo.SLOSpec.from_dict(
            {"max_stage_share": {"queue": 0.5}}
        )
        assert spec.max_stage_share == {"queue": 0.5}
        assert spec.to_dict()["max_stage_share"] == {"queue": 0.5}

    def test_evaluate_reads_the_attribution_section(self):
        from spark_bagging_tpu.telemetry import slo

        report = {
            "post_warmup_compiles": 0,
            "attribution": {"stages": {
                "queue": {"seconds": 0.06, "share": 0.6},
                "forward": {"seconds": 0.03, "share": 0.3},
                "scatter": {"seconds": 0.01, "share": 0.1},
            }},
        }
        ok = slo.evaluate(
            slo.SLOSpec(max_stage_share={"forward": 0.9}), report
        )
        assert ok.ok, ok.render()
        bad = slo.evaluate(
            slo.SLOSpec(max_stage_share={"queue": 0.5}), report
        )
        assert not bad.ok
        assert bad.failures[0]["name"] == "stage_share_queue"
        # a report with no attribution fails loudly, not silently
        missing = slo.evaluate(
            slo.SLOSpec(max_stage_share={"queue": 0.5}),
            {"post_warmup_compiles": 0},
        )
        assert not missing.ok


# -- serving-bench MFU -------------------------------------------------

class TestServingBenchMFU:
    def test_mfu_math_and_warn_once_none_path(self):
        import warnings

        from benchmarks import serving_latency as SL

        SL._mfu_warned[0] = False
        assert SL._serving_mfu(1000.0, 1e9, 100.0) == \
            pytest.approx(1000.0 * 1e9 / 1e14)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert SL._serving_mfu(1000.0, 1e9, None) is None
            assert SL._serving_mfu(1000.0, None, 100.0) is None
        mfu_warnings = [x for x in w if "MFU" in str(x.message)]
        assert len(mfu_warnings) == 1  # warn ONCE, then quiet
        assert SL._serving_mfu(None, 1e9, 100.0) is None


# -- series help completeness (the new sbt_perf_*/sbt_profile_*) -------

def test_new_series_have_help_entries():
    for name in (
        "sbt_perf_stage_seconds", "sbt_perf_stage_share",
        "sbt_perf_bucket_seconds_per_row",
        "sbt_perf_bucket_achieved_flops", "sbt_perf_mfu",
        "sbt_perf_dropped_total", "sbt_profile_captures_total",
        "sbt_profile_rejected_total", "sbt_profile_active",
    ):
        assert name in SERIES_HELP, name


def test_zz_perf_suite_under_budget(_module_clock):
    """Tier-1 allowance for this module (the PR-11 ratchet
    discipline): everything here is unit-sized except the one real
    profiler drill, whose one-time spin-up dominates."""
    elapsed = time.perf_counter() - _module_clock
    assert elapsed < 20.0, (
        f"tests/test_perf.py took {elapsed:.1f}s; move the offender "
        "to -m slow or shrink it"
    )
