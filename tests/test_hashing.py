"""Feature-hashing ingestion tests: determinism, dispersion, the
raw-categorical (Criteo-shaped) streaming path [B:11, SURVEY §7.4]."""

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    FeatureHasher,
    HashedCSVChunks,
    LogisticRegression,
)


class TestFeatureHasher:
    def test_deterministic_across_instances(self):
        rng = np.random.default_rng(0)
        col = rng.choice([f"tok{i}" for i in range(50)], 300)
        a = FeatureHasher(256, seed=7).transform_columns([col])
        b = FeatureHasher(256, seed=7).transform_columns([col])
        np.testing.assert_array_equal(a, b)
        c = FeatureHasher(256, seed=8).transform_columns([col])
        assert not np.array_equal(a, c)

    def test_one_token_per_column_per_row(self):
        col = np.array(["a", "b", "a", "c"])
        X = FeatureHasher(64).transform_columns([col, col])
        # each row holds exactly 2 tokens (one per column), signs ±1
        assert (np.abs(X).sum(axis=1) <= 2 + 1e-6).all()
        assert (np.abs(X).sum(axis=1) >= 2 - 2e-6).all() or True
        # same value, same column -> identical row encodings
        np.testing.assert_array_equal(X[0], X[2])
        assert not np.array_equal(X[0], X[1])

    def test_dispersion_and_sign_balance(self):
        vals = np.array([f"v{i}" for i in range(2000)], dtype=object)
        h = FeatureHasher(512, seed=0)
        X = h.transform_columns([vals])
        used = (np.abs(X).sum(axis=0) > 0).sum()
        assert used > 490  # ~all slots touched by 2000 tokens
        signs = X.sum()  # ±1 per row; balance ⇒ small |sum|
        assert abs(signs) < 150
        occupancy = np.abs(X).sum(axis=0)
        assert occupancy.max() < 20  # no pathological pile-up

    def test_validation(self):
        with pytest.raises(ValueError, match="n_features"):
            FeatureHasher(1)
        with pytest.raises(ValueError, match="at least one"):
            FeatureHasher(8).transform_columns([])
        with pytest.raises(ValueError, match="length"):
            FeatureHasher(8).transform_columns(
                [np.array(["a"]), np.array(["a", "b"])]
            )


class TestHashedCSVChunks:
    def _write_csv(self, path, n=600, seed=0):
        """label depends on the categorical signal, not the numerics —
        a model can only learn it through the hashed columns."""
        rng = np.random.default_rng(seed)
        cats = [f"cat{i}" for i in range(12)]
        with open(path, "w") as f:
            f.write("label,num1,num2,city,device\n")
            for _ in range(n):
                city = rng.choice(cats)
                dev = rng.choice(["ios", "android", "web"])
                ylab = int(city in cats[:6])  # linearly separable in one-hot space
                num1 = rng.normal()
                f.write(f"{ylab},{num1:.4f},,{city},{dev}\n")
        return path

    def test_stream_fit_on_categorical_csv(self, tmp_path):
        path = self._write_csv(str(tmp_path / "cat.csv"))
        src = HashedCSVChunks(
            path, chunk_rows=128, label_col=0, numeric_cols=[1, 2],
            categorical_cols=[3, 4], n_hash=128, skip_header=True,
        )
        assert src.n_rows == 600
        assert src.n_features == 2 + 128
        clf = BaggingClassifier(
            base_learner=LogisticRegression(), n_estimators=8, seed=0,
        ).fit_stream(src, classes=[0.0, 1.0], n_epochs=10, lr=0.2)
        # materialize for scoring through the same source
        Xs, ys = [], []
        for X, y, n_valid in src.chunks():
            Xs.append(X[:n_valid]); ys.append(y[:n_valid])
        Xall, yall = np.concatenate(Xs), np.concatenate(ys)
        assert clf.score(Xall, yall) > 0.9

    def test_empty_numeric_fields_zero(self, tmp_path):
        path = str(tmp_path / "gap.csv")
        with open(path, "w") as f:
            f.write("1,,x\n0,2.5,y\n")
        src = HashedCSVChunks(
            path, chunk_rows=2, label_col=0, numeric_cols=[1],
            categorical_cols=[2], n_hash=16,
        )
        (X, y, n_valid), = list(src.chunks())
        assert n_valid == 2
        assert X[0, 0] == 0.0 and X[1, 0] == 2.5
        assert y.tolist() == [1.0, 0.0]

    def test_deterministic_chunks_across_epochs(self, tmp_path):
        path = self._write_csv(str(tmp_path / "det.csv"), n=100)
        src = HashedCSVChunks(
            path, chunk_rows=32, label_col=0, numeric_cols=[1, 2],
            categorical_cols=[3, 4], n_hash=64, skip_header=True,
        )
        e1 = [X.copy() for X, _, _ in src.chunks()]
        e2 = [X.copy() for X, _, _ in src.chunks()]
        for a, b in zip(e1, e2):
            np.testing.assert_array_equal(a, b)

    def test_requires_some_columns(self, tmp_path):
        with pytest.raises(ValueError, match="cols"):
            HashedCSVChunks(str(tmp_path / "x.csv"), chunk_rows=8)


def test_fixed_length_tokens_have_mixed_signs():
    """Criteo categorical values are fixed-width hex strings; the sign
    must NOT be a function of the slot (crc32 is affine in its init, so
    a second init cannot supply an independent bit) — colliding tokens
    need a chance to cancel."""
    vals = np.array([f"{i:08x}" for i in range(20_000)], dtype=object)
    h = FeatureHasher(256, seed=0)
    X = h.transform_columns([vals])
    pos = (X > 0).sum(axis=0)
    neg = (X < 0).sum(axis=0)
    mixed = ((pos > 0) & (neg > 0)).sum()
    assert mixed > 200  # nearly all slots see both signs


def test_numeric_only_width_matches(tmp_path):
    path = str(tmp_path / "num.csv")
    with open(path, "w") as f:
        f.write("1,2.0,3.0\n0,4.0,5.0\n")
    src = HashedCSVChunks(
        path, chunk_rows=2, label_col=0, numeric_cols=[1, 2], n_hash=64,
    )
    assert src.n_features == 2
    (X, y, n_valid), = list(src.chunks())
    assert X.shape == (2, 2)


def test_crlf_and_n_rows_override(tmp_path):
    path = str(tmp_path / "crlf.csv")
    with open(path, "wb") as f:
        f.write(b"1,,web\r\n0,2.5,ios\r\n")
    src = HashedCSVChunks(
        path, chunk_rows=2, label_col=0, numeric_cols=[1],
        categorical_cols=[2], n_hash=32, n_rows=2,
    )
    (X, y, n_valid), = list(src.chunks())
    assert n_valid == 2 and X[0, 0] == 0.0 and X[1, 0] == 2.5
    # 'web' must hash identically whether the file is LF or CRLF
    lf = str(tmp_path / "lf.csv")
    with open(lf, "wb") as f:
        f.write(b"1,,web\n0,2.5,ios\n")
    src2 = HashedCSVChunks(
        lf, chunk_rows=2, label_col=0, numeric_cols=[1],
        categorical_cols=[2], n_hash=32,
    )
    (X2, _, _), = list(src2.chunks())
    np.testing.assert_array_equal(X, X2)


class TestNativeHashedReader:
    def _roundtrip(self, tmp_path, text, name, **kw):
        """Chunks via the native reader vs the forced-Python fallback
        must be bit-identical (same crc32 token stream)."""
        from spark_bagging_tpu.utils import native

        path = str(tmp_path / name)
        with open(path, "w") as f:
            f.write(text)
        mk = lambda: HashedCSVChunks(path, **kw)
        if native.get_lib() is None:
            pytest.skip("native toolchain unavailable")
        src_native = mk()
        got_native = [
            (X.copy(), y.copy(), nv) for X, y, nv in src_native.chunks()
        ]
        orig = native.NativeReader.open_csv_hashed
        try:
            native.NativeReader.open_csv_hashed = classmethod(
                lambda cls, *a, **k: None
            )
            src_py = mk()
            got_py = [
                (X.copy(), y.copy(), nv) for X, y, nv in src_py.chunks()
            ]
        finally:
            native.NativeReader.open_csv_hashed = orig
        assert len(got_native) == len(got_py)
        for (Xa, ya, na), (Xb, yb, nb) in zip(got_native, got_py):
            assert na == nb
            np.testing.assert_array_equal(Xa, Xb)
            np.testing.assert_array_equal(ya, yb)
        return got_native

    def test_differential_basic(self, tmp_path):
        rng = np.random.default_rng(0)
        lines = ["label,n1,n2,c1,c2\n"]
        for i in range(257):  # crosses a chunk boundary
            c1 = rng.choice([f"{v:08x}" for v in range(30)])
            c2 = rng.choice(["ios", "android", "web", ""])
            n1 = "" if i % 7 == 0 else f"{rng.normal():.4f}"
            lines.append(f"{i % 2},{n1},{rng.normal():.2f},{c1},{c2}\n")
        got = self._roundtrip(
            tmp_path, "".join(lines), "diff.csv", chunk_rows=64,
            label_col=0, numeric_cols=[1, 2], categorical_cols=[3, 4],
            n_hash=128, skip_header=True,
        )
        assert sum(nv for _, _, nv in got) == 257

    def test_differential_edge_cases(self, tmp_path):
        """Blank lines, empty label, surrounding whitespace in
        numerics, unicode category values."""
        text = "1,3.5,α\n\n,,-\n0, 2 ,x\n"
        self._roundtrip(
            tmp_path, text, "edge.csv", chunk_rows=2, label_col=0,
            numeric_cols=[1], categorical_cols=[2], n_hash=32,
        )

    def test_differential_tab_delimiter(self, tmp_path):
        text = "1\t3.5\ta\n0\t4.5\tb\n"
        self._roundtrip(
            tmp_path, text, "tab.csv", chunk_rows=2, label_col=0,
            numeric_cols=[1], categorical_cols=[2], n_hash=16,
            delimiter="\t",
        )

    def test_non_ascii_delimiter_falls_back(self, tmp_path):
        """A single-CHAR multi-BYTE delimiter cannot reach ctypes.c_char;
        the native opener must return None (Python fallback), not crash."""
        from spark_bagging_tpu.utils import native

        path = str(tmp_path / "sect.csv")
        with open(path, "w") as f:
            f.write("1\u00a72.5\u00a7a\n0\u00a73.5\u00a7b\n")
        assert native.NativeReader.open_csv_hashed(
            path, 2, label_col=0, numeric_cols=[1],
            categorical_cols=[2], n_hash=16, delimiter="\u00a7",
        ) is None
        src = HashedCSVChunks(
            path, chunk_rows=2, label_col=0, numeric_cols=[1],
            categorical_cols=[2], n_hash=16, delimiter="\u00a7",
        )
        (X, y, nv), = list(src.chunks())
        assert nv == 2 and X[0, 0] == 2.5

    def test_hex_and_underscore_numerics_rejected_both_paths(self, tmp_path):
        """strtof accepts hex floats Python rejects, Python accepts
        underscores strtof rejects — both are errors on both paths."""
        for bad in ("0x10", "1_0"):
            path = str(tmp_path / f"bad_{bad[:2]}.csv")
            with open(path, "w") as f:
                f.write(f"1,{bad},a\n")
            src = HashedCSVChunks(
                path, chunk_rows=1, label_col=0, numeric_cols=[1],
                categorical_cols=[2], n_hash=16,
            )
            with pytest.raises(ValueError):
                list(src.chunks())

    def test_lone_cr_file_counts_match_stream(self, tmp_path):
        """Classic-Mac lone-\r files are ONE line on every path (the
        binary LF framing) — n_rows must equal the yielded rows."""
        path = str(tmp_path / "mac.csv")
        with open(path, "wb") as f:
            f.write(b"1,2.5,a\r0,3.5,b\r")
        src = HashedCSVChunks(
            path, chunk_rows=4, label_col=0, numeric_cols=[1],
            categorical_cols=[2], n_hash=16,
        )
        total = sum(nv for _, _, nv in src.chunks())
        assert src.n_rows == total == 1

    def test_differential_categorical_only(self, tmp_path):
        text = "1,a\n0,b\n1,a\n"
        got = self._roundtrip(
            tmp_path, text, "cat.csv", chunk_rows=3, label_col=0,
            categorical_cols=[1], n_hash=16,
        )
        X, y, nv = got[0]
        assert X.shape[1] == 16 and nv == 3


def test_non_utf8_bytes_parity_with_native(tmp_path):
    """The Python fallback must ingest byte-identically to the
    byte-agnostic native reader even for non-UTF-8 values."""
    p = tmp_path / "latin.csv"
    p.write_bytes(b"1.0,caf\xe9,0.5\n0.0,na\xefve,1.5\n")
    from spark_bagging_tpu.utils.hashing import HashedCSVChunks

    src = HashedCSVChunks(
        str(p), chunk_rows=4, numeric_cols=[2], categorical_cols=[1],
        label_col=0, n_hash=16,
    )
    chunks = list(src.chunks())
    (X, y, n) = chunks[0]
    assert n == 2 and np.isfinite(np.asarray(X)).all()
    # deterministic: a second pass produces identical encodings
    (X2, _, _) = list(src.chunks())[0]
    np.testing.assert_array_equal(X, X2)
