"""Feature-hashing ingestion tests: determinism, dispersion, the
raw-categorical (Criteo-shaped) streaming path [B:11, SURVEY §7.4]."""

import numpy as np
import pytest

from spark_bagging_tpu import (
    BaggingClassifier,
    FeatureHasher,
    HashedCSVChunks,
    LogisticRegression,
)


class TestFeatureHasher:
    def test_deterministic_across_instances(self):
        rng = np.random.default_rng(0)
        col = rng.choice([f"tok{i}" for i in range(50)], 300)
        a = FeatureHasher(256, seed=7).transform_columns([col])
        b = FeatureHasher(256, seed=7).transform_columns([col])
        np.testing.assert_array_equal(a, b)
        c = FeatureHasher(256, seed=8).transform_columns([col])
        assert not np.array_equal(a, c)

    def test_one_token_per_column_per_row(self):
        col = np.array(["a", "b", "a", "c"])
        X = FeatureHasher(64).transform_columns([col, col])
        # each row holds exactly 2 tokens (one per column), signs ±1
        assert (np.abs(X).sum(axis=1) <= 2 + 1e-6).all()
        assert (np.abs(X).sum(axis=1) >= 2 - 2e-6).all() or True
        # same value, same column -> identical row encodings
        np.testing.assert_array_equal(X[0], X[2])
        assert not np.array_equal(X[0], X[1])

    def test_dispersion_and_sign_balance(self):
        vals = np.array([f"v{i}" for i in range(2000)], dtype=object)
        h = FeatureHasher(512, seed=0)
        X = h.transform_columns([vals])
        used = (np.abs(X).sum(axis=0) > 0).sum()
        assert used > 490  # ~all slots touched by 2000 tokens
        signs = X.sum()  # ±1 per row; balance ⇒ small |sum|
        assert abs(signs) < 150
        occupancy = np.abs(X).sum(axis=0)
        assert occupancy.max() < 20  # no pathological pile-up

    def test_validation(self):
        with pytest.raises(ValueError, match="n_features"):
            FeatureHasher(1)
        with pytest.raises(ValueError, match="at least one"):
            FeatureHasher(8).transform_columns([])
        with pytest.raises(ValueError, match="length"):
            FeatureHasher(8).transform_columns(
                [np.array(["a"]), np.array(["a", "b"])]
            )


class TestHashedCSVChunks:
    def _write_csv(self, path, n=600, seed=0):
        """label depends on the categorical signal, not the numerics —
        a model can only learn it through the hashed columns."""
        rng = np.random.default_rng(seed)
        cats = [f"cat{i}" for i in range(12)]
        with open(path, "w") as f:
            f.write("label,num1,num2,city,device\n")
            for _ in range(n):
                city = rng.choice(cats)
                dev = rng.choice(["ios", "android", "web"])
                ylab = int(city in cats[:6])  # linearly separable in one-hot space
                num1 = rng.normal()
                f.write(f"{ylab},{num1:.4f},,{city},{dev}\n")
        return path

    def test_stream_fit_on_categorical_csv(self, tmp_path):
        path = self._write_csv(str(tmp_path / "cat.csv"))
        src = HashedCSVChunks(
            path, chunk_rows=128, label_col=0, numeric_cols=[1, 2],
            categorical_cols=[3, 4], n_hash=128, skip_header=True,
        )
        assert src.n_rows == 600
        assert src.n_features == 2 + 128
        clf = BaggingClassifier(
            base_learner=LogisticRegression(), n_estimators=8, seed=0,
        ).fit_stream(src, classes=[0.0, 1.0], n_epochs=10, lr=0.2)
        # materialize for scoring through the same source
        Xs, ys = [], []
        for X, y, n_valid in src.chunks():
            Xs.append(X[:n_valid]); ys.append(y[:n_valid])
        Xall, yall = np.concatenate(Xs), np.concatenate(ys)
        assert clf.score(Xall, yall) > 0.9

    def test_empty_numeric_fields_zero(self, tmp_path):
        path = str(tmp_path / "gap.csv")
        with open(path, "w") as f:
            f.write("1,,x\n0,2.5,y\n")
        src = HashedCSVChunks(
            path, chunk_rows=2, label_col=0, numeric_cols=[1],
            categorical_cols=[2], n_hash=16,
        )
        (X, y, n_valid), = list(src.chunks())
        assert n_valid == 2
        assert X[0, 0] == 0.0 and X[1, 0] == 2.5
        assert y.tolist() == [1.0, 0.0]

    def test_deterministic_chunks_across_epochs(self, tmp_path):
        path = self._write_csv(str(tmp_path / "det.csv"), n=100)
        src = HashedCSVChunks(
            path, chunk_rows=32, label_col=0, numeric_cols=[1, 2],
            categorical_cols=[3, 4], n_hash=64, skip_header=True,
        )
        e1 = [X.copy() for X, _, _ in src.chunks()]
        e2 = [X.copy() for X, _, _ in src.chunks()]
        for a, b in zip(e1, e2):
            np.testing.assert_array_equal(a, b)

    def test_requires_some_columns(self, tmp_path):
        with pytest.raises(ValueError, match="cols"):
            HashedCSVChunks(str(tmp_path / "x.csv"), chunk_rows=8)


def test_fixed_length_tokens_have_mixed_signs():
    """Criteo categorical values are fixed-width hex strings; the sign
    must NOT be a function of the slot (crc32 is affine in its init, so
    a second init cannot supply an independent bit) — colliding tokens
    need a chance to cancel."""
    vals = np.array([f"{i:08x}" for i in range(20_000)], dtype=object)
    h = FeatureHasher(256, seed=0)
    X = h.transform_columns([vals])
    pos = (X > 0).sum(axis=0)
    neg = (X < 0).sum(axis=0)
    mixed = ((pos > 0) & (neg > 0)).sum()
    assert mixed > 200  # nearly all slots see both signs


def test_numeric_only_width_matches(tmp_path):
    path = str(tmp_path / "num.csv")
    with open(path, "w") as f:
        f.write("1,2.0,3.0\n0,4.0,5.0\n")
    src = HashedCSVChunks(
        path, chunk_rows=2, label_col=0, numeric_cols=[1, 2], n_hash=64,
    )
    assert src.n_features == 2
    (X, y, n_valid), = list(src.chunks())
    assert X.shape == (2, 2)


def test_crlf_and_n_rows_override(tmp_path):
    path = str(tmp_path / "crlf.csv")
    with open(path, "wb") as f:
        f.write(b"1,,web\r\n0,2.5,ios\r\n")
    src = HashedCSVChunks(
        path, chunk_rows=2, label_col=0, numeric_cols=[1],
        categorical_cols=[2], n_hash=32, n_rows=2,
    )
    (X, y, n_valid), = list(src.chunks())
    assert n_valid == 2 and X[0, 0] == 0.0 and X[1, 0] == 2.5
    # 'web' must hash identically whether the file is LF or CRLF
    lf = str(tmp_path / "lf.csv")
    with open(lf, "wb") as f:
        f.write(b"1,,web\n0,2.5,ios\n")
    src2 = HashedCSVChunks(
        lf, chunk_rows=2, label_col=0, numeric_cols=[1],
        categorical_cols=[2], n_hash=32,
    )
    (X2, _, _), = list(src2.chunks())
    np.testing.assert_array_equal(X, X2)
