"""GLM learner tests: sklearn parity per family, weighted exactness,
monotone IRLS, bagging/mesh/stream integration [SURVEY §4]."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_bagging_tpu import BaggingRegressor, make_mesh
from spark_bagging_tpu.models import GeneralizedLinearRegression as GLM

KEY = jax.random.key(0)


def _poisson_data(n=800, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 0.5, (n, d)).astype(np.float32)
    beta = rng.normal(0, 0.5, d)
    y = rng.poisson(np.exp(X @ beta + 0.3)).astype(np.float32)
    return X, y


class TestFamilies:
    def test_gaussian_identity_equals_ridge(self):
        from sklearn.linear_model import Ridge

        rng = np.random.default_rng(1)
        X = rng.normal(size=(300, 8)).astype(np.float32)
        y = (X @ rng.normal(size=8) + 0.1 * rng.normal(size=300)).astype(
            np.float32
        )
        glm = GLM(family="gaussian", l2=1e-6, max_iter=3)
        params, _ = glm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(300), 1
        )
        sk = Ridge(alpha=1e-6 * 300).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(params["beta"][:-1]), sk.coef_, atol=2e-3
        )

    def test_poisson_matches_sklearn(self):
        from sklearn.linear_model import PoissonRegressor

        X, y = _poisson_data()
        glm = GLM(family="poisson", l2=1e-4, max_iter=12)
        params, aux = glm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        sk = PoissonRegressor(alpha=1e-4, max_iter=300).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(params["beta"][:-1]), sk.coef_, atol=5e-3
        )
        np.testing.assert_allclose(
            float(params["beta"][-1]), sk.intercept_, atol=5e-3
        )

    def test_gamma_matches_sklearn(self):
        from sklearn.linear_model import GammaRegressor

        rng = np.random.default_rng(2)
        X = rng.normal(0, 0.4, (700, 5)).astype(np.float32)
        mu = np.exp(X @ rng.normal(0, 0.4, 5) + 1.0)
        y = rng.gamma(3.0, mu / 3.0).astype(np.float32)
        glm = GLM(family="gamma", l2=1e-4, max_iter=15)
        params, _ = glm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        sk = GammaRegressor(alpha=1e-4, max_iter=500).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(params["beta"][:-1]), sk.coef_, atol=1e-2
        )

    def test_tweedie_matches_sklearn(self):
        from sklearn.linear_model import TweedieRegressor

        rng = np.random.default_rng(3)
        X = rng.normal(0, 0.4, (900, 4)).astype(np.float32)
        mu = np.exp(X @ rng.normal(0, 0.3, 4) + 0.5)
        # compound-poisson-ish data: poisson count of gamma jumps
        nj = rng.poisson(mu)
        y = np.array([
            rng.gamma(2.0, 0.5 * m / 2.0) if k > 0 else 0.0
            for k, m in zip(nj, mu)
        ]).astype(np.float32)
        glm = GLM(family="tweedie", variance_power=1.5, l2=1e-4,
                  max_iter=20)
        params, _ = glm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        sk = TweedieRegressor(power=1.5, alpha=1e-4, max_iter=500).fit(X, y)
        np.testing.assert_allclose(
            np.asarray(params["beta"][:-1]), sk.coef_, atol=2e-2
        )

    def test_binomial_logit_recovers_probabilities(self):
        rng = np.random.default_rng(4)
        X = rng.normal(size=(1000, 5)).astype(np.float32)
        p = 1.0 / (1.0 + np.exp(-(X @ rng.normal(size=5))))
        y = (rng.uniform(size=1000) < p).astype(np.float32)
        glm = GLM(family="binomial", l2=1e-4, max_iter=12)
        params, _ = glm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(1000), 1
        )
        mu = np.asarray(glm.predict_scores(params, jnp.asarray(X)))
        assert ((mu > 0.5) == y.astype(bool)).mean() > 0.8
        assert (0 < mu).all() and (mu < 1).all()


class TestSolverProperties:
    def test_loss_curve_monotone(self):
        X, y = _poisson_data()
        glm = GLM(family="poisson", max_iter=10)
        _, aux = glm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(len(y)), 1
        )
        curve = np.asarray(aux["loss_curve"])
        assert np.all(np.diff(curve) <= 1e-6)
        assert np.isfinite(curve).all()

    def test_weighted_equals_duplicated(self):
        X, y = _poisson_data(n=300)
        rng = np.random.default_rng(5)
        k = rng.poisson(1.0, len(y))
        k[0] = max(k[0], 1)
        glm = GLM(family="poisson", max_iter=12)
        pw, _ = glm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y),
            jnp.asarray(k, jnp.float32), 1,
        )
        pd, _ = glm.fit_from_init(
            KEY, jnp.asarray(np.repeat(X, k, axis=0)),
            jnp.asarray(np.repeat(y, k)),
            jnp.ones(int(k.sum())), 1,
        )
        # not bit-exact: duplicated rows reorder f32 summations and the
        # line search may take a rounding-shifted candidate; the fits
        # must still agree to solver tolerance
        np.testing.assert_allclose(
            np.asarray(pw["beta"]), np.asarray(pd["beta"]),
            rtol=1e-3, atol=1e-4,
        )

    def test_extreme_eta_does_not_overflow(self):
        rng = np.random.default_rng(6)
        X = (10.0 * rng.normal(size=(200, 3))).astype(np.float32)
        y = rng.poisson(1.0, 200).astype(np.float32)
        glm = GLM(family="poisson", max_iter=8)
        params, aux = glm.fit_from_init(
            KEY, jnp.asarray(X), jnp.asarray(y), jnp.ones(200), 1
        )
        assert np.isfinite(np.asarray(params["beta"])).all()
        assert np.isfinite(float(aux["loss"]))

    def test_param_validation(self):
        with pytest.raises(ValueError, match="family"):
            GLM(family="weibull")
        with pytest.raises(ValueError, match="link"):
            GLM(link="probit")
        with pytest.raises(ValueError, match="logit"):
            GLM(family="poisson", link="logit")
        with pytest.raises(ValueError, match="variance_power"):
            GLM(family="tweedie", variance_power=2.5)


class TestIntegration:
    @pytest.mark.slow  # [PR 14 pyramid] ~2.3s GLM integration soak; solver exactness stays tier-1
    def test_bagged_poisson_and_mesh(self):
        X, y = _poisson_data()
        reg = BaggingRegressor(
            base_learner=GLM(family="poisson", max_iter=8),
            n_estimators=16, seed=0,
        ).fit(X, y)
        # mean deviance of the bagged mean beats the null model
        mu = reg.predict(X)
        assert mu.shape == (len(y),)
        assert np.isfinite(mu).all() and (mu > 0).all()
        mesh = make_mesh(data=8)
        a = BaggingRegressor(
            base_learner=GLM(family="poisson", max_iter=8),
            n_estimators=1, bootstrap=False, seed=0, mesh=mesh,
        ).fit(X, y)
        b = BaggingRegressor(
            base_learner=GLM(family="poisson", max_iter=8),
            n_estimators=1, bootstrap=False, seed=0,
        ).fit(X, y)
        np.testing.assert_allclose(
            a.predict(X), b.predict(X), rtol=1e-4, atol=1e-5
        )

    def test_streaming_fit(self):
        from spark_bagging_tpu import ArrayChunks

        X, y = _poisson_data()
        src = ArrayChunks(X, y, chunk_rows=200)
        reg = BaggingRegressor(
            base_learner=GLM(family="poisson"), n_estimators=8, seed=0,
        ).fit_stream(src, n_epochs=20, lr=0.05)
        mu = reg.predict(X)
        assert np.isfinite(mu).all() and (mu > 0).all()
        # learned something: correlation with targets
        assert np.corrcoef(mu, y)[0, 1] > 0.3

    @pytest.mark.slow  # [PR 14 pyramid] ~1s per-model checkpoint twin; generic round-trip stays tier-1 in test_checkpoint
    def test_checkpoint_roundtrip(self, tmp_path):
        from spark_bagging_tpu import load_model, save_model

        X, y = _poisson_data(n=200)
        reg = BaggingRegressor(
            base_learner=GLM(family="gamma", max_iter=6),
            n_estimators=4, seed=0,
        ).fit(X, np.maximum(y, 0.1))
        save_model(reg, str(tmp_path / "glm"))
        reg2 = load_model(str(tmp_path / "glm"))
        np.testing.assert_allclose(
            reg.predict(X[:50]), reg2.predict(X[:50]), rtol=1e-6
        )
