"""Plugging a CUSTOM base learner into the bagging engine.

The reference's plugin point is "any Spark ML Predictor" [SURVEY §1 L3];
here it is the `BaseLearner` contract (models/base.py): three pure
functions, each `vmap`-able over replicas. This example implements a
weighted centroid classifier in ~30 lines and bags it — subspaces, OOB,
chunked replicas and mesh sharding all work unchanged, because the
engine only ever calls the contract.

Contract rules (see models/base.py):
- treat `sample_weight` as exact per-row multiplicities,
- static shapes / no data-dependent Python control flow (it is jitted),
- reduce over rows through `maybe_psum(_, axis_name)` so the same code
  runs data-sharded.

    python examples/05_custom_learner.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import BaggingClassifier, BaseLearner
from spark_bagging_tpu.ops.reduce import maybe_psum


class CentroidClassifier(BaseLearner):
    """Nearest-weighted-centroid classifier (a minimal valid plugin)."""

    task = "classification"

    def __init__(self, ridge: float = 1e-6):
        self.ridge = ridge  # hyperparams live on the (hashable) object

    def init_params(self, key, n_features, n_outputs):
        del key
        return {"centroid": jnp.zeros((n_outputs, n_features), jnp.float32)}

    def fit(self, params, X, y, sample_weight, key, *,
            axis_name=None, prepared=None):
        del key, prepared
        C = params["centroid"].shape[0]
        Yw = jax.nn.one_hot(y, C, dtype=jnp.float32).T * sample_weight
        s1 = maybe_psum(Yw @ X, axis_name)                 # (C, F)
        cls_w = maybe_psum(Yw.sum(axis=1), axis_name)      # (C,)
        centroid = s1 / (cls_w[:, None] + self.ridge)
        params = {"centroid": centroid}
        scores = self.predict_scores(params, X)
        w_sum = jnp.maximum(maybe_psum(sample_weight.sum(), axis_name), 1e-9)
        err = (scores.argmax(1) != y).astype(jnp.float32)
        loss = maybe_psum((sample_weight * err).sum(), axis_name) / w_sum
        return params, {"loss": loss, "loss_curve": loss[None]}

    def predict_scores(self, params, X):
        c = params["centroid"]                              # (C, F)
        # negative squared distance, expanded to stay one matmul
        return 2.0 * (X @ c.T) - jnp.sum(c * c, axis=1)[None, :]


X, y = load_breast_cancer(return_X_y=True)
X = StandardScaler().fit_transform(X).astype(np.float32)

clf = BaggingClassifier(
    base_learner=CentroidClassifier(),
    n_estimators=64, max_features=0.5, oob_score=True, seed=0,
)
clf.fit(X, y)
print(f"bagged custom learner: acc {clf.score(X, y):.4f} "
      f"OOB {clf.oob_score_:.4f} "
      f"({clf.fit_report_['fits_per_sec']:.0f} fits/sec on "
      f"{clf.fit_report_['backend']})")
