"""Online serving: micro-batched, hot-swappable ensemble inference.

Fits a bag, registers it in a serving ModelRegistry, then drives the
MicroBatcher with simulated concurrent clients while hot-swapping in a
retrained model mid-traffic — the request-level analog of the batch
quickstart (01_quickstart.py).

Run anywhere: uses the TPU if one is attached, else CPU.

    python examples/09_serving.py

With the live observability plane (scrape it while it runs):

    SBT_METRICS_PORT=9100 python examples/09_serving.py
    curl :9100/healthz        # batcher liveness + live model version
    curl :9100/metrics        # Prometheus text, sbt_serving_* series
    curl :9100/varz           # JSON snapshot incl. latency quantiles
    curl :9100/debug/drift    # live drift scores vs the fit reference
    curl :9100/alerts         # burn-rate alert rule states

The traffic is also CAPTURED as a replayable workload file — the
record half of record→replay→report; replay it afterwards with:

    python -m benchmarks.replay --workload telemetry/example09.workload.jsonl --check
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import BaggingClassifier, LogisticRegression, telemetry
from spark_bagging_tpu.serving import ModelRegistry

X, y = load_breast_cancer(return_X_y=True)
X = StandardScaler().fit_transform(X).astype(np.float32)

clf_v1 = BaggingClassifier(
    base_learner=LogisticRegression(max_iter=10),
    n_estimators=64, seed=0,
).fit(X, y)

# -- register + warm: compile every row bucket BEFORE traffic ---------
registry = ModelRegistry(min_bucket_rows=8, max_batch_rows=128)
registry.register("cancer", clf_v1, warmup=True)
executor = registry.executor("cancer")
print(f"warmed buckets  : {executor.compiled_buckets}")

# -- model-quality plane: drift sketches + ensemble disagreement ------
# sticky per entry: the swap below re-attaches a fresh monitor against
# the new model's own fit-time reference profile
registry.enable_quality("cancer", refresh_every=64,
                        disagreement_every=8)
# rules sample the monitor's per-model gauges: labels must match
telemetry.alerts.install(telemetry.alerts.default_drift_rules(
    labels={"model": "cancer"}))
if (addr := telemetry.server_address()) is not None:
    host, port = addr
    print(f"metrics server  : http://{host}:{port}  "
          "(/metrics /healthz /varz /debug/spans)")

# -- simulated concurrent clients against the micro-batcher -----------
N_CLIENTS, N_REQUESTS = 8, 40
results: dict[int, int] = {}
lock = threading.Lock()


def client(cid: int, batcher) -> None:
    rng = np.random.default_rng(cid)
    ok = 0
    for _ in range(N_REQUESTS):
        i = int(rng.integers(0, len(X)))
        proba = batcher.predict_proba(X[i : i + 1], timeout=30)
        ok += int(proba.shape == (1, 2))
    with lock:
        results[cid] = ok


recorder = telemetry.workload.record()  # capture the arrival stream

with registry.batcher("cancer", max_delay_ms=2.0, max_queue=512) as b:
    threads = [
        threading.Thread(target=client, args=(c, b))
        for c in range(N_CLIENTS)
    ]
    for t in threads:
        t.start()

    # -- hot-swap a retrained model while requests are in flight ------
    clf_v2 = BaggingClassifier(
        base_learner=LogisticRegression(max_iter=10),
        n_estimators=64, seed=1,
    ).fit(X, y)
    registry.swap("cancer", clf_v2)  # atomic; in-flight batches finish
    print(f"swapped to      : version {registry.version('cancer')}")

    for t in threads:
        t.join()

served = sum(results.values())
reg = telemetry.registry()
lat = reg.histogram("sbt_serving_latency_seconds").quantiles()
print(f"requests served : {served}/{N_CLIENTS * N_REQUESTS}")
print("latency         : "
      + "  ".join(f"{k}={v * 1e3:.1f}ms" for k, v in lat.items()))
print(f"batches         : {int(reg.counter('sbt_serving_batches_total').value)}"
      f"  (coalescing ratio "
      f"{served / max(reg.counter('sbt_serving_batches_total').value, 1):.1f}"
      " requests/forward)")
print(f"compiles        : {int(reg.counter('sbt_serving_compiles_total').value)}"
      " (all during warmup/swap — zero per-request)")

# -- the model-quality plane's own /debug/drift summary ---------------
# (the same dict the scrape server serves at /debug/drift)
drift_view = telemetry.quality.debug_summary()
for mon in drift_view["monitors"]:
    drift = mon["drift"] or {}
    print("drift           : "
          f"rows={mon['rows_observed']}  "
          f"psi_max={drift.get('psi_max', 0.0):.3f}  "
          f"confidence_psi={drift.get('confidence_psi', 0.0):.3f}  "
          f"disagreement={drift.get('disagreement_mean', 0.0):.3f}  "
          f"(warmed={drift.get('warmed')})")
telemetry.alerts.get().evaluate()
print(f"alerts          : active={telemetry.alerts.get().active()}")

# -- the captured workload: this traffic is now a regression test -----
captured = telemetry.workload.stop()
wl_path = os.path.join(telemetry.telemetry_dir(),
                       "example09.workload.jsonl")
captured.save(wl_path)
print(f"workload        : {captured.n_requests} arrivals over "
      f"{captured.duration_s:.2f}s -> {wl_path}")
print("replay it       : python -m benchmarks.replay "
      f"--workload {wl_path} --check")
