"""Multi-host (multi-process) fit on ONE global mesh — runnable locally.

On a real TPU pod each host runs the SAME program and
``initialize_distributed()`` auto-detects the topology; this example
demonstrates the identical code path by spawning 2 local processes with
2 virtual CPU devices each, joined over loopback (Gloo standing in for
ICI/DCN — the setup tests/test_multihost.py verifies).

    python examples/04_multihost.py            # parent: spawns 2 workers
"""

import json
import os
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def worker(pid: int, nprocs: int, port: str, out: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from sklearn.datasets import load_breast_cancer
    from sklearn.preprocessing import StandardScaler

    from spark_bagging_tpu import BaggingClassifier, make_mesh
    from spark_bagging_tpu.parallel.distributed import initialize_distributed

    n_dev = initialize_distributed(f"localhost:{port}", nprocs, pid)

    # every process passes the same host matrix (bagging broadcasts the
    # dataset; each process transfers only its mesh shards)
    X, y = load_breast_cancer(return_X_y=True)
    X = StandardScaler().fit_transform(X).astype(np.float32)

    mesh = make_mesh(data=2, replica=2)  # global: spans both processes
    clf = BaggingClassifier(
        n_estimators=16, mesh=mesh, oob_score=True, seed=0
    ).fit(X, y)
    with open(f"{out}.{pid}", "w") as f:
        json.dump({
            "pid": pid,
            "global_devices": n_dev,
            "accuracy": clf.score(X, y),
            "oob": clf.oob_score_,
        }, f)


def main() -> None:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_PLATFORMS", None)
    with tempfile.TemporaryDirectory() as tmp:
        out = os.path.join(tmp, "result")
        procs = [
            subprocess.Popen(
                [sys.executable, __file__, "--worker", str(pid), port, out],
                env=env,
            )
            for pid in range(2)
        ]
        try:
            for p in procs:
                p.wait(timeout=300)
                assert p.returncode == 0, "worker failed"
        finally:
            for p in procs:  # never orphan the sibling on failure
                if p.poll() is None:
                    p.kill()
        for pid in range(2):
            with open(f"{out}.{pid}") as f:
                print(json.load(f))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]), 2, sys.argv[3], sys.argv[4])
    else:
        main()
