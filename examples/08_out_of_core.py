"""Out-of-core training beyond device AND host memory.

The reference reaches Criteo-1TB scale by leaving data distributed in
Spark partitions [SURVEY §1 L1]; the TPU-native equivalent streams
fixed-shape chunks through one donated-buffer optimizer step each, so
the total dataset size is bounded by NOTHING resident: benchmark
config 8 runs 40M rows x 1024 features f32 (~153 GiB) through a
16 GiB-HBM chip on a 125 GiB-RAM host this way.

This example scales the same wiring down to laptop size — turn
N_ROWS/N_FEATURES up and the resident footprint does not change:
only one chunk (plus the prefetch depth) ever exists on the host, and
one chunk plus the replica ensemble on the device.

Run: python examples/08_out_of_core.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from spark_bagging_tpu import BaggingClassifier, LogisticRegression
from spark_bagging_tpu.utils.datasets import synthetic_criteo
from spark_bagging_tpu.utils.io import SyntheticChunks
from spark_bagging_tpu.utils.metrics import roc_auc

N_ROWS, N_FEATURES, CHUNK_ROWS = 200_000, 128, 20_000


def make(n, seed=13, structure_seed=None):
    return synthetic_criteo(n, N_FEATURES, seed=seed,
                            structure_seed=structure_seed)


# the source GENERATES each chunk on demand (SeedSequence-mixed chunk
# seeds, epoch-stable) — swap in CSVChunks / LibsvmChunks /
# HashedCSVChunks / ArrowChunks for real files; the engine is identical
source = SyntheticChunks(make, N_ROWS, CHUNK_ROWS, seed=13)
data_gib = N_ROWS * N_FEATURES * 4 / 2**30

clf = BaggingClassifier(
    base_learner=LogisticRegression(l2=1e-4),
    n_estimators=32,
    seed=0,
)
clf.fit_stream(source, classes=[0, 1], n_epochs=1, steps_per_chunk=2,
               lr=0.05)

# held-out rows from the SAME mixture (structure pinned to the train
# source's, fresh row seeds), scored OUT-OF-CORE too:
# predict_proba_stream holds one chunk at a time, so the eval set's
# size is as unbounded as the training set's


def make_eval(n, seed=0):
    return make(n, seed=seed, structure_seed=13)


eval_src = SyntheticChunks(make_eval, 50_000, CHUNK_ROWS, seed=999)
proba = clf.predict_proba_stream(eval_src)
yte = np.concatenate([y[:n] for _, y, n in eval_src.chunks()])
auc = roc_auc(yte, proba[:, 1])
rep = clf.fit_report_
print(f"streamed {N_ROWS:,} rows x {N_FEATURES} features "
      f"({data_gib:.2f} GiB) in {rep['n_chunks']} chunks")
print(f"held-out AUC {auc:.4f}; "
      f"fit {rep['fit_seconds']:.1f}s on {rep['backend']}")
assert auc > 0.9

# -- the on-disk fast lane -------------------------------------------
# For wide data you WRITE yourself, store the features as ONE Arrow
# fixed-size-list column: the file is the row-major (n, d) block, so
# ArrowChunks decodes each chunk as a zero-copy reshape (no
# column->row transpose) and a cold scan runs at disk speed — the
# measured 23.67 GiB capture is benchmarks/out_of_core_file.json.
try:
    import pyarrow  # noqa: F401 — the deferred dependency

    from spark_bagging_tpu.utils.arrow import (
        ArrowChunks,
        write_row_major_ipc,
    )
except ImportError:
    print("pyarrow not installed — skipping the Arrow fast-lane demo")
else:
    import tempfile

    Xd, yd = make(20_000, seed=21, structure_seed=13)
    with tempfile.TemporaryDirectory() as td:
        fpath = os.path.join(td, "rows.arrow")
        write_row_major_ipc(fpath, Xd, yd, chunk_rows=CHUNK_ROWS,
                            label_dtype=np.int32)
        clf2 = BaggingClassifier(
            base_learner=LogisticRegression(l2=1e-4),
            n_estimators=8, seed=0,
        )
        clf2.fit_stream(ArrowChunks(fpath, CHUNK_ROWS),
                        classes=[0, 1], steps_per_chunk=2, lr=0.05)
        auc2 = roc_auc(yd, clf2.predict_proba(Xd)[:, 1])
        print(f"arrow fast lane: {clf2.n_features_in_} features "
              f"from a fixed-size-list file, train AUC {auc2:.3f}")
        assert auc2 > 0.9
