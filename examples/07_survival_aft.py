"""Bagged survival regression: Weibull AFT with right-censored data.

The Spark analog is ``AFTSurvivalRegression`` with a ``censorCol``;
here the censor indicator rides the ensemble engine's per-row ``aux``
channel (1.0 = event observed, 0.0 = right-censored) and quantile
prediction mirrors ``quantilesCol``.

    python examples/07_survival_aft.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from spark_bagging_tpu import AFTSurvivalRegression, BaggingRegressor

# Synthetic clinical-trial-shaped data: true survival time depends on
# 4 covariates; follow-up ends at a fixed administrative cutoff, so
# ~30% of subjects are right-censored (their event was never observed).
rng = np.random.default_rng(0)
n = 4000
X = rng.standard_normal((n, 4)).astype(np.float32)
beta_true = np.array([0.8, -0.5, 0.3, 0.0], np.float32)
T = np.exp(X @ beta_true + 0.6 + 0.5 * np.log(rng.exponential(1.0, n)))
cutoff = np.quantile(T, 0.7)
y = np.minimum(T, cutoff).astype(np.float32)  # observed time
censor = (T <= cutoff).astype(np.float32)     # 1 = event, 0 = censored
print(f"censored fraction: {1 - censor.mean():.2f}")

reg = BaggingRegressor(
    base_learner=AFTSurvivalRegression(max_iter=300),
    n_estimators=16,
    seed=0,
)
reg.fit(X, y, aux=censor)

pred = reg.predict(X[:5])              # e^mu — expected time scale
q = reg.predict_quantiles(X[:5], probs=(0.1, 0.5, 0.9))
print("predicted time scale:", np.round(pred, 2))
print("survival quantiles (10/50/90%):")
print(np.round(q, 2))
print("fits/sec:", round(reg.fit_report_["fits_per_sec"], 1))
