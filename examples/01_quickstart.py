"""Quickstart: bagged logistic regression with OOB scoring.

The TPU-native analog of the reference's README usage snippet
[SURVEY §2a #10]: construct, fit, predict, score — sklearn protocol.

Run anywhere: uses the TPU if one is attached, else CPU.

    python examples/01_quickstart.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from sklearn.datasets import load_breast_cancer
from sklearn.model_selection import train_test_split
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import BaggingClassifier, LogisticRegression

X, y = load_breast_cancer(return_X_y=True)
X = StandardScaler().fit_transform(X).astype(np.float32)
Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.2, random_state=0)

clf = BaggingClassifier(
    base_learner=LogisticRegression(max_iter=20, l2=1e-3),
    n_estimators=100,          # numBaseLearners
    max_samples=1.0,           # sampleRatio
    max_features=0.8,          # subspaceRatio
    oob_score=True,
    seed=0,
)
clf.fit(Xtr, ytr)

print(f"test accuracy : {clf.score(Xte, yte):.4f}")
print(f"OOB accuracy  : {clf.oob_score_:.4f}")
print(f"fits/sec      : {clf.fit_report_['fits_per_sec']:.1f} "
      f"(compile {clf.fit_report_['compile_seconds']:.1f}s, "
      f"backend {clf.fit_report_['backend']})")

proba = clf.predict_proba(Xte[:3])
print("predict_proba :", np.round(proba, 3).tolist())
