"""Device-mesh sharding: replicas and rows over a (data, replica) mesh.

The reference scales by Spark partitions + driver-side fit futures
[SURVEY §2c]; here the same two axes are a jax.sharding Mesh — replicas
shard over `replica`, rows over `data`, learner row-statistics `psum`
across data shards (bit-identical to the single-device fit).

Run with any device count; to fake an 8-device topology on CPU:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/02_mesh_sharding.py --cpu
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--cpu" in sys.argv:
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import numpy as np
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import BaggingClassifier, make_mesh

X, y = load_breast_cancer(return_X_y=True)
X = StandardScaler().fit_transform(X).astype(np.float32)

n_dev = jax.device_count()
data = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
mesh = make_mesh(data=data)  # remaining devices on the replica axis
print(f"mesh: {dict(mesh.shape)} over {n_dev} {jax.default_backend()} device(s)")

clf = BaggingClassifier(
    n_estimators=max(8, n_dev * 4), mesh=mesh, oob_score=True, seed=0
).fit(X, y)
print(f"accuracy {clf.score(X, y):.4f}  OOB {clf.oob_score_:.4f}")

# Multi-host pods: call initialize_distributed() first (one process per
# host), build the mesh over jax.devices() (global), and pass the same
# host arrays on every process — see tests/test_multihost.py for a
# runnable 2-process example.
