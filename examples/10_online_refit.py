"""The closed loop: record traffic, inject drift, watch the refit.

Serves a bag behind a drift monitor and a burn-rate alert rule, feeds
it traffic that covariate-shifts halfway through, and lets the online
trainer close the loop: the alert fires, the trainer drains the
recent labeled window, refits the ensemble with streaming Poisson(1)
weights (warm-started from the incumbent's stacked params), validates
the candidate against the incumbent, and publishes a version-2 swap +
``serve_config.json`` manifest — then prints the refit transcript and
the drift gauge's recovery.

Run anywhere: uses the TPU if one is attached, else CPU.

    python examples/10_online_refit.py

The same loop is a deterministic CI gate:

    python -m benchmarks.replay --drift --online --check
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json

import numpy as np

from spark_bagging_tpu import BaggingClassifier, LogisticRegression, telemetry
from spark_bagging_tpu.online import LabeledBuffer, OnlineTrainer
from spark_bagging_tpu.serving import ModelRegistry
from spark_bagging_tpu.telemetry import alerts, workload

telemetry.enable()

# -- a model and its (hidden) concept ------------------------------------
rng = np.random.default_rng(0)
d = 8
X_train = rng.normal(size=(512, d)).astype(np.float32)
w_true = rng.normal(size=d)


def labels(X):
    """The application's ground truth (arrives with the traffic here;
    on whatever delay your system has in production)."""
    return (np.asarray(X, np.float64) @ w_true > 0).astype(np.int32)


clf = BaggingClassifier(
    base_learner=LogisticRegression(max_iter=5),
    n_estimators=8, seed=0, oob_score=True,
).fit(X_train, labels(X_train))

# -- the serving stack + the continuous-learning plane -------------------
registry = ModelRegistry(min_bucket_rows=8, max_batch_rows=64)
registry.register("prod", clf, warmup=True)
monitor = registry.enable_quality("prod", refresh_every=1)  # sticky

engine = alerts.AlertEngine([alerts.AlertRule(
    "feature-drift", "sbt_quality_psi_max", labels=monitor.labels,
    threshold=0.5, fast_window_s=2.0, slow_window_s=8.0,
    cooldown_s=1e9,
)])

buffer = LabeledBuffer(capacity_rows=128, labels={"model": "prod"})
recorder = workload.WorkloadRecorder()
recorder.start()  # the capture half of record->replay
trainer = OnlineTrainer(
    registry, "prod", buffer,
    workload_recorder=recorder,
    epochs=2, min_refit_rows=32, margin=0.05, seed=0,
    publish_dir=os.path.join(telemetry.telemetry_dir(),
                             "example10_publish"),
    trigger_rules=("feature-drift",),
)
engine.subscribe(trainer.on_alert)  # the trigger bus

# -- traffic: steady, then covariate-shifted -----------------------------
# a stepped micro-batcher (threaded=False): requests coalesce exactly
# as in production, the recorder captures every arrival, and the whole
# script stays single-threaded + reproducible
batcher = registry.batcher("prod", threaded=False, max_delay_ms=2.0)
print("serving 400 requests; drift (X + 4.0) injected at request 200\n")
for t in range(400):
    Xq = rng.normal(size=(2, d)).astype(np.float32)
    if t >= 200:
        Xq = Xq + np.float32(4.0)  # the incident
    fut = batcher.submit(Xq)             # recorded arrival
    buffer.add(Xq, labels(Xq))           # the labeled feed
    batcher.run_pending()                # serve; feeds drift sketches
    fut.result(10.0)
    engine.evaluate(now=float(t) * 0.1)  # scrape-cadence evaluation
    refits = trainer.run_pending(now=float(t) * 0.1)  # stepped drive
    for rec in refits:
        print(f"refit at t={t}:")
        print(json.dumps({k: v for k, v in rec.items()
                          if k != "seconds"}, indent=2, default=str))

batcher.close()
recorder.stop()

# -- the outcome ---------------------------------------------------------
live = registry.executor("prod")
drift = live.quality.drift()
print("\nlive model version:", registry.version("prod"),
      "(was 1 before the alert)")
print("refit summary:", {k: v for k, v in trainer.summary().items()
                         if k != "transcript"})
print("post-swap drift psi_max:",
      round(drift["psi_max"], 4),
      "(warmed)" if drift["warmed"] else "(below evidence floor)")
print("alert state:", dict(
    fired=engine.state()["rules"][0]["fired"],
    resolved=engine.state()["rules"][0]["resolved"],
    active=engine.state()["rules"][0]["active"],
))
assert registry.version("prod") == 2, "the loop should have published"
print("\nthe loop closed: drift detected -> refit -> fleet-convergent "
      "swap -> monitor re-anchored on the adapted model")
