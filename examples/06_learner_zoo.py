"""Every base-learner family in one run — the L3 plugin slot tour.

The reference accepts any Spark ML Predictor as its base learner
[B:5, SURVEY §1 L3]; this example fits a small bagged ensemble of each
TPU-native family on the same data and prints train/OOB scores.

Run:  python examples/06_learner_zoo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from sklearn.datasets import load_breast_cancer, load_diabetes
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import (
    BaggingClassifier,
    BaggingRegressor,
    BernoulliNB,
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    FMClassifier,
    FMRegressor,
    GBTClassifier,
    GBTRegressor,
    GaussianNB,
    GeneralizedLinearRegression,
    IsotonicRegression,
    LinearRegression,
    LinearSVC,
    LogisticRegression,
    MLPClassifier,
    MLPRegressor,
    MultinomialNB,
    RandomForestClassifier,
    RandomForestRegressor,
)

X, y = load_breast_cancer(return_X_y=True)
Xs = StandardScaler().fit_transform(X).astype(np.float32)

print("== classification (breast-cancer, 16 bags) ==")
classifiers = [
    LogisticRegression(max_iter=8),
    LinearSVC(max_iter=6),
    DecisionTreeClassifier(max_depth=4),
    MLPClassifier(hidden=32, max_iter=150),
    GaussianNB(),
    BernoulliNB(),                      # binarizes at 0 (standardized)
    MultinomialNB(),                    # needs nonnegative features
    FMClassifier(factor_size=4, max_iter=150, lr=0.05),
    GBTClassifier(n_rounds=15, max_depth=3),
]
for learner in classifiers:
    Xin = np.abs(Xs) if isinstance(learner, MultinomialNB) else Xs
    clf = BaggingClassifier(
        base_learner=learner, n_estimators=16, seed=0, oob_score=True
    ).fit(Xin, y)
    print(f"  {type(learner).__name__:<22} "
          f"train={clf.score(Xin, y):.3f}  oob={clf.oob_score_:.3f}")

rf = RandomForestClassifier(n_estimators=32, max_depth=4, oob_score=True,
                            seed=0).fit(Xs, y)
print(f"  {'RandomForestClassifier':<22} train={rf.score(Xs, y):.3f}  "
      f"oob={rf.oob_score_:.3f}")

Xd, yd = load_diabetes(return_X_y=True)
Xd = StandardScaler().fit_transform(Xd).astype(np.float32)
# gradient learners (MLP/FM) want O(1) targets; GLM-poisson wants a
# positive mean near 1 — same standard practice as any framework
yz = ((yd - yd.mean()) / yd.std()).astype(np.float32)
yp = (yd / yd.mean()).astype(np.float32)
yd = yd.astype(np.float32)

print("== regression (diabetes, 16 bags) ==")
regressors = [
    (LinearRegression(), yd),
    (GeneralizedLinearRegression(family="gaussian"), yd),
    (GeneralizedLinearRegression(family="poisson", max_iter=20), yp),
    (DecisionTreeRegressor(max_depth=4), yd),
    (MLPRegressor(hidden=32, max_iter=300), yz),
    (FMRegressor(factor_size=4, max_iter=300, lr=0.03), yz),
    (GBTRegressor(n_rounds=20, max_depth=3), yd),
    (IsotonicRegression(n_bins=64), yd),  # single-feature (column 0)
]
for learner, target in regressors:
    reg = BaggingRegressor(
        base_learner=learner, n_estimators=16, seed=0
    ).fit(Xd, target)
    print(f"  {type(learner).__name__:<28} "
          f"({getattr(learner, 'family', ''):<8}) r2={reg.score(Xd, target):.3f}")

rfr = RandomForestRegressor(n_estimators=32, max_depth=4, seed=0).fit(Xd, yd)
print(f"  {'RandomForestRegressor':<28} {'':<10} r2={rfr.score(Xd, yd):.3f}")

# survival: censored targets ride the aux channel (see 07_survival_aft)
from spark_bagging_tpu import AFTSurvivalRegression

y_pos = yd - yd.min() + 1.0  # survival times must be positive
censor = (y_pos <= np.quantile(y_pos, 0.8)).astype(np.float32)
aft = BaggingRegressor(
    base_learner=AFTSurvivalRegression(max_iter=200), n_estimators=16,
    seed=0,
).fit(Xd, np.minimum(y_pos, np.quantile(y_pos, 0.8)), aux=censor)
corr = np.corrcoef(aft.predict(Xd), y_pos)[0, 1]
print(f"  {'AFTSurvivalRegression':<28} {'(20% censored)':<10} "
      f"corr={corr:.3f}")
