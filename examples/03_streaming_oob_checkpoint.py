"""Out-of-core streaming: chunked fit + streamed OOB + checkpoint/resume.

The reference reaches beyond-memory scale via Spark's partitioned
executors [SURVEY §1 L1]; the TPU-native engine streams fixed-shape
chunks host→HBM, regenerating every replica's bootstrap weights
on-device from (seed, chunk, replica) — so OOB scoring and bit-exact
resume need no global membership state.

    python examples/03_streaming_oob_checkpoint.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from sklearn.datasets import load_breast_cancer
from sklearn.preprocessing import StandardScaler

from spark_bagging_tpu import ArrayChunks, BaggingClassifier

X, y = load_breast_cancer(return_X_y=True)
X = StandardScaler().fit_transform(X).astype(np.float32)
src = ArrayChunks(X, y, chunk_rows=128)  # stand-in for Libsvm/CSV/ArrowChunks

with tempfile.TemporaryDirectory() as tmp:
    ckpt = os.path.join(tmp, "stream_ckpt")
    clf = BaggingClassifier(n_estimators=32, seed=0, oob_score=True)
    clf.fit_stream(
        src, n_epochs=10, lr=0.05,
        checkpoint_dir=ckpt, checkpoint_every=10,
    )
    print(f"stream fit: acc {clf.score(X, y):.4f}  OOB {clf.oob_score_:.4f} "
          f"({clf.fit_report_['n_chunks']} chunks x "
          f"{clf.fit_report_['n_epochs']} epochs)")

    # a killed fit resumes from the snapshot, bit-identical:
    resumed = BaggingClassifier(n_estimators=32, seed=0)
    resumed.fit_stream(src, n_epochs=10, lr=0.05, resume_from=ckpt)
    print(f"resumed fit: acc {resumed.score(X, y):.4f}")

# Model persistence (MLWritable analog): save/load the fitted ensemble
import tempfile as _tf

with _tf.TemporaryDirectory() as tmp:
    path = os.path.join(tmp, "model")
    clf.save(path)
    reloaded = BaggingClassifier.load(path)
    assert np.allclose(reloaded.predict_proba(X), clf.predict_proba(X))
    print("save/load round-trip: OK")
