"""Headline tuning sweep on the real chip: blocked Hessian, chunk size
and row-tile grid, 2 reps each (first rep pays warmup), steady-state
fits/sec per cell. Writes benchmarks/tune_headline.json.

Resumable per cell: already-measured cells (fps non-null in the
existing JSON) are kept and skipped, so a tunnel that dies mid-sweep
costs only the unmeasured cells on the next attempt — the watcher
re-invokes this script until the grid is fully measured."""
import json, os, sys
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import numpy as np
from spark_bagging_tpu import BaggingClassifier, LogisticRegression
from spark_bagging_tpu.utils.datasets import synthetic_covtype

OUT = os.path.join(REPO, "benchmarks", "tune_headline.json")
done: dict = {}
if os.path.exists(OUT):
    try:
        for c in json.load(open(OUT)):
            if c.get("fps"):
                done[(c["impl"], c["chunk"], c["row_tile"])] = c
    except Exception:
        pass

X, y = synthetic_covtype(581_012)
mu, sigma = X.mean(0), X.std(0) + 1e-8
X = ((X - mu) / sigma).astype(np.float32)
results = []
for impl, chunk, row_tile in [
    ("blocked", 200, None), ("blocked", 100, None), ("blocked", 300, None),
    ("blocked", 400, 65536), ("blocked", 500, 65536),
    # HBM-aware auto chunk [VERDICT r2 ask#8]: must pick a working
    # chunk unattended (the cell also validates the bytes model on
    # real silicon)
    ("blocked", None, None),
    # packed: blocked FLOPs at ~2.4x the MXU output-tile fill; temp is
    # O(tile*P*d) so it needs row tiling and a smaller replica chunk
    ("packed", 50, 16384), ("packed", 100, 8192), ("packed", 200, 4096),
    ("packed", 100, 16384),
    # pallas: packed math, wide operand built in VMEM (no HBM temp)
    ("pallas", 100, None), ("pallas", 200, None), ("pallas", 400, None),
]:
    if (impl, chunk, row_tile) in done:
        results.append(done[(impl, chunk, row_tile)])
        continue
    learner = LogisticRegression(l2=1e-3, max_iter=3, precision="high",
                                 row_tile=row_tile, hessian_impl=impl)
    clf = BaggingClassifier(base_learner=learner, n_estimators=1000,
                            chunk_size=chunk, seed=0)
    cell = {"impl": impl, "chunk": chunk, "row_tile": row_tile,
            "fps": None}
    try:
        best = None
        for r in range(2):
            clf.fit(X, y)
            rep = clf.fit_report_
            if best is None or rep["fit_seconds"] < best:
                best = rep["fit_seconds"]
                # the winning rep's on-chip efficiency [VERDICT r2 ask#2]
                cell["mfu"] = (
                    round(rep["mfu"], 3) if rep.get("mfu") else None
                )
                cell["tflops"] = (
                    round(rep["achieved_tflops"], 1)
                    if rep.get("achieved_tflops") else None
                )
        cell["fps"] = round(1000 / best, 1)
        cell["chunk_resolved"] = rep.get("chunk_size_resolved", chunk)
        cell["acc"] = round(float(clf.score(X[:100_000], y[:100_000])), 4)
    except Exception as e:
        cell["error"] = f"{type(e).__name__}: {e}"[:200]
    results.append(cell)
    print(json.dumps(cell), flush=True)
    # incremental write keeps prior-attempt measurements the loop has
    # not reached yet — dying mid-sweep must never lose a measured cell
    emitted = {(c["impl"], c["chunk"], c["row_tile"]) for c in results}
    rest = [c for k, c in done.items() if k not in emitted]
    with open(OUT, "w") as f:
        json.dump(results + rest, f, indent=1)
