"""Headline tuning sweep on the real chip: blocked Hessian, chunk size
and row-tile grid, 2 reps each (first rep pays warmup), steady-state
fits/sec per cell. Writes benchmarks/tune_headline.json.

Resumable per cell: already-measured cells (fps non-null in the
existing JSON) are kept and skipped, so a tunnel that dies mid-sweep
costs only the unmeasured cells on the next attempt — the watcher
re-invokes this script until the grid is fully measured.

Each cell runs in its OWN SUBPROCESS with a hard timeout: on
2026-07-31 a tunnel-side compile-helper crash (HTTP 500) left the
in-process sweep blocked in an RPC for 25+ minutes of a live TPU
window. A hung cell now costs at most CELL_TIMEOUT_S and is recorded
as an error; the next cell gets a fresh client connection. Protocol in
benchmarks/isolation.py.

Pallas compile-failure plan [VERDICT r4 ask#6]: the first Mosaic
compile of ops/gram.py is untried on silicon, so one pallas cell is
promoted FIRST (order_cells) — if Mosaic rejects the kernel, that
cell records the error and the sweep falls through, in order, to (1)
the promoted packed cell (same math, XLA matmul), (2) the remaining
never-attempted blocked/packed grid, (3) errored pallas cells LAST on
any re-invocation — a window never ends with healthy impls unmeasured
because pallas failed. Rehearsed end-to-end (mocked Mosaic error) in
tests/test_bench_tooling.py::TestPallasFallbackRehearsal.
"""
import json, os, sys
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

OUT = os.path.join(REPO, "benchmarks", "tune_headline.json")
CELL_TIMEOUT_S = 900

def order_cells(grid, prior_err):
    """Never-attempted cells first, previously-errored cells last: a
    persistently hanging early cell must not starve the rest of the
    grid under the watcher's outer timeout (each errored retry can
    cost CELL_TIMEOUT_S). Within the never-attempted group, one cell
    per untried impl leads (pallas, then packed): the first real
    Mosaic compile of ops/gram.py is an untested event, so it must
    happen while the window still has time to fall back — not after
    the blocked grid has consumed it. Stable within each group."""
    first_of_impl = {}
    for spec in grid:
        if spec not in prior_err:
            first_of_impl.setdefault(spec[0], spec)
    derisk_impls = ("pallas", "packed")
    derisk = {first_of_impl[i]: rank
              for rank, i in enumerate(derisk_impls)
              if i in first_of_impl}
    # default rank is a constant PAST every promotion rank — len(derisk)
    # would tie with the last promoted cell when an impl has no untried
    # cells left, silently demoting the other impl's promotion
    return sorted(grid, key=lambda k: (k in prior_err,
                                       derisk.get(k, len(derisk_impls))))


# cell = (impl, chunk, row_tile, max_iter, init)
GRID = [
    # controls: the round-2 headline config (3 cold Newton iters)
    ("blocked", 200, None, 3, "zeros"),
    ("blocked", 100, None, 3, "zeros"),
    # pooled warm start: ONE refinement iter from a shared pooled solve
    # reaches 3-cold-iter ensemble accuracy at ~1/3 the per-replica
    # Newton work (tests/test_pooled_init.py); max_iter=2 cell is the
    # parity fallback if 1 iter misses the gate at 581k
    ("blocked", 200, None, 1, "pooled"),
    ("blocked", 300, None, 1, "pooled"),
    ("blocked", 200, None, 2, "pooled"),
    # HBM-aware auto chunk [VERDICT r2 ask#8]: must pick a working
    # chunk unattended (the cell also validates the bytes model on
    # real silicon)
    ("blocked", None, None, 1, "pooled"),
    ("blocked", 400, 65536, 3, "zeros"),
    # packed: blocked FLOPs at ~2.4x the MXU output-tile fill; temp is
    # O(tile*P*d) so it needs row tiling and a smaller replica chunk
    ("packed", 100, 8192, 3, "zeros"),
    ("packed", 100, 8192, 1, "pooled"),
    ("packed", 200, 4096, 1, "pooled"),
    # pallas: packed math, wide operand built in VMEM — but its
    # (tile, P) scale-matrix input is an HBM temp per replica, so
    # row_tile is REQUIRED at headline scale (untiled S is ~65 MB x
    # chunk replicas; round-4 audit). Tiles are multiples of the
    # kernel's 512-row grid tile.
    ("pallas", 200, 65536, 3, "zeros"),
    ("pallas", 200, 65536, 1, "pooled"),
    ("pallas", 400, 32768, 1, "pooled"),
]


def run_cell(impl: str, chunk, row_tile, max_iter: int,
             init: str) -> dict:
    """Measure one grid cell (called in the child process)."""
    import compile_cache

    compile_cache.enable()
    from headline_data import HEADLINE, WORKLOAD, load_headline_data
    from spark_bagging_tpu import BaggingClassifier, LogisticRegression

    X, y = load_headline_data()
    learner = LogisticRegression(
        l2=HEADLINE["l2"], max_iter=max_iter, init=init,
        precision=HEADLINE["precision"], row_tile=row_tile,
        hessian_impl=impl)
    clf = BaggingClassifier(base_learner=learner,
                            n_estimators=HEADLINE["n_replicas"],
                            chunk_size=chunk, seed=0)
    cell = {"impl": impl, "chunk": chunk, "row_tile": row_tile,
            "max_iter": max_iter, "init": init, "fps": None}
    best = None
    for _ in range(2):
        clf.fit(X, y)
        rep = clf.fit_report_
        if best is None or rep["fit_seconds"] < best:
            best = rep["fit_seconds"]
            # the winning rep's on-chip efficiency [VERDICT r2 ask#2]
            cell["mfu"] = round(rep["mfu"], 3) if rep.get("mfu") else None
            cell["tflops"] = (
                round(rep["achieved_tflops"], 1)
                if rep.get("achieved_tflops") else None
            )
    cell["fps"] = round(HEADLINE["n_replicas"] / best, 1)
    cell["chunk_resolved"] = rep.get("chunk_size_resolved", chunk)
    cell["acc"] = round(float(clf.score(X[:100_000], y[:100_000])), 4)
    cell["workload"] = WORKLOAD
    cell["compile_cache"] = compile_cache.stats()
    return cell


def cell_key(c: dict) -> tuple:
    """Resume key; pre-pooled records default to (3, 'zeros') — the
    constants they were measured under."""
    return (c["impl"], c["chunk"], c["row_tile"],
            c.get("max_iter", 3), c.get("init", "zeros"))


def main() -> None:
    if "--cell" in sys.argv:
        spec = json.loads(sys.argv[sys.argv.index("--cell") + 1])
        impl, chunk, row_tile, max_iter, init = spec
        try:
            cell = run_cell(impl, chunk, row_tile, max_iter, init)
        except Exception as e:  # noqa: BLE001 — child reports, parent records
            cell = {"impl": impl, "chunk": chunk, "row_tile": row_tile,
                    "max_iter": max_iter, "init": init, "fps": None,
                    "error": f"{type(e).__name__}: {e}"[:200]}
        print("CELL_RESULT " + json.dumps(cell), flush=True)
        return

    from headline_data import WORKLOAD

    done: dict = {}
    prior_err: dict = {}
    if os.path.exists(OUT):
        try:
            for c in json.load(open(OUT)):
                # a cell measured under a different workload stamp (or
                # none) is stale — re-measure it, don't resume it
                if c.get("fps") and c.get("workload") == WORKLOAD:
                    done[cell_key(c)] = c
                elif c.get("error"):
                    prior_err[cell_key(c)] = c
        except Exception:
            pass

    from isolation import child_cmd, run_isolated_child

    results = []
    for spec in order_cells(GRID, prior_err):
        if spec in done:
            results.append(done[spec])
            continue
        impl, chunk, row_tile, max_iter, init = spec
        result, error = run_isolated_child(
            child_cmd(os.path.abspath(__file__), "--cell",
                      json.dumps(list(spec))),
            CELL_TIMEOUT_S, "CELL_RESULT",
        )
        cell = result if result is not None else {
            "impl": impl, "chunk": chunk, "row_tile": row_tile,
            "max_iter": max_iter, "init": init,
            # keep the TAIL — that's where the exception line lives
            "fps": None, "error": error[-200:],
        }
        results.append(cell)
        print(json.dumps(cell), flush=True)
        # incremental write keeps prior-attempt records the loop has not
        # reached yet — measured cells AND error records (the errored-
        # last ordering above depends on errors surviving rewrites)
        emitted = {cell_key(c) for c in results}
        rest = [c for k, c in {**prior_err, **done}.items()
                if k not in emitted]
        with open(OUT, "w") as f:
            json.dump(results + rest, f, indent=1)


if __name__ == "__main__":
    main()
