"""Run one unit of TPU work in an isolated, timed child process.

Shared by the tuning sweep (per grid cell) and the config suite (per
config). The protocol exists because a tunnel-side compile-helper crash
can leave a JAX client wedged in an RPC forever (observed 2026-07-31):

- own process group (``start_new_session``) + ``killpg`` on timeout,
  because JAX helper children inherit the pipes and would keep
  ``communicate()`` blocked past the direct child's death;
- a SIGTERM/SIGINT handler while the child runs, so the watcher's
  *outer* ``timeout`` killing the parent also kills the child's whole
  group — an orphaned child would keep running on the TPU and contend
  with the watcher's next stage;
- a shared persistent compilation cache, so process isolation doesn't
  re-pay compiles a prior unit already did;
- results ride one ``<prefix> <json>`` stdout line.
"""
import json
import os
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _kill_group(proc) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    try:
        proc.communicate(timeout=30)
    except subprocess.TimeoutExpired:
        pass


def run_isolated_child(cmd: list, timeout_s: float, result_prefix: str):
    """Returns ``(result_dict, None)`` or ``(None, error_str)``."""
    env = dict(os.environ,
               JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"))
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True,
    )

    def on_term(signum, frame):
        _kill_group(proc)
        # re-raise with default disposition so the parent still dies
        # with the right status for its own caller (the watcher)
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    prev = {s: signal.signal(s, on_term)
            for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            _kill_group(proc)
            return None, f"timed out at {timeout_s:.0f}s (hung RPC?)"
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
    prefix = result_prefix + " "
    for line in out.splitlines():
        if line.startswith(prefix):
            return json.loads(line[len(prefix):]), None
    return None, (
        f"child rc={proc.returncode}, no result: " + err.strip()[-300:]
    )


def child_cmd(script: str, *args: str) -> list:
    return [sys.executable, script, *args]
