"""Run one unit of TPU work in an isolated, timed child process.

Shared by the tuning sweep (per grid cell) and the config suite (per
config). The protocol exists because a tunnel-side compile-helper crash
can leave a JAX client wedged in an RPC forever (observed 2026-07-31):

- own process group (``start_new_session``) + ``killpg`` on timeout,
  because JAX helper children inherit the pipes and would keep
  ``communicate()`` blocked past the direct child's death;
- a SIGTERM/SIGINT handler while the child runs, so the watcher's
  *outer* ``timeout`` killing the parent also kills the child's whole
  group — an orphaned child would keep running on the TPU and contend
  with the watcher's next stage;
- a shared persistent compilation cache, so process isolation doesn't
  re-pay compiles a prior unit already did;
- results ride one ``<prefix> <json>`` stdout line.
"""
import fcntl
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOCK_PATH = os.path.join(REPO, ".tpu_lock")


def _kill_group(proc) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    try:
        proc.communicate(timeout=30)
    except subprocess.TimeoutExpired:
        pass


def _acquire_device_lock(deadline_s: float):
    """One TPU child at a time, machine-wide: the watcher's capture
    stages and a driver-invoked bench.py can overlap in wall-clock, and
    two benchmark processes contending for the single chip would
    corrupt both runs' timings (or OOM HBM). flock is released by the
    kernel when the holder exits, so a killed parent can't leak the
    lock. Polls nonblocking so a wedged holder costs at most
    ``deadline_s``, not forever."""
    f = open(LOCK_PATH, "w")
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except BlockingIOError:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                f.close()
                return None
            time.sleep(min(5.0, remaining))


def run_isolated_child(cmd: list, timeout_s: float, result_prefix: str):
    """Returns ``(result_dict, None)`` or ``(None, error_str)``.

    ``timeout_s`` is the TOTAL budget: lock wait and child run share
    it, so the caller's outer bound (the watcher's stage ``timeout``)
    stays meaningful even when another process holds the chip. A
    contended lock that leaves too little budget returns an error
    rather than starting a child doomed to be killed mid-measure.
    """
    start = time.monotonic()
    lock = _acquire_device_lock(deadline_s=timeout_s)
    if lock is None:
        return None, (
            f"device lock not acquired within {timeout_s:.0f}s — another "
            "benchmark process holds the TPU"
        )
    try:
        remaining = timeout_s - (time.monotonic() - start)
        if remaining < 60.0:
            return None, (
                f"device lock left only {remaining:.0f}s of the "
                f"{timeout_s:.0f}s budget — retry next window"
            )
        return _run_child_locked(cmd, remaining, result_prefix)
    finally:
        lock.close()


def _run_child_locked(cmd: list, timeout_s: float, result_prefix: str):
    env = dict(os.environ,
               JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"))
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, start_new_session=True,
    )

    def on_term(signum, frame):
        _kill_group(proc)
        # re-raise with default disposition so the parent still dies
        # with the right status for its own caller (the watcher)
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    prev = {s: signal.signal(s, on_term)
            for s in (signal.SIGTERM, signal.SIGINT)}
    try:
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            _kill_group(proc)
            return None, f"timed out at {timeout_s:.0f}s (hung RPC?)"
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
    prefix = result_prefix + " "
    for line in out.splitlines():
        if line.startswith(prefix):
            return json.loads(line[len(prefix):]), None
    return None, (
        f"child rc={proc.returncode}, no result: " + err.strip()[-300:]
    )


def child_cmd(script: str, *args: str) -> list:
    return [sys.executable, script, *args]
