"""Benchmark + replay tooling as an importable package.

Scripts here remain directly runnable (``python benchmarks/x.py``);
the package form exists so the replay gate has a stable CLI address —
``python -m benchmarks.replay --check`` — and so tests can drive the
replay engine in-process instead of paying a subprocess JAX import
per assertion.
"""
