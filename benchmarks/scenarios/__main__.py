"""CLI: ``python -m benchmarks.scenarios <command>``.

Commands::

    list                 registered scenarios (no jax, no execution)
    run    [--only a,b]  execute, print + write the conformance report
    record [--only a,b]  execute and (re)write the committed baselines
    check  [--only a,b]  execute and gate against the baselines
    history [--limit N]  the longitudinal trend store + verdicts

Exit codes (the shared gate contract, see benchmarks/BUDGETS.md):
0 pass, 2 digest/SLO breach (or missing baseline), 3 host-conditional
band only. ``history`` exits 0/2 on trend OK / digest flip.

Digest determinism is environment-bound: baselines are recorded under
``SCENARIO_DEVICES`` forced CPU devices (the test conftest's exact
setup), so when jax is not yet initialized the CLI forces the same
environment — a stock ``python -m benchmarks.scenarios check``
byte-matches the committed baselines with no flags.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)

from benchmarks.scenarios import SCENARIO_DEVICES, SCENARIOS, names  # noqa: E402


def _force_scenario_env() -> None:
    """Match the recording environment before jax initializes (the
    replay.py --devices precedent): forced host CPU devices so fit
    bits — and therefore every committed digest — reproduce. A jax
    imported earlier (tests, embedding processes) is left alone; the
    runner downgrades un-comparable digests to the band exit."""
    if "jax" in sys.modules:
        return
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count"
        f"={SCENARIO_DEVICES}"
    ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m benchmarks.scenarios",
        description="deterministic scenario-conformance runner",
    )
    ap.add_argument("command",
                    choices=("list", "run", "record", "check",
                             "history"))
    ap.add_argument("--only", default=None,
                    help="comma-separated scenario names (default: "
                         "all registered)")
    ap.add_argument("--repeats", type=int, default=None,
                    help="override per-scenario replay_median repeats")
    ap.add_argument("--out", default=None,
                    help="conformance report JSON path (default: "
                         "scenario_report.json in $SBT_TELEMETRY_DIR)")
    ap.add_argument("--baselines", default=None,
                    help="baseline directory override (default: the "
                         "committed benchmarks/baselines/scenarios/)")
    ap.add_argument("--no-history", action="store_true",
                    help="do not append this run to the longitudinal "
                         "history store")
    ap.add_argument("--limit", type=int, default=32,
                    help="history: newest records to render")
    args = ap.parse_args(argv)

    only = ([s.strip() for s in args.only.split(",") if s.strip()]
            if args.only else None)
    if only:
        unknown = [n for n in only if n not in SCENARIOS]
        if unknown:
            ap.error(f"unknown scenario(s) {unknown}; "
                     f"registered: {names()}")

    if args.command == "list":
        for n in names():
            sc = SCENARIOS[n]
            kind = ("fleet" if sc.fleet
                    else f"mesh({sc.devices})" if sc.devices
                    else "single")
            print(f"{n:>16}  [{kind}]  {sc.description}")
        print(f"{len(SCENARIOS)} scenarios registered; baselines in "
              "benchmarks/baselines/scenarios/")
        return 0

    _force_scenario_env()
    from spark_bagging_tpu import telemetry
    from spark_bagging_tpu.telemetry import history as history_mod

    if args.command == "history":
        report = history_mod.history_report(limit=args.limit)
        print(history_mod.render_history(report))
        return 0 if report["trend"]["ok"] else 2

    from benchmarks.scenarios import runner

    report = runner.run_conformance(
        args.command, only,
        repeats=args.repeats,
        baselines_root=args.baselines,
        append_history=not args.no_history,
    )
    out = args.out or os.path.join(
        telemetry.telemetry_dir(), "scenario_report.json"
    )
    with open(out, "w") as f:
        json.dump(report, f, indent=2, default=str)
        f.write("\n")
    print(runner.render_conformance(report))
    print(f"report: {out}")
    return report["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
