"""Scenario runner: execute, record, and check the registry.

Execution reuses ``benchmarks/replay.py`` wholesale — every scenario
is a :func:`~benchmarks.replay.replay_median` drive (repeats asserted
byte-identical) over a seeded synthetic workload against a freshly
registered model. ``record`` commits the resulting digest identity +
the scenario's SLO spec as the baseline JSON; ``check`` re-runs and
compares:

- **digests / counts** — exact (a flip is a hard breach, exit 2),
  comparable only when the environment matches the recording
  (backend + forced device count; a mismatch downgrades the scenario
  to the host-conditional band, exit 3, never a false breach);
- **SLO** — the BASELINE file's spec (round-tripped through
  ``SLOSpec.from_dict``, unknown fields loud) evaluated via
  ``replay.check_report`` so drift/fleet transcript checks ride along;
  failed host-band checks (rps/latency/stage-share) band to exit 3,
  anything else is a breach;
- **parity** — ``parity_with`` scenarios must reproduce the reference
  scenario's committed output digest bitwise.

Every run appends a compact record to the longitudinal trend store
(``telemetry/history.py``) and exports ``sbt_scenario_*`` series, so
the conformance plane is itself observable.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any

from benchmarks.scenarios import (
    SCENARIO_DEVICES,
    SCENARIO_SCHEMA_VERSION,
    Scenario,
    select,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE_SCHEMA_VERSION = 1


def baselines_dir() -> str:
    """The committed scenario baselines — the ONLY scenario artifacts
    under version control (run reports and history live in
    ``telemetry_dir()``)."""
    return os.path.join(REPO, "benchmarks", "baselines", "scenarios")


def baseline_path(name: str, root: str | None = None) -> str:
    return os.path.join(root or baselines_dir(), f"{name}.json")


def load_baseline(name: str,
                  root: str | None = None) -> dict[str, Any] | None:
    path = baseline_path(name, root)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def environment() -> dict[str, Any]:
    import jax

    return {
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "jax": jax.__version__,
    }


def env_comparable(env: dict[str, Any],
                   recorded: dict[str, Any] | None) -> bool:
    """Digests are byte-comparable only when backend and device count
    match the recording (fit bits depend on both). The jax version is
    recorded for forensics but not gated — the container pins it."""
    if not recorded:
        return False
    return (env.get("backend") == recorded.get("backend")
            and env.get("device_count") == recorded.get("device_count"))


# one fitted problem per (width, n_estimators, seed): scenarios sharing
# a shape share the fit (and the parity pair MUST — same model is part
# of its contract), which keeps a full `check` interactive. Cached as
# (model, label_fn) pairs so the online drill's label rule rides the
# same entry.
_MODEL_CACHE: dict[tuple, Any] = {}


def _problem_for(sc: Scenario):
    width = int(sc.workload.get("width", 16))
    n_est = int(sc.model.get("n_estimators", 8))
    seed = int(sc.model.get("seed", 0))
    key = (width, n_est, seed)
    if key not in _MODEL_CACHE:
        from benchmarks.replay import _default_problem

        _MODEL_CACHE[key] = _default_problem(width, n_est, seed=seed)
    return _MODEL_CACHE[key]


def _model_for(sc: Scenario):
    return _problem_for(sc)[0]


def _seeded_models_for(sc: Scenario, seed: int, count: int) -> list:
    """A K-model fleet, one entry per registered version/tenant,
    seeded by the drills' shared ``seed + 101 * (i + 1)`` rule (the
    same rule the CLI uses) and memoised through ``_MODEL_CACHE`` so
    repeats and re-runs re-drive the same fitted fleet."""
    from benchmarks.replay import _default_problem

    width = int(sc.workload.get("width", 16))
    n_est = int(sc.model.get("n_estimators", 8))
    models = []
    for i in range(count):
        key = (width, n_est, seed + 101 * (i + 1))
        if key not in _MODEL_CACHE:
            _MODEL_CACHE[key] = _default_problem(width, n_est,
                                                 seed=key[2])
        models.append(_MODEL_CACHE[key][0])
    return models


def run_scenario(sc: Scenario,
                 repeats: int | None = None) -> dict[str, Any]:
    """One scenario through the replay machinery; returns the
    ``replay_median`` report (cross-repeat byte identity already
    asserted by it)."""
    from spark_bagging_tpu.telemetry import workload as workload_mod
    from benchmarks import replay as R

    wl = workload_mod.synthetic_workload(**sc.workload)
    seed = int(sc.workload["seed"])
    model = _model_for(sc)
    drive = dict(sc.drive)
    chaos_name = drive.pop("chaos", None)
    chaos_spec = None
    if chaos_name is not None:
        from spark_bagging_tpu import faults as faults_mod

        chaos_spec = faults_mod.builtin_plan_spec(chaos_name, seed=seed)
        drive.setdefault("retries", 2)
    reps = repeats if repeats is not None else sc.repeats
    min_rows = int(sc.serving.get("min_bucket_rows", 8))
    max_rows = int(sc.serving.get("max_batch_rows", 32))
    if sc.tenants is not None:
        tenants_kwargs = dict(sc.tenants)
        n_tenants = int(tenants_kwargs.pop("n_tenants"))
        return R.replay_median(
            wl, repeats=reps, tenants=True,
            models=_seeded_models_for(sc, seed, n_tenants),
            n_tenants=n_tenants,
            residency_capacity=int(
                tenants_kwargs.pop("residency_capacity")),
            zipf_s=float(tenants_kwargs.pop("zipf_s", 1.1)),
            chaos=chaos_spec,
            seed=seed,
            min_bucket_rows=min_rows, bucket_max_rows=max_rows,
            **drive, **tenants_kwargs,
        )
    if sc.churn is not None:
        churn_kwargs = dict(sc.churn)
        return R.replay_median(
            wl, repeats=reps, churn=True,
            models=_seeded_models_for(sc, seed,
                                      int(sc.churn["n_models"])),
            n_models=int(churn_kwargs.pop("n_models")),
            cache_capacity=int(churn_kwargs.pop("cache_capacity")),
            zipf_s=float(churn_kwargs.pop("zipf_s", 1.1)),
            seed=seed,
            min_bucket_rows=min_rows, bucket_max_rows=max_rows,
            **drive, **churn_kwargs,
        )
    if sc.online:
        _, label_fn = _problem_for(sc)
        return R.replay_median(
            wl, repeats=reps, online=True, model=model,
            label_fn=label_fn, seed=seed,
            min_bucket_rows=min_rows, bucket_max_rows=max_rows,
            **drive,
        )
    if sc.fleet:
        return R.replay_median(
            wl, repeats=reps, fleet=sc.fleet, model=model,
            chaos=chaos_spec, seed=seed,
            min_bucket_rows=min_rows, bucket_max_rows=max_rows,
            **drive,
        )
    from spark_bagging_tpu.serving import ModelRegistry

    reg_opts: dict[str, Any] = dict(
        min_bucket_rows=min_rows, max_batch_rows=max_rows,
    )
    if sc.devices:
        from spark_bagging_tpu.parallel import make_mesh

        reg_opts["mesh"] = make_mesh(data=1, replica=sc.devices)
    reg = ModelRegistry(**reg_opts)
    reg.register("scenario", model, warmup=True)
    return R.replay_median(
        wl, repeats=reps, registry=reg, model_name="scenario",
        chaos=chaos_spec, seed=seed, **drive,
    )


def digests_of(report: dict[str, Any]) -> dict[str, str]:
    """The scenario's exact identity: every digest the replay plane
    asserts byte-identical across repeats, flattened for the baseline
    file and the history store."""
    d = {
        "workload": report["workload_digest"],
        "composition": report["composition_digest"],
        "output": report["output_digest"],
    }
    attr = report.get("attribution")
    if attr is not None:
        d["attribution"] = attr["digest"]
    drift = report.get("drift")
    if drift is not None:
        d["drift"] = drift["digest"]
    chaos = report.get("chaos")
    if chaos is not None:
        d["chaos_plan"] = chaos["plan_digest"]
        d["chaos_sites"] = hashlib.sha256(
            json.dumps(chaos["sites"], sort_keys=True).encode()
        ).hexdigest()
    fleet = report.get("fleet")
    if fleet is not None:
        d["fleet_merged"] = fleet["merged_digest"]
        d["fleet_skew"] = fleet["skew_digest"]
        d["fleet_incidents"] = fleet["incident_digest"]
    online = report.get("online")
    if online is not None:
        d["online_transcript"] = online["transcript_digest"]
    churn = report.get("churn")
    if churn is not None:
        d["churn_transcript"] = churn["transcript_digest"]
    tenants = report.get("tenants")
    if tenants is not None:
        d["tenants_transcript"] = tenants["transcript_digest"]
        journey = tenants.get("journey")
        if journey is not None:
            # the request-journey forensics digest [ISSUE 20]: stage
            # sums, verdict counts, and the tail set, all virtual
            d["tenants_journey"] = journey["digest"]
    return d


def counts_of(report: dict[str, Any]) -> dict[str, int]:
    """The exact integer surface checked alongside digests (all of
    these are inside replay_median's cross-repeat assertion set)."""
    return {
        "served": report["served"],
        "overloads": report["overloads"],
        "errors": report["errors"],
        "deadline_sheds": report.get("deadline_sheds", 0),
        "batches": report["batches"],
        "swaps": report["swaps"],
    }


def record_baseline(sc: Scenario, report: dict[str, Any],
                    root: str | None = None) -> str:
    """Commit the scenario's identity: digests + exact counts + the
    SLO spec (round-tripped so `check` gates on what was recorded) +
    the recording environment."""
    from spark_bagging_tpu.telemetry.slo import SLOSpec

    baseline = {
        "schema": BASELINE_SCHEMA_VERSION,
        "scenario": sc.name,
        "description": sc.description,
        "recorded_ts": time.time(),
        "environment": environment(),
        "repeats": report.get("repeats"),
        "slo": SLOSpec.from_dict(sc.slo).to_dict(),
        "digests": digests_of(report),
        "counts": counts_of(report),
    }
    root = root or baselines_dir()
    os.makedirs(root, exist_ok=True)
    path = baseline_path(sc.name, root)
    with open(path, "w") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def check_scenario(
    sc: Scenario,
    report: dict[str, Any],
    baseline: dict[str, Any] | None,
    *,
    baselines_root: str | None = None,
) -> dict[str, Any]:
    """Conformance verdict for one already-run scenario. Returns a
    dict with ``status`` in ``pass | digest-breach | slo-breach |
    band | no-baseline`` plus full detail (mismatch list, SLO checks,
    band notes)."""
    from spark_bagging_tpu.telemetry import slo as slo_mod
    from benchmarks.replay import check_report

    out: dict[str, Any] = {"scenario": sc.name}
    if baseline is None:
        out["status"] = "no-baseline"
        out["note"] = (
            f"no committed baseline for {sc.name!r}: run "
            f"`python -m benchmarks.scenarios record --only {sc.name}`"
        )
        return out

    env = environment()
    comparable = env_comparable(env, baseline.get("environment"))
    mismatches: list[dict[str, Any]] = []
    have = digests_of(report)
    for name, want in sorted((baseline.get("digests") or {}).items()):
        got = have.get(name)
        if got != want:
            mismatches.append({"field": f"digest.{name}",
                               "expected": want, "actual": got})
    counts = counts_of(report)
    for name, want in sorted((baseline.get("counts") or {}).items()):
        got = counts.get(name)
        if got != want:
            mismatches.append({"field": f"count.{name}",
                               "expected": want, "actual": got})
    if sc.parity_with is not None:
        ref = load_baseline(sc.parity_with, baselines_root)
        ref_digest = ((ref or {}).get("digests") or {}).get("output")
        out["parity_with"] = sc.parity_with
        if ref_digest is None:
            mismatches.append({
                "field": "parity.output",
                "expected": f"<{sc.parity_with} baseline missing>",
                "actual": have.get("output"),
            })
        elif have.get("output") != ref_digest:
            mismatches.append({"field": "parity.output",
                               "expected": ref_digest,
                               "actual": have.get("output")})

    spec = slo_mod.SLOSpec.from_dict(baseline.get("slo") or {})
    result = check_report(report, spec=spec)
    # a band-named check that measured NOTHING (actual None) is a
    # broken report, never host noise — same rule as slo.exit_code
    band_slo = [c for c in result.failures
                if slo_mod.is_host_band_check(c["name"])
                and c.get("actual") is not None]
    hard_slo = [c for c in result.failures if c not in band_slo]

    out["digest_match"] = not mismatches
    out["mismatches"] = mismatches
    out["slo"] = result.to_dict()
    out["env_comparable"] = comparable
    if mismatches and not comparable:
        # digests legitimately differ on a foreign environment: the
        # scenario cannot be byte-checked here — band, not breach
        out["status"] = "band"
        out["note"] = (
            f"environment {env} does not match the recording "
            f"{baseline.get('environment')}: digest identity is "
            "host-conditional on this host"
        )
    elif mismatches:
        out["status"] = "digest-breach"
    elif hard_slo:
        out["status"] = "slo-breach"
    elif band_slo:
        out["status"] = "band"
        out["note"] = ("only host-conditional performance bands "
                       "failed: " +
                       ", ".join(c["name"] for c in band_slo))
    else:
        out["status"] = "pass"
    return out


#: status -> the shared exit-code contract (telemetry.slo / BUDGETS.md)
_STATUS_EXIT = {
    "pass": 0,
    "band": 3,
    "skipped": 3,
    "no-baseline": 2,
    "digest-breach": 2,
    "slo-breach": 2,
}


def _scenario_metrics(name: str, status: str, wall_s: float) -> None:
    from spark_bagging_tpu import telemetry

    labels = {"scenario": name}
    telemetry.inc("sbt_scenario_runs_total", labels=labels)
    telemetry.set_gauge("sbt_scenario_wall_seconds", wall_s,
                        labels=labels)
    # digest_match is a CHECK verdict: run/record modes (status
    # ran/recorded) compared nothing and must not export a green light
    if status in ("pass", "band", "slo-breach", "digest-breach"):
        telemetry.set_gauge("sbt_scenario_digest_match",
                            0.0 if status == "digest-breach" else 1.0,
                            labels=labels)
    if status == "digest-breach":
        telemetry.inc("sbt_scenario_failures_total",
                      labels={"scenario": name, "kind": "digest"})
    elif status == "slo-breach":
        telemetry.inc("sbt_scenario_failures_total",
                      labels={"scenario": name, "kind": "slo"})
    elif status == "no-baseline":
        telemetry.inc("sbt_scenario_failures_total",
                      labels={"scenario": name,
                              "kind": "baseline-missing"})


def run_conformance(
    mode: str,
    only: list[str] | None = None,
    *,
    repeats: int | None = None,
    baselines_root: str | None = None,
    history_path: str | None = None,
    append_history: bool = True,
) -> dict[str, Any]:
    """The runner's core: execute the selected scenarios and build the
    machine-readable conformance report. ``mode``:

    - ``run`` — execute + report digests/sections, no baseline gate;
    - ``record`` — execute + (re)write the committed baselines;
    - ``check`` — execute + gate against the committed baselines.

    A scenario whose declared ``devices`` exceed what this process's
    jax can see is reported ``skipped`` (host-conditional, exit 3) —
    never silently green. Every executed scenario appends one record
    to the longitudinal history store.
    """
    import jax

    from spark_bagging_tpu import telemetry
    from spark_bagging_tpu.telemetry import history as history_mod

    if mode not in ("run", "record", "check"):
        raise ValueError(f"unknown conformance mode {mode!r}")
    from benchmarks.scenarios import validate_registry

    validate_registry()
    telemetry.enable()
    scenarios = select(only)
    rows: list[dict[str, Any]] = []
    for sc in scenarios:
        if sc.devices and jax.device_count() < sc.devices:
            rows.append({
                "scenario": sc.name, "status": "skipped",
                "note": (
                    f"needs {sc.devices} devices, jax sees "
                    f"{jax.device_count()} (host-conditional: run "
                    f"under --xla_force_host_platform_device_count="
                    f"{SCENARIO_DEVICES})"
                ),
            })
            continue
        t0 = time.perf_counter()
        report = run_scenario(sc, repeats=repeats)
        wall = time.perf_counter() - t0
        if mode == "record":
            path = record_baseline(sc, report, baselines_root)
            row: dict[str, Any] = {"scenario": sc.name,
                                   "status": "recorded",
                                   "baseline": path}
        elif mode == "check":
            row = check_scenario(
                sc, report, load_baseline(sc.name, baselines_root),
                baselines_root=baselines_root,
            )
        else:
            row = {"scenario": sc.name, "status": "ran"}
        row["wall_seconds"] = round(wall, 3)
        row["digests"] = digests_of(report)
        row["counts"] = counts_of(report)
        # scenario-class sections ride the report verbatim so the
        # conformance JSON is a one-stop incident view
        for section in ("attribution", "chaos", "fleet", "drift",
                        "online", "churn", "tenants"):
            if report.get(section) is not None:
                row[section] = report[section]
        rows.append(row)
        slo_ok = (row.get("slo") or {}).get("ok")
        _scenario_metrics(sc.name, row["status"], wall)
        if append_history:
            numbers = {"wall_seconds": wall}
            if report.get("rps"):
                numbers["rps"] = float(report["rps"])
            history_mod.append_record(
                "scenario", sc.name,
                digests=row["digests"],
                numbers=numbers,
                slo_ok=slo_ok if mode == "check" else None,
                detail={"mode": mode, "status": row["status"],
                        "counts": row["counts"]},
                path=history_path,
            )

    codes = [_STATUS_EXIT.get(r["status"], 0) for r in rows]
    exit_code = 2 if 2 in codes else (3 if 3 in codes else 0)
    return {
        "metric": "scenario_conformance",
        "schema": SCENARIO_SCHEMA_VERSION,
        "mode": mode,
        "ts": time.time(),
        "environment": environment(),
        "registered": len(select(None)),
        "scenarios": rows,
        "ok": exit_code == 0,
        "exit_code": exit_code,
    }


def render_conformance(report: dict[str, Any]) -> str:
    """One line per scenario for the CLI."""
    lines = [f"scenario conformance ({report['mode']}): "
             f"{len(report['scenarios'])} of "
             f"{report['registered']} scenarios"]
    for r in report["scenarios"]:
        status = r["status"].upper() if r["status"].endswith("breach") \
            else r["status"]
        wall = r.get("wall_seconds")
        extra = ""
        if r.get("mismatches"):
            fields = ", ".join(m["field"] for m in r["mismatches"])
            extra = f" [{fields}]"
        elif r.get("note"):
            extra = f" [{r['note']}]"
        lines.append(
            f"  [{status:>13}] {r['scenario']}"
            + (f" ({wall:.1f}s)" if wall is not None else "")
            + extra
        )
    lines.append("conformance OK" if report["ok"]
                 else f"conformance exit {report['exit_code']}")
    return "\n".join(lines)
